"""Fast Gradient Sign Method adversarial examples (reference
`example/adversary/adversary_generation.ipynb`): train a small classifier,
then perturb inputs by ``eps * sign(dL/dx)`` and watch accuracy collapse.

Exercises gradient-with-respect-to-INPUT — ``x.attach_grad()`` +
``autograd.record`` taping data as well as parameters (reference
``mark_variables``/`autograd.py:216`), which is also what neural-style and
saliency tooling need.

Run: ``./dev.sh python examples/adversary/fgsm.py``
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def make_blobs(rng, n, classes=4):
    """Well-separated gaussian blobs on a 2D grid, lifted to 16-D."""
    centers = np.array([[2, 2], [-2, 2], [-2, -2], [2, -2]], np.float32)
    y = rng.randint(0, classes, n)
    x2 = centers[y] + 0.35 * rng.randn(n, 2).astype(np.float32)
    # zero pad channels: room for the attack to also perturb dead inputs
    X = np.concatenate([x2, np.zeros((n, 16), np.float32)], axis=1)
    return X.astype(np.float32), y.astype(np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=60)
    p.add_argument("--eps", type=float, default=0.6)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.gluon import nn, Trainer
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    Xtr, ytr = make_blobs(rng, 2048)
    Xte, yte = make_blobs(rng, 512)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.2})
    loss_fn = SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        x, y = nd.array(Xtr), nd.array(ytr)
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(len(Xtr))

    clean_acc = (net(nd.array(Xte)).asnumpy().argmax(1) == yte).mean()

    # FGSM: gradient wrt the INPUT, not the params
    x = nd.array(Xte)
    x.attach_grad()
    with autograd.record():
        adv_loss = loss_fn(net(x), nd.array(yte))
    adv_loss.backward()
    x_adv = nd.array(Xte + args.eps * np.sign(x.grad.asnumpy()))
    adv_acc = (net(x_adv).asnumpy().argmax(1) == yte).mean()

    print("clean acc %.3f  adversarial acc %.3f (eps=%.2f)"
          % (clean_acc, adv_acc, args.eps))
    assert clean_acc > 0.95, "classifier failed to train"
    assert adv_acc < clean_acc - 0.2, "FGSM failed to degrade accuracy"
    print("FGSM ADVERSARY OK")


if __name__ == "__main__":
    main()
