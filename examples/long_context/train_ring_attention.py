"""Long-context transformer block trained with ring attention — sequence
parallelism over the `sp` mesh axis (absent in the reference, SURVEY §5.7;
this is the TPU-native upgrade: K/V blocks rotate around the ring with
lax.ppermute while each step's attention block computes, so sequence length
scales with the number of chips).

Trains a 1-layer causal transformer LM on a synthetic copy task whose target
REQUIRES long-range attention: the token at a marked position early in the
sequence must be reproduced at the end. Runs on the 8-device dev mesh
(sequence sharded 8-way) or real ICI.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--vocab", type=int, default=16)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--lr", type=float, default=5e-3)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from mxnet_tpu import parallel
    from mxnet_tpu.parallel.ring import ring_self_attention

    n_dev = len(jax.devices())
    mesh = parallel.make_mesh({"sp": n_dev})
    S, B, V, D, H = args.seq_len, args.batch_size, args.vocab, args.dim, args.heads
    assert S % n_dev == 0
    Dh = D // H

    rng = np.random.RandomState(0)
    params = {
        "embed": rng.randn(V, D).astype(np.float32) * 0.05,
        "wq": rng.randn(D, D).astype(np.float32) * 0.05,
        "wk": rng.randn(D, D).astype(np.float32) * 0.05,
        "wv": rng.randn(D, D).astype(np.float32) * 0.05,
        "wo": rng.randn(D, D).astype(np.float32) * 0.05,
        "w1": rng.randn(D, 2 * D).astype(np.float32) * 0.05,
        "w2": rng.randn(2 * D, D).astype(np.float32) * 0.05,
        "head": rng.randn(D, V).astype(np.float32) * 0.05,
    }
    pos = (np.arange(S)[:, None] / S * np.pi * np.arange(1, D + 1)[None, :])
    pos_emb = np.sin(pos).astype(np.float32) * 0.1

    def forward(p_, tokens):
        x = p_["embed"][tokens] + pos_emb[None]  # [B, S, D]
        q = (x @ p_["wq"]).reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        k = (x @ p_["wk"]).reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        v = (x @ p_["wv"]).reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
        # sequence dim sharded over sp; K/V ring-rotate via ppermute
        a = ring_self_attention(q, k, v, mesh=mesh, causal=True)  # [B, H, S, Dh]
        a = a.transpose(0, 2, 1, 3).reshape(B, S, D)
        x = x + a @ p_["wo"]
        x = x + jax.nn.relu(x @ p_["w1"]) @ p_["w2"]
        return x @ p_["head"]  # [B, S, V]

    def loss_fn(p_, tokens, targets, mask):
        logits = forward(p_, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return (nll * mask).sum() / mask.sum()

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    # adam on the host-side pytree (the point here is the sharded attention)
    m_state = jax.tree_util.tree_map(jnp.zeros_like, params)
    v_state = jax.tree_util.tree_map(jnp.zeros_like, params)

    @jax.jit
    def adam(p_, m_, v_, g_, t):
        b1, b2, eps = 0.9, 0.999, 1e-8
        m_ = jax.tree_util.tree_map(lambda a, b: b1 * a + (1 - b1) * b, m_, g_)
        v_ = jax.tree_util.tree_map(lambda a, b: b2 * a + (1 - b2) * b * b, v_, g_)
        def upd(w, mm, vv):
            mhat = mm / (1 - b1 ** t)
            vhat = vv / (1 - b2 ** t)
            return w - args.lr * mhat / (jnp.sqrt(vhat) + eps)
        return jax.tree_util.tree_map(upd, p_, m_, v_), m_, v_

    def make_batch(step_seed):
        r = np.random.RandomState(step_seed)
        toks = r.randint(2, V, (B, S))
        toks[:, 0] = 0  # marker
        payload = r.randint(2, V, (B,))
        toks[:, 1] = payload          # token to remember
        targets = np.roll(toks, -1, axis=1)
        targets[:, -1] = payload      # must recall the early payload
        mask = np.zeros((B, S), np.float32)
        mask[:, -1] = 1.0             # only the long-range recall is scored
        return (jnp.asarray(toks), jnp.asarray(targets), jnp.asarray(mask))

    losses = []
    for i in range(args.steps):
        toks, targets, mask = make_batch(i % 8)  # cycle a small task set
        loss, grads = grad_fn(params, toks, targets, mask)
        params, m_state, v_state = adam(params, m_state, v_state, grads, i + 1)
        losses.append(float(loss))
        if i % 10 == 0:
            print("step %d loss %.4f" % (i, losses[-1]))
    print("first=%.4f last=%.4f (seq=%d over %d-way sequence parallelism)"
          % (losses[0], losses[-1], S, n_dev))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    print("RING ATTENTION LM OK")


if __name__ == "__main__":
    main()
