"""Character-level Chinese text CNN — reference
``example/cnn_chinese_text_classification/text_cnn.py``.

Same symbol graph as the reference (Kim-CNN: char embedding → parallel
convs of widths 3/4/5 spanning the full embedding → max-over-time pool →
concat → dropout → FC → softmax), trained with the Module API + rmsprop as
the reference does.  Chinese text tokenizes per CHARACTER (no word
segmentation — the property that distinguishes this family from
``cnn_text_classification``): the synthetic corpus draws from a few
hundred codepoints of the CJK range with class-correlated character sets,
and the pipeline maps codepoints → indices exactly as data_helpers.py's
vocabulary build does.

Run: ./dev.sh python examples/cnn_chinese_text_classification/text_cnn.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def make_corpus(rng, n, seq_len=24, classes=2, chars_per_class=40,
                shared=120):
    """Synthetic char-level docs: each class favors its own CJK char set."""
    base = 0x4E00
    class_sets = [np.arange(base + c * chars_per_class,
                            base + (c + 1) * chars_per_class)
                  for c in range(classes)]
    shared_set = np.arange(base + 1000, base + 1000 + shared)
    docs, labels = [], []
    for _ in range(n):
        c = rng.randint(classes)
        cps = np.where(rng.rand(seq_len) < 0.35,
                       rng.choice(class_sets[c], seq_len),
                       rng.choice(shared_set, seq_len))
        docs.append("".join(chr(int(x)) for x in cps))
        labels.append(c)
    return docs, np.array(labels, np.float32)


def build_vocab(docs):
    """Char → index (data_helpers.py build_vocab: per-character, no
    segmentation)."""
    vocab = {"<pad>": 0}
    for d in docs:
        for ch in d:
            if ch not in vocab:
                vocab[ch] = len(vocab)
    return vocab


def encode(docs, vocab, seq_len):
    out = np.zeros((len(docs), seq_len), np.float32)
    for i, d in enumerate(docs):
        for j, ch in enumerate(d[:seq_len]):
            out[i, j] = vocab.get(ch, 0)
    return out


def sym_gen(sentence_size, num_embed, vocab_size, num_label=2,
            filter_list=(3, 4, 5), num_filter=32, dropout=0.3):
    """reference text_cnn.py sym_gen:126-165."""
    input_x = mx.sym.Variable("data")
    input_y = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(input_x, input_dim=vocab_size,
                             output_dim=num_embed, name="vocab_embed")
    conv_input = mx.sym.reshape(embed, shape=(0, 1, sentence_size, num_embed))
    pooled = []
    for fs in filter_list:
        convi = mx.sym.Convolution(conv_input, kernel=(fs, num_embed),
                                   num_filter=num_filter)
        relui = mx.sym.Activation(convi, act_type="relu")
        pooli = mx.sym.Pooling(relui, pool_type="max",
                               kernel=(sentence_size - fs + 1, 1),
                               stride=(1, 1))
        pooled.append(pooli)
    concat = mx.sym.Concat(*pooled, dim=1)
    h = mx.sym.reshape(concat, shape=(0, -1))
    if dropout > 0:
        h = mx.sym.Dropout(h, p=dropout)
    fc = mx.sym.FullyConnected(h, num_hidden=num_label)
    return mx.sym.SoftmaxOutput(fc, input_y, name="softmax")


def main(epochs=8, batch=50, seq_len=24, num_embed=48, seed=0):
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    docs, labels = make_corpus(rng, 1200, seq_len)
    vocab = build_vocab(docs)
    xs = encode(docs, vocab, seq_len)
    n_tr = 1000
    train = mx.io.NDArrayIter(xs[:n_tr], labels[:n_tr], batch, shuffle=True)
    val = mx.io.NDArrayIter(xs[n_tr:], labels[n_tr:], batch)

    net = sym_gen(seq_len, num_embed, len(vocab))
    mod = mx.mod.Module(net)
    mod.fit(train, eval_data=val, num_epoch=epochs, optimizer="rmsprop",
            optimizer_params={"learning_rate": 5e-4}, eval_metric="acc")
    metric = mx.metric.Accuracy()
    val.reset()
    mod.score(val, metric)
    acc = metric.get()[1]
    print("chinese char-CNN val acc %.3f (vocab %d chars)"
          % (acc, len(vocab)))
    return acc


if __name__ == "__main__":
    main()
