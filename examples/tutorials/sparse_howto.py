"""Companion script for docs/tutorials/sparse.md (reference
``docs/tutorials/sparse/{csr,row_sparse,train}.md``): CSR / RowSparse
arrays, sparse dot, LibSVM input, and lazy (sparse) SGD updates."""
from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd

# --- 1. CSRNDArray: compressed sparse rows -------------------------------
dense = np.array([[0, 1, 0, 2],
                  [0, 0, 0, 0],
                  [3, 0, 0, 0]], np.float32)
csr = nd.sparse.csr_matrix(dense)
assert csr.stype == "csr"
np.testing.assert_allclose(csr.asnumpy(), dense)
# the three constituent arrays, exactly the reference's layout
print("csr data=%s indices=%s indptr=%s"
      % (csr.data.asnumpy().tolist(), csr.indices.asnumpy().tolist(),
         csr.indptr.asnumpy().tolist()))

# construct from (data, indices, indptr) without densifying
csr2 = nd.sparse.csr_matrix(
    (csr.data.asnumpy(), csr.indices.asnumpy(), csr.indptr.asnumpy()),
    shape=(3, 4))
np.testing.assert_allclose(csr2.asnumpy(), dense)

# --- 2. sparse dot: the workhorse of sparse linear models ----------------
w = nd.array(np.arange(8, dtype=np.float32).reshape(4, 2))
out = nd.sparse.dot(csr, w)
np.testing.assert_allclose(out.asnumpy(), dense @ w.asnumpy())
print("sparse dot OK")

# --- 3. RowSparseNDArray: gradients that touch few rows ------------------
rsp = nd.sparse.row_sparse_array(
    (np.array([[1., 2.], [3., 4.]], np.float32), np.array([0, 3])),
    shape=(5, 2))
assert rsp.stype == "row_sparse"
full = rsp.asnumpy()
assert full[0].tolist() == [1, 2] and full[3].tolist() == [3, 4]
assert (full[[1, 2, 4]] == 0).all()

# retain a row subset (the kvstore row_sparse_pull primitive)
kept = nd.sparse.retain(rsp, nd.array(np.array([3], np.float32)))
assert kept.asnumpy()[3].tolist() == [3, 4] and (kept.asnumpy()[0] == 0).all()
print("row_sparse retain OK")

# --- 4. LibSVM input pipeline --------------------------------------------
tmp = tempfile.mkdtemp()
svm = os.path.join(tmp, "train.libsvm")
with open(svm, "w") as f:
    f.write("1 0:1.5 3:2.0\n0 1:1.0\n1 2:4.0 3:1.0\n0 0:0.5\n")
it = mx.io.LibSVMIter(data_libsvm=svm, data_shape=(4,), batch_size=2)
batches = list(it)
assert len(batches) == 2
assert batches[0].data[0].stype == "csr"
print("LibSVMIter read %d batches of csr data" % len(batches))

# --- 5. lazy sparse SGD: update only the touched rows --------------------
# (reference optimizer_op.cc sparse sgd_update; lazy_update skips untouched
# rows entirely — the reason row_sparse gradients exist)
weight = nd.array(np.ones((5, 2), np.float32))
opt = mx.optimizer.create("sgd", learning_rate=0.5, lazy_update=True)
upd = mx.optimizer.get_updater(opt)
upd(0, rsp, weight)
wn = weight.asnumpy()
np.testing.assert_allclose(wn[0], 1 - 0.5 * np.array([1., 2.]))
np.testing.assert_allclose(wn[3], 1 - 0.5 * np.array([3., 4.]))
np.testing.assert_allclose(wn[[1, 2, 4]], 1.0)  # untouched rows unchanged
print("lazy sparse SGD touched only rows [0, 3]")

print("SPARSE TUTORIAL OK")
