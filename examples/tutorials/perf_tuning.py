"""Companion script for docs/tutorials/performance.md — the performance
prescriptions from docs/PERF_NOTES.md as runnable code (reference
``docs/faq/perf.md``): one fused train step, bf16 mixed precision, state
donation, remat, and reading the compiled module's cost analysis."""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon.functional import make_train_step

import jax


def build():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.Conv2D(64, 3, padding=1, strides=2, activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.GlobalAvgPool2D(),
            gluon.nn.Dense(10))
    net.initialize()
    net(nd.zeros((2, 3, 32, 32)))
    return net


rng = np.random.RandomState(0)
X = rng.rand(64, 3, 32, 32).astype(np.float32)
y = (rng.rand(64) * 10).astype(np.float32)
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

# --- prescription 1: ONE jitted train step -------------------------------
# fwd + bwd + BN stats + optimizer in a single XLA module — no per-op
# dispatch, full fusion (the reference needed engine bulking for less).
mx.random.seed(0)
step, state, _ = make_train_step(build(), loss_fn, learning_rate=0.1,
                                 momentum=0.9)
# --- prescription 2: donate the state so buffers update in place ---------
jstep = jax.jit(step, donate_argnums=(0,))
key = jax.random.PRNGKey(0)
state, loss = jstep(state, X, y, key)          # compile
jax.block_until_ready(loss)

# --- prescription 3: read the compiled module's cost analysis ------------
# flops vs bytes tells you which roofline you are on; detection/CNN steps
# here are HBM-bound (PERF_NOTES: ResNet-50 at 152 GB/step vs 10 TF)
comp = jax.jit(step, donate_argnums=(0,)).lower(state, X, y, key).compile()
ca = comp.cost_analysis()
ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
flops, gbytes = ca.get("flops", 0) / 1e9, ca.get("bytes accessed", 0) / 1e9
print("cost analysis: %.2f GFLOP, %.3f GB accessed per step" % (flops, gbytes))
assert gbytes > 0

# --- prescription 4: bf16 compute, fp32 master params --------------------
# halves HBM traffic on the bound that matters; loss/BN stats stay fp32
mx.random.seed(0)
step16, state16, _ = make_train_step(build(), loss_fn, learning_rate=0.1,
                                     momentum=0.9, compute_dtype="bfloat16")
jstep16 = jax.jit(step16, donate_argnums=(0,))
state16, loss16 = jstep16(state16, X, y, key)
jax.block_until_ready(loss16)
print("bf16 step loss %.4f (fp32 %.4f) — master params stay fp32: %s"
      % (float(loss16), float(loss), state16[0][0].dtype))
assert state16[0][0].dtype == np.float32

# --- prescription 5: remat when activations crowd HBM --------------------
# ≡ the reference's MXNET_BACKWARD_DO_MIRROR, but ~free on memory-bound
# models (PERF_NOTES measured ~2% vs the reference's ~30%)
net_r = build()
net_r.set_remat(True)
mx.random.seed(0)
step_r, state_r, _ = make_train_step(net_r, loss_fn, learning_rate=0.1)
state_r, loss_r = jax.jit(step_r, donate_argnums=(0,))(state_r, X, y, key)
print("remat step runs: loss %.4f" % float(loss_r))

# --- prescription 6: measure honestly ------------------------------------
# chain steps with donated state and fetch ONE scalar; timing each step
# with a device sync measures dispatch latency, not the chip
# (docs/PERF_NOTES.md "Tunnel-measurement note")
for _ in range(3):
    state, loss = jstep(state, X, y, key)
t0 = time.perf_counter()
K = 10
for _ in range(K):
    state, loss = jstep(state, X, y, key)
float(loss)
print("chained measurement: %.2f ms/step over %d steps"
      % ((time.perf_counter() - t0) / K * 1e3, K))

print("PERF-TUNING TUTORIAL OK")
