"""Companion script for docs/tutorials/recordio.md (reference
``docs/faq/recordio.md`` + ``docs/architecture/note_data_loading.md``):
pack images into RecordIO, index it, and feed training through
ImageRecordIter's native C++ decode/augment pipeline."""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
tmp = tempfile.mkdtemp()

# --- 1. write a .rec of JPEG-packed synthetic images ---------------------
# (the reference workflow is `im2rec.py list/ + im2rec.py` over an image
# folder; pack_img is the same binary record format those tools write)
rec_path = os.path.join(tmp, "train.rec")
rec = recordio.MXRecordIO(rec_path, "w")
rng = np.random.RandomState(0)
N, H, W = 24, 32, 32
labels = []
for i in range(N):
    y = i % 3
    img = (rng.rand(H, W, 3) * 80).astype(np.uint8)
    img[:, :, y] += 120                     # class = dominant channel
    header = recordio.IRHeader(0, float(y), i, 0)
    rec.write(recordio.pack_img(header, img, quality=95, img_fmt=".jpg"))
    labels.append(y)
rec.close()
print("wrote %d jpeg records -> %s (%d bytes)"
      % (N, rec_path, os.path.getsize(rec_path)))

# --- 2. index it so shuffling can seek (rec2idx ≡ reference tool) --------
idx_path = os.path.join(tmp, "train.idx")
subprocess.run([sys.executable, os.path.join(REPO, "tools", "rec2idx.py"),
                rec_path, idx_path], check=True)
ridx = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
hdr, img = recordio.unpack_img(ridx.read_idx(5))
assert hdr.label == labels[5] and img.shape == (H, W, 3)
print("indexed read-back of record 5 OK (label %d)" % hdr.label)

# --- 3. ImageRecordIter: native C++ decode + augment + batch -------------
it = mx.io.ImageRecordIter(
    path_imgrec=rec_path, data_shape=(3, H, W), batch_size=8,
    shuffle=True, rand_mirror=True,
    mean_r=127.0, mean_g=127.0, mean_b=127.0,
    std_r=60.0, std_g=60.0, std_b=60.0)
seen = 0
for batch in it:
    x = batch.data[0]
    assert x.shape == (8, 3, H, W)
    seen += 8
assert seen == N, seen
print("ImageRecordIter streamed %d images in (8,3,%d,%d) batches" % (seen, H, W))

# --- 4. the pipeline feeds a trainable task ------------------------------
net = mx.gluon.nn.Dense(3)
net.initialize()
trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 5e-2})
loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
for epoch in range(12):
    it.reset()
    for batch in it:
        x = batch.data[0].reshape((8, -1))
        y = batch.label[0]
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)
it.reset()
correct = total = 0
for batch in it:
    pred = net(batch.data[0].reshape((8, -1))).asnumpy().argmax(axis=1)
    correct += (pred == batch.label[0].asnumpy()).sum()
    total += 8
acc = correct / total
print("trained on the .rec stream: accuracy %.3f" % acc)
assert acc > 0.8, acc

print("RECORDIO TUTORIAL OK")
