"""Companion script for docs/tutorials/int8.md (reference
``example/quantization/README.md``): train fp32, quantize to int8 with
calibration, verify accuracy, and deploy the quantized symbol."""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.contrib.quantization import quantize_model
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.test_utils import load_module_by_path

# reuse the example's dataset + net + accuracy harness (the full sweep over
# all three calib modes lives there; this walkthrough runs the recommended
# one end-to-end)
_ex = load_module_by_path(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                 "quantization", "quantize_model.py"), "_quant_example")

Xtr, ytr = _ex.make_data(1024, seed=0)
Xval, yval = _ex.make_data(256, seed=1)

# --- 1. train the fp32 model --------------------------------------------
mx.random.seed(0)
np.random.seed(0)
net = _ex.build_net()
mod = mx.mod.Module(net)
mod.fit(NDArrayIter(Xtr, ytr, 64, shuffle=True), num_epoch=8,
        optimizer="adam", optimizer_params={"learning_rate": 2e-3},
        initializer=mx.init.Xavier())
arg_params, aux_params = mod.get_params()
fp32_acc = _ex.accuracy(net, arg_params, Xval, yval, 64)
print("fp32 accuracy: %.4f" % fp32_acc)

# --- 2. quantize with naive (min/max) calibration ------------------------
# conv/fc become int8 kernels with int32 accumulation; calibration fixes
# each layer's quantization range offline so no runtime min/max pass runs
qsym, qargs, qaux = quantize_model(
    net, arg_params, aux_params, calib_mode="naive",
    calib_data=NDArrayIter(Xtr, ytr, 64), num_calib_examples=256)
q_ops = [n for n in str(qsym.tojson()).split('"') if n.startswith("_contrib_quantized")]
print("quantized ops in the graph: %s" % sorted(set(q_ops)))

# --- 3. accuracy check ----------------------------------------------------
q_acc = _ex.accuracy(qsym, qargs, Xval, yval, 64)
print("int8 accuracy: %.4f (delta %+.4f)" % (q_acc, q_acc - fp32_acc))
assert q_acc > fp32_acc - 0.02, (q_acc, fp32_acc)

# --- 4. the quantized symbol deploys like any other ----------------------
exe = qsym.simple_bind(grad_req="null", data=(64, 3, 16, 16))
for k, v in qargs.items():
    if k in exe.arg_dict:
        exe.arg_dict[k][:] = v.asnumpy()
exe.arg_dict["data"][:] = Xval[:64]
out = exe.forward(is_train=False)[0].asnumpy()
assert out.shape == (64, 8)
print("quantized deploy forward OK")

print("INT8 TUTORIAL OK")
