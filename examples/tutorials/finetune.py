"""Runnable companion to docs/tutorials/finetune.md (reference
``docs/faq/finetune.md``): pretrain a small CNN, then fine-tune it onto a
new label space by symbol surgery (get_internals → new FC head) with the
trunk held fixed (``fixed_param_names``, the reference's recipe).

Run: ./dev.sh python examples/tutorials/finetune.py
"""
from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def make_data(rng, n, classes, noise=0.15):
    """8×8 single-channel images whose class is a bright row index."""
    x = rng.rand(n, 1, 8, 8).astype(np.float32) * noise
    y = rng.randint(0, classes, n)
    for i, c in enumerate(y):
        x[i, 0, c % 8] += 1.0
    return x, y.astype(np.float32)


def feature_net(classes):
    data = sym.Variable("data")
    h = sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1), name="conv1")
    h = sym.Activation(h, act_type="relu")
    h = sym.Convolution(h, num_filter=16, kernel=(3, 3), pad=(1, 1), name="conv2")
    h = sym.Activation(h, act_type="relu", name="features")
    h = sym.Flatten(h)
    h = sym.FullyConnected(h, num_hidden=classes, name="fc_out")
    return sym.SoftmaxOutput(h, name="softmax")


def fit(mod, x, y, epochs, batch=32, lr=0.1):
    it = mx.io.NDArrayIter(x, y, batch, shuffle=True, label_name="softmax_label")
    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            optimizer_params={"learning_rate": lr},
            eval_metric="acc",
            initializer=mx.init.Xavier())


def accuracy(mod, x, y, batch=32):
    it = mx.io.NDArrayIter(x, y, batch, label_name="softmax_label")
    m = mx.metric.Accuracy()
    mod.score(it, m)
    return m.get()[1]


def main():
    mx.random.seed(0)
    rng = np.random.RandomState(0)

    # --- stage 1: "pretrain" on the 8-class source task -------------------
    net = feature_net(8)
    xs, ys = make_data(rng, 512, 8)
    mod = mx.mod.Module(net, data_names=["data"], label_names=["softmax_label"])
    fit(mod, xs, ys, epochs=4)
    acc_src = accuracy(mod, *make_data(rng, 256, 8))
    print("source-task accuracy: %.3f" % acc_src)
    assert acc_src > 0.8, acc_src

    prefix = os.path.join(tempfile.mkdtemp(), "pretrained")
    mod.save_checkpoint(prefix, 1)

    # --- stage 2: fine-tune onto a 3-class target task --------------------
    # (reference finetune.md get_fine_tune_model: truncate at the feature
    # layer, attach a fresh FC, keep the trunk fixed)
    loaded_sym, arg_params, aux_params = mx.model.load_checkpoint(prefix, 1)
    features = loaded_sym.get_internals()["features_output"]
    h = sym.Flatten(features)
    h = sym.FullyConnected(h, num_hidden=3, name="fc_new")
    tuned = sym.SoftmaxOutput(h, name="softmax")

    trunk_params = [n for n in tuned.list_arguments()
                    if n.startswith(("conv1", "conv2"))]
    ft = mx.mod.Module(tuned, data_names=["data"],
                       label_names=["softmax_label"],
                       fixed_param_names=trunk_params)
    xt, yt = make_data(rng, 256, 3)
    it = mx.io.NDArrayIter(xt, yt, 32, shuffle=True, label_name="softmax_label")
    # fit seeds from the checkpoint: pretrained weights where names match
    # (the trunk), fresh Xavier for the new head (allow_missing)
    drop_old_head = {n: v for n, v in arg_params.items()
                     if not n.startswith("fc_out")}
    ft.fit(it, num_epoch=4, optimizer="sgd",
           optimizer_params={"learning_rate": 0.1}, eval_metric="acc",
           initializer=mx.init.Xavier(), arg_params=drop_old_head,
           aux_params=aux_params, allow_missing=True)
    acc_tgt = accuracy(ft, *make_data(rng, 256, 3))
    print("target-task accuracy after fine-tune: %.3f" % acc_tgt)
    assert acc_tgt > 0.8, acc_tgt
    # the fixed trunk still equals the checkpoint exactly
    after = ft.get_params()[0]
    for n in trunk_params:
        np.testing.assert_array_equal(arg_params[n].asnumpy(),
                                      after[n].asnumpy())
    print("FINETUNE TUTORIAL OK")


if __name__ == "__main__":
    main()
