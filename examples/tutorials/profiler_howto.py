"""Companion script for docs/tutorials/profiler.md (reference
``docs/tutorials/python/profiler.md`` + ``example/profiler/``): configure
the profiler, bracket a workload, dump a chrome-trace JSON, and inspect
per-tensor stats with Monitor."""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler

tmp = tempfile.mkdtemp()
trace = os.path.join(tmp, "profile.json")

# --- 1. configure + bracket a workload -----------------------------------
profiler.set_config(profile_all=True, filename=trace)
profiler.set_state("run")

a = nd.random.uniform(shape=(256, 256))
b = nd.random.uniform(shape=(256, 256))
c = nd.dot(a, b)
d = nd.relu(c) + 1.0
d.wait_to_read()

# user-code annotation: domains + tasks (reference profiler.py:151-240)
domain = profiler.Domain("my_app")
task = profiler.Task(domain, "postprocess")
task.start()
e = (d * 2).sum()
e.wait_to_read()
task.stop()

# counters (reference ProfileCounter)
counter = profiler.Counter(domain, "batches_done")
counter.set_value(1)
counter += 1

profiler.set_state("stop")
profiler.dump()

# --- 2. the dump is chrome://tracing JSON --------------------------------
with open(trace) as f:
    events = json.load(f)["traceEvents"]
names = {ev.get("name") for ev in events}
assert any("dot" in (n or "").lower() for n in names), sorted(names)[:20]
assert "postprocess" in names, sorted(names)[:20]
print("chrome trace: %d events incl. op events and the 'postprocess' task"
      % len(events))

# --- 3. dumps() returns the same JSON as a string (dump(finished=True)
# already drained the buffer above, so this run starts fresh) -------------
assert json.loads(profiler.dumps())["traceEvents"] == []

# --- 4. Monitor: per-tensor stats through an executor --------------------
x = mx.sym.Variable("x")
h = mx.sym.FullyConnected(x, num_hidden=8, name="fc")
out = mx.sym.SoftmaxOutput(h, name="sm")
exe = out.simple_bind(x=(4, 16), sm_label=(4,))
seen = []
mon = mx.monitor.Monitor(1, stat_func=lambda arr: nd.max(nd.abs(arr)),
                         pattern=".*fc.*")
mon.install(exe)
exe.arg_dict["x"][:] = np.random.RandomState(0).rand(4, 16)
mon.tic()
exe.forward(is_train=True)
for batch, name, val in mon.toc():
    seen.append(name)
assert any("fc" in n for n in seen), seen
print("Monitor captured per-tensor stats: %s" % seen[:4])

print("PROFILER TUTORIAL OK")
