"""Runnable companion to docs/tutorials/bucketing.md (reference
``docs/faq/bucketing.md``): variable-length sequence training with
BucketSentenceIter + BucketingModule.  On TPU each bucket length is ONE
static-shape jit specialization — the XLA analog of the reference's
per-bucket shared-parameter executors.

The task is learnable: every sequence walks the vocabulary cyclically
(w_{t+1} = w_t + 1 mod V), so perplexity must fall well below uniform.

Run: ./dev.sh python examples/tutorials/bucketing.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym
import mxnet_tpu.rnn as mrnn

VOCAB = 12


def make_sentences(rng, n):
    """Cyclic successor walks of mixed lengths (two bucket populations)."""
    out = []
    for _ in range(n):
        ln = rng.choice([5, 6, 9, 10])
        start = rng.randint(1, VOCAB)
        out.append([(start + t - 1) % (VOCAB - 1) + 1 for t in range(ln)])
    return out


def sym_gen_factory(vocab_size):
    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        emb = sym.Embedding(data, input_dim=vocab_size, output_dim=16)
        cell = mrnn.LSTMCell(32, prefix="lstm_")
        outputs, _ = cell.unroll(seq_len, inputs=emb, merge_outputs=True,
                                 layout="NTC")
        pred = sym.Reshape(outputs, shape=(-1, 32))
        pred = sym.FullyConnected(pred, num_hidden=vocab_size)
        labf = sym.Reshape(label, shape=(-1,))
        return (sym.SoftmaxOutput(pred, labf, name="softmax"),
                ("data",), ("softmax_label",))
    return sym_gen


def main():
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    it = mrnn.BucketSentenceIter(make_sentences(rng, 400), batch_size=16,
                                 buckets=[6, 10], invalid_label=0)
    assert it.default_bucket_key == 10

    mod = mx.mod.BucketingModule(sym_gen_factory(VOCAB + 1),
                                 default_bucket_key=it.default_bucket_key)
    metric = mx.metric.Perplexity(ignore_label=0)
    mod.fit(it, eval_metric=metric, num_epoch=10,
            optimizer="adam", optimizer_params={"learning_rate": 0.01},
            batch_end_callback=mx.callback.Speedometer(16, 10))

    it.reset()
    metric.reset()
    mod.score(it, metric)
    ppl = metric.get()[1]
    print("final train perplexity: %.2f (uniform would be %.1f)"
          % (ppl, VOCAB))
    assert ppl < 2.5, ppl   # the cyclic-successor rule is learned (~1.3)
    print("BUCKETING TUTORIAL OK")


if __name__ == "__main__":
    main()
