"""Runnable companion to docs/tutorials/multi_devices.md (reference
``docs/faq/multi_devices.md``): scaling training across devices.

Two paths, in order of preference on TPU:

1. **Sharded jit (the TPU-native path)**: one jitted train step over a
   ``jax.sharding`` Mesh; XLA inserts the gradient all-reduce over ICI.
   The reference's multi-GPU data parallelism (ctx=[mx.gpu(0..N)] +
   kvstore) collapses into mesh + sharding annotations.
2. **KVStore processes (the reference-shaped path)**: N real processes
   with a ``dist_sync`` kvstore via ``tools/launch.py`` — the fake-cluster
   harness used by the dist tests; run here 2-process to prove the
   commands in the tutorial actually work.

Run: ./dev.sh python examples/tutorials/multi_devices.py
"""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import textwrap

REPO = os.path.abspath(os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
sys.path.insert(0, REPO)

import numpy as np


def sharded_jit_dp():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as loss_mod
    from mxnet_tpu.gluon.functional import make_train_step

    n = min(len(jax.devices()), 8)
    mesh = parallel.make_mesh({"dp": n})

    mx.random.seed(0)
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(32, activation="relu"))
    net.add(mx.gluon.nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((2, 8)))

    step, state, _meta = make_train_step(
        net, loss_mod.SoftmaxCrossEntropyLoss(), learning_rate=0.5,
        momentum=0.9)
    repl = NamedSharding(mesh, P())
    bsh = NamedSharding(mesh, P("dp"))
    state = jax.tree_util.tree_map(lambda v: jax.device_put(v, repl), state)

    rng = np.random.RandomState(0)
    jstep = jax.jit(step, donate_argnums=(0,))
    losses = []
    for s in range(80):
        x = rng.randn(4 * n, 8).astype(np.float32)
        y = (x[:, :4].argmax(1)).astype(np.float32)
        xb = jax.device_put(x, bsh)     # batch axis split over the mesh
        yb = jax.device_put(y, bsh)
        state, loss = jstep(state, xb, yb, jax.random.PRNGKey(s))
        losses.append(float(loss))
    print("sharded-jit dp over %d devices: loss %.3f -> %.3f"
          % (n, losses[0], losses[-1]))
    assert losses[-1] < losses[0] * 0.7, losses
    return n


KV_WORKER = textwrap.dedent("""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import dist
    from mxnet_tpu import nd, autograd

    dist.init()
    r, n = dist.rank(), dist.size()
    mx.random.seed(3)
    net = mx.gluon.nn.Dense(2)
    net.initialize()
    net(nd.zeros((2, 3)))
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.05}, kvstore="dist_sync")
    rng = np.random.RandomState(r)
    for s in range(5):
        xb = nd.array(rng.randn(2, 3).astype(np.float32))
        with autograd.record():
            loss = (net(xb) ** 2).sum()
        loss.backward()
        tr.step(2)
    vals = np.concatenate([p.data().asnumpy().ravel()
                           for p in net.collect_params().values()])
    print("RANK%d_OK %s" % (r, np.round(vals, 5).tolist()), flush=True)
    dist.shutdown()
""")


def kvstore_two_process():
    worker = os.path.join(tempfile.mkdtemp(), "worker.py")
    with open(worker, "w") as f:
        f.write(KV_WORKER)
    # rendezvous timeout raised above the 300 s jax default, subprocess
    # budget raised with it, and a timed-out attempt counts as a retry:
    # under a saturated 1-core host (full nightly suite) Gloo connects can
    # take minutes
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               MXNET_DIST_INIT_TIMEOUT="420")
    res = None
    for _attempt in range(3):
        try:
            res = subprocess.run(
                [sys.executable, os.path.join(REPO, "tools", "launch.py"),
                 "-n", "2", "--launcher", "local", sys.executable, worker],
                env=env, capture_output=True, text=True, timeout=540)
        except subprocess.TimeoutExpired:
            continue
        if res.returncode == 0:
            break
    assert res is not None and res.returncode == 0, (
        "launch attempts timed out" if res is None
        else res.stdout + res.stderr)
    lines = sorted(l.split("_OK ")[1] for l in res.stdout.splitlines()
                   if "_OK" in l)
    assert len(lines) == 2 and lines[0] == lines[1], res.stdout
    print("dist_sync 2-process: both ranks converged to identical params")


def main():
    n = sharded_jit_dp()
    kvstore_two_process()
    print("MULTI-DEVICES TUTORIAL OK (mesh=%d + 2-process dist_sync)" % n)


if __name__ == "__main__":
    main()
