"""Runnable companion to docs/tutorials/new_op.md (reference
``docs/faq/new_op.md``): the three ways to add an operator, fastest-path
first.

1. **Registry op (TPU-native)**: a pure jnp function registered with
   ``ops.registry.register`` — jax traces it, AD derives the backward,
   XLA fuses it into surrounding graphs.  This replaces the reference's
   C++ NNVM registration for almost every op in this repo.
2. **CustomOp (reference-compatible)**: host-python forward/backward via
   ``mx.operator.CustomOp`` — runs through ``jax.pure_callback`` so it
   still works inside jitted graphs.
3. Pallas kernels for hot loops (see ops/pallas_kernels.py and
   docs/PERF_NOTES.md; not exercised here).

Run: ./dev.sh python examples/tutorials/new_op.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def registry_op():
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import register, unregister

    @register("tutorial_softshrink")
    def softshrink(data, *, lambd=0.5):
        """y = sign(x)·max(|x|−λ, 0) — pure jnp; backward comes from AD."""
        return jnp.sign(data) * jnp.maximum(jnp.abs(data) - lambd, 0.0)

    try:
        x = nd.array(np.array([-2.0, -0.3, 0.2, 1.5], np.float32))
        x.attach_grad()
        with autograd.record():
            y = nd.tutorial_softshrink(x, lambd=0.5)
        y.backward(nd.ones((4,)))
        np.testing.assert_allclose(y.asnumpy(), [-1.5, 0.0, 0.0, 1.0],
                                   atol=1e-6)
        np.testing.assert_allclose(x.grad.asnumpy(), [1, 0, 0, 1], atol=1e-6)
        print("registry op: forward + AD backward OK")
    finally:
        unregister("tutorial_softshrink")


def custom_op():
    class Clip01(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0],
                        nd.array(np.clip(in_data[0].asnumpy(), 0.0, 1.0)))

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            x = in_data[0].asnumpy()
            g = out_grad[0].asnumpy() * ((x > 0) & (x < 1))
            self.assign(in_grad[0], req[0], nd.array(g.astype(np.float32)))

    @mx.operator.register("tutorial_clip01")
    class Clip01Prop(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            return Clip01()

    try:
        x = nd.array(np.array([-0.5, 0.25, 0.75, 2.0], np.float32))
        x.attach_grad()
        with autograd.record():
            y = nd.Custom(x, op_type="tutorial_clip01")
        y.backward(nd.ones((4,)))
        np.testing.assert_allclose(y.asnumpy(), [0.0, 0.25, 0.75, 1.0])
        np.testing.assert_allclose(x.grad.asnumpy(), [0, 1, 1, 0])
        print("CustomOp: host forward/backward through pure_callback OK")
    finally:
        mx.operator.unregister("tutorial_clip01")


def main():
    registry_op()
    custom_op()
    print("NEW-OP TUTORIAL OK")


if __name__ == "__main__":
    main()
