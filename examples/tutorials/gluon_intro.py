"""Companion script for docs/tutorials/gluon_intro.md — the imperative
Gluon workflow end-to-end (reference docs/tutorials/gluon/gluon.md):
define a net, train with autograd + Trainer, save/load parameters,
hybridize for compiled speed, export + reload through the deployment
predictor."""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd

# --- data: two interleaved spirals (a small nonlinear problem) -----------
rng = np.random.RandomState(0)
n = 256
t = rng.rand(n) * 3 * np.pi
lab = rng.randint(0, 2, n)
r = t / (3 * np.pi) + 0.05 * rng.randn(n)
X = np.stack([r * np.cos(t + np.pi * lab), r * np.sin(t + np.pi * lab)],
             axis=1).astype(np.float32)
y = lab.astype(np.float32)

# --- 1. define a net imperatively ----------------------------------------
net = gluon.nn.Sequential()
net.add(gluon.nn.Dense(64, activation="relu"),
        gluon.nn.Dense(64, activation="relu"),
        gluon.nn.Dense(2))
net.initialize(mx.init.Xavier())

# --- 2. train with autograd + Trainer ------------------------------------
trainer = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 1e-2})
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
for epoch in range(60):
    with autograd.record():
        loss = loss_fn(net(nd.array(X)), nd.array(y))
    loss.backward()
    trainer.step(n)
pred = net(nd.array(X)).asnumpy().argmax(axis=1)
acc = (pred == y).mean()
print("imperative training accuracy: %.3f" % acc)
assert acc > 0.9, acc

# --- 3. save / load parameters -------------------------------------------
tmp = tempfile.mkdtemp()
pfile = os.path.join(tmp, "spiral.params")
net.save_parameters(pfile)
net2 = gluon.nn.Sequential()
net2.add(gluon.nn.Dense(64, activation="relu"),
         gluon.nn.Dense(64, activation="relu"),
         gluon.nn.Dense(2))
net2.load_parameters(pfile)
np.testing.assert_allclose(net2(nd.array(X)).asnumpy(),
                           net(nd.array(X)).asnumpy(), rtol=1e-6)
print("save/load round-trip OK")

# --- 4. hybridize: compile the whole block as one XLA module -------------
net3 = gluon.nn.HybridSequential()
net3.add(gluon.nn.Dense(64, activation="relu"),
         gluon.nn.Dense(64, activation="relu"),
         gluon.nn.Dense(2))
net3.initialize()
net3.load_parameters(pfile)       # same structural names
net3.hybridize()
out_h = net3(nd.array(X))         # first call traces + compiles
np.testing.assert_allclose(out_h.asnumpy(), net(nd.array(X)).asnumpy(),
                           rtol=1e-5, atol=1e-6)
print("hybridized forward matches")

# --- 5. export the deployment pair and reload through the predictor ------
prefix = os.path.join(tmp, "spiral")
net3.export(prefix)               # spiral-symbol.json + spiral-0000.params
from mxnet_tpu import predictor

pred_exe = predictor.create(prefix + "-symbol.json", prefix + "-0000.params",
                            {"data": X.shape})
pred_exe.set_input("data", X)
pred_exe.forward()
np.testing.assert_allclose(pred_exe.get_output(0), out_h.asnumpy(),
                           rtol=1e-5, atol=1e-6)
print("deployment predictor matches")

print("GLUON-INTRO TUTORIAL OK")
