"""Policy-gradient RL (reference `example/reinforcement-learning/` — a3c/
dqn/ddpg on gym; here REINFORCE on an in-process gridworld, zero-egress).

Environment: 5x5 grid, start at (0,0), goal at (4,4), 20-step episodes,
reward 1 at the goal else -0.01.  Policy: MLP over one-hot position →
4 actions; actions are sampled host-side from the softmax probabilities
inside the environment loop, and the learning pass re-runs the policy
under ``autograd.record`` to differentiate the log-prob of the taken
actions weighted by discounted returns — the same actor-loss mechanics as
the reference's a3c example.

Run: ``./dev.sh python examples/reinforcement-learning/reinforce_gridworld.py``
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

SIZE, GOAL, STEPS = 5, (4, 4), 20
MOVES = np.array([[0, 1], [0, -1], [1, 0], [-1, 0]])  # E W S N


def rollout(net, nd, rng, batch):
    """Vectorized batch of episodes; returns (states, actions, returns)."""
    pos = np.zeros((batch, 2), np.int64)
    all_s, all_a, all_r = [], [], []
    for _ in range(STEPS):
        onehot = np.zeros((batch, SIZE * SIZE), np.float32)
        onehot[np.arange(batch), pos[:, 0] * SIZE + pos[:, 1]] = 1.0
        logits = net(nd.array(onehot))
        probs = nd.softmax(logits).asnumpy()
        # sample per-row actions (np for the env loop; the learning pass
        # below re-runs the net under autograd)
        u = rng.rand(batch, 1)
        act = (probs.cumsum(axis=1) < u).sum(axis=1).clip(0, 3)
        pos = np.clip(pos + MOVES[act], 0, SIZE - 1)
        done = (pos[:, 0] == GOAL[0]) & (pos[:, 1] == GOAL[1])
        r = np.where(done, 1.0, -0.01).astype(np.float32)
        all_s.append(onehot)
        all_a.append(act)
        all_r.append(r)
        # reset finished episodes to start (continuing task formulation)
        pos[done] = 0
    S = np.concatenate(all_s)
    A = np.concatenate(all_a).astype(np.float32)
    R = np.stack(all_r)                      # (T, B)
    G = np.zeros_like(R)
    run = np.zeros(batch, np.float32)
    for t in range(STEPS - 1, -1, -1):       # discounted returns
        run = R[t] + 0.95 * run
        G[t] = run
    return S, A, G.reshape(-1), R.sum() / batch


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=150)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.gluon import nn, Trainer

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="tanh"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": args.lr})

    first = last = None
    for it in range(args.iters):
        S, A, G, ep_reward = rollout(net, nd, rng, args.batch)
        adv = (G - G.mean()) / (G.std() + 1e-6)
        with autograd.record():
            logp = nd.log_softmax(net(nd.array(S)))
            taken = nd.pick(logp, nd.array(A), axis=1)
            loss = -(taken * nd.array(adv.astype(np.float32)))
        loss.backward()
        trainer.step(len(S))
        if first is None:
            first = ep_reward
        last = ep_reward
        if it % 25 == 0:
            print("iter %d avg episode reward %.3f" % (it, ep_reward))
    print("episode reward %.3f -> %.3f" % (first, last))
    assert last > first + 0.3, "policy failed to improve"
    print("REINFORCE OK")


if __name__ == "__main__":
    main()
