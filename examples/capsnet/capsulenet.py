"""CapsNet (Dynamic Routing Between Capsules) — reference
``example/capsnet/{capsulenet.py,capsulelayers.py}``.

The reference builds squash / primary-caps / routing as symbol-graph
helpers with the 3-iteration routing loop unrolled into the symbol graph
(capsulelayers.py CapsuleLayer.__call__).  Here the same three pieces are
Gluon HybridBlocks whose routing loop is a STATIC Python unroll inside
``hybrid_forward`` — jit sees a fixed 3-step dataflow (routing logits are
recomputed, never carried as Python state), so the whole net compiles to
one XLA module.  Margin loss matches capsulenet.py:L? (m+ 0.9, m− 0.1,
λ 0.5).

Run: ./dev.sh python examples/capsnet/capsulenet.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def squash(F, s, axis):
    """v = |s|²/(1+|s|²) · s/|s| (reference capsulelayers.py squash)."""
    sq = F.sum(F.square(s), axis=axis, keepdims=True)
    return F.broadcast_mul(s, sq / (1.0 + sq) / F.sqrt(sq + 1e-9))


class PrimaryCaps(gluon.HybridBlock):
    """Conv -> (B, n_caps, dim) capsules, squashed (primary_caps)."""

    def __init__(self, dim_vector=8, n_channels=8, kernel=3, stride=2, **kw):
        super().__init__(**kw)
        self.dim = dim_vector
        with self.name_scope():
            self.conv = nn.Conv2D(dim_vector * n_channels, kernel, stride)

    def hybrid_forward(self, F, x):
        out = self.conv(x)  # (B, dim*ch, H, W)
        out = F.Reshape(out, shape=(0, -1, self.dim))
        return squash(F, out, axis=2)


class DigitCaps(gluon.HybridBlock):
    """Fully-connected capsule layer with dynamic routing (CapsuleLayer).

    W: (in_caps, out_caps, in_dim, out_dim).  Routing: 3 iterations of
    softmax(b) coupling -> weighted sum -> squash -> agreement update, the
    loop statically unrolled (XLA-friendly; the reference unrolls into the
    symbol graph the same way).
    """

    def __init__(self, in_caps, out_caps=10, in_dim=8, out_dim=16,
                 num_routing=3, **kw):
        super().__init__(**kw)
        self.nr = int(num_routing)
        self.ic, self.idim = in_caps, in_dim
        self.oc, self.od = out_caps, out_dim
        with self.name_scope():
            self.w = self.params.get(
                "weight", shape=(in_caps, out_caps, in_dim, out_dim),
                init=mx.init.Normal(0.1))

    def hybrid_forward(self, F, x, w):
        # u_hat[b,i,j,d'] = Σ_d x[b,i,d]·W[i,j,d,d'] — broadcast-and-reduce
        # (XLA fuses this into a batched contraction; B·in·out·8·16 floats)
        x5 = F.Reshape(x, shape=(-1, self.ic, 1, self.idim, 1))
        w5 = F.Reshape(w, shape=(1, self.ic, self.oc, self.idim, self.od))
        u_hat = F.sum(F.broadcast_mul(x5, w5), axis=3)  # (B, in, out, od)
        # routing by agreement; coupling logits recomputed functionally
        b_ij = F.zeros_like(F.slice_axis(u_hat, axis=3, begin=0, end=1))
        b_ij = F.Reshape(b_ij, shape=(0, 0, -1))  # (B, in, out)
        u_nograd = F.BlockGrad(u_hat)
        for it in range(self.nr):
            c = F.softmax(b_ij, axis=2)  # coupling over out-caps
            # last iteration lets gradients flow through u_hat (reference
            # routes on stop-gradient predictions except the final pass)
            u = u_hat if it == self.nr - 1 else u_nograd
            s = F.sum(F.broadcast_mul(u, F.Reshape(c, shape=(0, 0, 0, 1))),
                      axis=1)  # (B, out, od)
            v = squash(F, s, axis=2)
            if it < self.nr - 1:
                v4 = F.Reshape(v, shape=(0, 1, -1, self.od))  # (B,1,out,od)
                b_ij = b_ij + F.sum(F.broadcast_mul(u_nograd, v4), axis=3)
        return v  # (B, out_caps, out_dim)


class CapsNet(gluon.HybridBlock):
    def __init__(self, classes=10, in_caps=None, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.conv1 = nn.Conv2D(64, 3, 1, activation="relu")
            self.primary = PrimaryCaps(dim_vector=8, n_channels=8)
            self.digit = DigitCaps(in_caps=in_caps, out_caps=classes)

    def hybrid_forward(self, F, x):
        v = self.digit(self.primary(self.conv1(x)))
        # class scores are capsule lengths
        return F.sqrt(F.sum(F.square(v), axis=2) + 1e-9)


def margin_loss(F, lengths, y, classes, m_pos=0.9, m_neg=0.1, lam=0.5):
    """L = T·max(0, m+−|v|)² + λ(1−T)·max(0, |v|−m−)² (capsulenet.py)."""
    t = F.one_hot(y, classes)
    pos = F.square(F.maximum(0.0, m_pos - lengths))
    neg = F.square(F.maximum(0.0, lengths - m_neg))
    return F.sum(t * pos + lam * (1.0 - t) * neg, axis=1)


def main(epochs=12, batch=64, lr=0.002, seed=0):
    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split

    mx.random.seed(seed)
    np.random.seed(seed)
    X, y = load_digits(return_X_y=True)
    X = (X.astype(np.float32) / 16.0).reshape(-1, 1, 8, 8)
    Xtr, Xte, ytr, yte = train_test_split(X, y.astype(np.float32),
                                          test_size=0.25, random_state=seed,
                                          stratify=y)
    # 8x8 input -> conv1 (3x3) 6x6 -> primary (3x3 s2) 2x2 x 8ch = 32 caps
    net = CapsNet(classes=10, in_caps=32)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    import mxnet_tpu.ndarray as F

    n = len(Xtr)
    for ep in range(epochs):
        perm = np.random.permutation(n)
        for s in range(0, n - batch + 1, batch):
            idx = perm[s:s + batch]
            xb, yb = nd.array(Xtr[idx]), nd.array(ytr[idx])
            with autograd.record():
                lengths = net(xb)
                loss = margin_loss(F, lengths, yb, 10).mean()
            loss.backward()
            trainer.step(batch)
    preds = np.argmax(net(nd.array(Xte)).asnumpy(), axis=1)
    acc = float((preds == yte).mean())
    print("capsnet: test acc %.4f (3-iteration dynamic routing)" % acc)
    return acc


if __name__ == "__main__":
    main()
