"""Conditional GAN — reference ``example/gan/`` (CGAN_train.R: an
MNIST conditional GAN where the generator concatenates the class one-hot
to the noise vector and the discriminator gets the label as extra input
channels).

Same construction in Gluon on sklearn digits (8×8, no egress), trained
imperatively with SigmoidBinaryCrossEntropyLoss.  Conditioning quality is
MEASURED: a small classifier pre-trained on real digits must recognize the
class the generator was asked for (far above the 10% chance rate).

Run: ./dev.sh python examples/gan/cgan.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

ZDIM, CLASSES = 16, 10


class Generator(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.fc1 = nn.Dense(128, activation="relu")
            self.fc2 = nn.Dense(128, activation="relu")
            self.out = nn.Dense(64, activation="sigmoid")  # 8x8 pixels in [0,1]

    def hybrid_forward(self, F, z, onehot):
        h = self.fc1(F.Concat(z, onehot, dim=1))
        return self.out(self.fc2(h))


class Discriminator(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.fc1 = nn.Dense(128, activation="relu")
            self.fc2 = nn.Dense(64, activation="relu")
            self.out = nn.Dense(1)

    def hybrid_forward(self, F, x, onehot):
        return self.out(self.fc2(self.fc1(F.Concat(x, onehot, dim=1))))


def train_ref_classifier(Xtr, ytr, seed):
    """Real-data digit classifier used only to SCORE conditional samples."""
    clf = nn.HybridSequential()
    clf.add(nn.Dense(96, activation="relu"), nn.Dense(10))
    clf.initialize(mx.init.Xavier())
    tr = gluon.Trainer(clf.collect_params(), "adam", {"learning_rate": 2e-3})
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(seed)
    for _ in range(300):
        idx = rng.randint(0, len(Xtr), 64)
        xb, yb = nd.array(Xtr[idx]), nd.array(ytr[idx])
        with autograd.record():
            l = lossfn(clf(xb), yb)
        l.backward()
        tr.step(64)
    return clf


def main(steps=1500, batch=64, lr=1e-3, seed=0):
    from sklearn.datasets import load_digits

    mx.random.seed(seed)
    np.random.seed(seed)
    X, y = load_digits(return_X_y=True)
    X = (X.astype(np.float32) / 16.0)
    y = y.astype(np.float32)

    G, D = Generator(), Discriminator()
    G.initialize(mx.init.Xavier())
    D.initialize(mx.init.Xavier())
    gt = gluon.Trainer(G.collect_params(), "adam", {"learning_rate": lr, "beta1": 0.5})
    dt = gluon.Trainer(D.collect_params(), "adam", {"learning_rate": lr, "beta1": 0.5})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    rng = np.random.RandomState(seed)
    ones, zeros = nd.ones((batch,)), nd.zeros((batch,))

    for s in range(steps):
        idx = rng.randint(0, len(X), batch)
        real, lab = nd.array(X[idx]), y[idx]
        oh = nd.one_hot(nd.array(lab), CLASSES)
        z = nd.array(rng.randn(batch, ZDIM).astype(np.float32))
        fake_lab = rng.randint(0, CLASSES, batch).astype(np.float32)
        foh = nd.one_hot(nd.array(fake_lab), CLASSES)
        # D step: real(label) -> 1, G(z|label) -> 0
        with autograd.record():
            fake = G(z, foh)
            dl = (bce(D(real, oh), ones)
                  + bce(D(nd.BlockGrad(fake), foh), zeros)).mean()
        dl.backward()
        dt.step(batch)
        # G step: fool D on the SAME condition
        with autograd.record():
            gl = bce(D(G(z, foh), foh), ones).mean()
        gl.backward()
        gt.step(batch)

    # conditional fidelity: ask G for each class, score with a real-data
    # classifier (the measurable CGAN property)
    clf = train_ref_classifier(X, y, seed)
    want = np.repeat(np.arange(CLASSES), 20).astype(np.float32)
    z = nd.array(np.random.RandomState(seed + 2).randn(len(want), ZDIM).astype(np.float32))
    samples = G(z, nd.one_hot(nd.array(want), CLASSES))
    got = clf(samples).asnumpy().argmax(1)
    cond_acc = float((got == want).mean())
    print("cgan: conditional fidelity %.3f (chance 0.10), D loss %.3f, "
          "G loss %.3f" % (cond_acc, float(dl.asnumpy()), float(gl.asnumpy())))
    return cond_acc


if __name__ == "__main__":
    main()
