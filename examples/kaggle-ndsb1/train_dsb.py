"""Kaggle National Data Science Bowl 1 (plankton) — reference
``example/kaggle-ndsb1/{symbol_dsb.py,train_dsb.py,gen_img_list.py}``.

The reference recipe: build train/val image lists, pack to RecordIO
(im2rec), train the ``symbol_dsb`` conv net with aspect-augmentation via
``ImageRecordIter``.  Offline port: synthetic "plankton" (procedural blob
silhouettes per class, the dataset's grayscale shape-classification
character) packed through the SAME .rec pipeline, then the dsb symbol at
reduced width.

Run: ./dev.sh python examples/kaggle-ndsb1/train_dsb.py
"""
from __future__ import annotations

import io as _io
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def get_symbol(num_classes=6, width=1):
    """symbol_dsb.py:21-47 scaled by ``width`` (reference trains 121-way)."""
    net = mx.sym.Variable("data")
    for nf, k, pool in [(8 * width, 5, True), (16 * width, 3, True),
                        (32 * width, 3, True)]:
        net = mx.sym.Convolution(net, kernel=(k, k), num_filter=nf,
                                 pad=(k // 2, k // 2))
        net = mx.sym.Activation(net, act_type="relu")
        if pool:
            net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                                 stride=(2, 2))
    net = mx.sym.Flatten(net)
    net = mx.sym.Dropout(net, p=0.25)
    net = mx.sym.FullyConnected(net, num_hidden=64)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def draw_plankton(rng, cls, size=32):
    """Procedural class-conditional silhouettes (disk / ring / bar / cross /
    twin disks / wedge) with position jitter — grayscale shape
    classification, the dataset's character."""
    yy, xx = np.mgrid[:size, :size].astype(np.float32)
    cy, cx = size / 2 + rng.randn(2) * 2
    dy, dx = yy - cy, xx - cx
    r = np.sqrt(dy ** 2 + dx ** 2)
    s = size / 4 + rng.randn() * 1.0
    if cls == 0:
        mask = r < s
    elif cls == 1:
        mask = (r < s) & (r > s * 0.55)
    elif cls == 2:
        mask = (np.abs(dy) < s * 0.35) & (np.abs(dx) < s * 1.4)
    elif cls == 3:
        mask = ((np.abs(dy) < s * 0.3) | (np.abs(dx) < s * 0.3)) & (r < s * 1.3)
    elif cls == 4:
        mask = (np.sqrt((dy - s * 0.8) ** 2 + dx ** 2) < s * 0.55) | (
            np.sqrt((dy + s * 0.8) ** 2 + dx ** 2) < s * 0.55)
    else:
        mask = (r < s * 1.2) & (np.abs(np.arctan2(dy, dx)) < 0.9)
    img = mask.astype(np.float32) + rng.rand(size, size) * 0.15
    return (np.clip(img, 0, 1) * 255).astype(np.uint8)


def build_rec(path, rng, n, classes, size=32):
    """gen_img_list.py + im2rec collapsed: pack synthetic JPEGs to .rec."""
    from PIL import Image

    rec = mx.recordio.MXIndexedRecordIO(path + ".idx", path, "w")
    labels = rng.randint(0, classes, n)
    for i in range(n):
        img = draw_plankton(rng, int(labels[i]), size)
        buf = _io.BytesIO()
        Image.fromarray(np.stack([img] * 3, -1)).save(buf, format="JPEG",
                                                      quality=92)
        rec.write_idx(i, mx.recordio.pack(
            mx.recordio.IRHeader(0, float(labels[i]), i, 0), buf.getvalue()))
    rec.close()
    return labels


def main(classes=6, epochs=10, batch=32, n_train=640, n_val=128, seed=0):
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    with tempfile.TemporaryDirectory() as td:
        tr_rec = os.path.join(td, "train.rec")
        va_rec = os.path.join(td, "val.rec")
        build_rec(tr_rec, rng, n_train, classes)
        build_rec(va_rec, rng, n_val, classes)
        train = mx.io.ImageRecordIter(
            path_imgrec=tr_rec, data_shape=(3, 28, 28), batch_size=batch,
            rand_crop=True, rand_mirror=True, shuffle=True)
        val = mx.io.ImageRecordIter(
            path_imgrec=va_rec, data_shape=(3, 28, 28), batch_size=batch)

        mod = mx.mod.Module(get_symbol(classes))
        mod.fit(train, eval_data=val, num_epoch=epochs, optimizer="adam",
                optimizer_params={"learning_rate": 2e-3},
                eval_metric="acc")
        val.reset()
        metric = mx.metric.Accuracy()
        mod.score(val, metric)
        acc = metric.get()[1]
        print("ndsb1 synthetic val acc %.3f" % acc)
        return acc


if __name__ == "__main__":
    main()
