"""LSTNet multivariate time-series forecasting — reference
``example/multivariate_time_series/src/lstnet.py`` (Lai et al., LSTNet).

Same four components as the reference symbol graph, on the Module API:

* causal CNN bank over the (q, num_series) window (multiple filter widths,
  left-padded so output length == q);
* GRU over the CNN features (reference stacked ``mx.rnn`` cells unrolled);
* skip-GRU sampling the sequence every ``seasonal_period`` steps;
* per-series autoregressive linear head added to the neural output
  (the component that makes LSTNet robust to scale drift).

Offline data: synthetic seasonal multivariate series (sines with per-series
phase + trend + noise) instead of the electricity.txt download.

Run: ./dev.sh python examples/multivariate_time_series/lstnet.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def synthetic_series(rng, T=2000, series=4, period=24):
    t = np.arange(T)[:, None]
    phase = rng.rand(1, series) * 2 * np.pi
    scale = 0.5 + rng.rand(1, series)
    x = (np.sin(2 * np.pi * t / period + phase) * scale
         + 0.0002 * t * rng.randn(1, series)
         + 0.1 * rng.randn(T, series))
    return x.astype(np.float32)


def build_iters(x, q, horizon, splits=(0.6, 0.2), batch=64):
    """Window the series into (n, q, series) → (n, series) examples
    (reference build_iters)."""
    n = x.shape[0] - q - horizon + 1
    xs = np.stack([x[i:i + q] for i in range(n)])
    ys = x[q + horizon - 1:q + horizon - 1 + n]
    n_tr = int(n * splits[0])
    n_va = int(n * splits[1])
    mk = lambda a, b: mx.io.NDArrayIter(xs[a:b], ys[a:b], batch,
                                        label_name="lro_label")
    return mk(0, n_tr), mk(n_tr, n_tr + n_va), mk(n_tr + n_va, n)


def sym_gen(q, series, filter_list=(3, 6, 12), num_filter=24, rnn_hidden=32,
            skip_hidden=16, seasonal_period=24, dropout=0.1):
    """The LSTNet symbol (reference sym_gen, lstnet.py:121-188)."""
    X = mx.sym.Variable("data")            # (B, q, series)
    Y = mx.sym.Variable("lro_label")

    conv_input = mx.sym.reshape(X, shape=(0, 1, q, -1))
    outputs = []
    for fs in filter_list:
        padi = mx.sym.pad(conv_input, mode="constant", constant_value=0,
                          pad_width=(0, 0, 0, 0, fs - 1, 0, 0, 0))
        convi = mx.sym.Convolution(padi, kernel=(fs, series),
                                   num_filter=num_filter)
        acti = mx.sym.Activation(convi, act_type="relu")
        # (B, F, q, 1) -> (B, q, F)
        trans = mx.sym.reshape(
            mx.sym.transpose(acti, axes=(0, 2, 1, 3)), shape=(0, 0, 0))
        outputs.append(trans)
    cnn_features = mx.sym.Concat(*outputs, dim=2)
    cnn_features = mx.sym.Dropout(cnn_features, p=dropout)

    # GRU over the full window (reference stacks mx.rnn cells + unroll)
    from mxnet_tpu import rnn as mrnn

    cell = mrnn.SequentialRNNCell()
    cell.add(mrnn.GRUCell(rnn_hidden, prefix="gru_"))
    cell.add(mrnn.DropoutCell(dropout))
    outputs, _ = cell.unroll(q, inputs=cnn_features, merge_outputs=False)
    rnn_features = outputs[-1]                           # (B, H)

    # skip-GRU: tap outputs every seasonal_period steps, newest first
    # (reference lstnet.py:165-170 reverses then samples)
    skip_cell = mrnn.SequentialRNNCell()
    skip_cell.add(mrnn.GRUCell(skip_hidden, prefix="skipgru_"))
    skip_cell.add(mrnn.DropoutCell(dropout))
    skip_outputs, _ = skip_cell.unroll(q, inputs=cnn_features,
                                       merge_outputs=False)
    taps = [skip_outputs[i] for i in range(q - 1, -1, -seasonal_period)]
    skip_features = mx.sym.concat(*taps, dim=1)

    # per-series AR head (reference lstnet.py:173-178)
    ar_list = []
    for i in range(series):
        ts = mx.sym.slice_axis(X, axis=2, begin=i, end=i + 1)
        ar_list.append(mx.sym.FullyConnected(ts, num_hidden=1))
    ar_output = mx.sym.concat(*ar_list, dim=1)

    neural = mx.sym.concat(rnn_features, skip_features, dim=1)
    neural_output = mx.sym.FullyConnected(neural, num_hidden=series)
    model_output = neural_output + ar_output
    return mx.sym.LinearRegressionOutput(model_output, Y, name="lro")


def main(epochs=8, q=48, series=4, horizon=3, batch=64, seed=0):
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    x = synthetic_series(rng, series=series)
    train_it, val_it, _ = build_iters(x, q, horizon, batch=batch)
    net = sym_gen(q, series)

    mod = mx.mod.Module(net, label_names=("lro_label",))
    mod.bind(data_shapes=train_it.provide_data,
             label_shapes=train_it.provide_label)
    mod.init_params(mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 2e-3})
    metric = mx.metric.MSE()
    for epoch in range(epochs):
        train_it.reset()
        metric.reset()
        for b in train_it:
            mod.forward(b, is_train=True)
            mod.update_metric(metric, b.label)
            mod.backward()
            mod.update()
        print("epoch %d  train mse %.4f" % (epoch, metric.get()[1]))

    val_it.reset()
    metric.reset()
    mod.score(val_it, metric)
    mse = metric.get()[1]
    naive = float(np.mean((x[q + horizon - 1:] - x[q - 1:-(horizon)]) ** 2))
    print("val mse %.4f vs naive-persistence %.4f" % (mse, naive))
    return mse, naive


if __name__ == "__main__":
    main()
