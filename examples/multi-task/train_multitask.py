"""Multi-task training (reference `example/multi-task/example_multi_task.py`:
one backbone, two softmax heads — digit class + odd/even — trained jointly
with a combined loss and per-task metrics).

Synthetic stand-in for MNIST: 2D blob coordinates lifted to 16-D; task A
classifies the blob (4-way), task B classifies its parity (2-way, derived
from the blob id) — correlated tasks sharing a representation, like the
reference's digit/parity split.

Run: ``./dev.sh python examples/multi-task/train_multitask.py``
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def make_data(rng, n):
    centers = np.array([[2, 2], [-2, 2], [-2, -2], [2, -2]], np.float32)
    y = rng.randint(0, 4, n)
    x = centers[y] + 0.4 * rng.randn(n, 2).astype(np.float32)
    pad = 0.1 * rng.randn(n, 14).astype(np.float32)
    return (np.concatenate([x, pad], 1).astype(np.float32),
            y.astype(np.float32), (y % 2).astype(np.float32))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=80)
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.gluon import nn, Trainer, HybridBlock
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    Xtr, ya, yb = make_data(rng, 2048)
    Xte, ta, tb = make_data(rng, 512)

    class MultiTask(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.backbone = nn.Dense(64, activation="relu")
                self.head_a = nn.Dense(4)   # blob id
                self.head_b = nn.Dense(2)   # parity

        def hybrid_forward(self, F, x):
            h = self.backbone(x)
            return self.head_a(h), self.head_b(h)

    net = MultiTask()
    net.initialize(mx.init.Xavier())
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": args.lr})
    loss_fn = SoftmaxCrossEntropyLoss()
    metric = mx.metric.CompositeEvalMetric(
        [mx.metric.Accuracy(name="task_a"), mx.metric.Accuracy(name="task_b")])

    for epoch in range(args.epochs):
        x = nd.array(Xtr)
        with autograd.record():
            la_logits, lb_logits = net(x)
            # joint objective, like the reference's summed softmax heads
            loss = loss_fn(la_logits, nd.array(ya)) + \
                loss_fn(lb_logits, nd.array(yb))
        loss.backward()
        trainer.step(len(Xtr))

    metric.reset()
    pa, pb = net(nd.array(Xte))
    # per-task update: CompositeEvalMetric.update feeds every child ALL
    # pairs (pooled accuracy); the reference example uses a custom
    # Multi_Accuracy for exactly this reason
    metric.get_metric(0).update(nd.array(ta), pa)
    metric.get_metric(1).update(nd.array(tb), pb)
    names, accs = metric.get()
    print("  ".join("%s=%.3f" % nv for nv in zip(names, accs)))
    assert all(a > 0.9 for a in accs), (names, accs)
    print("MULTI-TASK OK")


if __name__ == "__main__":
    main()
