"""NCE / sampled-softmax training — reference ``example/nce-loss/``
(wordvec.py, nce.py: noise-contrastive estimation over a large vocabulary).

The full-softmax denominator over a big vocab is the cost NCE avoids: score
the TRUE class plus k noise samples drawn from the unigram distribution and
train a binary logistic discriminator on them (exercises Embedding, the
sampler ops, and the binary-logistic path).

A skip-gram-style toy task: contexts predict center words whose identity is
a deterministic function of context (vocab/k per main() defaults).  The
validation metric is full-softmax argmax accuracy with the SAME embeddings
— showing the sampled objective learned the right scores without ever
computing the full softmax during training.

Run: ./dev.sh python examples/nce-loss/train_nce.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


_PERM_CACHE = {}


def make_data(rng, n, vocab, ctx_width=4):
    # permutation keyed by vocab: stale cross-vocab reuse would map labels
    # outside the model's embedding range
    if vocab not in _PERM_CACHE:
        _PERM_CACHE[vocab] = np.random.RandomState(99).permutation(vocab)
    _PERM = _PERM_CACHE[vocab]
    ctx = rng.randint(0, vocab, (n, ctx_width)).astype(np.float32)
    # center word = fixed permutation of the first context word — learnable
    # by aligning in/out embeddings (a skip-gram-like co-occurrence rule)
    center = _PERM[ctx[:, 0].astype(np.int64)]
    return ctx, center.astype(np.float32)


def main(vocab=500, dim=32, k=8, steps=900, batch=128, lr=20.0, seed=0):
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)

    class NCEModel(mx.gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed_in = mx.gluon.nn.Embedding(vocab, dim)
                self.embed_out = mx.gluon.nn.Embedding(vocab, dim)

        def hybrid_forward(self, F, ctx_words, cand_words):
            # ctx (B, W) -> mean context vector; cand (B, 1+k) candidate ids
            h = self.embed_in(ctx_words).mean(axis=1)  # (B, D)
            w = self.embed_out(cand_words)  # (B, 1+k, D)
            return (w * F.expand_dims(h, axis=1)).sum(axis=-1)  # (B, 1+k)

    bce = mx.gluon.loss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)
    net = NCEModel()
    # dot-product scores need O(1) logits and embedding-grad touch rate
    # scales as batch*(1+k)/vocab — hence the large-looking lr
    net.initialize(mx.init.Uniform(0.25))
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": lr})

    losses = []
    for s in range(steps):
        ctx, center = make_data(rng, batch, vocab)
        noise = rng.randint(0, vocab, (batch, k)).astype(np.float32)
        cands = np.concatenate([center[:, None], noise], axis=1)
        target = np.zeros((batch, 1 + k), np.float32)
        target[:, 0] = 1.0  # true word is the positive
        with autograd.record():
            logits = net(nd.array(ctx), nd.array(cands))
            # binary logistic NCE objective
            # the library's stable binary logistic loss IS the NCE
            # discriminator objective (gluon/loss.py)
            loss = nd.mean(bce(logits, nd.array(target)))
        loss.backward()
        trainer.step(1)  # the NCE objective is already a mean over the batch
        losses.append(float(loss.asnumpy()))
        if s % 200 == 0:
            print("step %3d  nce loss %.4f" % (s, losses[-1]), flush=True)

    # validation: FULL-softmax retrieval accuracy with the trained embeddings
    ctx, center = make_data(np.random.RandomState(seed + 1), 256, vocab)
    h = net.embed_in(nd.array(ctx)).mean(axis=1)
    all_w = net.embed_out.weight.data()  # (V, D)
    scores = nd.dot(h, nd.transpose(all_w)).asnumpy()  # (B, V)
    acc = (scores.argmax(1) == center.astype(np.int64)).mean()
    print("FINAL nce: loss %.4f -> %.4f, full-softmax retrieval acc %.3f"
          % (losses[0], np.mean(losses[-20:]), acc))
    return losses, acc


if __name__ == "__main__":
    main()
