"""Faster R-CNN VGG16 end-to-end, jit-fused — BASELINE config 2.

The reference recipe is ``example/rcnn/train_end2end.py`` (VGG16 symbol
``rcnn/symbol/symbol_vgg.py``, 600×1000 input, host-side AnchorLoader +
proposal_target CustomOp, MutableModule rebinds per shape bucket).  The
TPU-native redesign compiles the ENTIRE train step — VGG16 trunk, RPN,
MultiProposal, on-device anchor/proposal targets, 7×7 ROIPooling, fc6/fc7
heads, all four losses, momentum SGD — into ONE XLA module at ONE static
shape (608×1024, the (600, 1000) resize bucket rounded to stride multiples),
exactly like the Deformable R-FCN north-star driver
(examples/deformable_rfcn/train_fused.py).

Mixed precision: bf16 trunk/fc (MXU dtype), fp32 box math throughout —
gt/im_info/rois never downcast, MultiProposal upcasts at entry, ROIPooling
does its bin arithmetic in fp32.

Usage:
  python examples/rcnn/train_fused.py                 # tiny CPU run
  python examples/rcnn/train_fused.py --vgg16 --bench \
      --image-shape 608 1024          # chip measurement (BASELINE config 2)
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.gluon.functional import functionalize
from mxnet_tpu.gluon.model_zoo.detection import FasterRCNN, faster_rcnn_vgg16
from mxnet_tpu.test_utils import load_module_by_path

_HERE = os.path.dirname(os.path.abspath(__file__))
_rfcn = load_module_by_path(
    os.path.join(_HERE, "..", "deformable_rfcn", "train_fused.py"),
    "_rfcn_train_fused_for_frcnn")
# same synthetic dataset family as the north star (bright rectangles on
# noise, -1-padded gt) — the detection pipelines share one data story
synthetic_voc = _rfcn.synthetic_coco
synthetic_voc_device = _rfcn.synthetic_coco_device


def _smooth_l1(pred, target, weight, sigma):
    from mxnet_tpu.ops.elemwise import smooth_l1

    return smooth_l1((pred - target) * weight, scalar=sigma)


def make_frcnn_train_step(net, batch, learning_rate=1e-3, momentum=0.9,
                          compute_dtype=None):
    """→ (step, state): ``step(state, data, im_info, gt, key, lr) ->
    (state, loss, parts)``, fully jittable, state donate-able.

    Loss heads follow the reference e2e symbol (symbol_vgg.py get_vgg_train):
    RPN softmax CE over sampled anchors + smooth-L1(σ=3)/RPN_BATCH; R-CNN
    softmax CE over the 128 sampled rois + class-specific
    smooth-L1(σ=1)/BATCH_ROIS with normalized targets (BBOX_STDS).
    """
    import jax
    import jax.numpy as jnp

    apply, names, vals, aux_names = functionalize(net, train=True)
    aux_set = set(aux_names)
    learn_idx = [i for i, n in enumerate(names) if n not in aux_set]
    aux_idx = [i for i, n in enumerate(names) if n in aux_set]
    Hf, Wf = net.feat_shape
    A = net.num_anchors
    a_total = Hf * Wf * A
    ncand = net.rpn_post_nms + net.max_gts
    cdtype = jnp.dtype(compute_dtype) if compute_dtype is not None else None

    def loss_fn(learn, aux, data, im_info, gt, key):
        merged = [None] * len(names)
        for i, v in zip(learn_idx, learn):
            merged[i] = v.astype(cdtype) if cdtype is not None else v
        for i, v in zip(aux_idx, aux):
            merged[i] = v
        k1, k2, k3 = jax.random.split(key, 3)
        nz_rpn = jax.random.uniform(k1, (batch, a_total, 2), jnp.float32)
        nz_prop = jax.random.uniform(k2, (batch, ncand, 2), jnp.float32)
        x = data.astype(cdtype) if cdtype is not None else data
        outs, new_aux = apply(merged, (x, im_info, gt, nz_rpn, nz_prop), k3)
        (rpn_cls, rpn_bbox, rpn_label, rpn_bt, rpn_bw,
         _rois, label, bbox_target, bbox_weight, cls_score, bbox_pred) = (
            jnp.asarray(o).astype(jnp.float32) for o in outs)

        # RPN losses (anchor order h·(W·A)+w·A+a, as rpn_anchor_target)
        logits = rpn_cls.reshape(batch, 2, A, Hf, Wf).transpose(0, 3, 4, 2, 1)
        logits = logits.reshape(batch, a_total, 2)
        valid = rpn_label >= 0
        lab = jnp.maximum(rpn_label, 0.0).astype(jnp.int32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
        rpn_cls_loss = jnp.where(valid, ce, 0.0).sum() / jnp.maximum(valid.sum(), 1)
        bp = rpn_bbox.reshape(batch, A, 4, Hf, Wf).transpose(0, 3, 4, 1, 2)
        bp = bp.reshape(batch, a_total, 4)
        rpn_bbox_loss = _smooth_l1(bp, rpn_bt, rpn_bw, 3.0).sum() / (
            net.rpn_batch * batch)

        # R-CNN head: class-specific regression (4·(C+1) deltas per roi)
        logp2 = jax.nn.log_softmax(cls_score, axis=-1)
        rcnn_cls_loss = -jnp.take_along_axis(
            logp2, label.astype(jnp.int32)[:, None], axis=1).mean()
        rcnn_bbox_loss = _smooth_l1(bbox_pred, bbox_target, bbox_weight, 1.0
                                    ).sum() / label.shape[0]

        total = rpn_cls_loss + rpn_bbox_loss + rcnn_cls_loss + rcnn_bbox_loss
        parts = jnp.stack([rpn_cls_loss, rpn_bbox_loss, rcnn_cls_loss,
                           rcnn_bbox_loss])
        return total, (new_aux, parts)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step(state, data, im_info, gt, key, lr=learning_rate):
        learn, mom, aux = state
        (loss, (new_aux, parts)), grads = grad_fn(learn, aux, data, im_info,
                                                  gt, key)
        if momentum:
            mom = [momentum * m + g for m, g in zip(mom, grads)]
            upd = mom
        else:
            upd = grads
        learn = [p - lr * g for p, g in zip(learn, upd)]
        return (learn, mom, new_aux), loss, parts

    import jax.numpy as jnp  # noqa: F811  (zeros_like below)
    learn_vals = [vals[i] for i in learn_idx]
    aux_vals = [vals[i] for i in aux_idx]
    mom_vals = [jnp.zeros_like(v) for v in learn_vals] if momentum else []
    return step, (learn_vals, mom_vals, aux_vals)


def build_net(vgg16, image_shape=None, classes=None, rpn_pre_nms=None,
              rpn_post_nms=None, init=True):
    """→ (net, image_shape, classes): the full VGG16 config-2 model, or a
    tiny-trunk CPU configuration with the same graph.

    ``rpn_pre_nms/rpn_post_nms`` override the TRAIN proposal counts
    (12000/2000); pass the reference TEST config (6000/300,
    rcnn/config.py:95-96) to build the inference twin — parameter names and
    shapes are proposal-count independent, so trained values drop in."""
    if vgg16:
        shape = tuple(image_shape or (608, 1024))
        classes = classes or 20
        net = faster_rcnn_vgg16(classes=classes, image_shape=shape,
                                max_gts=16,
                                rpn_pre_nms=rpn_pre_nms or 12000,
                                rpn_post_nms=rpn_post_nms or 2000)
    else:
        shape = tuple(image_shape or (64, 96))
        classes = classes or 3
        net = FasterRCNN(
            classes=classes, image_shape=shape,
            filters=(8, 16, 32, 32, 32), units=(1, 1, 1, 1, 1), fc_hidden=64,
            scales=(1, 2), ratios=(0.5, 1, 2),
            rpn_pre_nms=rpn_pre_nms or 200,
            rpn_post_nms=rpn_post_nms or 32,
            batch_rois=16, rpn_batch=32, max_gts=8)
    if init:
        # He/MSRA-style init: the VGG trunk has NO normalization layers, so
        # default-uniform init explodes activations over 13 relu convs at
        # 608×1024 (first-step CE was ~200 vs the ~log(C+1) a calibrated
        # head gives).  The reference recipe sidesteps this with pretrained
        # trunk weights + Normal(0.01) new layers; from-scratch synthetic
        # training needs variance-preserving init instead.
        net.initialize(mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                      magnitude=2))
        net.init_params()
    return net, shape, classes


def run_bench(vgg16, batch=1, iters=10, image_shape=None, classes=None,
              dtype=None, lr=1e-3, windows=3, verbose=True):
    """Timed chained-step bench (state stays on device; one scalar fetch per
    window) → (img_per_sec, ms_per_step, final_loss)."""
    import jax

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net, shape, classes = build_net(vgg16, image_shape, classes)
    data, im_info, gt = synthetic_voc(rng, batch, shape, classes, net.max_gts)
    step, state = make_frcnn_train_step(
        net, batch, learning_rate=lr, momentum=0.9, compute_dtype=dtype)
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    d = jax.device_put(data)
    i = jax.device_put(im_info)
    g = jax.device_put(gt)
    t0 = time.time()
    state, loss, parts = jstep(state, d, i, g, key)
    jax.block_until_ready(loss)
    compile_s = time.time() - t0
    # no-op unless MXNET_TELEMETRY is set: feeds bench.py's telemetry block
    mx.telemetry.note_compile(compile_s, fn="frcnn_fused_step")
    if verbose:
        print("compile+first step: %.1fs  loss=%.4f" % (compile_s, float(loss)))
    best = None
    for w in range(windows):
        keys = [jax.random.fold_in(key, w * 1000 + it) for it in range(iters)]
        jax.block_until_ready(keys[-1])
        t0 = time.perf_counter()
        for it in range(iters):
            state, loss, parts = jstep(state, d, i, g, keys[it])
        float(loss)
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    return batch / best, best * 1e3, float(loss)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--vgg16", action="store_true",
                   help="full VGG16 trunk (default: tiny trunk for CPU)")
    p.add_argument("--image-shape", type=int, nargs=2, default=None)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--classes", type=int, default=None)
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--dtype", default=None)
    p.add_argument("--bench", action="store_true")
    p.add_argument("--bench-iters", type=int, default=10)
    args = p.parse_args()

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    if args.dtype is None and args.bench and on_tpu:
        args.dtype = "bfloat16"

    if args.bench:
        img_s, ms, loss = run_bench(
            args.vgg16, batch=args.batch_size, iters=args.bench_iters,
            image_shape=args.image_shape, classes=args.classes,
            dtype=args.dtype, lr=args.lr)
        print("frcnn_fused_bench: batch=%d dtype=%s  %.2f img/s (%.0f ms/step)"
              "  loss=%.4f"
              % (args.batch_size, args.dtype or "float32", img_s, ms, loss))
        return

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net, shape, classes = build_net(args.vgg16, args.image_shape, args.classes)
    data, im_info, gt = synthetic_voc(rng, args.batch_size, shape, classes,
                                      net.max_gts)
    step, state = make_frcnn_train_step(
        net, args.batch_size, learning_rate=args.lr, momentum=0.9,
        compute_dtype=args.dtype)
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)

    first = last = None
    for s in range(args.steps):
        data, im_info, gt = synthetic_voc(rng, args.batch_size, shape,
                                          classes, net.max_gts)
        state, loss, parts = jstep(state, data, im_info, gt,
                                   jax.random.fold_in(key, s))
        l = float(loss)
        pr = [float(x) for x in np.asarray(parts)]
        print("step %2d  loss=%.4f  (rpn_cls %.3f rpn_bbox %.3f "
              "rcnn_cls %.3f rcnn_bbox %.3f)" % (s, l, *pr))
        if first is None:
            first = l
        last = l
    assert np.isfinite(last), "loss diverged"
    assert last < first, "loss did not decrease (first=%.4f last=%.4f)" % (first, last)
    print("FASTER-RCNN FUSED TRAIN OK")


if __name__ == "__main__":
    main()
