"""Faster R-CNN — reference ``example/rcnn/`` (train_end2end.py,
rcnn/symbol/symbol_vgg.py get_vgg_train, rcnn/core/loader.py AnchorLoader,
rcnn/symbol/proposal_target.py CustomOp), rebuilt TPU-first.

End-to-end architecture (same as the reference end2end config):
backbone conv features → RPN (cls + bbox) → MultiProposal op →
proposal_target CustomOp (ROI sampling, host-side like the reference) →
ROIPooling → FC head → per-class cls_score + bbox_pred.

TPU notes: the Proposal/NMS path is the fixed-capacity masked formulation in
ops/detection.py (SURVEY §7.3's "dynamic shapes" hard part); proposal_target
keeps the reference's host-numpy sampling via the pure_callback CustomOp
bridge, returning fixed-size padded ROI batches so everything downstream jits.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn, HybridBlock, Block


# ---------------------------------------------------------------------------
# host-side target assignment (reference rcnn/processing/{generate_anchor,
# assign_anchor}; runs in the data path like AnchorLoader did)
# ---------------------------------------------------------------------------


def generate_anchors(stride, scales, ratios):
    """Base anchors — MUST be byte-identical to MultiProposal's device-side
    enumeration, so reuse the op's own helper (ops/detection.py:471)."""
    from mxnet_tpu.ops.detection import _generate_base_anchors

    return np.asarray(_generate_base_anchors(stride, scales, ratios), np.float32)


def _shift_anchors(base, stride, hf, wf):
    sx = np.arange(wf) * stride
    sy = np.arange(hf) * stride
    sx, sy = np.meshgrid(sx, sy)
    shifts = np.stack([sx.ravel(), sy.ravel(), sx.ravel(), sy.ravel()], axis=1)
    all_anchors = base[None, :, :] + shifts[:, None, :].astype(np.float32)
    return all_anchors.reshape(-1, 4)  # (Hf*Wf*A, 4)


def _np_iou(a, b):
    tl = np.maximum(a[:, None, :2], b[None, :, :2])
    br = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(br - tl + 1, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1)
    area_b = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-12)


def _bbox_transform(ex, gt):
    """Box regression targets (reference rcnn/processing/bbox_transform.py)."""
    ew = ex[:, 2] - ex[:, 0] + 1.0
    eh = ex[:, 3] - ex[:, 1] + 1.0
    ecx = ex[:, 0] + 0.5 * (ew - 1)
    ecy = ex[:, 1] + 0.5 * (eh - 1)
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gcx = gt[:, 0] + 0.5 * (gw - 1)
    gcy = gt[:, 1] + 0.5 * (gh - 1)
    return np.stack(
        [
            (gcx - ecx) / (ew + 1e-14),
            (gcy - ecy) / (eh + 1e-14),
            np.log(gw / ew),
            np.log(gh / eh),
        ],
        axis=1,
    ).astype(np.float32)


def assign_anchor(feat_shape, gt_boxes, im_info, stride=8, scales=(2, 4, 8),
                  ratios=(0.5, 1, 2), allowed_border=0, batch_rois=256, fg_fraction=0.5,
                  pos_thresh=0.7, neg_thresh=0.3, rng=None):
    """RPN target assignment (reference rcnn/core/loader.py AnchorLoader →
    assign_anchor).  Returns (label (A',), bbox_target (A',4), bbox_weight)."""
    rng = rng or np.random
    hf, wf = feat_shape
    base = generate_anchors(stride, scales, ratios)
    anchors = _shift_anchors(base, stride, hf, wf)
    total = anchors.shape[0]
    im_h, im_w = im_info[0], im_info[1]
    inds_inside = np.where(
        (anchors[:, 0] >= -allowed_border)
        & (anchors[:, 1] >= -allowed_border)
        & (anchors[:, 2] < im_w + allowed_border)
        & (anchors[:, 3] < im_h + allowed_border)
    )[0]
    label = np.full(total, -1, np.float32)
    bbox_target = np.zeros((total, 4), np.float32)
    bbox_weight = np.zeros((total, 4), np.float32)
    inside = anchors[inds_inside]
    gt = gt_boxes[gt_boxes[:, 0] >= 0][:, 1:5] if gt_boxes.size else np.zeros((0, 4), np.float32)
    if gt.shape[0]:
        iou = _np_iou(inside, gt)
        argmax = iou.argmax(axis=1)
        max_iou = iou[np.arange(inside.shape[0]), argmax]
        lab_in = np.full(inside.shape[0], -1, np.float32)
        lab_in[max_iou < neg_thresh] = 0
        # each gt's best anchor is fg (reference assign_anchor rule)
        gt_best = iou.argmax(axis=0)
        lab_in[gt_best] = 1
        lab_in[max_iou >= pos_thresh] = 1
        # subsample to batch_rois
        fg = np.where(lab_in == 1)[0]
        max_fg = int(batch_rois * fg_fraction)
        if len(fg) > max_fg:
            lab_in[rng.choice(fg, len(fg) - max_fg, replace=False)] = -1
        bg = np.where(lab_in == 0)[0]
        max_bg = batch_rois - min(len(np.where(lab_in == 1)[0]), max_fg)
        if len(bg) > max_bg:
            lab_in[rng.choice(bg, len(bg) - max_bg, replace=False)] = -1
        fg = np.where(lab_in == 1)[0]
        bbox_target[inds_inside[fg]] = _bbox_transform(inside[fg], gt[argmax[fg]])
        bbox_weight[inds_inside[fg]] = 1.0
        label[inds_inside] = lab_in
    else:
        lab_in = np.full(inside.shape[0], -1, np.float32)
        bg = rng.choice(inside.shape[0], min(batch_rois, inside.shape[0]), replace=False)
        lab_in[bg] = 0
        label[inds_inside] = lab_in
    return label, bbox_target, bbox_weight


# ---------------------------------------------------------------------------
# proposal_target CustomOp (reference rcnn/symbol/proposal_target.py:31,82)
# ---------------------------------------------------------------------------


@mx.operator.register("proposal_target")
class ProposalTargetProp(mx.operator.CustomOpProp):
    """``num_classes`` INCLUDES background (reference rcnn config convention:
    VOC num_classes=21)."""

    def __init__(self, num_classes="2", batch_images="1", batch_rois="64",
                 fg_fraction="0.25"):
        super().__init__(need_top_grad=False)
        self._num_classes = int(num_classes)
        self._batch_images = int(batch_images)
        self._batch_rois = int(batch_rois)
        self._fg_fraction = float(fg_fraction)
        if self._batch_rois % self._batch_images != 0:
            raise ValueError(
                "batch_rois (%d) must be divisible by batch_images (%d)"
                % (self._batch_rois, self._batch_images)
            )

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_output", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        rpn_rois_shape = in_shape[0]
        gt_boxes_shape = in_shape[1]
        rois = self._batch_rois
        C = self._num_classes
        return (
            [rpn_rois_shape, gt_boxes_shape],
            [(rois, 5), (rois,), (rois, 4 * C), (rois, 4 * C)],
            [],
        )

    def create_operator(self, ctx, shapes, dtypes):
        prop = self

        class ProposalTarget(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                all_rois = in_data[0].asnumpy()  # (R, 5)
                gt_flat = in_data[1].asnumpy()  # (B, N, 5) [cls,x1,y1,x2,y2]
                B = prop._batch_images
                per_im = prop._batch_rois // B
                fg_per_im = int(round(prop._fg_fraction * per_im))
                C = prop._num_classes
                rng = np.random
                rois_out, labels, bt, bw = [], [], [], []
                for b in range(B):
                    rois_b = all_rois[all_rois[:, 0] == b]
                    gt_b = gt_flat[b]
                    gt_b = gt_b[gt_b[:, 0] >= 0]
                    # include gt boxes as rois (reference behavior)
                    if gt_b.shape[0]:
                        gt_rois = np.concatenate(
                            [np.full((gt_b.shape[0], 1), b, np.float32), gt_b[:, 1:5]], axis=1
                        )
                        rois_b = np.concatenate([rois_b, gt_rois], axis=0)
                    if gt_b.shape[0]:
                        iou = _np_iou(rois_b[:, 1:5], gt_b[:, 1:5])
                        argmax = iou.argmax(axis=1)
                        max_iou = iou[np.arange(rois_b.shape[0]), argmax]
                    else:
                        argmax = np.zeros(rois_b.shape[0], np.int64)
                        max_iou = np.zeros(rois_b.shape[0], np.float32)
                    fg = np.where(max_iou >= 0.5)[0]
                    bg = np.where(max_iou < 0.5)[0]
                    n_fg = min(fg_per_im, fg.size)
                    if fg.size > n_fg:
                        fg = rng.choice(fg, n_fg, replace=False)
                    n_bg = per_im - n_fg
                    if bg.size > n_bg:
                        bg = rng.choice(bg, n_bg, replace=False)
                    elif bg.size < n_bg and bg.size > 0:
                        bg = np.concatenate([bg, rng.choice(bg, n_bg - bg.size)])
                    keep = np.concatenate([fg, bg]).astype(np.int64)
                    if keep.size == 0:  # no rois for this image at all
                        keep = np.zeros(per_im, np.int64)
                    while keep.size < per_im:  # degenerate: pad by repeating
                        keep = np.concatenate([keep, keep])[:per_im]
                    keep = keep[:per_im]
                    sel = rois_b[keep]
                    lab = np.zeros(per_im, np.float32)
                    t = np.zeros((per_im, 4 * C), np.float32)
                    w = np.zeros((per_im, 4 * C), np.float32)
                    if gt_b.shape[0]:
                        lab[: n_fg] = gt_b[argmax[keep[:n_fg]], 0] + 1  # 0 is bg
                        tgt = _bbox_transform(sel[:n_fg, 1:5], gt_b[argmax[keep[:n_fg]], 1:5])
                        for j in range(n_fg):
                            c = int(lab[j])
                            t[j, 4 * c : 4 * c + 4] = tgt[j]
                            w[j, 4 * c : 4 * c + 4] = 1.0
                    rois_out.append(sel)
                    labels.append(lab)
                    bt.append(t)
                    bw.append(w)
                self.assign(out_data[0], req[0], nd.array(np.concatenate(rois_out)))
                self.assign(out_data[1], req[1], nd.array(np.concatenate(labels)))
                self.assign(out_data[2], req[2], nd.array(np.concatenate(bt)))
                self.assign(out_data[3], req[3], nd.array(np.concatenate(bw)))

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                self.assign(in_grad[0], req[0], nd.array(np.zeros(in_data[0].shape, np.float32)))
                self.assign(in_grad[1], req[1], nd.array(np.zeros(in_data[1].shape, np.float32)))

        return ProposalTarget()


# ---------------------------------------------------------------------------
# network
# ---------------------------------------------------------------------------


class _Backbone(HybridBlock):
    """Small conv backbone, output stride 8 (stands in for VGG16 conv4/5;
    reference rcnn/symbol/symbol_vgg.py get_vgg_conv)."""

    def __init__(self, channels=(16, 32, 64), **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.body = nn.HybridSequential()
            for ch in channels:
                self.body.add(
                    nn.Conv2D(ch, kernel_size=3, padding=1),
                    nn.BatchNorm(),
                    nn.Activation("relu"),
                    nn.MaxPool2D(pool_size=2, strides=2),
                )

    def hybrid_forward(self, F, x):
        return self.body(x)


class RPN(HybridBlock):
    def __init__(self, num_anchors, channels=64, **kw):
        super().__init__(**kw)
        self.num_anchors = num_anchors
        with self.name_scope():
            self.conv = nn.Conv2D(channels, kernel_size=3, padding=1, activation="relu")
            self.cls = nn.Conv2D(2 * num_anchors, kernel_size=1)
            self.bbox = nn.Conv2D(4 * num_anchors, kernel_size=1)

    def hybrid_forward(self, F, x):
        t = self.conv(x)
        return self.cls(t), self.bbox(t)


class FasterRCNN(Block):
    """End-to-end Faster R-CNN (reference get_vgg_train / get_vgg_test)."""

    def __init__(self, num_classes, stride=8, scales=(2, 4, 8), ratios=(0.5, 1, 2),
                 batch_rois=64, roi_size=(7, 7), **kw):
        super().__init__(**kw)
        self.num_classes = num_classes  # excludes background
        self.stride = stride
        self.scales = scales
        self.ratios = ratios
        self.batch_rois = batch_rois
        self.roi_size = roi_size
        A = len(scales) * len(ratios)
        self.num_anchors = A
        with self.name_scope():
            self.backbone = _Backbone()
            self.rpn = RPN(A)
            self.head = nn.HybridSequential()
            self.head.add(nn.Dense(128, activation="relu"), nn.Dense(128, activation="relu"))
            self.cls_score = nn.Dense(num_classes + 1)
            self.bbox_pred = nn.Dense(4 * (num_classes + 1))

    def rpn_forward(self, x):
        feat = self.backbone(x)
        rpn_cls, rpn_bbox = self.rpn(feat)
        return feat, rpn_cls, rpn_bbox

    def proposals(self, rpn_cls, rpn_bbox, im_info, train=True):
        B, _, hf, wf = rpn_cls.shape
        A = self.num_anchors
        # 2-class softmax over anchors: reshape (B, 2A, H, W) -> (B, 2, A*H, W)
        score = nd.reshape(rpn_cls, shape=(B, 2, A * hf, wf))
        prob = nd.softmax(score, axis=1)
        prob = nd.reshape(prob, shape=(B, 2 * A, hf, wf))
        return nd.contrib.MultiProposal(
            prob, rpn_bbox, im_info,
            rpn_pre_nms_top_n=600 if train else 300,
            rpn_post_nms_top_n=self.batch_rois * 2 if train else 100,
            threshold=0.7,
            rpn_min_size=self.stride,
            scales=self.scales,
            ratios=self.ratios,
            feature_stride=self.stride,
        )

    def roi_head(self, feat, rois):
        pooled = nd.ROIPooling(
            feat, rois, pooled_size=self.roi_size, spatial_scale=1.0 / self.stride
        )
        h = self.head(nd.flatten(pooled))
        return self.cls_score(h), self.bbox_pred(h)

    def forward(self, x, im_info, gt_boxes=None):
        """Training forward: returns everything the loss needs."""
        feat, rpn_cls, rpn_bbox = self.rpn_forward(x)
        rois = self.proposals(rpn_cls, rpn_bbox, im_info, train=gt_boxes is not None)
        if gt_boxes is not None:
            rois, label, bbox_target, bbox_weight = nd.Custom(
                rois, gt_boxes, op_type="proposal_target",
                num_classes=str(self.num_classes + 1),  # incl. background
                batch_images=str(x.shape[0]),
                batch_rois=str(self.batch_rois), fg_fraction="0.25",
            )
            cls_score, bbox_pred = self.roi_head(feat, rois)
            return rpn_cls, rpn_bbox, rois, label, bbox_target, bbox_weight, cls_score, bbox_pred
        cls_score, bbox_pred = self.roi_head(feat, rois)
        return rois, cls_score, bbox_pred


def smooth_l1(pred, target, weight, sigma=1.0):
    d = (pred - target) * weight
    s2 = sigma * sigma
    absd = nd.abs(d)
    out = nd.where(absd < 1.0 / s2, 0.5 * s2 * d * d, absd - 0.5 / s2)
    return out.sum() / max(pred.shape[0], 1)


def rcnn_losses(net, x, im_info, gt_boxes, anchor_rng=None):
    """Full end-to-end loss (reference train_end2end.py loss heads)."""
    from mxnet_tpu.gluon import loss as gloss

    (rpn_cls, rpn_bbox, rois, label, bbox_target, bbox_weight, cls_score,
     bbox_pred) = net(x, im_info, gt_boxes)
    B, _, hf, wf = rpn_cls.shape
    A = net.num_anchors
    # host RPN targets per image (reference AnchorLoader)
    labs, bts, bws = [], [], []
    gt_np = gt_boxes.asnumpy()
    info_np = im_info.asnumpy()
    for b in range(B):
        lab, bt, bw = assign_anchor(
            (hf, wf), gt_np[b], info_np[b], stride=net.stride, scales=net.scales,
            ratios=net.ratios, rng=anchor_rng,
        )
        labs.append(lab)
        bts.append(bt)
        bws.append(bw)
    rpn_label = nd.array(np.stack(labs))  # (B, Hf*Wf*A)
    rpn_bt = nd.array(np.stack(bts))  # (B, Hf*Wf*A, 4)
    rpn_bw = nd.array(np.stack(bws))

    # rpn cls loss: logits (B, 2A, Hf, Wf), channel layout [A bg | A fg]
    # to MATCH what proposals()/MultiProposal read (detection.py:629
    # cls_prob[:, A:] = fg) -> (B, Hf*Wf*A, 2) with last dim (bg, fg)
    logits = nd.transpose(
        nd.reshape(rpn_cls, shape=(B, 2, A, hf, wf)), axes=(0, 3, 4, 2, 1)
    )
    logits = nd.reshape(logits, shape=(B, hf * wf * A, 2))
    ce = gloss.SoftmaxCrossEntropyLoss()
    valid = rpn_label >= 0
    rpn_cls_loss = (
        nd.reshape(ce(nd.reshape(logits, shape=(-1, 2)),
                      nd.reshape(nd.maximum(rpn_label, 0.0), shape=(-1,))),
                   shape=rpn_label.shape) * valid
    ).sum() / nd.maximum(valid.sum(), 1.0)

    # rpn bbox loss: preds (B, 4A, Hf, Wf) -> (B, Hf*Wf*A, 4)
    bp = nd.transpose(nd.reshape(rpn_bbox, shape=(B, A, 4, hf, wf)), axes=(0, 3, 4, 1, 2))
    bp = nd.reshape(bp, shape=(B, hf * wf * A, 4))
    rpn_bbox_loss = smooth_l1(bp, rpn_bt, rpn_bw, sigma=3.0)

    # rcnn head losses
    rcnn_cls_loss = ce(cls_score, label).mean()
    rcnn_bbox_loss = smooth_l1(bbox_pred, bbox_target, bbox_weight)
    total = rpn_cls_loss + rpn_bbox_loss + rcnn_cls_loss + rcnn_bbox_loss
    return total, {
        "rpn_cls": float(rpn_cls_loss.asnumpy()),
        "rpn_bbox": float(rpn_bbox_loss.asnumpy()),
        "rcnn_cls": float(rcnn_cls_loss.asnumpy()),
        "rcnn_bbox": float(rcnn_bbox_loss.asnumpy()),
    }
