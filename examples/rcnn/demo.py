"""Checkpoint → detections: the deployment inference entry for the
detection family (reference ``example/rcnn/demo.py`` + ``test.py``: load a
trained checkpoint, build the TEST symbol, forward, decode + NMS, emit
boxes).

The journey, wired through the deployment surface (``mxnet_tpu.predictor``,
the reference's ``c_predict_api`` equivalent):

1. a trained parameter file (``--params``, from ``--save-params`` on this
   script's ``--quick-train`` path or any training entry that calls
   ``net.save_parameters``) loads into the INFERENCE TWIN — the same net
   built at the reference TEST proposal config (6000→300,
   ``rcnn/config.py:95-96``); parameter names/shapes are proposal-count
   independent, so trained values drop in;
2. the twin is hybridized and ``export``-ed to the deployment pair
   (``*-symbol.json`` + ``*-0000.params``, the reference checkpoint
   format);
3. ``predictor.create`` loads that pair — symbol JSON in, one fused XLA
   inference module out — and runs ``set_input → forward → get_output``
   (≡ MXPredSetInput/MXPredForward/MXPredGetOutput);
4. raw (rois, cls_prob, bbox_pred) decode to boxes: inverse bbox transform
   (class-agnostic for R-FCN; class-specific × BBOX_STDS for Faster-RCNN,
   reference ``rcnn/core/tester.py``) + per-class NMS.

Usage:
  # one command, checkpoint → detections (tiny CPU nets, CI smoke):
  python examples/rcnn/demo.py --model rfcn  --quick-train 40
  python examples/rcnn/demo.py --model frcnn --quick-train 40

  # deployment on an existing checkpoint + your image:
  python examples/rcnn/demo.py --model frcnn --vgg16 \
      --params run.params --image image.npy --out dets.npy
"""
from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import load_module_by_path


def _modules(model):
    if model == "rfcn":
        train = load_module_by_path(
            os.path.join(_HERE, "..", "deformable_rfcn", "train_fused.py"),
            "_demo_rfcn_train")
        ev = load_module_by_path(
            os.path.join(_HERE, "..", "quality", "eval_rfcn_map.py"),
            "_demo_rfcn_eval")
        return train, ev
    train = load_module_by_path(
        os.path.join(_HERE, "train_fused.py"), "_demo_frcnn_train")
    ev = load_module_by_path(
        os.path.join(_HERE, "..", "quality", "eval_frcnn_map.py"),
        "_demo_frcnn_eval")
    return train, ev


def _build(model, train_mod, full, test_cfg):
    """Build the net; ``test_cfg`` selects the inference proposal counts
    (Faster-RCNN trains at 12000→2000 and infers at the reference TEST
    config 6000→300; R-FCN's counts are already the test config)."""
    if model == "rfcn":
        return train_mod.build_net(full)
    return train_mod.build_net(
        full, rpn_pre_nms=6000 if (test_cfg and full) else None,
        rpn_post_nms=300 if (test_cfg and full) else None)


def quick_train(model, train_mod, full, steps, params_out, seed=0):
    """A short synthetic training run producing a demo checkpoint (the
    reference demo downloads a released ``final-0000.params``; with zero
    egress the demo trains its own in-process)."""
    import jax

    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    net, shape, classes = _build(model, train_mod, full, test_cfg=False)
    if model == "rfcn":
        step, state = train_mod.make_rfcn_train_step(net, 1, learning_rate=2e-3)
        synth = train_mod.synthetic_coco
    else:
        step, state = train_mod.make_frcnn_train_step(net, 1, learning_rate=2e-3)
        synth = train_mod.synthetic_voc
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(seed)
    for s in range(steps):
        data, im_info, gt = synth(rng, 1, shape, classes, net.max_gts)
        state, loss, _ = jstep(state, data, im_info, gt,
                               jax.random.fold_in(key, s))
        if s % max(1, steps // 4) == 0:
            print("quick-train step %3d  loss %.4f" % (s, float(loss)),
                  flush=True)
    # write the trained functional state back into the Block and save the
    # standard gluon checkpoint (net.save_parameters — SURVEY §5.4)
    from mxnet_tpu.gluon.functional import functionalize, merge_params

    _, names, _, aux_names = functionalize(net)
    merged = merge_params(names, aux_names, state[0], state[2])
    params = dict(net.collect_params().items())
    for name, val in zip(names, merged):
        params[name].set_data(nd.NDArray(val))
    net.save_parameters(params_out)
    print("checkpoint saved: %s" % params_out, flush=True)
    return shape, classes


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", choices=("rfcn", "frcnn"), default="rfcn")
    p.add_argument("--vgg16", action="store_true",
                   help="full VGG16 Faster-RCNN (chip scale)")
    p.add_argument("--resnet101", action="store_true",
                   help="full ResNet-101 R-FCN (chip scale)")
    p.add_argument("--params", default=None,
                   help="trained .params checkpoint (net.save_parameters "
                        "format); required unless --quick-train")
    p.add_argument("--quick-train", type=int, default=0, metavar="STEPS",
                   help="train a throwaway synthetic checkpoint first")
    p.add_argument("--image", default=None,
                   help=".npy image, (H,W,3) or (3,H,W) float; default: one "
                        "synthetic scene (objects guaranteed)")
    p.add_argument("--out", default=None, help="save detections as .npy")
    p.add_argument("--score-thresh", type=float, default=0.3)
    p.add_argument("--nms-thresh", type=float, default=0.3)
    p.add_argument("--export-prefix", default=None,
                   help="where to write the deployment pair (default: "
                        "alongside --params)")
    args = p.parse_args()

    if args.vgg16 and args.model != "frcnn":
        p.error("--vgg16 is the Faster-RCNN trunk (use --model frcnn)")
    if args.resnet101 and args.model != "rfcn":
        p.error("--resnet101 is the R-FCN trunk (use --model rfcn)")
    full = args.vgg16 or args.resnet101
    train_mod, eval_mod = _modules(args.model)

    params_path = args.params
    if args.quick_train:
        params_path = params_path or os.path.join(
            os.getcwd(), "demo_%s.params" % args.model)
        quick_train(args.model, train_mod, full, args.quick_train, params_path)
    elif not params_path:
        p.error("--params is required (or use --quick-train N)")

    # ---- the inference twin at the TEST proposal config -----------------
    net, shape, classes = _build(args.model, train_mod, full, test_cfg=True)
    net.load_parameters(params_path)

    # ---- input image ----------------------------------------------------
    if args.image:
        img = np.load(args.image).astype(np.float32)
        if img.ndim != 3:
            raise SystemExit("--image must be (H,W,3) or (3,H,W), got %s"
                             % (img.shape,))
        if img.shape[-1] == 3:
            img = img.transpose(2, 0, 1)
        if img.shape[1:] != tuple(shape):
            raise SystemExit("image is %s, net expects %s — resize first "
                             "(mx.image.imresize)" % (img.shape[1:], shape))
        data = img[None]
        im_info = np.array([[shape[0], shape[1], 1.0]], np.float32)
    else:
        rng = np.random.RandomState(99)
        if args.model == "rfcn":
            data, im_info, gt = train_mod.synthetic_coco(
                rng, 1, shape, classes, net.max_gts)
        else:
            data, im_info, gt = train_mod.synthetic_voc(
                rng, 1, shape, classes, net.max_gts)
        print("synthetic scene with %d gt boxes"
              % int((gt[0, :, 0] >= 0).sum()), flush=True)

    # ---- export the deployment pair and load it through the predictor ---
    prefix = args.export_prefix or os.path.splitext(params_path)[0] + "-deploy"
    net.hybridize()
    net(nd.array(data), nd.array(im_info))   # build the cached graph
    net.export(prefix)
    print("deployment pair: %s-symbol.json + %s-0000.params"
          % (prefix, prefix), flush=True)

    from mxnet_tpu import predictor

    # exported graph inputs are data0 (image), data1 (im_info) — the gluon
    # export convention for multi-input blocks
    pred = predictor.create(
        prefix + "-symbol.json", prefix + "-0000.params",
        {"data0": data.shape, "data1": im_info.shape})
    pred.set_input("data0", data)
    pred.set_input("data1", im_info)
    pred.forward()
    rois = np.asarray(pred.get_output(0), np.float32)
    cls_prob = np.asarray(pred.get_output(1), np.float32)
    bbox_pred = np.asarray(pred.get_output(2), np.float32)

    # ---- decode + NMS → boxes ------------------------------------------
    if args.model == "rfcn":
        dets = eval_mod.decode_detections(
            rois, cls_prob, bbox_pred, classes, shape,
            score_thresh=args.score_thresh, nms_thresh=args.nms_thresh)
    else:
        dets = eval_mod.decode_detections(
            rois, cls_prob, bbox_pred, classes, shape,
            box_stds=net.box_stds,
            score_thresh=args.score_thresh, nms_thresh=args.nms_thresh)
    dets = dets[0]
    dets = dets[dets[:, 0] >= 0]
    print("%d detection(s)  [class score x1 y1 x2 y2]:" % len(dets))
    for d in dets:
        print("  %3d  %.3f  %7.1f %7.1f %7.1f %7.1f"
              % (int(d[0]), d[1], d[2], d[3], d[4], d[5]))
    if args.out:
        np.save(args.out, dets)
        print("saved: %s" % args.out)


if __name__ == "__main__":
    main()
