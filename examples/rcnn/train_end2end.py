"""Faster R-CNN end-to-end training, eager path — reference
``example/rcnn/train_end2end.py``.

This is the flexible eager/Trainer loop on a small ad-hoc trunk (useful
for stepping through the pipeline).  The FULL-FIDELITY config-2 recipe —
VGG16 trunk at 608×1024, one-XLA-module fused step, chip-benched
(55.7 img/s) and mAP-gated — is ``train_fused.py`` in this directory;
use that for anything beyond debugging.

--synthetic generates a shapes dataset (pixel-coordinate gt boxes) so the
whole pipeline runs anywhere; pass a detection .rec for real data.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon

from faster_rcnn import FasterRCNN, rcnn_losses


def synthetic_batches(batch_size, data_shape, num_batches, num_classes=2, seed=0):
    """Rectangles dataset with PIXEL-coordinate labels [cls, x1, y1, x2, y2]."""
    rng = np.random.RandomState(seed)
    c, h, w = data_shape
    for _ in range(num_batches):
        data = rng.rand(batch_size, c, h, w).astype(np.float32) * 0.2
        labels = np.full((batch_size, 2, 5), -1.0, dtype=np.float32)
        for b in range(batch_size):
            for j in range(rng.randint(1, 3)):
                cls = rng.randint(0, num_classes)
                bw = rng.uniform(0.3, 0.6) * w
                bh = rng.uniform(0.3, 0.6) * h
                x1 = rng.uniform(0, w - bw)
                y1 = rng.uniform(0, h - bh)
                labels[b, j] = [cls, x1, y1, x1 + bw, y1 + bh]
                data[b, cls % c, int(y1) : int(y1 + bh), int(x1) : int(x1 + bw)] += 0.8
        im_info = np.tile(np.array([h, w, 1.0], np.float32), (batch_size, 1))
        yield nd.array(data), nd.array(im_info), nd.array(labels)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument("--data-shape", type=int, nargs=3, default=[3, 64, 64])
    p.add_argument("--num-classes", type=int, default=2)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batches-per-epoch", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--synthetic", action="store_true", default=True)
    args = p.parse_args()

    net = FasterRCNN(num_classes=args.num_classes)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(
        net.collect_params(), "sgd", {"learning_rate": args.lr, "momentum": 0.9, "wd": 5e-4}
    )
    anchor_rng = np.random.RandomState(0)
    for epoch in range(args.epochs):
        tic = time.time()
        agg = {}
        nb = 0
        for data, im_info, labels in synthetic_batches(
            args.batch_size, tuple(args.data_shape), args.batches_per_epoch,
            args.num_classes, seed=epoch,
        ):
            with autograd.record():
                loss, parts = rcnn_losses(net, data, im_info, labels, anchor_rng=anchor_rng)
            loss.backward()
            trainer.step(args.batch_size)
            for k, v in parts.items():
                agg[k] = agg.get(k, 0.0) + v
            nb += 1
        msg = " ".join("%s=%.4f" % (k, v / nb) for k, v in sorted(agg.items()))
        print("epoch %d: %s (%.1fs)" % (epoch, msg, time.time() - tic))
    return net


if __name__ == "__main__":
    main()
