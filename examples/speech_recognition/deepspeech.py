"""DeepSpeech-style speech recognition — reference
``example/speech_recognition/`` (``arch_deepspeech.py``: conv front-end
over spectrograms + stacked bidirectional recurrent layers + CTC, trained
through a bucketing module over variable utterance lengths,
``stt_bucketing_module.py``).

TPU-native shape of the same design: a Gluon net (Conv2D front-end ×
BiGRU stack × per-frame vocab head) trained with ``gluon.loss.CTCLoss``
using EXPLICIT pred/label lengths — utterances are bucketed to a few
static padded lengths, so jit compiles once per bucket (the reference's
BucketingModule served the same purpose for cuDNN kernels).  Data is a
synthetic phone-to-spectrogram generator (no egress): each token emits a
variable-width band pattern, unaligned — the CTC problem.

Run: ./dev.sh python examples/speech_recognition/deepspeech.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn

VOCAB = 6           # tokens 1..6; 0 reserved (blank rides as class VOCAB)
NFREQ = 16          # spectrogram bins
BUCKETS = (24, 36)  # padded utterance lengths (frames)


def synth_utterances(rng, n, max_tokens=5):
    """Token sequences → unaligned spectrogram band runs, bucketed."""
    data = {b: [] for b in BUCKETS}
    for _ in range(n):
        ntok = rng.randint(2, max_tokens + 1)
        toks = rng.randint(1, VOCAB + 1, ntok)
        frames = []
        for t in toks:
            w = rng.randint(3, 7)
            f = np.zeros((w, NFREQ), np.float32)
            band = (t - 1) * 2
            f[:, band:band + 3] = 1.0
            frames.append(f)
        utt = np.concatenate(frames, axis=0)
        T = len(utt)
        b = next((b for b in BUCKETS if T <= b), None)
        if b is None:
            continue
        x = np.zeros((b, NFREQ), np.float32)
        x[:T] = utt
        lab = np.zeros((max_tokens,), np.float32)
        lab[:ntok] = toks
        data[b].append((x, T, lab, ntok))
    out = {}
    for b, rows in data.items():
        if not rows:
            continue
        X = np.stack([r[0] for r in rows]) + 0.1 * rng.randn(
            len(rows), b, NFREQ).astype(np.float32)
        out[b] = (X, np.array([r[1] for r in rows], np.float32),
                  np.stack([r[2] for r in rows]),
                  np.array([r[3] for r in rows], np.float32))
    return out


class DeepSpeechNet(gluon.Block):
    """Conv front-end + BiGRU stack + vocab head (arch_deepspeech.py
    topology at toy scale)."""

    def __init__(self, hidden=64, layers=2, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.conv = nn.Conv2D(8, (5, 5), strides=(1, 1), padding=(2, 2),
                                  activation="relu")
            self.birnn = rnn.GRU(hidden, num_layers=layers,
                                 bidirectional=True, layout="NTC")
            self.head = nn.Dense(VOCAB + 1, flatten=False)  # +1 CTC blank

    def forward(self, x):  # x (N, T, F)
        c = self.conv(x.expand_dims(1))            # (N, 8, T, F)
        c = c.transpose((0, 2, 1, 3)).reshape((0, 0, -1))  # (N, T, 8F)
        h = self.birnn(c)                          # (N, T, 2H)
        return self.head(h)                        # (N, T, V+1)


def greedy_decode(logits, lengths):
    ids = logits.asnumpy().argmax(-1)
    out = []
    for row, T in zip(ids, lengths.astype(int)):
        seq, prev = [], -1
        for t in row[:T]:
            if t != prev and t != VOCAB:  # collapse repeats, drop blank
                seq.append(int(t) + 1)    # head class i ↦ token i+1
            prev = t
        out.append(seq)
    return out


def main(steps=160, batch=16, lr=0.02, seed=0):
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    train = synth_utterances(rng, 400)
    test = synth_utterances(np.random.RandomState(seed + 1), 80)

    net = DeepSpeechNet()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    # labels are 1..V; CTCLoss blank_label='last' expects classes 0..V-1
    # with blank V — shift labels down by 1 at the loss boundary
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    buckets = sorted(train)
    losses = []
    for s in range(steps):
        b = buckets[s % len(buckets)]
        X, TL, Y, YL = train[b]
        idx = rng.randint(0, len(X), min(batch, len(X)))
        xb = nd.array(X[idx])
        with autograd.record():
            logits = net(xb)
            loss = ctc(logits, nd.array(Y[idx] - 1.0),
                       nd.array(TL[idx]), nd.array(YL[idx])).mean()
        loss.backward()
        trainer.step(len(idx))
        losses.append(float(loss.asnumpy()))

    # token accuracy via greedy decode on held-out utterances
    correct = total = 0
    for b, (X, TL, Y, YL) in sorted(test.items()):
        dec = greedy_decode(net(nd.array(X)), TL)
        for d, y, L in zip(dec, Y, YL.astype(int)):
            ref = [int(v) for v in y[:L]]
            total += L
            correct += sum(1 for a, r in zip(d, ref) if a == r)
    acc = correct / max(total, 1)
    print("deepspeech: ctc loss %.3f -> %.3f, greedy token acc %.3f "
          "(buckets %s)" % (np.mean(losses[:10]), np.mean(losses[-10:]),
                            acc, buckets))
    return np.asarray(losses), acc


if __name__ == "__main__":
    main()
