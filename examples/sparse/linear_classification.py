"""Sparse linear classification — parity with reference
``example/sparse/linear_classification`` (CSR features x dense weight via
sparse dot; row-sparse gradients drive lazy optimizer updates touching only
the observed feature rows).

TPU framing: the CSR batch densifies at the device boundary (XLA wants
static shapes), but gradient sparsity is preserved end-to-end: the backward
for dot(csr, w) touches only rows present in the batch, written as a
row_sparse gradient consumed by the lazy SGD path (optimizer.py sparse
updates, reference optimizer_op.cc sgd rowsparse kernels).
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, optimizer as optmod
from mxnet_tpu.ndarray import sparse


def synthetic_libsvm(num_samples, num_features, nnz_per_row, seed=0):
    """Synthetic sparse binary-classification data: y depends on a sparse
    ground-truth weight over a Zipf-distributed feature universe."""
    rng = np.random.RandomState(seed)
    w_true = np.zeros(num_features, np.float32)
    active = rng.choice(num_features, num_features // 10, replace=False)
    w_true[active] = rng.randn(len(active))
    rows = []
    for _ in range(num_samples):
        idx = np.unique(rng.zipf(1.3, nnz_per_row) % num_features)
        val = rng.rand(len(idx)).astype(np.float32)
        rows.append((idx.astype(np.int64), val))
    X = np.zeros((num_samples, num_features), np.float32)
    for i, (idx, val) in enumerate(rows):
        X[i, idx] = val
    y = (X @ w_true > 0).astype(np.float32)
    return rows, X, y


def batches(rows, y, batch_size, num_features):
    """Yields (csr_batch, labels, touched): ``touched`` is the batch's unique
    feature set — exactly the nonzero rows of the X^T grad, so the caller
    builds the row_sparse gradient without re-deriving the slice."""
    for i in range(0, len(rows) - batch_size + 1, batch_size):
        chunk = rows[i:i + batch_size]
        indptr = np.zeros(batch_size + 1, np.int64)
        indices = []
        data = []
        for j, (idx, val) in enumerate(chunk):
            indptr[j + 1] = indptr[j] + len(idx)
            indices.append(idx)
            data.append(val)
        all_idx = np.concatenate(indices)
        csr = sparse.csr_matrix(
            (np.concatenate(data), all_idx, indptr),
            shape=(batch_size, num_features))
        yield csr, nd.array(y[i:i + batch_size]), np.unique(all_idx)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-features", type=int, default=1000)
    p.add_argument("--num-samples", type=int, default=512)
    p.add_argument("--nnz", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.5)
    args = p.parse_args()

    rows, X, y = synthetic_libsvm(args.num_samples, args.num_features, args.nnz)
    w = nd.array(np.zeros((args.num_features, 1), np.float32))
    b = nd.array(np.zeros((1,), np.float32))
    opt = optmod.create("sgd", learning_rate=args.lr)
    w_state = opt.create_state(0, w)
    b_state = opt.create_state(1, b)

    first = last = None
    for ep in range(args.epochs):
        tot = 0.0
        n = 0
        for csr, yb, touched in batches(rows, y, args.batch_size, args.num_features):
            logits = sparse.dot(csr, w).reshape((-1,)) + b
            prob = nd.sigmoid(logits)
            # logistic loss + manual grads (the reference ships them through
            # the symbolic graph; here the point is the SPARSE update path)
            eps = 1e-7
            loss = -(yb * nd.log(prob + eps) + (1 - yb) * nd.log(1 - prob + eps)).mean()
            gl = (prob - yb) / args.batch_size  # dL/dlogits
            # dL/dw = X^T gl — nonzero only on this batch's touched rows:
            gw_dense = sparse.dot(csr, gl.reshape((-1, 1)), transpose_a=True)
            gw = sparse.row_sparse_array(
                (gw_dense.asnumpy()[touched], touched), shape=w.shape)
            gb = gl.sum()
            opt.update(0, w, gw, w_state)
            opt.update(1, b, gb.reshape((1,)), b_state)
            tot += float(loss.asnumpy())
            n += 1
        avg = tot / n
        if first is None:
            first = avg
        last = avg
        print("Epoch[%d] loss=%.4f" % (ep, avg))
    acc = (((X @ w.asnumpy()).ravel() + float(b.asnumpy()[0]) > 0) == (y > 0.5)).mean()
    print("first=%.4f last=%.4f train-acc=%.3f" % (first, last, acc))
    assert last < first
    print("SPARSE LINEAR OK")


if __name__ == "__main__":
    main()
