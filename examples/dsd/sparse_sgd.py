"""SparseSGD — DSD (Dense-Sparse-Dense) pruning optimizer, reference
``example/dsd/sparse_sgd.py``.

Same contract as the reference: an SGD whose per-weight masks prune the
smallest-|w| entries (by sparsity percentage, via topk-mask semantics) or
everything under a threshold, applied to weight, grad and momentum each
update; the schedule switches sparsity levels at ``pruning_switch_epoch``
boundaries (epochs counted per-index from ``batches_per_epoch``, the
reference's bookkeeping).  Masks recompute once per phase switch and stay
fixed until the next one — dense phases use sparsity/threshold 0 (no mask).
"""
from __future__ import annotations

import numpy as np

from mxnet_tpu import nd
from mxnet_tpu.optimizer import SGD, register


@register
class SparseSGD(SGD):
    def __init__(self, pruning_switch_epoch, batches_per_epoch,
                 weight_sparsity=None, bias_sparsity=None,
                 weight_threshold=None, bias_threshold=None, **kwargs):
        super().__init__(**kwargs)
        self.pruning_switch_epoch = list(pruning_switch_epoch)
        self.batches_per_epoch = int(batches_per_epoch)
        self.weight_sparsity = weight_sparsity
        self.bias_sparsity = bias_sparsity
        self.weight_threshold = weight_threshold
        self.bias_threshold = bias_threshold
        if weight_sparsity is not None:
            assert len(weight_sparsity) == len(bias_sparsity), \
                "weight and bias sparsity lists must pair up"
        else:
            assert len(weight_threshold) == len(bias_threshold), \
                "weight and bias threshold lists must pair up"
        self.masks = {}        # index -> mask NDArray or None (dense)
        self._mask_phase = {}  # index -> phase the mask was built for
        self._steps = {}       # index -> update count
        self.mask_history = {}  # (index, phase) -> pruned fraction

    # -- schedule ---------------------------------------------------------
    def _phase_of(self, index):
        """Phase = how many switch epochs this index's training has passed
        (reference pruning_switch_epoch, ascending)."""
        epoch = self._steps.get(index, 0) // self.batches_per_epoch
        phase = 0
        for e in self.pruning_switch_epoch:
            if epoch >= e:
                phase += 1
        return phase

    def _mask_for(self, phase, weight):
        levels = self.weight_sparsity or self.weight_threshold
        phase = min(phase, len(levels) - 1)
        is_bias = weight.ndim == 1
        w = np.abs(weight.asnumpy())
        if self.weight_sparsity is not None:
            sparsity = float((self.bias_sparsity if is_bias
                              else self.weight_sparsity)[phase])
            keep = int(round(w.size * (100.0 - sparsity) / 100.0))
            if keep >= w.size:
                return None  # dense phase
            if keep == 0:
                return nd.array(np.zeros_like(w, np.float32))
            # keep the largest-|w| entries (reference topk ret_typ='mask')
            cut = np.partition(w.ravel(), w.size - keep)[w.size - keep]
            mask = (w >= cut).astype(np.float32)
        else:
            thr = float((self.bias_threshold if is_bias
                         else self.weight_threshold)[phase])
            if thr <= 0:
                return None
            mask = (w >= thr).astype(np.float32)
        return nd.array(mask)

    # -- update -----------------------------------------------------------
    def update(self, index, weight, grad, state):
        self._steps[index] = self._steps.get(index, 0) + 1
        phase = self._phase_of(index)
        if self._mask_phase.get(index) != phase:
            self.masks[index] = self._mask_for(phase, weight)
            self._mask_phase[index] = phase
            m = self.masks[index]
            self.mask_history[(index, phase)] = (
                0.0 if m is None else 1.0 - float(m.asnumpy().mean()))
        mask = self.masks.get(index)
        if mask is not None:
            weight *= mask
            grad = grad * mask
            if state is not None:
                state *= mask
        super().update(index, weight, grad, state)

    @staticmethod
    def sparsity_of(weight):
        w = weight.asnumpy()
        return float((w == 0).mean())
