"""DSD (Dense-Sparse-Dense) MLP training — reference ``example/dsd/mlp.py``.

Dense phase → sparse phase (prune smallest |w|, train under the mask) →
dense re-training phase (mask lifted).  Same 128-64-10 MLP and Module-API
loop as the reference (which used MNIST idx files; sklearn digits here —
no egress).  The point of the example is exercising SparseSGD's
mask-the-update semantics end-to-end.

Run: ./dev.sh python examples/dsd/mlp.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

import mxnet_tpu as mx
from sparse_sgd import SparseSGD  # noqa: F401 — registers the optimizer


def get_symbol():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act1, name="fc2", num_hidden=64)
    act2 = mx.sym.Activation(fc2, name="relu2", act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, name="fc3", num_hidden=10)
    return mx.sym.SoftmaxOutput(fc3, name="sm")


def main(batch=64, lr=0.1, epochs_per_phase=6, sparsity=60.0, seed=0):
    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split

    mx.random.seed(seed)
    np.random.seed(seed)
    X, y = load_digits(return_X_y=True)
    X = X.astype(np.float32) / 16.0
    Xtr, Xte, ytr, yte = train_test_split(X, y.astype(np.float32),
                                          test_size=0.25, random_state=seed,
                                          stratify=y)
    train = mx.io.NDArrayIter(Xtr, ytr, batch_size=batch, shuffle=True,
                              label_name="sm_label")
    val = mx.io.NDArrayIter(Xte, yte, batch_size=batch,
                            label_name="sm_label")
    batches = int(np.ceil(len(Xtr) / batch))

    mod = mx.mod.Module(get_symbol(), label_names=("sm_label",))
    # schedule: dense (sparsity 0) -> sparse (prune) -> dense again
    opt = SparseSGD(
        pruning_switch_epoch=[epochs_per_phase, 2 * epochs_per_phase],
        batches_per_epoch=batches,
        weight_sparsity=[0.0, sparsity, 0.0],
        bias_sparsity=[0.0, 0.0, 0.0],
        learning_rate=lr, momentum=0.9,
        rescale_grad=1.0 / batch)  # manual optimizers must set this
        # themselves (Module only defaults it for string-created ones —
        # same contract as the reference, module.py:523)
    mod.fit(train, eval_data=val, optimizer=opt,
            num_epoch=3 * epochs_per_phase,
            initializer=mx.init.Xavier(),
            batch_end_callback=None)

    score = mod.score(val, mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    print("dsd: final accuracy %.4f (dense->%.0f%%-sparse->dense)"
          % (acc, sparsity))
    return acc, opt


if __name__ == "__main__":
    main()
