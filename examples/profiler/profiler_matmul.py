"""Profiling a matmul executor — reference
``example/profiler/profiler_matmul.py``: set_config → simple_bind a dot →
toggle set_state('run'/'stop') around a window of iterations → dump a
chrome-trace JSON.

Run: ./dev.sh python examples/profiler/profiler_matmul.py
     (open the JSON in chrome://tracing or Perfetto)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx


def main(iter_num=20, begin=5, end=15, n=256, filename=None):
    filename = filename or os.path.join(tempfile.gettempdir(),
                                        "profile_matmul.json")
    mx.profiler.set_config(profile_symbolic=True, filename=filename)
    print("profile file saves to", filename)

    A = mx.sym.Variable("A")
    B = mx.sym.Variable("B")
    C = mx.sym.dot(A, B)
    executor = C.simple_bind(mx.cpu(), grad_req="null", A=(n, n), B=(n, n))
    executor.arg_dict["A"][:] = mx.random.uniform(-1, 1, shape=(n, n))
    executor.arg_dict["B"][:] = mx.random.uniform(-1, 1, shape=(n, n))

    t0 = t1 = None
    for i in range(iter_num):
        if i == begin:
            t0 = time.perf_counter()
            mx.profiler.set_state("run")
        if i == end:
            t1 = time.perf_counter()
            mx.profiler.set_state("stop")
        executor.forward()
        executor.outputs[0].wait_to_read()
    mx.profiler.dump()
    dur = t1 - t0
    print("profiled window: %.1f ms (%.2f ms/forward)"
          % (dur * 1e3, dur * 1e3 / (end - begin)))

    with open(filename) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    print("trace has %d events" % len(events))
    return len(events)


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--iter_num", type=int, default=20)
    p.add_argument("--profile_filename", type=str, default=None)
    a = p.parse_args()
    main(iter_num=a.iter_num, filename=a.profile_filename)
