"""Profiling eager NDArray work — reference
``example/profiler/profiler_ndarray.py`` (it runs an NDArray op sweep under
the profiler).  Here: a burst of eager ops between set_state('run'/'stop'),
plus a custom domain/counter and a frame marker — the instrumentation
surface of ``mxnet_tpu/profiler.py``.

Run: ./dev.sh python examples/profiler/profiler_ndarray.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx


def main():
    filename = os.path.join(tempfile.gettempdir(), "profile_ndarray.json")
    mx.profiler.set_config(profile_imperative=True, filename=filename)
    mx.profiler.set_state("run")

    domain = mx.profiler.Domain("ndarray_sweep")
    counter = mx.profiler.Counter(domain, "bytes_touched", 0)
    with mx.profiler.Frame(domain, "sweep"):
        a = mx.nd.random.uniform(-1, 1, shape=(512, 512))
        b = mx.nd.random.uniform(-1, 1, shape=(512, 512))
        for _ in range(8):
            c = mx.nd.dot(a, b) + a * 2 - b.sum(axis=1, keepdims=True)
            counter += int(c.size * 4)
        c.wait_to_read()

    mx.profiler.set_state("stop")
    mx.profiler.dump()
    with open(filename) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    names = {e.get("name") for e in events if isinstance(e, dict)}
    print("trace: %d events; has sweep frame: %s"
          % (len(events), "sweep" in names))
    return len(events)


if __name__ == "__main__":
    main()
