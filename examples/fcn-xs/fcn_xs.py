"""FCN-xs semantic segmentation — reference ``example/fcn-xs/`` (symbol_fcnxs.py:
FCN-32s/16s/8s heads over a VGG trunk with bilinear-initialised Deconvolution
upsampling, Crop alignment, and skip fusion).

Exercises the surfaces the reference family exists for: ``Deconvolution``
with the ``Bilinear`` initializer, ``Crop`` (offset alignment of upsampled
maps), multi-scale skip fusion, and per-pixel ``SoftmaxOutput``
(multi_output mode).  Trains on procedurally generated shape masks; reports
held-out per-pixel accuracy and mean IoU.

Run: ./dev.sh python examples/fcn-xs/fcn_xs.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def make_data(rng, n, hw=32, classes=3):
    """Images with a bright rectangle (cls 1) and a disk (cls 2) on noise."""
    x = rng.rand(n, 3, hw, hw).astype(np.float32) * 0.3
    y = np.zeros((n, hw, hw), np.float32)
    yy, xx = np.mgrid[0:hw, 0:hw]
    for i in range(n):
        x1, y1 = rng.randint(2, hw // 2, 2)
        w, h = rng.randint(6, hw // 2, 2)
        x[i, 0, y1:y1 + h, x1:x1 + w] += 0.8
        y[i, y1:y1 + h, x1:x1 + w] = 1
        cx, cy, r = rng.randint(8, hw - 8), rng.randint(8, hw - 8), rng.randint(4, 8)
        disk = (yy - cy) ** 2 + (xx - cx) ** 2 <= r * r
        x[i, 2][disk] += 0.8
        y[i][disk] = 2
    return x, y


class FCN8ish(mx.gluon.Block):
    """Two-stage trunk + two skip heads fused FCN-8s-style via the symbol
    ops (Deconvolution/Crop are exercised through the nd namespace)."""

    def __init__(self, classes=3, **kw):
        super().__init__(**kw)
        self.classes = classes
        with self.name_scope():
            self.stage1 = mx.gluon.nn.HybridSequential()
            self.stage1.add(mx.gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
                            mx.gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
                            mx.gluon.nn.MaxPool2D(2, 2))  # /2
            self.stage2 = mx.gluon.nn.HybridSequential()
            self.stage2.add(mx.gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
                            mx.gluon.nn.MaxPool2D(2, 2))  # /4
            self.score1 = mx.gluon.nn.Conv2D(classes, 1)  # stride-2 head
            self.score2 = mx.gluon.nn.Conv2D(classes, 1)  # stride-4 head
            # 2x bilinear upsampling kernel for the deep head (the reference
            # initialises every FCN deconv with Bilinear, symbol_fcnxs.py)
            self.up_w = self.params.get(
                "up2_weight", shape=(classes, classes, 4, 4),
                init=mx.init.Bilinear())
            self.upfull_w = self.params.get(
                "upfull_weight", shape=(classes, classes, 4, 4),
                init=mx.init.Bilinear())

    def forward(self, x):
        f1 = self.stage1(x)          # (B, 16, H/2, W/2)
        f2 = self.stage2(f1)         # (B, 32, H/4, W/4)
        s1 = self.score1(f1)         # (B, C, H/2, W/2)
        s2 = self.score2(f2)         # (B, C, H/4, W/4)
        # upsample deep head 2x, crop-align to the shallow head, fuse
        up2 = nd.Deconvolution(s2, self.up_w.data(), kernel=(4, 4),
                               stride=(2, 2), pad=(1, 1),
                               num_filter=self.classes)
        up2 = nd.Crop(up2, s1)       # reference Crop with reference shape
        fused = up2 + s1
        # full-resolution upsample: fused sits at stride 2, so ONE 2x
        # bilinear deconv reaches H x W; Crop aligns any deconv overshoot
        up4 = nd.Deconvolution(fused, self.upfull_w.data(), kernel=(4, 4),
                               stride=(2, 2), pad=(1, 1),
                               num_filter=self.classes)
        return nd.Crop(up4, x)       # (B, C, H, W)


def _diagonalize_bilinear(param, classes):
    """Keep the bilinear kernel only on the class-diagonal channel pairs
    (classic FCN upsampling).  With the all-pairs fill, softmax gradients —
    zero-sum across classes at every pixel — are annihilated by the deconv
    input-VJP (conv of a per-pixel zero-sum with identical kernels), so the
    trunk would receive no signal at all."""
    w = param.data().asnumpy()
    mask = np.eye(classes, dtype=np.float32)[:, :, None, None]
    param.set_data(mx.nd.array(w * mask))


def main(steps=400, batch=8, hw=32, classes=3, lr=0.5, seed=0):
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    net = FCN8ish(classes=classes)
    net.initialize(mx.init.Xavier())
    net(nd.zeros((1, 3, hw, hw)))  # materialize deferred conv params
    _diagonalize_bilinear(net.up_w, classes)
    _diagonalize_bilinear(net.upfull_w, classes)
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": lr})
    for s in range(steps):
        x, y = make_data(rng, batch, hw, classes)
        with autograd.record():
            logits = net(nd.array(x))
            # per-pixel softmax CE via SoftmaxOutput multi_output (the
            # reference FCN head), normalized over valid pixels
            prob = nd.SoftmaxOutput(logits, nd.array(y), multi_output=True,
                                    normalization="valid", use_ignore=True,
                                    ignore_label=-1)
        prob.backward()
        trainer.step(1)
        if s % 100 == 0:
            pred = prob.asnumpy().argmax(1)
            acc = (pred == y).mean()
            print("step %3d  pixel acc %.3f" % (s, acc), flush=True)

    # held-out eval: pixel accuracy + mean IoU
    xte, yte = make_data(np.random.RandomState(seed + 1), 32, hw, classes)
    pred = net(nd.array(xte)).asnumpy().argmax(1)
    acc = (pred == yte).mean()
    ious = []
    for c in range(classes):
        inter = ((pred == c) & (yte == c)).sum()
        union = ((pred == c) | (yte == c)).sum()
        if union:
            ious.append(inter / union)
    miou = float(np.mean(ious))
    print("FINAL fcn-xs: held-out pixel acc %.3f  mIoU %.3f" % (acc, miou))
    return acc, miou


if __name__ == "__main__":
    main()
