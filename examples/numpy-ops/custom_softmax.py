"""CustomOp softmax written against the numpy-facing bridge — reference
``example/numpy-ops/custom_softmax.py`` (and its ``numpy_softmax.py``
NumpyOp twin; both define softmax+CE fused forward/backward by hand).

The CustomOp protocol is the reference's escape hatch for ops authored in
Python/numpy (``python/mxnet/operator.py``).  Here the bridge runs the
numpy bodies through ``jax.pure_callback`` with a ``custom_vjp`` around
them (mxnet_tpu/operator.py), so the hand-written backward participates in
jit-compiled training exactly like the reference's engine-scheduled one.

Run: ./dev.sh python examples/numpy-ops/custom_softmax.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


class Softmax(mx.operator.CustomOp):
    """Fused softmax + cross-entropy grad (custom_softmax.py:25-36): the
    forward emits probabilities; the backward ignores the incoming grad
    (``need_top_grad=False``) and writes p - onehot(label) directly."""

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        lab = in_data[1].asnumpy().ravel().astype(np.int64)
        y = out_data[0].asnumpy().copy()
        y[np.arange(lab.shape[0]), lab] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y / lab.shape[0]))


@mx.operator.register("numpy_softmax")
class SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softmax()


def make_blobs(rng, n, classes=4, dim=16):
    """Linearly separable gaussian blobs (offline stand-in for MNIST)."""
    centers = rng.randn(classes, dim) * 3
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, dim)
    return x.astype(np.float32), y.astype(np.float32)


def main(epochs=12, batch=64, classes=4):
    rng = np.random.RandomState(0)
    xs, ys = make_blobs(rng, 1024, classes)
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, name="fc2", num_hidden=classes)
    net = mx.sym.Custom(fc2, label, name="softmax", op_type="numpy_softmax")

    mod = mx.mod.Module(net, label_names=("softmax_label",))
    it = mx.io.NDArrayIter(xs, ys, batch, shuffle=True,
                           label_name="softmax_label")
    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            eval_metric="acc")
    it.reset()
    metric = mx.metric.Accuracy()
    mod.score(it, metric)
    acc = metric.get()[1]
    print("custom numpy softmax final train acc %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
