"""Class-weighted logistic regression as a CustomOp — reference
``example/numpy-ops/custom_sparse_sqr.py`` sibling
``weighted_logistic_regression.py``: the backward scales positive and
negative examples' gradients differently (class-imbalance handling the
stock LogisticRegressionOutput cannot express).

Run: ./dev.sh python examples/numpy-ops/weighted_logistic_regression.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


class WeightedLogisticRegression(mx.operator.CustomOp):
    def __init__(self, pos_grad_scale, neg_grad_scale):
        self.pos = float(pos_grad_scale)
        self.neg = float(neg_grad_scale)

    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0],
                    mx.nd.divide(1.0, 1.0 + mx.nd.exp(-in_data[0])))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        # reference weighted_logistic_regression.py:27-29: grad =
        # ((p-1)·y·pos + p·(1-y)·neg) / n  — positives pulled with ``pos``,
        # negatives pushed with ``neg``
        p = out_data[0].asnumpy()
        y = in_data[1].asnumpy()
        g = ((p - 1.0) * y * self.pos + p * (1.0 - y) * self.neg) / p.shape[1]
        self.assign(in_grad[0], req[0], mx.nd.array(g))


@mx.operator.register("weighted_logistic_regression")
class WeightedLogisticRegressionProp(mx.operator.CustomOpProp):
    def __init__(self, pos_grad_scale, neg_grad_scale):
        self.pos = pos_grad_scale
        self.neg = neg_grad_scale
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0], in_shape[0]], [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return WeightedLogisticRegression(self.pos, self.neg)


def main(pos=5.0, neg=0.1):
    rng = np.random.RandomState(0)
    m, n = 32, 8
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    out = mx.sym.Custom(data, label, op_type="weighted_logistic_regression",
                        pos_grad_scale=pos, neg_grad_scale=neg)
    x = rng.randn(m, n).astype(np.float32)
    y = (rng.rand(m, n) > 0.8).astype(np.float32)  # imbalanced positives

    exe = out.simple_bind(mx.cpu(), data=(m, n), label=(m, n),
                          grad_req={"data": "write", "label": "null"})
    exe.arg_dict["data"][:] = x
    exe.arg_dict["label"][:] = y
    exe.forward(is_train=True)
    exe.backward()
    g = exe.grad_dict["data"].asnumpy()
    p = exe.outputs[0].asnumpy()
    ref = ((p - 1) * y * pos + p * (1 - y) * neg) / n
    assert np.allclose(g, ref, atol=1e-5)
    # the asymmetry is the point: positive-example grads outweigh negatives
    ratio = np.abs(g[y > 0.5]).mean() / np.abs(g[y < 0.5]).mean()
    print("weighted grads: |pos|/|neg| mean ratio = %.1f" % ratio)
    return ratio


if __name__ == "__main__":
    main()
