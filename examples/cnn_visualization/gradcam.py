"""Grad-CAM + guided backprop — reference
``example/cnn_visualization/{gradcam.py,gradcam_demo.py}``.

Three capabilities:

* **Grad-CAM** (reference ``get_cam``): channel-mean of the target conv
  layer's output gradient weights its activation map into a class-evidence
  heatmap.  Capture uses the reference's own idiom — ``attach_grad()`` on
  the intermediate inside ``autograd.record`` (which, as in MXNet, detaches
  it into a leaf whose ``.grad`` fills on backward).
* **Guided backprop** (reference ReluOp CustomOp, Springenberg et al.
  sec 3.4): a ReLU CustomOp whose backward also zeroes negative upstream
  gradients, toggled by a class flag exactly like the reference's
  ``ReluOp.guided_backprop``.
* **Saliency post-processing** (reference gradcam_demo.py), cv2-free.

Run: ./dev.sh python examples/cnn_visualization/gradcam.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


class ReluOp(mx.operator.CustomOp):
    """ReLU with switchable guided backprop (reference gradcam.py:29-61)."""

    guided_backprop = False

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0]
        self.assign(out_data[0], req[0], nd.maximum(x, nd.zeros_like(x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        if ReluOp.guided_backprop:
            y = out_data[0]
            dy = out_grad[0]
            dx = nd.maximum(dy, nd.zeros_like(dy)) * (y > 0)
        else:
            dx = out_grad[0] * (in_data[0] > 0)
        self.assign(in_grad[0], req[0], dx)


def set_guided_backprop(mode=True):
    ReluOp.guided_backprop = mode


@mx.operator.register("gradcam_relu")
class ReluProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shapes):
        return (in_shapes[0],), (in_shapes[0],), ()

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return ReluOp()


class Activation(gluon.HybridBlock):
    """Drop-in for nn.Activation('relu') routing through the CustomOp
    (reference gradcam.py Activation)."""

    def hybrid_forward(self, F, x):
        return F.Custom(x, op_type="gradcam_relu")


def build_cnn(classes=4):
    net = nn.HybridSequential(prefix="net_")
    with net.name_scope():
        net.add(nn.Conv2D(16, 3, padding=1), Activation(),
                nn.MaxPool2D(2),
                nn.Conv2D(32, 3, padding=1), Activation(),
                nn.MaxPool2D(2),
                nn.Flatten(), nn.Dense(classes))
    return net


def get_cam(net, x, class_id, capture_index=3):
    """Grad-CAM heatmap (reference gradcam.py get_cam)."""
    x = nd.array(x) if not isinstance(x, nd.NDArray) else x
    feat = None
    with autograd.record():
        h = x
        for i, blk in enumerate(net):
            h = blk(h)
            if i == capture_index:
                h.attach_grad()   # leaf capture, as the reference Conv2D does
                feat = h
        score = h[:, class_id].sum()
    score.backward()
    w = feat.grad.asnumpy().mean(axis=(2, 3), keepdims=True)  # (B,C,1,1)
    cam = np.maximum((w * feat.asnumpy()).sum(axis=1), 0)      # (B,H,W)
    cam /= cam.max() + 1e-12
    return cam


def get_guided_grad(net, x, class_id):
    """Image-space guided-backprop saliency (reference get_guided_grad_image):
    flip the ReluOp flag, backprop the class score to the image."""
    x = nd.array(x) if not isinstance(x, nd.NDArray) else x
    x.attach_grad()
    set_guided_backprop(True)
    try:
        with autograd.record():
            score = net(x)[:, class_id].sum()
        score.backward()
    finally:
        set_guided_backprop(False)
    return x.grad.asnumpy()


def main():
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = build_cnn()
    net.initialize(mx.init.Xavier())

    # an image whose class evidence sits in one quadrant
    x = rng.rand(1, 3, 32, 32).astype(np.float32) * 0.1
    x[:, :, 16:, 16:] += 1.0
    cam = get_cam(net, x, class_id=1)
    print("gradcam heatmap", cam.shape, "max at",
          np.unravel_index(cam[0].argmax(), cam[0].shape))

    sal = get_guided_grad(net, x, class_id=1)
    plain = None
    x2 = nd.array(x)
    x2.attach_grad()
    with autograd.record():
        s = net(x2)[:, 1].sum()
    s.backward()
    plain = x2.grad.asnumpy()
    print("guided saliency: neg-fraction %.3f vs plain backprop %.3f"
          % (float((sal < 0).mean()), float((plain < 0).mean())))
    return cam, sal


if __name__ == "__main__":
    main()
