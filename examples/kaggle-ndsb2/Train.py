"""Kaggle Second National Data Science Bowl (cardiac volume) — reference
``example/kaggle-ndsb2/Train.py``.

The reference predicts end-systole/diastole heart volume from 30-frame MRI
loops: frame differences via SliceChannel → lenet trunk → a 600-way
LogisticRegressionOutput head trained against the CDF encoding
``P(volume < v)`` (Train.py encode_label), scored with CRPS after a
monotonic sweep.  Port keeps every stage — difference frames, CDF target,
isotonic fix-up, CRPS — on synthetic pulsating-disk "MRI" loops whose
ground-truth volume is the disk's systolic area, fed through CSVIter
exactly like the reference's csv pipeline.

Run: ./dev.sh python examples/kaggle-ndsb2/Train.py
"""
from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx

FRAMES = 10   # reference uses 30-frame loops
BINS = 60     # reference encodes 600 volume bins
SIZE = 24


def get_lenet():
    """Frame-difference lenet (Train.py get_lenet), reduced geometry."""
    source = mx.sym.Variable("data")
    source = (source - 128) * (1.0 / 128)
    frames = mx.sym.SliceChannel(source, num_outputs=FRAMES)
    diffs = [frames[i + 1] - frames[i] for i in range(FRAMES - 1)]
    source = mx.sym.Concat(*diffs)
    net = mx.sym.Convolution(source, kernel=(5, 5), num_filter=16)
    net = mx.sym.BatchNorm(net, fix_gamma=True)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=16)
    net = mx.sym.BatchNorm(net, fix_gamma=True)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    flat = mx.sym.Flatten(net)
    flat = mx.sym.Dropout(flat)
    fc1 = mx.sym.FullyConnected(flat, num_hidden=BINS)
    return mx.sym.LogisticRegressionOutput(fc1, name="softmax")


def CRPS(label, pred):
    """Continuous ranked probability score after the reference's monotonic
    fix-up sweep (Train.py:59-64)."""
    pred = pred.copy()
    for j in range(pred.shape[1] - 1):
        pred[:, j + 1] = np.maximum(pred[:, j + 1], pred[:, j])
    return float(np.sum(np.square(label - pred)) / label.size)


def encode_label(vol):
    """volume scalar → CDF target 1[v < bins] (Train.py encode_label)."""
    return np.array([(x < np.arange(BINS)) for x in vol], np.uint8)


def make_loops(rng, n):
    """Pulsating disk: radius oscillates over the loop; systolic volume
    (the label) is the minimum disk area, in bin units."""
    data = np.zeros((n, FRAMES, SIZE, SIZE), np.float32)
    vols = np.zeros(n)
    yy, xx = np.mgrid[:SIZE, :SIZE]
    for i in range(n):
        r_dia = rng.uniform(6, 10)
        r_sys = r_dia * rng.uniform(0.45, 0.8)
        vols[i] = np.pi * r_sys ** 2 * (BINS / (np.pi * 10 ** 2))
        cy, cx = SIZE / 2 + rng.randn(2)
        for t in range(FRAMES):
            r = r_sys + (r_dia - r_sys) * 0.5 * (
                1 + np.cos(2 * np.pi * t / FRAMES))
            mask = ((yy - cy) ** 2 + (xx - cx) ** 2) < r ** 2
            data[i, t] = mask * 200.0 + rng.rand(SIZE, SIZE) * 20
    return data, vols


def main(epochs=12, batch=32, n_train=384, n_val=96, seed=0):
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    data, vols = make_loops(rng, n_train + n_val)
    cdf = encode_label(vols)

    with tempfile.TemporaryDirectory() as td:
        dtr = os.path.join(td, "train-data.csv")
        ltr = os.path.join(td, "train-systole.csv")
        np.savetxt(dtr, data[:n_train].reshape(n_train, -1), delimiter=",",
                   fmt="%g")
        np.savetxt(ltr, cdf[:n_train], delimiter=",", fmt="%g")
        train = mx.io.CSVIter(data_csv=dtr, data_shape=(FRAMES, SIZE, SIZE),
                              label_csv=ltr, label_shape=(BINS,),
                              batch_size=batch)
        mod = mx.mod.Module(get_lenet())
        mod.fit(train, num_epoch=epochs, optimizer="adam",
                optimizer_params={"learning_rate": 2e-3})

    pred = mod.predict(mx.io.NDArrayIter(
        data[n_train:], None, batch)).asnumpy()
    crps = CRPS(cdf[n_train:], pred)
    base = CRPS(cdf[n_train:],
                np.tile(cdf[:n_train].mean(0), (n_val, 1)))
    print("ndsb2 val CRPS %.4f (train-mean baseline %.4f)" % (crps, base))
    return crps, base


if __name__ == "__main__":
    main()
