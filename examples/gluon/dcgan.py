"""DCGAN — parity with reference ``example/gluon/dcgan.py`` (generator of
Conv2DTranspose blocks vs discriminator of strided convs, alternating
adversarial training with the Gluon imperative API).

Trains on a synthetic 16x16 disk-image distribution (filled disks with
class-colored rims) so it runs anywhere with zero downloads.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn


def build_generator(ngf=32, nc=3):
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # 1x1 -> 4x4 -> 8x8 -> 16x16
        net.add(nn.Conv2DTranspose(ngf * 2, 4, strides=1, padding=0, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(ngf, 4, strides=2, padding=1, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(nc, 4, strides=2, padding=1, use_bias=False))
        net.add(nn.Activation("tanh"))
    return net


def build_discriminator(ndf=32):
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(ndf, 4, strides=2, padding=1, use_bias=False))
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(ndf * 2, 4, strides=2, padding=1, use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(1, 4, strides=1, padding=0, use_bias=False))
    return net


def real_batches(batch_size, num_batches, seed=0):
    """Structured image distribution: filled disks with class-colored rims."""
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[0:16, 0:16]
    for _ in range(num_batches):
        imgs = np.zeros((batch_size, 3, 16, 16), np.float32)
        for b in range(batch_size):
            cy, cx = rng.uniform(5, 11, 2)
            r = rng.uniform(3, 5)
            disk = ((yy - cy) ** 2 + (xx - cx) ** 2) < r ** 2
            ch = rng.randint(3)
            imgs[b, ch][disk] = 1.0
            imgs[b, (ch + 1) % 3][disk] = 0.5
        yield nd.array(imgs * 2 - 1)  # tanh range


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batches-per-epoch", type=int, default=8)
    p.add_argument("--nz", type=int, default=16)
    p.add_argument("--lr", type=float, default=2e-4)
    args = p.parse_args()

    mx.random.seed(0)
    gen = build_generator()
    disc = build_discriminator()
    gen.initialize(mx.init.Normal(0.02))
    disc.initialize(mx.init.Normal(0.02))

    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    rng = np.random.RandomState(1)
    d_losses, g_losses = [], []
    for ep in range(args.epochs):
        tic = time.time()
        for real in real_batches(args.batch_size, args.batches_per_epoch, seed=ep):
            bs = real.shape[0]
            z = nd.array(rng.randn(bs, args.nz, 1, 1).astype(np.float32))
            ones = nd.ones((bs,))
            zeros = nd.zeros((bs,))

            # discriminator step
            with autograd.record():
                out_real = disc(real).reshape((-1,))
                fake = gen(z)
                out_fake = disc(fake.detach()).reshape((-1,))
                d_loss = bce(out_real, ones) + bce(out_fake, zeros)
            d_loss.backward()
            d_tr.step(bs)

            # generator step
            with autograd.record():
                out = disc(gen(z)).reshape((-1,))
                g_loss = bce(out, ones)
            g_loss.backward()
            g_tr.step(bs)

            d_losses.append(float(d_loss.mean().asnumpy()))
            g_losses.append(float(g_loss.mean().asnumpy()))
        print("Epoch[%d] d_loss=%.4f g_loss=%.4f time=%.1fs"
              % (ep, np.mean(d_losses[-args.batches_per_epoch:]),
                 np.mean(g_losses[-args.batches_per_epoch:]), time.time() - tic))
    # adversarial health: discriminator learned something, generator pushed back
    assert np.mean(d_losses[-4:]) < np.mean(d_losses[:4]), "D never learned"
    assert np.isfinite(g_losses).all() and np.isfinite(d_losses).all()
    print("DCGAN OK")


if __name__ == "__main__":
    main()
