"""Inference throughput over the symbol zoo on synthetic data — parity with
reference example/image-classification/benchmark_score.py."""
import argparse
import logging
import time
from importlib import import_module

import numpy as np

import mxnet_tpu as mx


def get_symbol(network, num_layers, image_shape):
    net = import_module("symbols." + network)
    return net.get_symbol(num_classes=1000, num_layers=num_layers,
                          image_shape=image_shape)


def score(network, num_layers, batch_size, image_shape="3,224,224", repeats=5):
    sym = get_symbol(network, num_layers, image_shape)
    shape = (batch_size,) + tuple(int(x) for x in image_shape.split(","))
    mod = mx.mod.Module(symbol=sym, context=mx.current_context())
    mod.bind(for_training=False, data_shapes=[("data", shape)])
    mod.init_params(initializer=mx.init.Xavier())
    rng = np.random.RandomState(0)
    batch = mx.io.DataBatch([mx.nd.array(rng.rand(*shape).astype(np.float32))], [])
    mod.forward(batch, is_train=False)  # compile
    mod.get_outputs()[0].wait_to_read()
    tic = time.time()
    for _ in range(repeats):
        mod.forward(batch, is_train=False)
    mod.get_outputs()[0].wait_to_read()
    return repeats * batch_size / (time.time() - tic)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--network", type=str, default="resnet")
    parser.add_argument("--num-layers", type=int, default=50)
    parser.add_argument("--image-shape", type=str, default="3,224,224")
    parser.add_argument("--batch-sizes", type=str, default="1,32")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    for b in [int(x) for x in args.batch_sizes.split(",")]:
        speed = score(args.network, args.num_layers, b, args.image_shape)
        logging.info("network=%s-%d batch=%d %f img/s",
                     args.network, args.num_layers, b, speed)
