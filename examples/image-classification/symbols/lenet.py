"""LeNet-5 style convnet — parity with reference symbols/lenet.py."""
from mxnet_tpu import sym


def get_symbol(num_classes=10, add_stn=False, **kwargs):
    data = sym.Variable("data")
    if add_stn:
        data = sym.SpatialTransformer(
            data, sym.GridGenerator(
                sym.FullyConnected(sym.Flatten(data), num_hidden=6, name="stn_loc"),
                transform_type="affine", target_shape=(28, 28)),
            transform_type="bilinear", name="stn")
    conv1 = sym.Convolution(data, kernel=(5, 5), num_filter=20, name="conv1")
    tanh1 = sym.Activation(conv1, act_type="tanh")
    pool1 = sym.Pooling(tanh1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    conv2 = sym.Convolution(pool1, kernel=(5, 5), num_filter=50, name="conv2")
    tanh2 = sym.Activation(conv2, act_type="tanh")
    pool2 = sym.Pooling(tanh2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    flatten = sym.Flatten(pool2)
    fc1 = sym.FullyConnected(flatten, num_hidden=500, name="fc1")
    tanh3 = sym.Activation(fc1, act_type="tanh")
    fc2 = sym.FullyConnected(tanh3, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")
