"""VGG 11/13/16/19 (+BN) — parity with reference symbols/vgg.py."""
from mxnet_tpu import sym

vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


def get_symbol(num_classes=1000, num_layers=16, batch_norm=False, dtype="float32", **kwargs):
    if num_layers not in vgg_spec:
        raise ValueError("invalid num_layers %d: choose from %s" % (num_layers, sorted(vgg_spec)))
    layers, filters = vgg_spec[num_layers]
    data = sym.Variable("data")
    if dtype == "float16":
        data = sym.cast(data, dtype="float16")
    body = data
    for i, num in enumerate(layers):
        for j in range(num):
            body = sym.Convolution(body, kernel=(3, 3), pad=(1, 1), num_filter=filters[i],
                                   name="conv%d_%d" % (i + 1, j + 1))
            if batch_norm:
                body = sym.BatchNorm(body, name="bn%d_%d" % (i + 1, j + 1))
            body = sym.Activation(body, act_type="relu", name="relu%d_%d" % (i + 1, j + 1))
        body = sym.Pooling(body, pool_type="max", kernel=(2, 2), stride=(2, 2),
                           name="pool%d" % (i + 1))
    flatten = sym.Flatten(body, name="flatten")
    fc6 = sym.FullyConnected(flatten, num_hidden=4096, name="fc6")
    relu6 = sym.Activation(fc6, act_type="relu", name="relu6")
    drop6 = sym.Dropout(relu6, p=0.5, name="drop6")
    fc7 = sym.FullyConnected(drop6, num_hidden=4096, name="fc7")
    relu7 = sym.Activation(fc7, act_type="relu", name="relu7")
    drop7 = sym.Dropout(relu7, p=0.5, name="drop7")
    fc8 = sym.FullyConnected(drop7, num_hidden=num_classes, name="fc8")
    if dtype == "float16":
        fc8 = sym.cast(fc8, dtype="float32")
    return sym.SoftmaxOutput(fc8, name="softmax")
