"""Train on ImageNet-1K records — parity with reference
example/image-classification/train_imagenet.py (ResNet-50 recipe).

Point --data-train/--data-val at local .rec files, or --benchmark 1 for
synthetic throughput runs (the BASELINE.md headline config).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
from common import data, fit  # noqa: E402


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train imagenet-1k",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_data_aug_args(parser)
    data.set_data_aug_level(parser, 3)
    parser.set_defaults(
        network="resnet",
        num_layers=50,
        num_classes=1000,
        num_examples=1281167,
        image_shape="3,224,224",
        min_random_scale=1,
        batch_size=128,
        num_epochs=80,
        lr=0.1,
        lr_step_epochs="30,60",
    )
    args = parser.parse_args()

    from importlib import import_module

    net = import_module("symbols." + args.network)
    sym = net.get_symbol(**vars(args))

    fit.fit(args, sym, data.get_rec_iter)
