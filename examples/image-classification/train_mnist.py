"""Train on MNIST — parity with reference
example/image-classification/train_mnist.py (mlp/lenet over NDArrayIter).

Reads a local `mnist.npz` (--data-path) or generates a deterministic
synthetic stand-in when absent (zero-egress environment).
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
from common import fit  # noqa: E402

import mxnet_tpu as mx


def get_mnist_iter(args, kv):
    if args.data_path and os.path.exists(args.data_path):
        with np.load(args.data_path) as f:
            x_train, y_train = f["x_train"], f["y_train"]
            x_test, y_test = f["x_test"], f["y_test"]
        x_train = x_train.reshape(-1, 1, 28, 28).astype(np.float32) / 255
        x_test = x_test.reshape(-1, 1, 28, 28).astype(np.float32) / 255
    else:  # synthetic fallback: class-conditioned gaussians, learnable
        rng = np.random.RandomState(7)
        n = args.num_examples
        y_train = rng.randint(0, 10, n)
        protos = rng.randn(10, 1, 28, 28).astype(np.float32)
        x_train = protos[y_train] + 0.3 * rng.randn(n, 1, 28, 28).astype(np.float32)
        y_test = rng.randint(0, 10, n // 5)
        x_test = protos[y_test] + 0.3 * rng.randn(n // 5, 1, 28, 28).astype(np.float32)
    train = mx.io.NDArrayIter(x_train, y_train.astype(np.float32),
                              args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(x_test, y_test.astype(np.float32), args.batch_size)
    return train, val


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train mnist",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--num-examples", type=int, default=60000)
    parser.add_argument("--data-path", type=str, default="data/mnist.npz")
    fit.add_fit_args(parser)
    parser.set_defaults(
        network="mlp",
        batch_size=64,
        num_epochs=20,
        lr=0.05,
        lr_step_epochs="10",
    )
    args = parser.parse_args()

    from importlib import import_module

    net = import_module("symbols." + args.network)
    sym = net.get_symbol(**vars(args))

    fit.fit(args, sym, get_mnist_iter)
