"""Score a saved checkpoint on a validation .rec — parity with reference
example/image-classification/score.py."""
import argparse
import logging
import time

import mxnet_tpu as mx


def score(model_prefix, epoch, data_val, image_shape, batch_size, rgb_mean,
          metrics=None, max_num_examples=None, data_nthreads=4):
    mean = [float(x) for x in rgb_mean.split(",")]
    shape = tuple(int(x) for x in image_shape.split(","))
    data = mx.io.ImageRecordIter(
        path_imgrec=data_val, data_shape=shape, batch_size=batch_size,
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        preprocess_threads=data_nthreads,
    )
    sym, arg_params, aux_params = mx.model.load_checkpoint(model_prefix, epoch)
    mod = mx.mod.Module(symbol=sym, context=mx.current_context())
    mod.bind(for_training=False, data_shapes=data.provide_data,
             label_shapes=data.provide_label)
    mod.set_params(arg_params, aux_params)
    if metrics is None:
        metrics = [mx.metric.create("acc"), mx.metric.create("top_k_accuracy", top_k=5)]
    num = 0
    tic = time.time()
    for batch in data:
        mod.forward(batch, is_train=False)
        # last batch may be zero-padded: score only the valid rows
        valid = batch_size - (batch.pad or 0)
        outs = [o[:valid] for o in mod.get_outputs()]
        labels = [l[:valid] for l in batch.label]
        for m in metrics:
            m.update(labels, outs)
        num += valid
        if max_num_examples is not None and num >= max_num_examples:
            break
    speed = num / (time.time() - tic)
    logging.info("Finished with %f images per second", speed)
    return [m.get() for m in metrics]


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="score a model on a dataset")
    parser.add_argument("--model-prefix", type=str, required=True)
    parser.add_argument("--epoch", type=int, required=True)
    parser.add_argument("--data-val", type=str, required=True)
    parser.add_argument("--image-shape", type=str, default="3,224,224")
    parser.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939")
    parser.add_argument("--batch-size", type=int, default=32)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    for name, value in score(args.model_prefix, args.epoch, args.data_val,
                             args.image_shape, args.batch_size, args.rgb_mean):
        logging.info("%s = %f", name, value)
