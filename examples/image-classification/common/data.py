"""Data iterators for the classification examples — parity with reference
example/image-classification/common/data.py (add_data_args, get_rec_iter,
SyntheticDataIter for --benchmark)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.io import DataIter, DataBatch, DataDesc


def add_data_args(parser):
    data = parser.add_argument_group("Data", "the input images")
    data.add_argument("--data-train", type=str, help="the training data (.rec)")
    data.add_argument("--data-val", type=str, help="the validation data (.rec)")
    data.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939",
                      help="a tuple of size 3 for the mean rgb")
    data.add_argument("--pad-size", type=int, default=0,
                      help="padding the input image")
    data.add_argument("--image-shape", type=str,
                      help="the image shape feed into the network, e.g. (3,224,224)")
    data.add_argument("--num-classes", type=int, help="the number of classes")
    data.add_argument("--num-examples", type=int, help="the number of training examples")
    data.add_argument("--data-nthreads", type=int, default=4,
                      help="number of threads for data decoding")
    data.add_argument("--benchmark", type=int, default=0,
                      help="if 1, run synthetic random batches (no data files needed)")
    return data


def add_data_aug_args(parser):
    aug = parser.add_argument_group(
        "Image augmentations",
        "crop/mirror/pad/scale run in the data plane; rotate/shear/aspect "
        "are accepted for CLI parity but not implemented yet (warned at use)")
    aug.add_argument("--random-crop", type=int, default=1,
                     help="if or not randomly crop the image")
    aug.add_argument("--random-mirror", type=int, default=1,
                     help="if or not randomly flip horizontally")
    aug.add_argument("--max-random-aspect-ratio", type=float, default=0)
    aug.add_argument("--max-random-rotate-angle", type=int, default=0)
    aug.add_argument("--max-random-shear-ratio", type=float, default=0)
    aug.add_argument("--max-random-scale", type=float, default=1)
    aug.add_argument("--min-random-scale", type=float, default=1)
    return aug


def set_data_aug_level(parser, level):
    if level >= 1:
        parser.set_defaults(random_crop=1, random_mirror=1)
    if level >= 2:
        parser.set_defaults(max_random_scale=1.25, min_random_scale=0.533)
    if level >= 3:
        parser.set_defaults(max_random_rotate_angle=10, max_random_shear_ratio=0.1,
                            max_random_aspect_ratio=0.25)


class SyntheticDataIter(DataIter):
    """Deterministic random batches (the reference's --benchmark 1 path,
    common/data.py SyntheticDataIter)."""

    def __init__(self, num_classes, data_shape, max_iter, dtype="float32"):
        super().__init__(data_shape[0])
        self.batch_size = data_shape[0]
        self.cur_iter = 0
        self.max_iter = max_iter
        self.dtype = dtype
        rng = np.random.RandomState(0)
        label = rng.randint(0, num_classes, (self.batch_size,)).astype(np.float32)
        data = rng.uniform(-1, 1, data_shape).astype(dtype)
        self.data = mx.nd.array(data)
        self.label = mx.nd.array(label)
        self.provide_data = [DataDesc("data", data_shape, dtype)]
        self.provide_label = [DataDesc("softmax_label", (self.batch_size,), "float32")]

    def next(self):
        self.cur_iter += 1
        if self.cur_iter > self.max_iter:
            raise StopIteration
        return DataBatch([self.data], [self.label], pad=0,
                         provide_data=self.provide_data, provide_label=self.provide_label)

    def reset(self):
        self.cur_iter = 0


def get_rec_iter(args, kv=None):
    """(train, val) iterators over .rec files, or synthetic when
    --benchmark 1 (reference common/data.py get_rec_iter)."""
    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    if args.benchmark:
        data_shape = (args.batch_size,) + image_shape
        train = SyntheticDataIter(args.num_classes, data_shape, 50, "float32")
        return train, None
    mean = [float(x) for x in args.rgb_mean.split(",")]
    rank, nworker = (kv.rank, kv.num_workers) if kv else (0, 1)
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_train,
        data_shape=image_shape,
        batch_size=args.batch_size,
        label_width=1,
        shuffle=True,
        rand_crop=args.random_crop > 0,
        rand_mirror=args.random_mirror > 0,
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        pad=args.pad_size,
        max_random_scale=args.max_random_scale,
        min_random_scale=args.min_random_scale,
        max_random_rotate_angle=args.max_random_rotate_angle,
        max_random_shear_ratio=args.max_random_shear_ratio,
        max_random_aspect_ratio=args.max_random_aspect_ratio,
        preprocess_threads=args.data_nthreads,
        num_parts=nworker, part_index=rank,
    )
    if args.data_val is None:
        return train, None
    val = mx.io.ImageRecordIter(
        path_imgrec=args.data_val,
        data_shape=image_shape,
        batch_size=args.batch_size,
        label_width=1,
        shuffle=False,
        rand_crop=False,
        rand_mirror=False,
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        preprocess_threads=args.data_nthreads,
        num_parts=nworker, part_index=rank,
    )
    return train, val
