"""Shared training harness — parity with reference
example/image-classification/common/fit.py (add_fit_args :~60, fit :~140:
kvstore, lr schedule, Module.fit wiring, checkpointing, Speedometer)."""
import logging
import time

import mxnet_tpu as mx


def _get_lr_scheduler(args, kv):
    if "lr_factor" not in args or args.lr_factor >= 1:
        return (args.lr, None)
    epoch_size = _get_epoch_size(args, kv)
    begin_epoch = args.load_epoch if args.load_epoch else 0
    step_epochs = [int(l) for l in args.lr_step_epochs.split(",")] if args.lr_step_epochs else []
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjust learning rate to %e for epoch %d", lr, begin_epoch)
    steps = [
        epoch_size * (x - begin_epoch)
        for x in step_epochs if x - begin_epoch > 0
    ]
    if steps:
        return (lr, mx.lr_scheduler.MultiFactorScheduler(step=steps, factor=args.lr_factor))
    return (lr, None)


def _get_epoch_size(args, kv):
    return int(args.num_examples / args.batch_size / kv.num_workers)


def _load_model(args, rank=0):
    if getattr(args, "load_epoch", None) is None:
        return (None, None, None)
    assert args.model_prefix is not None
    model_prefix = args.model_prefix
    if rank > 0:
        model_prefix += "-%d" % rank
    sym, arg_params, aux_params = mx.model.load_checkpoint(model_prefix, args.load_epoch)
    logging.info("Loaded model %s_%04d.params", model_prefix, args.load_epoch)
    return (sym, arg_params, aux_params)


def _save_model(args, rank=0):
    if args.model_prefix is None:
        return None
    return mx.callback.do_checkpoint(
        args.model_prefix if rank == 0 else "%s-%d" % (args.model_prefix, rank),
        period=args.save_period,
    )


def add_fit_args(parser):
    train = parser.add_argument_group("Training", "model training")
    train.add_argument("--network", type=str, help="the neural network to use")
    train.add_argument("--num-layers", type=int, help="number of layers in the neural network")
    train.add_argument("--gpus", type=str, help="unused on TPU; kept for CLI parity")
    train.add_argument("--kv-store", type=str, default="device", help="key-value store type")
    train.add_argument("--num-epochs", type=int, default=100, help="max num of epochs")
    train.add_argument("--lr", type=float, default=0.1, help="initial learning rate")
    train.add_argument("--lr-factor", type=float, default=0.1,
                       help="the ratio to reduce lr on each step")
    train.add_argument("--lr-step-epochs", type=str, help="the epochs to reduce the lr, e.g. 30,60")
    train.add_argument("--initializer", type=str, default="default", help="the initializer type")
    train.add_argument("--optimizer", type=str, default="sgd", help="the optimizer type")
    train.add_argument("--mom", type=float, default=0.9, help="momentum for sgd")
    train.add_argument("--wd", type=float, default=0.0001, help="weight decay for sgd")
    train.add_argument("--batch-size", type=int, default=128, help="the batch size")
    train.add_argument("--disp-batches", type=int, default=20,
                       help="show progress for every n batches")
    train.add_argument("--model-prefix", type=str, help="model prefix")
    train.add_argument("--save-period", type=int, default=1, help="params saving period")
    train.add_argument("--load-epoch", type=int,
                       help="load the model on an epoch using the model-load-prefix")
    train.add_argument("--top-k", type=int, default=0,
                       help="report the top-k accuracy. 0 means no report.")
    train.add_argument("--dtype", type=str, default="float32",
                       help="precision: float32 or float16")
    train.add_argument("--monitor", dest="monitor", type=int, default=0,
                       help="log network parameters every N iters if larger than 0")
    train.add_argument("--test-io", type=int, default=0,
                       help="1 means test reading speed without training")
    return train


def fit(args, network, data_loader, **kwargs):
    """Train a model: args from argparse, network Symbol, data_loader(args, kv)
    -> (train, val) (reference common/fit.py fit)."""
    kv = mx.kvstore.create(args.kv_store)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s Node[" + str(kv.rank) + "] %(message)s")
    logging.info("start with arguments %s", args)

    (train, val) = data_loader(args, kv)

    if args.test_io:
        tic = time.time()
        for i, batch in enumerate(train):
            for j in batch.data:
                j.wait_to_read()
            if (i + 1) % args.disp_batches == 0:
                logging.info("Batch [%d]\tSpeed: %.2f samples/sec", i,
                             args.disp_batches * args.batch_size / (time.time() - tic))
                tic = time.time()
        return

    if "arg_params" in kwargs and "aux_params" in kwargs:
        arg_params = kwargs["arg_params"]
        aux_params = kwargs["aux_params"]
    else:
        _sym, arg_params, aux_params = _load_model(args, kv.rank)

    checkpoint = _save_model(args, kv.rank)

    lr, lr_scheduler = _get_lr_scheduler(args, kv)

    model = mx.mod.Module(symbol=network, context=mx.current_context())

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "lr_scheduler": lr_scheduler,
    }
    if args.optimizer in {"sgd", "dcasgd", "nag", "signum", "lbsgd"}:
        optimizer_params["momentum"] = args.mom
    if args.dtype == "float16":
        optimizer_params["multi_precision"] = True

    if args.initializer == "default":
        initializer = mx.init.Xavier(rnd_type="gaussian", factor_type="in", magnitude=2)
    elif args.initializer == "xavier":
        initializer = mx.init.Xavier()
    elif args.initializer == "msra":
        initializer = mx.init.MSRAPrelu()
    elif args.initializer == "orthogonal":
        initializer = mx.init.Orthogonal()
    elif args.initializer == "normal":
        initializer = mx.init.Normal()
    elif args.initializer == "uniform":
        initializer = mx.init.Uniform()
    elif args.initializer == "one":
        initializer = mx.init.One()
    elif args.initializer == "zero":
        initializer = mx.init.Zero()
    else:
        raise ValueError("unknown initializer %r" % args.initializer)

    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy", top_k=args.top_k))

    batch_end_callbacks = [mx.callback.Speedometer(args.batch_size, args.disp_batches)]
    monitor = mx.mon.Monitor(args.monitor, pattern=".*") if args.monitor > 0 else None

    model.fit(
        train,
        begin_epoch=args.load_epoch if args.load_epoch else 0,
        num_epoch=args.num_epochs,
        eval_data=val,
        eval_metric=eval_metrics,
        kvstore=kv,
        optimizer=args.optimizer,
        optimizer_params=optimizer_params,
        initializer=initializer,
        arg_params=arg_params,
        aux_params=aux_params,
        batch_end_callback=batch_end_callbacks,
        epoch_end_callback=checkpoint,
        allow_missing=True,
        monitor=monitor,
    )
    return model
