"""Train on CIFAR-10 — parity with reference
example/image-classification/train_cifar10.py (ResNet-110 recipe, Module API).

No network egress in this environment: point --data-train/--data-val at local
cifar10_{train,val}.rec files (build with tools/im2rec.py), or pass
--benchmark 1 for synthetic batches.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
from common import data, fit  # noqa: E402


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_data_aug_args(parser)
    data.set_data_aug_level(parser, 2)
    parser.set_defaults(
        network="resnet",
        num_layers=110,
        data_train=os.path.join("data", "cifar10_train.rec"),
        data_val=os.path.join("data", "cifar10_val.rec"),
        num_classes=10,
        num_examples=50000,
        image_shape="3,28,28",
        pad_size=4,
        batch_size=128,
        num_epochs=300,
        lr=0.05,
        lr_step_epochs="200,250",
    )
    args = parser.parse_args()

    from importlib import import_module

    net = import_module("symbols." + args.network)
    sym = net.get_symbol(**vars(args))

    fit.fit(args, sym, data.get_rec_iter)
