"""CNN sentence classification (reference `example/cnn_text_classification/`
— Kim-2014 style: word embeddings → parallel Conv2D filters of widths
3/4/5 → max-over-time pooling → concat → dropout → FC).

TPU-native shape: all filter widths run as batched convs in one jitted
module; max-over-time is a reduce the compiler fuses into the conv epilogue.
Synthetic "sentiment" data (keyword tokens decide the label, mixed with
noise tokens) replaces the MR dataset in this zero-egress environment.

Run: ``./dev.sh python examples/cnn_text_classification/train.py``
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def make_data(rng, n, vocab, seq_len, pos_tokens, neg_tokens):
    X = rng.randint(10, vocab, (n, seq_len))
    y = rng.randint(0, 2, n)
    for i in range(n):
        toks = pos_tokens if y[i] else neg_tokens
        # plant 2 sentiment keywords at random positions
        pos = rng.choice(seq_len, 2, replace=False)
        X[i, pos] = rng.choice(toks, 2)
    return X.astype(np.float32), y.astype(np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=200)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--embed", type=int, default=24)
    p.add_argument("--filters", type=int, default=32)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.gluon import nn, Trainer, HybridBlock
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    POS, NEG = np.arange(2, 6), np.arange(6, 10)
    Xtr, ytr = make_data(rng, 2048, args.vocab, args.seq_len, POS, NEG)
    Xva, yva = make_data(rng, 512, args.vocab, args.seq_len, POS, NEG)

    class TextCNN(HybridBlock):
        """reference symbol: conv widths 3/4/5 + max-over-time + concat
        (example/cnn_text_classification/text_cnn.py sym_gen)."""

        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(args.vocab, args.embed)
                self.convs = []
                for w in (3, 4, 5):
                    conv = nn.Conv2D(args.filters, kernel_size=(w, args.embed),
                                     activation="relu")
                    self.register_child(conv)
                    self.convs.append(conv)
                self.drop = nn.Dropout(0.3)
                self.fc = nn.Dense(2)

        def hybrid_forward(self, F, x):
            e = self.embed(x)                      # (B, T, E)
            e = e.reshape((0, 1, args.seq_len, args.embed))
            pooled = []
            for conv in self.convs:
                c = conv(e)                        # (B, F, T-w+1, 1)
                pooled.append(F.max(c, axis=2))    # max over time
            h = F.concat(*pooled, dim=1)
            h = self.drop(h.reshape((0, -1)))
            return self.fc(h)

    net = TextCNN()
    net.initialize(mx.init.Xavier())
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": args.lr})
    loss_fn = SoftmaxCrossEntropyLoss()

    n_batches = len(Xtr) // args.batch
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        tot = 0.0
        for b in range(n_batches):
            sl = perm[b * args.batch:(b + 1) * args.batch]
            x, y = nd.array(Xtr[sl]), nd.array(ytr[sl])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(args.batch)
            tot += float(loss.mean().asnumpy())
        pred = net(nd.array(Xva)).asnumpy().argmax(1)
        acc = (pred == yva).mean()
        print("epoch %d loss %.4f val-acc %.3f" % (epoch, tot / n_batches, acc))
    assert acc > 0.9, "text CNN failed to learn (val-acc %.3f)" % acc
    print("TEXT CNN OK")


if __name__ == "__main__":
    main()
