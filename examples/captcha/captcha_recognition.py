"""Multi-digit CAPTCHA recognition — reference ``example/captcha/``
(``mxnet_captcha.R``: a CNN over 4-digit captcha images with a length-4
multi-label softmax head; the reference ships it as an R-frontend example,
the capability here is the Python/TPU port).

Synthetic captchas: 4 digits rendered as 7-segment-style glyph masks at
jittered positions on a noisy canvas; the net reads out all 4 positions
with one shared trunk and a (4*10)-way head reshaped to (B,4,10) —
exactly the R example's ``mx.symbol.Reshape -> SoftmaxOutput(multi)``
structure.

Run: ./dev.sh python examples/captcha/captcha_recognition.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

# 7-segment truth table: which of (top, tl, tr, mid, bl, br, bottom) light up
_SEGS = {
    0: (1, 1, 1, 0, 1, 1, 1), 1: (0, 0, 1, 0, 0, 1, 0),
    2: (1, 0, 1, 1, 1, 0, 1), 3: (1, 0, 1, 1, 0, 1, 1),
    4: (0, 1, 1, 1, 0, 1, 0), 5: (1, 1, 0, 1, 0, 1, 1),
    6: (1, 1, 0, 1, 1, 1, 1), 7: (1, 0, 1, 0, 0, 1, 0),
    8: (1, 1, 1, 1, 1, 1, 1), 9: (1, 1, 1, 1, 0, 1, 1),
}


def _draw_digit(canvas, d, x0, y0, h=12, w=8):
    t, tl, tr, m, bl, br, b = _SEGS[d]
    x1, y1 = x0 + w, y0 + h
    ym = y0 + h // 2
    if t:
        canvas[y0:y0 + 2, x0:x1] = 1.0
    if m:
        canvas[ym:ym + 2, x0:x1] = 1.0
    if b:
        canvas[y1 - 2:y1, x0:x1] = 1.0
    if tl:
        canvas[y0:ym, x0:x0 + 2] = 1.0
    if tr:
        canvas[y0:ym, x1 - 2:x1] = 1.0
    if bl:
        canvas[ym:y1, x0:x0 + 2] = 1.0
    if br:
        canvas[ym:y1, x1 - 2:x1] = 1.0


def make_captchas(rng, n, digits=4, h=20, w=56):
    xs = rng.rand(n, 1, h, w).astype(np.float32) * 0.3
    ys = rng.randint(0, 10, (n, digits))
    for i in range(n):
        for j in range(digits):
            _draw_digit(xs[i, 0], int(ys[i, j]),
                        2 + j * 13 + rng.randint(0, 3), rng.randint(2, 6))
    return xs, ys.astype(np.int32)


class CaptchaNet(gluon.HybridBlock):
    """Conv trunk + one (digits*10) head (mxnet_captcha.R net structure)."""

    def __init__(self, digits=4, **kw):
        super().__init__(**kw)
        self.digits = digits
        with self.name_scope():
            self.features = nn.HybridSequential()
            self.features.add(
                nn.Conv2D(32, 3, padding=1), nn.Activation("relu"),
                nn.MaxPool2D(2),
                nn.Conv2D(64, 3, padding=1), nn.Activation("relu"),
                nn.MaxPool2D(2),
                nn.Flatten(), nn.Dense(256, activation="relu"))
            self.head = nn.Dense(digits * 10)

    def hybrid_forward(self, F, x):
        z = self.head(self.features(x))
        return F.reshape(z, (0, self.digits, 10))


def main(epochs=8, batch=64, n_train=2048, n_val=256):
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    xs, ys = make_captchas(rng, n_train + n_val)

    net = CaptchaNet()
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(axis=-1)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})

    for epoch in range(epochs):
        perm = rng.permutation(n_train)
        tot = 0.0
        for s in range(0, n_train, batch):
            idx = perm[s:s + batch]
            x = nd.array(xs[idx])
            y = nd.array(ys[idx].astype(np.float32))
            with autograd.record():
                logits = net(x)            # (B, 4, 10)
                loss = loss_fn(logits, y).mean()
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        print("epoch %d  loss %.4f" % (epoch, tot / (n_train // batch)))

    pred = net(nd.array(xs[n_train:])).asnumpy().argmax(-1)
    per_digit = (pred == ys[n_train:]).mean()
    per_captcha = (pred == ys[n_train:]).all(axis=1).mean()
    print("val per-digit acc %.3f, whole-captcha acc %.3f"
          % (per_digit, per_captcha))
    return per_digit, per_captcha


if __name__ == "__main__":
    main()
