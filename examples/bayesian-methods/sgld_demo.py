"""SGLD posterior sampling — reference ``example/bayesian-methods/``
(``sgld.ipynb`` + ``bdk_demo.py`` run_synthetic_SGLD: the Welling & Teh
2011 mixture-posterior experiment).

Same experiment, TPU-idiomatic: the gaussian-mixture log-posterior gradient
is plain autograd on a jit-able loss (the reference hand-codes it in numpy,
``bdk_demo.py synthetic_grad:119``), and SGLD's injected noise comes from
the framework optimizer (``mx.optimizer.SGLD``).  The sampled θ₁ histogram
must recover BOTH posterior modes — the property the paper's figure shows.

Run: ./dev.sh python examples/bayesian-methods/sgld_demo.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


SIGMA1, SIGMA2, SIGMAX = 10.0, 1.0, 2.0


def make_data(n=100, seed=10):
    """x ~ ½N(θ₁,σx²)+½N(θ₁+θ₂,σx²) at true θ=(0,1) (Welling&Teh §5.1)."""
    rng = np.random.RandomState(seed)
    comp = rng.rand(n) < 0.5
    x = np.where(comp, rng.randn(n) * SIGMAX + 0.0,
                 rng.randn(n) * SIGMAX + 1.0)
    return x.astype(np.float32)


def neg_log_posterior(theta, xb, n_total):
    """−log p(θ)·scale − Σ log p(x|θ), minibatch-rescaled (the SGLD
    gradient target; reference synthetic_grad)."""
    t1, t2 = theta[0], theta[1]
    lik1 = nd.exp(-0.5 * ((xb - t1) ** 2) / SIGMAX ** 2)
    lik2 = nd.exp(-0.5 * ((xb - t1 - t2) ** 2) / SIGMAX ** 2)
    log_lik = nd.log(0.5 * lik1 + 0.5 * lik2 + 1e-12).sum()
    log_prior = (-0.5 * (t1 ** 2) / SIGMA1 ** 2
                 - 0.5 * (t2 ** 2) / SIGMA2 ** 2)
    batch = xb.shape[0]
    return -(log_prior + (n_total / batch) * log_lik)


def main(n_samples=12000, batch=10, seed=0, burn_in=2000):
    mx.random.seed(seed)
    np.random.seed(seed)
    X = make_data()
    n = len(X)
    theta = nd.array(np.array([0.1, 0.1], np.float32))
    theta.attach_grad()
    # polynomial step-size decay a(b+t)^-γ as in the paper/reference
    opt = mx.optimizer.create("sgld", learning_rate=0.05,
                              lr_scheduler=mx.lr_scheduler.PolyScheduler(
                                  max_update=n_samples, base_lr=0.05,
                                  final_lr=0.0001, pwr=0.55))
    samples = []
    for t in range(n_samples):
        idx = np.random.randint(0, n, batch)
        xb = nd.array(X[idx])
        with autograd.record():
            loss = neg_log_posterior(theta, xb, n)
        loss.backward()
        opt.update(0, theta, theta.grad, None)
        if t >= burn_in:
            samples.append(theta.asnumpy().copy())
    S = np.asarray(samples)
    # the θ₁ posterior is bimodal (modes near 0 and ~1): both must be hit
    lo = float((S[:, 0] < 0.4).mean())
    hi = float((S[:, 0] > 0.6).mean())
    print("sgld: %d samples, theta1 mass below 0.4: %.2f, above 0.6: %.2f "
          "(bimodal => both > 0.05)" % (len(S), lo, hi))
    return S


if __name__ == "__main__":
    main()
