"""LSTM language model with bucketing — parity with reference
``example/rnn/bucketing/lstm_bucketing.py`` (BucketingModule over
BucketSentenceIter; each bucket length is one jit specialization, the
reference's per-bucket executor).

Zero-egress environment: point --data-train at a local PTB-format text file,
or omit it to train on a generated synthetic corpus with Zipfian unigrams and
bigram structure (learnable by the LM).
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx

parser = argparse.ArgumentParser(
    description="Train an LSTM LM with bucketing",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--data-train", type=str, default=None,
                    help="PTB-style text file; synthetic corpus when absent")
parser.add_argument("--num-layers", type=int, default=2)
parser.add_argument("--num-hidden", type=int, default=128)
parser.add_argument("--num-embed", type=int, default=64)
parser.add_argument("--kv-store", type=str, default="device")
parser.add_argument("--num-epochs", type=int, default=5)
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--optimizer", type=str, default="adam")
parser.add_argument("--mom", type=float, default=0.0)
parser.add_argument("--wd", type=float, default=0.00001)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--disp-batches", type=int, default=50)
parser.add_argument("--num-sentences", type=int, default=2000,
                    help="synthetic corpus size")


def tokenize_text(fname, vocab=None, invalid_label=-1, start_label=0):
    if not os.path.isfile(fname):
        raise IOError("file %s not found (downloads unavailable; pass a local "
                      "PTB-format file or omit --data-train)" % fname)
    lines = [list(filter(None, line.split(" "))) for line in open(fname)]
    return mx.rnn.encode_sentences(lines, vocab=vocab,
                                   invalid_label=invalid_label,
                                   start_label=start_label)


def synthetic_corpus(n, vocab_size=60, seed=0):
    """Zipfian unigrams + deterministic bigram successor structure."""
    rng = np.random.RandomState(seed)
    succ = rng.randint(1, vocab_size, size=vocab_size)
    sents = []
    for _ in range(n):
        length = rng.randint(4, 24)
        w = rng.zipf(1.5) % vocab_size or 1
        sent = [int(w)]
        for _ in range(length - 1):
            w = succ[w] if rng.rand() < 0.8 else (rng.zipf(1.5) % vocab_size or 1)
            sent.append(int(w))
        sents.append(sent)
    return sents, vocab_size


def main():
    logging.basicConfig(level=logging.INFO, format="%(asctime)-15s %(message)s")
    args = parser.parse_args()
    buckets = [10, 20, 30]
    start_label = 1
    invalid_label = 0

    if args.data_train:
        train_sent, vocab = tokenize_text(
            args.data_train, start_label=start_label, invalid_label=invalid_label)
        vocab_size = len(vocab) + start_label
    else:
        train_sent, vocab_size = synthetic_corpus(args.num_sentences)

    data_train = mx.rnn.BucketSentenceIter(
        train_sent, args.batch_size, buckets=buckets, invalid_label=invalid_label)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden, prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, states = stack.unroll(seq_len, inputs=embed, merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size, name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, label=label, name="softmax")
        return pred, ("data",), ("softmax_label",)

    model = mx.mod.BucketingModule(
        sym_gen=sym_gen,
        default_bucket_key=data_train.default_bucket_key,
        context=mx.current_context())

    model.fit(
        train_data=data_train,
        eval_metric=mx.metric.Perplexity(invalid_label),
        kvstore=args.kv_store,
        optimizer=args.optimizer,
        optimizer_params=(
            {"learning_rate": args.lr, "wd": args.wd, "momentum": args.mom}
            if args.optimizer in ("sgd", "nag", "signum")
            else {"learning_rate": args.lr, "wd": args.wd}),
        initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size, args.disp_batches),
    )
    return model


if __name__ == "__main__":
    main()
