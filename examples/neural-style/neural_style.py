"""Neural style transfer (reference `example/neural-style/nstyle.py`:
optimize an IMAGE so that deep conv features match a content image while
the Gram matrices of shallower features match a style image).

The reference descends on the input through a pretrained VGG-19; with zero
egress there are no pretrained weights here, so a fixed random conv
feature extractor stands in — random conv features are a known-workable
style/content signal (random-feature style transfer), and every framework
mechanism the reference exercises is identical: frozen network, gradient
with respect to the INPUT pixels, Gram-matrix losses, Adam on the image.

Run: ``./dev.sh python examples/neural-style/neural_style.py``
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--iters", type=int, default=120)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--style-weight", type=float, default=1.0)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    S = args.size

    # frozen random feature extractor: two conv stages (≡ vgg relu1/relu2)
    feat1 = nn.HybridSequential()
    with feat1.name_scope():
        feat1.add(nn.Conv2D(16, 3, padding=1, activation="relu"))
    feat2 = nn.HybridSequential()
    with feat2.name_scope():
        feat2.add(nn.Conv2D(32, 3, strides=2, padding=1, activation="relu"))
    for block in (feat1, feat2):
        block.initialize(mx.init.Xavier())

    def features(img):
        f1 = feat1(img)
        return f1, feat2(f1)

    def gram(f):
        b, c = f.shape[0], f.shape[1]
        flat = f.reshape((b, c, -1))
        n = flat.shape[2]
        return nd.batch_dot(flat, flat.transpose((0, 2, 1))) / n

    # content: smooth gradient image; style: high-frequency checkers
    yy, xx = np.mgrid[0:S, 0:S].astype(np.float32) / S
    content = np.stack([yy, xx, (yy + xx) / 2])[None]
    checker = ((np.indices((S, S)).sum(0) % 2) * 1.0).astype(np.float32)
    style = np.stack([checker, 1 - checker, checker])[None]

    c_img, s_img = nd.array(content), nd.array(style)
    _, c_feat = features(c_img)
    s1, s2 = features(s_img)
    s_grams = [gram(s1), gram(s2)]

    img = nd.array(rng.rand(1, 3, S, S).astype(np.float32))
    img.attach_grad()

    # the framework Adam applied to the IMAGE (reference nstyle.py does the
    # same with mx.optimizer on the img ndarray)
    opt = mx.optimizer.Adam(learning_rate=args.lr)
    opt_state = opt.create_state(0, img)
    losses = []
    for t in range(1, args.iters + 1):
        with autograd.record():
            f1, f2 = features(img)
            closs = ((f2 - c_feat) ** 2).mean()
            sloss = sum(((gram(f) - g) ** 2).mean()
                        for f, g in zip((f1, f2), s_grams))
            loss = closs + args.style_weight * sloss
        loss.backward()
        opt.update(0, img, img.grad, opt_state)
        losses.append(float(loss.asnumpy()))
    print("style+content loss %.4f -> %.4f" % (losses[0], losses[-1]))
    assert losses[-1] < losses[0] * 0.5, "style optimization did not converge"
    out = img.asnumpy()
    assert out.shape == (1, 3, S, S) and np.isfinite(out).all()
    print("NEURAL STYLE OK")


if __name__ == "__main__":
    main()
