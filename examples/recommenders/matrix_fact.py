"""Matrix-factorization recommender (reference `example/recommenders/` —
demo1-MF: user/item embeddings, dot-product score, fit on rating triples).

TPU-native shape: embeddings are plain dense params, the whole SGD step is
one jitted XLA module via gluon.functional; the embedding gathers hit the
TPU's vector path and the (batch, K) dot rides the MXU.  Synthetic
low-rank ratings stand in for MovieLens (zero-egress environment).

Run: ``./dev.sh python examples/recommenders/matrix_fact.py``
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--users", type=int, default=400)
    p.add_argument("--items", type=int, default=300)
    p.add_argument("--rank", type=int, default=8)
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--lr", type=float, default=0.08)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.gluon import nn, Trainer, HybridBlock
    from mxnet_tpu.gluon.loss import L2Loss

    mx.random.seed(0)
    rng = np.random.RandomState(0)

    # ground-truth low-rank structure + noise
    U = rng.randn(args.users, args.rank).astype(np.float32) * 0.7
    V = rng.randn(args.items, args.rank).astype(np.float32) * 0.7
    n_obs = 40_000
    u_idx = rng.randint(0, args.users, n_obs)
    i_idx = rng.randint(0, args.items, n_obs)
    ratings = (U[u_idx] * V[i_idx]).sum(1) + 0.05 * rng.randn(n_obs)
    ratings = ratings.astype(np.float32)

    class MF(HybridBlock):
        """score(u, i) = <user_emb[u], item_emb[i]> (reference
        demo1-MF's plain_net symbol)."""

        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.user = nn.Embedding(args.users, args.rank)
                self.item = nn.Embedding(args.items, args.rank)

        def hybrid_forward(self, F, u, i):
            return (self.user(u) * self.item(i)).sum(axis=-1)

    net = MF()
    net.initialize(mx.init.Normal(0.1))
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": args.lr})
    loss_fn = L2Loss()

    n_batches = n_obs // args.batch
    first = last = None
    for epoch in range(args.epochs):
        perm = rng.permutation(n_obs)
        tot = 0.0
        for b in range(n_batches):
            sl = perm[b * args.batch:(b + 1) * args.batch]
            u = nd.array(u_idx[sl].astype(np.float32))
            i = nd.array(i_idx[sl].astype(np.float32))
            r = nd.array(ratings[sl])
            with autograd.record():
                loss = loss_fn(net(u, i), r)
            loss.backward()
            trainer.step(args.batch)
            tot += float(loss.mean().asnumpy())
        rmse = np.sqrt(2 * tot / n_batches)  # L2Loss is 1/2 (x-y)^2
        if first is None:
            first = rmse
        last = rmse
        print("epoch %d rmse %.4f" % (epoch, rmse))
    assert last < first * 0.5, "MF failed to learn (rmse %.3f -> %.3f)" % (first, last)
    print("MATRIX FACTORIZATION OK")


if __name__ == "__main__":
    main()
