"""Deep Embedded Clustering (DEC) — reference
``example/deep-embedded-clustering/dec.py`` (Xie et al. 2016).

The reference pipeline: layerwise-pretrained autoencoder → k-means init of
cluster centers in code space → iterate { student-t soft assignment q,
sharpened target p = q^2/f (normalized), minimize KL(p||q) over encoder AND
centers } until label changes drop below tol.  Its DECLoss is a hand-written
NumpyOp with an analytic backward (dec.py:45-69).

TPU-native: q, p, and KL are ordinary differentiable expressions — autograd
derives the reference's analytic gradients, and the whole update jit-fuses.
k-means init is a few Lloyd iterations in jax (no sklearn offline);
cluster accuracy uses the Hungarian assignment (scipy)
exactly as the reference's ``cluster_acc``.

Run: ./dev.sh python examples/deep-embedded-clustering/dec.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def make_blobs(rng, n=1500, k=4, dim=32, spread=4.0):
    centers = rng.randn(k, dim) * spread
    y = rng.randint(0, k, n)
    return (centers[y] + rng.randn(n, dim)).astype(np.float32), y


class Encoder(gluon.HybridBlock):
    """Encoder half of the reference's [d,500,500,2000,10] SAE, scaled down."""

    def __init__(self, code=8, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.body = nn.HybridSequential()
            self.body.add(nn.Dense(64, activation="relu"),
                          nn.Dense(64, activation="relu"),
                          nn.Dense(code))

    def hybrid_forward(self, F, x):
        return self.body(x)


def pretrain_autoencoder(xs, code=8, epochs=30, batch=128, lr=5e-3, seed=0):
    """Reconstruction pretrain (stand-in for the reference's 100k-step SAE)."""
    mx.random.seed(seed)
    enc = Encoder(code)
    dec_head = nn.Dense(xs.shape[1])
    enc.initialize(mx.init.Xavier())
    dec_head.initialize(mx.init.Xavier())
    params = {}
    params.update(enc.collect_params())
    params.update(dec_head.collect_params())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": lr})
    rng = np.random.RandomState(seed)
    for _ in range(epochs):
        perm = rng.permutation(len(xs))
        for s in range(0, len(xs), batch):
            x = nd.array(xs[perm[s:s + batch]])
            with autograd.record():
                z = enc(x)
                rec = dec_head(z)
                loss = ((rec - x) ** 2).mean()
            loss.backward()
            trainer.step(1)
    return enc, float(loss.asnumpy())


def kmeans(z, k, iters=20, seed=0):
    """Plain Lloyd iterations (replaces the reference's sklearn KMeans)."""
    rng = np.random.RandomState(seed)
    mu = z[rng.choice(len(z), k, replace=False)].copy()
    for _ in range(iters):
        d = ((z[:, None] - mu[None]) ** 2).sum(-1)
        a = d.argmin(1)
        for j in range(k):
            if (a == j).any():
                mu[j] = z[a == j].mean(0)
    return mu, a


def soft_assign(z, mu, alpha=1.0):
    """Student-t similarity q_ij (reference DECLoss.forward)."""
    d2 = ((z.expand_dims(1) - mu.expand_dims(0)) ** 2).sum(-1)
    q = (1.0 + d2 / alpha) ** (-(alpha + 1.0) / 2.0)
    return q / q.sum(axis=1, keepdims=True)


def target_distribution(q):
    """p = q^2 / freq, normalized (the DEC sharpening step)."""
    w = (q ** 2) / q.sum(0, keepdims=True)
    return w / w.sum(1, keepdims=True)


def cluster_acc(pred, y):
    """Best 1:1 label matching (reference cluster_acc, Hungarian)."""
    from scipy.optimize import linear_sum_assignment

    D = int(max(pred.max(), y.max())) + 1
    w = np.zeros((D, D), np.int64)
    for i in range(pred.size):
        w[pred[i], int(y[i])] += 1
    r, c = linear_sum_assignment(w.max() - w)
    return w[r, c].sum() / pred.size


def main(n=1500, k=4, update_interval=30, tol=0.001, max_iter=12,
         batch=256, seed=0):
    rng = np.random.RandomState(seed)
    xs, y = make_blobs(rng, n, k)
    enc, rec_err = pretrain_autoencoder(xs, seed=seed)
    print("autoencoder pretrain reconstruction mse %.4f" % rec_err)

    z0 = enc(nd.array(xs)).asnumpy()
    mu0, a0 = kmeans(z0, k, seed=seed)
    print("kmeans init acc %.3f" % cluster_acc(a0, y))

    mu = nd.array(mu0)
    mu.attach_grad()
    trainer = gluon.Trainer(enc.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    last = a0
    for it in range(max_iter):
        # E-like step: refresh the sharpened target on the full set
        q_all = soft_assign(enc(nd.array(xs)), mu).asnumpy()
        p_all = target_distribution(nd.array(q_all)).asnumpy()
        pred = q_all.argmax(1)
        delta = (pred != last).mean()
        last = pred
        if it > 0 and delta < tol:
            print("converged: label delta %.4f < tol" % delta)
            break
        # M step: KL(p || q) minimized over encoder weights AND centers
        perm = rng.permutation(n)
        for s in range(0, n, batch):
            idx = perm[s:s + batch]
            x = nd.array(xs[idx])
            p = nd.array(p_all[idx])
            with autograd.record():
                q = soft_assign(enc(x), mu)
                kl = (p * ((p + 1e-10).log() - (q + 1e-10).log())).sum(1).mean()
            kl.backward()
            trainer.step(1)
            mu._rebind((mu - 0.1 * mu.grad)._data)  # plain SGD on centers
            mu.attach_grad()
        acc = cluster_acc(pred, y)
        print("iter %d  kl %.4f  delta %.4f  acc %.3f"
              % (it, float(kl.asnumpy()), delta, acc))
    final = cluster_acc(last, y)
    print("final cluster acc %.3f" % final)
    return final


if __name__ == "__main__":
    main()
