"""Stacked autoencoder — reference ``example/autoencoder/`` (autoencoder.py:
layerwise-pretrained dense AE on MNIST, finetuned end-to-end).

The reference family's core moves, reproduced on the offline-available real
dataset (sklearn digits): greedy LAYERWISE pretraining of each
encoder/decoder pair on the frozen representation below it, then end-to-end
finetuning — reporting reconstruction MSE and a linear-probe accuracy on
the learned code (shows the representation carries class structure).

Run: ./dev.sh python examples/autoencoder/train_ae.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


class DenseAE(mx.gluon.Block):
    """Encoder/decoder stacks with tied depth (reference AutoEncoderModel)."""

    def __init__(self, dims=(64, 32, 16), **kw):
        super().__init__(**kw)
        self.depth = len(dims) - 1
        with self.name_scope():
            self.encoders = mx.gluon.nn.HybridSequential()
            self.decoders = mx.gluon.nn.HybridSequential()
            for i in range(self.depth):
                self.encoders.add(mx.gluon.nn.Dense(dims[i + 1], activation="relu"))
            for i in reversed(range(self.depth)):
                act = "relu" if i > 0 else None
                self.decoders.add(mx.gluon.nn.Dense(dims[i], activation=act))

    def encode(self, x, depth=None):
        h = x
        for i in range(depth if depth is not None else self.depth):
            h = self.encoders[i](h)
        return h

    def forward(self, x, depth=None):
        # (Block.__call__ forwards positional args only)
        d = depth if depth is not None else self.depth
        h = self.encode(x, d)
        for i in range(self.depth - d, self.depth):
            h = self.decoders[i](h)
        return h


def main(pre_epochs=12, fine_epochs=20, batch=64, lr=0.05, seed=0):
    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split

    mx.random.seed(seed)
    np.random.seed(seed)
    X, y = load_digits(return_X_y=True)
    X = X.astype(np.float32) / 16.0
    Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=0.25,
                                          random_state=seed, stratify=y)
    net = DenseAE(dims=(64, 32, 16))
    net.initialize(mx.init.Xavier())
    net(nd.array(Xtr[:2]))  # materialize
    l2 = mx.gluon.loss.L2Loss()
    n = Xtr.shape[0]

    def run_epochs(depth, epochs, params):
        tr = mx.gluon.Trainer(params, "sgd", {"learning_rate": lr,
                                              "momentum": 0.9})
        last = None
        for _ in range(epochs):
            perm = np.random.permutation(n)
            tot = cnt = 0
            for i in range(0, n - batch + 1, batch):
                xb = nd.array(Xtr[perm[i:i + batch]])
                with autograd.record():
                    loss = l2(net(xb, depth), xb)
                loss.backward()
                tr.step(batch)
                tot += float(loss.mean().asnumpy())
                cnt += 1
            last = tot / cnt
        return last

    # greedy layerwise pretraining (reference layerwise_pretrain): train
    # each (encoder_i, decoder_{depth-1-i}) pair with the rest frozen
    for d in range(1, net.depth + 1):
        pair = {}
        pair.update(net.encoders[d - 1].collect_params())
        pair.update(net.decoders[net.depth - d].collect_params())
        mse = run_epochs(d, pre_epochs, pair)
        print("pretrain depth %d  mse %.5f" % (d, mse), flush=True)

    # end-to-end finetune (reference finetune)
    mse = run_epochs(None, fine_epochs, net.collect_params())
    rec_te = float(l2(net(nd.array(Xte)), nd.array(Xte)).mean().asnumpy())
    print("finetune train mse %.5f  held-out mse %.5f" % (mse, rec_te))

    # linear probe on the 16-d code: class structure survives compression
    ztr = net.encode(nd.array(Xtr)).asnumpy()
    zte = net.encode(nd.array(Xte)).asnumpy()
    from sklearn.linear_model import LogisticRegression

    clf = LogisticRegression(max_iter=2000).fit(ztr, ytr)
    probe = clf.score(zte, yte)
    print("FINAL autoencoder: held-out recon MSE %.5f  linear-probe acc %.4f"
          % (rec_te, probe))
    return rec_te, probe


if __name__ == "__main__":
    main()
