"""SVM-output training — reference ``example/svm_mnist/svm_mnist.py``
(an MLP trained with ``SVMOutput``'s multiclass hinge gradient instead of
softmax CE).

Exercises SVMOutput's injected hinge backward (L2-SVM default and the
``use_linear`` L1 variant) end-to-end on REAL data: sklearn's handwritten
digits (the reference used MNIST, unreachable offline).

Run: ./dev.sh python examples/svm_mnist/svm_mnist.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def main(epochs=30, batch=64, lr=0.02, use_linear=False, seed=0):
    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split

    mx.random.seed(seed)
    np.random.seed(seed)
    X, y = load_digits(return_X_y=True)
    X = (X.astype(np.float32) / 16.0)
    y = y.astype(np.float32)
    Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=0.25,
                                          random_state=seed, stratify=y)

    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(128, activation="relu"),
            mx.gluon.nn.Dense(64, activation="relu"),
            mx.gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": lr, "momentum": 0.9})

    n = Xtr.shape[0]
    for epoch in range(epochs):
        perm = np.random.permutation(n)
        for i in range(0, n - batch + 1, batch):
            sel = perm[i:i + batch]
            with autograd.record():
                scores = net(nd.array(Xtr[sel]))
                # hinge gradient injected by the layer (reference
                # svm_output-inl.h); margin/regularization per the example
                out = nd.SVMOutput(scores, nd.array(ytr[sel]), margin=1.0,
                                   regularization_coefficient=1.0,
                                   use_linear=use_linear)
            out.backward()
            trainer.step(batch)
        if epoch % 10 == 9:
            acc = (net(nd.array(Xte)).asnumpy().argmax(1) == yte).mean()
            print("epoch %2d  test acc %.4f" % (epoch, acc), flush=True)

    acc = (net(nd.array(Xte)).asnumpy().argmax(1) == yte).mean()
    print("FINAL svm_%s: test acc %.4f  (n_test=%d)"
          % ("l1" if use_linear else "l2", acc, len(yte)))
    return acc


if __name__ == "__main__":
    main()
    main(use_linear=True, epochs=15)
