"""Online serving demo (ISSUE 2): checkpoint -> warmed engine -> traffic.

End-to-end tour of `mxnet_tpu.serving` on a toy checkpoint (so it runs on
CPU in seconds): train-free random MLP saved with `model.save_checkpoint`,
re-loaded into an Engine with a (1, 2, 4, 8) bucket ladder, warmed up, then
hit with a burst of concurrent mixed-size requests while one request is
cancelled and one oversize request takes the direct-dispatch path.
Prints the engine stats that matter in production: compiles (== ladder
size, never growing with traffic), batch counts per bucket, sheds/timeouts.

Run:  python examples/serving/serve_mlp.py
With telemetry:  MXNET_TELEMETRY=1 python examples/serving/serve_mlp.py
(then inspect telemetry.jsonl, docs/OBSERVABILITY.md)
"""
import os
import sys
import tempfile
import threading

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

import mxnet_tpu as mx
from mxnet_tpu import nd, serving


def make_checkpoint(prefix):
    """A deployment-shaped artifact: *-symbol.json + *-0001.params."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    exe = net.simple_bind(grad_req="null", data=(2, 16))
    rng = np.random.RandomState(0)
    args = {n: nd.array(rng.randn(*a.shape).astype(np.float32) * 0.1)
            for n, a in exe.arg_dict.items()
            if n not in ("data", "softmax_label")}
    mx.model.save_checkpoint(prefix, 1, net, args, {})
    return prefix + "-symbol.json", prefix + "-0001.params"


def main():
    with tempfile.TemporaryDirectory() as tmp:
        sym_file, param_file = make_checkpoint(os.path.join(tmp, "mlp"))

        eng = serving.Engine(
            sym_file, param_file, sample_shapes={"data": (16,)},
            ladder=serving.BucketLadder(serving.pow2_ladder(8)),
            max_wait_ms=3, max_queue=128, start=False)

        print("== warmup: compile the whole ladder before traffic ==")
        for row in eng.warmup():
            print("  %-16s compile %.3fs" % (row["bucket"], row["compile_s"]))
        eng.start()

        print("== concurrent mixed-size burst ==")
        rng = np.random.RandomState(1)
        results, lock = [], threading.Lock()

        def client(i):
            n = int(rng.randint(1, 5))
            out = eng.predict({"data": np.random.rand(n, 16)
                               .astype(np.float32)}, timeout=5)
            with lock:
                results.append((i, n, out[0].shape))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(20)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        print("  %d requests served" % len(results))

        # async + cancel
        fut = eng.submit({"data": np.zeros((1, 16), np.float32)})
        if fut.cancel():
            print("  cancelled one queued request")

        # oversize -> direct dispatch (exact one-off signature)
        big = eng.predict({"data": np.zeros((13, 16), np.float32)})
        print("  direct-dispatch output: %s" % (big[0].shape,))

        s = eng.stats()
        print("== engine stats ==")
        print("  compiles=%d (ladder=%d + 1 direct)  batches=%d  "
              "cache_hits=%d" % (s["compiles"], len(s["ladder"]),
                                 s["batches"], s["cache_hits"]))
        print("  completed=%d shed=%d timeouts=%d cancelled=%d direct=%d"
              % (s["completed"], s["shed"], s["timeouts"], s["cancelled"],
                 s["direct"]))
        for bucket, count in sorted(s["buckets"].items()):
            print("  %-20s x%d" % (bucket, count))
        eng.close()


if __name__ == "__main__":
    main()
