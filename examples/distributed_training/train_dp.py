"""Data-parallel training over a device mesh — the BASELINE config-4
capability (reference: multi-GPU `--gpus 0,1,..` Module training with
kvstore 'device'; here jax.sharding over an ICI mesh, SURVEY §2.2).

On TPU pods this runs over real chips; for development it uses the virtual
8-device CPU mesh (dev.sh). The whole step — forward, backward, gradient
psum over dp, BN stats, SGD momentum — is ONE jitted XLA module; XLA inserts
the ICI collectives from the shardings (no NCCL/ps-lite analog needed).
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-devices", type=int, default=0,
                   help="0 = all visible devices")
    p.add_argument("--batch-per-device", type=int, default=8)
    p.add_argument("--steps", type=int, default=24)
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--ckpt-dir", default=None,
                   help="rotating sharded checkpoints + resume-from-latest "
                        "(the reference's recovery story: epoch checkpoints "
                        "+ relaunch, SURVEY §5.3/§5.4)")
    p.add_argument("--ckpt-every", type=int, default=8)
    args = p.parse_args()

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as loss_mod
    from mxnet_tpu.gluon.functional import make_train_step
    from mxnet_tpu.gluon.model_zoo import vision

    devs = jax.devices()
    n = args.num_devices or len(devs)
    mesh = parallel.make_mesh({"dp": n}, devices=devs[:n])

    mx.random.seed(0)
    np.random.seed(0)
    net = vision.resnet18_v1(classes=args.classes)
    net.initialize()
    net(mx.nd.zeros((1, 3, args.image_size, args.image_size)))

    step, state, _ = make_train_step(
        net, loss_mod.SoftmaxCrossEntropyLoss(),
        learning_rate=args.lr, momentum=0.9)

    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P("dp"))
    state = jax.tree_util.tree_map(lambda v: jax.device_put(v, repl), state)

    mgr, start_step = None, 0
    if args.ckpt_dir:
        from mxnet_tpu.parallel import checkpoint as ckpt

        mgr = ckpt.CheckpointManager(args.ckpt_dir, max_to_keep=2)
        if mgr.latest_step() is not None:
            start_step = mgr.latest_step()
            state = mgr.restore(like=state)
            print("resumed from step %d" % start_step)
        if start_step >= args.steps:
            print("checkpoint already at step %d >= --steps %d; nothing to do"
                  % (start_step, args.steps))
            mgr.close()
            print("DP TRAINING OK")
            return

    batch = n * args.batch_per_device
    jstep = jax.jit(step, donate_argnums=(0,))

    losses = []
    t0 = None
    for i in range(start_step, args.steps):
        # per-step seed: a resumed run draws the SAME stream positions an
        # uninterrupted run would (exact-resume continuity)
        rng = np.random.RandomState(1234 + i)
        y_np = rng.randint(0, args.classes, (batch,))
        x_np = rng.rand(batch, 3, args.image_size, args.image_size).astype(np.float32) * 0.2
        for b in range(batch):  # learnable signal: class-indexed bright band
            x_np[b, y_np[b] % 3, :, : 4 + y_np[b]] += 0.7
        x = jax.device_put(x_np, batch_sh)
        y = jax.device_put(y_np.astype(np.float32), batch_sh)
        state, loss = jstep(state, x, y, jax.random.PRNGKey(i))
        losses.append(float(jax.block_until_ready(loss)))
        if mgr is not None and (i + 1) % args.ckpt_every == 0:
            mgr.save(i + 1, state, force=True)  # async; overlaps next step
        if i == start_step:
            t0 = time.perf_counter()  # exclude compile
    if mgr is not None:
        mgr.wait_until_finished()
        mgr.close()
    dt = time.perf_counter() - t0
    n_timed = args.steps - start_step - 1
    imgs = batch * n_timed / dt if n_timed > 0 else 0
    print("devices=%d global-batch=%d  loss %.4f -> %.4f  %.1f img/s"
          % (n, batch, losses[0], losses[-1], imgs))
    if start_step == 0:
        assert np.mean(losses[-3:]) < losses[0], "loss did not decrease"
    print("DP TRAINING OK")


if __name__ == "__main__":
    main()
