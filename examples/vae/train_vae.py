"""Variational autoencoder (reference `example/vae/VAE_example.ipynb` —
MLP encoder/decoder VAE on MNIST; here synthetic 8x8 two-blob images).

Exercises the reparameterization trick through the framework's RNG plumbing
(``mx.nd.random_normal`` inside ``autograd.record``), a composite
ELBO loss (reconstruction + KL in one jitted backward), and generation by
decoding prior samples.

Run: ``./dev.sh python examples/vae/train_vae.py``
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def make_images(rng, n, size=8):
    """Two bright 2x2 blobs at random grid positions on a dark field."""
    X = np.zeros((n, size * size), np.float32)
    imgs = X.reshape(n, size, size)
    for i in range(n):
        for _ in range(2):
            r, c = rng.randint(0, size - 1, 2)
            imgs[i, r:r + 2, c:c + 2] = 1.0
    return X + 0.02 * rng.randn(n, size * size).astype(np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--latent", type=int, default=8)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.gluon import nn, Trainer, HybridBlock

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    X = make_images(rng, 4096)
    dim = X.shape[1]

    class VAE(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.enc = nn.Dense(args.hidden, activation="tanh")
                self.mu = nn.Dense(args.latent)
                self.logvar = nn.Dense(args.latent)
                self.dec1 = nn.Dense(args.hidden, activation="tanh")
                self.dec2 = nn.Dense(dim)

        def encode(self, x):
            h = self.enc(x)
            return self.mu(h), self.logvar(h)

        def decode(self, z):
            return self.dec2(self.dec1(z))

        def hybrid_forward(self, F, x):
            mu, logvar = self.encode(x)
            # reparameterization: z = mu + sigma * eps
            eps = F.random_normal(shape=mu.shape)
            z = mu + F.exp(0.5 * logvar) * eps
            return self.decode(z), mu, logvar

    net = VAE()
    net.initialize(mx.init.Xavier())
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": args.lr})

    def elbo_loss(recon, x, mu, logvar):
        # per-sample loss: Trainer.step(batch) applies the 1/batch rescale
        # (the repo-wide convention; see recommenders/cnn_text examples)
        rec = ((recon - x) ** 2).sum(axis=1)          # gaussian nll (unit var)
        kl = -0.5 * (1 + logvar - mu * mu - nd.exp(logvar)).sum(axis=1)
        return rec + 0.1 * kl

    n_batches = len(X) // args.batch
    first = last = None
    for epoch in range(args.epochs):
        perm = rng.permutation(len(X))
        tot = 0.0
        for b in range(n_batches):
            xb = nd.array(X[perm[b * args.batch:(b + 1) * args.batch]])
            with autograd.record():
                recon, mu, logvar = net(xb)
                loss = elbo_loss(recon, xb, mu, logvar)
            loss.backward()
            trainer.step(args.batch)
            tot += float(loss.mean().asnumpy())
        if first is None:
            first = tot / n_batches
        last = tot / n_batches
        if epoch % 5 == 0 or epoch == args.epochs - 1:
            print("epoch %d elbo-loss %.3f" % (epoch, last))
    assert last < first * 0.6, "VAE failed to learn (%.2f -> %.2f)" % (first, last)

    # generation: decode prior samples — output must be in data range
    z = nd.array(rng.randn(16, args.latent).astype(np.float32))
    samples = net.decode(z).asnumpy()
    assert samples.shape == (16, dim) and np.isfinite(samples).all()
    print("VAE OK (loss %.2f -> %.2f; generated %s samples)"
          % (first, last, samples.shape[0]))


if __name__ == "__main__":
    main()
