"""Stochastic-depth residual training — reference
``example/stochastic-depth/{sd_module.py,sd_cifar10.py}``.

The reference implements stochastic depth as a custom ``BaseModule``
subclass that coin-flips per forward whether to execute the compute branch
(sd_module.py StochasticDepthModule) and chains 100+ of them in a
SequentialModule, with a linearly-decaying death schedule
(sd_cifar10.py: death_rate ramps 0 → 0.5 with depth).

TPU-native redesign: a branch that vanishes at runtime is a dynamic graph —
hostile to XLA.  Instead the whole-batch survival gate IS a one-scalar
Dropout (axes=all ⇒ a single Bernoulli decision scaled by 1/(1−p)): the
graph stays static, the gate compiles into the fused step, and expectation
matches the reference's test-time (1−death_rate) scaling.  The schedule and
the residual topology mirror sd_cifar10.py.

Run: ./dev.sh python examples/stochastic-depth/sd_cifar10.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


class StochasticDepthBlock(gluon.HybridBlock):
    """Residual unit whose compute branch dies with ``death_rate`` per batch
    (one Bernoulli for the whole batch, as the reference's per-forward coin
    flip): out = skip(x) + SurvivalGate(branch(x))."""

    def __init__(self, channels, death_rate, downsample=False, **kw):
        super().__init__(**kw)
        self.death_rate = float(death_rate)
        stride = 2 if downsample else 1
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="body_")
            self.body.add(nn.Conv2D(channels, 3, stride, 1, use_bias=False),
                          nn.BatchNorm(), nn.Activation("relu"),
                          nn.Conv2D(channels, 3, 1, 1, use_bias=False),
                          nn.BatchNorm())
            self.sc = (nn.Conv2D(channels, 1, stride, use_bias=False)
                       if downsample else None)

    def hybrid_forward(self, F, x):
        branch = self.body(x)
        if self.death_rate >= 1.0:
            # fully dead: identity block (1/(1-p) scaling is degenerate)
            branch = F.zeros_like(branch)
        elif self.death_rate > 0:
            # axes over every dim -> shape-(1,1,1,1) Bernoulli: the whole
            # branch survives or dies together, pre-scaled by 1/(1-p) so
            # inference needs no rescale (same expectation as the
            # reference's test-time (1-death_rate) multiply)
            branch = F.Dropout(branch, p=self.death_rate, axes=(0, 1, 2, 3))
        skip = self.sc(x) if self.sc is not None else x
        return F.Activation(branch + skip, act_type="relu")


def build_net(classes=10, blocks_per_stage=(3, 3), channels=(16, 32),
              death_mode="linear_decay", death_rate=0.5):
    """Linear-decay death schedule over depth (sd_cifar10.py:120-133:
    block i of L dies with rate i/L * death_rate; 'uniform' uses the flat
    rate everywhere)."""
    net = nn.HybridSequential()
    total = sum(blocks_per_stage)
    i = 0
    with net.name_scope():
        net.add(nn.Conv2D(channels[0], 3, 1, 1, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"))
        for s, (nb, ch) in enumerate(zip(blocks_per_stage, channels)):
            for b in range(nb):
                rate = (death_rate * (i + 1) / total
                        if death_mode == "linear_decay" else death_rate)
                net.add(StochasticDepthBlock(ch, rate,
                                             downsample=(b == 0 and s > 0)))
                i += 1
        net.add(nn.GlobalAvgPool2D(), nn.Dense(classes))
    return net


def main(epochs=14, batch=64, lr=0.1, seed=0, death_rate=0.5):
    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split

    mx.random.seed(seed)
    np.random.seed(seed)
    X, y = load_digits(return_X_y=True)
    X = (X.astype(np.float32) / 16.0).reshape(-1, 1, 8, 8)
    Xtr, Xte, ytr, yte = train_test_split(X, y.astype(np.float32),
                                          test_size=0.25, random_state=seed,
                                          stratify=y)
    net = build_net(classes=10, death_rate=death_rate)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9, "wd": 1e-4})
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
    n = len(Xtr)
    for ep in range(epochs):
        perm = np.random.permutation(n)
        tot = 0.0
        for s in range(0, n - batch + 1, batch):
            idx = perm[s:s + batch]
            xb, yb = nd.array(Xtr[idx]), nd.array(ytr[idx])
            with autograd.record():
                loss = lossfn(net(xb), yb)
            loss.backward()
            trainer.step(batch)
            tot += float(loss.mean().asnumpy())
    preds = np.argmax(net(nd.array(Xte)).asnumpy(), axis=1)
    acc = float((preds == yte).mean())
    print("stochastic-depth: test acc %.4f (death_rate %.2f, linear decay)"
          % (acc, death_rate))
    return acc


if __name__ == "__main__":
    main()
