"""Module-API MLP walkthrough — reference ``example/module/mnist_mlp.py``.

Shows the low-level Module lifecycle the reference demonstrates instead of
``fit()``: bind → init_params → init_optimizer → per-batch
forward/update_metric/backward/update, then checkpoint save/load round-trip
(mnist_mlp.py's "intermediate-level" and "high-level" halves).

Run: ./dev.sh python examples/module/mnist_mlp.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def mlp_sym(classes=10):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=32)
    net = mx.sym.Activation(net, name="relu2", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc3", num_hidden=classes)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def synthetic_mnist(rng, n, classes=10, dim=64):
    centers = rng.randn(classes, dim) * 2.5
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, dim) * 0.7
    return x.astype(np.float32), y.astype(np.float32)


def main(epochs=10, batch=50, tmpdir="/tmp"):
    rng = np.random.RandomState(7)
    xs, ys = synthetic_mnist(rng, 1500)
    train = mx.io.NDArrayIter(xs[:1000], ys[:1000], batch, shuffle=True)
    val = mx.io.NDArrayIter(xs[1000:], ys[1000:], batch)

    # --- intermediate-level API (mnist_mlp.py:52-77) --------------------
    mod = mx.mod.Module(mlp_sym())
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    metric = mx.metric.create("acc")
    for epoch in range(epochs):
        train.reset()
        metric.reset()
        for batch_data in train:
            mod.forward(batch_data, is_train=True)
            mod.update_metric(metric, batch_data.label)
            mod.backward()
            mod.update()
        print("epoch %d, train %s=%.3f" % (epoch, *metric.get()))

    # --- checkpoint round-trip (mnist_mlp.py high-level half) -----------
    prefix = os.path.join(tmpdir, "module_mnist_mlp")
    mod.save_checkpoint(prefix, epochs)
    sym, args, auxs = mx.model.load_checkpoint(prefix, epochs)
    mod2 = mx.mod.Module(sym)
    mod2.bind(data_shapes=val.provide_data, for_training=False)
    mod2.set_params(args, auxs)
    metric.reset()
    mod2.score(val, metric)
    acc = metric.get()[1]
    print("restored-module val acc %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
