"""Custom Python loss via SequentialModule — reference
``example/module/python_loss.py``: a feature MLP Module chained with a
``PythonLossModule`` whose gradient is a hand-written numpy function
(multiclass hinge), wired together by ``SequentialModule.add(...,
take_labels=True, auto_wiring=True)``.

Run: ./dev.sh python examples/module/python_loss.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def mc_hinge_grad(scores, labels):
    """Crammer-Singer multiclass hinge subgradient (python_loss.py:25-41):
    push down the most-violating class, pull up the true class."""
    scores = scores.asnumpy()
    labels = labels.asnumpy().astype(int)
    n, _ = scores.shape
    grad = np.zeros_like(scores)
    for i in range(n):
        viol = 1.0 + scores[i] - scores[i, labels[i]]
        viol[labels[i]] = 0.0
        j = int(viol.argmax())
        if viol[j] > 0:
            grad[i, labels[i]] -= 1.0
            grad[i, j] += 1.0
    return mx.nd.array(grad / n)


def main(epochs=10, batch=64, classes=5, dim=24):
    rng = np.random.RandomState(3)
    centers = rng.randn(classes, dim) * 3
    y = rng.randint(0, classes, 1200)
    x = (centers[y] + rng.randn(1200, dim)).astype(np.float32)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=64)
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=classes)

    mlp = mx.mod.Module(net, label_names=())
    loss = mx.mod.PythonLossModule(grad_func=mc_hinge_grad)
    mod = mx.mod.SequentialModule().add(mlp).add(
        loss, take_labels=True, auto_wiring=True)

    it = mx.io.NDArrayIter(x, y.astype(np.float32), batch, shuffle=True)
    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9})

    # score by argmax over the feature module's raw scores
    it.reset()
    correct = total = 0
    for b in it:
        mod.forward(b, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
        lab = b.label[0].asnumpy().astype(int)
        correct += int((pred == lab).sum())
        total += lab.shape[0]
    acc = correct / total
    print("python hinge-loss module train acc %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
