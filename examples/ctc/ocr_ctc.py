"""CTC sequence recognition (reference `example/ctc/lstm_ocr.py`: LSTM over
captcha image columns trained with WarpCTC/contrib CTCLoss to emit digit
strings without frame alignments).

Synthetic "OCR" task: each digit renders as a run of noisy frames (variable
width, unaligned — exactly what CTC solves); a bi-LSTM reads the frame
sequence, per-frame logits over {blank} ∪ digits feed ``mx.nd.ctc_loss``,
and decoding is best-path (argmax + collapse-repeats + drop-blank).

Run: ``./dev.sh python examples/ctc/ocr_ctc.py``
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

VOCAB = 5          # digit classes 1..5 (0 is the CTC blank)
SEQ = 4            # digits per sample
FRAMES = 20        # total frames per sample
FDIM = 8           # frame feature dim


def render(rng, n):
    """Digits → unaligned frame runs: digit d emits 2-5 frames of its
    (noisy) one-hot-ish feature pattern."""
    X = np.zeros((n, FRAMES, FDIM), np.float32)
    Y = np.zeros((n, SEQ), np.float32)
    for i in range(n):
        digits = rng.randint(1, VOCAB + 1, SEQ)
        Y[i] = digits
        t = 0
        for d, w in zip(digits, rng.randint(2, 6, SEQ)):
            w = min(int(w), FRAMES - t)  # never run past the frame budget
            X[i, t:t + w, d - 1] = 1.0
            t += w
    X += 0.15 * rng.randn(n, FRAMES, FDIM).astype(np.float32)
    return X, Y


def best_path_decode(logits):
    """(T, N, C) → list of sequences: argmax, collapse repeats, drop blanks."""
    ids = logits.argmax(-1).T            # (N, T)
    out = []
    for row in ids:
        seq, prev = [], -1
        for t in row:
            if t != prev and t != 0:
                seq.append(int(t))
            prev = t
        out.append(seq)
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--batch", type=int, default=48)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--min-exact", type=float, default=0.8)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.gluon import nn, rnn, Trainer, HybridBlock

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    Xva, Yva = render(rng, 256)

    class OCRNet(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.lstm = rnn.LSTM(args.hidden, num_layers=1,
                                     bidirectional=True, layout="NTC")
                self.out = nn.Dense(VOCAB + 1, flatten=False)  # +blank

        def hybrid_forward(self, F, x):
            return self.out(self.lstm(x))     # (N, T, C)

    net = OCRNet()
    net.initialize(mx.init.Xavier())
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": args.lr})

    first = last = None
    for epoch in range(args.epochs):
        tot = 0.0
        for _ in range(20):
            xb, yb = render(rng, args.batch)
            x, y = nd.array(xb), nd.array(yb)
            with autograd.record():
                acts = net(x).transpose((1, 0, 2))   # (T, N, C) for CTC
                loss = nd.ctc_loss(acts, y)          # blank = id 0
            loss.backward()
            trainer.step(args.batch)
            tot += float(loss.mean().asnumpy())
        if first is None:
            first = tot / 20
        last = tot / 20
        decoded = best_path_decode(
            net(nd.array(Xva)).transpose((1, 0, 2)).asnumpy())
        exact = np.mean([d == list(map(int, t)) for d, t in zip(decoded, Yva)])
        print("epoch %d ctc-loss %.3f exact-match %.3f" % (epoch, last, exact), flush=True)
        if exact > max(0.95, args.min_exact):
            break
    # accuracy is the primary criterion; only demand a loss drop when the
    # run didn't already stop early on near-perfect decoding
    assert exact > args.min_exact, "sequence exact-match %.3f too low" % exact
    if exact <= 0.95:
        assert last < first * 0.5, \
            "CTC loss did not converge (%.2f -> %.2f)" % (first, last)
    print("CTC OCR OK")


if __name__ == "__main__":
    main()
