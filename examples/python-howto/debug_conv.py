"""Debugging a single conv with a Monitor — reference
``example/python-howto/debug_conv.py``: bind a one-op module, install a
monitor on its executor, and inspect every input/output tensor of the op.

Run: ./dev.sh python examples/python-howto/debug_conv.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx


class SimpleData:
    def __init__(self, data):
        self.data = data
        self.label = None
        self.pad = 0


def main():
    data_shape = (1, 3, 5, 5)
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), stride=(1, 1),
                              num_filter=1)
    mon = mx.monitor.Monitor(1, monitor_all=True)
    mod = mx.mod.Module(conv, label_names=())
    mod.bind(data_shapes=[("data", data_shape)])
    mod.init_params()
    mod.install_monitor(mon)

    mon.tic()
    mod.forward(SimpleData([mx.nd.ones(data_shape)]), is_train=False)
    res = mod.get_outputs()[0].asnumpy()
    entries = mon.toc()
    for _step, name, stat in entries:
        print("%-40s %s" % (name, stat))
    print("conv output:\n", res[0, 0])
    assert res.shape == (1, 1, 5, 5)
    return res


if __name__ == "__main__":
    main()
