"""Grouped multi-output symbols — reference
``example/python-howto/multiple_outputs.py``: tap an internal layer (fc1)
next to the loss head with ``mx.sym.Group`` and read both from one
executor forward.

Run: ./dev.sh python examples/python-howto/multiple_outputs.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def main():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    net = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=64)
    out = mx.sym.SoftmaxOutput(net, name="softmax")
    group = mx.sym.Group([fc1, out])
    print("group outputs:", group.list_outputs())

    exe = group.simple_bind(mx.cpu(), data=(4, 32),
                            grad_req="null")
    exe.arg_dict["data"][:] = np.random.RandomState(0).randn(4, 32)
    exe.forward(is_train=False)
    feats, probs = exe.outputs
    print("fc1 tap", feats.shape, "softmax", probs.shape,
          "rows sum to", float(probs.asnumpy().sum(1)[0]))
    return feats.shape, probs.shape


if __name__ == "__main__":
    main()
