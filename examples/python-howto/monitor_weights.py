"""Weight/activation monitoring during training — reference
``example/python-howto/monitor_weights.py``: install a ``Monitor`` with a
norm statistic on a Module and print per-batch tensor stats.

Run: ./dev.sh python examples/python-howto/monitor_weights.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def norm_stat(d):
    """RMS norm, the reference's statistic (monitor_weights.py:36-37);
    the monitor hands the tensor over as numpy."""
    return np.linalg.norm(d) / np.sqrt(d.size)


def main(batches=6):
    rng = np.random.RandomState(0)
    x = rng.randn(256, 20).astype(np.float32)
    y = (x[:, :10].sum(1) > 0).astype(np.float32)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    net = mx.sym.Activation(net, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=2)
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    mon = mx.monitor.Monitor(interval=2, stat_func=norm_stat,
                             pattern=".*weight", monitor_all=True)
    mod = mx.mod.Module(net)
    it = mx.io.NDArrayIter(x, y, 64)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})
    mod.install_monitor(mon)

    seen = []
    for i, b in enumerate(it):
        if i >= batches:
            break
        mon.tic()
        mod.forward(b, is_train=True)
        mod.backward()
        mod.update()
        for step, name, stat in mon.toc():
            seen.append(name)
            print("batch %d  %-24s %s" % (step, name, stat))
    assert any("weight" in n for n in seen)
    return seen


if __name__ == "__main__":
    main()
