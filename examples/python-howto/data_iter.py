"""RecordIO image iterator walkthrough — reference
``example/python-howto/data_iter.py``: build an ``ImageRecordIter`` over a
.rec pack with augmentation + background-threaded decode.  Since no CIFAR
pack can be fetched offline, this first WRITES a tiny synthetic .rec with
the repo's recordio/im2rec machinery, then iterates it the reference way.

Run: ./dev.sh python examples/python-howto/data_iter.py
"""
from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def write_synthetic_rec(path, n=48, size=36):
    """Pack n random JPEG-encoded images + labels into a .rec."""
    import io as _io

    from PIL import Image

    rec = mx.recordio.MXIndexedRecordIO(path + ".idx", path, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG", quality=90)
        header = mx.recordio.IRHeader(0, float(i % 10), i, 0)
        rec.write_idx(i, mx.recordio.pack(header, buf.getvalue()))
    rec.close()


def main():
    with tempfile.TemporaryDirectory() as td:
        rec_path = os.path.join(td, "synthetic.rec")
        write_synthetic_rec(rec_path)
        dataiter = mx.io.ImageRecordIter(
            path_imgrec=rec_path,
            data_shape=(3, 28, 28),   # random-crop target
            batch_size=16,
            rand_crop=True,
            rand_mirror=True,
            shuffle=True,
            preprocess_threads=2,
        )
        total = 0
        for batch in dataiter:
            assert batch.data[0].shape == (16, 3, 28, 28)
            total += batch.data[0].shape[0]
        print("iterated %d augmented images from the .rec" % total)
        return total


if __name__ == "__main__":
    main()
