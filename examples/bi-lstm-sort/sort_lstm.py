"""Bidirectional-LSTM sequence sorting (reference `example/bi-lstm-sort/`:
train a bi-LSTM to emit the sorted version of a digit sequence).

The bi-LSTM sees the whole sequence (forward+backward passes fused into one
lax.scan pair inside a single jitted step); a per-position classifier emits
the sorted tokens.  Same task as the reference, synthetic data generated
in-process.

Run: ``./dev.sh python examples/bi-lstm-sort/sort_lstm.py``
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=10)
    p.add_argument("--seq-len", type=int, default=8)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.gluon import nn, rnn, Trainer, HybridBlock
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

    mx.random.seed(0)
    rng = np.random.RandomState(0)

    def batch_data(n):
        x = rng.randint(0, args.vocab, (n, args.seq_len))
        y = np.sort(x, axis=1)
        return x.astype(np.float32), y.astype(np.float32)

    class SortNet(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(args.vocab, 16)
                self.lstm = rnn.LSTM(args.hidden, num_layers=1,
                                     bidirectional=True, layout="NTC")
                self.out = nn.Dense(args.vocab, flatten=False)

        def hybrid_forward(self, F, x):
            h = self.lstm(self.embed(x))       # (B, T, 2H)
            return self.out(h)                 # (B, T, V) logits

    net = SortNet()
    net.initialize(mx.init.Xavier())
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": args.lr})
    loss_fn = SoftmaxCrossEntropyLoss(axis=-1)

    Xva, Yva = batch_data(256)
    acc = 0.0
    for epoch in range(args.epochs):
        tot = 0.0
        for _ in range(40):
            xb, yb = batch_data(args.batch)
            x, y = nd.array(xb), nd.array(yb)
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(args.batch)
            tot += float(loss.mean().asnumpy())
        pred = net(nd.array(Xva)).asnumpy().argmax(-1)
        acc = (pred == Yva).mean()
        print("epoch %d loss %.4f token-acc %.3f" % (epoch, tot / 40, acc))
        if acc > 0.97:
            break
    assert acc > 0.9, "bi-LSTM sort failed to learn (token-acc %.3f)" % acc
    print("BI-LSTM SORT OK")


if __name__ == "__main__":
    main()
