"""VAE-GAN (adversarial variational autoencoder) — reference
``example/mxnet_adversarial_vae/vaegan_mxnet.py`` (Larsen et al. 2016).

The reference trains three modules adversarially: a conv **encoder**
(image → mu, log_var), a deconv **generator** (z → image), and a split
**discriminator** whose layer-ℓ features define the reconstruction metric
(``DiscriminatorLayerLoss``, vaegan_mxnet.py:173) — "learned similarity"
instead of pixel MSE — plus the usual GAN logistic loss and the KL prior
(``KLDivergenceLoss`` :185).  The reference wires them as three Modules
with manual forward/backward choreography; here each is a gluon Block,
the choreography is three ``autograd.record`` scopes per batch, and every
loss is a differentiable expression (no hand-written backward).

Offline data: 32×32 two-ellipse "faces" whose geometry is latent.

Run: ./dev.sh python examples/adversarial_vae/vaegan.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

Z_DIM = 16


class Encoder(gluon.HybridBlock):
    """32x32 image → (mu, log_var) (reference encoder(), nef conv stack)."""

    def __init__(self, nef=16, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.body = nn.HybridSequential()
            for i, ch in enumerate((nef, nef * 2, nef * 4)):
                self.body.add(nn.Conv2D(ch, 4, 2, 1, use_bias=False),
                              nn.BatchNorm(),
                              nn.LeakyReLU(0.2))
            self.body.add(nn.Flatten())
            self.mu = nn.Dense(Z_DIM)
            self.log_var = nn.Dense(Z_DIM)

    def hybrid_forward(self, F, x):
        h = self.body(x)
        return self.mu(h), self.log_var(h)


class Generator(gluon.HybridBlock):
    """z → 32x32 image via Deconvolution stack (reference generator())."""

    def __init__(self, ngf=16, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.body = nn.HybridSequential()
            self.body.add(nn.Dense(ngf * 4 * 4 * 4))
            self.deconvs = nn.HybridSequential()
            for ch in (ngf * 2, ngf):
                self.deconvs.add(
                    nn.Conv2DTranspose(ch, 4, 2, 1, use_bias=False),
                    nn.BatchNorm(), nn.Activation("relu"))
            self.out = nn.Conv2DTranspose(1, 4, 2, 1)

    def hybrid_forward(self, F, z):
        h = F.reshape(self.body(z), (0, -1, 4, 4))
        return F.sigmoid(self.out(self.deconvs(h)))


class Discriminator(gluon.HybridBlock):
    """Split discriminator: ``features`` is the layer-ℓ map used as the
    learned reconstruction metric (reference discriminator1/2 split)."""

    def __init__(self, ndf=16, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.d1 = nn.HybridSequential()
            self.d1.add(nn.Conv2D(ndf, 4, 2, 1), nn.LeakyReLU(0.2),
                        nn.Conv2D(ndf * 2, 4, 2, 1), nn.LeakyReLU(0.2))
            self.d2 = nn.HybridSequential()
            self.d2.add(nn.Conv2D(ndf * 4, 4, 2, 1), nn.LeakyReLU(0.2),
                        nn.Flatten(), nn.Dense(1))

    def features(self, x):
        return self.d1(x)

    def hybrid_forward(self, F, x):
        return self.d2(self.d1(x))


def make_faces(rng, n, size=32):
    """Two-ellipse images with latent geometry (offline celeb stand-in)."""
    xs = np.zeros((n, 1, size, size), np.float32)
    yy, xx = np.mgrid[:size, :size]
    for i in range(n):
        cy, cx = size / 2 + rng.randn(2) * 2
        a, b = rng.uniform(6, 11), rng.uniform(4, 8)
        face = (((yy - cy) / a) ** 2 + ((xx - cx) / b) ** 2) < 1
        eye = (((yy - cy + 3) / 1.5) ** 2
               + ((xx - cx - b / 2) / 1.2) ** 2) < 1
        eye2 = (((yy - cy + 3) / 1.5) ** 2
                + ((xx - cx + b / 2) / 1.2) ** 2) < 1
        xs[i, 0] = np.clip(face * 0.8 - eye * 0.6 - eye2 * 0.6
                           + rng.rand(size, size) * 0.05, 0, 1)
    return xs


def kl_loss(mu, log_var):
    """KLDivergenceLoss (vaegan_mxnet.py:185-193)."""
    return (-0.5 * (1 + log_var - mu * mu - log_var.exp())).sum(axis=1).mean()


def main(epochs=6, batch=32, n=512, seed=0):
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    xs = make_faces(rng, n)

    enc, gen, dis = Encoder(), Generator(), Discriminator()
    for b in (enc, gen, dis):
        b.initialize(mx.init.Normal(0.02))
    t_enc = gluon.Trainer(enc.collect_params(), "adam",
                          {"learning_rate": 1e-3, "beta1": 0.5})
    t_gen = gluon.Trainer(gen.collect_params(), "adam",
                          {"learning_rate": 1e-3, "beta1": 0.5})
    t_dis = gluon.Trainer(dis.collect_params(), "adam",
                          {"learning_rate": 5e-4, "beta1": 0.5})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    for epoch in range(epochs):
        perm = rng.permutation(n)
        stats = np.zeros(3)
        for s in range(0, n, batch):
            x = nd.array(xs[perm[s:s + batch]])
            B = x.shape[0]
            ones = nd.ones((B, 1))
            zeros = nd.zeros((B, 1))
            zp = nd.array(rng.randn(B, Z_DIM).astype(np.float32))

            # --- discriminator: real vs reconstruction vs prior sample ---
            mu, log_var = enc(x)
            eps = nd.array(rng.randn(B, Z_DIM).astype(np.float32))
            z = mu + (0.5 * log_var).exp() * eps
            with autograd.record():
                l_d = (bce(dis(x), ones)
                       + bce(dis(gen(z.detach())), zeros)
                       + bce(dis(gen(zp)), zeros)).mean()
            l_d.backward()
            t_dis.step(B)

            # --- encoder: KL + feature-space reconstruction --------------
            with autograd.record():
                mu, log_var = enc(x)
                eps2 = nd.array(rng.randn(B, Z_DIM).astype(np.float32))
                z = mu + (0.5 * log_var).exp() * eps2
                rec = gen(z)
                l_feat = ((dis.features(rec) - dis.features(x).detach())
                          ** 2).mean()
                l_e = kl_loss(mu, log_var) * 0.01 + l_feat
            l_e.backward()
            t_enc.step(B)

            # --- generator: fool the discriminator + match features ------
            with autograd.record():
                rec = gen(z.detach())
                fake = gen(zp)
                l_g = (bce(dis(rec), ones) + bce(dis(fake), ones)).mean() \
                    + ((dis.features(rec) - dis.features(x).detach())
                       ** 2).mean()
            l_g.backward()
            t_gen.step(B)
            stats += [float(l_d.asnumpy()), float(l_e.asnumpy()),
                      float(l_g.asnumpy())]
        k = n // batch
        print("epoch %d  D %.3f  E %.3f  G %.3f"
              % (epoch, *(stats / k)))

    # reconstruction quality in pixel space (not the training metric, but
    # an interpretable sanity check)
    mu, _ = enc(nd.array(xs[:64]))
    rec = gen(mu).asnumpy()
    mse = float(((rec - xs[:64]) ** 2).mean())
    base = float(((xs[:64].mean((0, 2, 3), keepdims=True) - xs[:64]) ** 2).mean())
    print("recon mse %.4f vs mean-image baseline %.4f" % (mse, base))
    return mse, base


if __name__ == "__main__":
    main()
