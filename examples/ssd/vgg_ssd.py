"""SSD-300 / SSD-512 with the VGG16-reduced backbone — reference
``example/ssd/symbol/{symbol_builder.py,vgg16_reduced.py}``.

The real architecture at real resolution (VERDICT round-2 weak item 7: the
repo's ``ssd.py`` toy ran at 64×64): conv1–conv5 VGG stages, dilated
fc6/fc7 convs, extra feature stages down to 1×1, per-scale cls/box heads
with the reference's anchor menu (8732 anchors at 300², 24564 at 512²).

TPU-first: anchors depend only on static feature shapes, so they are
precomputed fp32 constants OUTSIDE the traced step (a bf16 trunk must
never quantize box coordinates — same rule as the R-FCN path); the train
step (targets + losses + SGD) and the detection step (softmax + decode +
blocked NMS) each compile to ONE XLA module (train_fused.py).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

from mxnet_tpu.gluon import HybridBlock, nn

# reference example/ssd/symbol/symbol_factory.py get_config('vgg16_reduced')
SSD300 = dict(
    sizes=[[.1, .141], [.2, .272], [.37, .447], [.54, .619],
           [.71, .79], [.88, .961]],
    ratios=[[1, 2, .5], [1, 2, .5, 3, 1. / 3], [1, 2, .5, 3, 1. / 3],
            [1, 2, .5, 3, 1. / 3], [1, 2, .5], [1, 2, .5]],
    extra=((256, 512), (128, 256)), tail=2)
SSD512 = dict(
    sizes=[[.07, .1025], [.15, .2121], [.3, .3674], [.45, .5196],
           [.6, .6708], [.75, .8216], [.9, .9721]],
    ratios=[[1, 2, .5], [1, 2, .5, 3, 1. / 3], [1, 2, .5, 3, 1. / 3],
            [1, 2, .5, 3, 1. / 3], [1, 2, .5, 3, 1. / 3], [1, 2, .5],
            [1, 2, .5]],
    # 512: all five extra stages are stride-2 pad-1 convs (64→32 happened at
    # pool4): sources 64, 32, 16, 8, 4, 2, 1 — valid-conv tails would hit
    # 0×0 (the reference's 512 config also keeps stride-2 stages here)
    extra=((256, 512), (128, 256), (128, 256), (128, 256), (128, 256)),
    tail=0)


def _vgg_stage(n, ch, pool=True, ceil=False):
    blk = nn.HybridSequential()
    for _ in range(n):
        blk.add(nn.Conv2D(ch, 3, padding=1, activation="relu"))
    if pool:
        blk.add(nn.MaxPool2D(2, 2, ceil_mode=ceil))
    return blk


class VGGSSD(HybridBlock):
    """VGG16-reduced SSD; ``config`` is SSD300 or SSD512.

    ``width`` scales every trunk/extra channel count (heads keep their
    anchor-determined output channels).  Feature-map shapes — and therefore
    the anchor menu (8732 @300², 24564 @512²) — are width-independent, so
    ``width<1`` gives a CPU-affordable model whose MultiBoxTarget/Detection
    shapes are EXACTLY the real ones (the quality gate's point)."""

    def __init__(self, num_classes, config, width=1.0, **kw):
        super().__init__(**kw)
        self.num_classes = num_classes
        self.cfg = config
        self.anchors_per = [len(s) + len(r) - 1
                            for s, r in zip(config["sizes"], config["ratios"])]

        def W(c):
            return max(8, int(round(c * width)))

        with self.name_scope():
            self.conv1 = _vgg_stage(2, W(64))
            self.conv2 = _vgg_stage(2, W(128))
            self.conv3 = _vgg_stage(3, W(256), ceil=True)  # 75 -> 38 (ceil)
            self.conv4 = _vgg_stage(3, W(512), pool=False)  # source 0 (38x38)
            self.pool4 = nn.MaxPool2D(2, 2)
            self.conv5 = _vgg_stage(3, W(512), pool=False)
            self.pool5 = nn.MaxPool2D(3, 1, 1)           # stride-1 (reference)
            self.fc6 = nn.Conv2D(W(1024), 3, padding=6, dilation=6,
                                 activation="relu")      # atrous fc6
            self.fc7 = nn.Conv2D(W(1024), 1, activation="relu")  # source 1
            self.extras = nn.HybridSequential(prefix="extra_")
            for (c1, c2) in config["extra"]:
                blk = nn.HybridSequential()
                blk.add(nn.Conv2D(W(c1), 1, activation="relu"),
                        nn.Conv2D(W(c2), 3, strides=2, padding=1,
                                  activation="relu"))
                self.extras.add(blk)
            self.tails = nn.HybridSequential(prefix="tail_")
            for _ in range(config["tail"]):
                blk = nn.HybridSequential()
                blk.add(nn.Conv2D(W(128), 1, activation="relu"),
                        nn.Conv2D(W(256), 3, activation="relu"))  # valid conv
                self.tails.add(blk)
            self.cls_heads = nn.HybridSequential(prefix="cls_")
            self.box_heads = nn.HybridSequential(prefix="box_")
            for a in self.anchors_per:
                self.cls_heads.add(nn.Conv2D(a * (num_classes + 1), 3, padding=1))
                self.box_heads.add(nn.Conv2D(a * 4, 3, padding=1))

    def _sources(self, x):
        x = self.conv3(self.conv2(self.conv1(x)))
        s0 = self.conv4(x)
        x = self.fc7(self.fc6(self.pool5(self.conv5(self.pool4(s0)))))
        sources = [s0, x]
        for blk in self.extras:
            x = blk(x)
            sources.append(x)
        for blk in self.tails:
            x = blk(x)
            sources.append(x)
        return sources

    def hybrid_forward(self, F, x):
        sources = self._sources(x)
        cls_outs, box_outs = [], []
        for i, s in enumerate(sources):
            c = self.cls_heads[i](s)
            b = self.box_heads[i](s)
            cls_outs.append(F.flatten(F.transpose(c, axes=(0, 2, 3, 1))))
            box_outs.append(F.flatten(F.transpose(b, axes=(0, 2, 3, 1))))
        cls_preds = F.Reshape(F.Concat(*cls_outs, dim=1),
                              shape=(0, -1, self.num_classes + 1))
        box_preds = F.Concat(*box_outs, dim=1)  # (B, A_total*4)
        return cls_preds, box_preds

    def feature_shapes(self, image_size):
        """Static per-source (H, W) — drives anchor precomputation."""
        s = image_size
        s //= 2; s //= 2                    # conv1, conv2
        s = -(-s // 2)                      # conv3 ceil pool
        shapes = [s]                        # conv4 source
        s //= 2                             # pool4 (pool5/fc6 keep size)
        shapes.append(s)
        for _ in self.cfg["extra"]:
            s = -(-s // 2)                  # stride-2 pad-1
            shapes.append(s)
        for _ in range(self.cfg["tail"]):
            s = s - 2                       # 3x3 valid conv
            shapes.append(s)
        return [(h, h) for h in shapes]

    def make_anchors(self, image_size):
        """fp32 anchor constant (A_total, 4), reference MultiBoxPrior menu."""
        import jax.numpy as jnp

        from mxnet_tpu.ops.detection import multibox_prior

        parts = []
        for (h, w), sizes, ratios in zip(self.feature_shapes(image_size),
                                         self.cfg["sizes"], self.cfg["ratios"]):
            dummy = jnp.zeros((1, 1, h, w), jnp.float32)
            parts.append(np.asarray(
                multibox_prior(dummy, sizes=tuple(sizes),
                               ratios=tuple(ratios)))[0])
        return np.concatenate(parts, axis=0).astype(np.float32)
