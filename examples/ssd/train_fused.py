"""SSD-300/512 fused train + inference benches — reference
``example/ssd/{train.py,benchmark_score.py}`` (published bar: VGG16 SSD
300² at 95 FPS, batch 16, TITAN X — ``example/ssd/README.md:44-50``).

One XLA module per direction, exactly like the R-FCN north star:
- train step: VGG16-reduced forward, on-device MultiBoxTarget (bipartite
  match + negative mining), CE + smooth-L1, momentum SGD, donated state;
- score step: forward + softmax + MultiBoxDetection (decode + per-class
  blocked NMS over all 8732/24564 anchors).

Usage:
  ./dev.sh python examples/ssd/train_fused.py                 # CPU smoke
  python examples/ssd/train_fused.py --size 300 --bench       # chip bench
  python examples/ssd/train_fused.py --size 512 --bench
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.gluon.functional import functionalize
from vgg_ssd import SSD300, SSD512, VGGSSD


def synthetic_voc(rng, batch, size, classes, max_gts=8):
    """Bright rectangles on noise; labels (B, G, 5) [cls, x1..y2] in [0,1]
    corner format (MultiBoxTarget's convention), -1-padded."""
    data = (rng.rand(batch, 3, size, size) * 0.2).astype(np.float32)
    gt = np.full((batch, max_gts, 5), -1.0, np.float32)
    for b in range(batch):
        for j in range(rng.randint(1, 5)):
            cls = rng.randint(0, classes)
            w, h = rng.uniform(0.1, 0.5, 2)
            x1, y1 = rng.uniform(0, 1 - w), rng.uniform(0, 1 - h)
            gt[b, j] = [cls, x1, y1, x1 + w, y1 + h]
            px = (np.array([x1, y1, x1 + w, y1 + h]) * size).astype(int)
            data[b, cls % 3, px[1]:px[3], px[0]:px[2]] += 0.8
    return data, gt


def make_ssd_train_step(net, anchors, batch, learning_rate=1e-3,
                        momentum=0.9, compute_dtype=None):
    """→ (step, state): one-XLA-module SSD train step; state donate-able."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.detection import multibox_target
    from mxnet_tpu.ops.elemwise import smooth_l1

    apply, names, vals, aux_names = functionalize(net, train=True)
    aux_set = set(aux_names)
    learn_idx = [i for i, n in enumerate(names) if n not in aux_set]
    aux_idx = [i for i, n in enumerate(names) if n in aux_set]
    cdtype = jnp.dtype(compute_dtype) if compute_dtype is not None else None
    anc = jnp.asarray(anchors)[None]  # (1, A, 4) fp32 — never downcast

    def loss_fn(learn, aux, data, gt, key):
        merged = [None] * len(names)
        for i, v in zip(learn_idx, learn):
            merged[i] = v.astype(cdtype) if cdtype is not None else v
        for i, v in zip(aux_idx, aux):
            merged[i] = v
        x = data.astype(cdtype) if cdtype is not None else data
        (cls_preds, box_preds), new_aux = apply(merged, (x,), key)
        cls_preds = cls_preds.astype(jnp.float32)
        box_preds = box_preds.astype(jnp.float32)
        # on-device targets (reference MultiBoxTarget semantics: bipartite
        # match + 0.5 IoU, 3:1 negative mining); cls_preds (B, C+1, A)
        bt, bm, ct = multibox_target(
            anc, gt, cls_preds.transpose(0, 2, 1),
            negative_mining_ratio=3.0)
        valid = (ct >= 0).astype(jnp.float32)
        logp = jax.nn.log_softmax(cls_preds, axis=-1)
        ce = -jnp.take_along_axis(
            logp, jnp.maximum(ct, 0).astype(jnp.int32)[..., None], axis=-1
        )[..., 0] * valid
        npos = jnp.maximum(bm.reshape(bm.shape[0], -1, 4)[..., 0].sum(), 1.0)
        cls_loss = ce.sum() / npos
        loc_loss = smooth_l1((box_preds - bt) * bm, scalar=1.0).sum() / npos
        return cls_loss + loc_loss, (new_aux, jnp.stack([cls_loss, loc_loss]))

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step(state, data, gt, key, lr=learning_rate):
        learn, mom, aux = state
        (loss, (new_aux, parts)), grads = grad_fn(learn, aux, data, gt, key)
        mom = [momentum * m + g for m, g in zip(mom, grads)]
        learn = [p - lr * m for p, m in zip(learn, mom)]
        return (learn, mom, new_aux), loss, parts

    learn_vals = [vals[i] for i in learn_idx]
    aux_vals = [vals[i] for i in aux_idx]
    import jax.numpy as jnp2
    mom_vals = [jnp2.zeros_like(v) for v in learn_vals]
    return step, (learn_vals, mom_vals, aux_vals)


def make_score_step(net, anchors, compute_dtype=None):
    """→ score(params, x): forward + decode + NMS, one XLA module
    (reference benchmark_score.py measures exactly this)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.detection import multibox_detection

    apply, names, vals, _aux = functionalize(net, train=False)
    cdtype = jnp.dtype(compute_dtype) if compute_dtype is not None else None
    anc = jnp.asarray(anchors)[None]

    def score(pvals, x, key):
        if cdtype is not None:
            pvals = [v.astype(cdtype) if jnp.issubdtype(v.dtype, jnp.floating)
                     else v for v in pvals]
            x = x.astype(cdtype)
        (cls_preds, box_preds), _ = apply(pvals, (x,), key)
        cls_prob = jax.nn.softmax(cls_preds.astype(jnp.float32), axis=-1)
        return multibox_detection(
            cls_prob.transpose(0, 2, 1), box_preds.astype(jnp.float32), anc,
            nms_threshold=0.45, nms_topk=400)

    return score, vals


def run_bench(size=300, classes=20, train_batch=8, score_batch=16, iters=10,
              windows=3, dtype=None, verbose=True):
    import jax

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    cfg = SSD300 if size == 300 else SSD512
    net = VGGSSD(classes, cfg)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, 3, size, size)))  # materialize params
    anchors = net.make_anchors(size)
    if verbose:
        print("ssd%d: %d anchors, %d params" % (
            size, len(anchors),
            sum(int(np.prod(p.shape)) for p in
                net.collect_params().values() for p in [p.data()])))

    results = {}
    # -- train step ------------------------------------------------------
    step, state = make_ssd_train_step(net, anchors, train_batch,
                                      compute_dtype=dtype)
    jstep = jax.jit(step, donate_argnums=(0,))
    data, gt = synthetic_voc(rng, train_batch, size, classes)
    d, g = jax.device_put(data), jax.device_put(gt)
    key = jax.random.PRNGKey(0)
    t0 = time.time()
    state, loss, parts = jstep(state, d, g, key)
    jax.block_until_ready(loss)
    if verbose:
        print("train compile+first: %.1fs loss=%.3f" % (time.time() - t0, float(loss)))
    best = None
    for w in range(windows):
        keys = [jax.random.fold_in(key, w * 100 + i) for i in range(iters)]
        jax.block_until_ready(keys[-1])
        t0 = time.perf_counter()
        for i in range(iters):
            state, loss, parts = jstep(state, d, g, keys[i])
        float(loss)
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    results["train"] = (train_batch / best, best * 1e3, float(loss))

    # -- score (inference+NMS) step — the reference's 95-FPS metric ------
    score, _fresh = make_score_step(net, anchors, compute_dtype=dtype)
    jscore = jax.jit(score)
    svals = [jax.device_put(v) for v in _merge_vals(net, state)]
    xs = jax.device_put(synthetic_voc(rng, score_batch, size, classes)[0])
    out = jscore(svals, xs, key)
    float(out[0, 0, 0])  # scalar sync (block_until_ready is unreliable
    # over the tunnel — docs/PERF_NOTES.md measurement note)
    bests = None
    for w in range(windows):
        t0 = time.perf_counter()
        for i in range(iters):
            out = jscore(svals, xs, key)
        float(out[0, 0, 0])
        dt = (time.perf_counter() - t0) / iters
        bests = dt if bests is None else min(bests, dt)
    results["score"] = (score_batch / bests, bests * 1e3)
    return results


def _merge_vals(net, state):
    """Reassemble functionalize's value list (learnables + aux running
    stats) from a trained train-step state."""
    from mxnet_tpu.gluon.functional import functionalize, merge_params

    _apply, names, _vals, aux_names = functionalize(net, train=True)
    learn, _mom, aux = state
    return merge_params(names, aux_names, learn, aux)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--size", type=int, default=300, choices=(300, 512))
    p.add_argument("--classes", type=int, default=20)
    p.add_argument("--train-batch", type=int, default=None)
    p.add_argument("--score-batch", type=int, default=16)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--bench", action="store_true")
    p.add_argument("--steps", type=int, default=8)
    args = p.parse_args()

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    dtype = "bfloat16" if on_tpu else None

    if args.bench:
        tb = args.train_batch or (8 if args.size == 300 else 4)
        r = run_bench(size=args.size, classes=args.classes, train_batch=tb,
                      score_batch=args.score_batch, iters=args.iters,
                      dtype=dtype)
        print("ssd%d_bench: train %.1f img/s (%.0f ms/step, batch %d) | "
              "score+nms %.1f img/s (%.0f ms, batch %d) vs reference bar "
              "95 FPS @300^2"
              % (args.size, r["train"][0], r["train"][1], tb,
                 r["score"][0], r["score"][1], args.score_batch))
        return

    # CPU smoke: tiny size but the REAL graph; loss must decrease
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    size, classes, batch = 128, 3, 2
    cfg = dict(SSD300, tail=0,
               sizes=SSD300["sizes"][:4], ratios=SSD300["ratios"][:4])
    net = VGGSSD(classes, cfg)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, 3, size, size)))
    anchors = net.make_anchors(size)
    step, state = make_ssd_train_step(net, anchors, batch, learning_rate=5e-3)
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    first = last = None
    for s in range(args.steps):
        data, gt = synthetic_voc(rng, batch, size, classes)
        state, loss, parts = jstep(state, data, gt, jax.random.fold_in(key, s))
        l = float(loss)
        print("step %d loss=%.4f (cls %.3f loc %.3f)"
              % (s, l, *[float(x) for x in np.asarray(parts)]))
        first = first if first is not None else l
        last = l
    assert np.isfinite(last) and last < first, (first, last)
    print("SSD FUSED TRAIN OK")


if __name__ == "__main__":
    main()
