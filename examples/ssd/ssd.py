"""SSD detector — reference ``example/ssd/`` (symbol/symbol_builder.py,
symbol/common.py multibox layers) rebuilt as a gluon HybridBlock.

TPU-first notes: the whole forward — backbone, heads, anchor generation —
is one jit-compiled graph of static shapes; MultiBoxTarget/Detection are the
registry ops (mxnet_tpu/ops/detection.py) whose NMS/matching are masked
fixed-capacity computations, not dynamic host loops.
"""
from __future__ import annotations

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn, HybridBlock, loss as gloss


def _conv_block(channels):
    """conv-bn-relu x2 (reference symbol/common.py conv_act_layer)."""
    blk = nn.HybridSequential()
    for _ in range(2):
        blk.add(
            nn.Conv2D(channels, kernel_size=3, padding=1),
            nn.BatchNorm(),
            nn.Activation("relu"),
        )
    return blk


def _down_sample(channels):
    blk = _conv_block(channels)
    blk.add(nn.MaxPool2D(pool_size=2, strides=2))
    return blk


def _cls_predictor(num_anchors, num_classes):
    return nn.Conv2D(num_anchors * (num_classes + 1), kernel_size=3, padding=1)


def _box_predictor(num_anchors):
    return nn.Conv2D(num_anchors * 4, kernel_size=3, padding=1)


class SSD(HybridBlock):
    """Multi-scale single-shot detector.

    Parameters mirror the reference SSD example: per-scale anchor ``sizes``
    and ``ratios``; ``num_classes`` excludes background.
    """

    def __init__(
        self,
        num_classes,
        base_channels=(16, 32, 64),
        scale_channels=64,
        num_scales=4,
        sizes=None,
        ratios=None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.num_scales = num_scales
        if sizes is None:
            s = np.linspace(0.2, 0.9, num_scales + 1)
            sizes = [[float(s[i]), float(np.sqrt(s[i] * s[i + 1]))] for i in range(num_scales)]
        if ratios is None:
            ratios = [[1.0, 2.0, 0.5]] * num_scales
        self.sizes = sizes
        self.ratios = ratios
        self.num_anchors = len(sizes[0]) + len(ratios[0]) - 1
        with self.name_scope():
            self.base = nn.HybridSequential()
            for ch in base_channels:
                self.base.add(_down_sample(ch))
            self.stages = []
            self.cls_preds = []
            self.box_preds = []
            for i in range(num_scales):
                stage = _down_sample(scale_channels) if i > 0 else _conv_block(scale_channels)
                cls = _cls_predictor(self.num_anchors, num_classes)
                box = _box_predictor(self.num_anchors)
                setattr(self, "stage%d" % i, stage)
                setattr(self, "cls%d" % i, cls)
                setattr(self, "box%d" % i, box)
                self.stages.append(stage)
                self.cls_preds.append(cls)
                self.box_preds.append(box)

    def hybrid_forward(self, F, x):
        x = self.base(x)
        anchors, cls_outs, box_outs = [], [], []
        for i in range(self.num_scales):
            x = self.stages[i](x)
            anchors.append(
                F.contrib.MultiBoxPrior(x, sizes=self.sizes[i], ratios=self.ratios[i])
            )
            c = self.cls_preds[i](x)  # (B, A*(C+1), H, W)
            b = self.box_preds[i](x)  # (B, A*4, H, W)
            cls_outs.append(F.flatten(F.transpose(c, axes=(0, 2, 3, 1))))
            box_outs.append(F.flatten(F.transpose(b, axes=(0, 2, 3, 1))))
        anchors = F.concat(*anchors, dim=1)  # (1, A_total, 4)
        cls_preds = F.reshape(
            F.concat(*cls_outs, dim=1), shape=(0, -1, self.num_classes + 1)
        )  # (B, A, C+1)
        box_preds = F.concat(*box_outs, dim=1)  # (B, A*4)
        return anchors, cls_preds, box_preds


def training_targets(anchors, cls_preds, labels, negative_mining_ratio=3.0):
    """MultiBoxTarget wrapper: anchors (1,A,4), cls_preds (B,A,C+1),
    labels (B,N,5) -> (box_target, box_mask, cls_target)."""
    cls_preds_t = nd.transpose(cls_preds, axes=(0, 2, 1))  # (B, C+1, A)
    return nd.contrib.MultiBoxTarget(
        anchors, labels, cls_preds_t, negative_mining_ratio=negative_mining_ratio
    )


class SSDLoss:
    """cls CE (ignoring -1) + smooth-L1 on matched boxes (reference
    example/ssd training loss: MultiBoxTarget + SoftmaxOutput/SmoothL1)."""

    def __init__(self):
        self._ce = gloss.SoftmaxCrossEntropyLoss()
        self._l1 = gloss.HuberLoss()

    def __call__(self, cls_preds, box_preds, cls_target, box_target, box_mask):
        valid = cls_target >= 0  # ignore_label rows contribute nothing
        ce = self._ce(
            nd.reshape(cls_preds, shape=(-1, cls_preds.shape[-1])),
            nd.reshape(nd.maximum(cls_target, 0.0), shape=(-1,)),
        )
        ce = nd.reshape(ce, shape=cls_target.shape) * valid
        l1 = self._l1(box_preds * box_mask, box_target * box_mask)
        return ce.mean() + l1.mean()


def detect(net, x, threshold=0.01, nms_threshold=0.45):
    """Inference: decode + NMS via MultiBoxDetection; returns (B, A, 6)."""
    anchors, cls_preds, box_preds = net(x)
    cls_prob = nd.transpose(nd.softmax(cls_preds, axis=-1), axes=(0, 2, 1))
    return nd.contrib.MultiBoxDetection(
        cls_prob, box_preds, anchors, threshold=threshold, nms_threshold=nms_threshold
    )
