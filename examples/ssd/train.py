"""SSD training — reference ``example/ssd/train.py`` + ``train/train_net.py``.

Runs on a .rec detection dataset (ImageDetIter) or, with --synthetic, on a
generated shapes dataset so the full pipeline is runnable anywhere.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon

from ssd import SSD, SSDLoss, training_targets, detect
from metric import VOCMApMetric


def synthetic_batches(batch_size, data_shape, num_batches, num_classes=2, seed=0):
    """Random colored rectangles on noise; label = [cls, x1, y1, x2, y2]."""
    rng = np.random.RandomState(seed)
    c, h, w = data_shape
    for _ in range(num_batches):
        data = rng.rand(batch_size, c, h, w).astype(np.float32) * 0.2
        labels = np.full((batch_size, 2, 5), -1.0, dtype=np.float32)
        for b in range(batch_size):
            n = rng.randint(1, 3)
            for j in range(n):
                cls = rng.randint(0, num_classes)
                bw, bh = rng.uniform(0.25, 0.5, 2)
                x1 = rng.uniform(0, 1 - bw)
                y1 = rng.uniform(0, 1 - bh)
                x2, y2 = x1 + bw, y1 + bh
                labels[b, j] = [cls, x1, y1, x2, y2]
                ix1, iy1 = int(x1 * w), int(y1 * h)
                ix2, iy2 = max(ix1 + 1, int(x2 * w)), max(iy1 + 1, int(y2 * h))
                # class-dependent intensity pattern makes the task learnable
                data[b, cls % c, iy1:iy2, ix1:ix2] += 0.8
        yield nd.array(data), nd.array(labels)


def train(args):
    net = SSD(num_classes=args.num_classes)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(
        net.collect_params(), "sgd", {"learning_rate": args.lr, "momentum": 0.9, "wd": 5e-4}
    )
    loss_fn = SSDLoss()

    for epoch in range(args.epochs):
        tic = time.time()
        tot_loss, nb = 0.0, 0
        if args.synthetic:
            batches = synthetic_batches(
                args.batch_size, tuple(args.data_shape), args.batches_per_epoch, args.num_classes,
                seed=epoch,
            )
        else:
            it = mx.image.ImageDetIter(
                batch_size=args.batch_size,
                data_shape=tuple(args.data_shape),
                path_imgrec=args.train_rec,
                shuffle=True,
                rand_mirror=True,
                mean=True,
                std=True,
            )
            batches = ((b.data[0], b.label[0]) for b in it)
        for data, labels in batches:
            with autograd.record():
                anchors, cls_preds, box_preds = net(data)
                box_target, box_mask, cls_target = training_targets(anchors, cls_preds, labels)
                loss = loss_fn(cls_preds, box_preds, cls_target, box_target, box_mask)
            loss.backward()
            trainer.step(args.batch_size)
            tot_loss += float(loss.asnumpy())
            nb += 1
        print(
            "epoch %d: loss %.4f (%.1fs, %.1f samples/s)"
            % (epoch, tot_loss / max(nb, 1), time.time() - tic,
               nb * args.batch_size / max(time.time() - tic, 1e-9))
        )
    return net


def evaluate(net, args):
    metric = VOCMApMetric(iou_thresh=0.5)
    batches = synthetic_batches(
        args.batch_size, tuple(args.data_shape), 4, args.num_classes, seed=999
    )
    for data, labels in batches:
        dets = detect(net, data, threshold=0.1)
        metric.update(dets.asnumpy(), labels.asnumpy())
    name, val = metric.get()
    print("%s: %.4f" % (name, val))
    return val


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train-rec", default=None, help=".rec file (ImageDetIter)")
    p.add_argument("--synthetic", action="store_true", default=False)
    # default = the reference's real SSD-300 resolution (the 64×64 toy
    # shape is still reachable explicitly for smoke runs; the fast path at
    # this shape is train_fused.py / the eval_ssd_map.py quality gate)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--data-shape", type=int, nargs=3, default=[3, 300, 300])
    p.add_argument("--num-classes", type=int, default=2)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batches-per-epoch", type=int, default=16)
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args()
    if args.train_rec is None:
        args.synthetic = True
    net = train(args)
    evaluate(net, args)


if __name__ == "__main__":
    main()
