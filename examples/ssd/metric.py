"""VOC mAP metric — reference ``example/ssd/evaluate/eval_metric.py``
(MApMetric/VOC07MApMetric)."""
from __future__ import annotations

import numpy as np


class VOCMApMetric:
    """Mean average precision for detection.

    update() takes detections (B, A, 6) [cls, score, x1, y1, x2, y2] (cls -1
    = invalid) and ground-truth labels (B, N, 5+) [cls, x1, y1, x2, y2]
    (cls -1 = padding).
    """

    def __init__(self, iou_thresh=0.5, class_names=None, use_voc07=False):
        self.iou_thresh = iou_thresh
        self.class_names = class_names
        self.use_voc07 = use_voc07
        self.reset()

    def reset(self):
        self._records = {}  # cls -> list of (score, tp)
        self._gt_counts = {}

    @staticmethod
    def _iou(box, boxes):
        ix1 = np.maximum(box[0], boxes[:, 0])
        iy1 = np.maximum(box[1], boxes[:, 1])
        ix2 = np.minimum(box[2], boxes[:, 2])
        iy2 = np.minimum(box[3], boxes[:, 3])
        iw = np.maximum(ix2 - ix1, 0)
        ih = np.maximum(iy2 - iy1, 0)
        inter = iw * ih
        union = (
            (box[2] - box[0]) * (box[3] - box[1])
            + (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
            - inter
        )
        return inter / np.maximum(union, 1e-12)

    def update(self, dets, labels):
        dets = np.asarray(dets)
        labels = np.asarray(labels)
        for b in range(dets.shape[0]):
            gt = labels[b]
            gt = gt[gt[:, 0] >= 0]
            for c in np.unique(gt[:, 0]).astype(int):
                self._gt_counts[c] = self._gt_counts.get(c, 0) + int((gt[:, 0] == c).sum())
            det = dets[b]
            det = det[det[:, 0] >= 0]
            order = np.argsort(-det[:, 1])
            det = det[order]
            matched = np.zeros(gt.shape[0], dtype=bool)
            for row in det:
                c = int(row[0])
                cls_gt_idx = np.where(gt[:, 0] == c)[0]
                tp = 0
                if cls_gt_idx.size:
                    ious = self._iou(row[2:6], gt[cls_gt_idx, 1:5])
                    best = np.argmax(ious)
                    if ious[best] >= self.iou_thresh and not matched[cls_gt_idx[best]]:
                        matched[cls_gt_idx[best]] = True
                        tp = 1
                self._records.setdefault(c, []).append((float(row[1]), tp))

    def _average_precision(self, rec, prec):
        if self.use_voc07:
            ap = 0.0
            for t in np.arange(0.0, 1.1, 0.1):
                p = prec[rec >= t].max() if (rec >= t).any() else 0.0
                ap += p / 11.0
            return ap
        mrec = np.concatenate([[0.0], rec, [1.0]])
        mpre = np.concatenate([[0.0], prec, [0.0]])
        for i in range(mpre.size - 1, 0, -1):
            mpre[i - 1] = max(mpre[i - 1], mpre[i])
        idx = np.where(mrec[1:] != mrec[:-1])[0]
        return float(((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]).sum())

    def get(self):
        aps = []
        names = []
        # every class with ground truth counts: zero detections -> AP 0
        for c in sorted(set(self._records) | set(self._gt_counts)):
            npos = self._gt_counts.get(c, 0)
            if npos == 0:
                continue
            recs = self._records.get(c, [])
            if not recs:
                aps.append(0.0)
                names.append(self.class_names[c] if self.class_names else str(c))
                continue
            recs = sorted(recs, key=lambda x: -x[0])
            tps = np.array([tp for _, tp in recs], dtype=np.float64)
            tp_cum = np.cumsum(tps)
            fp_cum = np.cumsum(1.0 - tps)
            rec = tp_cum / npos
            prec = tp_cum / np.maximum(tp_cum + fp_cum, 1e-12)
            aps.append(self._average_precision(rec, prec))
            names.append(self.class_names[c] if self.class_names else str(c))
        mean_ap = float(np.mean(aps)) if aps else 0.0
        return "mAP", mean_ap
