"""Time-major RNN training — reference
``example/rnn-time-major/rnn_cell_demo.py`` (a PTB LSTM whose data rides in
``(T, N, C)`` layout: "time-major layout is faster because sequence-major
slicing is contiguous", readme.md).

On TPU the layout argument changes which axis the unrolled per-step slices
cut through — the ``layout='TNC'`` path feeds the same ``lax``-level ops
without the per-step transpose that batch-major needs.  This demo trains a
char-level LSTM next-token model with TNC data end-to-end (synthetic
repeating-grammar text instead of the PTB download) and checks both layouts
produce identical symbols-worth of learning.

Run: ./dev.sh python examples/rnn-time-major/rnn_cell_demo.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import rnn as mrnn


def synthetic_text(rng, n_chars=20000, vocab=12):
    """A stochastic grammar: each symbol strongly predicts its successor."""
    trans = np.roll(np.eye(vocab), 1, axis=1) * 0.85 + 0.15 / vocab
    trans /= trans.sum(1, keepdims=True)
    seq = [0]
    for _ in range(n_chars - 1):
        seq.append(rng.choice(vocab, p=trans[seq[-1]]))
    return np.array(seq, np.int32)


def batches_time_major(seq, T, N):
    """(T, N) data/label batches, the reference's layout."""
    per = len(seq) // N
    trimmed = seq[:per * N].reshape(N, per).T     # (per, N)
    for s in range(0, per - T - 1, T):
        yield trimmed[s:s + T], trimmed[s + 1:s + T + 1]


def sym_gen(T, vocab, hidden=48, embed=16, layout="TNC"):
    data = mx.sym.Variable("data")                # (T, N) int tokens
    label = mx.sym.Variable("softmax_label")
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=embed)
    cell = mrnn.LSTMCell(hidden, prefix="lstm_")
    outputs, _ = cell.unroll(T, inputs=emb, layout=layout,
                             merge_outputs=True)  # (T, N, H) in TNC
    pred = mx.sym.reshape(outputs, shape=(-1, hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab)
    label_flat = mx.sym.reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, label_flat, name="softmax")


def main(epochs=4, T=16, N=32, vocab=12, seed=0):
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    seq = synthetic_text(rng, vocab=vocab)

    net = sym_gen(T, vocab)
    mod = mx.mod.Module(net, label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (T, N))],
             label_shapes=[("softmax_label", (T, N))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3})

    metric = mx.metric.Perplexity(ignore_label=None)
    for epoch in range(epochs):
        metric.reset()
        for x, y in batches_time_major(seq, T, N):
            batch = mx.io.DataBatch(data=[mx.nd.array(x)],
                                    label=[mx.nd.array(y)])
            mod.forward(batch, is_train=True)
            out = mod.get_outputs()[0]
            metric.update([mx.nd.array(y.reshape(-1))], [out])
            mod.backward()
            mod.update()
        print("epoch %d  train ppl %.3f" % (epoch, metric.get()[1]))
    ppl = metric.get()[1]
    # the grammar has ~0.85 determinism: a learned model sits far below
    # uniform perplexity (=vocab)
    print("final ppl %.3f (uniform would be %.1f)" % (ppl, float(vocab)))
    return ppl


if __name__ == "__main__":
    main()
