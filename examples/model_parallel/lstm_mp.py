"""Model-parallel LSTM language model over a device mesh.

Reference counterpart: `example/model-parallel/` + `docs/faq/model_parallel_lstm.md`
(each LSTM layer pinned to a different GPU via `group2ctx`, activations
copied between devices by `_CrossDeviceCopy`;
`tests/python/unittest/test_model_parallel.py`).

The TPU-native version does not place layers on devices by hand.  The model's
weights are *sharded* over an ``mp`` mesh axis (each chip owns a slice of
every gate matrix), the hidden state is kept ``mp``-sharded with
``with_sharding_constraint``, and XLA inserts the all-gather/psum collectives
over ICI where the reference inserted explicit device-to-device copies.  This
is strictly more parallel than the reference's scheme: every chip computes on
every timestep instead of idling while other layers run.

Run: ``./dev.sh python examples/model_parallel/lstm_mp.py`` (8-dev CPU mesh)
or on real chips.  ``--check-replicated`` re-runs the first loss on a
single-device replica and asserts the sharded program computes the same
numbers — the correctness bar the reference's test_model_parallel.py sets.
"""
from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def init_params(rng, vocab, embed, hidden, layers):
    p = {"embed": rng.normal(0, 0.08, (vocab, embed)).astype(np.float32)}
    for l in range(layers):
        din = embed if l == 0 else hidden
        p["wx%d" % l] = rng.normal(0, 0.08, (din, 4 * hidden)).astype(np.float32)
        p["wh%d" % l] = rng.normal(0, 0.08, (hidden, 4 * hidden)).astype(np.float32)
        p["b%d" % l] = np.zeros((4 * hidden,), np.float32)
    p["wout"] = rng.normal(0, 0.08, (hidden, vocab)).astype(np.float32)
    p["bout"] = np.zeros((vocab,), np.float32)
    return p


def shard_specs(layers):
    """Tensor-parallel layout: gate/output dims split over mp (Megatron-style
    column-parallel wx/wh, row-parallel wout ⇒ one psum per step)."""
    from jax.sharding import PartitionSpec as P

    spec = {"embed": P(None, None), "wout": P("mp", None), "bout": P(None)}
    for l in range(layers):
        spec["wx%d" % l] = P(None, "mp")
        spec["wh%d" % l] = P(None, "mp")
        spec["b%d" % l] = P("mp")
    return spec


def make_loss_fn(layers, hidden, mesh):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def constrain(x, *spec):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))

    def lstm_cell(p, l, x_t, h, c):
        # wx/wh are column-sharded ⇒ gates land mp-sharded; h is gathered by
        # XLA for the wh matmul (the ICI hop that replaces _CrossDeviceCopy)
        gates = x_t @ p["wx%d" % l] + h @ p["wh%d" % l] + p["b%d" % l]
        gates = constrain(gates, None, "mp")
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return constrain(h, None, "mp"), constrain(c, None, "mp")

    def loss_fn(params, tokens):
        # tokens: (batch, T+1) int32
        x = params["embed"][tokens[:, :-1]]          # (B, T, E)
        y = tokens[:, 1:]
        B, T = y.shape
        hc = [(jnp.zeros((B, hidden)), jnp.zeros((B, hidden)))] * layers

        def step(carry, x_t):
            hc = list(carry)
            inp = x_t
            for l in range(layers):
                h, c = lstm_cell(params, l, inp, *hc[l])
                hc[l] = (h, c)
                inp = h
            logits = inp @ params["wout"] + params["bout"]  # row-parallel psum
            return tuple(hc), logits

        _, logits = jax.lax.scan(step, tuple(hc), jnp.swapaxes(x, 0, 1))
        logits = jnp.swapaxes(logits, 0, 1)          # (B, T, V)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, y[..., None], axis=-1)
        return nll.mean()

    return loss_fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--embed", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=48)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--check-replicated", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxnet_tpu import parallel

    n = len(jax.devices())
    mesh = parallel.make_mesh({"mp": n})
    assert args.hidden % n == 0, "hidden must divide over the mp axis"

    rng = np.random.RandomState(0)
    params = init_params(rng, args.vocab, args.embed, args.hidden, args.layers)
    specs = shard_specs(args.layers)
    params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in params.items()}

    loss_fn = make_loss_fn(args.layers, args.hidden, mesh)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(params, tokens, lr):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    def sample_batch(i):
        # learnable structure: tokens follow t_{k+1} = (t_k + stride) % V with
        # a per-sequence stride in {1,2,3}; the LM must use its state to learn it
        r = np.random.RandomState(1000 + i)
        stride = r.randint(1, 4, (args.batch, 1))
        start = r.randint(0, args.vocab, (args.batch, 1))
        ar = np.arange(args.seq_len + 1)[None, :]
        return ((start + stride * ar) % args.vocab).astype(np.int32)

    if args.check_replicated:
        # oracle: same math fully replicated (= single-device semantics)
        repl = {k: jax.device_put(np.asarray(v), NamedSharding(mesh, P()))
                for k, v in params.items()}
        t = sample_batch(0)
        a = float(jax.jit(loss_fn)(params, t))
        b = float(jax.jit(loss_fn)(repl, t))
        assert abs(a - b) < 1e-4, (a, b)
        print("sharded-vs-replicated loss match: %.6f vs %.6f" % (a, b))

    losses, t0 = [], None
    for i in range(args.steps):
        params, loss = train_step(params, sample_batch(i), args.lr)
        losses.append(float(loss))
        if i == 0:
            t0 = time.perf_counter()
    dt = time.perf_counter() - t0
    toks = args.batch * args.seq_len * (args.steps - 1) / dt
    print("mp=%d  loss %.4f -> %.4f  (%.0f tok/s)" % (n, losses[0], losses[-1], toks))
    assert losses[-1] < losses[0] * 0.6, "model-parallel LM failed to learn"
    print("MODEL PARALLEL LSTM OK")


if __name__ == "__main__":
    main()
