"""Pipeline-parallel training with gpipe (reference counterpart:
`example/model-parallel/` manual per-layer placement — which ran ONE device
at a time; this streams microbatches so all stages compute concurrently,
see `mxnet_tpu/parallel/pipeline.py`).

Each device owns one MLP stage's weights; M microbatches flow through the
``pp`` mesh axis with ``lax.ppermute`` hops; ``jax.grad`` differentiates
straight through the schedule, so the whole pipeline trains with plain SGD.

Run: ``./dev.sh python examples/model_parallel/pipeline_mlp.py``
(8 virtual devices; real chips on a pod).
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--microbatch", type=int, default=8)
    ap.add_argument("--microbatches-per-step", type=int, default=0,
                    help="0 = 4x the stage count (75%% steady-state util)")
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from mxnet_tpu import parallel

    n = len(jax.devices())
    mesh = parallel.make_mesh({"pp": n})
    M = args.microbatches_per_step or 4 * n
    rng = np.random.RandomState(0)

    # one residual-MLP stage per device (uniform stages, gpipe's contract)
    stages = [{"w": (rng.randn(args.dim, args.dim) * 0.15).astype(np.float32),
               "b": np.zeros(args.dim, np.float32)} for _ in range(n)]
    sp = parallel.stack_stage_params(stages)

    def stage_fn(p, h):
        return h + jnp.tanh(h @ p["w"] + p["b"])  # residual keeps depth sane

    # task: regress a fixed random rotation of the input
    R = (np.linalg.qr(rng.randn(args.dim, args.dim))[0] * 0.8).astype(np.float32)
    xs = jnp.asarray(rng.randn(M, args.microbatch, args.dim).astype(np.float32))
    tgt = jnp.asarray(np.asarray(xs) @ R)

    def loss_fn(sp):
        out = parallel.gpipe(stage_fn, sp, xs, mesh=mesh)
        return jnp.mean((out - tgt) ** 2)

    vg = jax.jit(jax.value_and_grad(loss_fn))
    l0 = t0 = None
    for i in range(args.steps):
        l, g = vg(sp)
        sp = jax.tree_util.tree_map(lambda p, gg: p - args.lr * gg, sp, g)
        if i == 0:
            l0 = float(l)              # params still un-updated here
            t0 = time.perf_counter()   # excludes compile
    jax.block_until_ready(sp)          # async dispatch: sync before timing
    dt = time.perf_counter() - t0
    steps_s = (args.steps - 1) / dt if args.steps > 1 else 0
    bubble = (n - 1) / (M + n - 1)
    print("pp=%d microbatches=%d (bubble %.0f%%)  loss %.4f -> %.4f  %.1f steps/s"
          % (n, M, 100 * bubble, float(l0), float(l), steps_s))
    assert float(l) < float(l0) * 0.5, "pipeline failed to learn"
    print("PIPELINE MLP OK")


if __name__ == "__main__":
    main()
