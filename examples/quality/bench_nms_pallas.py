"""Head-to-head: XLA blocked NMS vs the Pallas NMS kernel, on the chip.

VERDICT r2 item 3 — the "Pallas where profiling justifies it" claim needs
profiling that includes the Pallas side.  This benches the north-star NMS
shapes (rpn_pre_nms_top_n=6000 single-class, reference
multi_proposal.cc:221-273 / rcnn config) and the SSD-512 decode shape
(24,564 anchors x 20-class per-class NMS, multibox_detection.cc:83-190)
for both implementations, checks they agree on-chip, and prints a table
for docs/PERF_NOTES.md.

Run:  python examples/quality/bench_nms_pallas.py
"""
from __future__ import annotations

import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np
import jax
import jax.numpy as jnp

from mxnet_tpu.ops.detection import _nms_alive_blocked
from mxnet_tpu.ops.pallas_kernels import nms_alive_pallas


def make_boxes(n, seed, extent=1000.0):
    rng = np.random.RandomState(seed)
    ctr = rng.uniform(0, extent, (n, 2))
    wh = rng.uniform(8, 300, (n, 2))
    return np.concatenate([ctr - wh / 2, ctr + wh / 2], 1).astype(np.float32)


def bench(step, boxes, valid, ids, iters=256):
    """Chained on-device timing, robust to the tunnel's async dispatch.

    ``block_until_ready`` on this platform can return before execution
    (docs/PERF_NOTES.md tunnel note), so: run K data-dependent NMS steps
    inside ONE jitted fori_loop (each step's boxes are nudged by the
    previous survivor count, forcing sequential execution), fetch the
    final scalar to host, and report (T(K) - T(1)) / (K - 1) to cancel
    the ~100 ms tunnel roundtrip.
    """
    import functools

    @functools.partial(jax.jit, static_argnames=("k",))
    def chain(b, v, i, k):
        def body(_, carry):
            bx, acc = carry
            alive = step(bx, v, i)
            s = alive.sum().astype(jnp.float32)
            return bx + 1e-30 * s, acc + s

        _, acc = jax.lax.fori_loop(0, k, body, (b, jnp.float32(0)))
        return acc

    def timed(k):
        float(chain(boxes, valid, ids, k))  # compile
        best = None
        for _ in range(5):
            t0 = time.perf_counter()
            float(chain(boxes, valid, ids, k))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    t1, tk = timed(1), timed(iters)
    ms = (tk - t1) / (iters - 1) * 1e3
    return ms, step(boxes, valid, ids)


def main():
    print(f"backend: {jax.default_backend()}  device: {jax.devices()[0]}")
    rows = []
    for name, n, ids_n, iters in [
            ("proposal 6000 (north star)", 6000, 0, 2048),
            ("proposal 12000", 12000, 0, 512),
            ("ssd-512 decode 24564 x 20cls", 24564, 20, 256)]:
        boxes = jnp.asarray(make_boxes(n, 7))
        valid = jnp.ones((n,), bool)
        if ids_n:
            ids = jnp.asarray(np.random.RandomState(1).randint(0, ids_n, n))
            fs, po = False, 0.0
        else:
            ids, fs, po = None, True, 1.0

        # _nms_alive_blocked auto-dispatches to pallas on TPU now; pin the
        # XLA side explicitly so this stays a real head-to-head
        os.environ["MXNET_NMS_IMPL"] = "xla"
        xla = lambda b, v, i: _nms_alive_blocked(
            b, 0.7, valid=v, ids=i, force_suppress=fs, plus_one=po)
        pal = lambda b, v, i: nms_alive_pallas(
            b, v, i, thresh=0.7, plus_one=po, force_suppress=fs)

        t_x, r_x = bench(xla, boxes, valid, ids, iters=iters)
        t_p, r_p = bench(pal, boxes, valid, ids, iters=iters)
        os.environ.pop("MXNET_NMS_IMPL", None)
        agree = bool((np.asarray(r_x) == np.asarray(r_p)).all())
        rows.append((name, n, t_x, t_p, int(np.asarray(r_x).sum()), agree))
        print(f"{name:32s} N={n:6d}  xla {t_x:7.2f} ms  pallas {t_p:7.2f} ms"
              f"  speedup {t_x / t_p:5.2f}x  survivors={rows[-1][4]}"
              f"  agree={agree}")

    print("\n| shape | N | XLA blocked | Pallas | speedup |")
    print("|---|---|---|---|---|")
    for name, n, t_x, t_p, _, agree in rows:
        assert agree, f"MISMATCH on {name}"
        print(f"| {name} | {n} | {t_x:.2f} ms | {t_p:.2f} ms "
              f"| {t_x / t_p:.2f}x |")


if __name__ == "__main__":
    main()
