"""Faster R-CNN VGG16 detection quality: mAP on synthetic VOC-format data.

BASELINE config 2's quality bar is VOC07 mAP 70.23
(``example/rcnn/README.md:38-42``); real VOC cannot be fetched (no egress),
so — exactly like the R-FCN gate (eval_rfcn_map.py) — this measures the
strongest available proxy: the full jit-fused Faster-RCNN recipe
(examples/rcnn/train_fused.py) trained on deterministic synthetic
rectangles and evaluated with ``VOCMApMetric`` over a held-out stream.
A rising mAP proves RPN → proposals → class-specific targets → ROIPooling
→ fc heads → per-class decode+NMS learns detection end-to-end.

Class-SPECIFIC decode: unlike R-FCN's class-agnostic head, each class c
has its own 4 deltas at ``bbox_pred[:, 4(c+1):4(c+2)]``, un-normalized by
BBOX_STDS before applying (reference rcnn/core/tester.py pred_eval →
bbox_pred with stds multiplied back).

Run (chip):      python examples/quality/eval_frcnn_map.py --vgg16
Run (CPU smoke): ./dev.sh python examples/quality/eval_frcnn_map.py --steps 30
"""
from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.gluon.functional import functionalize
from mxnet_tpu.test_utils import load_module_by_path


def _load(name, *relpath):
    return load_module_by_path(os.path.join(_HERE, "..", *relpath), name)


_ssd_metric = _load("_ssd_metric_frcnn", "ssd", "metric.py")
_frcnn = _load("_frcnn_train_fused", "rcnn", "train_fused.py")
VOCMApMetric = _ssd_metric.VOCMApMetric
build_net = _frcnn.build_net
make_frcnn_train_step = _frcnn.make_frcnn_train_step
synthetic_voc = _frcnn.synthetic_voc
synthetic_voc_device = _frcnn.synthetic_voc_device


def decode_detections(rois, cls_prob, bbox_pred, num_classes, im_shape,
                      box_stds=(0.1, 0.1, 0.2, 0.2),
                      score_thresh=0.05, nms_thresh=0.3, max_det=100):
    """rois (R,5) + class-specific deltas (R, 4(C+1)) → (1, K, 6)
    [cls, score, x1..y2] after per-class delta application and NMS."""
    from mxnet_tpu.ops.detection import box_nms

    import jax
    import jax.numpy as jnp

    boxes = rois[:, 1:5]
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1.0)
    cy = boxes[:, 1] + 0.5 * (h - 1.0)
    stds = np.asarray(box_stds, np.float32)

    rows = []
    for c in range(num_classes):
        d = bbox_pred[:, 4 * (c + 1): 4 * (c + 2)] * stds[None, :]
        pcx = d[:, 0] * w + cx
        pcy = d[:, 1] * h + cy
        pw = np.exp(np.clip(d[:, 2], -10, 10)) * w
        ph = np.exp(np.clip(d[:, 3], -10, 10)) * h
        x1 = np.clip(pcx - 0.5 * (pw - 1.0), 0, im_shape[1] - 1)
        y1 = np.clip(pcy - 0.5 * (ph - 1.0), 0, im_shape[0] - 1)
        x2 = np.clip(pcx + 0.5 * (pw - 1.0), 0, im_shape[1] - 1)
        y2 = np.clip(pcy + 0.5 * (ph - 1.0), 0, im_shape[0] - 1)
        sc = cls_prob[:, c + 1]
        keep = sc >= score_thresh
        if not keep.any():
            continue
        rows.append(np.stack([
            np.full(keep.sum(), c, np.float32), sc[keep],
            x1[keep], y1[keep], x2[keep], y2[keep]], axis=1))
    if not rows:
        return np.full((1, 1, 6), -1, np.float32)
    dat = np.concatenate(rows, axis=0)[None]  # (1, N, 6)
    # fixed-size bucket + host-CPU NMS (see eval_rfcn_map.py: an exact-N jit
    # would recompile per eval image)
    cap = 512
    n = dat.shape[1]
    if n < cap:
        dat = np.concatenate(
            [dat, np.full((1, cap - n, 6), -1, np.float32)], axis=1)
    else:
        dat = dat[:, np.argsort(-dat[0, :, 1])[:cap]]
    with jax.default_device(jax.devices("cpu")[0]):
        out = np.asarray(box_nms(
            jnp.asarray(dat), overlap_thresh=nms_thresh, coord_start=2,
            score_index=1, id_index=0, force_suppress=False))
    out = out[0]
    out = out[out[:, 0] >= 0][:max_det]
    return out[None] if out.size else np.full((1, 1, 6), -1, np.float32)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--vgg16", action="store_true")
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--eval-images", type=int, default=500)
    p.add_argument("--classes", type=int, default=3)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--map-floor", type=float, default=None,
                   help="exit 1 if final mAP falls below this (CI tier)")
    p.add_argument("--host-data", action="store_true")
    p.add_argument("--flat-lr", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    import jax

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    steps = args.steps or (800 if args.vgg16 else 30)

    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    net, shape, classes = build_net(args.vgg16, classes=args.classes)
    step, state = make_frcnn_train_step(
        net, 1, learning_rate=args.lr, momentum=0.9,
        compute_dtype="bfloat16" if (on_tpu and args.vgg16) else None)
    key = jax.random.PRNGKey(args.seed)
    use_device_data = on_tpu and not args.host_data

    if use_device_data:
        def step_with_data(st, sidx, lr_v):
            kd, ks = jax.random.split(jax.random.fold_in(key, sidx))
            data, im_info, gt = synthetic_voc_device(
                kd, 1, shape, classes, net.max_gts)
            return step(st, data, im_info, gt, ks, lr_v)

        jstep_dev = jax.jit(step_with_data, donate_argnums=(0,))
    else:
        jstep = jax.jit(step, donate_argnums=(0,))

    decay_points = set() if args.flat_lr else {int(steps * 0.6), int(steps * 0.85)}
    lr = args.lr
    for s in range(steps):
        if s in decay_points:
            lr *= 0.1
            print("lr -> %g at step %d" % (lr, s), flush=True)
        if use_device_data:
            state, loss, parts = jstep_dev(state, np.int32(s), np.float32(lr))
        else:
            data, im_info, gt = synthetic_voc(rng, 1, shape, classes,
                                              net.max_gts)
            state, loss, parts = jstep(state, data, im_info, gt,
                                       jax.random.fold_in(key, s),
                                       np.float32(lr))
        if s % max(1, steps // 8) == 0:
            print("step %4d  loss %.4f" % (s, float(loss)), flush=True)

    # --- evaluation: inference twin at the TEST proposal config ----------
    from mxnet_tpu.gluon.functional import merge_params

    eval_net, _, _ = build_net(args.vgg16, classes=args.classes,
                               rpn_pre_nms=6000 if args.vgg16 else None,
                               rpn_post_nms=300 if args.vgg16 else None)
    apply, names, vals, aux_names = functionalize(eval_net, train=False)
    learn, _mom, aux = state
    merged = merge_params(names, aux_names, learn, aux)

    infer = jax.jit(lambda m, x, i: apply(m, (x, i), jax.random.PRNGKey(0))[0])
    metric = VOCMApMetric(iou_thresh=0.5)
    eval_rng = np.random.RandomState(12345)
    if use_device_data:
        ekey = jax.random.PRNGKey(54321)
        gen = jax.jit(lambda i: synthetic_voc_device(
            jax.random.fold_in(ekey, i), 1, shape, classes, net.max_gts))
    for _i in range(args.eval_images):
        if use_device_data:
            data, im_info, gt = gen(np.int32(_i))
            gt = np.asarray(gt)
        else:
            data, im_info, gt = synthetic_voc(eval_rng, 1, shape, classes,
                                              net.max_gts)
        rois, prob, deltas = infer(merged, data, im_info)
        dets = decode_detections(
            np.asarray(rois).astype(np.float32),
            np.asarray(prob).astype(np.float32),
            np.asarray(deltas).astype(np.float32), classes, shape,
            box_stds=net.box_stds)
        metric.update(dets, gt[:, :, :5])
    name, value = metric.get()
    print("FINAL frcnn %s synthetic-VOC %s = %.4f  (steps=%d, classes=%d, "
          "eval n=%d)" % ("vgg16" if args.vgg16 else "tiny",
                          name, value, steps, classes, args.eval_images))
    if args.map_floor is not None and value < args.map_floor:
        print("FAIL: mAP %.4f below floor %.4f" % (value, args.map_floor))
        sys.exit(1)


if __name__ == "__main__":
    main()
