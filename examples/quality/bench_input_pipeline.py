"""Input-pipeline throughput: ImageRecordIter → device → jitted train step.

The reference keeps its GPUs fed with a multithreaded C++ decode+augment
pipeline (``src/io/iter_image_recordio_2.cc:50,663``).  This script
measures each stage of the equivalent path here — native RecordIO/JPEG
batch loader, host→device transfer, double-buffered prefetch into the
jitted ResNet-50 train step — and reports the end-to-end steady state
next to the synthetic-batch number.

Environment honesty (documented in docs/PERF_NOTES.md): this box has ONE
CPU core and the chip hangs off a tunnel (~47 MB/s H2D, ~13 MB/s D2H), so
neither the decode (reference used 72-vcore hosts) nor the H2D leg can
physically keep a 2,300 img/s step fed; the measurement proves the
machinery (overlap, prefetch, native decode) and quantifies each stage's
ceiling.

Run (chip): python examples/quality/bench_input_pipeline.py
CPU smoke:  ./dev.sh python examples/quality/bench_input_pipeline.py --images 64 --batch 16 --steps 2
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time
import threading

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import recordio


def write_rec(path, n, hw=224, seed=0):
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path, "w")
    for i in range(n):
        img = (rng.rand(hw, hw, 3) * 255).astype(np.uint8)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 10), i, 0), img, quality=85))
    rec.close()


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--images", type=int, default=512)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--image-size", type=int, default=224)
    args = p.parse_args()

    import jax

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    tmp = tempfile.mkdtemp()
    rec_path = os.path.join(tmp, "bench.rec")
    t0 = time.perf_counter()
    write_rec(rec_path, args.images, args.image_size)
    print("wrote %d jpegs in %.1fs" % (args.images, time.perf_counter() - t0))

    # -- stage 1: host pipeline throughput (native decode+augment+batch) --
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, args.image_size, args.image_size),
        batch_size=args.batch, rand_mirror=True, preprocess_threads=2)
    n = 0
    t0 = time.perf_counter()
    for batch in it:
        n += batch.data[0].shape[0] - batch.pad
    host_dt = time.perf_counter() - t0
    host_ips = n / host_dt
    print("host pipeline (native decode+augment): %.1f img/s" % host_ips)

    # -- stage 2: H2D transfer bandwidth for one batch --------------------
    it.reset()
    first = next(iter(it))
    arr = first.data[0].asnumpy()
    mb = arr.nbytes / 1e6
    t0 = time.perf_counter()
    d = jax.device_put(arr)
    jax.block_until_ready(d)
    h2d_dt = time.perf_counter() - t0
    print("H2D: %.1f MB batch in %.2fs (%.1f MB/s)" % (mb, h2d_dt, mb / h2d_dt))

    from mxnet_tpu.gluon import loss as loss_mod
    from mxnet_tpu.gluon.functional import make_train_step
    from __graft_entry__ import _build_resnet

    net = _build_resnet(classes=10, version=50, image_size=args.image_size)
    step, state, _ = make_train_step(
        net, loss_mod.SoftmaxCrossEntropyLoss(), learning_rate=0.05,
        momentum=0.9, compute_dtype="bfloat16" if on_tpu else None)
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)

    # -- stage 3: synthetic-batch reference (also compiles the step) ------
    rng = np.random.RandomState(0)
    xs = jax.device_put(rng.randn(args.batch, 3, args.image_size,
                                  args.image_size).astype(np.float32))
    ys = jax.device_put(rng.randint(0, 10, (args.batch,)).astype(np.float32))
    state, loss = jstep(state, xs, ys, key)
    jax.block_until_ready(loss)
    # keys precomputed outside the timed window (eager fold_in costs
    # several tunneled dispatches per step)
    kpre = [jax.random.fold_in(key, 100 + s) for s in range(args.steps)]
    jax.block_until_ready(kpre[-1])
    t0 = time.perf_counter()
    for s in range(args.steps):
        state, loss = jstep(state, xs, ys, kpre[s])
    jax.block_until_ready(loss)
    syn_dt = time.perf_counter() - t0
    syn_ips = args.steps * args.batch / syn_dt
    print("synthetic-batch step:                  %.1f img/s" % syn_ips)
    # -- stage 4: pipeline-fed train step, double-buffered ----------------
    # double-buffer: a loader thread decodes + device_puts the NEXT batch
    # while the current step runs (jax dispatch is async, so device_put and
    # compute overlap naturally; the thread hides the host decode)
    it.reset()
    it_iter = [iter(it)]
    slot = {}

    def stage(i):
        try:
            b = next(it_iter[0])
        except StopIteration:  # epoch boundary: wrap like a training loop
            it.reset()
            it_iter[0] = iter(it)
            b = next(it_iter[0])
        slot[i] = (jax.device_put(b.data[0].asnumpy()),
                   jax.device_put(b.label[0].asnumpy()))

    stage(0)
    kfeed = [jax.random.fold_in(key, s) for s in range(args.steps)]
    jax.block_until_ready(kfeed[-1])
    t0 = time.perf_counter()
    loader = None
    done = 0
    for s in range(args.steps):
        if loader is not None:
            loader.join()
        x, y = slot.pop(s)
        if s + 1 < args.steps:
            loader = threading.Thread(target=stage, args=(s + 1,))
            loader.start()
        state, loss = jstep(state, x, y, kfeed[s])
        done += args.batch
    jax.block_until_ready(loss)
    fed_dt = time.perf_counter() - t0
    fed_ips = done / fed_dt
    print("pipeline-fed train step (double-buffered): %.1f img/s "
          "over %d steps" % (fed_ips, args.steps))

    print("SUMMARY input_pipeline: host=%.1f h2d=%.1fMB/s fed=%.1f "
          "synthetic=%.1f img/s (batch %d)"
          % (host_ips, mb / h2d_dt, fed_ips, syn_ips, args.batch))


if __name__ == "__main__":
    main()
