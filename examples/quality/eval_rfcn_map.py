"""Detection quality: mAP on deterministic VOC-format synthetic data.

Real VOC/COCO cannot be fetched (no egress; BASELINE.md bars —
Faster-RCNN VGG16 VOC07 mAP 70.23, ``example/rcnn/README.md:38-42``), so
this measures the strongest available proxy: the full jit-fused Deformable
R-FCN training recipe on a deterministic synthetic VOC-format dataset
(bright rectangles, known ground truth), evaluated with the repo's own
``VOCMApMetric`` over held-out images.  A rising, stable mAP proves the
whole pipeline — RPN, proposals, target assignment, deformable PS-ROI
scoring, box decoding, per-class NMS — learns detection end-to-end.

Run (chip):  python examples/quality/eval_rfcn_map.py --resnet101
Run (CPU smoke): ./dev.sh python examples/quality/eval_rfcn_map.py --steps 30
"""
from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.gluon.functional import functionalize


from mxnet_tpu.test_utils import load_module_by_path


def _load(name, *relpath):
    return load_module_by_path(os.path.join(_HERE, "..", *relpath), name)


_ssd_metric = _load("_ssd_metric", "ssd", "metric.py")
_rfcn = _load("_rfcn_train_fused", "deformable_rfcn", "train_fused.py")
VOCMApMetric = _ssd_metric.VOCMApMetric
build_net = _rfcn.build_net
make_rfcn_train_step = _rfcn.make_rfcn_train_step
synthetic_coco = _rfcn.synthetic_coco


def decode_detections(rois, cls_prob, bbox_pred, num_classes, im_shape,
                      score_thresh=0.05, nms_thresh=0.3, max_det=100):
    """rois (R,5) + class-agnostic deltas → (1, K, 6) [cls, score, x1..y2].

    Inverse of the training targets' bbox_transform (+1 convention,
    reference rcnn/processing/bbox_transform.py bbox_pred), then per-class
    NMS via the registry box_nms op."""
    from mxnet_tpu.ops.detection import box_nms

    import jax.numpy as jnp

    boxes = rois[:, 1:5]
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1.0)
    cy = boxes[:, 1] + 0.5 * (h - 1.0)
    d = bbox_pred[:, 4:8]  # fg deltas (class-agnostic head)
    pcx = d[:, 0] * w + cx
    pcy = d[:, 1] * h + cy
    pw = np.exp(d[:, 2]) * w
    ph = np.exp(d[:, 3]) * h
    x1 = np.clip(pcx - 0.5 * (pw - 1.0), 0, im_shape[1] - 1)
    y1 = np.clip(pcy - 0.5 * (ph - 1.0), 0, im_shape[0] - 1)
    x2 = np.clip(pcx + 0.5 * (pw - 1.0), 0, im_shape[1] - 1)
    y2 = np.clip(pcy + 0.5 * (ph - 1.0), 0, im_shape[0] - 1)

    rows = []
    for c in range(num_classes):
        sc = cls_prob[:, c + 1]
        keep = sc >= score_thresh
        if not keep.any():
            continue
        rows.append(np.stack([
            np.full(keep.sum(), c, np.float32), sc[keep],
            x1[keep], y1[keep], x2[keep], y2[keep]], axis=1))
    if not rows:
        return np.full((1, 1, 6), -1, np.float32)
    dat = np.concatenate(rows, axis=0)[None]  # (1, N, 6)
    # decode NMS on the host CPU backend (recompiling per shape over the
    # TPU tunnel is wasteful), padded to a fixed-size bucket: per-image
    # detection counts vary, and an exact-N jit would recompile for nearly
    # every eval image (seconds each on this host — the former n=500 eval
    # bottleneck).  Pad rows score -1 sort behind real ones and decode to
    # class -1, which the metric update drops.
    import jax

    cap = 512
    n = dat.shape[1]
    if n < cap:
        pad = np.full((1, cap - n, 6), -1, np.float32)
        dat = np.concatenate([dat, pad], axis=1)
    else:
        dat = dat[:, np.argsort(-dat[0, :, 1])[:cap]]
    with jax.default_device(jax.devices("cpu")[0]):
        out = np.asarray(box_nms(
            jnp.asarray(dat), overlap_thresh=nms_thresh, coord_start=2,
            score_index=1, id_index=0, force_suppress=False))
    out = out[0]
    out = out[out[:, 0] >= 0][:max_det]
    return out[None] if out.size else np.full((1, 1, 6), -1, np.float32)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--resnet101", action="store_true")
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--eval-images", type=int, default=500,
                   help="held-out eval set size; n=500 bounds mAP noise to "
                        "a few points (the old n=48 default produced the "
                        "spurious 3000-vs-6000-step 'regression', "
                        "QUALITY.md)")
    p.add_argument("--classes", type=int, default=3)
    p.add_argument("--lr", type=float, default=2e-3)
    p.add_argument("--map-floor", type=float, default=None,
                   help="exit 1 if final mAP falls below this (CI tier)")
    p.add_argument("--host-data", action="store_true",
                   help="force host-side numpy data generation even on TPU "
                        "(the CPU nightly config; on-chip runs default to "
                        "on-device generation, ~60x less per-step host+H2D)")
    p.add_argument("--live-bn", action="store_true",
                   help="train BatchNorm statistics (from-scratch runs; the "
                        "frozen-BN recipe assumes pretrained weights)")
    p.add_argument("--flat-lr", action="store_true",
                   help="disable the 60%%/85%% step decay (reproduces the "
                        "flat-lr rows in QUALITY.md)")
    p.add_argument("--seed", type=int, default=0,
                   help="seed for init + train stream (the held-out eval "
                        "stream stays FIXED so cross-seed variation is "
                        "model-only); non-zero seeds are the "
                        "floor-calibration runs, QUALITY.md §3")
    args = p.parse_args()

    import jax

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    steps = args.steps or (800 if args.resnet101 else 30)

    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    net, shape, classes = build_net(args.resnet101, classes=args.classes,
                                    frozen_bn=not args.live_bn)
    step, state = make_rfcn_train_step(
        net, 1, learning_rate=args.lr, momentum=0.9,
        compute_dtype="bfloat16" if (on_tpu and args.resnet101) else None)
    key = jax.random.PRNGKey(args.seed)
    # On the chip, generate the batch ON DEVICE inside the jitted step: over
    # the tunnel, host generation + H2D costs ~0.6 s/step (7.5 MB batch at
    # ~15 MB/s, plus an eager fold_in roundtrip) vs ~10 ms dispatch for the
    # fused gen+step — the difference between a 10-minute and a 2-hour
    # R-101 quality run.  CPU keeps the host generator (and its calibrated
    # nightly floor).
    use_device_data = on_tpu and not args.host_data

    if use_device_data:
        synthetic_coco_device = _rfcn.synthetic_coco_device

        def step_with_data(st, sidx, lr_v):
            kd, ks = jax.random.split(jax.random.fold_in(key, sidx))
            data, im_info, gt = synthetic_coco_device(
                kd, 1, shape, classes, net.max_gts)
            return step(st, data, im_info, gt, ks, lr_v)

        jstep_dev = jax.jit(step_with_data, donate_argnums=(0,))
    else:
        jstep = jax.jit(step, donate_argnums=(0,))

    # staged lr (the recipe's step decays): lr is a TRACED step argument,
    # so decays cost zero recompiles
    decay_points = set() if args.flat_lr else {int(steps * 0.6), int(steps * 0.85)}
    lr = args.lr
    for s in range(steps):
        if s in decay_points:
            lr *= 0.1
            print("lr -> %g at step %d" % (lr, s), flush=True)
        if use_device_data:
            state, loss, parts = jstep_dev(state, np.int32(s),
                                           np.float32(lr))
        else:
            data, im_info, gt = synthetic_coco(rng, 1, shape, classes,
                                               net.max_gts)
            state, loss, parts = jstep(state, data, im_info, gt,
                                       jax.random.fold_in(key, s),
                                       np.float32(lr))
        if s % max(1, steps // 8) == 0:
            print("step %4d  loss %.4f" % (s, float(loss)), flush=True)

    # --- evaluation: inference forward with the TRAINED parameters -------
    from mxnet_tpu.gluon.functional import merge_params

    apply, names, vals, aux_names = functionalize(net, train=False)
    learn, _mom, aux = state
    merged = merge_params(names, aux_names, learn, aux)

    infer = jax.jit(lambda m, x, i: apply(m, (x, i), jax.random.PRNGKey(0))[0])
    metric = VOCMApMetric(iou_thresh=0.5)
    eval_rng = np.random.RandomState(12345)  # held-out stream
    if use_device_data:
        ekey = jax.random.PRNGKey(54321)     # held-out device stream
        gen = jax.jit(lambda i: _rfcn.synthetic_coco_device(
            jax.random.fold_in(ekey, i), 1, shape, classes, net.max_gts))
    for _i in range(args.eval_images):
        if use_device_data:
            data, im_info, gt = gen(np.int32(_i))
            gt = np.asarray(gt)              # (1, G, 5) — a tiny D2H
        else:
            data, im_info, gt = synthetic_coco(eval_rng, 1, shape, classes,
                                               net.max_gts)
        rois, prob, deltas = infer(merged, data, im_info)
        dets = decode_detections(
            np.asarray(rois).astype(np.float32),
            np.asarray(prob).astype(np.float32),
            np.asarray(deltas).astype(np.float32), classes, shape)
        metric.update(dets, gt[:, :, :5])
    name, value = metric.get()
    print("FINAL rfcn %s synthetic-VOC %s = %.4f  (steps=%d, classes=%d, "
          "eval n=%d)" % ("resnet101" if args.resnet101 else "tiny",
                          name, value, steps, classes, args.eval_images))
    if args.map_floor is not None and value < args.map_floor:
        print("FAIL: mAP %.4f below floor %.4f" % (value, args.map_floor))
        sys.exit(1)


if __name__ == "__main__":
    main()
