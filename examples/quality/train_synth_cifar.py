"""CIFAR-recipe training end-to-end on a deterministic synthetic dataset.

Real CIFAR-10 can't be fetched (no egress), so the reference recipe
(``example/image-classification/train_cifar10.py``: ResNet-20, batch 128,
SGD momentum 0.9, wd 1e-4, lr 0.1 stepped down, pad-4 random crop + flip)
runs on a procedurally generated 32×32 10-class dataset — oriented
textures × color mixtures + heavy noise, with a held-out test split, so
the reported number is genuine generalization, not memorization.  The
accuracy bar this proxies: reference CIFAR ResNet convergence
(``example/image-classification/README.md``).

TPU-native details: the whole train set lives on-device; augmentation
(pad-4 random crop + horizontal flip) runs INSIDE the jitted train step;
the LR schedule is a step input.  Mid-run the state checkpoints through
``mxnet_tpu.parallel.checkpoint`` and training RESUMES from disk —
exercising the checkpoint/resume path the recipe requires.

Run (chip): python examples/quality/train_synth_cifar.py
CPU smoke:  ./dev.sh python examples/quality/train_synth_cifar.py \
                --train-n 512 --test-n 256 --epochs 2
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.gluon.functional import functionalize
from mxnet_tpu.gluon.model_zoo.vision.resnet import ResNetV1, BasicBlockV1


def make_dataset(n, seed):
    """Deterministic 32×32 10-class images: class = (orientation, frequency)
    texture + class color mixture, with per-sample phase/brightness jitter
    and strong noise."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32.0
    # class-overlapping parameters: orientation/frequency jitter blurs the
    # class boundaries so the task needs real feature learning and retains
    # irreducible error (a ceiling well below 1.0)
    theta = (y // 5) * (np.pi / 3) + (y % 5) * 0.2 \
        + 0.25 * rng.randn(n).astype(np.float32)
    freq = 2.0 + (y % 5) + 0.6 * rng.randn(n).astype(np.float32)
    phase = rng.rand(n).astype(np.float32) * 2 * np.pi
    carrier = np.sin(
        2 * np.pi * freq[:, None, None]
        * (xx[None] * np.cos(theta)[:, None, None]
           + yy[None] * np.sin(theta)[:, None, None])
        + phase[:, None, None])
    cmat = np.random.RandomState(7).rand(10, 3).astype(np.float32) * 2 - 1
    img = carrier[:, None] * cmat[y][:, :, None, None]  # (n, 3, 32, 32)
    img += 0.5 * rng.randn(n, 1, 1, 1).astype(np.float32)  # brightness jitter
    img += 2.0 * rng.randn(n, 3, 32, 32).astype(np.float32)  # heavy noise
    return img.astype(np.float32), y.astype(np.int32)


def build_resnet20(classes=10):
    """CIFAR ResNet-20: 3 stages × 3 basic blocks, 16/32/64 channels
    (reference symbols/resnet.py cifar branch: (depth-2) % 6 == 0)."""
    net = ResNetV1(BasicBlockV1, [3, 3, 3], [16, 16, 32, 64],
                   classes=classes, thumbnail=True)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, 3, 32, 32)))  # materialize
    return net


def make_step(net, wd=1e-4, momentum=0.9):
    import jax
    import jax.numpy as jnp

    apply, names, vals, aux_names = functionalize(net, train=True)
    aux_set = set(aux_names)
    learn_idx = [i for i, n in enumerate(names) if n not in aux_set]
    aux_idx = [i for i, n in enumerate(names) if n in aux_set]

    def augment(x, key):
        """pad-4 random crop + horizontal flip, on device, per image."""
        B = x.shape[0]
        k1, k2 = jax.random.split(key)
        xp = jnp.pad(x, ((0, 0), (0, 0), (4, 4), (4, 4)))
        off = jax.random.randint(k1, (B, 2), 0, 9)
        flip = jax.random.bernoulli(k2, 0.5, (B,))

        def one(img, o, f):
            c = jax.lax.dynamic_slice(img, (0, o[0], o[1]), (3, 32, 32))
            return jnp.where(f, c[:, :, ::-1], c)

        return jax.vmap(one)(xp, off, flip)

    def loss_fn(learn, aux, x, y, key):
        merged = [None] * len(names)
        for i, v in zip(learn_idx, learn):
            merged[i] = v
        for i, v in zip(aux_idx, aux):
            merged[i] = v
        ka, kf = jax.random.split(key)
        xa = augment(x, ka)
        out, new_aux = apply(merged, xa, kf)
        logp = jax.nn.log_softmax(out, axis=-1)
        ce = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
        return ce, new_aux

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step(state, x, y, lr, key):
        learn, mom, aux = state
        (loss, new_aux), grads = grad_fn(learn, aux, x, y, key)
        # reference sgd_update: grad = grad + wd * weight, then momentum
        mom = [momentum * m + g + wd * p for m, g, p in zip(mom, grads, learn)]
        learn = [p - lr * m for p, m in zip(learn, mom)]
        return (learn, mom, new_aux), loss

    def eval_logits(state, x):
        learn, _mom, aux = state
        merged = [None] * len(names)
        for i, v in zip(learn_idx, learn):
            merged[i] = v
        for i, v in zip(aux_idx, aux):
            merged[i] = v
        ev_apply, *_ = _EVAL_CACHE
        out, _ = ev_apply(merged, x, jax.random.PRNGKey(0))
        return out

    _EVAL_CACHE = functionalize(net, train=False)

    learn = [vals[i] for i in learn_idx]
    aux = [vals[i] for i in aux_idx]
    mom = [np.zeros(np.shape(v), np.float32) for v in learn]
    return step, eval_logits, (learn, mom, aux)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train-n", type=int, default=20000)
    p.add_argument("--test-n", type=int, default=4000)
    p.add_argument("--epochs", type=int, default=24)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--ckpt-dir", default="/tmp/synth_cifar_ckpt")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    mx.random.seed(0)
    Xtr, ytr = make_dataset(args.train_n, seed=1)
    Xte, yte = make_dataset(args.test_n, seed=2)  # held-out stream
    # standardize with train statistics
    mu, sd = Xtr.mean(), Xtr.std()
    Xtr = (Xtr - mu) / sd
    Xte = (Xte - mu) / sd

    net = build_resnet20()
    step, eval_logits, state = make_step(net)
    jstep = jax.jit(step, donate_argnums=(0,))
    jeval = jax.jit(eval_logits)

    dXtr = jax.device_put(Xtr)
    dytr = jax.device_put(ytr)
    dXte = jax.device_put(Xte)

    steps_per_epoch = args.train_n // args.batch
    total_steps = steps_per_epoch * args.epochs
    # reference lr-step-epochs at 50% / 75% of the run, factor 0.1
    bounds = (int(total_steps * 0.5), int(total_steps * 0.75))
    key = jax.random.PRNGKey(0)
    rng = np.random.RandomState(0)

    def epoch_pass(state, epoch, gstep):
        perm = rng.permutation(args.train_n)
        tot = 0.0
        for i in range(steps_per_epoch):
            sel = jnp.asarray(perm[i * args.batch:(i + 1) * args.batch])
            lr = args.lr * (0.1 ** sum(gstep >= b for b in bounds))
            state, loss = jstep(state, dXtr[sel], dytr[sel], lr,
                                jax.random.fold_in(key, gstep))
            tot += 0.0  # loss fetched lazily below
            gstep += 1
        return state, float(loss), gstep

    def test_acc(state):
        preds = []
        for i in range(0, args.test_n, 500):
            preds.append(np.asarray(jeval(state, dXte[i:i + 500])).argmax(1))
        return (np.concatenate(preds) == yte[:len(np.concatenate(preds))]).mean()

    from mxnet_tpu.parallel import checkpoint as ckpt

    gstep = 0
    resume_at = args.epochs // 2
    t0 = time.time()
    for epoch in range(args.epochs):
        state, last_loss, gstep = epoch_pass(state, epoch, gstep)
        print("epoch %2d  loss %.4f  (%.1fs)" % (epoch, last_loss,
                                                 time.time() - t0), flush=True)
        if epoch == resume_at - 1:
            # checkpoint, DROP the live state, and resume from disk — the
            # recipe's save/resume leg through the framework's checkpointer
            ckpt.save(os.path.join(args.ckpt_dir, "mid"), state)
            like = state
            state = None
            state = ckpt.restore(os.path.join(args.ckpt_dir, "mid"), like=like)
            print("checkpoint saved + restored at epoch %d" % epoch, flush=True)

    tr_acc = None
    te_acc = test_acc(state)
    print("FINAL synth-cifar ResNet-20 (recipe: bs%d, sgd m0.9 wd1e-4, "
          "lr %.2f stepped at 50%%/75%%, pad4-crop+flip, ckpt+resume): "
          "TEST acc %.4f  (train_n=%d, test_n=%d, %d epochs)"
          % (args.batch, args.lr, te_acc, args.train_n, args.test_n,
             args.epochs))


if __name__ == "__main__":
    main()
