"""Roofline + batch-scaling measurement for the fused Deformable R-FCN step.

VERDICT round-2 item 1: ResNet-50 got an XLA cost analysis (flops, bytes,
peak temp) that proved it HBM-bound at ~100% of the hand-written ceiling;
the north-star step had nothing.  This script publishes the same numbers
for ``make_rfcn_train_step`` (batch 1..N) so "fast" is judged against the
chip's roofline, not just the 2018 GPU bar.

Usage (on the chip, ambient axon env, from /root/repo):
    python examples/quality/rfcn_roofline.py --batches 1 2 4
    python examples/quality/rfcn_roofline.py --batches 1 --ledger rfcn.jsonl

Prints, per batch size: cost-analysis flops/bytes, the implied MXU/HBM
time bounds (v5e: ~197 bf16 TFLOP/s, ~819 GB/s HBM), measured ms/step and
img/s.  Tunnel rules apply: chained steps with donated state, scalar-only
fetch (docs/PERF_NOTES.md "Tunnel-measurement note").

``--ledger`` records each batch size's executable into a compile-plane
cost ledger (ISSUE 13; it enables ``MXNET_COSTPLANE`` for this process),
so the roofline workflow no longer hand-saves ``cost_analysis()`` JSON:
``tools/trace_summary.py profile.json --ledger rfcn.jsonl`` merges the
measured module totals, and ``tools/bench_compare.py old.jsonl new.jsonl
--gate-cost`` turns a flop/peak regression between two builds into a CI
failure (docs/tutorials/performance.md).
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

V5E_BF16_TFLOPS = 197e12
V5E_HBM_BPS = 819e9


sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "deformable_rfcn"))


def analyze(batch, image_shape, iters, windows, dtype="bfloat16",
            ledger=False):
    import jax

    import mxnet_tpu as mx
    from train_fused import build_net, make_rfcn_train_step, synthetic_coco

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net, shape, classes = build_net(True, image_shape, None)
    data, im_info, gt = synthetic_coco(rng, batch, shape, classes, net.max_gts)
    step, state = make_rfcn_train_step(net, batch, learning_rate=5e-4,
                                       momentum=0.9, compute_dtype=dtype)
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    d = jax.device_put(data)
    i = jax.device_put(im_info)
    g = jax.device_put(gt)

    t0 = time.time()
    lowered = jstep.lower(state, d, i, g, key)
    comp = lowered.compile()
    compile_s = time.time() - t0
    if ledger:
        # compile-plane row (ISSUE 13): the same extraction the library's
        # compile sites use, keyed stably by batch/shape/dtype so two
        # builds' ledgers diff row-for-row in bench_compare --gate-cost
        from mxnet_tpu.telemetry import costplane

        costplane.record_compile(
            "rfcn_train_step",
            ("rfcn_train_step", tuple(image_shape), dtype),
            "batch%d" % batch, comp, compile_s)
    ca = comp.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    flops = float(ca.get("flops", 0.0))
    bytes_acc = float(ca.get("bytes accessed", 0.0))
    peak = None
    try:
        ma = comp.memory_analysis()
        peak = getattr(ma, "temp_size_in_bytes", None)
    except Exception:
        pass

    # timed chained steps on the ALREADY-COMPILED executable (jax's AOT path
    # doesn't seed the jit cache — calling jstep would recompile), state
    # donated, scalar fetch only
    state, loss, parts = comp(state, d, i, g, key)
    jax.block_until_ready(loss)
    best = None
    for w in range(windows):
        keys = [jax.random.fold_in(key, w * 1000 + it) for it in range(iters)]
        jax.block_until_ready(keys[-1])
        t0 = time.perf_counter()
        for it in range(iters):
            state, loss, parts = comp(state, d, i, g, keys[it])
        float(loss)
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)

    mxu_ms = flops / V5E_BF16_TFLOPS * 1e3
    hbm_ms = bytes_acc / V5E_HBM_BPS * 1e3
    return dict(batch=batch, compile_s=compile_s, flops=flops,
                bytes=bytes_acc, peak=peak, mxu_ms=mxu_ms, hbm_ms=hbm_ms,
                ms=best * 1e3, img_s=batch / best, loss=float(loss))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batches", type=int, nargs="+", default=[1, 2])
    p.add_argument("--image-shape", type=int, nargs=2, default=[608, 1024])
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--windows", type=int, default=3)
    p.add_argument("--ledger", default=None,
                   help="record each executable into this compile-plane "
                        "cost ledger (sets MXNET_COSTPLANE/MXNET_COST_"
                        "LEDGER for this process; read it back with "
                        "trace_summary --ledger / bench_compare "
                        "--gate-cost)")
    args = p.parse_args()
    if args.ledger:
        os.environ["MXNET_COSTPLANE"] = "1"
        os.environ["MXNET_COST_LEDGER"] = args.ledger

    rows = []
    for b in args.batches:
        try:
            r = analyze(b, tuple(args.image_shape), args.iters, args.windows,
                        ledger=bool(args.ledger))
        except Exception as exc:  # OOM at larger batches is a finding, not a crash
            print("batch %d FAILED: %r" % (b, exc))
            continue
        rows.append(r)
        print("batch %d: compile %.0fs | %.2f TF, %.1f GB%s | bounds: MXU %.1f ms, "
              "HBM %.1f ms | measured %.1f ms/step = %.2f img/s | loss %.4f"
              % (r["batch"], r["compile_s"], r["flops"] / 1e12, r["bytes"] / 1e9,
                 (", peak temp %.1f GB" % (r["peak"] / 1e9)) if r["peak"] else "",
                 r["mxu_ms"], r["hbm_ms"], r["ms"], r["img_s"], r["loss"]),
              flush=True)
    if rows:
        b1 = rows[0]
        for r in rows[1:]:
            print("scaling: batch %d = %.2fx batch-%d throughput (linear would be %.1fx)"
                  % (r["batch"], r["img_s"] / b1["img_s"], b1["batch"],
                     r["batch"] / b1["batch"]), flush=True)


if __name__ == "__main__":
    main()
