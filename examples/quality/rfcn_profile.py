"""Per-op on-chip trace of the north-star R-FCN train step (VERDICT
round-3 item 3: attribute the gap between the HBM roofline bound and the
measured step).

Runs N profiled steps of the batch-B fused Deformable R-FCN step under
``jax.profiler.trace``, parses the chrome-trace device lane, and prints a
duration-by-kernel-class table: where every microsecond of the step goes.

Run (chip): python examples/quality/rfcn_profile.py --batch 4
Also works for the Faster-RCNN step: --model frcnn
"""
from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import load_module_by_path


# kernel-name → class rules, most specific first (XLA fusion names keep
# the dominant op in the name)
CLASS_RULES = [
    ("sort", r"sort"),
    ("nms/iou (detection)", r"(iou|nms|while)"),
    ("conv (MXU)", r"convolution|conv_general"),
    ("matmul (MXU)", r"dot|einsum"),
    ("scatter/gather", r"scatter|gather|dynamic-slice|dynamic_update"),
    ("reduce/norm", r"reduce|all-reduce"),
    ("copy/layout", r"copy|transpose|bitcast|reshape"),
    ("rng", r"rng|threefry"),
    ("elementwise/other fusion", r"fusion|add|multiply|select"),
]


def classify(name):
    n = name.lower()
    for cls, pat in CLASS_RULES:
        if re.search(pat, n):
            return cls
    return "other"


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--iters", type=int, default=6)
    p.add_argument("--model", default="rfcn", choices=("rfcn", "frcnn"))
    p.add_argument("--image-shape", type=int, nargs=2, default=None)
    p.add_argument("--keep-trace", default=None,
                   help="directory to keep the raw trace in")
    args = p.parse_args()

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    if args.model == "rfcn":
        m = load_module_by_path(
            os.path.join(_HERE, "..", "deformable_rfcn", "train_fused.py"),
            "_rfcn_prof")
        net, shape, classes = m.build_net(on_tpu, args.image_shape)
        step, state = m.make_rfcn_train_step(
            net, args.batch, compute_dtype="bfloat16" if on_tpu else None)
        data, im_info, gt = m.synthetic_coco(
            np.random.RandomState(0), args.batch, shape, classes, net.max_gts)
        sargs = (jax.device_put(data), jax.device_put(im_info),
                 jax.device_put(gt))
    else:
        m = load_module_by_path(
            os.path.join(_HERE, "..", "rcnn", "train_fused.py"),
            "_frcnn_prof")
        net, shape, classes = m.build_net(on_tpu, args.image_shape)
        step, state = m.make_frcnn_train_step(
            net, args.batch, compute_dtype="bfloat16" if on_tpu else None)
        data, im_info, gt = m.synthetic_voc(
            np.random.RandomState(0), args.batch, shape, classes, net.max_gts)
        sargs = (jax.device_put(data), jax.device_put(im_info),
                 jax.device_put(gt))

    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    state, loss, _parts = jstep(state, *sargs, key)  # compile
    jax.block_until_ready(loss)

    tdir = args.keep_trace or tempfile.mkdtemp(prefix="rfcn_prof_")
    keys = [jax.random.fold_in(key, i) for i in range(args.iters)]
    jax.block_until_ready(keys[-1])
    with jax.profiler.trace(tdir):
        for i in range(args.iters):
            state, loss, _parts = jstep(state, *sargs, keys[i])
        float(loss)

    traces = sorted(glob.glob(os.path.join(
        tdir, "plugins", "profile", "*", "*.trace.json.gz")))
    assert traces, "no trace produced under %s" % tdir
    with gzip.open(traces[-1]) as f:
        tr = json.load(f)
    ev = tr.get("traceEvents", [])
    dev_pids = {e["pid"] for e in ev
                if e.get("ph") == "M" and e.get("name") == "process_name"
                and "TPU" in e["args"].get("name", "")}
    on_device_lane = bool(dev_pids)
    if not on_device_lane:
        # CPU backend: no device lane — XLA ops run inside host threads.
        # Keep events that look like XLA kernels (drop Python/runtime ones).
        dev_pids = {e["pid"] for e in ev if e.get("ph") == "X"}
    by_name = collections.Counter()
    for e in ev:
        if e.get("ph") == "X" and e.get("pid") in dev_pids:
            name = e.get("name", "?")
            if name.startswith("jit_"):   # the whole-module envelope event
                continue
            if not on_device_lane and (
                    "$" in name or ".py" in name or name.startswith("Pjit")
                    or classify(name) == "other"):
                continue
            by_name[name] += e.get("dur", 0)

    by_class = collections.Counter()
    for name, dur in by_name.items():
        by_class[classify(name)] += dur
    total = sum(by_class.values())
    per_step = total / args.iters / 1e3
    print("%s batch=%d %s: device busy %.1f ms/step over %d steps"
          % (args.model, args.batch, shape, per_step, args.iters))
    print("%-28s %9s %7s" % ("class", "ms/step", "%"))
    for cls, dur in by_class.most_common():
        print("%-28s %9.2f %6.1f%%"
              % (cls, dur / args.iters / 1e3, 100.0 * dur / total))
    print("\ntop kernels:")
    for name, dur in by_name.most_common(18):
        print("  %8.2f ms/step  %s" % (dur / args.iters / 1e3, name[:110]))
    if not args.keep_trace:
        print("(trace dir: %s)" % tdir)


if __name__ == "__main__":
    main()
