"""SSD detection quality at REAL resolution: mAP on synthetic VOC data.

VERDICT round-3 item 4: the fused SSD path (examples/ssd/train_fused.py)
had throughput at 300²/512² but no quality signal at those shapes — a
target-assignment bug at the real 8,732-anchor menu would ship with green
CI.  This gate trains the REAL SSD-300 geometry (full anchor menu; trunk
width scalable so the CPU nightly can afford it — anchors are
width-independent) on a seeded synthetic-VOC stream and evaluates mAP with
``VOCMApMetric`` over a held-out stream through the fused score step
(softmax + MultiBoxDetection decode + per-class NMS over all anchors).

Quality bar proxied: SSD300 VOC07 mAP 77.8 (`example/ssd/README.md:36-42`;
real VOC unfetchable — see QUALITY.md honest framing).

Run (chip):      python examples/quality/eval_ssd_map.py --full
Run (CPU smoke): ./dev.sh python examples/quality/eval_ssd_map.py --steps 30 --eval-images 20
"""
from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import load_module_by_path


def _load(name, *relpath):
    return load_module_by_path(os.path.join(_HERE, "..", *relpath), name)


_ssd_metric = _load("_ssd_metric_gate", "ssd", "metric.py")
_fused = _load("_ssd_train_fused_gate", "ssd", "train_fused.py")
_vgg = _load("_vgg_ssd_gate", "ssd", "vgg_ssd.py")
VOCMApMetric = _ssd_metric.VOCMApMetric
make_ssd_train_step = _fused.make_ssd_train_step
make_score_step = _fused.make_score_step
synthetic_voc = _fused.synthetic_voc
_merge_vals = _fused._merge_vals


def synthetic_voc_device(key, batch, size, classes, max_gts=8):
    """``synthetic_voc`` generated ON DEVICE (all jnp, call inside jit):
    same construction — noise canvas, 1..4 rectangles of 0.1-0.5 relative
    size painted +0.8 onto channel cls%3, gt [cls, x1..y2] in [0,1],
    -1-padded — but zero host work / zero H2D over the tunnel."""
    import jax
    import jax.numpy as jnp

    kn, kg, kc, kw, kh, kx, ky = jax.random.split(key, 7)
    data = jax.random.uniform(kn, (batch, 3, size, size), jnp.float32) * 0.2
    n_boxes = jax.random.randint(kg, (batch,), 1, 5)
    cls = jax.random.randint(kc, (batch, max_gts), 0, classes)
    bw = jax.random.uniform(kw, (batch, max_gts)) * 0.4 + 0.1
    bh = jax.random.uniform(kh, (batch, max_gts)) * 0.4 + 0.1
    x1 = jax.random.uniform(kx, (batch, max_gts)) * (1.0 - bw)
    y1 = jax.random.uniform(ky, (batch, max_gts)) * (1.0 - bh)
    valid = jnp.arange(max_gts)[None, :] < n_boxes[:, None]
    gt = jnp.where(
        valid[..., None],
        jnp.stack([cls.astype(jnp.float32), x1, y1, x1 + bw, y1 + bh], -1),
        -1.0)
    yy = jnp.arange(size, dtype=jnp.float32)[:, None] / size
    xx = jnp.arange(size, dtype=jnp.float32)[None, :] / size
    chan = jax.nn.one_hot(cls % 3, 3)

    def paint(g, img):
        m = ((yy >= y1[:, g, None, None]) & (yy < (y1 + bh)[:, g, None, None])
             & (xx >= x1[:, g, None, None]) & (xx < (x1 + bw)[:, g, None, None])
             & valid[:, g, None, None])
        return img + 0.8 * m[:, None] * chan[:, g, :, None, None]

    data = jax.lax.fori_loop(0, max_gts, paint, data)
    return data, gt


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--full", action="store_true",
                   help="full-width trunk (chip); default width=0.25 (CPU)")
    p.add_argument("--size", type=int, default=300, choices=(300, 512))
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--eval-images", type=int, default=500)
    p.add_argument("--classes", type=int, default=3)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--map-floor", type=float, default=None,
                   help="exit 1 if final mAP falls below this (CI tier)")
    p.add_argument("--host-data", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    import jax

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    steps = args.steps or (2000 if args.full else 600)
    width = 1.0 if args.full else 0.25

    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    cfg = _vgg.SSD300 if args.size == 300 else _vgg.SSD512
    net = _vgg.VGGSSD(args.classes, cfg, width=width)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, 3, args.size, args.size)))
    anchors = net.make_anchors(args.size)
    print("ssd%d gate: width=%.2f, %d anchors (the real menu), %d steps"
          % (args.size, width, len(anchors), steps), flush=True)
    assert len(anchors) == (8732 if args.size == 300 else 24564), \
        "anchor menu drifted from the reference count"

    step, state = make_ssd_train_step(
        net, anchors, args.batch, learning_rate=args.lr, momentum=0.9,
        compute_dtype="bfloat16" if (on_tpu and args.full) else None)
    key = jax.random.PRNGKey(args.seed)
    use_device_data = on_tpu and not args.host_data

    if use_device_data:
        def step_with_data(st, sidx, lr_v):
            kd, ks = jax.random.split(jax.random.fold_in(key, sidx))
            data, gt = synthetic_voc_device(kd, args.batch, args.size,
                                            args.classes)
            return step(st, data, gt, ks, lr_v)

        jstep_dev = jax.jit(step_with_data, donate_argnums=(0,))
    else:
        jstep = jax.jit(step, donate_argnums=(0,))

    # linear warmup then step decay: from-scratch SSD is warmup-sensitive —
    # without it the hard-negative-mining cold start collapses some seeds
    # (chip calibration measured 0.35 vs 0.90 across seeds pre-warmup)
    decay_points = {int(steps * 0.6), int(steps * 0.85)}
    warmup = max(1, steps // 10)
    lr = args.lr
    for s in range(steps):
        if s in decay_points:
            lr *= 0.1
            print("lr -> %g at step %d" % (lr, s), flush=True)
        lr_t = lr * min(1.0, (s + 1) / warmup)
        if use_device_data:
            state, loss, parts = jstep_dev(state, np.int32(s),
                                           np.float32(lr_t))
        else:
            data, gt = synthetic_voc(rng, args.batch, args.size, args.classes)
            state, loss, parts = jstep(state, data, gt,
                                       jax.random.fold_in(key, s),
                                       np.float32(lr_t))
        if s % max(1, steps // 8) == 0:
            print("step %4d  loss %.4f" % (s, float(loss)), flush=True)

    # --- evaluation through the fused score step -------------------------
    score, _fresh = make_score_step(net, anchors)
    jscore = jax.jit(score)
    svals = [jax.device_put(v) for v in _merge_vals(net, state)]
    metric = VOCMApMetric(iou_thresh=0.5)
    eval_rng = np.random.RandomState(12345)
    if use_device_data:
        ekey = jax.random.PRNGKey(54321)
        gen = jax.jit(lambda i: synthetic_voc_device(
            jax.random.fold_in(ekey, i), 1, args.size, args.classes))
    for _i in range(args.eval_images):
        if use_device_data:
            data, gt = gen(np.int32(_i))
            gt = np.asarray(gt)
        else:
            data, gt = synthetic_voc(eval_rng, 1, args.size, args.classes)
        dets = np.asarray(jscore(svals, data, key))
        metric.update(dets, gt[:, :, :5])
    name, value = metric.get()
    print("FINAL ssd%d %s synthetic-VOC %s = %.4f  (steps=%d, classes=%d, "
          "eval n=%d, %d anchors)"
          % (args.size, "full" if args.full else "w%.2f" % width, name,
             value, steps, args.classes, args.eval_images, len(anchors)))
    if args.map_floor is not None and value < args.map_floor:
        print("FAIL: mAP %.4f below floor %.4f" % (value, args.map_floor))
        sys.exit(1)


if __name__ == "__main__":
    main()
