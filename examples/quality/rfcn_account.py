"""Per-execution roofline accounting of the fused detection train step.

VERDICT round-4 item 1: the batch-8 north star measured 235 ms against a
188.9 ms "naive" HBM bound (80%) computed from the compiled module's
aggregate cost analysis — and that bound is wrong in BOTH directions:

* ``while`` bodies are counted ONCE by ``Compiled.cost_analysis()``, not
  once per trip (the pooling/deformable scans run NB=49 iterations), so
  the naive bound UNDERcounts loop bytes;
* fusion operands that stay resident in VMEM across the fusion boundary
  are counted as HBM traffic, so it OVERcounts streamed bytes (the
  round-4 "A-matrix never re-read" explanation — visible in the trace as
  loop fusions with apparent bandwidth ABOVE the 819 GB/s HBM peak).

This tool replaces that aggregate with a per-execution accounting built
from the device trace itself: every "XLA Ops" event carries XLA's
per-instruction ``bytes_accessed`` and ``model_flops``, so summing over
*leaf* events (envelope events like the scan ``while`` contain their body
events — interval containment on the lane gives the nesting) counts each
loop iteration exactly once at instruction granularity.  Reported:

* module wall per step ("XLA Modules" lane — the true device time);
* leaf-sum ms (≈ wall when the TensorCore runs ops serially — a check
  that the attribution covers 100% of the step);
* corrected HBM/MXU bounds and the **per-op serial roofline**
  Σ max(bytes/BW_peak, flops/MXU_peak) — the defended bound;
* a ms-by-ms table by HLO category with achieved bandwidth.

Run (chip): python examples/quality/rfcn_account.py --batch 8
Also: --model frcnn, --batches 1 4 8 for a scaling table.
"""
from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys
import tempfile
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np

V5E_HBM_BPS = 819e9
V5E_BF16_FLOPS = 197e12


def build_step(model, batch, image_shape):
    import jax

    from mxnet_tpu.test_utils import load_module_by_path

    on_tpu = jax.devices()[0].platform == "tpu"
    if model == "rfcn":
        m = load_module_by_path(
            os.path.join(_HERE, "..", "deformable_rfcn", "train_fused.py"),
            "_rfcn_acct")
        net, shape, classes = m.build_net(on_tpu, image_shape)
        step, state = m.make_rfcn_train_step(
            net, batch, compute_dtype="bfloat16" if on_tpu else None)
        data, im_info, gt = m.synthetic_coco(
            np.random.RandomState(0), batch, shape, classes, net.max_gts)
    else:
        m = load_module_by_path(
            os.path.join(_HERE, "..", "rcnn", "train_fused.py"), "_frcnn_acct")
        net, shape, classes = m.build_net(on_tpu, image_shape)
        step, state = m.make_frcnn_train_step(
            net, batch, compute_dtype="bfloat16" if on_tpu else None)
        data, im_info, gt = m.synthetic_voc(
            np.random.RandomState(0), batch, shape, classes, net.max_gts)
    sargs = (jax.device_put(data), jax.device_put(im_info),
             jax.device_put(gt))
    return step, state, sargs, shape


def parse_trace(tdir, iters):
    traces = sorted(glob.glob(os.path.join(
        tdir, "plugins", "profile", "*", "*.trace.json.gz")))
    assert traces, "no trace under %s" % tdir
    with gzip.open(traces[-1]) as f:
        tr = json.load(f)
    ev = tr.get("traceEvents", [])
    tidname = {}
    for e in ev:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tidname[(e["pid"], e.get("tid"))] = e["args"].get("name", "")

    def lane(name):
        return [e for e in ev if e.get("ph") == "X"
                and tidname.get((e["pid"], e.get("tid"))) == name]

    mods = lane("XLA Modules")
    ops = lane("XLA Ops")
    if not mods:   # CPU backend — no device lanes; tool is chip-only
        raise SystemExit("no device lane in trace (run on the chip)")
    # normalize EVERYTHING by the module executions actually captured —
    # a dropped/extra launch in the profiler window would otherwise skew
    # the leaf-sum-vs-wall identity the report certifies
    if len(mods) != iters:
        print("note: trace captured %d module executions (requested %d); "
              "normalizing by %d" % (len(mods), iters, len(mods)))
    iters = len(mods)
    wall_ms = sum(e["dur"] for e in mods) / len(mods) / 1e3

    # nesting by interval containment on the single ops lane: an event
    # whose [ts, ts+dur) contains later events is an envelope (scan/while);
    # only LEAVES carry real instruction cost exactly once per execution
    ops.sort(key=lambda e: (e["ts"], -e["dur"]))
    stack, has_child = [], set()
    for i, e in enumerate(ops):
        while stack and (ops[stack[-1]]["ts"] + ops[stack[-1]]["dur"]
                         <= e["ts"] + 1e-9):
            stack.pop()
        if stack:
            has_child.add(stack[-1])
        stack.append(i)

    cat = collections.defaultdict(lambda: [0.0, 0.0, 0.0])  # dur, bytes, flops
    tot = [0.0, 0.0, 0.0]
    serial_us = 0.0
    for i, e in enumerate(ops):
        if i in has_child:
            continue
        a = e.get("args", {})
        b = float(a.get("bytes_accessed", 0) or 0)
        f = float(a.get("model_flops", 0) or 0)
        d = e["dur"]
        c = cat[a.get("hlo_category", "?")]
        c[0] += d; c[1] += b; c[2] += f
        tot[0] += d; tot[1] += b; tot[2] += f
        serial_us += max(b / V5E_HBM_BPS * 1e6, f / V5E_BF16_FLOPS * 1e6)
    n = float(iters)
    return dict(
        wall_ms=wall_ms,
        leaf_ms=tot[0] / n / 1e3,
        bytes_gb=tot[1] / n / 1e9,
        flops_tf=tot[2] / n / 1e12,
        hbm_ms=tot[1] / n / V5E_HBM_BPS * 1e3,
        mxu_ms=tot[2] / n / V5E_BF16_FLOPS * 1e3,
        serial_ms=serial_us / n / 1e3,
        cats={k: (v[0] / n / 1e3, v[1] / n / 1e9, v[2] / n / 1e12)
              for k, v in cat.items()},
    )


def run_one(model, batch, image_shape, iters, keep_trace):
    import jax

    step, state, sargs, shape = build_step(model, batch, image_shape)
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    t0 = time.time()
    lowered = jstep.lower(state, *sargs, key)
    comp = lowered.compile()
    compile_s = time.time() - t0
    ca = comp.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    naive_gb = float(ca.get("bytes accessed", 0.0)) / 1e9
    naive_tf = float(ca.get("flops", 0.0)) / 1e12

    state, loss, _ = comp(state, *sargs, key)
    jax.block_until_ready(loss)
    # measured wall: chained steps, donated state, scalar fetch (tunnel rules)
    keys = [jax.random.fold_in(key, i) for i in range(iters)]
    jax.block_until_ready(keys[-1])
    t0 = time.perf_counter()
    for k in keys:
        state, loss, _ = comp(state, *sargs, k)
    float(loss)
    meas_ms = (time.perf_counter() - t0) / iters * 1e3

    tdir = keep_trace or tempfile.mkdtemp(prefix="acct_%s_b%d_" % (model, batch))
    keys = [jax.random.fold_in(key, 100 + i) for i in range(iters)]
    jax.block_until_ready(keys[-1])
    with jax.profiler.trace(tdir):
        for k in keys:
            state, loss, _ = comp(state, *sargs, k)
        float(loss)
    r = parse_trace(tdir, iters)
    if not keep_trace:     # 6-step device traces run to hundreds of MB
        import shutil

        shutil.rmtree(tdir, ignore_errors=True)
        tdir = None
    r.update(model=model, batch=batch, shape=shape, compile_s=compile_s,
             naive_gb=naive_gb, naive_tf=naive_tf, meas_ms=meas_ms,
             naive_hbm_ms=naive_gb * 1e9 / V5E_HBM_BPS * 1e3, trace=tdir)
    return r


def report(r):
    print("\n== %s batch=%d %s (compile %.0fs) ==" %
          (r["model"], r["batch"], r["shape"], r["compile_s"]))
    print("measured %.1f ms/step (%.2f img/s) | module wall %.1f ms | "
          "host/dispatch %.1f ms" %
          (r["meas_ms"], r["batch"] / r["meas_ms"] * 1e3, r["wall_ms"],
           r["meas_ms"] - r["wall_ms"]))
    print("naive module cost analysis: %.1f GB, %.2f TF -> HBM bound %.1f ms "
          "(while bodies x1, VMEM residents counted)" %
          (r["naive_gb"], r["naive_tf"], r["naive_hbm_ms"]))
    print("per-execution leaves: %.1f GB, %.2f TF | leaf-sum %.1f ms "
          "(%.0f%% of wall -> serial TensorCore, full coverage)" %
          (r["bytes_gb"], r["flops_tf"], r["leaf_ms"],
           100.0 * r["leaf_ms"] / r["wall_ms"]))
    print("corrected bounds: HBM %.1f ms, MXU %.1f ms | per-op serial "
          "roofline %.1f ms | wall = %.0f%% of serial roofline" %
          (r["hbm_ms"], r["mxu_ms"], r["serial_ms"],
           100.0 * r["wall_ms"] / r["serial_ms"]))
    if r.get("trace"):
        print("trace kept at: %s" % r["trace"])
    print("%-24s %8s %8s %9s %8s %9s" %
          ("category", "ms/step", "GB/step", "GB/s", "TF/step", "bound ms"))
    for k, (d, b, f) in sorted(r["cats"].items(), key=lambda kv: -kv[1][0]):
        if d < 0.05:
            continue
        bound = max(b * 1e9 / V5E_HBM_BPS, f * 1e12 / V5E_BF16_FLOPS) * 1e3
        print("%-24s %8.2f %8.2f %9.0f %8.3f %9.2f" %
              (k, d, b, b / d * 1e3 if d else 0, f, bound))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="rfcn", choices=("rfcn", "frcnn"))
    p.add_argument("--batches", type=int, nargs="+", default=[8])
    p.add_argument("--image-shape", type=int, nargs=2, default=None)
    p.add_argument("--iters", type=int, default=6)
    p.add_argument("--keep-trace", default=None)
    args = p.parse_args()
    for b in args.batches:
        r = run_one(args.model, b, args.image_shape and tuple(args.image_shape),
                    args.iters, args.keep_trace)
        report(r)


if __name__ == "__main__":
    main()
