"""REAL-data accuracy: handwritten digits (sklearn.datasets.load_digits).

The environment has no network egress, so ImageNet/CIFAR/VOC can't be
fetched (BASELINE.md bars).  ``load_digits`` ships real 8×8 handwritten
digit images (1,797 samples, 10 classes) inside scikit-learn — the one
genuine real-image dataset available — so this run gives a measured,
non-synthetic accuracy point: a LeNet-style CNN (reference
``example/image-classification/symbols/lenet.py`` family) trained with the
framework's gluon path to a held-out test accuracy.

Run: ./dev.sh python examples/quality/train_digits.py  (CPU, ~1 min)
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def main(epochs=40, batch=64, lr=0.1, seed=0):
    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split

    mx.random.seed(seed)
    np.random.seed(seed)
    X, y = load_digits(return_X_y=True)
    X = (X.astype(np.float32) / 16.0).reshape(-1, 1, 8, 8)
    y = y.astype(np.float32)
    Xtr, Xte, ytr, yte = train_test_split(
        X, y, test_size=0.25, random_state=seed, stratify=y)

    net = mx.gluon.nn.HybridSequential()
    net.add(
        mx.gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
        mx.gluon.nn.MaxPool2D(2, 2),
        mx.gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
        mx.gluon.nn.MaxPool2D(2, 2),
        mx.gluon.nn.Flatten(),
        mx.gluon.nn.Dense(64, activation="relu"),
        mx.gluon.nn.Dense(10),
    )
    net.initialize(mx.init.Xavier())
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": lr, "momentum": 0.9, "wd": 1e-4})
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    n = Xtr.shape[0]
    for epoch in range(epochs):
        perm = np.random.permutation(n)
        tot = 0.0
        for i in range(0, n - batch + 1, batch):
            sel = perm[i:i + batch]
            with autograd.record():
                out = net(nd.array(Xtr[sel]))
                loss = loss_fn(out, nd.array(ytr[sel]))
            loss.backward()
            trainer.step(batch)
            tot += float(loss.mean().asnumpy())
        if epoch % 10 == 9:
            acc = (net(nd.array(Xte)).asnumpy().argmax(1) == yte).mean()
            print("epoch %2d  loss %.4f  test acc %.4f"
                  % (epoch, tot / (n // batch), acc), flush=True)

    train_acc = (net(nd.array(Xtr)).asnumpy().argmax(1) == ytr).mean()
    test_acc = (net(nd.array(Xte)).asnumpy().argmax(1) == yte).mean()
    print("FINAL digits: train acc %.4f  TEST acc %.4f  (n_test=%d)"
          % (train_acc, test_acc, len(yte)))
    return test_acc


if __name__ == "__main__":
    main()
