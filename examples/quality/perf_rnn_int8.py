"""Chip perf for the two perf-motivated non-detection op families
(VERDICT r4 item 3): both had correctness evidence but no chip numbers,
while the reference treats both as *performance* features.

(a) **Fused RNN** — the reference justifies its fused RNN op by kernel
    fusion (``src/operator/rnn-inl.h``, cuDNN ``cudnn_rnn-inl.h``): one
    call instead of per-step ops.  Here the fused op is ``ops/rnn.py``'s
    single ``lax.scan`` per layer with the input projection hoisted into
    one big MXU matmul; the baseline is the same cell math traced
    UNROLLED with a per-step input projection — the shape a user gets
    from ``rnn_cell.LSTMCell().unroll`` (the reference's non-fused path).
    Measured: LSTM LM train-step tokens/s (embed 512 → 2×LSTM(512) →
    vocab-10k softmax, batch 32, seq 64).

(b) **INT8 quantization** — the whole point of
    ``example/quantization`` in the reference is measured speedup.
    Measured: ResNet-50 (symbol zoo) batch-32 scoring img/s — fp32 vs
    bf16 vs the int8 graph produced by ``contrib.quantization
    .quantize_model`` (naive calibration) — plus the accuracy-delta
    protocol of ``examples/quantization/quantize_model.py`` for the
    quality side.

Tunnel rules (docs/PERF_NOTES.md): chained executions, one scalar fetch
at the end bounds the serial device queue; best-of-windows.

Run (chip): python examples/quality/perf_rnn_int8.py [--which rnn|int8]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np

import mxnet_tpu as mx


# ---------------------------------------------------------------------------
# (a) fused vs unrolled LSTM LM
# ---------------------------------------------------------------------------


def bench_rnn(batch=32, seq=64, vocab=10000, embed=512, hidden=512,
              layers=2, iters=20, windows=3, dtype="float32"):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.rnn import rnn as fused_rnn
    from mxnet_tpu.ops.rnn import _step_fn, _unpack_params, rnn_param_size

    dt = jnp.dtype(dtype)
    rng = np.random.RandomState(0)
    psize = rnn_param_size("lstm", embed, hidden, layers, False)
    params = dict(
        emb=jnp.asarray(rng.randn(vocab, embed).astype(np.float32) * 0.02, dt),
        rnn=jnp.asarray(rng.randn(psize).astype(np.float32) * 0.05, dt),
        wo=jnp.asarray(rng.randn(hidden, vocab).astype(np.float32) * 0.02, dt),
        bo=jnp.zeros((vocab,), dt),
    )
    tokens = jnp.asarray(rng.randint(0, vocab, (batch, seq + 1)))

    def unrolled_rnn(x, rnn_p):
        """Same cell math, traced unrolled with per-step projection — the
        op-per-step shape of the reference's non-fused cell path."""
        lp = _unpack_params(rnn_p, "lstm", embed, hidden, layers, 1)
        step = _step_fn("lstm", hidden)
        for layer in range(layers):
            wi, wh, bi, bh = lp[layer]
            carry = (jnp.zeros((batch, hidden), x.dtype),
                     jnp.zeros((batch, hidden), x.dtype))
            ys = []
            for t in range(x.shape[0]):
                xg = x[t] @ wi.T + bi
                carry, y = step(carry, xg, wh, bh)
                ys.append(y)
            x = jnp.stack(ys)
        return x

    def make_step(fused):
        def loss_fn(p, tokens):
            x = p["emb"][tokens[:, :-1]]          # (B, T, E)
            xs = x.transpose(1, 0, 2)             # (T, B, E) sequence-major
            if fused:
                z = jnp.zeros((layers, batch, hidden), xs.dtype)
                out, _h, _c = fused_rnn(xs, p["rnn"], z, z,
                                        state_size=hidden, num_layers=layers)
            else:
                out = unrolled_rnn(xs, p["rnn"])
            logits = out.reshape(seq * batch, hidden) @ p["wo"] + p["bo"]
            labels = tokens[:, 1:].T.reshape(-1)
            lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
            ll = jnp.take_along_axis(
                logits.astype(jnp.float32), labels[:, None], axis=1)[:, 0]
            return jnp.mean(lse - ll)

        def step(p, tokens):
            loss, g = jax.value_and_grad(loss_fn)(p, tokens)
            return {k: v - 1e-3 * g[k].astype(v.dtype) for k, v in p.items()}, loss

        return step

    results = {}
    for name, fused in (("fused(scan)", True), ("unrolled", False)):
        step = jax.jit(make_step(fused), donate_argnums=(0,))
        t0 = time.time()
        p = jax.tree_util.tree_map(jnp.copy, params)
        p, loss = step(p, tokens)
        jax.block_until_ready(loss)
        compile_s = time.time() - t0
        best = None
        for w in range(windows):
            t0 = time.perf_counter()
            for _ in range(iters):
                p, loss = step(p, tokens)
            float(loss)
            dt_s = (time.perf_counter() - t0) / iters
            best = dt_s if best is None else min(best, dt_s)
        toks = batch * seq / best
        results[name] = toks
        print("rnn %-13s compile %5.1fs  %7.2f ms/step  %9.0f tokens/s  "
              "loss %.3f" % (name, compile_s, best * 1e3, toks, float(loss)),
              flush=True)
    print("rnn fused/unrolled speedup: %.2fx"
          % (results["fused(scan)"] / results["unrolled"]), flush=True)
    return results


# ---------------------------------------------------------------------------
# (b) int8 vs bf16/fp32 ResNet-50 scoring
# ---------------------------------------------------------------------------


def _score_executor(exe, batch, iters, windows):
    """N serial forwards + ONE scalar fetch: executions serialize on the
    core, so the final fetch bounds the whole queue (tunnel rules)."""
    best = None
    for w in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = exe.forward(is_train=False)
        float(out[0].sum().asnumpy())
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    return batch / best, best


def bench_int8(batch=32, iters=20, windows=3):
    sys.path.insert(0, os.path.join(_HERE, "..", "image-classification"))
    from importlib import import_module

    from mxnet_tpu import nd
    from mxnet_tpu.contrib.quantization import quantize_model
    from mxnet_tpu.io import NDArrayIter

    resnet = import_module("symbols.resnet")
    sym = resnet.get_symbol(num_classes=1000, num_layers=50,
                            image_shape="3,224,224")
    shape = (batch, 3, 224, 224)
    rng = np.random.RandomState(0)
    x = rng.rand(*shape).astype(np.float32)

    results = {}
    for dtype in ("float32", "bfloat16"):
        exe = sym.simple_bind(grad_req="null", data=shape,
                              type_dict={n: dtype for n in sym.list_arguments()})
        for k, v in exe.arg_dict.items():
            if k == "data":
                v[:] = x
            elif k.endswith("weight") or k.endswith("gamma"):
                v[:] = rng.randn(*v.shape).astype(np.float32) * 0.05
        t0 = time.time()
        exe.forward(is_train=False)
        compile_s = time.time() - t0
        ips, ms = _score_executor(exe, batch, iters, windows)
        results[dtype] = ips
        print("resnet50 score %-9s compile %5.1fs  %6.1f ms/batch  %8.1f img/s"
              % (dtype, compile_s, ms * 1e3, ips), flush=True)

    # int8 graph (naive calibration over one batch)
    args_p = {k: nd.array(rng.randn(*v.shape).astype(np.float32) * 0.05)
              for k, v in exe.arg_dict.items() if k != "data"}
    aux_p = {k: nd.array(np.abs(rng.randn(*v.shape)).astype(np.float32) * 0.01 + 1)
             for k, v in exe.aux_dict.items()}
    t0 = time.time()
    qsym, qargs, qaux = quantize_model(
        sym, args_p, aux_p, calib_mode="naive",
        calib_data=NDArrayIter(x, np.zeros(batch, np.float32), batch),
        num_calib_examples=batch)
    print("quantize_model (naive calib): %.1fs" % (time.time() - t0), flush=True)
    qexe = qsym.simple_bind(grad_req="null", data=shape)
    for k, v in qargs.items():
        if k in qexe.arg_dict:
            qexe.arg_dict[k][:] = v.asnumpy()
    for k, v in qaux.items():
        if k in qexe.aux_dict:
            qexe.aux_dict[k][:] = v.asnumpy()
    qexe.arg_dict["data"][:] = x
    t0 = time.time()
    qexe.forward(is_train=False)
    compile_s = time.time() - t0
    ips, ms = _score_executor(qexe, batch, iters, windows)
    results["int8"] = ips
    print("resnet50 score %-9s compile %5.1fs  %6.1f ms/batch  %8.1f img/s"
          % ("int8", compile_s, ms * 1e3, ips), flush=True)
    print("int8 vs bf16: %.2fx, vs fp32: %.2fx"
          % (results["int8"] / results["bfloat16"],
             results["int8"] / results["float32"]), flush=True)
    return results


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--which", choices=("rnn", "int8", "both"), default="both")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq", type=int, default=64,
                   help="RNN sequence length (PERF_NOTES reports 64 and 256)")
    args = p.parse_args()
    if args.which in ("rnn", "both"):
        bench_rnn(batch=args.batch, seq=args.seq, iters=args.iters)
    if args.which in ("int8", "both"):
        bench_int8(batch=args.batch, iters=args.iters)


if __name__ == "__main__":
    main()
