"""Training-memory cost experiment — reference
``example/memcost/{inception_memcost.py,Makefile,README.md}``.

The reference measures an Inception-BN's training memory under the graph
planner's knobs (``MXNET_BACKWARD_DO_MIRROR=1``, NNVM memory sharing) and
reports device-memory numbers per setting.  TPU-native: XLA owns the
memory plan, and the mirror knob maps to rematerialisation
(``Block.set_remat`` ≡ ``jax.checkpoint``, see docs/ENV_VARS.md) — so the
experiment compiles the SAME fused train step with and without remat and
reads the planner's own peak-temporary number from the compiled module's
memory analysis.

Run: ./dev.sh python examples/memcost/inception_memcost.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.functional import make_train_step


class ConvFactory(gluon.HybridBlock):
    """conv → BN → relu (inception_memcost.py ConvFactory)."""

    def __init__(self, num_filter, kernel, stride=1, pad=0, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.conv = nn.Conv2D(num_filter, kernel, stride, pad,
                                  use_bias=False)
            self.bn = nn.BatchNorm()

    def hybrid_forward(self, F, x):
        return F.Activation(self.bn(self.conv(x)), act_type="relu")


class InceptionA(gluon.HybridBlock):
    """4-branch inception unit (inception_memcost.py InceptionFactoryA)."""

    def __init__(self, n1, n3r, n3, nd3r, nd3, proj, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.c1 = ConvFactory(n1, 1)
            self.c3r = ConvFactory(n3r, 1)
            self.c3 = ConvFactory(n3, 3, pad=1)
            self.cd3r = ConvFactory(nd3r, 1)
            self.cd3a = ConvFactory(nd3, 3, pad=1)
            self.cd3b = ConvFactory(nd3, 3, pad=1)
            self.proj = ConvFactory(proj, 1)

    def hybrid_forward(self, F, x):
        pool = F.Pooling(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                         pool_type="avg")
        return F.concat(self.c1(x), self.c3(self.c3r(x)),
                        self.cd3b(self.cd3a(self.cd3r(x))),
                        self.proj(pool), dim=1)


def build_inception(classes=10):
    net = nn.HybridSequential(prefix="incep_")
    with net.name_scope():
        net.add(ConvFactory(32, 3, stride=2, pad=1),
                InceptionA(16, 16, 32, 16, 24, 16),
                InceptionA(24, 24, 48, 24, 32, 24),
                nn.GlobalAvgPool2D(), nn.Flatten(), nn.Dense(classes))
    return net


def measure(remat, batch=32, image=64):
    """Compile the fused train step; return (flops, peak device bytes).

    Peak bytes come from the live device allocator on TPU
    (``memory_stats()['peak_bytes_in_use']`` after one real step — the
    number the reference's nvidia-smi methodology corresponds to); the CPU
    backend exposes no allocator stats, so there the compute side of the
    trade (recompute flops) is the measurable quantity.
    """
    import jax

    mx.random.seed(0)
    net = build_inception()
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, 3, image, image)))  # materialize deferred shapes
    if remat:
        # per-STAGE remat, as the reference mirrors per-node: checkpointing
        # the whole net would just replay the full forward in backward and
        # save nothing — each checkpointed stage stores only its boundary
        for stage in net:
            stage.set_remat(True)
    step, state, _meta = make_train_step(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), learning_rate=0.05)
    rng = np.random.RandomState(0)
    x = rng.randn(batch, 3, image, image).astype(np.float32)
    y = rng.randint(0, 10, batch).astype(np.float32)
    key = jax.random.PRNGKey(0)
    compiled = jax.jit(step).lower(state, x, y, key).compile()
    ca = compiled.cost_analysis()
    flops = int((ca[0] if isinstance(ca, list) else ca)["flops"])
    dev = jax.devices()[0]
    peak = None
    if dev.platform == "tpu":
        jax.block_until_ready(compiled(state, x, y, key))
        stats = dev.memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
    return flops, peak


def main():
    f0, m0 = measure(remat=False)
    f1, m1 = measure(remat=True)
    fmt_m = lambda m: ("%.1f MB" % (m / 2**20)) if m else "n/a (CPU)"
    print("| setting | train-step flops | peak device bytes |")
    print("|---|---|---|")
    print("| plain backward | %.2f G | %s |" % (f0 / 1e9, fmt_m(m0)))
    print("| remat (≡ MXNET_BACKWARD_DO_MIRROR) | %.2f G | %s |"
          % (f1 / 1e9, fmt_m(m1)))
    print("mirror recomputes %.0f%% extra flops to drop saved activations"
          % (100 * (f1 / f0 - 1)))
    assert f1 > f0, "remat did not engage"
    return (f0, m0), (f1, m1)


if __name__ == "__main__":
    main()
