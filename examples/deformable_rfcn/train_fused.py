"""Deformable R-FCN (ResNet-101) — the north-star workload, jit-fused.

The reference fork exists to run this model (``/root/reference/README.md:1-7``);
its published throughput (~3.8 img/s on a K40, external Deformable-ConvNets
repo) is the BASELINE north-star bar.  Round 1 lost to it because the
detection step was eager + host-synced (host numpy proposal targets).  This
driver compiles the ENTIRE train step — ResNet-101 backbone, RPN,
MultiProposal, on-device anchor/proposal targets, deformable PS-ROI heads,
all four losses, and momentum SGD — into ONE XLA module, exactly like the
classification path's ``make_train_step`` (mxnet_tpu/gluon/functional.py).

Usage:
  python examples/deformable_rfcn/train_fused.py               # tiny CPU run
  python examples/deformable_rfcn/train_fused.py --resnet101 --bench \
      --image-shape 608 1024         # north-star measurement on the chip
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.gluon.functional import functionalize
from mxnet_tpu.gluon.model_zoo.detection import DeformableRFCN, rfcn_resnet101


def synthetic_coco(rng, batch, image_shape, classes, max_gts):
    """One synthetic COCO-scale batch: bright rectangles on noise.

    Returns (data (B,3,H,W), im_info (B,3), gt (B,G,5) [-1-padded])."""
    h, w = image_shape
    data = (rng.rand(batch, 3, h, w) * 0.2).astype(np.float32)
    gt = np.full((batch, max_gts, 5), -1.0, np.float32)
    for b in range(batch):
        for j in range(rng.randint(1, min(max_gts, 8) + 1)):
            cls = rng.randint(0, classes)
            bw = rng.uniform(0.08, 0.5) * w
            bh = rng.uniform(0.08, 0.5) * h
            x1 = rng.uniform(0, w - bw)
            y1 = rng.uniform(0, h - bh)
            gt[b, j] = [cls, x1, y1, x1 + bw, y1 + bh]
            data[b, cls % 3, int(y1):int(y1 + bh), int(x1):int(x1 + bw)] += 0.8
    im_info = np.tile(np.array([h, w, 1.0], np.float32), (batch, 1))
    return data, im_info, gt


def synthetic_coco_device(key, batch, image_shape, classes, max_gts):
    """``synthetic_coco`` generated ON DEVICE from a PRNG key (all jnp; call
    inside jit).  Same construction — noise canvas, 1..min(G,8) rectangles
    of 0.08-0.5 relative size painted +0.8 onto channel ``cls % 3``, raw
    float coords in gt, -1 padding — but zero host work and zero H2D: over
    the tunnel, host-side generation costs ~0.6 s/step of transfer (a 608
    x1024 batch is 7.5 MB at ~15 MB/s) vs ~10 ms dispatch for this path."""
    import jax
    import jax.numpy as jnp

    h, w = image_shape
    kn, kg, kc, kw, kh, kx, ky = jax.random.split(key, 7)
    data = jax.random.uniform(kn, (batch, 3, h, w), jnp.float32) * 0.2
    n_boxes = jax.random.randint(kg, (batch,), 1, min(max_gts, 8) + 1)
    cls = jax.random.randint(kc, (batch, max_gts), 0, classes)
    bw = (jax.random.uniform(kw, (batch, max_gts)) * 0.42 + 0.08) * w
    bh = (jax.random.uniform(kh, (batch, max_gts)) * 0.42 + 0.08) * h
    x1 = jax.random.uniform(kx, (batch, max_gts)) * (w - bw)
    y1 = jax.random.uniform(ky, (batch, max_gts)) * (h - bh)
    valid = jnp.arange(max_gts)[None, :] < n_boxes[:, None]
    gt = jnp.where(
        valid[..., None],
        jnp.stack([cls.astype(jnp.float32), x1, y1, x1 + bw, y1 + bh], -1),
        -1.0)
    yy = jnp.arange(h, dtype=jnp.float32)[:, None]
    xx = jnp.arange(w, dtype=jnp.float32)[None, :]
    chan = jax.nn.one_hot(cls % 3, 3)                      # (B, G, 3)

    def paint(g, img):
        # int() truncation bounds, as the host generator paints
        m = ((yy >= jnp.floor(y1[:, g, None, None]))
             & (yy < jnp.floor(y1[:, g] + bh[:, g])[:, None, None])
             & (xx >= jnp.floor(x1[:, g, None, None]))
             & (xx < jnp.floor(x1[:, g] + bw[:, g])[:, None, None])
             & valid[:, g, None, None])
        return img + 0.8 * m[:, None] * chan[:, g, :, None, None]

    data = jax.lax.fori_loop(0, max_gts, paint, data)
    im_info = jnp.tile(jnp.array([h, w, 1.0], jnp.float32), (batch, 1))
    return data, im_info, gt


def _smooth_l1(pred, target, weight, sigma):
    """Weighted smooth-L1 via the registered op (ops/elemwise.py smooth_l1,
    reference mshadow_op.h smooth_l1_loss)."""
    from mxnet_tpu.ops.elemwise import smooth_l1

    return smooth_l1((pred - target) * weight, scalar=sigma)


def make_rfcn_train_step(net, batch, learning_rate=5e-4, momentum=0.9,
                         compute_dtype=None):
    """→ (step, state): ``step(state, data, im_info, gt, key) ->
    (state, loss, parts)``, fully jittable, state donate-able.

    Mixed precision (``compute_dtype='bfloat16'``): parameters and image in
    bf16 for the conv trunk (MXU dtype, halved HBM traffic); box/coordinate
    math stays fp32 — gt/im_info/rois are never downcast, MultiProposal
    upcasts its inputs at entry (ops/detection.py multi_proposal), and the
    PS-ROI pooling computes sample coordinates in fp32.
    """
    import jax
    import jax.numpy as jnp

    apply, names, vals, aux_names = functionalize(net, train=True)
    aux_set = set(aux_names)
    learn_idx = [i for i, n in enumerate(names) if n not in aux_set]
    aux_idx = [i for i, n in enumerate(names) if n in aux_set]
    Hf, Wf = net.feat_shape
    A = net.num_anchors
    a_total = Hf * Wf * A
    ncand = net.rpn_post_nms + net.max_gts
    cdtype = jnp.dtype(compute_dtype) if compute_dtype is not None else None

    def loss_fn(learn, aux, data, im_info, gt, key):
        merged = [None] * len(names)
        for i, v in zip(learn_idx, learn):
            merged[i] = v.astype(cdtype) if cdtype is not None else v
        for i, v in zip(aux_idx, aux):
            merged[i] = v
        k1, k2, k3 = jax.random.split(key, 3)
        nz_rpn = jax.random.uniform(k1, (batch, a_total, 2), jnp.float32)
        nz_prop = jax.random.uniform(k2, (batch, ncand, 2), jnp.float32)
        x = data.astype(cdtype) if cdtype is not None else data
        outs, new_aux = apply(merged, (x, im_info, gt, nz_rpn, nz_prop), k3)
        (rpn_cls, rpn_bbox, rpn_label, rpn_bt, rpn_bw,
         _rois, label, bbox_target, bbox_weight, cls_score, bbox_pred) = (
            jnp.asarray(o).astype(jnp.float32) for o in outs)

        # RPN losses (reference train_end2end loss heads; anchor order
        # h·(W·A)+w·A+a matches rpn_anchor_target / MultiProposal)
        logits = rpn_cls.reshape(batch, 2, A, Hf, Wf).transpose(0, 3, 4, 2, 1)
        logits = logits.reshape(batch, a_total, 2)
        valid = rpn_label >= 0
        lab = jnp.maximum(rpn_label, 0.0).astype(jnp.int32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
        rpn_cls_loss = jnp.where(valid, ce, 0.0).sum() / jnp.maximum(valid.sum(), 1)
        bp = rpn_bbox.reshape(batch, A, 4, Hf, Wf).transpose(0, 3, 4, 1, 2)
        bp = bp.reshape(batch, a_total, 4)
        rpn_bbox_loss = _smooth_l1(bp, rpn_bt, rpn_bw, 3.0).sum() / (
            net.rpn_batch * batch)

        # R-CNN head losses (class-agnostic bbox, R-FCN convention)
        logp2 = jax.nn.log_softmax(cls_score, axis=-1)
        rcnn_cls_loss = -jnp.take_along_axis(
            logp2, label.astype(jnp.int32)[:, None], axis=1).mean()
        rcnn_bbox_loss = _smooth_l1(bbox_pred, bbox_target, bbox_weight, 1.0
                                    ).sum() / label.shape[0]

        total = rpn_cls_loss + rpn_bbox_loss + rcnn_cls_loss + rcnn_bbox_loss
        parts = jnp.stack([rpn_cls_loss, rpn_bbox_loss, rcnn_cls_loss,
                           rcnn_bbox_loss])
        return total, (new_aux, parts)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step(state, data, im_info, gt, key, lr=learning_rate):
        # ``lr`` defaults to the baked constant; schedules pass it per step
        # as a traced scalar — decays then cost zero recompiles
        learn, mom, aux = state
        (loss, (new_aux, parts)), grads = grad_fn(learn, aux, data, im_info, gt, key)
        if momentum:
            mom = [momentum * m + g for m, g in zip(mom, grads)]
            upd = mom
        else:
            upd = grads
        learn = [p - lr * g for p, g in zip(learn, upd)]
        return (learn, mom, new_aux), loss, parts

    learn_vals = [vals[i] for i in learn_idx]
    aux_vals = [vals[i] for i in aux_idx]
    # zeros_like on the jax arrays: shapes/dtypes only, no D2H transfer
    mom_vals = [jnp.zeros_like(v) for v in learn_vals] if momentum else []
    return step, (learn_vals, mom_vals, aux_vals)


def build_net(resnet101, image_shape=None, classes=None, frozen_bn=True):
    """→ (net, image_shape, classes): the full ResNet-101 north-star model,
    or the tiny-trunk CPU configuration with the same graph."""
    if resnet101:
        shape = tuple(image_shape or (608, 1024))
        classes = classes or 80
        net = rfcn_resnet101(classes=classes, image_shape=shape, max_gts=16,
                             frozen_bn=frozen_bn)
    else:
        shape = tuple(image_shape or (64, 96))
        classes = classes or 3
        # anchor scales sized for the tiny image (stride 16 ⇒ 16/32-px boxes)
        net = DeformableRFCN(
            classes=classes, image_shape=shape, units=(1, 1, 1, 1),
            scales=(1, 2), ratios=(0.5, 1, 2), rpn_pre_nms=200,
            rpn_post_nms=32, batch_rois=16, rpn_batch=32, max_gts=8,
            frozen_bn=frozen_bn)
    net.initialize()
    net.init_params()  # tiny dummy pass; H/W-independent param shapes
    return net, shape, classes


def run_bench(resnet101, batch=1, iters=10, image_shape=None, classes=None,
              dtype=None, lr=5e-4, windows=3, verbose=True):
    """Timed chained-step bench (state stays on device; one scalar fetch per
    window).  → (img_per_sec, ms_per_step, final_loss).  This is THE repo
    headline measurement — bench.py calls it."""
    import jax

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net, shape, classes = build_net(resnet101, image_shape, classes)
    data, im_info, gt = synthetic_coco(rng, batch, shape, classes, net.max_gts)
    step, state = make_rfcn_train_step(
        net, batch, learning_rate=lr, momentum=0.9, compute_dtype=dtype)
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    d = jax.device_put(data)
    i = jax.device_put(im_info)
    g = jax.device_put(gt)
    t0 = time.time()
    state, loss, parts = jstep(state, d, i, g, key)
    jax.block_until_ready(loss)
    compile_s = time.time() - t0
    # no-op unless MXNET_TELEMETRY is set: feeds bench.py's telemetry block
    mx.telemetry.note_compile(compile_s, fn="rfcn_fused_step")
    if verbose:
        print("compile+first step: %.1fs  loss=%.4f" % (compile_s, float(loss)))
    best = None
    for w in range(windows):
        # keys precomputed OUTSIDE the timed window: an eager fold_in is
        # several tunneled dispatches per step (measured in the step trace)
        keys = [jax.random.fold_in(key, w * 1000 + it) for it in range(iters)]
        jax.block_until_ready(keys[-1])
        t0 = time.perf_counter()
        for it in range(iters):
            state, loss, parts = jstep(state, d, i, g, keys[it])
        float(loss)  # sync via the scalar; state never leaves the device
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    return batch / best, best * 1e3, float(loss)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--resnet101", action="store_true",
                   help="full ResNet-101 trunk (default: tiny units for CPU)")
    p.add_argument("--image-shape", type=int, nargs=2, default=None)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--classes", type=int, default=None)
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--lr", type=float, default=5e-4)
    p.add_argument("--dtype", default=None,
                   help="compute dtype (bfloat16 on TPU; fp32 default)")
    p.add_argument("--bench", action="store_true")
    p.add_argument("--bench-iters", type=int, default=10)
    args = p.parse_args()

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    if args.dtype is None and args.bench and on_tpu:
        args.dtype = "bfloat16"

    if args.bench:
        img_s, ms, loss = run_bench(
            args.resnet101, batch=args.batch_size, iters=args.bench_iters,
            image_shape=args.image_shape, classes=args.classes,
            dtype=args.dtype, lr=args.lr)
        print("rfcn_fused_bench: batch=%d dtype=%s  %.2f img/s (%.0f ms/step)"
              "  loss=%.4f"
              % (args.batch_size, args.dtype or "float32", img_s, ms, loss))
        return

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net, shape, classes = build_net(args.resnet101, args.image_shape, args.classes)
    data, im_info, gt = synthetic_coco(rng, args.batch_size, shape, classes,
                                       net.max_gts)
    step, state = make_rfcn_train_step(
        net, args.batch_size, learning_rate=args.lr, momentum=0.9,
        compute_dtype=args.dtype)
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)

    first = last = None
    for s in range(args.steps):
        data, im_info, gt = synthetic_coco(rng, args.batch_size, shape,
                                           classes, net.max_gts)
        state, loss, parts = jstep(state, data, im_info, gt,
                                   jax.random.fold_in(key, s))
        l = float(loss)
        pr = [float(x) for x in np.asarray(parts)]
        print("step %2d  loss=%.4f  (rpn_cls %.3f rpn_bbox %.3f "
              "rcnn_cls %.3f rcnn_bbox %.3f)" % (s, l, *pr))
        if first is None:
            first = l
        last = l
    assert np.isfinite(last), "loss diverged"
    assert last < first, "loss did not decrease (first=%.4f last=%.4f)" % (first, last)
    print("DEFORMABLE-RFCN FUSED TRAIN OK")


if __name__ == "__main__":
    main()
