"""Deformable R-FCN end-to-end training on a synthetic shapes dataset —
the BASELINE config-3 north star run anywhere (reference: Deformable R-FCN
over the deformable ops this fork exists for; model recipe from the external
Deformable-ConvNets repo, rebuilt TPU-first)."""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd

from deformable_rfcn import DeformableRFCN, rfcn_losses, rpn_losses


def synthetic_batches(batch_size, data_shape, num_batches, num_classes=2, seed=0):
    """Bright rectangles on dim noise; labels [cls, x1, y1, x2, y2] in pixels."""
    rng = np.random.RandomState(seed)
    c, h, w = data_shape
    for _ in range(num_batches):
        data = rng.rand(batch_size, c, h, w).astype(np.float32) * 0.2
        labels = np.full((batch_size, 2, 5), -1.0, dtype=np.float32)
        for b in range(batch_size):
            for j in range(rng.randint(1, 3)):
                cls = rng.randint(0, num_classes)
                bw = rng.uniform(0.3, 0.6) * w
                bh = rng.uniform(0.3, 0.6) * h
                x1 = rng.uniform(0, w - bw)
                y1 = rng.uniform(0, h - bh)
                labels[b, j] = [cls, x1, y1, x1 + bw, y1 + bh]
                data[b, cls % c, int(y1):int(y1 + bh), int(x1):int(x1 + bw)] += 0.8
        im_info = np.tile(np.array([h, w, 1.0], np.float32), (batch_size, 1))
        yield nd.array(data), nd.array(im_info), nd.array(labels)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument("--data-shape", type=int, nargs=3, default=[3, 64, 64])
    p.add_argument("--num-classes", type=int, default=2)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batches-per-epoch", type=int, default=6)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--bench", action="store_true",
                   help="measure steady-state training img/s at the given "
                        "data shape (north-star metric: Deformable R-FCN "
                        "imgs/sec/chip, BASELINE.md)")
    p.add_argument("--bench-iters", type=int, default=10)
    args = p.parse_args()

    net = DeformableRFCN(num_classes=args.num_classes)
    net.initialize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": args.lr, "momentum": 0.9})

    def train_step(data, im_info, labels):
        """One full detection train step (shared by training and --bench so
        the published img/s measures exactly what training runs)."""
        with autograd.record():
            rois, cls_score, bbox_pred, rpn_cls, rpn_bbox = net(data, im_info)
            cls_loss, bbox_loss = rfcn_losses(
                rois, cls_score, bbox_pred, labels, args.num_classes)
            rpn_cls_loss, rpn_bbox_loss = rpn_losses(
                net, rpn_cls, rpn_bbox, labels, im_info)
            loss = cls_loss + bbox_loss + rpn_cls_loss + rpn_bbox_loss
        loss.backward()
        trainer.step(args.batch_size)
        return float(loss.asnumpy())

    if args.bench:
        iters = max(1, args.bench_iters)
        data, im_info, labels = next(iter(synthetic_batches(
            args.batch_size, tuple(args.data_shape), 1, args.num_classes)))
        train_step(data, im_info, labels)  # warmup/compile
        tic = time.time()
        for _ in range(iters):
            train_step(data, im_info, labels)
        dt = (time.time() - tic) / iters
        print("rfcn_bench: shape=%s batch=%d  %.2f img/s (%.0f ms/step)"
              % (tuple(args.data_shape), args.batch_size,
                 args.batch_size / dt, dt * 1e3))
        return

    first_loss = last_loss = None
    for epoch in range(args.epochs):
        tic = time.time()
        total = 0.0
        n = 0
        for data, im_info, labels in synthetic_batches(
                args.batch_size, tuple(args.data_shape),
                args.batches_per_epoch, args.num_classes, seed=epoch):
            total += train_step(data, im_info, labels)
            n += 1
        avg = total / n
        if first_loss is None:
            first_loss = avg
        last_loss = avg
        print("Epoch[%d] loss=%.4f time=%.1fs" % (epoch, avg, time.time() - tic))
    print("first=%.4f last=%.4f" % (first_loss, last_loss))
    assert last_loss < first_loss, "loss did not decrease"
    print("DEFORMABLE-RFCN TRAIN OK")


if __name__ == "__main__":
    main()
