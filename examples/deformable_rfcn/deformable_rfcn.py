"""Deformable R-FCN — the north-star model family of the reference fork
(README.md:1-7: "the CPU version of Deformable-RCNN code"; ops
``src/operator/contrib/deformable_convolution-inl.h:99``,
``deformable_psroi_pooling.cc:66``, ``multi_proposal.cc:38``; model code
lives in the external Deformable-ConvNets repo which this fork serves).

TPU-native composition: backbone convs → a deformable conv block (offsets
learned by a plain conv) → RPN + MultiProposal (fixed-capacity top-k, jit
friendly) → position-sensitive score/bbox maps → DeformablePSROIPooling with
learned per-ROI ``trans`` offsets → per-ROI classification + bbox deltas.
Everything jits into one XLA module per phase.
"""
from __future__ import annotations

import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import HybridBlock, nn


class Backbone(HybridBlock):
    """Small strided conv trunk ending at stride 8, with one deformable
    conv block at the end (the Deformable-ConvNets recipe applies deformable
    convs in the last stage)."""

    def __init__(self, channels=(16, 32, 64), defconv_filters=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="")
            for i, ch in enumerate(channels):
                self.body.add(nn.Conv2D(ch, 3, strides=2, padding=1, prefix="down%d_" % i))
                self.body.add(nn.BatchNorm())
                self.body.add(nn.Activation("relu"))
            # offsets for a 3x3 deformable conv: 2*3*3=18 channels, zero-init
            # (starts as a regular conv, learns sampling locations)
            self.offset_conv = nn.Conv2D(
                18, 3, padding=1, weight_initializer="zeros",
                bias_initializer="zeros", prefix="offset_")
            self.def_weight = self.params.get(
                "defconv_weight", shape=(defconv_filters, channels[-1], 3, 3),
                init=mx.init.Xavier())
            self.def_bias = self.params.get(
                "defconv_bias", shape=(defconv_filters,), init="zeros")

    def hybrid_forward(self, F, x, def_weight, def_bias):
        feat = self.body(x)
        offsets = self.offset_conv(feat)
        return F.contrib.DeformableConvolution(
            feat, offsets, def_weight, def_bias,
            kernel=(3, 3), num_filter=def_weight.shape[0], pad=(1, 1),
            num_deformable_group=1,
        )


class RPN(HybridBlock):
    """(reference rcnn symbol rpn_* layers)"""

    def __init__(self, num_anchors, channels=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.conv = nn.Conv2D(channels, 3, padding=1, prefix="conv_")
            self.cls = nn.Conv2D(2 * num_anchors, 1, prefix="cls_")
            self.bbox = nn.Conv2D(4 * num_anchors, 1, prefix="bbox_")

    def hybrid_forward(self, F, x):
        t = F.relu(self.conv(x))
        return self.cls(t), self.bbox(t)


class DeformableRFCN(HybridBlock):
    """R-FCN head: position-sensitive maps + deformable PSROI pooling.

    cls branch:  conv -> (C+1)*p*p score maps -> def-psroi -> (R, C+1)
    bbox branch: conv -> 4*p*p maps          -> def-psroi -> (R, 4)
    trans branch: per-ROI offset maps pooled with no_trans, predicting the
    deformation applied in the second (deformable) pooling pass — the
    two-stage scheme of Deformable R-FCN.
    """

    def __init__(self, num_classes=2, num_anchors=9, pooled_size=3,
                 stride=8, rpn_post_nms=32, **kw):
        super().__init__(**kw)
        self.num_classes = num_classes
        self.p = pooled_size
        self.stride = stride
        self.rpn_post_nms = rpn_post_nms
        self.num_anchors = num_anchors
        self.scales = (2, 4, 8)
        self.ratios = (0.5, 1, 2)
        with self.name_scope():
            self.backbone = Backbone(prefix="backbone_")
            self.rpn = RPN(num_anchors, prefix="rpn_")
            cpp = (num_classes + 1) * pooled_size * pooled_size
            self.ps_cls = nn.Conv2D(cpp, 1, prefix="pscls_")
            self.ps_bbox = nn.Conv2D(4 * pooled_size * pooled_size, 1, prefix="psbbox_")
            # offset (trans) maps: 2 channels (dx, dy); per-bin variation
            # comes from the stage-1 pooling reading each bin's own spatial
            # region (group_size=1 pooling consumes exactly output_dim=2
            # channels, detection.py:314)
            self.ps_trans = nn.Conv2D(2, 1,
                                      weight_initializer="zeros",
                                      bias_initializer="zeros", prefix="pstrans_")

    def hybrid_forward(self, F, data, im_info):
        feat = self.backbone(data)
        rpn_cls, rpn_bbox = self.rpn(feat)
        # (B, 2A, H, W) -> softmax over {bg, fg} per anchor; shapes stay
        # symbolic (MXNet reshape specials + reshape_like), so the block
        # also hybridizes
        rpn_prob = F.softmax(F.Reshape(rpn_cls, shape=(0, 2, -1)), axis=1)
        rpn_prob = F.reshape_like(rpn_prob, rpn_cls)
        rois = F.contrib.MultiProposal(
            rpn_prob, rpn_bbox, im_info,
            feature_stride=self.stride, scales=(2, 4, 8), ratios=(0.5, 1, 2),
            rpn_pre_nms_top_n=128, rpn_post_nms_top_n=self.rpn_post_nms,
            threshold=0.7, rpn_min_size=4,
        )  # (B*post, 5)
        cls_maps = self.ps_cls(feat)
        bbox_maps = self.ps_bbox(feat)
        trans_maps = self.ps_trans(feat)
        ss = 1.0 / self.stride
        # stage 1: pool the trans maps without deformation -> per-ROI offsets
        trans = F.contrib.DeformablePSROIPooling(
            trans_maps, rois, spatial_scale=ss, output_dim=2,
            group_size=1, pooled_size=self.p, no_trans=True,
        )  # (R, 2, p, p)
        cls = F.contrib.DeformablePSROIPooling(
            cls_maps, rois, trans, spatial_scale=ss,
            output_dim=self.num_classes + 1, group_size=self.p,
            pooled_size=self.p, trans_std=0.1,
        )  # (R, C+1, p, p)
        bbox = F.contrib.DeformablePSROIPooling(
            bbox_maps, rois, trans, spatial_scale=ss, output_dim=4,
            group_size=self.p, pooled_size=self.p, trans_std=0.1,
        )  # (R, 4, p, p)
        cls_score = F.Reshape(cls, shape=(0, 0, -1)).mean(axis=2)
        bbox_pred = F.Reshape(bbox, shape=(0, 0, -1)).mean(axis=2)
        return rois, cls_score, bbox_pred, rpn_cls, rpn_bbox


def _rcnn_example():
    """The sibling Faster R-CNN example's helpers (vectorized IoU, anchor
    assignment, smooth-L1) — shared numerics across the detection examples."""
    import importlib
    import sys

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "rcnn")
    if path not in sys.path:
        sys.path.insert(0, path)
    return importlib.import_module("faster_rcnn")


def _roi_targets(rois_np, gt_np, iou_fg=0.5):
    """Host-side per-ROI targets from IoU vs gt (the reference's
    proposal_target CustomOp runs on host too; targets carry no gradient)."""
    fr = _rcnn_example()
    boxes = rois_np[:, 1:]
    bidx = rois_np[:, 0].astype(np.int32)
    R = boxes.shape[0]
    labels = np.zeros((R,), np.float32)
    tgt = np.zeros((R, 4), np.float32)
    for b in np.unique(bidx):
        sel = np.where(bidx == b)[0]
        g = gt_np[b]
        g = g[g[:, 0] >= 0]
        if not len(g):
            continue
        iou = fr._np_iou(boxes[sel], g[:, 1:])  # (r, G)
        j = iou.argmax(axis=1)
        best = iou.max(axis=1)
        fg = best >= iou_fg
        labels[sel[fg]] = g[j[fg], 0] + 1  # background = 0
        bx = boxes[sel]
        gb = g[j, 1:]
        bw = np.maximum(bx[:, 2] - bx[:, 0], 1.0)
        bh = np.maximum(bx[:, 3] - bx[:, 1], 1.0)
        t = np.stack([
            ((gb[:, 0] + gb[:, 2]) / 2 - (bx[:, 0] + bx[:, 2]) / 2) / bw,
            ((gb[:, 1] + gb[:, 3]) / 2 - (bx[:, 1] + bx[:, 3]) / 2) / bh,
            np.log(np.maximum(gb[:, 2] - gb[:, 0], 1.0) / bw),
            np.log(np.maximum(gb[:, 3] - gb[:, 1], 1.0) / bh),
        ], axis=1)
        tgt[sel[fg]] = t[fg]
    return labels, tgt


def rpn_losses(net, rpn_cls, rpn_bbox, gt_boxes, im_info, anchor_rng=None):
    """RPN cls/bbox losses via the shared anchor assignment (the same loss
    heads as examples/rcnn — without them the RPN receives zero gradient,
    since ROI coordinates enter pooling through a round())."""
    from mxnet_tpu.gluon import loss as gloss

    fr = _rcnn_example()
    B, _, hf, wf = rpn_cls.shape
    A = net.num_anchors
    labs, bts, bws = [], [], []
    gt_np = gt_boxes.asnumpy()
    info_np = im_info.asnumpy()
    for b in range(B):
        lab, bt, bw = fr.assign_anchor(
            (hf, wf), gt_np[b], info_np[b], stride=net.stride,
            scales=net.scales, ratios=net.ratios, rng=anchor_rng)
        labs.append(lab)
        bts.append(bt)
        bws.append(bw)
    rpn_label = nd.array(np.stack(labs))
    rpn_bt = nd.array(np.stack(bts))
    rpn_bw = nd.array(np.stack(bws))

    logits = nd.transpose(
        nd.reshape(rpn_cls, shape=(B, 2, A, hf, wf)), axes=(0, 3, 4, 2, 1))
    logits = nd.reshape(logits, shape=(B, hf * wf * A, 2))
    ce = gloss.SoftmaxCrossEntropyLoss()
    valid = rpn_label >= 0
    cls_loss = (
        nd.reshape(ce(nd.reshape(logits, shape=(-1, 2)),
                      nd.reshape(nd.maximum(rpn_label, 0.0), shape=(-1,))),
                   shape=rpn_label.shape) * valid
    ).sum() / nd.maximum(valid.sum(), 1.0)

    bp = nd.transpose(nd.reshape(rpn_bbox, shape=(B, A, 4, hf, wf)), axes=(0, 3, 4, 1, 2))
    bp = nd.reshape(bp, shape=(B, hf * wf * A, 4))
    bbox_loss = fr.smooth_l1(bp, rpn_bt, rpn_bw, sigma=3.0)
    return cls_loss, bbox_loss


def rfcn_losses(rois, cls_score, bbox_pred, gt_boxes, num_classes, iou_fg=0.5):
    """(cls_loss, bbox_loss) scalars; targets on host (no grad), losses as
    taped nd ops so gradients flow into the score/bbox branches."""
    from mxnet_tpu.gluon import loss as gloss

    labels_np, tgt_np = _roi_targets(rois.asnumpy(), gt_boxes.asnumpy(), iou_fg)
    labels = nd.array(labels_np)
    tgt = nd.array(tgt_np)

    ce = gloss.SoftmaxCrossEntropyLoss()
    cls_loss = ce(cls_score, labels).mean()

    fg = nd.reshape(labels > 0, shape=(-1, 1))
    diff = nd.abs(bbox_pred - tgt)
    smooth = nd.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
    bbox_loss = (smooth * fg).sum() / nd.maximum(fg.sum(), 1.0)
    return cls_loss, bbox_loss
