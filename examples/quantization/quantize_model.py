"""Post-training INT8 quantization — parity with reference
``example/quantization/imagenet_gen_qsym.py`` (train fp32, quantize with
calibration, compare accuracies).

Runs anywhere: trains a small convnet on a synthetic 3-class image task,
then quantizes with each calib mode and reports accuracy deltas.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.contrib.quantization import quantize_model
from mxnet_tpu.io import NDArrayIter


def make_data(n, seed=0, num_classes=8):
    """Class = which spatial quadrant+channel carries a WEAK brightness bump;
    weak enough that fp32 lands below saturation, so int8 deltas are
    informative."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, num_classes, n)
    x = rng.rand(n, 3, 16, 16).astype(np.float32) * 0.5
    for i in range(n):
        ch = y[i] % 3
        qy, qx = (y[i] // 3) % 2, (y[i] // 6) % 2
        x[i, ch, qy * 8:qy * 8 + 8, qx * 8:qx * 8 + 8] += 0.15
    return x, y.astype(np.float32)


def build_net():
    data = sym.Variable("data")
    c1 = sym.Convolution(data, kernel=(3, 3), num_filter=16, pad=(1, 1), name="conv1")
    r1 = sym.Activation(c1, act_type="relu", name="relu1")
    p1 = sym.Pooling(r1, kernel=(2, 2), stride=(2, 2), pool_type="max", name="pool1")
    c2 = sym.Convolution(p1, kernel=(3, 3), num_filter=32, pad=(1, 1), name="conv2")
    r2 = sym.Activation(c2, act_type="relu", name="relu2")
    p2 = sym.Pooling(r2, kernel=(2, 2), stride=(2, 2), pool_type="max", name="pool2")
    fl = sym.Flatten(p2, name="flat")
    fc = sym.FullyConnected(fl, num_hidden=8, name="fc")
    return sym.SoftmaxOutput(fc, name="softmax")


def accuracy(net_sym, params, X, y, batch_size=64):
    exe = None
    correct = 0
    for i in range(0, len(X) - batch_size + 1, batch_size):
        xb = X[i:i + batch_size]
        if exe is None:
            exe = net_sym.simple_bind(grad_req="null", data=xb.shape)
            for k, v in params.items():
                if k in exe.arg_dict:
                    exe.arg_dict[k][:] = v
        outs = exe.forward(is_train=False, data=nd.array(xb))
        pred = outs[0].asnumpy().argmax(axis=1)
        correct += (pred == y[i:i + batch_size]).sum()
    return correct / (len(X) // batch_size * batch_size)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-train", type=int, default=1024)
    p.add_argument("--num-val", type=int, default=512)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-calib-batches", type=int, default=4)
    args = p.parse_args()

    Xtr, ytr = make_data(args.num_train, seed=0)
    Xval, yval = make_data(args.num_val, seed=1)

    mx.random.seed(0)
    np.random.seed(0)
    net = build_net()
    mod = mx.mod.Module(net)
    mod.fit(NDArrayIter(Xtr, ytr, args.batch_size, shuffle=True),
            num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 1e-3},
            initializer=mx.init.Xavier())
    arg_params, aux_params = mod.get_params()

    fp32_acc = accuracy(net, arg_params, Xval, yval, args.batch_size)
    print("fp32 accuracy: %.4f" % fp32_acc)

    for calib_mode in ("none", "naive", "entropy"):
        kwargs = {}
        if calib_mode != "none":
            kwargs["calib_data"] = NDArrayIter(Xtr, ytr, args.batch_size)
            kwargs["num_calib_examples"] = args.batch_size * args.num_calib_batches
        qsym, qargs, _ = quantize_model(
            net, arg_params, aux_params, calib_mode=calib_mode, **kwargs)
        q_acc = accuracy(qsym, qargs, Xval, yval, args.batch_size)
        print("int8 (%s calib) accuracy: %.4f  (delta %.4f)"
              % (calib_mode, q_acc, q_acc - fp32_acc))
        assert q_acc > fp32_acc - 0.02, (calib_mode, q_acc, fp32_acc)
    print("QUANTIZATION EXAMPLE OK")


if __name__ == "__main__":
    main()
