"""Benchmark: north-star throughput, single chip.  Prints ONE JSON line.

Default metric: **Deformable R-FCN (ResNet-101) training img/s** at COCO
shapes (608x1024, 80 classes) — the model family this reference fork exists
for (BASELINE.md north star; published ~3.8 img/s on the reference's
GPU setup, external Deformable-ConvNets repo).  The measured step is the
FULL detection train step — ResNet-101 + deformable res5, RPN,
MultiProposal, on-device targets, deformable PS-ROI heads, 4 losses,
momentum SGD — compiled into one XLA module
(examples/deformable_rfcn/train_fused.py).

``MXNET_BENCH=resnet50`` selects the classification headline instead
(ResNet-50 train, baseline 109 img/s on 1x K80,
`example/image-classification/README.md:145-156`);
``MXNET_BENCH=frcnn`` the Faster-RCNN VGG16 fused step (BASELINE config
2, `examples/rcnn/train_fused.py`).
"""
import json
import os
import time

import numpy as np


def _emit(payload, attach_telemetry=True):
    """Print one bench JSON line; with MXNET_TELEMETRY enabled, attach
    the telemetry block (compile_s, peak_hbm_bytes, data_wait_frac, and —
    when a Module train loop ran — dispatches_per_step, the ISSUE 3 fused
    step's regression surface, plus trainhealth_drain_s, the ISSUE 12
    health plane's whole host-side overhead; see docs/OBSERVABILITY.md)
    and flush the JSONL event log.  The line's schema is linted by
    ci/check_bench_schema.py.

    ``attach_telemetry=False`` is for FOLLOW-UP rows in a multi-row run
    (the ISSUE 15 per-tier predictor rows): ``telemetry.summary()`` totals
    process-cumulative counters, so a second row would fold the first
    row's compile/memory into its own block and bench_compare would
    mis-attribute fp32 drift to the tier row — per-executable compile
    cost for twins lives in the costplane ledger instead."""
    from mxnet_tpu import telemetry

    if telemetry.enabled():
        if attach_telemetry:
            telemetry.sample_memory()
            payload["telemetry"] = telemetry.summary()
        telemetry.event("bench_result", **payload)
        telemetry.flush()
    print(json.dumps(payload))


def main():
    which = os.environ.get("MXNET_BENCH", "rfcn")
    if which == "frcnn":
        return main_frcnn()
    if which == "module":
        return main_module()
    if which == "predictor":
        return main_predictor()
    if which != "resnet50":
        return main_rfcn()
    import jax

    platform = jax.devices()[0].platform
    dtype = os.environ.get(
        "MXNET_BENCH_DTYPE", "bfloat16" if platform == "tpu" else "float32")
    # TPU: batch 448 saturates one v5e chip's HBM for ResNet-50 bf16 train
    # (480 falls off the memory cliff); fp32 activations are twice the size,
    # so the fp32 run halves the default batch. CPU smoke runs stay tiny.
    if platform == "tpu":
        default_batch = 448 if dtype != "float32" else 224
    else:
        default_batch = 4
    batch = int(os.environ.get("MXNET_BENCH_BATCH", default_batch))
    iters = int(os.environ.get("MXNET_BENCH_ITERS", 20 if platform == "tpu" else 2))
    image = 224

    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu.gluon import loss as loss_mod
    from mxnet_tpu.gluon.functional import make_train_step
    from __graft_entry__ import _build_resnet

    # bf16 compute with fp32 master weights is the TPU-native training config
    # (MXU native dtype, halved HBM traffic); MXNET_BENCH_DTYPE=float32 gives
    # the fp32 number (with a halved default batch, above)
    net = _build_resnet(classes=1000, version=50, image_size=image)
    step, state, _meta = make_train_step(
        net, loss_mod.SoftmaxCrossEntropyLoss(), learning_rate=0.05, momentum=0.9,
        compute_dtype=None if dtype == "float32" else dtype,
    )
    from mxnet_tpu import telemetry

    # identity when MXNET_TELEMETRY is off; otherwise counts compiles and
    # attributes first-call wall time to jit_compile_seconds_total
    jstep = telemetry.instrument_step(
        jax.jit(step, donate_argnums=(0,)),
        name="resnet50_train_step", batch_size=batch)

    rng = np.random.RandomState(0)
    x = jax.device_put(rng.randn(batch, 3, image, image).astype(np.float32))
    y = jax.device_put(rng.randint(0, 1000, (batch,)).astype(np.float32))
    key = jax.random.PRNGKey(0)

    # warmup/compile
    state, loss = jstep(state, x, y, key)
    jax.block_until_ready(loss)

    # best of 3 windows: the tunnel/host adds run-to-run jitter; peak window
    # reflects the chip's steady-state throughput
    best_dt = None
    for w in range(3):
        # keys precomputed OUTSIDE the timed window: an eager fold_in is
        # several tunneled dispatches per step
        keys = [jax.random.fold_in(key, w * iters + i) for i in range(iters)]
        jax.block_until_ready(keys[-1])
        t0 = time.perf_counter()
        for i in range(iters):
            state, loss = jstep(state, x, y, keys[i])
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)

    imgs_per_sec = batch * iters / best_dt
    baseline = 109.0  # 1x K80, batch 32
    _emit({
        "metric": "resnet50_train_imgs_per_sec",
        "value": round(imgs_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(imgs_per_sec / baseline, 3),
    })


def main_rfcn():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "examples", "deformable_rfcn"))
    import jax
    from train_fused import run_bench

    on_tpu = jax.devices()[0].platform == "tpu"
    # batch 8 is the round-4 single-chip optimum (roofline:
    # examples/quality/rfcn_roofline.py — 33.8 img/s after the
    # deformable-conv one-hot-matmul rewrite moved batch 1 to 99% of its
    # HBM bound; batch 4: 32.0, batch 1: 23.5); scaling beyond this is
    # capped by near-linear bytes/step growth, see docs/PERF_NOTES.md
    batch = int(os.environ.get("MXNET_BENCH_BATCH", 8 if on_tpu else 1))
    iters = int(os.environ.get("MXNET_BENCH_ITERS", 10 if on_tpu else 2))
    imgs_per_sec, _ms, _loss = run_bench(
        resnet101=on_tpu, batch=batch, iters=iters,
        dtype="bfloat16" if on_tpu else None, verbose=False)
    baseline = 3.8  # Deformable R-FCN reference throughput (BASELINE.md)
    if on_tpu:
        _emit({
            "metric": "deformable_rfcn_r101_coco_train_imgs_per_sec",
            "value": round(imgs_per_sec, 2),
            "unit": "img/s",
            "vs_baseline": round(imgs_per_sec / baseline, 3),
        })
    else:  # CPU smoke: tiny toy trunk — never report it as the R-101 number
        _emit({
            "metric": "deformable_rfcn_tiny_cpu_smoke_imgs_per_sec",
            "value": round(imgs_per_sec, 2),
            "unit": "img/s",
            "vs_baseline": None,
        })


def main_module():
    """``MXNET_BENCH=module``: symbolic Module train-step microbench
    (ISSUE 3 fused executor).  A small MLP driven through the
    forward_backward/update loop; with MXNET_TELEMETRY=1 the emitted
    telemetry block carries ``dispatches_per_step`` — 1.0 on the fused path
    vs 2+P legacy (set MXNET_MODULE_FUSED_STEP=0 to measure the regression
    surface the fused path removes)."""
    import mxnet_tpu as mx
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu.io import DataBatch

    batch = int(os.environ.get("MXNET_BENCH_BATCH", 64))
    iters = int(os.environ.get("MXNET_BENCH_ITERS", 50))
    rng = np.random.RandomState(0)
    X = rng.randn(batch, 128).astype(np.float32)
    y = rng.randint(0, 10, (batch,)).astype(np.float32)

    data = mx.sym.var("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, name="fc1", num_hidden=256),
        name="a1", act_type="relu")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(h, name="fc2", num_hidden=256),
        name="a2", act_type="relu")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, name="fc3", num_hidden=10), name="softmax")

    mod = mod_mod.Module(sym)
    mod.bind(data_shapes=[("data", (batch, 128))],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    b = DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(y)])
    # trainhealth plane (ISSUE 12): with MXNET_TRAINHEALTH=1 this bench
    # drains like the fit loop would, so the emitted telemetry block's
    # trainhealth_drain_s measures the plane's whole per-step overhead
    # inside the timed loop (None when the gate is off)
    from mxnet_tpu import telemetry

    health = telemetry.trainhealth.plane()
    mod.forward_backward(b)
    mod.update()  # warmup/compile
    mod.get_outputs()[0].asnumpy()
    if health is not None:
        health.drain(mod, step=0)

    t0 = time.perf_counter()
    for i in range(iters):
        mod.forward_backward(b)
        mod.update()
        if health is not None:
            mod.get_outputs()[0].asnumpy()  # the fit loop's metric sync
            health.drain(mod, step=i + 1)
    mod.get_outputs()[0].asnumpy()  # sync the async dispatch chain
    dt = time.perf_counter() - t0
    _emit({
        "metric": "module_mlp_train_samples_per_sec",
        "value": round(batch * iters / dt, 2),
        "unit": "samples/s",
        "vs_baseline": None,
    })


def main_predictor():
    """``MXNET_BENCH=predictor``: symbolic inference-twin microbench
    (ISSUE 7 graph passes).  A two-head deploy graph — conv+BN trunk, then
    a classifier head AND an embedding head, each re-deriving the pooled
    trunk features through a shared helper (the standard exporter pattern:
    every head's builder recomputes its own normalize/flatten chain, so
    the captured graph carries duplicated subexpressions the passes merge;
    dropout nodes vanish from the eval plan and BatchNorms become affine).
    Driven through ``Predictor.forward`` — the serving shape the bucket
    ladder compiles.  With MXNET_TELEMETRY=1 the telemetry block carries
    ``graph_nodes_pre``/``graph_nodes_post``/``pass_time_s`` and
    ``compile_s`` (the first forward's trace+compile, via note_compile);
    run with MXNET_GRAPH_PASSES=0 to measure the unoptimized plan the
    passes replace (docs/PERF_NOTES.md "Graph passes").

    With ``MXNET_PRECISION_TIER=bf16|int8`` set (ISSUE 15) a SECOND line
    follows for that deploy twin (``Predictor.with_precision``) — each
    line carries the ``tier`` discriminator, so bench_compare diffs
    fp32-vs-fp32 and twin-vs-twin but never across tiers
    (docs/PERF_NOTES.md "Precision tiers")."""
    from mxnet_tpu.predictor import Predictor
    from mxnet_tpu.graph_passes import precision
    from mxnet_tpu.test_utils import deploy_twin_checkpoint

    batch = int(os.environ.get("MXNET_BENCH_BATCH", 16))
    iters = int(os.environ.get("MXNET_BENCH_ITERS", 200))
    image = 32

    # the two-head deploy graph lives in test_utils so the numerics CI
    # (ci/check_numerics.py, ISSUE 11) gates the exact topology benched here
    sym, params, input_shapes = deploy_twin_checkpoint(batch=batch,
                                                       image=image)
    rng = np.random.RandomState(0)

    from mxnet_tpu import telemetry

    pred = Predictor(sym, params, input_shapes)
    # the baseline row is ALWAYS the fp32 plan: with the tier env set, the
    # bind above already built the twin, so rebuild the fp32 sibling
    # explicitly (shared weight buffers either way)
    tier = precision.tier()
    if tier:
        pred = pred.with_precision(None)
    x = rng.rand(batch, 3, image, image).astype(np.float32)

    def run_one(p, label):
        t0 = time.perf_counter()
        p.forward(data=x)
        p.get_output(0)
        telemetry.note_compile(time.perf_counter() - t0,
                               fn="predictor_fwd_%s" % label)
        t0 = time.perf_counter()
        for _ in range(iters):
            p.forward(data=x)
        p.get_output(0)  # sync the async dispatch chain
        dt = time.perf_counter() - t0
        _emit({
            "metric": "predictor_cnn_infer_samples_per_sec",
            "value": round(batch * iters / dt, 2),
            "unit": "samples/s",
            "vs_baseline": None,
            "tier": label,
        }, attach_telemetry=(label == "fp32"))

    run_one(pred, "fp32")
    if tier:
        calibration = None
        if tier == "int8":
            calibration = precision.calibrate(
                pred, ({"data": rng.rand(batch, 3, image, image)
                        .astype(np.float32)} for _ in range(4)))
        run_one(pred.with_precision(tier, calibration), tier)


def main_frcnn():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "examples", "rcnn"))
    import jax
    from train_fused import run_bench

    on_tpu = jax.devices()[0].platform == "tpu"
    # batch 8 is the round-4 optimum (55.7 img/s; 16 plateaus at 57.3 —
    # docs/PERF_NOTES.md Faster-RCNN section)
    batch = int(os.environ.get("MXNET_BENCH_BATCH", 8 if on_tpu else 1))
    iters = int(os.environ.get("MXNET_BENCH_ITERS", 10 if on_tpu else 2))
    imgs_per_sec, _ms, _loss = run_bench(
        vgg16=on_tpu, batch=batch, iters=iters,
        dtype="bfloat16" if on_tpu else None, verbose=False)
    if on_tpu:
        # no published img/s in the reference tree for this recipe (the bar
        # is mAP 70.23, example/rcnn/README.md:38-42) — vs_baseline omitted
        _emit({
            "metric": "faster_rcnn_vgg16_voc_train_imgs_per_sec",
            "value": round(imgs_per_sec, 2),
            "unit": "img/s",
            "vs_baseline": None,
        })
    else:
        _emit({
            "metric": "faster_rcnn_tiny_cpu_smoke_imgs_per_sec",
            "value": round(imgs_per_sec, 2),
            "unit": "img/s",
            "vs_baseline": None,
        })


if __name__ == "__main__":
    main()
