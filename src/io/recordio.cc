#include "recordio.h"

#include <cstring>

namespace mxtpu {

namespace {
inline uint32_t EncodeLRec(uint32_t cflag, uint32_t length) {
  return (cflag << 29u) | length;
}
inline uint32_t DecodeFlag(uint32_t rec) { return (rec >> 29u) & 7u; }
inline uint32_t DecodeLength(uint32_t rec) { return rec & ((1u << 29u) - 1u); }
}  // namespace

RecordIOWriter::RecordIOWriter(const std::string& path) {
  fp_ = std::fopen(path.c_str(), "wb");
}

RecordIOWriter::~RecordIOWriter() { Close(); }

void RecordIOWriter::Close() {
  if (fp_) {
    std::fclose(fp_);
    fp_ = nullptr;
  }
}

uint64_t RecordIOWriter::Tell() { return fp_ ? (uint64_t)std::ftell(fp_) : 0; }

uint64_t RecordIOWriter::WriteRecord(const void* buf, size_t size) {
  // lrec stores chunk length in 29 bits; larger payloads cannot be framed.
  if (size >= (1u << 29)) return UINT64_MAX;
  const uint64_t start = Tell();
  const char* data = static_cast<const char*>(buf);
  const uint32_t magic = kMagic;
  // Split payload at occurrences of the magic word so readers can resync.
  size_t begin = 0;
  bool first = true;
  std::vector<std::pair<size_t, size_t>> chunks;  // (offset, len)
  size_t i = 0;
  while (i + 4 <= size) {
    if (std::memcmp(data + i, &magic, 4) == 0) {
      chunks.emplace_back(begin, i - begin);
      begin = i + 4;
      i += 4;
    } else {
      ++i;
    }
  }
  chunks.emplace_back(begin, size - begin);
  (void)first;
  const size_t n = chunks.size();
  for (size_t c = 0; c < n; ++c) {
    uint32_t cflag;
    if (n == 1) {
      cflag = 0;
    } else if (c == 0) {
      cflag = 1;
    } else if (c + 1 == n) {
      cflag = 3;
    } else {
      cflag = 2;
    }
    uint32_t len = (uint32_t)chunks[c].second;
    uint32_t lrec = EncodeLRec(cflag, len);
    std::fwrite(&magic, 4, 1, fp_);
    std::fwrite(&lrec, 4, 1, fp_);
    if (len) std::fwrite(data + chunks[c].first, 1, len, fp_);
    const uint32_t pad = (4 - (len & 3u)) & 3u;
    static const char zeros[4] = {0, 0, 0, 0};
    if (pad) std::fwrite(zeros, 1, pad, fp_);
  }
  return start;
}

RecordIOReader::RecordIOReader(const std::string& path) {
  fp_ = std::fopen(path.c_str(), "rb");
}

RecordIOReader::~RecordIOReader() { Close(); }

void RecordIOReader::Close() {
  if (fp_) {
    std::fclose(fp_);
    fp_ = nullptr;
  }
}

uint64_t RecordIOReader::Tell() { return fp_ ? (uint64_t)std::ftell(fp_) : 0; }

void RecordIOReader::Seek(uint64_t pos) {
  if (fp_) std::fseek(fp_, (long)pos, SEEK_SET);
}

bool RecordIOReader::NextRecord(std::vector<char>* out) {
  out->clear();
  if (!fp_) return false;
  bool in_continuation = false;
  while (true) {
    uint32_t magic = 0, lrec = 0;
    if (std::fread(&magic, 4, 1, fp_) != 1) return false;
    if (magic != RecordIOWriter::kMagic) return false;  // corrupt / EOF pad
    if (std::fread(&lrec, 4, 1, fp_) != 1) return false;
    const uint32_t cflag = DecodeFlag(lrec);
    const uint32_t len = DecodeLength(lrec);
    const size_t cur = out->size();
    // Continuation chunks were split at a magic word in the payload:
    // reinsert it between chunks.
    if (in_continuation) {
      const uint32_t m = RecordIOWriter::kMagic;
      out->resize(cur + 4);
      std::memcpy(out->data() + cur, &m, 4);
    }
    const size_t base = out->size();
    out->resize(base + len);
    if (len && std::fread(out->data() + base, 1, len, fp_) != len) return false;
    const uint32_t pad = (4 - (len & 3u)) & 3u;
    if (pad) std::fseek(fp_, pad, SEEK_CUR);
    if (cflag == 0 || cflag == 3) return true;
    in_continuation = true;
  }
}

}  // namespace mxtpu
