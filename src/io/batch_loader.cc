// Threaded image-record batch loader: the native data plane that keeps the
// TPU fed.  Reference behavior: src/io/iter_image_recordio_2.cc
// (ImageRecordIOParser2: multithreaded JPEG decode + augment + batch
// assembly) and the prefetcher layer iter_prefetcher.h, rebuilt without
// OpenCV/dmlc on a std::thread worker pool.
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include "recordio.h"

namespace mxtpu {

bool DecodeJPEG(const uint8_t* buf, size_t len, std::vector<uint8_t>* out,
                int* width, int* height, int* channels);
void ResizeBilinear(const uint8_t* src, int sh, int sw, int c, uint8_t* dst,
                    int dh, int dw);
void NormalizeToCHW(const uint8_t* src, int h, int w, int src_c, float* dst,
                    int out_c, const float* mean, const float* stdv,
                    int mirror);

// Image-record payload header: struct {u32 flag; f32 label; u64 id; u64 id2}
// (+ flag extra f32 labels), mirroring python/mxnet/recordio.py _IR_FORMAT.
struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};

struct LoaderConfig {
  int batch_size = 1;
  int height = 224;
  int width = 224;
  int channels = 3;
  int label_width = 1;
  int rand_crop = 0;
  int rand_mirror = 0;
  int shuffle = 0;
  int num_threads = 4;
  uint64_t seed = 0;
  float mean[3] = {0.f, 0.f, 0.f};
  float stdv[3] = {1.f, 1.f, 1.f};
};

struct ItemPlan {
  uint64_t offset;
  int mirror;
  float crop_y;  // in [0,1): relative crop origin
  float crop_x;
};

class ImageRecordLoader {
 public:
  ImageRecordLoader(const std::string& rec_path, const LoaderConfig& cfg)
      : path_(rec_path), cfg_(cfg), rng_(cfg.seed) {
    {
      RecordIOReader probe(rec_path);
      ok_ = probe.ok();
    }
    if (!ok_) return;
    // Prefer the .idx sidecar (written by im2rec / MXIndexedRecordIO) over a
    // full sequential scan — on large .rec files the scan is minutes of IO.
    if (!LoadIndex(rec_path)) {
      RecordIOReader scan(rec_path);
      std::vector<char> tmp;
      uint64_t pos = scan.Tell();
      while (scan.NextRecord(&tmp)) {
        offsets_.push_back(pos);
        pos = scan.Tell();
      }
    }
    order_.resize(offsets_.size());
    Reset();
    // Persistent worker pool with per-worker readers (the reference keeps a
    // persistent decode pool in ImageRecordIOParser2 for the same reason:
    // per-batch thread/file churn would rival the decode cost).
    const int nt = std::max(1, cfg_.num_threads);
    workers_.reserve(nt);
    for (int t = 0; t < nt; ++t)
      workers_.emplace_back([this]() { WorkerLoop(); });
  }

  ~ImageRecordLoader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      shutdown_ = true;
    }
    cv_start_.notify_all();
    for (auto& th : workers_) th.join();
  }

  bool ok() const { return ok_; }
  size_t size() const { return offsets_.size(); }

  void Reset() {
    for (size_t i = 0; i < order_.size(); ++i) order_[i] = i;
    if (cfg_.shuffle) {
      std::shuffle(order_.begin(), order_.end(), rng_);
    }
    cursor_ = 0;
  }

  // Fills data (N,C,H,W f32) and label (N,label_width f32).  Returns the
  // number of valid samples (0 at epoch end; < batch_size on last batch,
  // remaining slots zero-filled).
  int NextBatch(float* data, float* label) {
    const size_t n = offsets_.size();
    if (cursor_ >= n) return 0;
    const int bs = cfg_.batch_size;
    const int valid = (int)std::min((size_t)bs, n - cursor_);
    // Plan randomness on the control thread for determinism.
    plan_.resize(valid);
    std::uniform_real_distribution<float> uf(0.f, 1.f);
    for (int i = 0; i < valid; ++i) {
      plan_[i].offset = offsets_[order_[cursor_ + i]];
      plan_[i].mirror = cfg_.rand_mirror ? (rng_() & 1) : 0;
      plan_[i].crop_y = cfg_.rand_crop ? uf(rng_) : 0.5f;
      plan_[i].crop_x = cfg_.rand_crop ? uf(rng_) : 0.5f;
    }
    const size_t dstride = (size_t)cfg_.channels * cfg_.height * cfg_.width;
    std::memset(data, 0, sizeof(float) * dstride * bs);
    std::memset(label, 0, sizeof(float) * (size_t)cfg_.label_width * bs);
    {
      std::lock_guard<std::mutex> lk(mu_);
      cur_data_ = data;
      cur_label_ = label;
      cur_valid_ = valid;
      next_item_.store(0);
      done_workers_ = 0;
      ++gen_;
    }
    cv_start_.notify_all();
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_done_.wait(lk, [&] { return done_workers_ == (int)workers_.size(); });
    }
    cursor_ += valid;
    return valid;
  }

 private:
  // Parses PREFIX.idx ("key\toffset\n" per record) next to PREFIX.rec.
  bool LoadIndex(const std::string& rec_path) {
    std::string idx_path = rec_path;
    const size_t dot = idx_path.rfind('.');
    if (dot == std::string::npos) return false;
    idx_path = idx_path.substr(0, dot) + ".idx";
    std::FILE* f = std::fopen(idx_path.c_str(), "r");
    if (!f) return false;
    char line[256];
    while (std::fgets(line, sizeof(line), f)) {
      long long key, off;
      if (std::sscanf(line, "%lld\t%lld", &key, &off) == 2)
        offsets_.push_back((uint64_t)off);
    }
    std::fclose(f);
    return !offsets_.empty();
  }

  void WorkerLoop() {
    RecordIOReader reader(path_);
    std::vector<char> rec;
    std::vector<uint8_t> img, resized;
    uint64_t seen_gen = 0;
    while (true) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_start_.wait(lk, [&] { return shutdown_ || gen_ > seen_gen; });
        if (shutdown_) return;
        seen_gen = gen_;
      }
      const size_t dstride = (size_t)cfg_.channels * cfg_.height * cfg_.width;
      while (true) {
        const int i = next_item_.fetch_add(1);
        if (i >= cur_valid_) break;
        reader.Seek(plan_[i].offset);
        if (!reader.NextRecord(&rec)) continue;
        DecodeOne(rec, plan_[i], cur_data_ + dstride * i,
                  cur_label_ + (size_t)cfg_.label_width * i, &img, &resized);
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (++done_workers_ == (int)workers_.size()) cv_done_.notify_all();
      }
    }
  }

 private:
  void DecodeOne(const std::vector<char>& rec, const ItemPlan& plan,
                 float* data, float* label, std::vector<uint8_t>* img,
                 std::vector<uint8_t>* resized) {
    if (rec.size() < sizeof(IRHeader)) return;
    IRHeader hdr;
    std::memcpy(&hdr, rec.data(), sizeof(hdr));
    const char* payload = rec.data() + sizeof(hdr);
    size_t payload_len = rec.size() - sizeof(hdr);
    if (hdr.flag > 0) {
      const size_t lbytes = (size_t)hdr.flag * 4;
      if (payload_len < lbytes) return;
      const int nl = std::min((int)hdr.flag, cfg_.label_width);
      std::memcpy(label, payload, (size_t)nl * 4);
      payload += lbytes;
      payload_len -= lbytes;
    } else {
      label[0] = hdr.label;
    }
    int w = 0, h = 0, c = 0;
    if (!DecodeJPEG((const uint8_t*)payload, payload_len, img, &w, &h, &c))
      return;
    const int th = cfg_.height, tw = cfg_.width;
    const uint8_t* src = img->data();
    std::vector<uint8_t> cropped;
    if (cfg_.rand_crop && h > th && w > tw) {
      // Random fixed-size crop then no resize (sizes match), mirroring the
      // reference's rand_crop augmenter.
      const int oy = (int)(plan.crop_y * (h - th));
      const int ox = (int)(plan.crop_x * (w - tw));
      cropped.resize((size_t)th * tw * c);
      for (int y = 0; y < th; ++y)
        std::memcpy(cropped.data() + (size_t)y * tw * c,
                    src + ((size_t)(y + oy) * w + ox) * c, (size_t)tw * c);
      src = cropped.data();
      w = tw;
      h = th;
    }
    if (h != th || w != tw) {
      resized->resize((size_t)th * tw * c);
      ResizeBilinear(src, h, w, c, resized->data(), th, tw);
      src = resized->data();
    }
    NormalizeToCHW(src, th, tw, c, data, cfg_.channels, cfg_.mean, cfg_.stdv,
                   plan.mirror);
  }

  std::string path_;
  LoaderConfig cfg_;
  std::mt19937_64 rng_;
  bool ok_ = false;
  std::vector<uint64_t> offsets_;
  std::vector<size_t> order_;
  size_t cursor_ = 0;
  // worker-pool state (guarded by mu_ except the atomics)
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_start_, cv_done_;
  std::vector<ItemPlan> plan_;
  float* cur_data_ = nullptr;
  float* cur_label_ = nullptr;
  int cur_valid_ = 0;
  std::atomic<int> next_item_{0};
  int done_workers_ = 0;
  uint64_t gen_ = 0;
  bool shutdown_ = false;
};

}  // namespace mxtpu

// ---------------------------------------------------------------------------
// C API (ctypes boundary — the reference's equivalent is the MXRecordIO* /
// MXDataIter* entry points in src/c_api/c_api.cc).
// ---------------------------------------------------------------------------
extern "C" {

void* MXTRecordIOWriterCreate(const char* path) {
  auto* w = new mxtpu::RecordIOWriter(path);
  if (!w->ok()) {
    delete w;
    return nullptr;
  }
  return w;
}

uint64_t MXTRecordIOWriterWrite(void* handle, const char* buf, uint64_t size) {
  return static_cast<mxtpu::RecordIOWriter*>(handle)->WriteRecord(buf, size);
}

uint64_t MXTRecordIOWriterTell(void* handle) {
  return static_cast<mxtpu::RecordIOWriter*>(handle)->Tell();
}

void MXTRecordIOWriterFree(void* handle) {
  delete static_cast<mxtpu::RecordIOWriter*>(handle);
}

struct ReaderHandle {
  mxtpu::RecordIOReader reader;
  std::vector<char> buf;
  explicit ReaderHandle(const char* path) : reader(path) {}
};

void* MXTRecordIOReaderCreate(const char* path) {
  auto* r = new ReaderHandle(path);
  if (!r->reader.ok()) {
    delete r;
    return nullptr;
  }
  return r;
}

// Reads the next record; *ptr points into an internal buffer valid until the
// next call.  Returns 1 on success (possibly zero-length record), 0 at EOF.
int MXTRecordIOReaderNext(void* handle, const char** ptr, uint64_t* len) {
  auto* r = static_cast<ReaderHandle*>(handle);
  if (!r->reader.NextRecord(&r->buf)) {
    *ptr = nullptr;
    *len = 0;
    return 0;
  }
  *len = r->buf.size();
  static const char kEmpty = 0;
  *ptr = r->buf.empty() ? &kEmpty : r->buf.data();
  return 1;
}

void MXTRecordIOReaderSeek(void* handle, uint64_t pos) {
  static_cast<ReaderHandle*>(handle)->reader.Seek(pos);
}

uint64_t MXTRecordIOReaderTell(void* handle) {
  return static_cast<ReaderHandle*>(handle)->reader.Tell();
}

void MXTRecordIOReaderFree(void* handle) {
  delete static_cast<ReaderHandle*>(handle);
}

int MXTDecodeJPEG(const uint8_t* buf, uint64_t len, uint8_t* out,
                  uint64_t out_capacity, int* w, int* h, int* c) {
  std::vector<uint8_t> tmp;
  if (!mxtpu::DecodeJPEG(buf, len, &tmp, w, h, c)) return -1;
  if (tmp.size() > out_capacity) return -2;
  std::memcpy(out, tmp.data(), tmp.size());
  return 0;
}

int MXTResizeBilinear(const uint8_t* src, int sh, int sw, int c, uint8_t* dst,
                      int dh, int dw) {
  mxtpu::ResizeBilinear(src, sh, sw, c, dst, dh, dw);
  return 0;
}

void* MXTImageRecordLoaderCreate(const char* rec_path, int batch_size,
                                 int height, int width, int channels,
                                 int label_width, int rand_crop,
                                 int rand_mirror, int shuffle, int num_threads,
                                 uint64_t seed, const float* mean,
                                 const float* stdv) {
  mxtpu::LoaderConfig cfg;
  cfg.batch_size = batch_size;
  cfg.height = height;
  cfg.width = width;
  cfg.channels = channels;
  cfg.label_width = label_width;
  cfg.rand_crop = rand_crop;
  cfg.rand_mirror = rand_mirror;
  cfg.shuffle = shuffle;
  cfg.num_threads = num_threads;
  cfg.seed = seed;
  for (int i = 0; i < 3 && i < channels; ++i) {
    if (mean) cfg.mean[i] = mean[i];
    if (stdv) cfg.stdv[i] = stdv[i];
  }
  auto* l = new mxtpu::ImageRecordLoader(rec_path, cfg);
  if (!l->ok()) {
    delete l;
    return nullptr;
  }
  return l;
}

uint64_t MXTImageRecordLoaderSize(void* handle) {
  return static_cast<mxtpu::ImageRecordLoader*>(handle)->size();
}

int MXTImageRecordLoaderNext(void* handle, float* data, float* label) {
  return static_cast<mxtpu::ImageRecordLoader*>(handle)->NextBatch(data, label);
}

void MXTImageRecordLoaderReset(void* handle) {
  static_cast<mxtpu::ImageRecordLoader*>(handle)->Reset();
}

void MXTImageRecordLoaderFree(void* handle) {
  delete static_cast<mxtpu::ImageRecordLoader*>(handle);
}

}  // extern "C"
