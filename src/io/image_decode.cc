// JPEG decode + bilinear resize + augmentation primitives for the native
// data plane.  Reference behavior: src/io/iter_image_recordio_2.cc (OpenCV
// imdecode + augmenters) rebuilt on libjpeg with no OpenCV dependency.
#include <cstdio>

#include <jpeglib.h>

#include <algorithm>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <vector>

namespace mxtpu {

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

static void jpeg_error_exit(j_common_ptr cinfo) {
  JpegErrorMgr* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Decodes a JPEG buffer to interleaved RGB u8.  Returns false on failure.
bool DecodeJPEG(const uint8_t* buf, size_t len, std::vector<uint8_t>* out,
                int* width, int* height, int* channels) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), (unsigned long)len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int w = cinfo.output_width, h = cinfo.output_height;
  const int c = cinfo.output_components;
  out->resize((size_t)w * h * c);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() + (size_t)cinfo.output_scanline * w * c;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *width = w;
  *height = h;
  *channels = c;
  return true;
}

// Bilinear resize, interleaved u8 HWC.
void ResizeBilinear(const uint8_t* src, int sh, int sw, int c, uint8_t* dst,
                    int dh, int dw) {
  const float sy = dh > 1 ? (float)(sh - 1) / (dh - 1) : 0.f;
  const float sx = dw > 1 ? (float)(sw - 1) / (dw - 1) : 0.f;
  for (int y = 0; y < dh; ++y) {
    const float fy = y * sy;
    const int y0 = (int)fy;
    const int y1 = std::min(y0 + 1, sh - 1);
    const float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      const float fx = x * sx;
      const int x0 = (int)fx;
      const int x1 = std::min(x0 + 1, sw - 1);
      const float wx = fx - x0;
      for (int k = 0; k < c; ++k) {
        const float v00 = src[((size_t)y0 * sw + x0) * c + k];
        const float v01 = src[((size_t)y0 * sw + x1) * c + k];
        const float v10 = src[((size_t)y1 * sw + x0) * c + k];
        const float v11 = src[((size_t)y1 * sw + x1) * c + k];
        const float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                        v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[((size_t)y * dw + x) * c + k] = (uint8_t)(v + 0.5f);
      }
    }
  }
}

// HWC u8 (src_c interleaved channels) -> CHW float (out_c planes) with
// mean/std and optional horizontal mirror.  out_c == 1 with an RGB source
// converts to luminance (matching the reference's grayscale decode path);
// otherwise extra output planes replicate the last source channel.
void NormalizeToCHW(const uint8_t* src, int h, int w, int src_c, float* dst,
                    int out_c, const float* mean, const float* stdv,
                    int mirror) {
  const bool to_gray = (out_c == 1 && src_c >= 3);
  for (int k = 0; k < out_c; ++k) {
    const float m = mean ? mean[k] : 0.f;
    const float s = stdv ? stdv[k] : 1.f;
    const int sk = k < src_c ? k : src_c - 1;
    float* plane = dst + (size_t)k * h * w;
    for (int y = 0; y < h; ++y) {
      for (int x = 0; x < w; ++x) {
        const int sx = mirror ? (w - 1 - x) : x;
        const uint8_t* px = src + ((size_t)y * w + sx) * src_c;
        const float v = to_gray
                            ? 0.299f * px[0] + 0.587f * px[1] + 0.114f * px[2]
                            : (float)px[sk];
        plane[(size_t)y * w + x] = (v - m) / s;
      }
    }
  }
}

}  // namespace mxtpu
