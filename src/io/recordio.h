// RecordIO on-disk format (dmlc-compatible framing).
//
// Reference behavior: 3rdparty/dmlc-core recordio (used by the reference's
// src/io/ iterators and python/mxnet/recordio.py via the C API
// MXRecordIOWriterCreate/MXRecordIOReaderCreate).  The framing is:
//   [kMagic:u32le][lrec:u32le][payload ... pad to 4B]
// where lrec encodes cflag (upper 3 bits) and length (lower 29 bits).
// Payloads containing the magic word are split into continuation chunks
// (cflag 1=begin, 2=middle, 3=end; 0=whole record) so a reader can always
// resynchronize on the magic word.
#ifndef MXTPU_IO_RECORDIO_H_
#define MXTPU_IO_RECORDIO_H_

#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

namespace mxtpu {

class RecordIOWriter {
 public:
  static const uint32_t kMagic = 0xced7230a;
  explicit RecordIOWriter(const std::string& path);
  ~RecordIOWriter();
  bool ok() const { return fp_ != nullptr; }
  // Writes one logical record; returns byte offset of the record start.
  uint64_t WriteRecord(const void* buf, size_t size);
  uint64_t Tell();
  void Close();

 private:
  std::FILE* fp_ = nullptr;
};

class RecordIOReader {
 public:
  explicit RecordIOReader(const std::string& path);
  ~RecordIOReader();
  bool ok() const { return fp_ != nullptr; }
  // Reads the next logical record into out; false at EOF.
  bool NextRecord(std::vector<char>* out);
  void Seek(uint64_t pos);
  uint64_t Tell();
  void Close();

 private:
  std::FILE* fp_ = nullptr;
};

}  // namespace mxtpu

#endif  // MXTPU_IO_RECORDIO_H_
