// Native-side unit tests for the data plane (reference tests/cpp/ pattern:
// C++ components get C++ tests — engine/storage/op harness there, the
// RecordIO framing layer here).  Assert-based standalone binary; built and
// run by `make -C src test` (wrapped by tests/test_native_cpp.py).
#include <unistd.h>

#include <cassert>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

#include "../io/recordio.h"

namespace {

int failures = 0;

#define CHECK_TRUE(cond)                                              \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,    \
                   #cond);                                            \
      ++failures;                                                     \
    }                                                                 \
  } while (0)

std::string TempPath(const char* name) {
  std::string dir = "/tmp";
  if (const char* t = std::getenv("TMPDIR")) dir = t;
  // pid suffix: concurrent runs must not clobber each other's files
  return dir + "/" + name + "." + std::to_string(getpid());
}

// Round-trip records of many sizes, including payloads that embed the magic
// word (must be split into continuation chunks and reassembled losslessly).
void TestRoundTrip() {
  const std::string path = TempPath("mxtpu_test_rio.rec");
  std::mt19937 rng(7);
  std::vector<std::string> records;
  for (int i = 0; i < 64; ++i) {
    size_t len = (i * 37) % 300 + 1;
    std::string payload(len, '\0');
    for (auto& c : payload) c = static_cast<char>(rng() & 0xff);
    if (i % 5 == 0) {
      // plant the magic word mid-payload to force chunking
      uint32_t magic = mxtpu::RecordIOWriter::kMagic;
      if (payload.size() >= 8) std::memcpy(&payload[2], &magic, 4);
    }
    records.push_back(payload);
  }
  std::vector<uint64_t> offsets;
  {
    mxtpu::RecordIOWriter w(path);
    CHECK_TRUE(w.ok());
    for (auto& r : records) offsets.push_back(w.WriteRecord(r.data(), r.size()));
  }
  {
    mxtpu::RecordIOReader r(path);
    CHECK_TRUE(r.ok());
    std::vector<char> buf;
    size_t n = 0;
    while (r.NextRecord(&buf)) {
      CHECK_TRUE(n < records.size());
      CHECK_TRUE(buf.size() == records[n].size());
      CHECK_TRUE(std::memcmp(buf.data(), records[n].data(), buf.size()) == 0);
      ++n;
    }
    CHECK_TRUE(n == records.size());
  }
  // indexed access: seek straight to each record (the .idx fast path)
  {
    mxtpu::RecordIOReader r(path);
    std::vector<char> buf;
    for (size_t i = 0; i < records.size(); i += 7) {
      r.Seek(offsets[i]);
      CHECK_TRUE(r.NextRecord(&buf));
      CHECK_TRUE(buf.size() == records[i].size());
      CHECK_TRUE(std::memcmp(buf.data(), records[i].data(), buf.size()) == 0);
    }
  }
  std::remove(path.c_str());
}

// Empty file and missing file behave as clean EOF / not-ok.
void TestEdgeCases() {
  const std::string path = TempPath("mxtpu_test_rio_empty.rec");
  { mxtpu::RecordIOWriter w(path); CHECK_TRUE(w.ok()); }
  {
    mxtpu::RecordIOReader r(path);
    CHECK_TRUE(r.ok());
    std::vector<char> buf;
    CHECK_TRUE(!r.NextRecord(&buf));
  }
  std::remove(path.c_str());
  mxtpu::RecordIOReader missing(TempPath("definitely_not_there.rec"));
  CHECK_TRUE(!missing.ok());
  // zero-length record is legal
  const std::string p2 = TempPath("mxtpu_test_rio_zero.rec");
  {
    mxtpu::RecordIOWriter w(p2);
    w.WriteRecord("", 0);
    w.WriteRecord("x", 1);
  }
  {
    mxtpu::RecordIOReader r(p2);
    std::vector<char> buf;
    CHECK_TRUE(r.NextRecord(&buf));
    CHECK_TRUE(buf.empty());
    CHECK_TRUE(r.NextRecord(&buf));
    CHECK_TRUE(buf.size() == 1 && buf[0] == 'x');
  }
  std::remove(p2.c_str());
}

// Tell() after write equals file position a reader can resume from
// (mirrors python recordio.MXIndexedRecordIO index building).
void TestTellResume() {
  const std::string path = TempPath("mxtpu_test_rio_tell.rec");
  uint64_t second_off;
  {
    mxtpu::RecordIOWriter w(path);
    w.WriteRecord("first", 5);
    second_off = w.Tell();
    w.WriteRecord("second", 6);
  }
  {
    mxtpu::RecordIOReader r(path);
    r.Seek(second_off);
    std::vector<char> buf;
    CHECK_TRUE(r.NextRecord(&buf));
    CHECK_TRUE(std::string(buf.begin(), buf.end()) == "second");
  }
  std::remove(path.c_str());
}

}  // namespace

int main() {
  TestRoundTrip();
  TestEdgeCases();
  TestTellResume();
  if (failures == 0) {
    std::printf("ALL NATIVE TESTS PASSED\n");
    return 0;
  }
  std::fprintf(stderr, "%d native test failures\n", failures);
  return 1;
}
