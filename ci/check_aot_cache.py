#!/usr/bin/env python
"""AOT persistent-cache smoke (ISSUE 6 CI satellite) — unit tier.

Runs ``Engine.warmup()`` over a bucket ladder in TWO fresh subprocesses
against one shared ``MXNET_AOT_CACHE`` directory:

* run 1 (cold): every bucket must be an AOT-cache **miss** (compiled and
  persisted), paying real XLA compile seconds;
* run 2 (warm restart): every bucket must be an AOT-cache **hit** with zero
  misses, zero errors, and — the deterministic heart of the acceptance —
  ``warmup.aot_compile_s == 0``: the second engine compiled ZERO fresh XLA
  modules, the whole compile storm became disk reads.  Its warmup
  wall-clock must also beat run 1's; the model below is deep enough that
  per-bucket compile (hundreds of ms) dwarfs a restore (tens of ms), but
  wall-clock on a shared box is still noisy, so the timing comparison alone
  gets up to two warm re-runs (cache stays populated; best-of compared) —
  the hit/miss/compile-seconds assertions stay strict on the first warm run.

Subprocesses matter: the cache must survive a real process boundary, and
``MXNET_AOT_CACHE`` must be in the environment before import (jax latches
its persistent-cache directory at first compile).

Usage (ci/run_tests.sh unit tier)::

    python ci/check_aot_cache.py            # parent: orchestrates both runs
    python ci/check_aot_cache.py --child    # one warmup run (internal)
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

LADDER = (1, 2, 4)


def child():
    sys.path.insert(0, os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..")))
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import compile_cache, nd, serving

    # deep enough that one bucket's XLA compile is hundreds of ms — the
    # quantity the warm restart must drive to zero (a restore is a ~10ms
    # disk read; tiny_mlp_checkpoint's compile is so small that restore vs
    # compile wall-clock is a coin flip on a loaded box)
    x = mx.sym.Variable("data")
    for i in range(6):
        x = mx.sym.Activation(
            mx.sym.FullyConnected(x, num_hidden=64, name="fc%d" % i),
            act_type="relu", name="relu%d" % i)
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(x, num_hidden=4, name="out"), name="softmax")
    exe = sym.simple_bind(grad_req="null", data=(2, 8))
    rng = np.random.RandomState(0)
    params = {n: nd.array(rng.randn(*a.shape).astype(np.float32) * 0.1)
              for n, a in exe.arg_dict.items()
              if n not in ("data", "softmax_label")}

    eng = serving.Engine(sym, params, {"data": (8,)},
                         ladder=serving.BucketLadder(LADDER), start=False)
    t0 = time.perf_counter()
    eng.warmup()
    warmup_s = time.perf_counter() - t0
    # one request proves the warmed engine actually serves
    eng.start()
    out = eng.predict({"data": np.zeros((2, 8), np.float32)})
    assert out[0].shape == (2, 4)
    stats = eng.stats()
    eng.close()
    print("AOT_SMOKE " + json.dumps({
        "warmup_s": round(warmup_s, 4),
        "warmup": stats["warmup"],
        "cache": compile_cache.stats(),
        "compiles": stats["compiles"]}))
    return 0


def main():
    if "--child" in sys.argv:
        return child()
    cache_dir = tempfile.mkdtemp(prefix="mxnet-aot-smoke-")
    try:
        return _main(cache_dir)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def _main(cache_dir):
    env = dict(os.environ, MXNET_AOT_CACHE=cache_dir)

    def one_run(i):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            env=env, capture_output=True, text=True, timeout=600)
        sys.stdout.write(proc.stdout)
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr)
            print("check_aot_cache: FAIL run %d exited %d"
                  % (i, proc.returncode), file=sys.stderr)
            return None
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("AOT_SMOKE ")]
        if not line:
            print("check_aot_cache: FAIL run %d printed no AOT_SMOKE line"
                  % i, file=sys.stderr)
            return None
        return json.loads(line[-1][len("AOT_SMOKE "):])

    cold = one_run(1)
    warm = one_run(2)
    if cold is None or warm is None:
        return 1
    # the wall-clock beat is load-sensitive (an anomalously fast cold run
    # can land under a noisy warm restore): best-of up to 3 warm runs for
    # the TIMING only — the hit/miss/compile-seconds acceptance below
    # judges the first warm run
    warm_s = warm["warmup_s"]
    for i in (3, 4):
        if warm_s < cold["warmup_s"]:
            break
        rerun = one_run(i)
        if rerun is None:
            return 1
        warm_s = min(warm_s, rerun["warmup_s"])
    n = len(LADDER)
    failures = []
    if cold["warmup"]["cache_misses"] != n or cold["warmup"]["cache_hits"]:
        failures.append("cold run: expected %d misses/0 hits, got %s"
                        % (n, cold["warmup"]))
    if cold["warmup"]["aot_compile_s"] <= 0:
        failures.append("cold run paid no XLA compile seconds: %s"
                        % cold["warmup"])
    if warm["warmup"]["cache_hits"] != n or warm["warmup"]["cache_misses"]:
        failures.append("warm run: expected %d hits/0 misses (zero fresh "
                        "modules), got %s" % (n, warm["warmup"]))
    if warm["warmup"]["aot_compile_s"] != 0:
        failures.append("warm run compiled fresh XLA modules "
                        "(aot_compile_s=%s)"
                        % warm["warmup"]["aot_compile_s"])
    if warm["cache"]["errors"]:
        failures.append("warm run: %d cache errors" % warm["cache"]["errors"])
    if warm["compiles"] != 0:
        failures.append("warm run: stats()['compiles']=%d, restores must "
                        "not count as compiles" % warm["compiles"])
    if not warm_s < cold["warmup_s"]:
        failures.append("warm warmup %.3fs did not beat cold %.3fs"
                        % (warm_s, cold["warmup_s"]))
    for msg in failures:
        print("check_aot_cache: FAIL %s" % msg, file=sys.stderr)
    if not failures:
        print("check_aot_cache: ok — cold %.3fs (%d compiles, %.3fs in "
              "XLA) -> warm %.3fs (all cached, 0 compile seconds, %.1fx "
              "faster)"
              % (cold["warmup_s"], n, cold["warmup"]["aot_compile_s"],
                 warm_s, cold["warmup_s"] / max(warm_s, 1e-9)))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
