#!/usr/bin/env python
"""Graph-pass smoke (ISSUE 7 CI satellite) — unit tier.

Builds a symbol whose captured plan carries (a) a duplicated subexpression
(two auto-named exp->sqrt chains over the same input — the helper-function
duplication CSE exists for), which after the merge leaves a known-DEAD
branch for the eliminator to sweep, (b) a constant subgraph (an ``arange``
feeding an add) for the folder, and (c) an eval-identity Dropout for the
inference rewrite.  Asserts:

* post-pass node count equals the hand-counted minimum (and the captured
  count equals the hand-counted raw plan);
* forward results with passes ON match passes OFF;
* with ``MXNET_GRAPH_PASSES=0`` the optimized plan IS the raw captured
  plan (same object — byte-identical lowering) and no stats are recorded.

Run from ci/run_tests.sh unit tier::

    python ci/check_graph_passes.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def build():
    import mxnet_tpu as mx

    data = mx.sym.var("data")
    # duplicated subexpression: helper re-derives the same chain per head
    def norm(x):
        return mx.sym.sqrt(mx.sym.exp(x))

    out = norm(data) * norm(data)          # 2x (exp, sqrt) + mul  -> 5 raw
    offset = mx.sym.arange(0, 4)           # constant subgraph     -> +1
    out = out + offset                     # live consumer         -> +1
    out = mx.sym.Dropout(out, p=0.5)       # eval-identity         -> +1
    return out                             # raw plan: 8 nodes


# hand count after the pipeline (eval mode):
#   arange folds to a baked constant            (-1)
#   CSE merges the second exp->sqrt chain       (redirect)
#   Dropout deleted (identity at inference)     (-1)
#   DCE sweeps the orphaned exp+sqrt pair       (-2)
# leaving: exp, sqrt, mul, add                  = 4 nodes
RAW_NODES = 8
MIN_NODES = 4


def run(passes, x):
    os.environ["MXNET_GRAPH_PASSES"] = passes
    from mxnet_tpu import nd

    exe = build().bind(None, {"data": nd.array(x)})
    out = exe.forward()[0].asnumpy()
    plan, heads, const = exe._opt_plan(False)
    return exe, out, plan, const


def main():
    x = np.random.RandomState(0).rand(2, 4).astype(np.float32)

    exe0, out0, plan0, const0 = run("0", x)
    assert len(exe0._plan) == RAW_NODES, \
        "captured %d nodes, hand count says %d" % (len(exe0._plan), RAW_NODES)
    assert plan0 is exe0._plan and const0 is None, \
        "passes off must hand the RAW plan to lowering, untouched"
    assert exe0.pass_stats() == {}, exe0.pass_stats()

    exe1, out1, plan1, const1 = run("1", x)
    assert len(exe1._plan) == RAW_NODES
    assert len(plan1) == MIN_NODES, \
        "post-pass plan has %d nodes, hand count says %d (plan: %s)" % (
            len(plan1), MIN_NODES, [n.name for n, _ in plan1])
    assert const1, "arange should have folded into a baked constant"
    stats = exe1.pass_stats()["eval"]
    assert (stats["nodes_pre"], stats["nodes_post"]) == (RAW_NODES, MIN_NODES)

    assert np.allclose(out0, out1, atol=1e-6), \
        "forward parity broke: max delta %g" % np.abs(out0 - out1).max()

    print("check_graph_passes: ok (plan %d -> %d nodes, parity holds, "
          "passes-off plan untouched)" % (RAW_NODES, MIN_NODES))


if __name__ == "__main__":
    main()
