#!/usr/bin/env python
"""Autotuning smoke (ISSUE 9) — run from ci/run_tests.sh unit tier.

End-to-end over real subprocesses, the way an operator would run it:

1. ``tools/loadgen.py --save-trace`` records a skewed traffic trace
   (request sizes 3/5/6 against the default 1,2,4,8 ladder — every
   request pads badly) and the trace passes the schema lint;
2. the ladder tuner's proposal from that trace scores a STRICTLY lower
   padding-waste x compile-count objective than the default ladder on
   the same trace (the ISSUE 9 acceptance);
3. ``tools/autotune.py search`` (measured dconv block-shape search on a
   CPU-sized problem, then the ladder search) persists winners, and a
   SECOND run of each against the warm store performs ZERO new
   measurements;
4. the dconv winner is never worse than the hand-tuned default on the
   microbench (the searcher measures the default first and keeps it on
   ties);
5. (ISSUE 18) two exhaustive-grid seeding runs under MXNET_COSTPLANE
   accumulate trial rows, then the learned cost model's
   predict-then-measure finds the known dconv winner deterministically
   (trial seconds replayed from the store) with at most HALF the grid's
   measured trials — the acceptance gate;
6. a CLI ``--strategy predict`` run at a fresh shape measures at most
   half its grid, surfaces ``trials_saved`` (AUTOTUNE line and bench
   telemetry block, schema-linted), stays never-worse, and a second run
   is a warm hit with zero measurements;
7. ``--all-kernels`` sweeps every runnable space — the new kernel spaces
   plus the non-kernel ``fused_step_layout`` — each recording an
   AUTOTUNE line, with one final telemetry block.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run(cmd, env=None):
    print("+ %s" % " ".join(cmd), flush=True)
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=REPO, timeout=600)
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        raise SystemExit("FAIL: %r exited %d" % (cmd, proc.returncode))
    return proc.stdout


def autotune_line(out):
    for line in out.splitlines():
        if line.startswith("AUTOTUNE "):
            return json.loads(line[len("AUTOTUNE "):])
    raise SystemExit("FAIL: no AUTOTUNE line in output")


def main():
    from ci.check_bench_schema import validate_trace_file

    tmp = tempfile.mkdtemp(prefix="mxnet_autotune_smoke_")
    trace = os.path.join(tmp, "trace.jsonl")
    env = dict(os.environ)
    env["MXNET_AUTOTUNE_CACHE"] = os.path.join(tmp, "autotune.json")
    py = sys.executable

    # 1: record traffic whose sizes (3/5/6) the default ladder pads badly
    run([py, os.path.join(REPO, "tools", "loadgen.py"), "--mode", "open",
         "--rate", "150", "--duration", "1.0", "--sizes", "3,5,6",
         "--batch-ladder", "1,2,4,8", "--save-trace", trace], env=env)
    n = validate_trace_file(trace)
    print("trace lint ok: %d records" % n)

    # 2: the proposal beats the default on its own trace (acceptance).
    # Replay with the SAME flush deadline the recording engine batched
    # under (loadgen's default --max-wait-ms 2), so the tuner models the
    # coalescing that actually produced — and would serve — this traffic
    from mxnet_tpu.autotune import ladder as lt

    wait_s = 0.002
    recs = lt.load_trace(trace)
    obj_default = lt.objective((1, 2, 4, 8), recs, max_wait_s=wait_s)
    tuned, rep = lt.propose(recs, max_wait_s=wait_s)
    print("ladder objective: default %.4f -> tuned %s %.4f"
          % (obj_default, tuned, rep["objective_tuned"]))
    assert rep["objective_tuned"] < obj_default, \
        "proposed ladder %s did not beat the default (%.4f >= %.4f)" % (
            tuned, rep["objective_tuned"], obj_default)

    at = os.path.join(REPO, "tools", "autotune.py")
    # 3a: measured dconv search (CPU-sized problem), never-worse winner
    out = autotune_line(run(
        [py, at, "search", "--kernel", "dconv_col_pallas",
         "--warmup", "1", "--repeat", "2"], env=env))
    assert out["measurements"] > 0 and not out["cached"]
    # never-worse is a BEHAVIORAL gate: a non-default winner must have
    # strictly beaten the measured default (best_s <= default_s holds by
    # construction, so asserting only that could never catch a searcher
    # that prefers a tying candidate over the hand-tuned default)
    from mxnet_tpu.autotune import get_space

    default_cfg = get_space("dconv_col_pallas").default
    assert out["config"] == default_cfg or out["best_s"] < out["default_s"], \
        "non-default winner must STRICTLY beat the measured default: %r" % out
    # 3b: warm store => zero new measurements
    out2 = autotune_line(run(
        [py, at, "search", "--kernel", "dconv_col_pallas",
         "--warmup", "1", "--repeat", "2"], env=env))
    assert out2["cached"] and out2["measurements"] == 0, out2
    assert out2["config"] == out["config"]

    # 3c: same persistence contract for the ladder search (again at the
    # recording engine's 2 ms flush deadline)
    out3 = autotune_line(run([py, at, "search", "--trace", trace,
                              "--max-wait-ms", "2"], env=env))
    assert not out3["cached"]
    assert out3["objective_tuned"] < out3["objective_default"], out3
    out4 = autotune_line(run([py, at, "search", "--trace", trace,
                              "--max-wait-ms", "2"], env=env))
    assert out4["cached"] and out4["measurements"] == 0, out4

    show = run([py, at, "show"], env=env)
    assert "dconv_col_pallas" in show and "bucket_ladder" in show

    # ------------------------------------------------------------------
    # ISSUE 18: learned cost model over the pipeline
    # ------------------------------------------------------------------
    def autotune_lines(out, kind=None):
        got = []
        for line in out.splitlines():
            if line.startswith("AUTOTUNE "):
                d = json.loads(line[len("AUTOTUNE "):])
                if kind is None or d.get("kind") == kind:
                    got.append(d)
        return got

    env18 = dict(env)
    env18["MXNET_COSTPLANE"] = "1"   # trial rows carry ledger features
    env18["MXNET_TELEMETRY"] = "1"   # counters + the trailing block

    # 5a: seed the store with exhaustive-grid trial rows at two shapes
    for n in ("384", "512"):
        seeded = autotune_line(run(
            [py, at, "search", "--kernel", "dconv_col_pallas", "--n", n,
             "--strategy", "grid", "--warmup", "0", "--repeat", "1"],
            env=env18))
        assert seeded["strategy"] == "grid" and not seeded["cached"], seeded

    # 5b: DETERMINISTIC acceptance gate — fit the model from the seeded
    # store, replay the recorded per-config seconds as the measurer, and
    # require predict-then-measure to reach an equal-or-better winner
    # than the exhaustive grid with <= 50% of its measured trials
    os.environ["MXNET_AUTOTUNE"] = "1"
    os.environ["MXNET_AUTOTUNE_CACHE"] = env["MXNET_AUTOTUNE_CACHE"]
    from mxnet_tpu.autotune import costmodel
    from mxnet_tpu.autotune import search as at_search
    from mxnet_tpu.autotune import store as at_store

    rows = costmodel.training_rows("dconv_col_pallas")
    assert len(rows) >= 2 * costmodel.MIN_ROWS, \
        "seeding left only %d training rows" % len(rows)
    model = costmodel.model_for("dconv_col_pallas")
    assert model is not None and model.ready
    sig512 = "N512-HW32-C16-i4"
    replay = {tuple(sorted(r["config"].items())): r["seconds"]
              for r in rows if r["sig"] == sig512}
    assert len(replay) >= 4, "expected a full seeded grid at N512: %r" % replay
    grid_best = min(replay.values())
    measured = []

    def replay_measure(cfg):
        measured.append(cfg)
        return replay[tuple(sorted(cfg.items()))]

    best, results, repd = at_search.predict_then_measure(
        get_space("dconv_col_pallas"), replay_measure,
        lambda c: model.predict_one(sig512, c,
                                    device_kind=at_store._device_kind()),
        ctx={"N": 512, "HW": 32, "C": 16, "itemsize": 4}, top_k=1)
    best_s = min(r["seconds"] for r in results)
    assert len(measured) <= repd["candidates"] // 2, \
        "predict measured %d of %d (> 50%%)" % (len(measured),
                                                repd["candidates"])
    assert best_s <= grid_best, \
        "predict winner %r (%.6f s) worse than grid best %.6f s" % (
            best, best_s, grid_best)
    print("model gate: winner %r in %d/%d measurements (grid best matched)"
          % (best, len(measured), repd["candidates"]))

    # 6: CLI predict leg at a FRESH shape: fewer measurements, the
    # trials_saved surface, never-worse, schema-linted telemetry block
    pred_out = run(
        [py, at, "search", "--kernel", "dconv_col_pallas", "--n", "256",
         "--strategy", "predict", "--top-k", "1",
         "--warmup", "0", "--repeat", "1"], env=env18)
    outp = autotune_lines(pred_out, kind="dconv")[0]
    assert outp["strategy"] == "predict", outp
    assert outp["measurements"] <= max(1, outp["grid"] // 2), outp
    assert outp["trials_saved"] == outp["grid"] - outp["measurements"], outp
    assert outp["config"] == default_cfg \
        or outp["best_s"] < outp["default_s"], \
        "predict winner must stay never-worse: %r" % outp
    tel = autotune_lines(pred_out, kind="telemetry")
    assert tel, "no telemetry block after a telemetry-enabled search"
    assert tel[0]["telemetry"]["trials_saved"] == outp["trials_saved"], tel
    from ci.check_bench_schema import validate_line

    validate_line({"metric": "autotune_smoke", "value": 1, "unit": "runs",
                   "telemetry": tel[0]["telemetry"]}, "autotune telemetry")
    # 6b: warm store again beats everything — zero measurements
    outw = autotune_line(run(
        [py, at, "search", "--kernel", "dconv_col_pallas", "--n", "256",
         "--strategy", "predict", "--top-k", "1",
         "--warmup", "0", "--repeat", "1"], env=env18))
    assert outw["cached"] and outw["measurements"] == 0, outw

    # 7: --all-kernels sweeps every runnable space (small shapes); the
    # new kernel spaces AND the non-kernel layout space all record lines
    sweep_out = run(
        [py, at, "search", "--all-kernels", "--warmup", "0", "--repeat",
         "1", "--n", "96", "--nms-boxes", "256", "--ab-n", "64",
         "--q-rows", "256", "--fs-steps", "2"], env=env18)
    swept = {d["kernel"]: d for d in autotune_lines(sweep_out)
             if "kernel" in d}
    for kern in ("nms_alive_pallas", "psroi_abuild_pallas",
                 "quantize_int8_pallas", "dequantize_int8_pallas",
                 "fused_step_layout"):
        assert kern in swept, "--all-kernels skipped %s" % kern
        assert swept[kern]["cached"] or swept[kern]["measurements"] > 0, \
            swept[kern]
    tel2 = autotune_lines(sweep_out, kind="telemetry")
    assert tel2 and "trials_saved" in tel2[0]["telemetry"], tel2
    validate_line({"metric": "autotune_sweep", "value": 1, "unit": "runs",
                   "telemetry": tel2[0]["telemetry"]}, "sweep telemetry")
    show2 = run([py, at, "show", "--features"], env=env)
    assert "fused_step_layout" in show2 and "nms_alive_pallas" in show2
    assert "trial rows:" in show2, "show --features lost the trial rows"
    print("check_autotune: OK")


if __name__ == "__main__":
    main()
