#!/usr/bin/env python
"""Autotuning smoke (ISSUE 9) — run from ci/run_tests.sh unit tier.

End-to-end over real subprocesses, the way an operator would run it:

1. ``tools/loadgen.py --save-trace`` records a skewed traffic trace
   (request sizes 3/5/6 against the default 1,2,4,8 ladder — every
   request pads badly) and the trace passes the schema lint;
2. the ladder tuner's proposal from that trace scores a STRICTLY lower
   padding-waste x compile-count objective than the default ladder on
   the same trace (the ISSUE 9 acceptance);
3. ``tools/autotune.py search`` (measured dconv block-shape search on a
   CPU-sized problem, then the ladder search) persists winners, and a
   SECOND run of each against the warm store performs ZERO new
   measurements;
4. the dconv winner is never worse than the hand-tuned default on the
   microbench (the searcher measures the default first and keeps it on
   ties).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run(cmd, env=None):
    print("+ %s" % " ".join(cmd), flush=True)
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=REPO, timeout=600)
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        raise SystemExit("FAIL: %r exited %d" % (cmd, proc.returncode))
    return proc.stdout


def autotune_line(out):
    for line in out.splitlines():
        if line.startswith("AUTOTUNE "):
            return json.loads(line[len("AUTOTUNE "):])
    raise SystemExit("FAIL: no AUTOTUNE line in output")


def main():
    from ci.check_bench_schema import validate_trace_file

    tmp = tempfile.mkdtemp(prefix="mxnet_autotune_smoke_")
    trace = os.path.join(tmp, "trace.jsonl")
    env = dict(os.environ)
    env["MXNET_AUTOTUNE_CACHE"] = os.path.join(tmp, "autotune.json")
    py = sys.executable

    # 1: record traffic whose sizes (3/5/6) the default ladder pads badly
    run([py, os.path.join(REPO, "tools", "loadgen.py"), "--mode", "open",
         "--rate", "150", "--duration", "1.0", "--sizes", "3,5,6",
         "--batch-ladder", "1,2,4,8", "--save-trace", trace], env=env)
    n = validate_trace_file(trace)
    print("trace lint ok: %d records" % n)

    # 2: the proposal beats the default on its own trace (acceptance).
    # Replay with the SAME flush deadline the recording engine batched
    # under (loadgen's default --max-wait-ms 2), so the tuner models the
    # coalescing that actually produced — and would serve — this traffic
    from mxnet_tpu.autotune import ladder as lt

    wait_s = 0.002
    recs = lt.load_trace(trace)
    obj_default = lt.objective((1, 2, 4, 8), recs, max_wait_s=wait_s)
    tuned, rep = lt.propose(recs, max_wait_s=wait_s)
    print("ladder objective: default %.4f -> tuned %s %.4f"
          % (obj_default, tuned, rep["objective_tuned"]))
    assert rep["objective_tuned"] < obj_default, \
        "proposed ladder %s did not beat the default (%.4f >= %.4f)" % (
            tuned, rep["objective_tuned"], obj_default)

    at = os.path.join(REPO, "tools", "autotune.py")
    # 3a: measured dconv search (CPU-sized problem), never-worse winner
    out = autotune_line(run(
        [py, at, "search", "--kernel", "dconv_col_pallas",
         "--warmup", "1", "--repeat", "2"], env=env))
    assert out["measurements"] > 0 and not out["cached"]
    # never-worse is a BEHAVIORAL gate: a non-default winner must have
    # strictly beaten the measured default (best_s <= default_s holds by
    # construction, so asserting only that could never catch a searcher
    # that prefers a tying candidate over the hand-tuned default)
    from mxnet_tpu.autotune import get_space

    default_cfg = get_space("dconv_col_pallas").default
    assert out["config"] == default_cfg or out["best_s"] < out["default_s"], \
        "non-default winner must STRICTLY beat the measured default: %r" % out
    # 3b: warm store => zero new measurements
    out2 = autotune_line(run(
        [py, at, "search", "--kernel", "dconv_col_pallas",
         "--warmup", "1", "--repeat", "2"], env=env))
    assert out2["cached"] and out2["measurements"] == 0, out2
    assert out2["config"] == out["config"]

    # 3c: same persistence contract for the ladder search (again at the
    # recording engine's 2 ms flush deadline)
    out3 = autotune_line(run([py, at, "search", "--trace", trace,
                              "--max-wait-ms", "2"], env=env))
    assert not out3["cached"]
    assert out3["objective_tuned"] < out3["objective_default"], out3
    out4 = autotune_line(run([py, at, "search", "--trace", trace,
                              "--max-wait-ms", "2"], env=env))
    assert out4["cached"] and out4["measurements"] == 0, out4

    show = run([py, at, "show"], env=env)
    assert "dconv_col_pallas" in show and "bucket_ladder" in show
    print("check_autotune: OK")


if __name__ == "__main__":
    main()
