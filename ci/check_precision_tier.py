#!/usr/bin/env python
"""Precision-tier smoke (ISSUE 15) — run from ci/run_tests.sh unit tier.

Asserts the three contracts of the CastPlan-consuming deploy tier
(``mxnet_tpu/graph_passes/precision.py``):

1. **off path** — ``MXNET_PRECISION_TIER`` unset ⇒ the lowered eval plan
   IS the structural plan (same object), ``pipeline_fingerprint()``
   carries no tier segment, and the executor's AOT logical key is
   byte-identical to a pre-tier build's.
2. **bf16 twin** — on the deploy-twin checkpoint, the
   ``Predictor.with_precision("bf16")`` twin (a) meets the tier's declared
   rtol/atol tolerance contract vs the fp32 predictor on fixed inputs,
   (b) removes every ``_bn_affine`` node (weight folding), and (c) shows
   STRICTLY lower XLA ``bytes_accessed`` — and no higher peak bytes — in
   its compile-plane ledger row than the fp32 sibling (the ISSUE 13 ruler
   measuring the ISSUE 15 payoff; real CPU-XLA numbers).
3. **int8 twin** — a ``calibrate()``-d twin meets the int8 tolerance
   contract and rewrites its conv/FC nodes to int8 compute; an
   UNCALIBRATED twin leaves every node provably untouched (no ``_int8_*``
   op in the plan).
"""
import os
import sys

# costplane must be on before mxnet_tpu imports anywhere below; the tier
# gate itself stays UNSET — twins are built explicitly so this process
# exercises both sides
os.environ["MXNET_COSTPLANE"] = "1"
os.environ.pop("MXNET_PRECISION_TIER", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402


def main():
    from mxnet_tpu import graph_passes
    from mxnet_tpu.graph_passes import precision
    from mxnet_tpu.predictor import Predictor
    from mxnet_tpu.telemetry import costplane
    from mxnet_tpu.test_utils import deploy_twin_checkpoint

    sym, params, shapes = deploy_twin_checkpoint(batch=8, image=32)
    pred = Predictor(sym, params, shapes)
    exe = pred._exec

    # -- 1. off path --------------------------------------------------------
    assert precision.tier() is None
    fp = graph_passes.pipeline_fingerprint()
    assert fp is not None and "tier" not in fp, fp
    assert exe._opt_plan(False) is exe._structural_plan(False), \
        "tier off must lower the structural plan itself"
    assert exe._tier_key_parts(False) == (), \
        "tier off must leave AOT key parts untouched"
    os.environ["MXNET_PRECISION_TIER"] = "bf16"
    try:
        assert "tier=bf16" in graph_passes.pipeline_fingerprint()
        env_pred = Predictor(sym, params, shapes)
        assert env_pred.precision_tier == "bf16", \
            "env gate must build tier twins"
    finally:
        del os.environ["MXNET_PRECISION_TIER"]
    print("check_precision_tier: off-path identity + env gate ok")

    # -- 2. bf16 twin -------------------------------------------------------
    rng = np.random.RandomState(0)
    x = rng.rand(8, 3, 32, 32).astype(np.float32)
    out_f = [pred.forward(data=x)[i].asnumpy() for i in range(2)]
    row_f = [r for r in costplane.rows() if r["site"] == "executor_fwd"][-1]

    twin = pred.with_precision("bf16")
    plan, _, _ = twin._exec._opt_plan(False)
    affine = [n.name for n, _ in plan
              if getattr(n.op, "name", "") == "_bn_affine"]
    assert not affine, "fold_conv_affine left %s in the bf16 plan" % affine
    n0 = costplane.row_count()
    out_b = [twin.forward(data=x)[i].asnumpy() for i in range(2)]
    rows_b = costplane.rows_since(n0, site="executor_fwd")
    assert rows_b, "bf16 twin compile produced no ledger row"
    row_b = rows_b[-1]

    tol = precision.tier_tolerance("bf16")
    for i, (a, b) in enumerate(zip(out_f, out_b)):
        assert b.dtype == a.dtype, "head %d dtype drifted: %s" % (i, b.dtype)
        assert np.allclose(a, b, **tol), \
            "bf16 twin head %d breaks its tolerance contract: " \
            "max|Δ|=%.3g (rtol=%g atol=%g)" % (
                i, float(np.abs(a - b).max()), tol["rtol"], tol["atol"])
    ba_f, ba_b = row_f["bytes_accessed"], row_b["bytes_accessed"]
    pk_f, pk_b = row_f["peak_bytes"], row_b["peak_bytes"]
    assert ba_f is not None and ba_b is not None, \
        "CPU XLA reported no bytes_accessed — cannot gate the payoff"
    assert ba_b < ba_f, \
        "bf16 twin must read strictly fewer bytes: %d !< %d" % (ba_b, ba_f)
    assert pk_b is None or pk_f is None or pk_b <= pk_f, \
        "bf16 twin peak bytes grew: %s > %s" % (pk_b, pk_f)
    print("check_precision_tier: bf16 twin ok (max|Δ|=%.2e; "
          "bytes_accessed %d -> %d, peak %s -> %s)"
          % (max(float(np.abs(a - b).max())
                 for a, b in zip(out_f, out_b)), ba_f, ba_b, pk_f, pk_b))

    # -- 3. int8 twin -------------------------------------------------------
    table = precision.calibrate(
        pred, ({"data": rng.rand(8, 3, 32, 32).astype(np.float32)}
               for _ in range(4)))
    q = pred.with_precision("int8", calibration=table)
    planq, _, _ = q._exec._opt_plan(False)
    q_ops = [getattr(n.op, "name", "") for n, _ in planq]
    assert any(o.startswith("_int8_") for o in q_ops), \
        "calibrated int8 twin rewrote nothing: %s" % q_ops
    out_q = [q.forward(data=x)[i].asnumpy() for i in range(2)]
    tol = precision.tier_tolerance("int8")
    for i, (a, b) in enumerate(zip(out_f, out_q)):
        assert np.allclose(a, b, **tol), \
            "int8 twin head %d breaks its tolerance contract: " \
            "max|Δ|=%.3g" % (i, float(np.abs(a - b).max()))

    bare = pred.with_precision("int8")  # no calibration table
    planb, _, _ = bare._exec._opt_plan(False)
    assert not any(getattr(n.op, "name", "").startswith("_int8_")
                   for n, _ in planb), \
        "uncalibrated int8 twin must leave conv/FC nodes untouched"
    struct, _, _ = pred._exec._structural_plan(False)
    # fold still runs (it needs no calibration); everything NOT folded
    # must be the structural node object itself
    folded = {n.name for n, _ in planb} - {n.name for n, _ in struct}
    assert not folded, "int8-without-table invented nodes: %s" % folded
    print("check_precision_tier: int8 twin ok (calibrated rewrites %d "
          "conv/FC, uncalibrated untouched)"
          % sum(1 for o in q_ops if o.startswith("_int8_")))
    print("check_precision_tier: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
