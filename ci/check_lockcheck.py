#!/usr/bin/env python
"""Lock-discipline smoke (ISSUE 8) — unit tier.

Runs a short concurrent serving burst — warmup, multi-threaded bucketed
submits, an oversize direct dispatch, stats() reads — on a real Engine
under ``MXNET_LOCKCHECK=1`` and asserts the checker records ZERO
violations: the engine's documented mutex discipline
(``_cache_mu``/``_device_mu``/``_stats_mu`` and the containers each owns)
holds on the paths production traffic exercises.

Then proves the detector itself is live: a seeded out-of-order acquisition
and an unguarded mutation must each be recorded (a checker that can't fire
would pass the burst vacuously).

Run from ci/run_tests.sh unit tier::

    ./dev.sh python ci/check_lockcheck.py
"""
from __future__ import annotations

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MXNET_LOCKCHECK"] = "1"
# ISSUE 10 acceptance: the burst must stay violation-free WITH the live
# ops plane wired into the reply path (SLO monitor records per completed
# request, the flight recorder per lifecycle event) — their state lives
# outside the three-mutex discipline (docs/ANALYSIS.md) and this proves it
os.environ["MXNET_SLO"] = "*:p99:250:60"
os.environ["MXNET_FLIGHTREC_DIR"] = os.environ.get(
    "TMPDIR", "/tmp") + "/check_lockcheck_flightrec"

import numpy as np  # noqa: E402


def main():
    from mxnet_tpu.analysis import lockcheck
    from mxnet_tpu.serving import BucketLadder, Engine
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    errors = []
    with Engine(sym, params, {"data": (8,)},
                ladder=BucketLadder((1, 2, 4))) as eng:
        assert isinstance(eng._cache_mu, lockcheck.CheckedLock), \
            "MXNET_LOCKCHECK=1 did not instrument the engine"
        eng.warmup()

        def client(n_reqs, n_samples):
            try:
                for _ in range(n_reqs):
                    r = eng.submit(
                        {"data": np.zeros((n_samples, 8), np.float32)})
                    r.result(30.0)
            except Exception as e:  # surfaced below — don't die silently
                errors.append(e)

        threads = [threading.Thread(target=client, args=(8, n))
                   for n in (1, 2, 3)]
        # oversize -> direct-dispatch path (exercises _direct_cache)
        threads.append(threading.Thread(target=client, args=(2, 6)))
        for t in threads:
            t.start()
        for _ in range(4):
            eng.stats()  # reader path interleaved with the burst
        for t in threads:
            t.join()
        stats = eng.stats()

    assert not errors, "serving burst failed: %r" % errors
    assert stats["completed"] == 26, stats
    bad = lockcheck.violations()
    assert not bad, \
        "engine lock discipline violated under burst:\n%s" \
        % "\n".join(str(d) for d in bad)

    # detector liveness: seed one inversion + one unguarded mutation
    lockcheck.reset()
    a, b = lockcheck.CheckedLock("A"), lockcheck.CheckedLock("B")
    with a:
        with b:
            pass
    with b:
        with a:  # A->B then B->A: must be flagged
            pass
    guarded = lockcheck.guard({}, lockcheck.CheckedLock("C"), "_field")
    guarded["k"] = 1  # mutation without holding C: must be flagged
    codes = sorted(d.code for d in lockcheck.violations())
    assert codes == ["lock-inversion", "lock-unguarded-mutation"], codes

    print("check_lockcheck: ok (%d requests served with zero violations; "
          "seeded inversion + unguarded mutation both detected)"
          % stats["completed"])


if __name__ == "__main__":
    main()
