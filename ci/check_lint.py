#!/usr/bin/env python
"""mxlint CI gate (ISSUE 8) — unit tier.

Two directions, both must hold or this exits nonzero:

1. **The repo is clean against its baseline**: ``tools/mxlint.py`` over
   ``mxnet_tpu/`` with the committed ``ci/mxlint_baseline.txt`` must exit 0
   — a new finding means either fix the code or add a baseline entry WITH a
   justification (docs/ANALYSIS.md has the workflow).
2. **The lint actually bites**: a seeded hazard file (one deliberate
   instance of every rule) must make mxlint exit nonzero and name each
   expected rule — guarding against the lint rotting into a rubber stamp.

Run from ci/run_tests.sh unit tier::

    python ci/check_lint.py
"""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MXLINT = os.path.join(REPO, "tools", "mxlint.py")

# one deliberate instance of every rule; qualnames kept distinct so each
# finding is attributable in the failure message
SEEDED = '''\
import time
import numpy as np
import jax


@jax.jit
def np_hazard(x):
    return np.log(x)            # np-in-traced


@jax.jit
def coerce_hazard(x):
    return float(x) + 1.0       # scalar-coerce-in-traced


@jax.jit
def branch_hazard(x):
    if x:                       # branch-on-traced-param
        return x
    return -x


@jax.jit
def time_hazard(x):
    return x + time.time()      # time-in-traced


def swallow():
    try:
        return 1
    except:                     # bare-except
        return 0


def build_step(fn):
    return jax.jit(fn, donate_argnums=(0,))   # donated-jit-unkeyed


@jax.jit
def literal_hazard(x):
    return x + 1e-5             # mixed-dtype-literal (1 + 1e-5 == 1 in bf16)


@jax.jit
def downcast_hazard(x):
    import jax.numpy as jnp
    return x.astype(jnp.bfloat16)  # implicit-downcast
'''

EXPECT = ("np-in-traced", "scalar-coerce-in-traced", "branch-on-traced-param",
          "time-in-traced", "bare-except", "donated-jit-unkeyed",
          "mixed-dtype-literal", "implicit-downcast")


def run(*args):
    p = subprocess.run([sys.executable, MXLINT] + list(args),
                       capture_output=True, text=True, cwd=REPO)
    return p.returncode, p.stdout + p.stderr


def main():
    # 1. repo vs committed baseline
    rc, out = run()
    if rc != 0:
        print(out)
        print("check_lint: FAIL — mxlint found new hazards in mxnet_tpu/ "
              "(fix them or baseline with a justification)")
        return 1

    # 2. seeded hazards must trip every rule
    with tempfile.TemporaryDirectory() as td:
        seeded = os.path.join(td, "seeded_hazards.py")
        with open(seeded, "w") as fh:
            fh.write(SEEDED)
        rc, out = run(seeded, "--no-baseline")
    if rc == 0:
        print(out)
        print("check_lint: FAIL — mxlint exited 0 on a file of seeded "
              "hazards (the lint is not detecting anything)")
        return 1
    missing = [rule for rule in EXPECT if "[%s]" % rule not in out]
    if missing:
        print(out)
        print("check_lint: FAIL — seeded hazards not detected: %s"
              % ", ".join(missing))
        return 1

    print("check_lint: ok (repo clean vs baseline; all %d seeded rules "
          "trip)" % len(EXPECT))
    return 0


if __name__ == "__main__":
    sys.exit(main())
