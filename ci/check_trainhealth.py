#!/usr/bin/env python
"""CI smoke for the training health plane (ISSUE 12).

Three phases, exit 0 only when all pass — wired into the unit tier of
``ci/run_tests.sh``:

1. **Off path clean.**  With ``MXNET_TRAINHEALTH`` unset, a fused train
   step carries no health state (no stats staged, no plane, no
   ``trainhealth_*`` metrics) and its AOT key carries no trainhealth
   marker — the gate-off step is byte-identical to a build without the
   feature.  No flight-recorder dump may appear.
2. **Seeded divergence trips the tripwire.**  With the gate on, a NaN-fed
   step's drained row must carry a non-finite census blaming a verdict
   class, the ``precision_verdict_violations_total`` counter must fire for
   a blessed class, and the flight recorder must emit a ``trainhealth``
   dump artifact naming the first offending parameter group.
3. **Healthy steps report real numbers.**  Grad/param norms positive and
   finite, the drained global grad norm matching a numpy recomputation
   from the executor's own grad buffers.
"""
from __future__ import annotations

import glob
import json
import os
import shutil
import sys

FREC_DIR = "/tmp/trainhealth_smoke_frec"


def _module(mx, mod_mod, batch=8):
    import numpy as np

    data = mx.sym.var("data")
    x = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    x = mx.sym.Activation(x, name="relu1", act_type="relu")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(x, name="fc2", num_hidden=4), name="softmax")
    mod = mod_mod.Module(sym)
    mod.bind(data_shapes=[("data", (batch, 8))],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    return mod, rng


def _batch(mx, rng, batch=8, nan=False):
    import numpy as np

    from mxnet_tpu.io import DataBatch

    x = rng.randn(batch, 8).astype(np.float32)
    if nan:
        x[0, 0] = np.nan
    return DataBatch(
        data=[mx.nd.array(x)],
        label=[mx.nd.array(rng.randint(0, 4, (batch,)).astype(np.float32))])


def main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["MXNET_TELEMETRY"] = "1"
    os.environ.setdefault("MXNET_TELEMETRY_FILE",
                          "/tmp/trainhealth_smoke.jsonl")
    os.environ["MXNET_MODULE_FUSED_STEP"] = "1"
    os.environ.pop("MXNET_TRAINHEALTH", None)
    os.environ["MXNET_FLIGHTREC_DIR"] = FREC_DIR
    shutil.rmtree(FREC_DIR, ignore_errors=True)

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu.telemetry import instrument as tin
    from mxnet_tpu.telemetry import trainhealth

    # -- phase 1: off path ---------------------------------------------------
    mod, rng = _module(mx, mod_mod)
    for _ in range(2):
        mod.forward_backward(_batch(mx, rng))
        mod.update()
    ok = True
    if mod._fused is None or mod._fused._health_groups is not None \
            or mod._fused._last_health is not None:
        print("check_trainhealth: OFF path staged health state",
              file=sys.stderr)
        ok = False
    if trainhealth.plane() is not None or mod.trainer_stats() is not None:
        print("check_trainhealth: OFF path materialized the plane",
              file=sys.stderr)
        ok = False
    if mod._fused is not None and mod._fused._aot_key is not None \
            and "trainhealth" in mod._fused._aot_key:
        print("check_trainhealth: OFF path AOT key carries the "
              "trainhealth marker", file=sys.stderr)
        ok = False
    if tin.registry().get("trainhealth_global_grad_norm") is not None:
        print("check_trainhealth: OFF path fed the registry",
              file=sys.stderr)
        ok = False
    if glob.glob(os.path.join(FREC_DIR, "flightrec-*")):
        print("check_trainhealth: OFF path wrote a flightrec dump",
              file=sys.stderr)
        ok = False

    # -- phase 2 + 3: gate on ------------------------------------------------
    os.environ["MXNET_TRAINHEALTH"] = "1"
    mod, rng = _module(mx, mod_mod)
    mod.forward_backward(_batch(mx, rng))
    mod.update()
    plane = trainhealth.plane()
    row = plane.drain(mod, epoch=0, step=0)
    if row is None or row["nonfinite_groups"]:
        print("check_trainhealth: healthy step drained %r" % (row,),
              file=sys.stderr)
        return 1
    # recompute the global grad norm from the executor's grad buffers
    tot = 0.0
    for n in mod._param_names:
        g = mod._exec.grad_dict[n].asnumpy().astype(np.float64)
        tot += float((g ** 2).sum())
    if not np.isclose(np.sqrt(tot), row["global_grad_norm"], rtol=1e-4):
        print("check_trainhealth: global_grad_norm %.6f != numpy %.6f"
              % (row["global_grad_norm"], np.sqrt(tot)), file=sys.stderr)
        ok = False
    for g, s in row["groups"].items():
        if not (np.isfinite(s["grad_norm"]) and s["param_norm"] > 0
                and np.isfinite(s["update_ratio"])):
            print("check_trainhealth: implausible stats for group %r: %r"
                  % (g, s), file=sys.stderr)
            ok = False

    # seeded divergence
    mod.forward_backward(_batch(mx, rng, nan=True))
    mod.update()
    row = plane.drain(mod, epoch=0, step=1)
    if not row["nonfinite_groups"] or not row["nonfinite_census"]:
        print("check_trainhealth: NaN step produced no census: %r"
              % (row,), file=sys.stderr)
        return 1
    blamed = set(row["nonfinite_census"])
    verdicts = {s["verdict"] for s in row["groups"].values()}
    if not blamed <= (verdicts | {"unknown"}):
        print("check_trainhealth: census classes %s not drawn from the "
              "plan's verdicts %s" % (blamed, verdicts), file=sys.stderr)
        ok = False
    pvv = tin.registry().get("precision_verdict_violations_total")
    if pvv is None or not any(s["value"] > 0 for s in pvv.samples()):
        print("check_trainhealth: blessed-class violation counter did not "
              "fire", file=sys.stderr)
        ok = False
    dumps = glob.glob(os.path.join(FREC_DIR, "flightrec-*-trainhealth.json"))
    if not dumps:
        print("check_trainhealth: divergence produced no flightrec dump",
              file=sys.stderr)
        return 1
    meta = json.load(open(dumps[0]))["flightrec"]
    if meta.get("group") not in row["groups"]:
        print("check_trainhealth: dump names unknown group %r"
              % meta.get("group"), file=sys.stderr)
        ok = False
    if not meta.get("health_rows"):
        print("check_trainhealth: dump carries no health rows",
              file=sys.stderr)
        ok = False

    if ok:
        print("check_trainhealth: OK — off path clean, census blamed %s, "
              "dump %s names group %r"
              % (sorted(blamed), os.path.basename(dumps[0]),
                 meta.get("group")))
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
