#!/usr/bin/env python
"""SLO-policy router smoke (ISSUE 17) — run from ci/run_tests.sh unit tier.

Three phases, one process:

1. **Off path**: the router layer is opt-in construction, not ambient
   state — setting every ``MXNET_ROUTER_*`` variable must not move a
   Predictor's AOT logical key (the variables are read once, inside
   ``policy.config_from_env()`` at Router construction, never on the
   Engine path), and a bare Engine run must emit a SERVE_BENCH line
   without ``priority``/``router_policy`` keys.

2. **Degrade-first beats shedding**: the acceptance bake-off.  One
   mixed-priority open-loop overload (tools/loadgen.py in-process, same
   seed/rate/mix) replayed against three targets — a single Engine, a
   Router in ``shed`` mode (class-blind queue-overflow shedding, the
   pre-twin baseline) and a Router in ``degrade`` mode (best-effort
   traffic rerouted to the bf16 twin pool on overload, shedding last).
   Degrade mode must STRICTLY beat both baselines on paid-class goodput,
   hold the paid p99 inside its SLO target, and actually downgrade
   best-effort traffic (downgrades > 0, tier-labeled replies).  Every
   line is linted against the SERVE_BENCH schema.

3. **Lock discipline**: the whole run executes under ``MXNET_LOCKCHECK=1``
   — the router's policy loop, shared SLO monitor and per-tier engine
   pools must finish with zero recorded violations.

Tuning notes (determinism under CI, not realism): ladder=(1,) caps
per-request capacity at the dispatch overhead so a modest open-loop rate
floods any host; max_queue=512 keeps the saturated-FIFO delay far above
the paid target (≈ queue * service_time ≫ target).  The paid target
itself budgets for the degrade transient: the flood keeps arriving while
the policy notices the pressure and flips the route, so the native pool
must drain ≈ flood_rate * trigger_latency queued best-effort requests
before paid latency settles — a few hundred ms that lands inside the
target with margin, while the saturated baselines sit far outside it.
"""
from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MXNET_LOCKCHECK"] = "1"
# per-class SLO: paid tight, best-effort loose — 2 s windows so the burn
# signal reacts inside the run; the pressure signal triggers the policy
# regardless of the 1/s SLO evaluation throttle
PAID_TARGET_MS = 500.0
BE_TARGET_MS = 1000.0
os.environ["MXNET_SLO"] = ("paid:p95:%g:2,best_effort:p95:%g:2"
                           % (PAID_TARGET_MS, BE_TARGET_MS))
# router knobs: near-instant policy ticks, trigger on a 15%-full native
# pool (small backlog to drain after the degrade flips), never restore
# mid-run (the overload never clears while the loadgen floods)
os.environ["MXNET_ROUTER_POLICY"] = "degrade"
os.environ["MXNET_ROUTER_INTERVAL_S"] = "0.02"
os.environ["MXNET_ROUTER_PRESSURE"] = "0.15"
os.environ["MXNET_ROUTER_HOLD_S"] = "60"

import numpy as np  # noqa: E402


LADDER = (1,)
MAX_QUEUE = 512
DURATION_S = 3.0
RATE_RPS = 6000.0
CLASS_MIX = "paid:0.1,best_effort:0.9"


def _exec_key(pred):
    from mxnet_tpu import compile_cache

    exe = pred._exec
    return repr(("executor_fwd",
                 compile_cache.symbol_fingerprint(exe._symbol),
                 False) + exe._tier_key_parts(False))


def _loadgen_args():
    return argparse.Namespace(
        duration=DURATION_S, concurrency=2, sizes=(1,), timeout_s=60.0,
        rate=RATE_RPS, seed=0, slo_ms=0.0,
        class_slo={"paid": PAID_TARGET_MS, "best_effort": BE_TARGET_MS},
        class_mix=[("paid", 0.1), ("best_effort", 0.9)], router="off")


def _single_engine():
    from mxnet_tpu.serving import BucketLadder, Engine
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    return Engine(sym, params, {"data": (8,)}, ladder=BucketLadder(LADDER),
                  max_wait_ms=1.0, max_queue=MAX_QUEUE, name="rtck-single")


def _router(mode):
    from mxnet_tpu import serving
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    reg = serving.ModelRegistry()
    model = reg.register("rtck", sym, params, {"data": (8,)},
                         tiers=("fp32", "bf16"),
                         ladder=serving.BucketLadder(LADDER),
                         max_wait_ms=1.0, max_queue=MAX_QUEUE)
    return serving.Router(model, replicas=1, policy=mode,
                          name="rtck-%s" % mode)


def _bake(loadgen, cbs, target, label, router_mode="off"):
    args = _loadgen_args()
    args.router = router_mode
    target.warmup()
    line = loadgen.run(target, {"data": (8,)}, args, "open")
    cbs.validate_serve_line(line, label)
    return line


def _paid(line):
    return (line.get("priority") or {}).get("paid") or {}


def main():
    from mxnet_tpu.analysis import lockcheck
    from mxnet_tpu.predictor import Predictor
    from mxnet_tpu.test_utils import (load_module_by_path,
                                      tiny_mlp_checkpoint)

    loadgen = load_module_by_path(os.path.join(_REPO, "tools", "loadgen.py"))
    cbs = load_module_by_path(os.path.join(_REPO, "ci",
                                           "check_bench_schema.py"))
    ok = True

    # -- phase 1: off path ---------------------------------------------------
    sym, params = tiny_mlp_checkpoint()
    router_env = {k: os.environ.pop(k) for k in list(os.environ)
                  if k.startswith("MXNET_ROUTER_")}
    key_unset = _exec_key(Predictor(sym, params, {"data": (1, 8)}))
    os.environ.update(router_env)
    key_set = _exec_key(Predictor(sym, params, {"data": (1, 8)}))
    if key_set != key_unset:
        print("check_router: MXNET_ROUTER_* shifted the AOT logical key:\n"
              "  unset %s\n  set   %s" % (key_unset, key_set),
              file=sys.stderr)
        ok = False

    eng = _single_engine()
    try:
        line_single = _bake(loadgen, cbs, eng, "single-engine line")
    finally:
        eng.close()
    for k in ("router_policy",):
        if k in line_single:
            print("check_router: bare-Engine SERVE_BENCH line carries %r"
                  % k, file=sys.stderr)
            ok = False
    print("check_router: off path clean (single-engine paid goodput "
          "%.1f rps)" % _paid(line_single).get("goodput_rps", 0.0))

    # -- phase 2: degrade-first vs shed-only vs single -----------------------
    rt = _router("shed")
    try:
        line_shed = _bake(loadgen, cbs, rt, "shed-mode line", "shed")
        shed_stats = rt.stats()
    finally:
        rt.close()
    rt = _router("degrade")
    try:
        line_deg = _bake(loadgen, cbs, rt, "degrade-mode line", "degrade")
        deg_stats = rt.stats()
    finally:
        rt.close()

    paid_single = _paid(line_single).get("goodput_rps", 0.0)
    paid_shed = _paid(line_shed).get("goodput_rps", 0.0)
    paid_deg = _paid(line_deg).get("goodput_rps", 0.0)
    print("check_router: paid goodput rps — single %.1f, shed %.1f, "
          "degrade %.1f" % (paid_single, paid_shed, paid_deg))
    if not (paid_deg > paid_shed and paid_deg > paid_single):
        print("check_router: degrade-first must STRICTLY beat both "
              "baselines on paid goodput", file=sys.stderr)
        ok = False
    paid_p99 = _paid(line_deg).get("p99_ms", float("inf"))
    if paid_p99 > PAID_TARGET_MS:
        print("check_router: degrade-mode paid p99 %.1f ms blew the %g ms "
              "target" % (paid_p99, PAID_TARGET_MS), file=sys.stderr)
        ok = False
    be_deg = (line_deg.get("priority") or {}).get("best_effort") or {}
    if not be_deg.get("downgrades", 0) > 0:
        print("check_router: degrade mode never downgraded best-effort "
              "traffic (downgrades=%r)" % be_deg.get("downgrades"),
              file=sys.stderr)
        ok = False
    if line_deg.get("router_policy") != "degrade" \
            or line_shed.get("router_policy") != "shed":
        print("check_router: SERVE_BENCH router_policy labels wrong: %r/%r"
              % (line_deg.get("router_policy"),
                 line_shed.get("router_policy")), file=sys.stderr)
        ok = False
    # shed mode is a policy no-op by contract: no transitions, no
    # downgrades — its only overload response is admission-queue overflow
    if shed_stats["router"]["policy_counts"]["degrade"] != 0 \
            or shed_stats["downgrades"] != 0:
        print("check_router: shed-only router degraded traffic",
              file=sys.stderr)
        ok = False
    if deg_stats["router"]["policy_counts"]["degrade"] < 1:
        print("check_router: degrade router recorded no policy transition",
              file=sys.stderr)
        ok = False

    # -- phase 3: lock discipline --------------------------------------------
    bad = lockcheck.violations()
    if bad:
        print("check_router: %d lockcheck violation(s):" % len(bad),
              file=sys.stderr)
        for v in bad[:10]:
            print("  %s" % (v,), file=sys.stderr)
        ok = False
    else:
        print("check_router: zero lockcheck violations")

    if not ok:
        print("check_router: FAIL", file=sys.stderr)
        return 1
    print("check_router: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
