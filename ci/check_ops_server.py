#!/usr/bin/env python
"""Live ops-plane smoke (ISSUE 10) — unit tier.

Starts a real Engine with ``MXNET_OPS_PORT=0`` (ephemeral port), drives it
with ``tools/loadgen.py``'s closed loop (the SAME run the offline
percentile comes from), then asserts the live surfaces:

1. ``/metrics`` parses as Prometheus text exposition and contains
   ``serve_requests_total`` (the registry and the endpoint share one
   formatter — a scrape must agree with the PrometheusSink);
2. ``/statusz`` JSON round-trips and carries the engine's stats + SLO +
   warmup + bucket_stats blocks;
3. the streaming P99 in ``/statusz`` agrees with loadgen's offline
   ``latency_ms_p99`` (``np.percentile`` over client-observed latencies,
   same run) within the estimator's documented relative error bound
   (``slo.RELATIVE_ERROR``) plus a small absolute cushion for the
   client-vs-engine measurement point (the client stamps after its
   ``Event.wait`` wake, the engine at ``set_result``);
4. ``/healthz`` flips 200 → 503 when the device loop is frozen (held
   behind the device mutex with a request pending) and recovers to 200
   after release.

Run from ci/run_tests.sh unit tier::

    ./dev.sh python ci/check_ops_server.py
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# gates BEFORE any mxnet_tpu import: ephemeral ops port, a generous-window
# aggregate SLO objective ("*" — loadgen labels requests by size class, so
# the all-classes estimator is the one comparable to loadgen's overall
# percentile; the window must cover the whole run),
# telemetry for /metrics content, and a fast heartbeat-staleness threshold
# so the frozen-loop assertion doesn't stall CI
os.environ["MXNET_OPS_PORT"] = "0"
os.environ["MXNET_SLO"] = "*:p99:1000:600"
os.environ["MXNET_TELEMETRY"] = "1"
os.environ.setdefault("MXNET_TELEMETRY_FILE", "/tmp/check_ops_server.jsonl")
os.environ["MXNET_OPS_STALE_S"] = "1.0"

import numpy as np  # noqa: E402

from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.telemetry import ops_server, slo  # noqa: E402
from mxnet_tpu.test_utils import tiny_mlp_checkpoint  # noqa: E402

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")

# absolute cushion (ms) on top of the estimator's relative bound: the
# client measures submit→wake, the engine submit→set_result; the wake hop
# plus scheduler jitter on a loaded CI box lands inside this
CLIENT_CUSHION_MS = 10.0

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$")


def _get(port, path):
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d%s" % (port, path), timeout=10) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def parse_prometheus(text):
    """Minimal exposition-format check → set of metric sample names.
    Every non-comment, non-blank line must be ``name[{labels}] value``."""
    names = set()
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        if not _PROM_LINE.match(line):
            raise AssertionError(
                "metrics line %d is not Prometheus text format: %r"
                % (i, line))
        names.add(line.split("{", 1)[0].split(" ", 1)[0])
    return names


def main():
    import mxnet_tpu.test_utils as tu

    sys.path.insert(0, TOOLS)
    loadgen = tu.load_module_by_path(os.path.join(TOOLS, "loadgen.py"))

    sym, params = tiny_mlp_checkpoint()
    engine = serving.Engine(sym, params, {"data": (8,)},
                            ladder=serving.BucketLadder((1, 2, 4)),
                            max_wait_ms=2.0, max_queue=256,
                            name="opscheck", start=True)
    port = ops_server.port()
    assert port, "ops server did not start under MXNET_OPS_PORT=0"
    print("check_ops_server: ops server on 127.0.0.1:%d" % port)
    try:
        engine.warmup()

        # -- drive the engine through loadgen's own closed loop ------------
        args = argparse.Namespace(duration=1.0, concurrency=2,
                                  sizes=(1, 2), timeout_s=30.0, rate=0.0,
                                  seed=0, slo_ms=0.0)
        line = loadgen.run(engine, {"data": (8,)}, args, "closed")
        assert line["completed"] > 20 and line["errors"] == 0, \
            "loadgen run unhealthy: %r" % (line,)

        # -- 1: /metrics parses + carries the serving counters -------------
        code, body = _get(port, "/metrics")
        assert code == 200, "/metrics -> %d" % code
        names = parse_prometheus(body)
        for want in ("serve_requests_total", "serve_latency_seconds_count"):
            assert want in names, \
                "/metrics missing %s (got %d series)" % (want, len(names))
        print("check_ops_server: /metrics ok (%d sample names)" % len(names))

        # -- 2: /statusz round-trips with the stats blocks ------------------
        code, body = _get(port, "/statusz")
        assert code == 200, "/statusz -> %d" % code
        status = json.loads(body)
        assert json.loads(json.dumps(status)) == status
        st = status["engines"]["opscheck"]
        for key in ("slo", "warmup", "bucket_stats", "heartbeat_age_s"):
            assert st.get(key) is not None, "/statusz missing %r" % key
        assert status["health"]["ok"] is True

        # -- 3: streaming P99 vs loadgen's offline percentile ---------------
        obj = st["slo"]["objectives"][0]
        assert obj["class"] == "*" and obj["window_n"] > 0
        # the per-size-class estimators must have split the same traffic
        assert set(st["slo"]["classes"]) == {"1", "2"}, st["slo"]["classes"]
        live_p99 = obj["value_ms"]
        offline_p99 = line["latency_ms_p99"]
        tol = slo.RELATIVE_ERROR * offline_p99 + CLIENT_CUSHION_MS
        print("check_ops_server: streaming p99 %.3f ms vs offline %.3f ms "
              "(tol %.3f)" % (live_p99, offline_p99, tol))
        assert abs(live_p99 - offline_p99) <= tol, \
            "streaming p99 %.3f disagrees with offline %.3f beyond %.3f" \
            % (live_p99, offline_p99, tol)

        # -- 4: /healthz flips 200 -> 503 on a frozen device loop -----------
        code, _ = _get(port, "/healthz")
        assert code == 200, "/healthz -> %d on a healthy engine" % code
        engine._device_mu.acquire()  # freeze: dispatch blocks right here
        try:
            frozen = engine.submit({"data": np.zeros((1, 8), np.float32)})
            deadline = time.monotonic() + 10.0
            code = 200
            while time.monotonic() < deadline:
                code, body = _get(port, "/healthz")
                if code == 503:
                    break
                time.sleep(0.2)
            assert code == 503, \
                "/healthz stayed %d with the device loop frozen" % code
            detail = json.loads(body)
            eng = detail["engines"][0]
            assert not eng["ok"] and eng["heartbeat_age_s"] is not None
            print("check_ops_server: frozen loop -> 503 "
                  "(heartbeat_age_s=%.3f)" % eng["heartbeat_age_s"])
        finally:
            engine._device_mu.release()
        frozen.result(timeout=30)
        deadline = time.monotonic() + 10.0
        code = 503
        while time.monotonic() < deadline:
            code, _ = _get(port, "/healthz")
            if code == 200:
                break
            time.sleep(0.2)
        assert code == 200, "/healthz did not recover after release"
        print("check_ops_server: recovered -> 200")
    finally:
        engine.close()
        ops_server.stop()
    print("check_ops_server: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
