#!/usr/bin/env python
"""Multi-seed mAP gate (ADVICE round 5 recalibration).

The old chip quality gates compared ONE training run against a
worst-seed-minus-20% floor; with cross-seed variance as wide as
0.09..0.38 (R-FCN R-101) or 0.34..0.89 (SSD-512) such a floor only
catches catastrophic breakage (<=0.03) and would pass a regression that
halves typical mAP.  This helper instead gates the MEDIAN of n fixed-seed
runs (== the mean for n=2) against a floor calibrated from the seed-sweep
mean, which a halved-mAP regression cannot clear.

Used by ci/run_tests.sh's tpu tier::

    python ci/gate_map.py --extract run.log        # print the FINAL mAP
    python ci/gate_map.py --floor 0.14 0.09 0.27   # gate median(values)
"""
from __future__ import annotations

import argparse
import re
import statistics
import sys

# the eval_*_map.py scripts all print:  FINAL <recipe> <name> = <value>  (...)
# — non-greedy up to the first spaced '=' so the trailing "(steps=3000,
# eval n=500)" annotations can't shadow the mAP value
_FINAL_RE = re.compile(r"^FINAL\b.*?\s=\s+([0-9]*\.?[0-9]+)")


def extract_map(path):
    """Last FINAL-line mAP value in a log file (the eval scripts print one)."""
    value = None
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            m = _FINAL_RE.match(line.strip())
            if m:
                value = float(m.group(1))
    if value is None:
        raise SystemExit("%s: no 'FINAL ... = <mAP>' line found" % path)
    return value


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--extract", metavar="LOG",
                   help="print the FINAL mAP value parsed from LOG and exit")
    p.add_argument("--floor", type=float,
                   help="exit 1 unless median(values) >= FLOOR")
    p.add_argument("values", nargs="*", type=float,
                   help="per-seed mAP values to gate")
    args = p.parse_args(argv)

    if args.extract:
        print("%.4f" % extract_map(args.extract))
        return 0
    if args.floor is None or not args.values:
        p.error("need either --extract LOG, or --floor F plus values")
    med = statistics.median(args.values)
    line = "gate_map: median(%s) = %.4f vs floor %.4f" % (
        ", ".join("%.4f" % v for v in args.values), med, args.floor)
    if med < args.floor:
        print("FAIL: " + line)
        return 1
    print("PASS: " + line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
