#!/usr/bin/env python
"""Schema lint for bench.py's JSON line (ISSUE 1 CI satellite).

BENCH_r*.json (the driver's per-round capture) and the live ``python
bench.py`` output must stay machine-parseable: one JSON object with exactly
the known keys, including the optional ``telemetry`` block added by
MXNET_TELEMETRY.  Run from ci/run_tests.sh unit tier::

    python ci/check_bench_schema.py --self-test BENCH_r*.json
    python bench.py | python ci/check_bench_schema.py -   # lint a live line

Driver captures are validated through their ``parsed`` field; raw files
containing a bare bench line are validated directly.
"""
from __future__ import annotations

import json
import sys

# "tier" (ISSUE 15 precision tiers): the compilation tier the benched
# plan ran under — optional (captures predating the tier read as fp32);
# bench_compare diffs same-tier rows only, cross-tier rows display-only
TOP_KEYS = {"metric", "value", "unit", "vs_baseline", "telemetry", "tier"}
TIER_VALUES = {"fp32", "bf16", "int8"}
TEL_REQ_KEYS = {"compile_s", "peak_hbm_bytes", "data_wait_frac"}
# dispatches_per_step (ISSUE 3 fused Module step), warmup_s (ISSUE 6 AOT
# cache restart surface), the graph-pass keys (ISSUE 7: plan nodes in/out
# of the pass pipeline + its wall time), autotune_trials (ISSUE 9:
# candidate configs measured — 0/null in steady state, when the winner
# store answers) and the serve latency quantiles (ISSUE 10: submit->reply
# p50/p99 from the serve_latency_seconds histogram — null when no serving
# ran) are optional: captures predating that work carry only the three
# original keys
# analysis_findings (ISSUE 11): graph-IR analyzer diagnostics the manager
# recorded this process — null when nothing was recorded (no
# check()/warmup analysis ran, or everything analyzed was clean)
# trainhealth_drain_s (ISSUE 12): host seconds the training-health plane's
# per-step drain cost — THE health-overhead number (the in-graph stat
# reductions ride the fused dispatch for free); null when no drain ran
# xla_flops / xla_peak_bytes (ISSUE 13 compile plane): XLA-measured module
# flops summed (and peak executable bytes maxed) over every executable the
# process built — null when MXNET_COSTPLANE is off or the backend cannot
# report (the partial-row contract)
# trials_saved (ISSUE 18 learned autotuning): measurements the cost model
# skipped under predict-then-measure (ranked minus measured candidates) —
# null when no ranked search ran this process
# pod (ISSUE 19 pod observability plane): rank-0 aggregator rollup for a
# multichip run — {ranks, max_step_lag, ledger_divergences, incidents},
# all non-negative ints; null/absent when MXNET_POD_METRICS is off or the
# benched process was not the aggregating rank
TEL_OPT_KEYS = {"dispatches_per_step", "warmup_s",
                "graph_nodes_pre", "graph_nodes_post", "pass_time_s",
                "autotune_trials", "trials_saved",
                "serve_p50_ms", "serve_p99_ms",
                "analysis_findings", "trainhealth_drain_s",
                "xla_flops", "xla_peak_bytes", "pod"}
TEL_KEYS = TEL_REQ_KEYS | TEL_OPT_KEYS
POD_KEYS = {"ranks", "max_step_lag", "ledger_divergences", "incidents"}

# SERVE_BENCH line (tools/loadgen.py, ISSUE 2) — docs/SERVING.md schema
SERVE_PREFIX = "SERVE_BENCH "
SERVE_REQ_KEYS = {"mode", "requests", "completed", "shed", "timeouts",
                  "errors", "shed_rate", "duration_s", "throughput_rps",
                  "latency_ms_p50", "latency_ms_p99", "compiles"}
SERVE_OPT_KEYS = {"concurrency", "rate_rps", "batch_fill_mean",
                  "padding_waste_mean", "first_request_ms", "warmup_s",
                  # ISSUE 10 live-ops surface: per-size-class percentiles
                  # + goodput under a --slo-ms target
                  "latency_by_class", "goodput_rps", "slo_ms",
                  # ISSUE 15: the engine's compiled precision tier
                  "tier",
                  # ISSUE 16 quality plane: {tier: {p50, p99, n,
                  # violations}} over shadow-sampled contract fractions —
                  # absent when MXNET_QUALITYPLANE is off or nothing was
                  # sampled during the run
                  "divergence",
                  # ISSUE 17 router: per-priority-class breakdown
                  # ({class: {requests, completed, sheds, downgrades,
                  # p50_ms, p99_ms, goodput_rps[, slo_ms]}}) and the policy
                  # mode the fronting Router ran — both absent on bare
                  # Engine runs (--router off)
                  "priority", "router_policy"}
SERVE_MODES = {"closed", "open"}
ROUTER_POLICIES = {"degrade", "shed"}
PRIORITY_REQ_KEYS = {"requests", "completed", "sheds", "downgrades",
                     "p50_ms", "p99_ms", "goodput_rps"}
PRIORITY_OPT_KEYS = {"slo_ms"}


class SchemaError(ValueError):
    pass


# loadgen request-trace record (tools/loadgen.py --save-trace, ISSUE 9) —
# the offline input the bucket-ladder tuner replays (autotune/ladder.py)
TRACE_KEYS = {"t", "n", "shapes", "class"}


def validate_trace_line(obj, where="<line>"):
    """Validate one --save-trace JSONL record; raises SchemaError."""
    if not isinstance(obj, dict):
        raise SchemaError("%s: trace record must be a JSON object, got %s"
                          % (where, type(obj).__name__))
    if set(obj) != TRACE_KEYS:
        raise SchemaError("%s: trace record keys %s != %s"
                          % (where, sorted(obj), sorted(TRACE_KEYS)))
    if not _num(obj["t"]) or obj["t"] < 0:
        raise SchemaError("%s: 't' must be a non-negative number (seconds "
                          "since run start)" % where)
    if not isinstance(obj["n"], int) or isinstance(obj["n"], bool) \
            or obj["n"] < 1:
        raise SchemaError("%s: 'n' must be a positive int sample count"
                          % where)
    shp = obj["shapes"]
    if not isinstance(shp, dict) or not shp:
        raise SchemaError("%s: 'shapes' must be a non-empty object of "
                          "input -> per-sample dims" % where)
    for name, dims in shp.items():
        if not isinstance(name, str) or not isinstance(dims, list) or any(
                not isinstance(d, int) or isinstance(d, bool) or d < 0
                for d in dims):
            raise SchemaError(
                "%s: shapes[%r] must be a list of non-negative int dims"
                % (where, name))
    if not isinstance(obj["class"], str) or not obj["class"]:
        raise SchemaError("%s: 'class' must be a non-empty string" % where)


def validate_trace_file(path):
    """Validate every line of a --save-trace JSONL file; empty = error
    (an empty trace replays to nothing — the tuner would crash later)."""
    n = 0
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            validate_trace_line(json.loads(line), "%s:%d" % (path, i))
            n += 1
    if not n:
        raise SchemaError("%s: empty trace file" % path)
    return n


def _num(x):
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def validate_line(obj, where="<line>"):
    """Validate one bench JSON line dict; raises SchemaError."""
    if not isinstance(obj, dict):
        raise SchemaError("%s: bench line must be a JSON object, got %s"
                          % (where, type(obj).__name__))
    unknown = set(obj) - TOP_KEYS
    if unknown:
        raise SchemaError("%s: unknown top-level keys %s (schema: %s)"
                          % (where, sorted(unknown), sorted(TOP_KEYS)))
    for req in ("metric", "value", "unit"):
        if req not in obj:
            raise SchemaError("%s: missing required key %r" % (where, req))
    if not isinstance(obj["metric"], str) or not obj["metric"]:
        raise SchemaError("%s: 'metric' must be a non-empty string" % where)
    if not _num(obj["value"]):
        raise SchemaError("%s: 'value' must be a number" % where)
    if not isinstance(obj["unit"], str):
        raise SchemaError("%s: 'unit' must be a string" % where)
    if "vs_baseline" in obj and obj["vs_baseline"] is not None \
            and not _num(obj["vs_baseline"]):
        raise SchemaError("%s: 'vs_baseline' must be a number or null" % where)
    if "tier" in obj and obj["tier"] not in TIER_VALUES:
        raise SchemaError("%s: 'tier' must be one of %s (omit for legacy "
                          "fp32 captures), got %r"
                          % (where, sorted(TIER_VALUES), obj["tier"]))
    if "telemetry" in obj:
        tel = obj["telemetry"]
        if tel is None:
            return
        if not isinstance(tel, dict):
            raise SchemaError("%s: 'telemetry' must be an object or null"
                              % where)
        unknown = set(tel) - TEL_KEYS
        if unknown:
            raise SchemaError("%s: unknown telemetry keys %s (schema: %s)"
                              % (where, sorted(unknown), sorted(TEL_KEYS)))
        for k in TEL_REQ_KEYS:
            if k not in tel:
                raise SchemaError("%s: telemetry block missing %r" % (where, k))
        if not _num(tel["compile_s"]):
            raise SchemaError("%s: telemetry.compile_s must be a number"
                              % where)
        if tel["peak_hbm_bytes"] is not None \
                and not isinstance(tel["peak_hbm_bytes"], int):
            raise SchemaError(
                "%s: telemetry.peak_hbm_bytes must be an int or null" % where)
        if not _num(tel["data_wait_frac"]) or not 0 <= tel["data_wait_frac"] <= 1:
            raise SchemaError(
                "%s: telemetry.data_wait_frac must be a number in [0, 1]"
                % where)
        dps = tel.get("dispatches_per_step")
        if dps is not None and (not _num(dps) or dps < 0):
            raise SchemaError(
                "%s: telemetry.dispatches_per_step must be a non-negative "
                "number or null" % where)
        ws = tel.get("warmup_s")
        if ws is not None and (not _num(ws) or ws < 0):
            raise SchemaError(
                "%s: telemetry.warmup_s must be a non-negative number or "
                "null" % where)
        for k in ("graph_nodes_pre", "graph_nodes_post"):
            gn = tel.get(k)
            if gn is not None and (not isinstance(gn, int)
                                   or isinstance(gn, bool) or gn < 0):
                raise SchemaError(
                    "%s: telemetry.%s must be a non-negative int or null"
                    % (where, k))
        pt = tel.get("pass_time_s")
        if pt is not None and (not _num(pt) or pt < 0):
            raise SchemaError(
                "%s: telemetry.pass_time_s must be a non-negative number "
                "or null" % where)
        for k in ("autotune_trials", "trials_saved"):
            at = tel.get(k)
            if at is not None and (not isinstance(at, int)
                                   or isinstance(at, bool) or at < 0):
                raise SchemaError(
                    "%s: telemetry.%s must be a non-negative int "
                    "or null" % (where, k))
        for k in ("serve_p50_ms", "serve_p99_ms", "trainhealth_drain_s"):
            sv = tel.get(k)
            if sv is not None and (not _num(sv) or sv < 0):
                raise SchemaError(
                    "%s: telemetry.%s must be a non-negative number or "
                    "null" % (where, k))
        for k in ("xla_flops", "xla_peak_bytes"):
            xv = tel.get(k)
            if xv is not None and (not isinstance(xv, int)
                                   or isinstance(xv, bool) or xv < 0):
                raise SchemaError(
                    "%s: telemetry.%s must be a non-negative int or null"
                    % (where, k))
        if tel.get("serve_p50_ms") is not None \
                and tel.get("serve_p99_ms") is not None \
                and tel["serve_p99_ms"] < tel["serve_p50_ms"]:
            raise SchemaError(
                "%s: telemetry serve p99 below p50 — percentiles swapped?"
                % where)
        pod = tel.get("pod")
        if pod is not None:
            if not isinstance(pod, dict):
                raise SchemaError(
                    "%s: telemetry.pod must be an object or null" % where)
            unknown_pod = set(pod) - POD_KEYS
            if unknown_pod:
                raise SchemaError(
                    "%s: unknown telemetry.pod keys %s (schema: %s)"
                    % (where, sorted(unknown_pod), sorted(POD_KEYS)))
            for k, pv in pod.items():
                if not isinstance(pv, int) or isinstance(pv, bool) \
                        or pv < 0:
                    raise SchemaError(
                        "%s: telemetry.pod.%s must be a non-negative int"
                        % (where, k))
            if "ranks" in pod and pod["ranks"] < 1:
                raise SchemaError(
                    "%s: telemetry.pod.ranks must be >= 1 (an aggregator "
                    "always counts itself)" % where)


def validate_serve_line(obj, where="<line>"):
    """Validate one SERVE_BENCH JSON dict; raises SchemaError."""
    if not isinstance(obj, dict):
        raise SchemaError("%s: SERVE_BENCH must be a JSON object, got %s"
                          % (where, type(obj).__name__))
    unknown = set(obj) - SERVE_REQ_KEYS - SERVE_OPT_KEYS
    if unknown:
        raise SchemaError("%s: unknown SERVE_BENCH keys %s (schema: %s + "
                          "optional %s)" % (where, sorted(unknown),
                                            sorted(SERVE_REQ_KEYS),
                                            sorted(SERVE_OPT_KEYS)))
    missing = SERVE_REQ_KEYS - set(obj)
    if missing:
        raise SchemaError("%s: SERVE_BENCH missing required keys %s"
                          % (where, sorted(missing)))
    if obj["mode"] not in SERVE_MODES:
        raise SchemaError("%s: mode must be one of %s, got %r"
                          % (where, sorted(SERVE_MODES), obj["mode"]))
    for k in ("requests", "completed", "shed", "timeouts", "errors",
              "compiles"):
        if not isinstance(obj[k], int) or isinstance(obj[k], bool) \
                or obj[k] < 0:
            raise SchemaError("%s: %r must be a non-negative int, got %r"
                              % (where, k, obj[k]))
    for k in ("shed_rate", "duration_s", "throughput_rps",
              "latency_ms_p50", "latency_ms_p99"):
        if not _num(obj[k]) or obj[k] < 0:
            raise SchemaError("%s: %r must be a non-negative number, got %r"
                              % (where, k, obj[k]))
    if obj["shed_rate"] > 1:
        raise SchemaError("%s: shed_rate must be in [0, 1]" % where)
    if obj["latency_ms_p99"] < obj["latency_ms_p50"]:
        raise SchemaError("%s: p99 latency below p50 — percentiles swapped?"
                          % where)
    if obj["completed"] > obj["requests"]:
        raise SchemaError("%s: completed > requests" % where)
    for k in ("batch_fill_mean", "padding_waste_mean"):
        if k in obj and (not _num(obj[k]) or not 0 <= obj[k] <= 1):
            raise SchemaError("%s: %r must be a number in [0, 1]" % (where, k))
    if "warmup_s" in obj and (not _num(obj["warmup_s"]) or obj["warmup_s"] < 0):
        raise SchemaError("%s: 'warmup_s' must be a non-negative number"
                          % where)
    if "first_request_ms" in obj:
        fr = obj["first_request_ms"]
        if not isinstance(fr, dict) or not fr:
            raise SchemaError(
                "%s: 'first_request_ms' must be a non-empty object of "
                "size-class -> ms" % where)
        for k, v in fr.items():
            if not isinstance(k, str) or not _num(v) or v < 0:
                raise SchemaError(
                    "%s: first_request_ms[%r] must map a string size class "
                    "to a non-negative number" % (where, k))
    if "goodput_rps" in obj and (not _num(obj["goodput_rps"])
                                 or obj["goodput_rps"] < 0):
        raise SchemaError("%s: 'goodput_rps' must be a non-negative number"
                          % where)
    if "slo_ms" in obj and (not _num(obj["slo_ms"]) or obj["slo_ms"] <= 0):
        raise SchemaError("%s: 'slo_ms' must be a positive number (omit "
                          "the key when no target was set)" % where)
    if "tier" in obj and obj["tier"] not in TIER_VALUES:
        raise SchemaError("%s: 'tier' must be one of %s (omit for legacy "
                          "fp32 captures), got %r"
                          % (where, sorted(TIER_VALUES), obj["tier"]))
    if "latency_by_class" in obj:
        bc = obj["latency_by_class"]
        if not isinstance(bc, dict) or not bc:
            raise SchemaError(
                "%s: 'latency_by_class' must be a non-empty object of "
                "size-class -> {p50_ms, p99_ms, n}" % where)
        for k, v in bc.items():
            if not isinstance(k, str) or not isinstance(v, dict) \
                    or set(v) != {"p50_ms", "p99_ms", "n"}:
                raise SchemaError(
                    "%s: latency_by_class[%r] must be an object with "
                    "exactly {p50_ms, p99_ms, n}" % (where, k))
            if not isinstance(v["n"], int) or isinstance(v["n"], bool) \
                    or v["n"] < 1:
                raise SchemaError(
                    "%s: latency_by_class[%r].n must be a positive int"
                    % (where, k))
            for pk in ("p50_ms", "p99_ms"):
                if not _num(v[pk]) or v[pk] < 0:
                    raise SchemaError(
                        "%s: latency_by_class[%r].%s must be a "
                        "non-negative number" % (where, k, pk))
            if v["p99_ms"] < v["p50_ms"]:
                raise SchemaError(
                    "%s: latency_by_class[%r] p99 below p50 — percentiles "
                    "swapped?" % (where, k))
    if "divergence" in obj:
        div = obj["divergence"]
        if not isinstance(div, dict) or not div:
            raise SchemaError(
                "%s: 'divergence' must be a non-empty object of "
                "tier -> {p50, p99, n, violations} (omit the key when the "
                "quality plane is off)" % where)
        for k, v in div.items():
            if k not in TIER_VALUES:
                raise SchemaError(
                    "%s: divergence tier must be one of %s, got %r"
                    % (where, sorted(TIER_VALUES), k))
            if not isinstance(v, dict) \
                    or set(v) != {"p50", "p99", "n", "violations"}:
                raise SchemaError(
                    "%s: divergence[%r] must be an object with exactly "
                    "{p50, p99, n, violations}" % (where, k))
            for ck in ("n", "violations"):
                if not isinstance(v[ck], int) or isinstance(v[ck], bool) \
                        or v[ck] < 0:
                    raise SchemaError(
                        "%s: divergence[%r].%s must be a non-negative int"
                        % (where, k, ck))
            for pk in ("p50", "p99"):
                if not _num(v[pk]) or v[pk] < 0:
                    raise SchemaError(
                        "%s: divergence[%r].%s must be a non-negative "
                        "number" % (where, k, pk))
            if v["p99"] < v["p50"]:
                raise SchemaError(
                    "%s: divergence[%r] p99 below p50 — percentiles "
                    "swapped?" % (where, k))
    if "router_policy" in obj and obj["router_policy"] not in ROUTER_POLICIES:
        raise SchemaError(
            "%s: 'router_policy' must be one of %s (omit the key when no "
            "router fronted the run), got %r"
            % (where, sorted(ROUTER_POLICIES), obj["router_policy"]))
    if "priority" in obj:
        pb = obj["priority"]
        if not isinstance(pb, dict) or not pb:
            raise SchemaError(
                "%s: 'priority' must be a non-empty object of priority "
                "class -> per-class stats (omit the key when no --class-mix "
                "ran)" % where)
        for k, v in pb.items():
            if not isinstance(k, str) or not k:
                raise SchemaError(
                    "%s: priority class names must be non-empty strings"
                    % where)
            if not isinstance(v, dict):
                raise SchemaError("%s: priority[%r] must be an object"
                                  % (where, k))
            unknown = set(v) - PRIORITY_REQ_KEYS - PRIORITY_OPT_KEYS
            if unknown:
                raise SchemaError(
                    "%s: priority[%r] unknown keys %s (schema: %s + "
                    "optional %s)" % (where, k, sorted(unknown),
                                      sorted(PRIORITY_REQ_KEYS),
                                      sorted(PRIORITY_OPT_KEYS)))
            missing = PRIORITY_REQ_KEYS - set(v)
            if missing:
                raise SchemaError("%s: priority[%r] missing keys %s"
                                  % (where, k, sorted(missing)))
            for ck in ("requests", "completed", "sheds", "downgrades"):
                if not isinstance(v[ck], int) or isinstance(v[ck], bool) \
                        or v[ck] < 0:
                    raise SchemaError(
                        "%s: priority[%r].%s must be a non-negative int"
                        % (where, k, ck))
            if v["completed"] > v["requests"]:
                raise SchemaError("%s: priority[%r] completed > requests"
                                  % (where, k))
            for nk in ("p50_ms", "p99_ms", "goodput_rps"):
                if not _num(v[nk]) or v[nk] < 0:
                    raise SchemaError(
                        "%s: priority[%r].%s must be a non-negative number"
                        % (where, k, nk))
            if v["p99_ms"] < v["p50_ms"]:
                raise SchemaError(
                    "%s: priority[%r] p99 below p50 — percentiles swapped?"
                    % (where, k))
            if "slo_ms" in v and (not _num(v["slo_ms"]) or v["slo_ms"] <= 0):
                raise SchemaError(
                    "%s: priority[%r].slo_ms must be a positive number "
                    "(omit when no per-class target was set)" % (where, k))


def validate_capture(path):
    """Validate a BENCH_r*.json driver capture (or a raw bench line file)."""
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)
    if isinstance(obj, dict) and "parsed" in obj:
        if obj.get("rc", 0) != 0:
            print("%s: rc=%s capture — skipping parse check" % (path, obj["rc"]))
            return
        if obj["parsed"] is None:
            raise SchemaError("%s: rc=0 capture with no parsed bench line"
                              % path)
        validate_line(obj["parsed"], path)
    else:
        validate_line(obj, path)


def self_test():
    good = [
        {"metric": "m", "value": 1.5, "unit": "img/s", "vs_baseline": None},
        {"metric": "m", "value": 1, "unit": "img/s", "vs_baseline": 2.0,
         "telemetry": {"compile_s": 3.2, "peak_hbm_bytes": 123,
                       "data_wait_frac": 0.01}},
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "dispatches_per_step": 1.0}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "dispatches_per_step": None}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "warmup_s": 1.25}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "warmup_s": None}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "graph_nodes_pre": 34,
                       "graph_nodes_post": 27, "pass_time_s": 0.002}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "graph_nodes_pre": None,
                       "graph_nodes_post": None, "pass_time_s": None}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "autotune_trials": 15}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "autotune_trials": None}},
        # ISSUE 18 learned autotuning: measurements the cost model skipped
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "autotune_trials": 2,
                       "trials_saved": 3}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "trials_saved": None}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "serve_p50_ms": 2.5,
                       "serve_p99_ms": 11.0}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "serve_p50_ms": None,
                       "serve_p99_ms": None}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "trainhealth_drain_s": 0.0213}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "trainhealth_drain_s": None}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "xla_flops": 528383,
                       "xla_peak_bytes": 32788}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "xla_flops": None,
                       "xla_peak_bytes": None}},
        # ISSUE 15: per-tier deploy-twin rows
        {"metric": "m", "value": 1, "unit": "samples/s", "tier": "fp32"},
        {"metric": "m", "value": 1, "unit": "samples/s", "tier": "bf16"},
        {"metric": "m", "value": 1, "unit": "samples/s", "tier": "int8"},
        # ISSUE 19 pod observability: aggregator rollup on multichip rows
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "pod": {"ranks": 2, "max_step_lag": 3,
                               "ledger_divergences": 0, "incidents": 1}}},
        {"metric": "m", "value": 1, "unit": "samples/s",
         "telemetry": {"compile_s": 0.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "pod": None}},
    ]
    bad = [
        {},                                                  # empty
        {"metric": "m", "value": "fast", "unit": "img/s"},   # value type
        {"metric": "m", "value": 1, "unit": "img/s", "extra": 1},
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0}},                   # missing keys
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": 1.5,
                       "data_wait_frac": 0.0}},              # float bytes
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 1.7}},              # frac range
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "dispatches_per_step": -2}},          # negative dps
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "warmup_s": -1}},  # neg warmup
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "graph_nodes_post": 1.5}},        # float node count
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "graph_nodes_pre": -3}},          # negative nodes
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "pass_time_s": -0.1}},            # negative pass time
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "autotune_trials": 1.5}},         # float trial count
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "trials_saved": -1}},             # negative saved
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "trials_saved": 2.5}},            # float saved
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "serve_p50_ms": -1.0}},           # negative latency
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0, "serve_p50_ms": 9.0,
                       "serve_p99_ms": 3.0}},            # p99 < p50
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "trainhealth_drain_s": -0.5}},    # negative drain
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "trainhealth_drain_s": True}},    # bool drain
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "xla_flops": 1.5}},               # float flops
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "xla_peak_bytes": -8}},           # negative peak
        {"metric": "m", "value": 1, "unit": "img/s",
         "tier": "fp16"},                                # unknown tier
        {"metric": "m", "value": 1, "unit": "img/s",
         "tier": None},                                  # null tier (omit it)
        # ISSUE 19 pod block
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "pod": {"ranks": 2.5}}},          # float ranks
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "pod": {"ranks": 2,
                               "ledger_divergences": -1}}},  # negative
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "pod": {"ranks": 2, "bogus": 1}}},  # unknown key
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "pod": {"ranks": 0}}},            # rankless pod
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "pod": {"ranks": 2,
                               "incidents": True}}},     # bool counter
        {"metric": "m", "value": 1, "unit": "img/s",
         "telemetry": {"compile_s": 1.0, "peak_hbm_bytes": None,
                       "data_wait_frac": 0.0,
                       "pod": [2]}},                     # wrong type
    ]
    serve_good = {"mode": "closed", "requests": 10, "completed": 9,
                  "shed": 1, "timeouts": 0, "errors": 0, "shed_rate": 0.1,
                  "duration_s": 1.5, "throughput_rps": 6.0,
                  "latency_ms_p50": 2.0, "latency_ms_p99": 9.5,
                  "compiles": 3, "concurrency": 4}
    serve_bad = [
        {},
        dict(serve_good, mode="sideways"),           # unknown mode
        dict(serve_good, shed_rate=1.2),             # rate out of range
        dict(serve_good, compiles=1.5),              # non-int counter
        dict(serve_good, latency_ms_p99=1.0),        # p99 < p50
        dict(serve_good, completed=11),              # completed > requests
        dict(serve_good, extra=1),                   # unknown key
        {k: v for k, v in serve_good.items() if k != "throughput_rps"},
        dict(serve_good, warmup_s=-0.5),             # negative warmup
        dict(serve_good, first_request_ms={}),       # empty map
        dict(serve_good, first_request_ms={"1": -2}),  # negative latency
        dict(serve_good, first_request_ms=[1.0]),    # wrong type
        dict(serve_good, goodput_rps=-1.0),          # negative goodput
        dict(serve_good, slo_ms=0),                  # zero target
        dict(serve_good, latency_by_class={}),       # empty class map
        dict(serve_good, latency_by_class={          # missing n
            "1": {"p50_ms": 1.0, "p99_ms": 2.0}}),
        dict(serve_good, latency_by_class={          # p99 < p50
            "1": {"p50_ms": 5.0, "p99_ms": 2.0, "n": 3}}),
        dict(serve_good, latency_by_class={          # zero count
            "1": {"p50_ms": 1.0, "p99_ms": 2.0, "n": 0}}),
        dict(serve_good, tier="fp16"),               # unknown tier
        dict(serve_good, tier=None),                 # null tier (omit it)
        # ISSUE 16 quality-plane divergence block
        dict(serve_good, divergence={}),             # empty map (omit it)
        dict(serve_good, divergence=None),           # null (omit it)
        dict(serve_good, divergence={                # unknown tier key
            "fp16": {"p50": 0.1, "p99": 0.2, "n": 4, "violations": 0}}),
        dict(serve_good, divergence={                # missing violations
            "bf16": {"p50": 0.1, "p99": 0.2, "n": 4}}),
        dict(serve_good, divergence={                # p99 < p50
            "bf16": {"p50": 0.5, "p99": 0.2, "n": 4, "violations": 0}}),
        dict(serve_good, divergence={                # float count
            "bf16": {"p50": 0.1, "p99": 0.2, "n": 4.5, "violations": 0}}),
        dict(serve_good, divergence={                # negative violations
            "bf16": {"p50": 0.1, "p99": 0.2, "n": 4, "violations": -1}}),
        # ISSUE 17 router priority block
        dict(serve_good, router_policy="static"),    # unknown policy mode
        dict(serve_good, router_policy=None),        # null (omit it)
        dict(serve_good, priority={}),               # empty map (omit it)
        dict(serve_good, priority={"paid": {         # missing downgrades
            "requests": 5, "completed": 5, "sheds": 0,
            "p50_ms": 1.0, "p99_ms": 2.0, "goodput_rps": 4.0}}),
        dict(serve_good, priority={"paid": {         # completed > requests
            "requests": 5, "completed": 6, "sheds": 0, "downgrades": 0,
            "p50_ms": 1.0, "p99_ms": 2.0, "goodput_rps": 4.0}}),
        dict(serve_good, priority={"paid": {         # p99 < p50
            "requests": 5, "completed": 5, "sheds": 0, "downgrades": 0,
            "p50_ms": 3.0, "p99_ms": 2.0, "goodput_rps": 4.0}}),
        dict(serve_good, priority={"paid": {         # float counter
            "requests": 5, "completed": 4.5, "sheds": 0, "downgrades": 0,
            "p50_ms": 1.0, "p99_ms": 2.0, "goodput_rps": 4.0}}),
        dict(serve_good, priority={"paid": {         # zero slo target
            "requests": 5, "completed": 5, "sheds": 0, "downgrades": 0,
            "p50_ms": 1.0, "p99_ms": 2.0, "goodput_rps": 4.0,
            "slo_ms": 0}}),
        dict(serve_good, priority={"paid": {         # unknown per-class key
            "requests": 5, "completed": 5, "sheds": 0, "downgrades": 0,
            "p50_ms": 1.0, "p99_ms": 2.0, "goodput_rps": 4.0,
            "tier": "bf16"}}),
        dict(serve_good, priority={"": {             # empty class name
            "requests": 5, "completed": 5, "sheds": 0, "downgrades": 0,
            "p50_ms": 1.0, "p99_ms": 2.0, "goodput_rps": 4.0}}),
    ]
    for obj in good:
        validate_line(obj, "self-test good")
    validate_serve_line(serve_good, "self-test serve good")
    validate_serve_line(dict(serve_good, mode="open", rate_rps=200.0,
                             batch_fill_mean=0.8), "self-test serve good2")
    validate_serve_line(dict(serve_good, warmup_s=0.42,
                             first_request_ms={"1": 2.5, "4": 3.75}),
                        "self-test serve good3")
    validate_serve_line(dict(serve_good, goodput_rps=5.5, slo_ms=50.0,
                             latency_by_class={
                                 "1": {"p50_ms": 1.5, "p99_ms": 8.0, "n": 40},
                                 "4": {"p50_ms": 2.5, "p99_ms": 9.0, "n": 7}}),
                        "self-test serve good4")
    validate_serve_line(dict(serve_good, tier="bf16"),
                        "self-test serve good5")
    validate_serve_line(dict(serve_good, tier="int8", divergence={
        "int8": {"p50": 0.004, "p99": 0.09, "n": 17, "violations": 0},
        "bf16": {"p50": 0.001, "p99": 0.01, "n": 3, "violations": 1}}),
        "self-test serve good6")
    validate_serve_line(dict(serve_good, router_policy="degrade", priority={
        "paid": {"requests": 8, "completed": 8, "sheds": 0,
                 "downgrades": 0, "p50_ms": 1.2, "p99_ms": 4.0,
                 "goodput_rps": 5.3, "slo_ms": 50.0},
        "best_effort": {"requests": 30, "completed": 26, "sheds": 4,
                        "downgrades": 19, "p50_ms": 2.0, "p99_ms": 9.0,
                        "goodput_rps": 15.0}}),
        "self-test serve good7")
    validate_serve_line(dict(serve_good, router_policy="shed"),
                        "self-test serve good8")
    for i, obj in enumerate(bad):
        try:
            validate_line(obj, "self-test bad[%d]" % i)
        except SchemaError:
            continue
        raise AssertionError("self-test: bad line %d passed: %r" % (i, obj))
    for i, obj in enumerate(serve_bad):
        try:
            validate_serve_line(obj, "self-test serve bad[%d]" % i)
        except SchemaError:
            continue
        raise AssertionError(
            "self-test: bad SERVE_BENCH line %d passed: %r" % (i, obj))
    trace_good = {"t": 0.125, "n": 3, "shapes": {"data": [8]},
                  "class": "open"}
    validate_trace_line(trace_good, "self-test trace good")
    validate_trace_line({"t": 0, "n": 1, "shapes": {"data": []},
                         "class": "closed"}, "self-test trace good2")
    trace_bad = [
        {},
        dict(trace_good, t=-1.0),                    # negative arrival
        dict(trace_good, n=0),                       # empty request
        dict(trace_good, n=2.5),                     # non-int count
        dict(trace_good, shapes={}),                 # no inputs
        dict(trace_good, shapes={"data": [8.5]}),    # float dim
        {k: v for k, v in trace_good.items() if k != "class"},
        dict(trace_good, extra=1),                   # unknown key
    ]
    for i, obj in enumerate(trace_bad):
        try:
            validate_trace_line(obj, "self-test trace bad[%d]" % i)
        except SchemaError:
            continue
        raise AssertionError(
            "self-test: bad trace record %d passed: %r" % (i, obj))


def main(argv):
    args = list(argv)
    if "--self-test" in args:
        args.remove("--self-test")
        self_test()
        print("self-test ok")
    trace_mode = "--trace" in args
    if trace_mode:
        args.remove("--trace")
    rc = 0
    for path in args:
        try:
            if trace_mode:
                n = validate_trace_file(path)
                print("%s: ok (%d trace records)" % (path, n))
                continue
            if path == "-":
                for n, line in enumerate(sys.stdin, 1):
                    line = line.strip()
                    if line.startswith(SERVE_PREFIX):
                        validate_serve_line(
                            json.loads(line[len(SERVE_PREFIX):]),
                            "<stdin>:%d" % n)
                    elif line.startswith("{"):
                        validate_line(json.loads(line), "<stdin>:%d" % n)
            else:
                validate_capture(path)
            print("%s: ok" % path)
        except (SchemaError, json.JSONDecodeError, OSError) as e:
            print("%s: FAIL: %s" % (path, e), file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
