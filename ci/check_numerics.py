#!/usr/bin/env python
"""Numerics-analyzer CI gate (ISSUE 11) — unit tier.

Same anti-rubber-stamp contract as ``ci/check_lint.py``: the gate proves
the analyzer BITES before trusting that the repo is clean against it.

1. **Seeded hazards must ALL trip** — a bf16-accumulated reduction
   (``low-precision-accum``), a mixed-dtype binop (``mixed-dtype-binop``),
   a softmax fed an unbounded bf16 range (``exp-unbounded-lowp`` +
   an ``fp32_only`` verdict), and — at the source layer — a non-bf16-exact
   float literal (``mixed-dtype-literal`` via mxlint).  Any of these
   coming back clean means the analyzer rotted into a rubber stamp.
2. **The deploy-twin predictor is clean and correctly planned** — the
   ``MXNET_BENCH=predictor`` two-head graph (one shared definition,
   ``test_utils.deploy_twin_checkpoint``) must produce zero diagnostics in
   fp32, and its cast plan must satisfy the ISSUE 11 acceptance shape: a
   MAJORITY of nodes ``bf16_safe``, every reduction/BN-stat node
   ``fp32_accum``, every exp/log-family node reached by an unbounded range
   ``fp32_only``, and a fingerprint that is stable across rebuilds.

Run from ci/run_tests.sh unit tier::

    python ci/check_numerics.py
"""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

SEEDED_SOURCE = '''\
import jax


@jax.jit
def eps_guard(x):
    return x + 1e-5   # mixed-dtype-literal: 1 + 1e-5 == 1 in bf16
'''


def fail(msg):
    print("check_numerics: FAIL — %s" % msg)
    return 1


def main():
    import numpy as np
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.analysis.numerics import (BF16_SAFE, FP32_ACCUM,
                                             FP32_ONLY)
    from mxnet_tpu.graph_passes.ir import EXP_RANGE, REDUCE, CANCELLATION
    from mxnet_tpu.predictor import Predictor
    from mxnet_tpu.test_utils import deploy_twin_checkpoint

    def bind(sym, **arrays):
        return sym.bind(None, {k: nd.array(v) for k, v in arrays.items()})

    def codes(exe):
        return [d.code for d in exe.check()]

    # -- 1. seeded hazards ---------------------------------------------------
    x = mx.sym.var("data")
    exe = bind(mx.sym.sum(x), data=np.ones((8, 8)).astype(jnp.bfloat16))
    if "low-precision-accum" not in codes(exe):
        return fail("a bf16-accumulated reduction did not trip "
                    "low-precision-accum")
    if exe.precision_plan().rows[0]["verdict"] != FP32_ACCUM:
        return fail("the bf16 sum node's verdict is not fp32_accum")

    a, b = mx.sym.var("a"), mx.sym.var("b")
    exe = bind(mx.sym.broadcast_add(a, b),
               a=np.ones((2, 2)).astype(jnp.bfloat16),
               b=np.ones((2, 2), np.float32))
    if "mixed-dtype-binop" not in codes(exe):
        return fail("a bf16+f32 binop did not trip mixed-dtype-binop")

    exe = bind(mx.sym.softmax(x), data=np.ones((2, 8)).astype(jnp.bfloat16))
    if "exp-unbounded-lowp" not in codes(exe):
        return fail("softmax fed an unbounded bf16 range did not trip "
                    "exp-unbounded-lowp")
    if exe.precision_plan().rows[0]["verdict"] != FP32_ONLY:
        return fail("softmax fed an unbounded range is not fp32_only")

    # bounded producer range flips the same softmax to bf16_safe — the
    # interval analysis is live, not a constant verdict
    exe = bind(mx.sym.softmax(mx.sym.sigmoid(x)),
               data=np.ones((2, 8)).astype(jnp.bfloat16))
    plan = exe.precision_plan()
    if plan.verdict("softmax1") not in (None, BF16_SAFE) or \
            not any(r["op"] == "softmax" and r["verdict"] == BF16_SAFE
                    for r in plan.rows):
        return fail("softmax fed a sigmoid-bounded [0,1] range should be "
                    "bf16_safe (interval analysis dead?)")

    # source layer: the mixed-dtype-literal lint rule must trip via mxlint
    with tempfile.TemporaryDirectory() as td:
        seeded = os.path.join(td, "seeded_literal.py")
        with open(seeded, "w") as fh:
            fh.write(SEEDED_SOURCE)
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "mxlint.py"),
             seeded, "--no-baseline"],
            capture_output=True, text=True, cwd=REPO)
    if p.returncode == 0 or "[mixed-dtype-literal]" not in \
            (p.stdout + p.stderr):
        print(p.stdout + p.stderr)
        return fail("the seeded float-literal source did not trip "
                    "mxlint's mixed-dtype-literal rule")

    # -- 2. the deploy-twin predictor ---------------------------------------
    sym, params, input_shapes = deploy_twin_checkpoint(batch=4, image=16)
    pred = Predictor(sym, params, input_shapes)
    diags = pred.check()
    if diags:
        for d in diags:
            print("  %s" % d)
        return fail("the fp32 deploy-twin predictor is not clean")
    plan = pred.precision_plan()
    counts = plan.counts()
    total = len(plan.rows)
    if counts[BF16_SAFE] * 2 <= total:
        return fail("deploy-twin cast plan: bf16_safe is not a majority "
                    "(%s of %d nodes)" % (counts, total))
    bad = [r for r in plan.rows
           if r["sensitivity"] in (REDUCE, CANCELLATION)
           and r["verdict"] != FP32_ACCUM]
    if bad:
        return fail("reduction/BN-stat nodes without fp32_accum: %s" % bad)
    # every exp/log-family node fed an unbounded range must be fp32_only;
    # in this graph that is exactly the classifier softmax (fed raw FC
    # logits) — the embedding head has no exp/log op
    exp_rows = [r for r in plan.rows if r["sensitivity"] == EXP_RANGE]
    if not exp_rows:
        return fail("deploy twin lost its softmax head?")
    if any(r["verdict"] != FP32_ONLY for r in exp_rows):
        return fail("unbounded-range exp/log nodes not fp32_only: %s"
                    % exp_rows)
    # fingerprint: stable across an identical rebuild, moved by a plan edit
    pred2 = Predictor(sym, params, input_shapes)
    if pred2.precision_plan().fingerprint() != plan.fingerprint():
        return fail("cast-plan fingerprint is not stable across rebuilds")
    head = mx.sym.softmax(mx.sym.var("data"), name="p")
    other = Predictor(head, {}, {"data": (4, 10)})
    if other.precision_plan().fingerprint() == plan.fingerprint():
        return fail("two different plans share a cast-plan fingerprint")

    print("check_numerics: ok (4 seeded hazards trip; deploy twin clean: "
          "%d bf16_safe / %d fp32_accum / %d fp32_only of %d nodes, %s)"
          % (counts[BF16_SAFE], counts[FP32_ACCUM], counts[FP32_ONLY],
             total, plan.fingerprint()))
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the acceptance shape (majority bf16_safe) is defined over the
    # OPTIMIZED eval plan — the plan the deployment tier actually lowers.
    # The raw plan carries duplicated pre-CSE heads and train-only BN/
    # dropout nodes that tilt the histogram; pin the gate on.
    os.environ["MXNET_GRAPH_PASSES"] = "1"
    sys.exit(main())
