#!/usr/bin/env python
"""Well-formedness lint for exported chrome-trace JSON (ISSUE 4 CI satellite).

Validates the invariants the span tracer (``mxnet_tpu/telemetry/tracing.py``)
promises and downstream tools (Perfetto, ``tools/trace_summary.py``,
``tools/trace_merge.py``) rely on:

* every non-metadata event carries a finite, non-negative ``ts``; every
  "X" duration event a finite, non-negative ``dur``;
* per (pid, tid) track, "X" slices nest strictly (a slice may contain or be
  disjoint from another, never partially overlap) — the chrome-trace
  rendering contract the tracer's per-trace lanes exist to satisfy;
* flow events pair up: every flow id has exactly one "s" and at least one
  "f", and no "f" precedes its "s" (monotonic handoff order).

Usage::

    python ci/check_trace.py mxtrace.json        # validate a file
    python ci/check_trace.py --smoke             # end-to-end smoke:
        # serve a few requests + run a couple of train steps with
        # MXNET_TRACE=1, export, validate, and assert one request's spans
        # share a trace id across the submit and device-loop threads

The smoke is the unit-tier acceptance run (ci/run_tests.sh).
"""
from __future__ import annotations

import argparse
import gzip
import json
import math
import sys

_EPS_US = 1e-3  # export rounds ts/dur to 1ns; tolerate that much slop


def load_events(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        return data.get("traceEvents", [])
    return data


def _num(x):
    return isinstance(x, (int, float)) and not isinstance(x, bool) \
        and math.isfinite(x)


def validate(events):
    """→ list of problem strings (empty = well-formed)."""
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    problems = []
    tracks = {}
    flows = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append("event %d: not an object" % i)
            continue
        ph = ev.get("ph")
        if not ph:
            problems.append("event %d: missing ph" % i)
            continue
        if ph == "M":
            continue  # metadata carries no timestamp
        ts = ev.get("ts")
        if not _num(ts) or ts < 0:
            problems.append("event %d (%s %r): bad ts %r"
                            % (i, ph, ev.get("name"), ts))
            continue
        if ph == "X":
            dur = ev.get("dur")
            if not _num(dur) or dur < 0:
                problems.append("event %d (X %r): bad dur %r"
                                % (i, ev.get("name"), dur))
                continue
            tracks.setdefault((ev.get("pid", 0), ev.get("tid", 0)),
                              []).append((ts, dur, ev.get("name", "?")))
        elif ph in ("s", "t", "f"):
            if "id" not in ev:
                problems.append("event %d (flow %s): missing id" % (i, ph))
                continue
            flows.setdefault(ev["id"], {}).setdefault(ph, []).append(ts)
    for (pid, tid), slices in sorted(tracks.items()):
        # sort outer-first at equal start so nesting resolves deterministically
        slices.sort(key=lambda s: (s[0], -s[1]))
        open_ends = []  # stack of (end_ts, name)
        for ts, dur, name in slices:
            while open_ends and open_ends[-1][0] <= ts + _EPS_US:
                open_ends.pop()
            if open_ends and ts + dur > open_ends[-1][0] + _EPS_US:
                problems.append(
                    "pid %s tid %s: slice %r [%f..%f] partially overlaps "
                    "enclosing %r (ends %f) — X events must nest"
                    % (pid, tid, name, ts, ts + dur, open_ends[-1][1],
                       open_ends[-1][0]))
            open_ends.append((ts + dur, name))
    for fid, d in sorted(flows.items(), key=lambda kv: str(kv[0])):
        if "s" not in d:
            problems.append("flow id %r: 'f'/'t' without an 's' start" % fid)
            continue
        if len(d["s"]) > 1:
            problems.append("flow id %r: %d 's' starts (want 1)"
                            % (fid, len(d["s"])))
        if "f" not in d:
            problems.append("flow id %r: 's' without a matching 'f'" % fid)
        elif min(d["f"]) + _EPS_US < d["s"][0]:
            problems.append("flow id %r: 'f' at %f precedes 's' at %f"
                            % (fid, min(d["f"]), d["s"][0]))
    return problems


def _assert_smoke_content(events):
    """Beyond well-formedness, the smoke asserts the ISSUE 4 acceptance:
    request spans cross threads under one trace id, and train steps carry
    step/data_wait spans."""
    problems = []
    xs = [ev for ev in events if ev.get("ph") == "X"]
    by_trace = {}
    for ev in xs:
        tr = ev.get("args", {}).get("trace")
        if tr is not None:
            by_trace.setdefault(tr, []).append(ev)
    req_ok = False
    for tr, evs in by_trace.items():
        names = {e["name"] for e in evs}
        tids = {e["tid"] for e in evs}
        if {"request", "queue", "execute"} <= names and len(tids) >= 2:
            req_ok = True
            break
    if not req_ok:
        problems.append("no request trace with queue+execute spans across "
                        ">=2 threads")
    names = {e["name"] for e in xs}
    for want in ("step", "data_wait", "forward_backward", "update"):
        if want not in names:
            problems.append("no %r span in the traced fit run" % want)
    flows = [ev for ev in events if ev.get("ph") in ("s", "f")]
    if not flows:
        problems.append("no flow events linking the thread handoff")
    return problems


def smoke():
    """Serve a few requests + run two train steps with MXNET_TRACE=1,
    export, validate."""
    import os
    import tempfile

    os.environ["MXNET_TRACE"] = "1"
    os.environ["MXNET_TRACE_SAMPLE"] = "1"
    # invoked as `python ci/check_trace.py`: the script dir is on sys.path,
    # the repo root is not (same bootstrap as tools/trace_summary.py)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.serving import BucketLadder, Engine
    from mxnet_tpu.telemetry import tracing
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    with Engine(sym, params, {"data": (8,)}, ladder=BucketLadder((1, 2)),
                max_wait_ms=1.0, name="smoke") as eng:
        for _ in range(4):
            eng.predict({"data": np.zeros((1, 8), np.float32)})

    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    X = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    y = np.zeros((16,), np.float32)
    mod = mx.mod.Module(net)
    mod.fit(mx.io.NDArrayIter(X, y, batch_size=8), num_epoch=1,
            optimizer="sgd")

    path = os.path.join(tempfile.mkdtemp(prefix="mxtrace_smoke_"),
                        "trace.json")
    tracing.export(path)
    events = load_events(path)
    problems = validate(events) + _assert_smoke_content(events)
    for msg in problems:
        print("check_trace smoke: %s" % msg, file=sys.stderr)
    if problems:
        return 1
    nspans = sum(1 for ev in events if ev.get("ph") == "X")
    print("check_trace smoke OK: %d spans, trace well-formed (%s)"
          % (nspans, path))
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        description="validate chrome-trace JSON (ts sanity, X nesting, "
                    "matched flow ids)")
    p.add_argument("trace", nargs="?", help="trace file (.json or .json.gz)")
    p.add_argument("--smoke", action="store_true",
                   help="run the serve+train tracing smoke instead of "
                        "validating a file")
    args = p.parse_args(argv)
    if args.smoke:
        return smoke()
    if not args.trace:
        p.error("need a trace file (or --smoke)")
    try:
        events = load_events(args.trace)
    except (OSError, json.JSONDecodeError) as e:
        print("check_trace: cannot read %s: %s" % (args.trace, e),
              file=sys.stderr)
        return 2
    problems = validate(events)
    for msg in problems:
        print("check_trace: %s" % msg, file=sys.stderr)
    if problems:
        return 1
    print("check_trace OK: %d events" % len(events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
