#!/usr/bin/env python
"""Inference quality-plane smoke (ISSUE 16) — run from ci/run_tests.sh
unit tier.

Three phases, exit 0 only when all pass:

1. **Off path clean.**  With ``MXNET_QUALITYPLANE`` unset, an engine
   serves with no plane, no shadow thread, no ring, no ``quality``
   block in ``stats()``, no quality metrics in the registry, and no
   flightrec dump — and a bf16 twin's AOT logical key is byte-identical
   to the key the same checkpoint produces with the gate ON (the plane
   is runtime-only; it must never shift what XLA builds).  A loadgen
   run records the gate-off SERVE_BENCH baseline (no ``divergence``
   key).
2. **bf16 twin in tolerance.**  Gate on with ``MXNET_QUALITY_SAMPLE=1``:
   every completed bf16 request is shadow-replayed through the fp32
   sibling; divergence rows must appear, every sampled contract
   fraction must sit inside ``tier_tolerance("bf16")`` (zero
   violations), the ``tier_divergence`` histogram must carry samples,
   and loadgen's SERVE_BENCH line must embed the ``divergence`` block
   (schema-linted).  The gate-on P99 is compared against phase 1's
   gate-off P99 under a generous bound — shadow sampling must not
   inflate the live tail (both lines are printed so CI logs record the
   comparison).
3. **Poisoned int8 table trips drift + violation.**  An int8 twin
   calibrated on inputs 100x smaller than live traffic, on a RAW
   (non-normalized) head: the per-site drift ratio must trip
   ``calibration_drift_total``, the tolerance contract must trip
   ``tier_tolerance_violations_total``, and a throttled
   ``quality_violation`` flightrec dump must appear naming the tier and
   bucket.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import threading
import time

FREC_DIR = "/tmp/quality_smoke_frec"

# env BEFORE any mxnet_tpu import: telemetry for the registry feed,
# flightrec for the violation dump, the quality gate initially UNSET so
# phase 1 exercises the off path in the same process
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MXNET_TELEMETRY"] = "1"
os.environ.setdefault("MXNET_TELEMETRY_FILE", "/tmp/quality_smoke.jsonl")
os.environ.pop("MXNET_QUALITYPLANE", None)
os.environ.pop("MXNET_QUALITY_SAMPLE", None)
os.environ["MXNET_FLIGHTREC_DIR"] = FREC_DIR
shutil.rmtree(FREC_DIR, ignore_errors=True)

import numpy as np  # noqa: E402


def _quality_threads():
    return [t.name for t in threading.enumerate()
            if t.name.startswith("mxnet-quality")]


def _exec_key(pred):
    from mxnet_tpu import compile_cache

    exe = pred._exec
    return repr(("executor_fwd",
                 compile_cache.symbol_fingerprint(exe._symbol),
                 False) + exe._tier_key_parts(False))


def _raw_head_checkpoint(seed=0):
    """conv -> relu -> flatten -> FC with NO normalizing head: softmax /
    L2Norm heads renormalize away int8 quantization error, so only a raw
    head can demonstrate the tolerance-violation path."""
    import mxnet_tpu as mx

    data = mx.sym.var("data")
    h = mx.sym.Convolution(data, name="conv0", kernel=(3, 3), num_filter=8,
                           pad=(1, 1))
    h = mx.sym.Activation(h, act_type="relu", name="relu0")
    h = mx.sym.Flatten(h)
    out = mx.sym.FullyConnected(h, name="fc1", num_hidden=4)
    rng = np.random.RandomState(seed)
    shapes = {"data": (2, 3, 8, 8)}
    arg_shapes, _, _ = out.infer_shape(**shapes)
    params = {n: mx.nd.array(rng.randn(*s).astype(np.float32) * 0.1)
              for n, s in zip(out.list_arguments(), arg_shapes)
              if n != "data"}
    return out, params, shapes


def _bf16_engine(loadgen_unused=None):
    from mxnet_tpu.serving import BucketLadder, Engine
    from mxnet_tpu.test_utils import deploy_twin_checkpoint

    sym, params, _ = deploy_twin_checkpoint(batch=4, image=16)
    eng = Engine(sym, params, {"data": (3, 16, 16)},
                 ladder=BucketLadder((1, 2)), max_wait_ms=2.0,
                 max_queue=256, name="qualcheck")
    # tier on the proto BEFORE warmup/first dispatch: with_shapes
    # propagates (tier, calibration) to every bucket twin
    eng._proto._exec.set_precision_tier("bf16")
    return eng


def _loadgen_line(loadgen, eng, duration=1.0):
    args = argparse.Namespace(duration=duration, concurrency=2,
                              sizes=(1, 2), timeout_s=30.0, rate=0.0,
                              seed=0, slo_ms=0.0)
    return loadgen.run(eng, {"data": (3, 16, 16)}, args, "closed")


def main():
    from mxnet_tpu.graph_passes import precision
    from mxnet_tpu.predictor import Predictor
    from mxnet_tpu.serving import BucketLadder, Engine
    from mxnet_tpu.telemetry import instrument as tin
    from mxnet_tpu.telemetry import qualityplane
    from mxnet_tpu.test_utils import (deploy_twin_checkpoint,
                                      load_module_by_path,
                                      tiny_mlp_checkpoint)

    tools = os.path.join(_REPO, "tools")
    loadgen = load_module_by_path(os.path.join(tools, "loadgen.py"))
    cbs = load_module_by_path(os.path.join(_REPO, "ci",
                                           "check_bench_schema.py"))
    ok = True

    # -- phase 1: off path ---------------------------------------------------
    if qualityplane.plane() is not None or qualityplane.status() is not None:
        print("check_quality_plane: OFF path materialized the plane",
              file=sys.stderr)
        ok = False
    sym, params = tiny_mlp_checkpoint()
    eng = Engine(sym, params, {"data": (8,)}, ladder=BucketLadder((1, 2)),
                 max_wait_ms=2.0, name="qualoff")
    try:
        eng.predict({"data": np.zeros((1, 8), np.float32)})
        st = eng.stats()
    finally:
        eng.close()
    if st["quality"] is not None:
        print("check_quality_plane: OFF path stats() grew a quality block",
              file=sys.stderr)
        ok = False
    if _quality_threads():
        print("check_quality_plane: OFF path started a shadow thread: %s"
              % _quality_threads(), file=sys.stderr)
        ok = False
    if getattr(eng, "_quality", "sentinel") is not None \
            or hasattr(eng, "_quality_q"):
        print("check_quality_plane: OFF path allocated quality state",
              file=sys.stderr)
        ok = False
    for m in ("tier_divergence", "tier_tolerance_violations_total",
              "calibration_drift_total", "quality_shed_total"):
        if tin.registry().get(m) is not None:
            print("check_quality_plane: OFF path fed registry metric %r"
                  % m, file=sys.stderr)
            ok = False
    if glob.glob(os.path.join(FREC_DIR, "flightrec-*")):
        print("check_quality_plane: OFF path wrote a flightrec dump",
              file=sys.stderr)
        ok = False

    # AOT-key invariance: same checkpoint, gate off vs on (set below) —
    # the plane is runtime-only, the logical key must not move
    dsym, dparams, dshapes = deploy_twin_checkpoint(batch=4, image=16)
    key_off = _exec_key(
        Predictor(dsym, dparams, dshapes).with_precision("bf16"))

    # gate-off SERVE_BENCH baseline on the exact phase-2 engine config
    eng_off = _bf16_engine()
    try:
        eng_off.warmup()
        line_off = _loadgen_line(loadgen, eng_off)
    finally:
        eng_off.close()
    cbs.validate_serve_line(line_off, "gate-off line")
    if "divergence" in line_off:
        print("check_quality_plane: OFF path SERVE_BENCH line carries a "
              "divergence block", file=sys.stderr)
        ok = False
    print("check_quality_plane: off path clean (p99 %.3f ms)"
          % line_off["latency_ms_p99"])

    # -- phase 2: bf16 twin, sampling=1.0 ------------------------------------
    os.environ["MXNET_QUALITYPLANE"] = "1"
    os.environ["MXNET_QUALITY_SAMPLE"] = "1.0"
    qualityplane._reset_for_tests()

    key_on = _exec_key(
        Predictor(dsym, dparams, dshapes).with_precision("bf16"))
    if key_on != key_off:
        print("check_quality_plane: gate shifted the AOT logical key:\n"
              "  off %s\n  on  %s" % (key_off, key_on), file=sys.stderr)
        ok = False

    eng_on = _bf16_engine()
    try:
        eng_on.warmup()
        # seed a few shadow samples and wait for the replays so the
        # loadgen line below deterministically carries the block
        rng = np.random.RandomState(0)
        for _ in range(4):
            eng_on.predict(
                {"data": rng.rand(1, 3, 16, 16).astype(np.float32)})
        deadline = time.monotonic() + 60.0
        q = None
        while time.monotonic() < deadline:
            q = eng_on.stats()["quality"]
            if q and q["rows"] and q["divergence"]:
                break
            time.sleep(0.1)
        line_on = _loadgen_line(loadgen, eng_on)
    finally:
        # close() joins the shadow thread: every replay that will ever
        # happen has happened — the verdicts below are final
        eng_on.close()
    q = qualityplane.status()
    rows = qualityplane.plane().rows()
    if not q or not q["rows"] or not q["divergence"] or not rows:
        print("check_quality_plane: gate-on bf16 engine produced no "
              "divergence rows: %r" % (q,), file=sys.stderr)
        return 1
    if "bf16" not in q["divergence"]:
        print("check_quality_plane: divergence summary missing the bf16 "
              "tier: %r" % (q["divergence"],), file=sys.stderr)
        ok = False
    bad = [r for r in rows
           if r["violation"] or r["contract_frac"] is None
           or r["contract_frac"] > 1.0]
    if bad or q["violations"]:
        print("check_quality_plane: bf16 twin broke its tolerance "
              "contract: violations=%s rows=%r"
              % (q["violations"], bad[:3]), file=sys.stderr)
        ok = False
    hist = tin.registry().get("tier_divergence")
    if hist is None or not any(
            s["count"] > 0 and s["labels"].get("tier") == "bf16"
            for s in hist.samples()):
        print("check_quality_plane: tier_divergence histogram has no "
              "bf16 samples", file=sys.stderr)
        ok = False
    cbs.validate_serve_line(line_on, "gate-on line")
    if not line_on.get("divergence", {}).get("bf16"):
        print("check_quality_plane: gate-on SERVE_BENCH line lacks the "
              "bf16 divergence block: %r" % (line_on.get("divergence"),),
              file=sys.stderr)
        ok = False
    # shadow sampling must not inflate the live tail: generous bound for
    # a noisy 2-core CI box sharing the replay thread with live dispatch
    p99_off, p99_on = line_off["latency_ms_p99"], line_on["latency_ms_p99"]
    if p99_on > 5.0 * p99_off + 100.0:
        print("check_quality_plane: shadow sampling inflated live p99: "
              "%.3f ms -> %.3f ms" % (p99_off, p99_on), file=sys.stderr)
        ok = False
    print("check_quality_plane: bf16 twin ok (%d rows, p99 contract_frac "
          "%.3g, violations %d; live p99 %.3f -> %.3f ms)"
          % (q["rows"], q["divergence"]["bf16"]["p99"],
             q["violations"], p99_off, p99_on))

    # -- phase 3: poisoned int8 table ----------------------------------------
    qualityplane._reset_for_tests()
    rsym, rparams, rshapes = _raw_head_checkpoint()
    pred = Predictor(rsym, rparams, rshapes)
    rng = np.random.RandomState(1)
    # calibrate on inputs 100x smaller than live traffic: every live
    # activation saturates the baked int8 range
    table = precision.calibrate(
        pred, ({"data": rng.rand(2, 3, 8, 8).astype(np.float32) * 0.01}
               for _ in range(4)))
    eng3 = Engine(rsym, rparams, {"data": (3, 8, 8)},
                  ladder=BucketLadder((1, 2)), max_wait_ms=2.0,
                  name="qualdrift")
    eng3._proto._exec.set_precision_tier("int8", table)
    try:
        eng3.predict({"data": rng.rand(1, 3, 8, 8).astype(np.float32)})
        deadline = time.monotonic() + 60.0
        q3 = None
        while time.monotonic() < deadline:
            q3 = eng3.stats()["quality"]
            if q3 and q3["rows"] and q3["violations"] and q3["drift"] \
                    and any(d["trips"] for d in q3["drift"].values()):
                break
            time.sleep(0.1)
    finally:
        eng3.close()
    q3 = qualityplane.status()  # final: shadow thread joined by close()
    if not q3 or not q3["rows"]:
        print("check_quality_plane: poisoned int8 engine produced no "
              "quality rows: %r" % (q3,), file=sys.stderr)
        return 1
    if not q3["drift"] or not any(d["trips"] for d in q3["drift"].values()):
        print("check_quality_plane: poisoned table tripped no drift: %r"
              % (q3.get("drift"),), file=sys.stderr)
        ok = False
    drift = tin.registry().get("calibration_drift_total")
    if drift is None or not any(s["value"] > 0 for s in drift.samples()):
        print("check_quality_plane: calibration_drift_total did not fire",
              file=sys.stderr)
        ok = False
    if not q3["violations"]:
        print("check_quality_plane: poisoned table tripped no tolerance "
              "violation: %r" % (q3,), file=sys.stderr)
        ok = False
    viol = tin.registry().get("tier_tolerance_violations_total")
    if viol is None or not any(
            s["value"] > 0 and s["labels"].get("tier") == "int8"
            for s in viol.samples()):
        print("check_quality_plane: tier_tolerance_violations_total{int8} "
              "did not fire", file=sys.stderr)
        ok = False
    dumps = glob.glob(
        os.path.join(FREC_DIR, "flightrec-*-quality_violation.json"))
    if not dumps:
        print("check_quality_plane: violation produced no flightrec dump",
              file=sys.stderr)
        return 1
    meta = json.load(open(dumps[0]))["flightrec"]
    if meta.get("tier") != "int8" or not meta.get("bucket"):
        print("check_quality_plane: dump does not name tier+bucket: %r"
              % (meta,), file=sys.stderr)
        ok = False

    if ok:
        worst = max((d.get("ratio") or 0.0) for d in q3["drift"].values())
        print("check_quality_plane: OK — off path clean, bf16 rows in "
              "tolerance, poisoned int8 drift ratio %.3g tripped, dump %s "
              "names tier=%s bucket=%s"
              % (worst, os.path.basename(dumps[0]), meta.get("tier"),
                 meta.get("bucket")))
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
