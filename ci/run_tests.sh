#!/bin/bash
# CI tiers — the reference's layout (ci/docker/runtime_functions.sh:491-599
# unit nosetests; tests/nightly/test_all.sh nightly tier; gpu tier re-runs
# the suite on device) mapped to this repo:
#
#   ./ci/run_tests.sh unit      fast unit tier (CPU, virtual 8-dev mesh)
#   ./ci/run_tests.sh nightly   multi-process dist cluster + example E2E +
#                               quality trainings (slow, CPU)
#   ./ci/run_tests.sh tpu       device tier on the attached chip:
#                               CPU-vs-TPU check_consistency + benches
#                               (needs PYTHONPATH to be EXACTLY the axon
#                               site — enforced below; both unsetting it
#                               and adding repo paths break the plugin)
#   ./ci/run_tests.sh all       unit + nightly
set -euo pipefail
SELF="$(cd "$(dirname "$0")" && pwd)/$(basename "$0")"
cd "$(dirname "$0")/.."

NIGHTLY_FILES=(
  tests/test_launch_dist.py
  tests/test_examples_classification.py
  tests/test_examples_detection.py
  tests/test_examples_rnn_sparse.py
  tests/test_examples_quant_dp.py
  tests/test_examples_misc.py
  tests/test_examples_nce_fcn_svm.py
  tests/test_example_deformable_rfcn.py
  tests/test_examples_round3.py
  tests/test_examples_round3b.py
  tests/test_examples_round4.py
  tests/test_examples_round5.py
  tests/test_tutorials.py
  tests/test_quality_map.py
  tests/test_quality_map_frcnn.py
  tests/test_quality_map_ssd.py
)

tier="${1:-unit}"
case "$tier" in
  unit)
    # bench-line schema lint (ISSUE 1): BENCH_r*.json and the telemetry
    # block must stay machine-parseable for the driver
    python ci/check_bench_schema.py --self-test BENCH_r*.json
    # serving smoke (ISSUE 2): tiny-symbol engine on CPU, closed+open load,
    # SERVE_BENCH lines must parse and pass the schema lint
    ./dev.sh python tools/loadgen.py --smoke \
      | python ci/check_bench_schema.py -
    # telemetry unit tests (tests/test_telemetry.py) run as part of tests/
    ignore=()
    for f in "${NIGHTLY_FILES[@]}"; do ignore+=(--ignore "$f"); done
    # -m 'not slow': the loadgen smoke above already covers the slow
    # subprocess serving test end-to-end
    exec ./dev.sh python -m pytest tests/ -q -m 'not slow' "${ignore[@]}"
    ;;
  nightly)
    exec ./dev.sh python -m pytest "${NIGHTLY_FILES[@]}" -q
    ;;
  tpu)
    # device tier: consistency sweep on the real chip, then both benches.
    # The axon TPU plugin registers through the ambient PYTHONPATH
    # (/root/.axon_site sitecustomize); dev-style additions to PYTHONPATH
    # break its discovery, so reset it to exactly the axon site when that
    # exists (bare-unset would ALSO break the plugin).
    if [ -d /root/.axon_site ]; then
      export PYTHONPATH=/root/.axon_site
    else
      echo "tpu tier: /root/.axon_site missing — refusing to fall back to CPU" >&2
      exit 1
    fi
    # one FULL retry: the axon tunnel occasionally drops a remote_compile
    # mid-read ("response body closed before all bytes"), surfacing as a
    # JaxRuntimeError on a random case — environmental, not numeric; real
    # consistency failures reproduce on the retry.  A full re-run (not
    # --last-failed) so a hard crash can't leave cases silently unexecuted
    MXNET_TEST_DEVICE=tpu python -m pytest tests/test_consistency_tpu.py -q \
      || MXNET_TEST_DEVICE=tpu python -m pytest tests/test_consistency_tpu.py -q
    python bench.py
    MXNET_BENCH=resnet50 python bench.py
    # detection-quality gate on the chip (VERDICT r2 item 5): full R-101
    # recipe, on-device synthetic stream, n=500 eval.  Round-5
    # recalibration with the fused dconv kernel: seeds 0/1/2 →
    # 0.0900/0.2743/0.3828 — wider true variance than round 4 measured
    # (any numerical perturbation ≈ a fresh seed draw: the SAME xla
    # formulation re-ran at 0.1440 after an unrelated einsum reshape, vs
    # 0.1757 calibrated).  Floor 0.07 = worst − ~20% (QUALITY.md §3);
    # the gate's target failure (broken sampling/targets) scores ≤0.03
    python examples/quality/eval_rfcn_map.py --resnet101 --steps 3000 \
      --live-bn --map-floor 0.07
    # Faster-RCNN VGG16 chip gate (round 4): seeds 0/1/2 → 0.8085/0.7883/
    # 0.8113 — floor 0.63 = worst − ~20% (QUALITY.md §3)
    python examples/quality/eval_frcnn_map.py --vgg16 --steps 3000 \
      --map-floor 0.63
    # SSD-300 full-width chip gate (round 4, with lr warmup): seeds 0/1/2
    # → 0.6802/0.9034/0.9214 — floor 0.54 = worst − ~20% (QUALITY.md §3)
    python examples/quality/eval_ssd_map.py --full --steps 2000 \
      --map-floor 0.54
    # SSD-512 at the 24564-anchor menu (round-5 calibration): seeds 0/1/2
    # → 0.8868/0.3357/0.4145 — wide from-scratch variance at 512², like
    # SSD-300's 0.68-0.92; floor 0.26 = worst − ~20% (QUALITY.md §3).  The
    # gate's target failure (broken MultiBox assignment) scores ~0.001
    python examples/quality/eval_ssd_map.py --full --size 512 --steps 2000 \
      --map-floor 0.26
    ;;
  all)
    "$SELF" unit
    "$SELF" nightly
    ;;
  *)
    echo "usage: $0 {unit|nightly|tpu|all}" >&2
    exit 2
    ;;
esac
