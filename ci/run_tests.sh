#!/bin/bash
# CI tiers — the reference's layout (ci/docker/runtime_functions.sh:491-599
# unit nosetests; tests/nightly/test_all.sh nightly tier; gpu tier re-runs
# the suite on device) mapped to this repo:
#
#   ./ci/run_tests.sh unit      fast unit tier (CPU, virtual 8-dev mesh)
#   ./ci/run_tests.sh nightly   multi-process dist cluster + example E2E +
#                               quality trainings (slow, CPU)
#   ./ci/run_tests.sh tpu       device tier on the attached chip:
#                               CPU-vs-TPU check_consistency + benches
#                               (needs PYTHONPATH to be EXACTLY the axon
#                               site — enforced below; both unsetting it
#                               and adding repo paths break the plugin)
#   ./ci/run_tests.sh all       unit + nightly
set -euo pipefail
SELF="$(cd "$(dirname "$0")" && pwd)/$(basename "$0")"
cd "$(dirname "$0")/.."

NIGHTLY_FILES=(
  tests/test_launch_dist.py
  tests/test_examples_classification.py
  tests/test_examples_detection.py
  tests/test_examples_rnn_sparse.py
  tests/test_examples_quant_dp.py
  tests/test_examples_misc.py
  tests/test_examples_nce_fcn_svm.py
  tests/test_example_deformable_rfcn.py
  tests/test_examples_round3.py
  tests/test_examples_round3b.py
  tests/test_examples_round4.py
  tests/test_examples_round5.py
  tests/test_tutorials.py
  tests/test_quality_map.py
  tests/test_quality_map_frcnn.py
  tests/test_quality_map_ssd.py
)

tier="${1:-unit}"
case "$tier" in
  unit)
    # bench-line schema lint (ISSUE 1): BENCH_r*.json and the telemetry
    # block must stay machine-parseable for the driver
    python ci/check_bench_schema.py --self-test BENCH_r*.json
    # serving smoke (ISSUE 2): tiny-symbol engine on CPU, closed+open load,
    # SERVE_BENCH lines must parse and pass the schema lint
    ./dev.sh python tools/loadgen.py --smoke \
      | python ci/check_bench_schema.py -
    # tracing smoke (ISSUE 4): serve a few requests + two train steps with
    # MXNET_TRACE=1, export, and validate the chrome trace (ts sanity, X
    # nesting, matched flow ids, cross-thread request trace)
    ./dev.sh python ci/check_trace.py --smoke
    # sharded fused step smoke (ISSUE 5): 2 train steps on an 8-host-device
    # dp mesh must be 1 compiled dispatch each with finite loss
    ./dev.sh python ci/check_mesh_fused.py
    # AOT cache smoke (ISSUE 6): warmup twice against one cache dir in
    # subprocesses — second run must be all cache hits and faster
    ./dev.sh python ci/check_aot_cache.py
    # graph-pass smoke (ISSUE 7): dead branch + duplicated subexpression +
    # constant subgraph must reduce to the hand-counted minimum node count
    # with forward parity against MXNET_GRAPH_PASSES=0
    ./dev.sh python ci/check_graph_passes.py
    # autotuning smoke (ISSUE 9): loadgen-recorded trace lints, the ladder
    # proposal beats the default on that trace, and a second autotune.py
    # run against the warm winner store performs zero new measurements
    ./dev.sh python ci/check_autotune.py
    # live ops plane smoke (ISSUE 10): Engine under MXNET_OPS_PORT=0 —
    # /metrics must parse as Prometheus text and carry the serving
    # counters, /healthz must flip 200->503 when the device loop is
    # frozen, /statusz JSON must round-trip, and the streaming SLO p99
    # must agree with loadgen's offline percentile on the same run
    ./dev.sh python ci/check_ops_server.py
    # source lint (ISSUE 8): mxlint over mxnet_tpu/ must be clean against
    # the committed baseline, and a file of seeded hazards must trip every
    # rule (new findings = nonzero exit; docs/ANALYSIS.md)
    ./dev.sh python ci/check_lint.py
    # numerics smoke (ISSUE 11): seeded precision hazards (bf16-accumulated
    # reduction, mixed-dtype binop, softmax fed an unbounded bf16 range,
    # non-bf16-exact float literal) must ALL trip, and the deploy-twin
    # predictor's cast plan must match the acceptance shape (majority
    # bf16_safe, reductions fp32_accum, unbounded exp/log fp32_only)
    ./dev.sh python ci/check_numerics.py
    # lock-discipline smoke (ISSUE 8): concurrent serving burst under
    # MXNET_LOCKCHECK=1 must record zero violations on the real engine,
    # and the seeded inversion/unguarded-mutation must both be detected
    ./dev.sh python ci/check_lockcheck.py
    # compile plane smoke (ISSUE 13): gate off = no rows, no ledger,
    # AOT-cache keys gate-invariant; gate on = the deploy twin yields
    # ledger rows at every compile site with real CPU-XLA flops/peak
    # numbers, and a seeded halved-flops baseline ledger makes
    # bench_compare --gate-cost exit nonzero while identical ledgers pass
    ./dev.sh python ci/check_costplane.py
    # training-health smoke (ISSUE 12): gate off = no staged stats, no
    # plane, no key marker, no dump; a seeded NaN divergence must trip the
    # verdict-class census + blessed-class violation counter and emit a
    # flightrec dump artifact naming the offending parameter group
    ./dev.sh python ci/check_trainhealth.py
    # precision-tier smoke (ISSUE 15): gate off = structural plans + AOT
    # keys byte-identical; the bf16 deploy twin must meet its rtol
    # contract vs fp32 AND show strictly lower ledger bytes_accessed; a
    # calibrated int8 twin meets tolerance, an uncalibrated one is
    # provably untouched
    ./dev.sh python ci/check_precision_tier.py
    # quality plane smoke (ISSUE 16): gate off = no plane, no shadow
    # thread, no quality stats, AOT keys gate-invariant; gate on at
    # sampling=1.0 = the bf16 deploy twin's shadow-sampled divergence rows
    # all sit inside the tier tolerance with zero violations and the
    # SERVE_BENCH line embeds the divergence block; a poisoned int8
    # calibration table (ranges 100x below live traffic) must trip both
    # the calibration-drift counter and a tolerance-violation flightrec
    # dump naming the tier and bucket
    ./dev.sh python ci/check_quality_plane.py
    # SLO-policy router smoke (ISSUE 17): MXNET_ROUTER_* must not move
    # AOT logical keys (off-path invariance); under the same mixed-
    # priority open-loop overload, degrade-first (best-effort rerouted to
    # the bf16 twin pool) must STRICTLY beat the single-engine and
    # shed-only baselines on paid-class goodput, hold the paid p99 target
    # and label downgraded replies with the serving tier; whole run under
    # MXNET_LOCKCHECK=1 with zero violations
    ./dev.sh python ci/check_router.py
    # pod observability smoke (ISSUE 19): MXNET_POD_METRICS unset leaves
    # the fit loop with no plane/thread/socket and no pod_* series; a
    # 2-process launch.py cluster must aggregate both ranks on /podz,
    # trip the ledger-divergence detector on a seeded fingerprint
    # mismatch with correlated (shared incident id) flightrec dumps on
    # both ranks, and raise a straggler verdict when rank 1 freezes
    ./dev.sh python ci/check_pod_obs.py
    # pod-scale fused training smoke (ISSUE 20): a 2-process launch.py
    # cluster joined into ONE 8-device dp mesh (fused step + ZeRO-1 over
    # the process boundary, per-rank half-batches, Gloo CPU collectives)
    # must match the single-process control bit-for-tolerance after a
    # mid-run straggler checkpoint-and-rejoin through MXNET_ELASTIC_DIR,
    # book its dp collectives as DCN bytes, and warm-restart from
    # per-rank AOT caches with zero fresh compiles and a clean non-empty
    # cross-rank ledger diff
    ./dev.sh python ci/check_pod_train.py
    # telemetry unit tests (tests/test_telemetry.py) run as part of tests/
    ignore=()
    for f in "${NIGHTLY_FILES[@]}"; do ignore+=(--ignore "$f"); done
    # -m 'not slow': the loadgen smoke above already covers the slow
    # subprocess serving test end-to-end
    exec ./dev.sh python -m pytest tests/ -q -m 'not slow' "${ignore[@]}"
    ;;
  nightly)
    exec ./dev.sh python -m pytest "${NIGHTLY_FILES[@]}" -q
    ;;
  tpu)
    # device tier: consistency sweep on the real chip, then both benches.
    # The axon TPU plugin registers through the ambient PYTHONPATH
    # (/root/.axon_site sitecustomize); dev-style additions to PYTHONPATH
    # break its discovery, so reset it to exactly the axon site when that
    # exists (bare-unset would ALSO break the plugin).
    if [ -d /root/.axon_site ]; then
      export PYTHONPATH=/root/.axon_site
    else
      echo "tpu tier: /root/.axon_site missing — refusing to fall back to CPU" >&2
      exit 1
    fi
    # one FULL retry: the axon tunnel occasionally drops a remote_compile
    # mid-read ("response body closed before all bytes"), surfacing as a
    # JaxRuntimeError on a random case — environmental, not numeric; real
    # consistency failures reproduce on the retry.  A full re-run (not
    # --last-failed) so a hard crash can't leave cases silently unexecuted
    MXNET_TEST_DEVICE=tpu python -m pytest tests/test_consistency_tpu.py -q \
      || MXNET_TEST_DEVICE=tpu python -m pytest tests/test_consistency_tpu.py -q
    python bench.py
    MXNET_BENCH=resnet50 python bench.py
    # detection-quality gates on the chip (VERDICT r2 item 5, recalibrated
    # per ADVICE round 5): each recipe now runs at TWO fixed seeds and the
    # MEDIAN (== mean at n=2) is gated via ci/gate_map.py, replacing the
    # old single-run worst-seed-minus-20% floors (0.07/0.63/0.54/0.26)
    # that, over cross-seed variance as wide as 0.09..0.38, only caught
    # catastrophic (<=0.03) breakage and would pass a halved-mAP
    # regression.  Floors = mean(seed 0, seed 1 calibration, QUALITY.md §3
    # round-4/5 sweeps) − ~20%:
    #   R-FCN R-101  0.0900/0.2743 → mean 0.182 → floor 0.14
    #   FRCNN VGG16  0.8085/0.7883 → mean 0.798 → floor 0.64
    #   SSD-300      0.6802/0.9034 → mean 0.792 → floor 0.63
    #   SSD-512      0.8868/0.3357 → mean 0.611 → floor 0.49
    run_map_gate() {
      local floor="$1"; shift
      local vals=() log
      for seed in 0 1; do
        log="$(mktemp)"
        "$@" --seed "$seed" | tee "$log"
        vals+=("$(python ci/gate_map.py --extract "$log")")
        rm -f "$log"
      done
      python ci/gate_map.py --floor "$floor" "${vals[@]}"
    }
    run_map_gate 0.14 python examples/quality/eval_rfcn_map.py --resnet101 \
      --steps 3000 --live-bn
    run_map_gate 0.64 python examples/quality/eval_frcnn_map.py --vgg16 \
      --steps 3000
    run_map_gate 0.63 python examples/quality/eval_ssd_map.py --full \
      --steps 2000
    run_map_gate 0.49 python examples/quality/eval_ssd_map.py --full \
      --size 512 --steps 2000
    ;;
  all)
    "$SELF" unit
    "$SELF" nightly
    ;;
  *)
    echo "usage: $0 {unit|nightly|tpu|all}" >&2
    exit 2
    ;;
esac
