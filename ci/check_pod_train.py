#!/usr/bin/env python
"""CI smoke for pod-scale fused training (ISSUE 20).

Three phases over the same model/data, exit 0 only when all pass —
wired into the unit tier of ``ci/run_tests.sh``:

1. **Control.**  A single-process 8-device run (mesh ``dp=8``, fused
   step + ZeRO-1) trains 20 global steps and writes its final params.
2. **Pod train (launch A).**  ``tools/launch.py -n 2 --launcher local``
   spawns two processes x 4 virtual devices joined into the SAME
   8-device dp mesh; each rank feeds only its half of every global
   batch (``parallel.global_batch_array`` — no host gathering).  Mid-
   run rank 1 stalls 3.5 s (between the 2 s straggler and 6 s death
   thresholds): rank 0's detector mints a straggler incident carrying
   the agreed ``rejoin_step``, BOTH ranks checkpoint-and-rejoin at that
   boundary through the shared ``MXNET_ELASTIC_DIR``, and the final
   params must still match the control run — the rebase is
   value-preserving and the pod run is step-for-step the single-process
   program.  Asserts the dp collectives were booked as DCN bytes and
   zero ledger divergences between the ranks' compile fingerprints.
3. **Pod warm restart (launch B).**  Same dirs, one more epoch: every
   rank resumes from the durable checkpoint (fast-forwarding the 20
   restored steps), restores its fused step from its per-rank
   ``MXNET_AOT_CACHE`` with ``compile_s == 0.0`` and zero tier-1
   misses, and rank 0 sees both ranks publish NON-empty cost ledgers
   (the AOT restore path re-publishes the stored fingerprint) with
   zero divergences — the proof both ranks run the identical compiled
   program without recompiling anywhere.
"""
from __future__ import annotations

import os
import shutil
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORK = "/tmp/pod_train_smoke"

WORKER = textwrap.dedent("""
    import os, sys, time

    phase = os.environ["POD_TRAIN_PHASE"]      # control | train | warm
    base = os.environ["POD_TRAIN_DIR"]
    rank = int(os.environ.get("MXNET_WORKER_RANK", "0"))
    ndev = 8 if phase == "control" else 4
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=%d"
                               % ndev)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["MXNET_TELEMETRY_FILE"] = os.path.join(
        base, "tel_%s_r%d.jsonl" % (phase, rank))
    if phase != "control":
        # per-rank AOT cache dir: launch B must restore warm on EVERY
        # rank from its own store (MXNET_AOT_CACHE itself is propagated
        # by tools/launch.py; the per-rank suffix is worker-side)
        os.environ["MXNET_AOT_CACHE"] = os.path.join(base, "aot_r%d" % rank)

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import compile_cache
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu import parallel
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.parallel.mesh import mesh_batch_factor, \\
        mesh_spans_processes
    from mxnet_tpu.telemetry import instrument as tin

    GB, DIM, CLASSES, SPE = 16, 8, 4, 10   # global batch, dims, steps/epoch

    def make_data():
        rng = np.random.RandomState(7)
        X = rng.randn(SPE * GB, DIM).astype(np.float32)
        W = rng.randn(DIM, CLASSES).astype(np.float32)
        y = np.argmax(X @ W, axis=1).astype(np.float32)
        return X, y

    def build(mesh):
        data = mx.sym.var("data")
        x = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
        x = mx.sym.Activation(x, name="relu1", act_type="relu")
        x = mx.sym.FullyConnected(x, name="fc2", num_hidden=CLASSES)
        sym = mx.sym.SoftmaxOutput(x, name="softmax")
        mod = mod_mod.Module(sym, mesh=mesh)
        lb = GB // mesh_batch_factor(mesh)   # host-local batch rows
        mod.bind(data_shapes=[("data", (lb, DIM))],
                 label_shapes=[("softmax_label", (lb,))])
        rng = np.random.RandomState(3)       # identical init on every rank
        shapes = {n: a.shape for n, a in mod._exec.arg_dict.items()}
        arg = {n: mx.nd.array(rng.randn(*shapes[n]).astype(np.float32) * 0.1)
               for n in sorted(mod._param_names)}
        return mod, arg

    X, y = make_data()
    opt_params = {"learning_rate": 0.1, "momentum": 0.9}

    if phase == "control":
        mx.random.seed(11)
        mod, arg = build(parallel.make_mesh({"dp": 8}))
        it = NDArrayIter(X, y, batch_size=GB, label_name="softmax_label")
        mod.fit(it, num_epoch=2, arg_params=arg, optimizer_params=opt_params)
        assert mod._fused is not None and mod._fused.zero
        np.savez(os.path.join(base, "control.npz"),
                 **{n: v.asnumpy() for n, v in mod.get_params()[0].items()})
        print("CONTROL_RESULT ok", flush=True)
        sys.exit(0)

    from mxnet_tpu.parallel import dist
    from mxnet_tpu.telemetry import podplane

    dist.init()
    import jax
    assert dist.size() == 2 and len(jax.devices()) == 8, \\
        (dist.size(), jax.devices())
    mesh = parallel.make_mesh({"dp": 8})     # spans both processes
    assert mesh_spans_processes(mesh)
    assert mesh_batch_factor(mesh) == 2
    pod = podplane.plane()
    assert pod is not None and pod.size == 2

    mod, arg = build(mesh)
    # each rank feeds its contiguous half of every global batch (default
    # make_mesh layout: process r's rows sit at global offset r*8)
    Xl = X.reshape(SPE, GB, DIM)[:, rank * 8:(rank + 1) * 8, :] \\
        .reshape(-1, DIM)
    yl = y.reshape(SPE, GB)[:, rank * 8:(rank + 1) * 8].reshape(-1)

    def make_iter():
        return NDArrayIter(Xl, yl, batch_size=GB // 2,
                           label_name="softmax_label")

    def assert_parity(mod):
        ctrl = np.load(os.path.join(base, "control.npz"))
        args_out, _ = mod.get_params()
        for n in ctrl.files:
            np.testing.assert_allclose(args_out[n].asnumpy(), ctrl[n],
                                       rtol=2e-5, atol=1e-6, err_msg=n)

    mx.random.seed(11)
    if phase == "train":
        stalled = []

        def stall_cb(param):
            # rank 1 stalls once, past the 2 s straggler age and under
            # the 6 s death age — the detector must call it a straggler
            if rank == 1 and param.epoch == 0 and param.nbatch == 4 \\
                    and not stalled:
                stalled.append(1)
                time.sleep(3.5)

        mod.fit(make_iter(), num_epoch=2, arg_params=arg,
                batch_end_callback=stall_cb, optimizer_params=opt_params)
        assert mod._fused is not None and mod._fused.mesh is not None \\
            and mod._fused.zero
        st = mod.elastic_stats()
        assert st is not None and st["resume_step"] == 0, st
        # the acceptance gate: the straggler incident triggered one
        # checkpoint-and-rejoin at the agreed boundary, before the end
        assert st["rejoins"] == 1 and st["last_rejoin_step"] is not None, st
        assert st["last_rejoin_step"] < 20, st
        assert st["steps"][-1] == 20, st      # final step durably saved
        # ...and the rebase was value-preserving: 20-step parity vs the
        # single-process control, straggler response included
        assert_parity(mod)
        cs = compile_cache.stats()
        assert cs["misses"] >= 1, cs          # cold: compiled + stored
        r = tin.registry()
        assert r.get("train_steps_total").value(path="fused_mesh") == 20
        assert r.get("module_fused_fallback_total") is None
        # dp spans processes: the in-step collectives are DCN bytes
        link = r.get("collective_link_bytes_total")
        dcn = sum((link.value(link="dcn", op=op) or 0)
                  for op in ("psum_grads", "reduce_scatter", "allgather"))
        assert dcn > 0, "no dp collective booked as dcn"
        assert not any((link.value(link="ici", op=op) or 0)
                       for op in ("psum_grads", "reduce_scatter",
                                  "allgather")), "pod dp bytes booked as ici"
        # ZeRO-1 really sharded: some state leaf holds 1/dp per device
        sharded = 0
        for i, n in enumerate(mod._param_names):
            s = mod._updater.states[i]
            if s is None:
                continue
            for leaf in ([s] if not isinstance(s, (tuple, list)) else s):
                a = leaf._data
                if int(np.prod(a.sharding.shard_shape(a.shape))) * 8 \\
                        == int(np.prod(a.shape)):
                    sharded += 1
        assert sharded > 0, "no ZeRO-sharded optimizer state leaf"
        if rank == 0:
            pz = pod.podz()
            assert pz["ranks_reporting"] == 2, pz
            assert pz["straggler_verdicts"] >= 1, pz
            incs = [i for i in pz["incidents"]
                    if i["reason"] == "straggler"]
            assert incs and incs[0]["meta"].get("rejoin_step") is not None, \\
                pz["incidents"]
            assert incs[0]["meta"]["rejoin_step"] == st["last_rejoin_step"]
            assert pz["ledger_divergence_count"] == 0, \\
                pz["ledger_divergences"]
        print("RANK%d_TRAIN ok" % rank, flush=True)
    else:
        assert phase == "warm", phase
        mod.fit(make_iter(), num_epoch=3, arg_params=arg,
                optimizer_params=opt_params)
        st = mod.elastic_stats()
        assert st is not None and st["resume_step"] == 20, st
        assert st["steps"][-1] == 30, st
        cs = compile_cache.stats()
        # THE warm-restart acceptance: every rank restored its compiled
        # step from its own AOT store — zero fresh tier-1 compiles,
        # zero seconds spent in XLA compilation
        assert cs["hits"] >= 1, cs
        assert cs["misses"] == 0, cs
        assert cs["compile_s"] == 0.0, cs
        if rank == 0:
            pz = pod.podz()
            assert pz["ranks_reporting"] == 2, pz
            # the restore path re-published each entry's stored cost
            # fingerprint, so the cross-rank ledger diff is non-vacuous
            for rk in ("0", "1"):
                assert pz["ranks"][rk]["ledger_keys"] >= 1, pz["ranks"][rk]
            assert pz["ledger_divergence_count"] == 0, \\
                pz["ledger_divergences"]
        print("RANK%d_WARM ok" % rank, flush=True)
    dist.shutdown()
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _check_launcher_propagation():
    """Satellite: tools/launch.py forwards the AOT/autotune/elastic cache
    env families into worker env even when built from scratch (ssh path,
    base={}) — a pod restart must be warm on every rank, not just the
    launcher's."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import launch

    probe = {"MXNET_AOT_CACHE": "/x/aot", "MXNET_AOT_CACHE_MAX_MB": "64",
             "MXNET_AUTOTUNE": "1", "MXNET_AUTOTUNE_CACHE": "/x/tune",
             "MXNET_ELASTIC_DIR": "/x/el"}
    old = {k: os.environ.get(k) for k in probe}
    os.environ.update(probe)
    try:
        env = launch._env_for(1, 2, "h0:29400", base={})
        for k, v in probe.items():
            assert env.get(k) == v, (k, env.get(k))
        assert env["MXNET_WORKER_RANK"] == "1"
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    print("launcher env propagation (AOT/autotune/elastic families) — ok")


def _base_env():
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO,
        POD_TRAIN_DIR=WORK,
        # the whole pod story under one env: fused + ZeRO across the
        # process boundary, costplane ledgers, elastic checkpoints
        MXNET_MODULE_FUSED_STEP="1",
        MXNET_FUSED_ZERO="1",
        # donation off: a donated executable cannot legally restore from
        # disk on the CPU backend (docs/PERF_NOTES.md) — and launch B's
        # whole point is the disk restore
        MXNET_FUSED_DONATE="0",
        MXNET_COSTPLANE="1",
        MXNET_TELEMETRY="1",
        MXNET_ELASTIC_DIR=os.path.join(WORK, "elastic"),
        # only the rejoin + final saves: keeps the collective-save count
        # deterministic under the stall
        MXNET_ELASTIC_SAVE_STEPS="50",
    )
    env.pop("MXNET_OPS_PORT", None)
    env.pop("MXNET_FLIGHTREC_DIR", None)
    env.pop("MXNET_POD_METRICS", None)
    env.pop("MXNET_POD_METRICS_ADDR", None)
    env.pop("MXNET_AOT_CACHE", None)  # per-rank, set by the worker
    return env


def check_control(worker):
    env = _base_env()
    env["POD_TRAIN_PHASE"] = "control"
    # no elastic for the control: its final save would otherwise land in
    # the shared MXNET_ELASTIC_DIR and launch A would resume from it
    # instead of training its own 20 steps
    env.pop("MXNET_ELASTIC_DIR", None)
    res = subprocess.run([sys.executable, worker], env=env,
                         capture_output=True, text=True, timeout=420)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "CONTROL_RESULT ok" in res.stdout, res.stdout + res.stderr
    print("control: 20-step single-process fused+ZeRO run — ok")


def _launch2(worker, phase, extra_env):
    env = _base_env()
    env["POD_TRAIN_PHASE"] = phase
    env["MXNET_POD_METRICS"] = "1"
    env["MXNET_POD_METRICS_ADDR"] = "127.0.0.1:%d" % _free_port()
    env["MXNET_POD_PUSH_S"] = "0"            # push every step
    env.update(extra_env)
    launch = os.path.join(REPO, "tools", "launch.py")
    # Gloo inter-process connects can time out on a saturated host —
    # retry like tests/test_launch_dist.py
    for _ in range(3):
        res = subprocess.run(
            [sys.executable, launch, "-n", "2", "--launcher", "local",
             sys.executable, worker],
            env=env, capture_output=True, text=True, timeout=420)
        if res.returncode == 0:
            break
    assert res.returncode == 0, res.stdout + res.stderr
    out = res.stdout
    marker = phase.upper()
    assert "RANK0_%s ok" % marker in out, out + res.stderr
    assert "RANK1_%s ok" % marker in out, out + res.stderr
    assert any(l.startswith("[rank 0] ") for l in out.splitlines())
    assert any(l.startswith("[rank 1] ") for l in out.splitlines())
    return out


def check_train(worker):
    out = _launch2(worker, "train", {
        # rank 1's 3.5 s stall sits between straggler (2 s) and death
        # (3x = 6 s) thresholds: a straggler verdict, not a presumed death
        "MXNET_POD_STRAGGLER_AGE_S": "2",
    })
    assert "elastic: straggler incident" in out, out
    assert "elastic: rejoined from durable checkpoint" in out, out
    print("launch A: 2-process fused+ZeRO parity with control, straggler "
          "checkpoint-and-rejoin at the agreed step — ok")


def check_warm(worker):
    out = _launch2(worker, "warm", {
        "MXNET_POD_STRAGGLER_AGE_S": "30",   # nothing stalls here
    })
    assert "elastic: resumed from durable checkpoint" in out, out
    print("launch B: both ranks AOT-warm (compile_s == 0.0, zero misses), "
          "resumed at step 20, clean non-empty ledger diff — ok")


def main():
    _check_launcher_propagation()
    shutil.rmtree(WORK, ignore_errors=True)
    os.makedirs(WORK, exist_ok=True)
    worker = os.path.join(WORK, "worker.py")
    with open(worker, "w") as f:
        f.write(WORKER)
    check_control(worker)
    check_train(worker)
    check_warm(worker)
    print("check_pod_train: all phases passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
