#!/usr/bin/env python
"""CI smoke for the sharded fused Module train step (ISSUE 5).

Runs a tiny MLP Module over an 8-device dp mesh (the virtual CPU host
devices ``dev.sh`` forces via ``--xla_force_host_platform_device_count=8``),
takes two train steps, and asserts the acceptance criteria of the issue:

* the fused_mesh path engaged (no fallback counted),
* exactly ONE compiled dispatch per step
  (``step_dispatches_total{path="fused_mesh"} == train steps`` and
  ``summary()["dispatches_per_step"] == 1``),
* the loss heads are finite.

Exit 0 on success, 1 with a message on any violation — wired into the unit
tier of ``ci/run_tests.sh``.
"""
from __future__ import annotations

import os
import sys


def main():
    # invoked as `python ci/check_mesh_fused.py`: the script dir is on
    # sys.path, the repo root is not — add it so mxnet_tpu imports
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["MXNET_TELEMETRY"] = "1"
    os.environ.setdefault("MXNET_TELEMETRY_FILE", "/tmp/mesh_fused_smoke.jsonl")
    os.environ["MXNET_MODULE_FUSED_STEP"] = "1"
    os.environ.setdefault("MXNET_FUSED_ZERO", "0")

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu import parallel
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu.telemetry import instrument as tin

    import jax

    ndev = len(jax.devices())
    if ndev < 8:
        print("check_mesh_fused: need 8 devices, have %d (run under dev.sh)"
              % ndev, file=sys.stderr)
        return 1

    mx.random.seed(0)
    mesh = parallel.make_mesh({"dp": 8})
    data = mx.sym.var("data")
    x = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    x = mx.sym.Activation(x, name="relu1", act_type="relu")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(x, name="fc2", num_hidden=4), name="softmax")

    mod = mod_mod.Module(sym, mesh=mesh)
    mod.bind(data_shapes=[("data", (16, 8))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})

    rng = np.random.RandomState(0)
    steps = 2
    for _ in range(steps):
        b = DataBatch(
            data=[mx.nd.array(rng.randn(16, 8).astype(np.float32))],
            label=[mx.nd.array(rng.randint(0, 4, (16,)).astype(np.float32))])
        mod.forward_backward(b)
        mod.update()
        out = mod.get_outputs()[0].asnumpy()
        if not np.isfinite(out).all():
            print("check_mesh_fused: non-finite outputs after a step",
                  file=sys.stderr)
            return 1

    r = tin.registry()
    got_steps = r.get("train_steps_total")
    got_steps = got_steps.value(path="fused_mesh") if got_steps else 0
    disp = r.get("step_dispatches_total")
    disp = disp.value(path="fused_mesh") if disp else 0
    fallbacks = r.get("module_fused_fallback_total")
    dps = tin.summary()["dispatches_per_step"]

    ok = True
    if got_steps != steps:
        print("check_mesh_fused: expected %d fused_mesh steps, counted %s"
              % (steps, got_steps), file=sys.stderr)
        ok = False
    if disp != steps:
        print("check_mesh_fused: expected 1 dispatch/step (%d total), "
              "counted %s" % (steps, disp), file=sys.stderr)
        ok = False
    if fallbacks is not None:
        print("check_mesh_fused: unexpected fallbacks: %s"
              % (fallbacks.samples(),), file=sys.stderr)
        ok = False
    if dps != 1.0:
        print("check_mesh_fused: dispatches_per_step %s != 1.0" % dps,
              file=sys.stderr)
        ok = False
    if ok:
        print("check_mesh_fused: OK — %d steps, 1 dispatch/step, finite loss "
              "(dp=8 mesh)" % steps)
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
