#!/usr/bin/env python
"""CI smoke for the pod observability plane (ISSUE 19).

Two phases, exit 0 only when both pass — wired into the unit tier of
``ci/run_tests.sh``:

1. **Off path clean.**  With ``MXNET_POD_METRICS`` unset (telemetry ON,
   so the registry is live and would show any leak), a Module fit run
   creates no plane, no listener thread, no socket, no ``pod_*`` metric
   series, and ``podz()`` answers ``{"enabled": false}`` — the `is None`
   zero-overhead contract.
2. **2-process pod smoke.**  A real ``tools/launch.py -n 2 --launcher
   local`` fake cluster over ``jax.distributed`` (Gloo handshake only —
   the pod channel is podplane's own socket, so the CPU backend's
   missing collectives don't matter): both ranks fit a tiny module;
   rank 0's ``/podz`` HTTP endpoint must show BOTH ranks' series; a
   seeded per-rank ledger fingerprint mismatch must trip the divergence
   counter with correlated (same incident id) flight-recorder dumps on
   both ranks; and a frozen rank 1 must raise a straggler verdict on
   rank 0.  The parent then runs ``tools/pod_status.py --collect`` over
   the two per-rank dump dirs and requires one merged incident timeline,
   and checks every worker stdout line carries its ``[rank N]`` prefix.
"""
from __future__ import annotations

import glob
import json
import os
import shutil
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORK = "/tmp/pod_obs_smoke"

WORKER = textwrap.dedent("""
    import glob, json, os, sys, time, urllib.request

    rank = int(os.environ["MXNET_WORKER_RANK"])
    base = os.environ["POD_SMOKE_DIR"]
    os.environ["MXNET_FLIGHTREC_DIR"] = os.path.join(base, "frec_r%d" % rank)
    os.environ["MXNET_TELEMETRY_FILE"] = os.path.join(
        base, "tel_r%d.jsonl" % rank)
    if rank == 0:
        os.environ["MXNET_OPS_PORT"] = "0"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.parallel import dist
    from mxnet_tpu.telemetry import flightrec, ops_server, podplane

    dist.init()
    assert dist.size() == 2, dist.size()

    pod = podplane.plane()
    assert pod is not None and pod.rank == rank and pod.size == 2
    flightrec.record("smoke_warm", rank=rank)  # non-empty ring can dump
    # seeded fingerprint mismatch: same stable key, different flops — the
    # divergence detector's job is to notice without a real compile skew
    pod.seed_ledger("smoke#fwd", flops=1000 * (rank + 1),
                    bytes_accessed=4096, compile_s=0.1)

    data = mx.sym.var("data")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=4), name="softmax")
    mod = mod_mod.Module(sym)
    rng = np.random.RandomState(rank)
    it = NDArrayIter(rng.randn(64, 8).astype(np.float32),
                     rng.randint(0, 4, (64,)).astype(np.float32),
                     batch_size=8)
    mod.fit(it, num_epoch=2, optimizer_params={"learning_rate": 0.1})

    if rank == 0:
        # keep ticking (so rank 0 never reads stale to itself and its
        # _observe_incidents runs) while waiting for rank 1's pushes
        deadline = time.monotonic() + 120.0
        pz = pod.podz()
        while time.monotonic() < deadline and not (
                pz["ranks_reporting"] == 2
                and pz["ledger_divergence_count"] >= 1):
            pod.tick()
            time.sleep(0.2)
            pz = pod.podz()
        assert pz["ranks_reporting"] == 2, pz
        assert pz["ledger_divergence_count"] == 1, pz
        d = pz["ledger_divergences"]["smoke#fwd"]
        assert sorted(d["ranks"]) == [0, 1], d
        # both ranks' step series on the aggregated view
        assert pz["ranks"]["0"]["steps"] == 16
        assert pz["ranks"]["1"]["steps"] >= 1
        assert pz["ranks"]["1"]["step_p50_ms"] is not None
        # ...and over the REAL ops endpoint
        port = ops_server.port()
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/podz" % port, timeout=10) as r:
            over_http = json.loads(r.read())
        assert set(over_http["ranks"]) == {"0", "1"}, over_http
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % port, timeout=10) as r:
            metrics = r.read().decode()
        assert "pod_ledger_divergence_total" in metrics
        assert 'pod_' in metrics and 'rank="1"' in metrics, \\
            "no rank-labeled mirrored series on /metrics"
        # straggler: rank 1 goes quiet (it is sleeping through its
        # freeze); with MXNET_POD_STRAGGLER_AGE_S=1 the verdict must
        # flip within a few scrapes
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline \\
                and not pod.podz()["ranks"]["1"]["straggler"]:
            pod.tick()
            time.sleep(0.2)
        pz = pod.podz()
        assert pz["ranks"]["1"]["straggler"] is True, pz["ranks"]["1"]
        assert pz["straggler_verdicts"] >= 1
        assert "pod_straggler_verdicts_total" in urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % port, timeout=10).read().decode()
        # the divergence detail dump exists on the aggregating rank
        assert glob.glob(os.path.join(
            base, "frec_r0", "*pod_ledger_divergence*.json"))
        print("RANK0_RESULT ok", flush=True)
    else:
        # wait for the incident broadcast (the divergence incident rides
        # a push response), then freeze so rank 0 sees a straggler
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline \\
                and pod.push_stats()["incidents_seen"] < 1:
            pod.tick()
            time.sleep(0.1)
        assert pod.push_stats()["incidents_seen"] >= 1, pod.push_stats()
        dumps = glob.glob(os.path.join(base, "frec_r1",
                                       "*pod_incident*.json"))
        assert dumps, "no incident-tagged dump on rank 1"
        time.sleep(6.0)  # frozen: no pushes -> rank 0's straggler signal
        print("RANK1_RESULT ok", flush=True)
    dist.shutdown()
""")


def check_off_path():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["MXNET_TELEMETRY"] = "1"
    os.environ["MXNET_TELEMETRY_FILE"] = os.path.join(WORK, "off.jsonl")
    os.environ.pop("MXNET_POD_METRICS", None)
    os.environ.pop("MXNET_POD_METRICS_ADDR", None)

    import threading

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.telemetry import instrument as tin
    from mxnet_tpu.telemetry import podplane

    threads_before = {t.name for t in threading.enumerate()}
    assert podplane.plane() is None
    assert podplane.podz() == {"enabled": False}
    assert podplane.status() is None

    data = mx.sym.var("data")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=4), name="softmax")
    mod = mod_mod.Module(sym)
    rng = np.random.RandomState(0)
    it = NDArrayIter(rng.randn(16, 8).astype(np.float32),
                     rng.randint(0, 4, (16,)).astype(np.float32),
                     batch_size=8)
    mod.fit(it, num_epoch=1, optimizer_params={"learning_rate": 0.1})

    assert podplane.plane() is None
    names = [m["name"] for m in tin.registry().collect()]
    polluted = [n for n in names if n.startswith("pod_")]
    assert not polluted, "off path leaked pod series: %s" % polluted
    new_threads = {t.name for t in threading.enumerate()} - threads_before
    assert not any("pod" in n for n in new_threads), new_threads
    print("off path: no plane, no thread, no pod_* series — ok")


def check_two_process():
    shutil.rmtree(WORK, ignore_errors=True)
    os.makedirs(WORK, exist_ok=True)
    worker = os.path.join(WORK, "worker.py")
    with open(worker, "w") as f:
        f.write(WORKER)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    pod_port = s.getsockname()[1]
    s.close()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO,
        POD_SMOKE_DIR=WORK,
        MXNET_POD_METRICS="1",
        # explicit channel addr: the coordinator-derived default port
        # could collide on a shared CI host
        MXNET_POD_METRICS_ADDR="127.0.0.1:%d" % pod_port,
        MXNET_POD_PUSH_S="0",           # push every step
        MXNET_POD_STRAGGLER_AGE_S="1",  # freeze detected in ~1 s
        MXNET_TELEMETRY="1",
    )
    env.pop("MXNET_OPS_PORT", None)      # rank 0 sets its own
    env.pop("MXNET_FLIGHTREC_DIR", None)  # per-rank, set by the worker
    launch = os.path.join(REPO, "tools", "launch.py")
    # Gloo inter-process connects can time out on a saturated host —
    # retry like tests/test_launch_dist.py
    for attempt in range(3):
        res = subprocess.run(
            [sys.executable, launch, "-n", "2", "--launcher", "local",
             sys.executable, worker],
            env=env, capture_output=True, text=True, timeout=420)
        if res.returncode == 0:
            break
    assert res.returncode == 0, res.stdout + res.stderr
    out = res.stdout
    assert "RANK0_RESULT ok" in out, out
    assert "RANK1_RESULT ok" in out, out
    # launcher satellite: every worker line is rank-attributable
    assert any(line.startswith("[rank 0] ") for line in out.splitlines())
    assert any(line.startswith("[rank 1] ") for line in out.splitlines())
    print("2-process: /podz both ranks, divergence + straggler — ok")

    # correlated dumps: one shared incident id across BOTH rank dirs
    def _ids(rankdir):
        ids = set()
        for p in glob.glob(os.path.join(WORK, rankdir, "*.json")):
            meta = json.load(open(p)).get("flightrec") or {}
            if meta.get("incident"):
                ids.add(meta["incident"])
        return ids

    shared = _ids("frec_r0") & _ids("frec_r1")
    assert shared, "no shared incident id across rank dumps"
    print("correlated incident dumps on both ranks: %s — ok"
          % sorted(shared))

    # pod_status --collect merges the correlated dumps onto one timeline
    merged_dir = os.path.join(WORK, "merged")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pod_status.py"),
         "--collect", os.path.join(WORK, "frec_r0"),
         os.path.join(WORK, "frec_r1"), "-o", merged_dir],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr
    merged = glob.glob(os.path.join(merged_dir, "*.json"))
    assert merged, res.stdout
    evs = json.load(open(merged[0]))["traceEvents"]
    ranks = {e.get("args", {}).get("rank") for e in evs
             if e.get("ph") != "M"}
    assert {0, 1} <= ranks, ranks
    print("pod_status --collect merged %d incident timeline(s) — ok"
          % len(merged))


def main():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    shutil.rmtree(WORK, ignore_errors=True)
    os.makedirs(WORK, exist_ok=True)
    check_two_process()  # subprocesses first: the off-path phase imports
    check_off_path()     # jax into THIS process, harmless after
    print("check_pod_obs: all phases passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
