#!/usr/bin/env python
"""CI smoke for the compile plane (ISSUE 13).

Three phases, exit 0 only when all pass — wired into the unit tier of
``ci/run_tests.sh``:

1. **Off path clean.**  With ``MXNET_COSTPLANE`` unset, forwards and
   fused train steps record no rows, write no ledger, and the AOT-cache
   logical key for a given computation is byte-identical to the gate-on
   key (the gate must never move executable-cache identity).
2. **Every compile site produces rows.**  Gate on, the two-head deploy
   twin (``test_utils.deploy_twin_checkpoint``) served through an Engine
   warmup plus a fused Module train step must yield ledger rows from the
   ``executor_fwd`` site (one per warmed bucket, carrying real CPU-XLA
   flops/peak numbers), the ``fused_step`` site, and — with
   ``MXNET_AOT_CACHE`` set — the CachedFunction finalize hook (same site
   labels, rows recorded at the one place XLA actually compiled).
3. **Seeded regression gates.**  A baseline ledger seeded with HALVED
   flops against the real current ledger makes
   ``tools/bench_compare.py --gate-cost`` exit nonzero, and the identical
   pair passes silently.
"""
from __future__ import annotations

import json
import os
import sys


def main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for p in (repo, os.path.join(repo, "tools")):
        if p not in sys.path:
            sys.path.insert(0, p)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["MXNET_MODULE_FUSED_STEP"] = "1"
    os.environ.pop("MXNET_COSTPLANE", None)
    os.environ.pop("MXNET_COST_LEDGER", None)
    ledger = "/tmp/costplane_smoke_ledger.jsonl"
    aot_dir = "/tmp/costplane_smoke_aot"
    for path in (ledger, ledger + ".base"):
        try:
            os.remove(path)
        except OSError:
            pass
    # a previous run's executables would restore from disk and record no
    # rows (a restore builds nothing) — every run starts cold
    import shutil

    shutil.rmtree(aot_dir, ignore_errors=True)

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import compile_cache, serving
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu.telemetry import costplane
    from mxnet_tpu.test_utils import deploy_twin_checkpoint

    ok = True

    def fail(msg):
        nonlocal ok
        ok = False
        print("FAIL: %s" % msg)

    def train_module(batch=6):
        data = mx.sym.var("data")
        sym = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(
                mx.sym.FullyConnected(data, name="fc1", num_hidden=8),
                name="fc2", num_hidden=4), name="softmax")
        mod = mod_mod.Module(sym)
        mod.bind(data_shapes=[("data", (batch, 8))],
                 label_shapes=[("softmax_label", (batch,))])
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        rng = np.random.RandomState(0)
        for _ in range(2):
            b = DataBatch(
                data=[mx.nd.array(rng.randn(batch, 8).astype(np.float32))],
                label=[mx.nd.array(rng.randint(0, 4, (batch,))
                                   .astype(np.float32))])
            mod.forward_backward(b)
            mod.update()
        return mod

    # -- phase 1: off path ----------------------------------------------------
    train_module()
    sym, params, input_shapes = deploy_twin_checkpoint(batch=2, image=16)
    pred = mx.predictor.Predictor(sym, params, input_shapes)
    pred.forward(data=np.zeros(input_shapes["data"], np.float32))
    if costplane.row_count() != 0:
        fail("gate off recorded %d rows" % costplane.row_count())
    if os.path.exists(ledger):
        fail("gate off wrote a ledger")
    # AOT key identity across the gate
    import jax

    os.environ["MXNET_AOT_CACHE"] = aot_dir
    jfn = jax.jit(lambda x: x + 1)
    key_off = compile_cache.CachedFunction(jfn, ("smoke", 1), name="s")._key
    os.environ["MXNET_COSTPLANE"] = "1"
    key_on = compile_cache.CachedFunction(jfn, ("smoke", 1), name="s")._key
    if key_off != key_on:
        fail("AOT logical key moved with the gate: %r vs %r"
             % (key_off, key_on))
    print("phase 1 ok: off path clean, AOT keys gate-invariant")

    # -- phase 2: rows at every compile site ----------------------------------
    os.environ["MXNET_COST_LEDGER"] = ledger
    costplane._reset_for_tests()
    # fused train step (goes through CachedFunction: MXNET_AOT_CACHE is on,
    # donated ⇒ in-memory AOT split on CPU, finalize hook records)
    train_module()
    # deploy twin through the serving plane: warmup compiles every bucket
    eng = serving.Engine(sym, params, {"data": input_shapes["data"][1:]},
                         start=False, name="cp_smoke")
    try:
        report = eng.warmup()
    finally:
        eng.close()
    sites = {r["site"] for r in costplane.rows()}
    for want in ("fused_step", "executor_fwd"):
        if want not in sites:
            fail("no compile row from site %r (got %s)" % (want,
                                                           sorted(sites)))
    fresh = [r for r in report if r["fresh"]]
    if not fresh or any(r.get("xla_flops") in (None, 0) for r in fresh):
        fail("warmup report rows missing xla_flops: %r"
             % [(r["bucket"], r.get("xla_flops")) for r in report])
    if any(r.get("xla_peak_bytes") in (None, 0) for r in fresh):
        fail("warmup report rows missing xla_peak_bytes")
    st = costplane.status()
    if st["rows"] < 1 + len(fresh):
        fail("expected >= %d rows, got %d" % (1 + len(fresh), st["rows"]))
    if not os.path.exists(ledger):
        fail("gate on wrote no ledger")
    else:
        led = costplane.load_ledger(ledger)
        nulls = [k for k, r in led.items() if r.get("flops") is None]
        if nulls:
            fail("CPU XLA rows with null flops (degradation misfired): %s"
                 % nulls)
        print("phase 2 ok: %d rows over sites %s, %d ledger keys"
              % (st["rows"], sorted(st["by_site"]), len(led)))

    # -- phase 3: seeded regression gates -------------------------------------
    import bench_compare

    base = ledger + ".base"
    with open(ledger) as f, open(base, "w") as out:
        for line in f:
            row = json.loads(line)
            row["flops"] = row["flops"] // 2  # the seeded regression:
            out.write(json.dumps(row) + "\n")  # current = 2x baseline flops
    rc_same = bench_compare.main([ledger, ledger, "--gate-cost"])
    if rc_same != 0:
        fail("identical ledgers gated nonzero (%d)" % rc_same)
    rc_gate = bench_compare.main([base, ledger, "--gate-cost"])
    if rc_gate == 0:
        fail("halved-flops baseline not caught by --gate-cost")
    rc_ungated = bench_compare.main([base, ledger])
    if rc_ungated != 0:
        fail("ungated ledger diff must only display (got rc %d)"
             % rc_ungated)
    if ok:
        print("phase 3 ok: --gate-cost trips on the seeded regression "
              "(rc %d) and passes identical ledgers" % rc_gate)

    print("check_costplane: %s" % ("OK" if ok else "FAILED"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
