"""Precision-flow analyzer tests (ISSUE 11): sensitivity registry, interval
analysis, dtype-flow diagnostics, cast-plan verdicts + fingerprints, the
serving/telemetry surfaces, and the two new mxlint rules."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import analysis
from mxnet_tpu.analysis import source_lint
from mxnet_tpu.analysis.diagnostics import INFO, WARNING
from mxnet_tpu.analysis.numerics import (BF16_SAFE, FP32_ACCUM, FP32_ONLY,
                                         CastPlan, contract_fingerprint)
from mxnet_tpu.graph_passes.ir import (CANCELLATION, EXP_RANGE, NEUTRAL,
                                       REDUCE, op_sensitivity)
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import BucketLadder, Engine
from mxnet_tpu.telemetry import instrument as tin
from mxnet_tpu.test_utils import deploy_twin_checkpoint, tiny_mlp_checkpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tel_disabled(monkeypatch):
    monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
    tin._reset_for_tests()
    yield
    tin._reset_for_tests()


def _bind(sym, **arrays):
    return sym.bind(None, {k: nd.array(v) for k, v in arrays.items()})


def _bf16(a):
    import jax.numpy as jnp

    return np.asarray(a).astype(jnp.bfloat16)


def _codes(diags):
    return [d.code for d in diags]


# -- sensitivity registry -----------------------------------------------------
class TestSensitivityRegistry:
    def _node(self, opname, attrs=None):
        from mxnet_tpu.graph_passes.ir import PlanNode, SynthOp

        return PlanNode(SynthOp(opname, lambda *a, **k: a[0]),
                        attrs or {}, "n")

    def test_core_classes(self):
        assert op_sensitivity(self._node("sum")) == REDUCE
        assert op_sensitivity(self._node("Convolution")) == REDUCE
        assert op_sensitivity(self._node("FullyConnected")) == REDUCE
        assert op_sensitivity(self._node("softmax")) == EXP_RANGE
        assert op_sensitivity(self._node("exp")) == EXP_RANGE
        assert op_sensitivity(self._node("BatchNorm")) == CANCELLATION
        assert op_sensitivity(self._node("moments")) == CANCELLATION
        assert op_sensitivity(self._node("relu")) == NEUTRAL
        assert op_sensitivity(self._node("no_such_op")) == NEUTRAL

    def test_attr_dependent_pooling_and_activation(self):
        assert op_sensitivity(
            self._node("Pooling", {"pool_type": "avg"})) == REDUCE
        assert op_sensitivity(
            self._node("Pooling", {"pool_type": "max"})) == NEUTRAL
        # default pool_type (max) via the op's defaults
        assert op_sensitivity(self._node("Pooling")) == NEUTRAL
        assert op_sensitivity(
            self._node("Activation", {"act_type": "softrelu"})) == EXP_RANGE
        assert op_sensitivity(
            self._node("Activation", {"act_type": "relu"})) == NEUTRAL


# -- dtype-flow diagnostics ---------------------------------------------------
class TestNumericsDiagnostics:
    def test_bf16_reduction_trips_low_precision_accum(self):
        x = mx.sym.var("data")
        exe = _bind(mx.sym.sum(x), data=_bf16(np.ones((8, 8))))
        diags = [d for d in exe.check() if d.code == "low-precision-accum"]
        assert len(diags) == 1 and diags[0].severity == WARNING
        assert "sum" in diags[0].message

    def test_fp32_reduction_is_clean(self):
        x = mx.sym.var("data")
        exe = _bind(mx.sym.sum(x), data=np.ones((8, 8), np.float32))
        assert exe.check() == []

    def test_mxu_contraction_bf16_not_diagnosed_but_fp32_accum(self):
        """dot/conv/FC accumulate fp32 in MXU hardware: a bf16 input is no
        diagnostic — the verdict still demands fp32 accumulation."""
        sym, params, shapes = deploy_twin_checkpoint(batch=2, image=16)
        pred = Predictor(sym, params, shapes, dtype="bfloat16")
        codes = _codes(pred.check())
        assert "low-precision-accum" in codes  # avg-pool / L2Norm DO warn
        plan = pred.precision_plan()
        conv = [r for r in plan.rows if r["op"] == "Convolution"]
        assert conv and all(r["verdict"] == FP32_ACCUM for r in conv)

    def test_mixed_dtype_binop_flagged(self):
        a, b = mx.sym.var("a"), mx.sym.var("b")
        exe = _bind(mx.sym.broadcast_add(a, b),
                    a=_bf16(np.ones((2, 2))),
                    b=np.ones((2, 2), np.float32))
        diags = [d for d in exe.check() if d.code == "mixed-dtype-binop"]
        assert len(diags) == 1
        assert "bfloat16" in diags[0].message
        assert "float32" in diags[0].message

    def test_softmax_unbounded_bf16_flagged_and_fp32_only(self):
        x = mx.sym.var("data")
        exe = _bind(mx.sym.softmax(x), data=_bf16(np.ones((2, 8))))
        assert "exp-unbounded-lowp" in _codes(exe.check())
        assert exe.precision_plan().rows[0]["verdict"] == FP32_ONLY

    def test_softmax_bounded_by_sigmoid_is_safe(self):
        """Interval analysis seeds sigmoid's [0, 1] output range, so the
        downstream softmax needs no fp32 protection."""
        x = mx.sym.var("data")
        exe = _bind(mx.sym.softmax(mx.sym.sigmoid(x)),
                    data=_bf16(np.ones((2, 8))))
        assert exe.check() == []
        rows = {r["op"]: r["verdict"] for r in exe.precision_plan().rows}
        assert rows["softmax"] == BF16_SAFE

    def test_lp_and_sum_pooling_escape_the_input_hull(self):
        """lp/sum pooling output exceeds the input interval (window sums),
        so a downstream exp must NOT inherit a bounded range from them."""
        x = mx.sym.var("data")
        for pt in ("lp", "sum"):
            sym = mx.sym.exp(mx.sym.Pooling(
                mx.sym.sigmoid(x), kernel=(2, 2), pool_type=pt, p_value=1))
            exe = _bind(sym, data=_bf16(np.ones((1, 1, 4, 4))))
            rows = {r["op"]: r["verdict"]
                    for r in exe.precision_plan().rows}
            assert rows["exp"] == FP32_ONLY, pt
        # max pooling preserves the hull: same graph is safe
        sym = mx.sym.exp(mx.sym.Pooling(
            mx.sym.sigmoid(x), kernel=(2, 2), pool_type="max"))
        exe = _bind(sym, data=_bf16(np.ones((1, 1, 4, 4))))
        rows = {r["op"]: r["verdict"] for r in exe.precision_plan().rows}
        assert rows["exp"] == BF16_SAFE

    def test_joint_power_never_bf16_safe(self):
        """x**y blows up from the JOINT base/exponent ranges (base near 0,
        negative exponent) — per-input bands prove nothing."""
        a, b = mx.sym.var("a"), mx.sym.var("b")
        sym = mx.sym.broadcast_power(mx.sym.sigmoid(a),
                                     mx.sym.clip(b, a_min=-8.0, a_max=8.0))
        exe = _bind(sym, a=_bf16(np.ones((2, 2))), b=_bf16(np.ones((2, 2))))
        rows = {r["op"]: r["verdict"] for r in exe.precision_plan().rows}
        assert rows["broadcast_power"] == FP32_ONLY

    def test_f64_input_cast_away_is_not_creep(self):
        """An f64 input immediately consumed by an explicit downcast never
        taints anything — no zero-downstream creep noise (the promotion
        itself stays shape_dtype's f64-promotion territory)."""
        code = (
            "import numpy as np, jax\n"
            "import jax.numpy as jnp\n"
            "from mxnet_tpu import analysis\n"
            "from mxnet_tpu.graph_passes import Graph\n"
            "from mxnet_tpu.graph_passes.ir import PlanNode, SynthOp\n"
            "cast = PlanNode(SynthOp('cast',\n"
            "    lambda x: x.astype(jnp.float32)), {}, 'c')\n"  # mxlint: ignore[implicit-downcast]
            "g = Graph([(cast, ('a',))], ['c_output'])\n"
            "ctx = analysis.GraphContext(g, arg_names=['a'], aux_names=[],\n"
            "    arg_avals={'a': jax.ShapeDtypeStruct((3,), np.float64)},\n"
            "    aux_avals={})\n"
            "creep = [d for d in analysis.analyze(ctx)\n"
            "         if d.code == 'f64-creep']\n"
            "assert creep == [], creep\n"
            "print('NO_CREEP_OK')\n")
        env = dict(os.environ, JAX_ENABLE_X64="1", JAX_PLATFORMS="cpu")
        p = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                           capture_output=True, text=True, timeout=120)
        assert p.returncode == 0, p.stdout + p.stderr
        assert "NO_CREEP_OK" in p.stdout

    def test_clip_bounds_feed_the_interval_analysis(self):
        x = mx.sym.var("data")
        clipped = mx.sym.clip(x, a_min=-5.0, a_max=5.0)
        exe = _bind(mx.sym.exp(clipped), data=_bf16(np.ones((4,))))
        assert exe.check() == []
        rows = {r["op"]: r["verdict"] for r in exe.precision_plan().rows}
        assert rows["exp"] == BF16_SAFE
        # without the clip the same exp is fp32_only
        exe2 = _bind(mx.sym.exp(x), data=_bf16(np.ones((4,))))
        rows2 = {r["op"]: r["verdict"] for r in exe2.precision_plan().rows}
        assert rows2["exp"] == FP32_ONLY

    def test_f64_creep_names_origin_in_x64_subprocess(self):
        """float64 only exists under JAX_ENABLE_X64, so the creep test runs
        in a subprocess with the flag on; the diagnostic must name the
        originating input and the downstream reach."""
        code = (
            "import numpy as np, jax\n"
            "import jax.numpy as jnp\n"
            "from mxnet_tpu import analysis\n"
            "from mxnet_tpu.graph_passes import Graph\n"
            "from mxnet_tpu.graph_passes.ir import PlanNode, SynthOp\n"
            "sq = PlanNode(SynthOp('sqrt', jnp.sqrt), {}, 's')\n"
            "ex = PlanNode(SynthOp('exp', jnp.exp), {}, 'e')\n"
            "g = Graph([(sq, ('a',)), (ex, ('s_output',))], ['e_output'])\n"
            "ctx = analysis.GraphContext(g, arg_names=['a'], aux_names=[],\n"
            "    arg_avals={'a': jax.ShapeDtypeStruct((3,), np.float64)},\n"
            "    aux_avals={})\n"
            "diags = [d for d in analysis.analyze(ctx)\n"
            "         if d.code == 'f64-creep']\n"
            "assert len(diags) == 1, diags\n"
            "msg = diags[0].message\n"
            "assert \"input 'a'\" in msg and '2 downstream' in msg, msg\n"
            "print('F64_CREEP_OK')\n")
        env = dict(os.environ, JAX_ENABLE_X64="1", JAX_PLATFORMS="cpu")
        p = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                           capture_output=True, text=True, timeout=120)
        assert p.returncode == 0, p.stdout + p.stderr
        assert "F64_CREEP_OK" in p.stdout

    def test_silent_downcast_flagged_but_explicit_cast_exempt(self):
        import jax.numpy as jnp
        from mxnet_tpu.graph_passes import Graph
        from mxnet_tpu.graph_passes.ir import PlanNode, SynthOp
        import jax

        def narrowing(xv):
            return xv.astype(jnp.bfloat16)  # mxlint: ignore[implicit-downcast] (the seeded hazard under test)

        shady = PlanNode(SynthOp("my_fused_op", narrowing), {}, "n")
        g = Graph([(shady, ("a",))], ["n_output"])
        ctx = analysis.GraphContext(
            g, arg_names=["a"], aux_names=[],
            arg_avals={"a": jax.ShapeDtypeStruct((3,), np.float32)},
            aux_avals={})
        assert "silent-downcast" in _codes(analysis.analyze(ctx))
        # the SAME narrowing through the explicit cast op is exempt: the
        # graph says what it does
        x = mx.sym.var("data")
        exe = _bind(mx.sym.cast(x, dtype="float16"),
                    data=np.ones((2, 2), np.float32))
        assert [d for d in exe.check()
                if d.code == "silent-downcast"] == []


# -- the cast plan ------------------------------------------------------------
class TestCastPlan:
    def test_deploy_twin_acceptance_shape(self):
        """The ISSUE 11 acceptance criterion, verbatim: majority bf16_safe,
        every reduction/BN-stat fp32_accum, every unbounded exp/log
        fp32_only."""
        sym, params, shapes = deploy_twin_checkpoint(batch=4, image=16)
        plan = Predictor(sym, params, shapes).precision_plan()
        counts = plan.counts()
        assert counts[BF16_SAFE] * 2 > len(plan.rows)
        for r in plan.rows:
            if r["sensitivity"] in (REDUCE, CANCELLATION):
                assert r["verdict"] == FP32_ACCUM, r
            if r["sensitivity"] == EXP_RANGE:
                assert r["verdict"] == FP32_ONLY, r  # fed raw FC logits

    def test_fingerprint_stable_and_plan_sensitive(self):
        sym, params, shapes = deploy_twin_checkpoint(batch=4, image=16)
        fp1 = Predictor(sym, params, shapes).precision_plan().fingerprint()
        fp2 = Predictor(sym, params, shapes).precision_plan().fingerprint()
        assert fp1 == fp2
        sym2, params2 = tiny_mlp_checkpoint()
        fp3 = Predictor(sym2, params2,
                        {"data": (2, 8)}).precision_plan().fingerprint()
        assert fp3 != fp1

    def test_fingerprint_moves_with_registry_version(self):
        rows = [{"node": "n", "op": "sum", "sensitivity": REDUCE,
                 "verdict": FP32_ACCUM, "dtype": "float32"}]
        a = CastPlan("eval", rows).fingerprint()
        b = CastPlan("eval", rows, versions=(999, 1)).fingerprint()
        c = CastPlan("eval", rows, versions=(999, 1)).fingerprint()
        assert a != b
        assert b == c  # same versions + rows -> same identity

    def test_executor_train_vs_eval_plans(self):
        x = mx.sym.var("data")
        sym = mx.sym.Dropout(mx.sym.sum(x), p=0.5)
        exe = _bind(sym, data=np.ones((4, 4), np.float32))
        ev = exe.precision_plan(is_train=False)
        tr = exe.precision_plan(is_train=True)
        assert ev.mode == "eval" and tr.mode == "train"
        assert {r["op"] for r in tr.rows} >= {"sum", "Dropout"}

    def test_to_dict_round_trips_counts(self):
        sym, params = tiny_mlp_checkpoint()
        plan = Predictor(sym, params, {"data": (2, 8)}).precision_plan()
        d = plan.to_dict()
        assert d["counts"] == plan.counts()
        assert d["fingerprint"] == plan.fingerprint()
        assert len(d["rows"]) == len(plan.rows)

    def test_unbound_executor_raises(self):
        x = mx.sym.var("data")
        exe = mx.sym.exp(x).bind(None, {})
        with pytest.raises(ValueError, match="bound shapes"):
            exe.precision_plan()

    def test_contract_fingerprint_in_aot_env(self):
        from mxnet_tpu import compile_cache

        fp = compile_cache._env_fingerprint()
        assert fp["numerics"] == contract_fingerprint()
        assert "sensitivity:" in fp["numerics"]


# -- analyzer-skipped + degradation (ISSUE 11 satellites) ---------------------
class TestManagerContracts:
    def test_missing_avals_reports_skip_not_silence(self):
        from mxnet_tpu.graph_passes import Graph
        from mxnet_tpu.graph_passes.ir import PlanNode, SynthOp

        node = PlanNode(SynthOp("exp", lambda x: x), {}, "n0")
        g = Graph([(node, ("a",))], ["n0_output"])
        ctx = analysis.GraphContext(g, arg_names=["a"], aux_names=[])
        diags = analysis.analyze(ctx)
        skipped = [d for d in diags if d.code == "analyzer-skipped"]
        assert sorted(d.analyzer for d in skipped) == ["numerics",
                                                       "shape_dtype"]
        assert all(d.severity == INFO for d in skipped)

    def test_raising_analyzer_degrades_and_rest_still_run(self, monkeypatch):
        """Satellite: one INFO for the failed analyzer, every later
        analyzer still contributes findings (a seeded bf16 reduction proves
        numerics ran after the crash)."""
        def boom(ctx):
            raise RuntimeError("kaboom")
        monkeypatch.setattr(analysis, "_ANALYZERS",
                            [("boom", 1, boom)] + analysis._ANALYZERS)
        x = mx.sym.var("data")
        exe = _bind(mx.sym.sum(x), data=_bf16(np.ones((4, 4))))
        diags = exe.check()
        failed = [d for d in diags if d.code == "analyzer-failed"]
        assert len(failed) == 1 and failed[0].severity == INFO
        assert "kaboom" in failed[0].message
        # the analyzers AFTER the crash still ran
        assert "low-precision-accum" in _codes(diags)

    def test_raising_analyzer_degrades_in_warmup_path(self, monkeypatch,
                                                      tel_disabled):
        """Satellite: the MXNET_GRAPH_ANALYZERS=1 warmup surface counts the
        degraded INFO instead of crashing the warmup pass."""
        monkeypatch.setenv("MXNET_GRAPH_ANALYZERS", "1")

        def boom(ctx):
            raise RuntimeError("kaboom")
        monkeypatch.setattr(analysis, "_ANALYZERS",
                            [("boom", 1, boom)] + analysis._ANALYZERS)
        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)},
                    ladder=BucketLadder((1,)), start=False) as eng:
            report = eng.warmup()
            assert all(r["check_warnings"] == 1 for r in report)  # the INFO
            assert eng.stats()["warmup"]["check_warnings"] == len(report)


# -- serving + telemetry surfaces --------------------------------------------
class TestSurfaces:
    def test_warmup_rows_carry_verdict_histogram(self, monkeypatch,
                                                 tel_disabled):
        monkeypatch.setenv("MXNET_GRAPH_ANALYZERS", "1")
        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)},
                    ladder=BucketLadder((1, 2)), start=False) as eng:
            report = eng.warmup()
            for r in report:
                v = r["precision_verdicts"]
                assert set(v) == {BF16_SAFE, FP32_ACCUM, FP32_ONLY}
                assert v[FP32_ACCUM] == 2  # fc1, fc2
            agg = eng.stats()["warmup"]["precision_verdicts"]
            assert agg[FP32_ACCUM] == 2 * len(report)

    def test_warmup_rows_verdicts_none_when_gate_off(self, monkeypatch,
                                                     tel_disabled):
        monkeypatch.delenv("MXNET_GRAPH_ANALYZERS", raising=False)
        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)},
                    ladder=BucketLadder((1,)), start=False) as eng:
            report = eng.warmup()
            assert all(r["precision_verdicts"] is None for r in report)
            assert eng.stats()["warmup"]["precision_verdicts"] is None

    def test_shared_context_walks_the_plan_once(self, monkeypatch):
        """analyze() + precision_plan() on one GraphContext share one
        abstract walk via the _flow memo (the warmup path's cost model)."""
        from mxnet_tpu.analysis import graph_analyzers, numerics

        calls = {"n": 0}
        real = graph_analyzers._abstract_walk

        def counting(graph, ctx, record=None):
            if record is not None:
                calls["n"] += 1
            return real(graph, ctx, record)

        monkeypatch.setattr(numerics, "_abstract_walk", counting,
                            raising=False)
        # numerics imports the walk inside _flow, so patch at the source
        monkeypatch.setattr(graph_analyzers, "_abstract_walk", counting)
        sym, params = tiny_mlp_checkpoint()
        pred = Predictor(sym, params, {"data": (2, 8)})
        ctx = analysis.executor_context(pred._exec, is_train=False)
        analysis.analyze(ctx)
        after_check = calls["n"]
        numerics.precision_plan(ctx)
        # shape_dtype walks once, numerics walks once; the plan read adds 0
        assert calls["n"] == after_check == 2

    def test_analysis_findings_counter_and_summary(self, monkeypatch,
                                                   tmp_path):
        monkeypatch.setenv("MXNET_TELEMETRY", "1")
        monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
        tin._reset_for_tests()
        try:
            x = mx.sym.var("data")
            exe = _bind(mx.sym.sum(x), data=_bf16(np.ones((4, 4))))
            exe.check()
            c = tin.registry().get("analysis_findings_total")
            assert c is not None
            assert c.value(analyzer="numerics", severity="warning") == 1
            assert tin.summary()["analysis_findings"] == 1
        finally:
            tin._reset_for_tests()

    def test_no_counter_and_null_summary_key_without_findings(
            self, monkeypatch, tmp_path):
        monkeypatch.setenv("MXNET_TELEMETRY", "1")
        monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
        tin._reset_for_tests()
        try:
            assert tin.summary()["analysis_findings"] is None
        finally:
            tin._reset_for_tests()


# -- the two new mxlint rules -------------------------------------------------
class TestNumericsLintRules:
    def _codes(self, src):
        return [f.code for f in source_lint.lint_source(src)]

    def test_inexact_literal_on_traced_param_flagged(self):
        src = ("import jax\n\n@jax.jit\ndef f(x):\n"
               "    return x + 1e-5\n")
        assert self._codes(src) == ["mixed-dtype-literal"]

    def test_bf16_exact_literals_are_exempt(self):
        src = ("import jax\n\n@jax.jit\ndef f(x):\n"
               "    return x * 0.5 + 2.0 - 127.0\n")
        assert self._codes(src) == []

    def test_literal_against_untraced_value_exempt(self):
        src = ("import jax\n\n@jax.jit\ndef f(x, *, eps=1e-5):\n"
               "    scale = 3.0 * 1.1\n"   # no traced param involved
               "    return x * scale\n")
        assert self._codes(src) == []

    def test_negative_literal_unwrapped(self):
        src = ("import jax\n\n@jax.jit\ndef f(x):\n"
               "    return x - -1e-5\n")
        assert self._codes(src) == ["mixed-dtype-literal"]

    def test_astype_narrow_in_traced_flagged(self):
        src = ("import jax\nimport jax.numpy as jnp\n\n"
               "@jax.jit\ndef f(x):\n"
               "    return x.astype(jnp.bfloat16)\n")
        assert self._codes(src) == ["implicit-downcast"]

    def test_astype_string_and_view_forms(self):
        src = ("import jax\n\n@jax.jit\ndef f(x):\n"
               "    a = x.astype('float16')\n"
               "    b = x.view('int8')\n"
               "    return a, b\n")
        assert self._codes(src) == ["implicit-downcast"] * 2

    def test_widening_astype_and_host_code_exempt(self):
        src = ("import jax\nimport jax.numpy as jnp\nimport numpy as np\n\n"
               "@jax.jit\ndef f(x):\n"
               "    return x.astype(jnp.float32)\n\n"
               "def host(img):\n"
               "    return img.astype(np.uint8)\n")
        assert self._codes(src) == []

    def test_ignore_comment_suppresses_downcast(self):
        src = ("import jax\nimport jax.numpy as jnp\n\n"
               "@jax.jit\ndef f(x):\n"
               "    return x.astype(jnp.int8)"
               "  # mxlint: ignore[implicit-downcast]\n")
        assert self._codes(src) == []

    def test_repo_is_clean_with_new_rules(self):
        findings = source_lint.lint_paths(
            [os.path.join(REPO, "mxnet_tpu")], root=REPO)
        baseline = source_lint.load_baseline(
            os.path.join(REPO, "ci", "mxlint_baseline.txt"))
        new = [f for f in findings
               if f.code in ("mixed-dtype-literal", "implicit-downcast")
               and f.fingerprint not in baseline]
        assert not new, "\n".join(str(f) for f in new)
