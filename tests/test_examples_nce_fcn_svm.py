"""nce-loss / fcn-xs / svm_mnist example families (VERDICT round-1 missing
item 8: the reference example families that exercise otherwise-untested
framework surface — sampled softmax, bilinear Deconvolution+Crop FCN heads,
SVMOutput's injected hinge gradient)."""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

EX = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "examples"))
for sub in ("nce-loss", "fcn-xs", "svm_mnist"):
    p = os.path.join(EX, sub)
    if p not in sys.path:
        sys.path.insert(0, p)


def test_svm_output_hinge_backward_matches_numpy():
    """The injected L1/L2 hinge gradients vs a numpy oracle (reference
    svm_output-inl.h backward)."""
    from mxnet_tpu import autograd

    rng = np.random.RandomState(0)
    s = rng.randn(5, 4).astype(np.float32)
    y = np.array([0, 2, 1, 3, 2], np.float32)
    for use_linear in (False, True):
        x = nd.array(s)
        x.attach_grad()
        with autograd.record():
            out = nd.SVMOutput(x, nd.array(y), margin=1.0,
                               regularization_coefficient=0.7,
                               use_linear=use_linear)
        out.backward()
        g = x.grad.asnumpy()
        # numpy oracle: one-vs-rest hinge, reference svm_output.cc L1_SVM/L2_SVM
        margin, c = 1.0, 0.7
        exp = np.zeros_like(s)
        for i in range(5):
            yi = int(y[i])
            for j in range(4):
                if j == yi:
                    if use_linear:
                        exp[i, j] = -c * float(margin > s[i, j])
                    else:
                        exp[i, j] = -c * 2.0 * (margin - s[i, j]) if margin > s[i, j] else 0.0
                else:
                    if use_linear:
                        exp[i, j] = c * float(margin > -s[i, j])
                    else:
                        exp[i, j] = c * 2.0 * (margin + s[i, j]) if margin > -s[i, j] else 0.0
        np.testing.assert_allclose(g, exp, rtol=1e-5, atol=1e-6)
    # advisor round-2 regression case: s=[2,0,0], y=0, margin=1 must give
    # [0, +c, +c] under L1 (the old Crammer-Singer form gave all-zeros)
    x = nd.array(np.array([[2.0, 0.0, 0.0]], np.float32))
    x.attach_grad()
    with autograd.record():
        o = nd.SVMOutput(x, nd.array(np.array([0.0], np.float32)),
                         margin=1.0, regularization_coefficient=1.0,
                         use_linear=True)
    o.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [[0.0, 1.0, 1.0]], atol=1e-6)
    # forward is identity on the scores
    np.testing.assert_allclose(out.asnumpy(), s, rtol=1e-6)


def test_nce_example_learns():
    import train_nce

    losses, acc = train_nce.main(vocab=120, dim=16, k=4, steps=250, batch=64,
                                 lr=10.0)
    assert np.mean(losses[-20:]) < 0.75 * np.mean(losses[:10]), (
        losses[:3], losses[-3:])
    assert acc > 2.0 / 120  # above the 1/120 chance rate (short run)


def test_fcn_example_learns_all_classes():
    import fcn_xs

    acc, miou = fcn_xs.main(steps=300, batch=8, hw=32, lr=0.5)
    # correct up-sampling geometry segments all classes well (the loose
    # 0.30 bar once masked a 2x misalignment bug — keep this tight)
    assert acc > 0.95 and miou > 0.7, (acc, miou)


def test_svm_example_real_digits():
    import svm_mnist

    acc = svm_mnist.main(epochs=8, lr=0.02)
    assert acc > 0.9, acc


def test_autoencoder_example_layerwise_plus_finetune():
    aedir = os.path.join(EX, "autoencoder")
    if aedir not in sys.path:
        sys.path.insert(0, aedir)
    import train_ae

    rec, probe = train_ae.main(pre_epochs=4, fine_epochs=6)
    assert rec < 0.05, rec            # reconstructs real digits
    assert probe > 0.5, probe         # 16-d code keeps class structure
