"""KVStore + parallel tests.

Mirrors reference ``tests/python/unittest/test_kvstore.py`` semantics (init /
push aggregation / pull / updater / compression) and adds mesh/collective and
ring-attention checks on the virtual 8-device CPU mesh (conftest.py), the
local stand-in for the reference's N-process fake cluster (SURVEY §4.1).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kv_mod
from mxnet_tpu import parallel

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def init_kv(kv_type="local"):
    kv = kv_mod.create(kv_type)
    kv.init(3, mx.nd.zeros(SHAPE))
    kv.init(KEYS, [mx.nd.zeros(SHAPE)] * len(KEYS))
    return kv


def check_diff_to_scalar(A, x):
    assert np.sum(np.abs(A.asnumpy() - x)) == 0, (A.asnumpy(), x)


class TestKVStore:
    def test_single_kv_pair(self):
        kv = init_kv()
        kv.push(3, mx.nd.ones(SHAPE) * 4)
        out = mx.nd.empty(SHAPE)
        kv.pull(3, out=out)
        check_diff_to_scalar(out, 4)

    def test_list_kv_pair(self):
        kv = init_kv()
        kv.push(KEYS, [mx.nd.ones(SHAPE) * (k + 1) for k in range(len(KEYS))])
        out = [mx.nd.empty(SHAPE) for _ in KEYS]
        kv.pull(KEYS, out=out)
        for k, o in enumerate(out):
            check_diff_to_scalar(o, k + 1)

    def test_aggregator(self):
        """Per-device value lists are summed (reference test_kvstore.py
        test_aggregator, 4 'devices')."""
        kv = init_kv()
        num_devs = 4
        vals = [mx.nd.ones(SHAPE)] * num_devs
        kv.push(3, vals)
        outs = [mx.nd.empty(SHAPE) for _ in range(num_devs)]
        kv.pull(3, out=outs)
        for o in outs:
            check_diff_to_scalar(o, num_devs)

    def test_updater(self):
        kv = init_kv()

        def updater(key, recv, stored):
            stored += recv * 2

        kv.set_updater(updater)
        kv.push(3, mx.nd.ones(SHAPE))
        out = mx.nd.empty(SHAPE)
        kv.pull(3, out=out)
        check_diff_to_scalar(out, 2)
        kv.push(3, [mx.nd.ones(SHAPE)] * 4)
        kv.pull(3, out=out)
        check_diff_to_scalar(out, 2 + 8)

    def test_optimizer_in_store(self):
        kv = init_kv()
        kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1))
        kv.push(3, mx.nd.ones(SHAPE))
        out = mx.nd.empty(SHAPE)
        kv.pull(3, out=out)
        # w = 0 - 0.1 * grad(=1) = -0.1 (wd=0 default)
        np.testing.assert_allclose(out.asnumpy(), -0.1 * np.ones(SHAPE), rtol=1e-6)

    def test_gradient_compression(self):
        """2-bit quantization with error feedback
        (reference tests/nightly/dist_sync_kvstore.py:232)."""
        kv = init_kv()
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.push(3, mx.nd.ones(SHAPE) * 0.3)  # below threshold → 0, residual 0.3
        out = mx.nd.empty(SHAPE)
        kv.pull(3, out=out)
        check_diff_to_scalar(out, 0)
        kv.push(3, mx.nd.ones(SHAPE) * 0.3)  # residual 0.3+0.3 ≥ 0.5 → +0.5
        kv.pull(3, out=out)
        check_diff_to_scalar(out, 0.5)

    def test_row_sparse_pull(self):
        kv = kv_mod.create("local")
        w = np.random.rand(6, 3).astype(np.float32)
        kv.init("w", mx.nd.array(w))
        rid = mx.nd.array([0, 3], dtype="int32")
        out = mx.nd.empty((2, 3))
        kv.row_sparse_pull("w", out=out, row_ids=rid)
        np.testing.assert_allclose(out.asnumpy(), w[[0, 3]])

    def test_uninit_push_raises(self):
        kv = kv_mod.create("local")
        with pytest.raises(KeyError):
            kv.push(99, mx.nd.ones(SHAPE))

    def test_factory_rejects_unknown(self):
        with pytest.raises(ValueError):
            kv_mod.create("bogus")

    def test_save_load_optimizer_states(self, tmp_path):
        kv = init_kv()
        kv.set_optimizer(mx.optimizer.create("adam", learning_rate=0.01))
        kv.push(3, mx.nd.ones(SHAPE))
        f = str(tmp_path / "opt.states")
        kv.save_optimizer_states(f)
        kv2 = init_kv()
        kv2.set_optimizer(mx.optimizer.create("adam", learning_rate=0.01))
        kv2.load_optimizer_states(f)
        assert set(kv2._updater.states.keys()) == set(kv._updater.states.keys())


class TestMesh:
    def test_make_mesh_default(self):
        mesh = parallel.make_mesh()
        assert mesh.axis_names == ("dp",)
        assert mesh.devices.size == 8

    def test_make_mesh_2d(self):
        mesh = parallel.make_mesh(dp=2, tp=4)
        assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4
        # canonical ordering: dp before tp
        assert mesh.axis_names == ("dp", "tp")

    def test_make_mesh_infer(self):
        mesh = parallel.make_mesh(dp=-1, tp=2)
        assert mesh.shape["dp"] == 4

    def test_shard_and_replicate(self):
        mesh = parallel.make_mesh(dp=8)
        x = mx.nd.ones((16, 4))
        xs = parallel.shard(x, ("dp", None), mesh=mesh)
        assert xs.shape == (16, 4)
        np.testing.assert_allclose(xs.asnumpy(), np.ones((16, 4)))
        xr = parallel.replicate(x, mesh=mesh)
        assert xr.asnumpy().shape == (16, 4)

    def test_shard_params_rules(self):
        mesh = parallel.make_mesh(dp=2, tp=4)
        params = {"dense0_weight": mx.nd.ones((8, 8)), "dense0_bias": mx.nd.ones((8,))}
        out = parallel.shard_params(params, mesh=mesh, rules=[("weight", (None, "tp"))])
        assert out["dense0_weight"].shape == (8, 8)
        assert out["dense0_bias"].shape == (8,)


class TestCollectives:
    def test_allreduce_in_shard_map(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from mxnet_tpu.parallel.shard_map_compat import shard_map

        mesh = parallel.make_mesh(dp=8)

        def step(x):
            return parallel.allreduce(x, "dp")

        fn = shard_map(step, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        x = jnp.arange(8.0)
        out = fn(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))

    def test_pmean_and_reduce_scatter(self):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from mxnet_tpu.parallel.shard_map_compat import shard_map

        mesh = parallel.make_mesh(dp=8)
        x = jnp.arange(16.0).reshape(8, 2)

        fn = shard_map(lambda v: parallel.pmean(v, "dp"), mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        out = np.asarray(fn(x))
        np.testing.assert_allclose(out, np.tile(x.mean(axis=0), (8, 1)))

        fn2 = shard_map(
            lambda v: parallel.reduce_scatter(v, "dp", axis=0),
            mesh=mesh,
            in_specs=P(None),
            out_specs=P("dp"),
        )
        y = jnp.ones((8, 8))
        out2 = np.asarray(fn2(y))
        np.testing.assert_allclose(out2, 8 * np.ones((8, 8)))


class TestRingAttention:
    def _reference_attention(self, q, k, v, causal=False):
        scale = 1.0 / np.sqrt(q.shape[-1])
        s = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
        if causal:
            S = q.shape[2]
            mask = np.tril(np.ones((S, S), bool))
            s = np.where(mask[None, None], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_matches_dense(self, causal):
        mesh = parallel.make_mesh(sp=8)
        B, H, S, D = 2, 2, 32, 8
        rng = np.random.RandomState(0)
        q = rng.randn(B, H, S, D).astype(np.float32)
        k = rng.randn(B, H, S, D).astype(np.float32)
        v = rng.randn(B, H, S, D).astype(np.float32)
        out = parallel.ring_self_attention(q, k, v, mesh=mesh, causal=causal)
        expect = self._reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), expect, rtol=2e-4, atol=2e-5)


class TestReviewRegressions:
    """Regressions for code-review findings (layout, prefetch, symbolblock)."""

    def test_nhwc_conv_matches_nchw(self):
        from mxnet_tpu import gluon

        np.random.seed(0)
        x = np.random.randn(2, 8, 8, 3).astype(np.float32)  # NHWC
        c_last = gluon.nn.Conv2D(4, 3, layout="NHWC", in_channels=3)
        c_last.initialize()
        out = c_last(mx.nd.array(x))
        assert out.shape == (2, 6, 6, 4)
        # same weights, channel-first path
        w = c_last.weight.data().asnumpy()  # (O, Kh, Kw, I)
        b = c_last.bias.data().asnumpy()
        c_first = gluon.nn.Conv2D(4, 3, layout="NCHW", in_channels=3)
        c_first.initialize()
        c_first.weight.set_data(mx.nd.array(np.transpose(w, (0, 3, 1, 2))))
        c_first.bias.set_data(mx.nd.array(b))
        out2 = c_first(mx.nd.array(np.transpose(x, (0, 3, 1, 2))))
        np.testing.assert_allclose(
            out.asnumpy(), np.transpose(out2.asnumpy(), (0, 2, 3, 1)), rtol=1e-4, atol=1e-5
        )

    def test_nhwc_pooling(self):
        from mxnet_tpu import gluon

        x = np.arange(2 * 4 * 4 * 3, dtype=np.float32).reshape(2, 4, 4, 3)
        p = gluon.nn.MaxPool2D((2, 2), layout="NHWC")
        out = p(mx.nd.array(x)).asnumpy()
        ref = x.reshape(2, 2, 2, 2, 2, 3).max(axis=(2, 4))
        np.testing.assert_allclose(out, ref)

    def test_bad_layout_rejected(self):
        from mxnet_tpu import gluon

        with pytest.raises(ValueError):
            gluon.nn.Conv2D(4, 3, layout="NCWH")

    def test_dataloader_prefetch_zero(self):
        from mxnet_tpu import gluon

        ds = gluon.data.ArrayDataset(np.arange(10, dtype=np.float32))
        loader = gluon.data.DataLoader(ds, batch_size=2, num_workers=2, prefetch=0)
        seen = [b.asnumpy() for b in loader]
        assert len(seen) == 5

    def test_symbolblock_param_names_unprefixed(self, tmp_path):
        from mxnet_tpu import gluon
        import mxnet_tpu.symbol as sym

        data = sym.var("data")
        out = sym.FullyConnected(data, name="fc", num_hidden=3)
        blk = gluon.SymbolBlock(out, [data])
        names = set(blk.collect_params().keys())
        assert "fc_weight" in names and "fc_bias" in names, names
