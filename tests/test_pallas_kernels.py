"""Pallas TPU kernel tests (interpret mode on CPU; the same kernels compile
for real TPU — verified bit-accurate vs the jnp formulation on hardware)."""
import numpy as np
import pytest
import jax.numpy as jnp

from mxnet_tpu.ops.pallas_kernels import (
    dequantize_int8_pallas, quantize_int8_pallas, supported,
)


def test_supported_predicate():
    assert supported((16, 256), np.float32)
    assert supported((8, 128), np.float32)
    assert not supported((3, 5), np.float32)  # not tile aligned
    assert not supported((16, 256), np.int32)  # wrong dtype


def test_quantize_matches_jnp_formula():
    rng = np.random.RandomState(0)
    x = (rng.randn(16, 256) * 3).astype(np.float32)
    rr = jnp.asarray(np.abs(x).max())
    q = quantize_int8_pallas(jnp.asarray(x), rr, interpret=True)
    scale = 127.0 / float(rr)
    ref = (np.sign(x) * np.minimum(np.abs(x) * scale + 0.5, 127.0)).astype(np.int8)
    np.testing.assert_array_equal(np.asarray(q), ref)


def test_dequantize_roundtrip():
    rng = np.random.RandomState(1)
    x = (rng.randn(32, 128) * 5).astype(np.float32)
    rr = jnp.asarray(np.abs(x).max())
    q = quantize_int8_pallas(jnp.asarray(x), rr, interpret=True)
    back = dequantize_int8_pallas(q, rr, interpret=True)
    assert np.abs(np.asarray(back) - x).max() < float(rr) / 127 * 1.01


def test_3d_shape_and_uneven_rows():
    rng = np.random.RandomState(2)
    x = (rng.randn(3, 8, 384) * 2).astype(np.float32)  # 9216 = 72 tiles
    assert supported(x.shape, x.dtype)
    rr = jnp.asarray(np.abs(x).max())
    q = quantize_int8_pallas(jnp.asarray(x), rr, interpret=True)
    assert q.shape == x.shape and q.dtype == jnp.int8
