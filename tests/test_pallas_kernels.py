"""Pallas TPU kernel tests (interpret mode on CPU; the same kernels compile
for real TPU — verified bit-accurate vs the jnp formulation on hardware)."""
import numpy as np
import pytest
import jax.numpy as jnp

from mxnet_tpu.ops.pallas_kernels import (
    dequantize_int8_pallas, quantize_int8_pallas, supported,
)


def test_supported_predicate():
    assert supported((16, 256), np.float32)
    assert supported((8, 128), np.float32)
    assert not supported((3, 5), np.float32)  # not tile aligned
    assert not supported((16, 256), np.int32)  # wrong dtype


def test_quantize_matches_jnp_formula():
    rng = np.random.RandomState(0)
    x = (rng.randn(16, 256) * 3).astype(np.float32)
    rr = jnp.asarray(np.abs(x).max())
    q = quantize_int8_pallas(jnp.asarray(x), rr, interpret=True)
    scale = 127.0 / float(rr)
    ref = (np.sign(x) * np.minimum(np.abs(x) * scale + 0.5, 127.0)).astype(np.int8)
    np.testing.assert_array_equal(np.asarray(q), ref)


def test_dequantize_roundtrip():
    rng = np.random.RandomState(1)
    x = (rng.randn(32, 128) * 5).astype(np.float32)
    rr = jnp.asarray(np.abs(x).max())
    q = quantize_int8_pallas(jnp.asarray(x), rr, interpret=True)
    back = dequantize_int8_pallas(q, rr, interpret=True)
    assert np.abs(np.asarray(back) - x).max() < float(rr) / 127 * 1.01


def test_3d_shape_and_uneven_rows():
    rng = np.random.RandomState(2)
    x = (rng.randn(3, 8, 384) * 2).astype(np.float32)  # 9216 = 72 tiles
    assert supported(x.shape, x.dtype)
    rr = jnp.asarray(np.abs(x).max())
    q = quantize_int8_pallas(jnp.asarray(x), rr, interpret=True)
    assert q.shape == x.shape and q.dtype == jnp.int8


# ---------------------------------------------------------------------------
# Blocked greedy NMS kernel (VERDICT r2 item 3)
# ---------------------------------------------------------------------------

def _rand_boxes(rng, *lead, n, extent=800.0):
    ctr = rng.uniform(0, extent, lead + (n, 2))
    wh = rng.uniform(8, 250, lead + (n, 2))
    return np.concatenate([ctr - wh / 2, ctr + wh / 2], -1).astype(np.float32)


def test_nms_pallas_matches_xla_blocked():
    import jax
    from mxnet_tpu.ops.detection import _nms_alive_blocked
    from mxnet_tpu.ops.pallas_kernels import nms_alive_pallas

    rng = np.random.RandomState(0)
    for n in (100, 300, 700):  # below, at, and across the 256 tile
        boxes = jnp.asarray(_rand_boxes(rng, n=n))
        valid = jnp.asarray(rng.rand(n) > 0.1)
        ref = np.asarray(_nms_alive_blocked(boxes, 0.5, valid=valid))
        got = np.asarray(nms_alive_pallas(boxes, valid, None, thresh=0.5,
                                          interpret=True))
        np.testing.assert_array_equal(ref, got)


def test_nms_pallas_per_class_ids():
    from mxnet_tpu.ops.detection import _nms_alive_blocked
    from mxnet_tpu.ops.pallas_kernels import nms_alive_pallas

    rng = np.random.RandomState(1)
    n = 400
    boxes = jnp.asarray(_rand_boxes(rng, n=n))
    valid = jnp.asarray(rng.rand(n) > 0.05)
    ids = jnp.asarray(rng.randint(0, 6, n))
    ref = np.asarray(_nms_alive_blocked(
        boxes, 0.5, valid=valid, ids=ids, force_suppress=False, plus_one=0.0))
    got = np.asarray(nms_alive_pallas(
        boxes, valid, ids, thresh=0.5, plus_one=0.0, force_suppress=False,
        interpret=True))
    np.testing.assert_array_equal(ref, got)


def test_nms_pallas_vmap_hits_batched_grid():
    import jax
    from mxnet_tpu.ops.detection import _nms_alive_blocked
    from mxnet_tpu.ops.pallas_kernels import nms_alive_pallas

    rng = np.random.RandomState(2)
    B, n = 3, 512
    boxes = jnp.asarray(_rand_boxes(rng, B, n=n))
    valid = jnp.asarray(rng.rand(B, n) > 0.1)
    got = np.asarray(jax.vmap(
        lambda b, v: nms_alive_pallas(b, v, None, thresh=0.5,
                                      interpret=True))(boxes, valid))
    ref = np.stack([np.asarray(_nms_alive_blocked(
        boxes[i], 0.5, valid=valid[i])) for i in range(B)])
    np.testing.assert_array_equal(ref, got)


def test_nms_pallas_grad_is_zero_not_error():
    """The survivor mask is piecewise-constant: grad through a consumer
    must flow through box VALUES only (same as the XLA bool-mask path)."""
    import jax
    from mxnet_tpu.ops.pallas_kernels import nms_alive_pallas

    rng = np.random.RandomState(3)
    n = 300
    boxes = jnp.asarray(_rand_boxes(rng, n=n))
    valid = jnp.ones((n,), bool)

    def loss(b):
        alive = nms_alive_pallas(b, valid, None, thresh=0.5, interpret=True)
        return jnp.where(alive[:, None], b, 0.0).sum()

    g = np.asarray(jax.grad(loss)(boxes))
    alive = np.asarray(nms_alive_pallas(boxes, valid, None, thresh=0.5,
                                        interpret=True))
    np.testing.assert_array_equal(
        g, np.broadcast_to(np.where(alive[:, None], 1.0, 0.0), g.shape))


def test_dispatch_env_override(monkeypatch):
    """MXNET_NMS_IMPL=pallas routes _nms_alive_blocked through the kernel
    on CPU (interpret); =xla keeps the jnp path; results identical."""
    from mxnet_tpu.ops import detection

    rng = np.random.RandomState(4)
    boxes = jnp.asarray(_rand_boxes(rng, n=200))
    monkeypatch.setenv("MXNET_NMS_IMPL", "xla")
    ref = np.asarray(detection._nms_alive_blocked(boxes, 0.6))
    monkeypatch.setenv("MXNET_NMS_IMPL", "pallas")
    got = np.asarray(detection._nms_alive_blocked(boxes, 0.6))
    np.testing.assert_array_equal(ref, got)


def test_dconv_vmem_guard(monkeypatch):
    """ADVICE round 5: the fused-dconv auto branch must keep known-good
    north-star shapes on the kernel but push conv4-scale feature maps
    (whose backward working set hard-fails Mosaic) to the XLA scan."""
    from mxnet_tpu.ops.pallas_kernels import (dconv_bwd_vmem_bytes,
                                              dconv_fits_vmem)

    monkeypatch.delenv("MXNET_DCONV_VMEM_MB", raising=False)
    # north-star res5: 38x64 map, cpg=512 — measured working, stays fused
    assert dconv_fits_vmem(38 * 64, 512, 2)
    assert dconv_fits_vmem(38 * 64, 512, 4)
    # conv4-scale: 76x128 map — the hard-fail case, falls back
    assert not dconv_fits_vmem(76 * 128, 512, 2)
    assert dconv_bwd_vmem_bytes(76 * 128, 512, 2) > (24 << 20)
    # env override wins in both directions
    monkeypatch.setenv("MXNET_DCONV_VMEM_MB", "1024")
    assert dconv_fits_vmem(76 * 128, 512, 2)
    monkeypatch.setenv("MXNET_DCONV_VMEM_MB", "1")
    assert not dconv_fits_vmem(38 * 64, 64, 2)
