"""CustomOp bridge tests — mirrors reference
tests/python/unittest/test_operator.py test_custom_op and the docs softmax
example (docs/faq/new_op.md)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


@mx.operator.register("sqr")
class SqrProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def create_operator(self, ctx, shapes, dtypes):
        outer = self

        class Sqr(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = in_data[0].asnumpy()
                self.assign(out_data[0], req[0], nd.array(x * x))

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                x = in_data[0].asnumpy()
                g = out_grad[0].asnumpy()
                self.assign(in_grad[0], req[0], nd.array(2 * x * g))

        return Sqr()


@mx.operator.register("np_softmax")
class NpSoftmaxProp(mx.operator.CustomOpProp):
    """The canonical reference example: softmax+CE loss as a custom op."""

    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        class NpSoftmax(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = in_data[0].asnumpy()
                y = np.exp(x - x.max(axis=1, keepdims=True))
                y /= y.sum(axis=1, keepdims=True)
                self.assign(out_data[0], req[0], nd.array(y))

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                lab = in_data[1].asnumpy().astype(np.int32)
                y = out_data[0].asnumpy().copy()
                y[np.arange(lab.shape[0]), lab] -= 1.0
                self.assign(in_grad[0], req[0], nd.array(y))
                self.assign(in_grad[1], req[1], nd.array(np.zeros_like(lab, np.float32)))

        return NpSoftmax()


@mx.operator.register("split2")
class Split2Prop(mx.operator.CustomOpProp):
    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["lo", "hi"]

    def infer_shape(self, in_shape):
        n = in_shape[0][0] // 2
        half = (n,) + tuple(in_shape[0][1:])
        return in_shape, [half, half], []

    def create_operator(self, ctx, shapes, dtypes):
        class Split2(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = in_data[0].asnumpy()
                n = x.shape[0] // 2
                self.assign(out_data[0], req[0], nd.array(x[:n]))
                self.assign(out_data[1], req[1], nd.array(x[n:]))

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                g = np.concatenate([out_grad[0].asnumpy(), out_grad[1].asnumpy()])
                self.assign(in_grad[0], req[0], nd.array(g))

        return Split2()


def test_custom_forward():
    x = nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    y = nd.Custom(x, op_type="sqr")
    np.testing.assert_allclose(y.asnumpy(), [[1, 4], [9, 16]], rtol=1e-6)


def test_custom_backward():
    from mxnet_tpu import autograd

    x = nd.array(np.array([[1.0, -2.0], [0.5, 3.0]], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="sqr")
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy(), rtol=1e-5)


def test_custom_softmax_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 5).astype(np.float32)
    lab = np.array([0, 2, 1, 4], np.float32)
    out = nd.Custom(nd.array(x), nd.array(lab), op_type="np_softmax")
    e = np.exp(x - x.max(axis=1, keepdims=True))
    want = e / e.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)


def test_custom_softmax_grad():
    from mxnet_tpu import autograd

    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(4, 5).astype(np.float32))
    lab = nd.array(np.array([0, 2, 1, 4], np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, lab, op_type="np_softmax")
        # pseudo-loss: the custom op defines its own backward (need_top_grad
        # False in reference; here the ct on y is ones, ignored by backward)
        s = y.sum()
    s.backward()
    e = np.exp(x.asnumpy() - x.asnumpy().max(axis=1, keepdims=True))
    sm = e / e.sum(axis=1, keepdims=True)
    want = sm.copy()
    want[np.arange(4), [0, 2, 1, 4]] -= 1.0
    np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5)


def test_custom_multi_output():
    x = nd.array(np.arange(8, dtype=np.float32).reshape(4, 2))
    lo, hi = nd.Custom(x, op_type="split2")
    np.testing.assert_allclose(lo.asnumpy(), x.asnumpy()[:2])
    np.testing.assert_allclose(hi.asnumpy(), x.asnumpy()[2:])


def test_custom_in_jit():
    """The callback must survive jit tracing (the CachedOp/hybridize path)."""
    import jax

    from mxnet_tpu.ops import registry

    fn = registry.get("Custom").fn
    x = np.array([[1.0, 2.0]], np.float32)

    @jax.jit
    def f(a):
        return fn(a, op_type="sqr")

    np.testing.assert_allclose(np.asarray(f(x)), [[1.0, 4.0]], rtol=1e-6)


def test_custom_symbol_graph():
    from mxnet_tpu import sym

    data = sym.Variable("data")
    out = sym.Custom(data, op_type="sqr", name="sq")
    exe = out.simple_bind(data=(2, 3))
    x = np.random.RandomState(0).rand(2, 3).astype(np.float32)
    (y,) = exe.forward(is_train=True, data=nd.array(x))
    np.testing.assert_allclose(y.asnumpy(), x * x, rtol=1e-5)
    exe.backward(nd.array(np.ones_like(x)))
    np.testing.assert_allclose(exe.grad_arrays[0].asnumpy(), 2 * x, rtol=1e-5)


def test_unregistered_op_type_raises():
    with pytest.raises(Exception):
        nd.Custom(nd.array(np.zeros((2, 2), np.float32)), op_type="nope_missing")


def test_attrs_reach_prop_as_strings():
    seen = {}

    @mx.operator.register("attr_check")
    class AttrProp(mx.operator.CustomOpProp):
        def __init__(self, alpha="1", beta="x"):
            super().__init__()
            seen["alpha"] = alpha
            seen["beta"] = beta

        def create_operator(self, ctx, shapes, dtypes):
            class Id(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0])

                def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0])

            return Id()

    x = nd.array(np.ones((2, 2), np.float32))
    nd.Custom(x, op_type="attr_check", alpha=3, beta="hello")
    assert seen["alpha"] == "3"
    assert seen["beta"] == "hello"


def test_is_train_flag_follows_context():
    """Review regression: is_train must follow autograd/executor state, not
    be baked at trace time."""
    seen = []

    @mx.operator.register("train_probe")
    class TrainProbeProp(mx.operator.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            class P(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    seen.append(bool(is_train))
                    self.assign(out_data[0], req[0], in_data[0])

                def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0])

            return P()

    from mxnet_tpu import autograd

    x = nd.array(np.ones((2, 2), np.float32))
    nd.Custom(x, op_type="train_probe")
    with autograd.record():
        nd.Custom(x, op_type="train_probe")
    assert seen[-2:] == [False, True]

    from mxnet_tpu import sym

    out = sym.Custom(sym.Variable("data"), op_type="train_probe")
    exe = out.simple_bind(data=(2, 2))
    seen.clear()
    exe.forward(is_train=True, data=x)
    assert seen and seen[-1] is True
    seen.clear()
    exe.forward(is_train=False, data=x)
    assert seen and seen[-1] is False


def test_string_attrs_verbatim():
    """Review regression: '1e3' must not be re-parsed into '1000.0'."""
    got = {}

    @mx.operator.register("verbatim")
    class VerbatimProp(mx.operator.CustomOpProp):
        def __init__(self, thresh="1e3"):
            super().__init__()
            got["thresh"] = thresh

        def create_operator(self, ctx, shapes, dtypes):
            class Id(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0])

                def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                    self.assign(in_grad[0], req[0], out_grad[0])

            return Id()

    nd.Custom(nd.array(np.ones((1,), np.float32)), op_type="verbatim", thresh="1e3")
    assert got["thresh"] == "1e3"
