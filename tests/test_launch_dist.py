"""Multi-process distributed tests — the reference's fake-cluster pattern
(tests/nightly/dist_sync_kvstore.py launched via `tools/launch.py -n N
--launcher local`, ci/docker/runtime_functions.sh:673-682): N REAL processes
coordinate through jax.distributed (Gloo on CPU) — not a virtual in-process
mesh."""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
LAUNCH = os.path.join(REPO, "tools", "launch.py")

WORKER = textwrap.dedent("""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import dist
    from mxnet_tpu import nd

    dist.init()
    r, n = dist.rank(), dist.size()

    kv = mx.kv.create("dist_sync")
    assert kv.rank == r and kv.num_workers == n

    # push/pull aggregation across processes (reference dist_sync_kvstore.py)
    kv.init("w", nd.zeros((4,)))
    kv.push("w", nd.array(np.full((4,), float(r + 1), np.float32)))
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), sum(range(1, n + 1))), out.asnumpy()

    # multi-key list API
    kv.init(["a", "b"], [nd.zeros((2,)), nd.zeros((3,))])
    kv.push(["a", "b"], [nd.array(np.ones(2, np.float32)),
                         nd.array(np.full(3, 2.0, np.float32))])
    oa, ob = nd.zeros((2,)), nd.zeros((3,))
    kv.pull(["a", "b"], out=[oa, ob])
    assert np.allclose(oa.asnumpy(), n) and np.allclose(ob.asnumpy(), 2 * n)

    # updater path: sgd on aggregated grads (rank-identical results)
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    kv2 = mx.kv.create("dist_sync")
    kv2.set_optimizer(opt)
    kv2.init("p", nd.array(np.ones(3, np.float32)))
    kv2.push("p", nd.array(np.full(3, float(r + 1), np.float32)))
    po = nd.zeros((3,))
    kv2.pull("p", out=po)
    kv.barrier()
    print("RANK%d_RESULT %s" % (r, po.asnumpy().tolist()), flush=True)
    dist.shutdown()
""")


@pytest.mark.skipif(sys.platform != "linux", reason="local fake cluster uses fork/Gloo")
def test_dist_sync_kvstore_two_processes(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    # Gloo inter-process connects can time out when the host is saturated
    # (full-suite runs on one core); one retry keeps the signal without flakes
    for attempt in range(2):
        res = subprocess.run(
            [sys.executable, LAUNCH, "-n", "2", "--launcher", "local",
             sys.executable, str(worker)],
            env=env, capture_output=True, text=True, timeout=420,
        )
        if res.returncode == 0:
            break
    assert res.returncode == 0, res.stdout + res.stderr
    lines = [l for l in res.stdout.splitlines() if "_RESULT" in l]
    assert len(lines) == 2, res.stdout + res.stderr
    # both ranks ended with identical parameters
    vals = sorted(l.split("_RESULT ")[1] for l in lines)
    assert vals[0] == vals[1], vals


def test_launcher_cli_validation():
    res = subprocess.run(
        [sys.executable, LAUNCH, "-n", "1", "--launcher", "local"],
        capture_output=True, text=True, timeout=60,
    )
    assert res.returncode != 0
    assert "no command given" in res.stderr
