"""Multi-process distributed tests — the reference's fake-cluster pattern
(tests/nightly/dist_sync_kvstore.py launched via `tools/launch.py -n N
--launcher local`, ci/docker/runtime_functions.sh:673-682): N REAL processes
coordinate through jax.distributed (Gloo on CPU) — not a virtual in-process
mesh."""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
LAUNCH = os.path.join(REPO, "tools", "launch.py")

WORKER = textwrap.dedent("""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import dist
    from mxnet_tpu import nd

    dist.init()
    r, n = dist.rank(), dist.size()

    kv = mx.kv.create("dist_sync")
    assert kv.rank == r and kv.num_workers == n

    # push/pull aggregation across processes (reference dist_sync_kvstore.py)
    kv.init("w", nd.zeros((4,)))
    kv.push("w", nd.array(np.full((4,), float(r + 1), np.float32)))
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), sum(range(1, n + 1))), out.asnumpy()

    # multi-key list API
    kv.init(["a", "b"], [nd.zeros((2,)), nd.zeros((3,))])
    kv.push(["a", "b"], [nd.array(np.ones(2, np.float32)),
                         nd.array(np.full(3, 2.0, np.float32))])
    oa, ob = nd.zeros((2,)), nd.zeros((3,))
    kv.pull(["a", "b"], out=[oa, ob])
    assert np.allclose(oa.asnumpy(), n) and np.allclose(ob.asnumpy(), 2 * n)

    # updater path: sgd on aggregated grads (rank-identical results)
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    kv2 = mx.kv.create("dist_sync")
    kv2.set_optimizer(opt)
    kv2.init("p", nd.array(np.ones(3, np.float32)))
    kv2.push("p", nd.array(np.full(3, float(r + 1), np.float32)))
    po = nd.zeros((3,))
    kv2.pull("p", out=po)
    kv.barrier()
    print("RANK%d_RESULT %s" % (r, po.asnumpy().tolist()), flush=True)
    dist.shutdown()
""")


@pytest.mark.skipif(sys.platform != "linux", reason="local fake cluster uses fork/Gloo")
def test_dist_sync_kvstore_two_processes(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    # Gloo inter-process connects can time out when the host is saturated
    # (full-suite runs on one core); retries keep the signal without flakes
    for attempt in range(3):
        res = subprocess.run(
            [sys.executable, LAUNCH, "-n", "2", "--launcher", "local",
             sys.executable, str(worker)],
            env=env, capture_output=True, text=True, timeout=420,
        )
        if res.returncode == 0:
            break
    assert res.returncode == 0, res.stdout + res.stderr
    lines = [l for l in res.stdout.splitlines() if "_RESULT" in l]
    assert len(lines) == 2, res.stdout + res.stderr
    # both ranks ended with identical parameters
    vals = sorted(l.split("_RESULT ")[1] for l in lines)
    assert vals[0] == vals[1], vals


def test_launcher_cli_validation():
    res = subprocess.run(
        [sys.executable, LAUNCH, "-n", "1", "--launcher", "local"],
        capture_output=True, text=True, timeout=60,
    )
    assert res.returncode != 0
    assert "no command given" in res.stderr


WORKER_DEADNODE = textwrap.dedent("""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import dist

    dist.init()
    r = dist.rank()
    assert dist.size() == 3
    if r == 2:
        os._exit(0)  # the dead node: vanishes right after startup
    try:
        dist.barrier("deadcheck", timeout_ms=8000)
        print("RANK%d_NOERROR" % r, flush=True)
    except dist.DeadNodeError as e:
        print("RANK%d_DEAD %s" % (r, e.missing_ranks), flush=True)
    # grace period: rank 0 hosts the coordination service — exiting the
    # instant it diagnoses would kill peers mid-diagnostic (jax's client
    # fatally terminates on service loss)
    import time
    time.sleep(4)
    # skip dist.shutdown(): the coordination service already lost a member
    os._exit(0)
""")


@pytest.mark.skipif(sys.platform != "linux", reason="local fake cluster uses fork/Gloo")
def test_dist_dead_node_fails_fast_with_named_rank(tmp_path):
    """VERDICT round-2 item 9: kill one of N processes — the survivors must
    fail fast with an error NAMING the dead rank (reference dead-node check
    at barrier setup, kvstore_dist.h:110-118), not hang."""
    worker = tmp_path / "worker_dead.py"
    worker.write_text(WORKER_DEADNODE)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    for attempt in range(3):
        res = subprocess.run(
            [sys.executable, LAUNCH, "-n", "3", "--launcher", "local",
             sys.executable, str(worker)],
            env=env, capture_output=True, text=True, timeout=300,
        )
        lines = [l for l in res.stdout.splitlines() if "_DEAD" in l]
        if len(lines) == 2:
            break
    assert len(lines) == 2, res.stdout + res.stderr
    assert all(l.endswith("[2]") for l in lines), lines
    assert not any("_NOERROR" in l for l in res.stdout.splitlines()), res.stdout


WORKER_NIGHTLY = textwrap.dedent("""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import dist
    from mxnet_tpu import nd, autograd

    dist.init()
    r, n = dist.rank(), dist.size()

    # --- scenario 1: rowsparse pulls (reference dist_sync_kvstore.py:232
    # test_sync_push_pull rsp + row_sparse_pull) -------------------------
    kv = mx.kv.create("dist_sync")
    ROWS, COLS = 10, 3
    kv.init("rsp", nd.zeros((ROWS, COLS)))
    grad = np.zeros((ROWS, COLS), np.float32)
    grad[r % ROWS] = r + 1          # each rank touches its own row
    grad[(r + 1) % ROWS] += 0.5     # and overlaps the neighbour's
    kv.push("rsp", nd.array(grad))
    expected = np.zeros((ROWS, COLS), np.float32)
    for q in range(n):
        expected[q % ROWS] += q + 1
        expected[(q + 1) % ROWS] += 0.5
    # subset pull incl. a duplicate row id (gather semantics)
    rid = nd.array(np.array([1, 1, 3], np.float32))
    out = nd.zeros((3, COLS))
    kv.row_sparse_pull("rsp", out=out, row_ids=rid)
    assert np.allclose(out.asnumpy(), expected[[1, 1, 3]]), out.asnumpy()
    # full-shape pull with permuted row ids keeps scatter semantics
    perm = np.random.RandomState(0).permutation(ROWS).astype(np.float32)
    outf = nd.zeros((ROWS, COLS))
    kv.row_sparse_pull("rsp", out=outf, row_ids=nd.array(perm))
    assert np.allclose(outf.asnumpy(), expected), outf.asnumpy()

    # --- scenario 2: 2-bit compression with error feedback
    # (reference dist_sync_kvstore.py test_sync_2bit_compression) --------
    kv2 = mx.kv.create("dist_sync")
    kv2.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv2.init("c", nd.zeros((4,)))
    # push 0.3: below threshold -> quantized 0 everywhere, residual 0.3
    kv2.push("c", nd.array(np.full(4, 0.3, np.float32)))
    o = nd.zeros((4,))
    kv2.pull("c", out=o)
    assert np.allclose(o.asnumpy(), 0.0), o.asnumpy()
    # push 0.3 again: residual 0.6 >= 0.5 -> +0.5 per worker, residual 0.1
    kv2.push("c", nd.array(np.full(4, 0.3, np.float32)))
    kv2.pull("c", out=o)
    assert np.allclose(o.asnumpy(), 0.5 * n), o.asnumpy()

    # --- scenario 3: multiprecision (reference test_sync_push_pull fp16 /
    # mp sgd, optimizer_op.cc mp_sgd_mom_update) -------------------------
    kv3 = mx.kv.create("dist_sync")
    opt = mx.optimizer.create("sgd", learning_rate=0.1, multi_precision=True,
                              rescale_grad=1.0 / n)
    kv3.set_optimizer(opt)
    w16 = nd.array(np.ones(4, np.float16))
    kv3.init("mp", w16)
    kv3.push("mp", nd.array(np.full(4, float(r + 1), np.float16)))
    om = nd.zeros((4,), dtype="float16")
    kv3.pull("mp", out=om)
    mean_grad = sum(range(1, n + 1)) / n
    exp = np.float16(1.0 - 0.1 * mean_grad)
    assert np.allclose(om.asnumpy(), exp, atol=1e-3), (om.asnumpy(), exp)

    # --- scenario 4: Gluon Trainer over dist_sync (reference
    # dist_sync_kvstore.py:353 test_gluon_trainer_type) ------------------
    mx.random.seed(7)  # identical init on every rank
    netd = mx.gluon.nn.Dense(2)
    netd.initialize()
    xb = nd.array(np.ones((2, 3), np.float32) * (r + 1))  # rank-dependent data
    netd(xb)
    tr = mx.gluon.Trainer(netd.collect_params(), "sgd",
                          {"learning_rate": 0.05}, kvstore="dist_sync")
    with autograd.record():
        loss = (netd(xb) ** 2).sum()
    loss.backward()
    tr.step(2)
    vals = np.concatenate([p.data().asnumpy().ravel()
                           for p in netd.collect_params().values()])
    kv.barrier()
    print("RANK%d_NIGHTLY %s" % (r, np.round(vals, 5).tolist()), flush=True)
    dist.shutdown()
""")


WORKER_RECOVERY = textwrap.dedent("""
    import os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import dist
    from mxnet_tpu import nd, autograd

    CKPT = os.environ["RECOVERY_CKPT"]          # checkpoint prefix
    MODE = os.environ["RECOVERY_MODE"]          # control | crash | resume
    TOTAL = 10
    CRASH_AT = 5

    dist.init()
    r, n = dist.rank(), dist.size()

    mx.random.seed(11)                          # identical init on every rank
    net = mx.gluon.nn.Dense(2)
    net.initialize()
    net(nd.zeros((2, 3)))
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.05, "momentum": 0.9},
                          kvstore="dist_sync")

    start = 0
    if MODE == "resume":
        # relaunch into a live job from the last durable checkpoint
        # (reference is_recovery path, kvstore_dist.h:52-55 — recovery =
        # checkpoint + relaunch in this design, docs/ENV_VARS.md)
        start = int(open(CKPT + ".step").read())
        net.load_parameters(CKPT + ".params")
        tr.load_states(CKPT + ".states")

    kv = mx.kv.create("dist_sync")
    for t in range(start, TOTAL):
        if MODE == "crash" and r == 1 and t == CRASH_AT:
            os._exit(1)                          # rank dies mid-training
        # deterministic, rank- and step-dependent batch
        rng = np.random.RandomState(100 * t + r)
        xb = nd.array(rng.randn(2, 3).astype(np.float32))
        with autograd.record():
            loss = (net(xb) ** 2).sum()
        loss.backward()
        try:
            # fail fast if a peer vanished (the dead-node check)
            dist.barrier("step%d" % t, timeout_ms=8000)
        except dist.DeadNodeError as e:
            print("RANK%d_DIED_AT %d missing=%s" % (r, t, e.missing_ranks),
                  flush=True)
            import time; time.sleep(2)
            os._exit(3)
        tr.step(2)
        if r == 0:                               # durable checkpoint per step
            net.save_parameters(CKPT + ".params")
            tr.save_states(CKPT + ".states")
            with open(CKPT + ".step", "w") as f:
                f.write(str(t + 1))
    vals = np.concatenate([p.data().asnumpy().ravel()
                           for p in net.collect_params().values()])
    kv.barrier()
    print("RANK%d_FINAL %s" % (r, np.round(vals, 6).tolist()), flush=True)
    dist.shutdown()
""")


def _run_crash_recovery_story(tmp_path, worker_src, marker, crash_step,
                              ckpt_committed, timeout=420):
    """Shared control/crash/resume harness (reference is_recovery semantics,
    kvstore_dist.h:52-55, realized as checkpoint+relaunch).

    Launches ``worker_src`` three times via the local fake cluster: an
    uninterrupted control run, a run where rank 1 dies at ``crash_step``
    (the survivor must fail fast NAMING it — dead-node heartbeat,
    kvstore_dist.h:110-118 — and a durable checkpoint must exist, checked
    by ``ckpt_committed(prefix)``), and a relaunch that must finish with
    output identical to the control.  Workers read RECOVERY_MODE /
    RECOVERY_CKPT and print ``RANK<r><marker> <digest>`` on success,
    ``RANK<r>_DIED_AT <t> missing=[...]`` on fail-fast."""
    env_base = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    worker = tmp_path / "worker_recovery.py"
    worker.write_text(worker_src)

    def launch(mode, ckpt):
        env = dict(env_base, RECOVERY_MODE=mode, RECOVERY_CKPT=str(ckpt))
        return subprocess.run(
            [sys.executable, LAUNCH, "-n", "2", "--launcher", "local",
             sys.executable, str(worker)],
            env=env, capture_output=True, text=True, timeout=timeout)

    def finals(res):
        return sorted(l.split(marker + " ")[1]
                      for l in res.stdout.splitlines() if marker in l)

    # control: uninterrupted run
    for attempt in range(3):
        res = launch("control", tmp_path / "ctl")
        if res.returncode == 0:
            break
    assert res.returncode == 0, res.stdout + res.stderr
    control = finals(res)
    assert len(control) == 2 and control[0] == control[1], res.stdout

    # crash: rank 1 dies at crash_step; rank 0 must fail fast naming it.
    # Retry on ANY other outcome — a saturated host can time a barrier out
    # spuriously at an earlier step (the Gloo flake the retries exist for),
    # which must not escape the loop and fail the wrong assert
    want = "_DIED_AT %d missing=[1]" % crash_step
    for attempt in range(3):
        crash = launch("crash", tmp_path / "job")
        died = [l for l in crash.stdout.splitlines() if "_DIED_AT" in l]
        if (died and all(want in l for l in died)
                and ckpt_committed(tmp_path / "job")):
            break
    assert died and all(want in l for l in died), crash.stdout + crash.stderr
    assert ckpt_committed(tmp_path / "job"), "no durable checkpoint at crash"

    # resume: relaunch from the checkpoint; must match the control exactly
    for attempt in range(3):
        res2 = launch("resume", tmp_path / "job")
        if res2.returncode == 0:
            break
    assert res2.returncode == 0, res2.stdout + res2.stderr
    resumed = finals(res2)
    assert len(resumed) == 2, res2.stdout
    assert resumed == control, (resumed, control)


@pytest.mark.skipif(sys.platform != "linux", reason="local fake cluster uses fork/Gloo")
def test_dist_recovery_checkpoint_relaunch(tmp_path):
    """VERDICT round-3 item 8: the documented recovery story executed by CI.

    A 2-rank seeded training job checkpoints every step; rank 1 is killed
    mid-run and the survivor fails fast (DeadNodeError naming rank 1);
    the job is then RELAUNCHED from the checkpoint and must produce final
    parameters identical to an uninterrupted control run."""
    _run_crash_recovery_story(
        tmp_path, WORKER_RECOVERY, "_FINAL", crash_step=5,
        ckpt_committed=lambda p: p.with_suffix(".step").exists()
        and p.with_suffix(".step").read_text() == "5")


@pytest.mark.skipif(sys.platform != "linux", reason="local fake cluster uses fork/Gloo")
def test_dist_sync_kvstore_nightly_seven_processes(tmp_path):
    """The reference nightly tier's coverage (tests/nightly/
    dist_sync_kvstore.py, launched -n 7 --launcher local): rowsparse pulls,
    2-bit compression, multiprecision, and a Gluon Trainer over dist_sync —
    all on a 7-process fake cluster."""
    worker = tmp_path / "worker_nightly.py"
    worker.write_text(WORKER_NIGHTLY)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    for attempt in range(2):
        res = subprocess.run(
            [sys.executable, LAUNCH, "-n", "7", "--launcher", "local",
             sys.executable, str(worker)],
            env=env, capture_output=True, text=True, timeout=560,
        )
        if res.returncode == 0:
            break
    assert res.returncode == 0, res.stdout + res.stderr
    lines = [l for l in res.stdout.splitlines() if "_NIGHTLY" in l]
    assert len(lines) == 7, res.stdout + res.stderr
    # trainer left identical parameters on every rank
    vals = {l.split("_NIGHTLY ")[1] for l in lines}
    assert len(vals) == 1, vals


WORKER_POD_DETECTION = textwrap.dedent("""
    import os, sys
    # 4 virtual CPU devices per process -> a 2-process x 4-device global
    # mesh, the closest this host gets to a multi-host TPU pod slice
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=4")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.parallel import dist
    from mxnet_tpu.test_utils import load_module_by_path

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(mx.__file__)))
    CKPT = os.environ["RECOVERY_CKPT"] + ".ckpts"
    MODE = os.environ["RECOVERY_MODE"]       # control | crash | resume
    TOTAL = 6
    CRASH_AT = 3

    dist.init()
    import jax
    r, n = dist.rank(), dist.size()
    assert n == 2 and len(jax.devices()) == 8, (n, jax.devices())

    m = load_module_by_path(os.path.join(
        REPO, "examples", "deformable_rfcn", "train_fused.py"), "_pod_rfcn")
    mx.random.seed(5)                         # identical init on every rank
    net, shape, classes = m.build_net(False)  # tiny trunk, same graph
    B = 8
    step, state = m.make_rfcn_train_step(net, B, learning_rate=1e-3,
                                         momentum=0.9)

    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = parallel.make_mesh({"dp": 8})      # spans both processes
    repl = NamedSharding(mesh, P())
    bsh = NamedSharding(mesh, P("dp"))

    def globalize(a, sh):
        a = np.asarray(a)
        return jax.make_array_from_callback(a.shape, sh, lambda i: a[i])

    state = jax.tree_util.tree_map(lambda v: globalize(v, repl), state)

    # the PRODUCT recovery path: parallel.checkpoint.CheckpointManager
    # (orbax, step-indexed, atomic commit, every rank participates) — the
    # subsystem docs/ENV_VARS.md names for checkpoint+relaunch recovery
    from mxnet_tpu.parallel import checkpoint as ckpt_mod
    mgr = ckpt_mod.CheckpointManager(CKPT, max_to_keep=3)
    start = 0
    if MODE == "resume":
        start = mgr.latest_step()
        assert start is not None, "resume with no checkpoint"
        state = mgr.restore(step=start, like=state)

    jstep = jax.jit(step, donate_argnums=(0,))

    for t in range(start, TOTAL):
        if MODE == "crash" and r == 1 and t == CRASH_AT:
            os._exit(1)                       # rank dies mid-training
        try:
            dist.barrier("pod_step%d" % t, timeout_ms=12000)
        except dist.DeadNodeError as e:
            print("RANK%d_DIED_AT %d missing=%s" % (r, t, e.missing_ranks),
                  flush=True)
            import time; time.sleep(2)
            os._exit(3)
        # deterministic per-step global batch; every rank builds the same
        # numpy batch, make_array_from_callback shards it over dp
        rng = np.random.RandomState(1000 + t)
        data, info, gt = m.synthetic_coco(rng, B, shape, classes, net.max_gts)
        state, loss, _parts = jstep(state, globalize(data, bsh),
                                    globalize(info, bsh), globalize(gt, bsh),
                                    jax.random.PRNGKey(t))
        l = float(loss)                       # replicated scalar
        assert np.isfinite(l), l
        mgr.save(t + 1, state, force=True)    # collective (all ranks)
        mgr.wait_until_finished()             # durable before the next step
    flat, _ = jax.tree_util.tree_flatten(state)
    digest = float(sum(
        np.abs(np.asarray(v.addressable_shards[0].data).astype(np.float64)).sum()
        for v in flat))
    dist.barrier("pod_done", timeout_ms=60000)
    print("RANK%d_POD %.6f loss %.6f" % (r, digest, l), flush=True)
    dist.shutdown()
""")


@pytest.mark.skipif(sys.platform != "linux", reason="local fake cluster uses fork/Gloo")
def test_pod_story_one_program_fused_detection(tmp_path):
    """VERDICT round-4 item 2: the pod story as ONE program.

    ``tools/launch.py -n 2`` spawns two REAL processes, each with 4 virtual
    CPU devices; ``jax.distributed`` joins them into one 8-device dp mesh
    (≡ launcher + tracker roles, SURVEY §3.5) and the FUSED Deformable
    R-FCN train step (reduced trunk, full graph: trunk + RPN +
    MultiProposal + deformable PS-ROI heads + 4 losses + momentum SGD)
    runs across the process boundary with GSPMD-inserted gradient
    collectives over Gloo.  Mid-run, rank 1 is killed: the survivor fails
    fast naming it (dead-node check, kvstore_dist.h:110-118), and the job
    RELAUNCHES from the last durable checkpoint, finishing with parameters
    identical to an uninterrupted control run (is_recovery ≡
    checkpoint+relaunch, kvstore_dist.h:52-55), through the product
    ``parallel.checkpoint.CheckpointManager`` (orbax, atomic commit)."""
    _run_crash_recovery_story(
        tmp_path, WORKER_POD_DETECTION, "_POD", crash_step=3,
        ckpt_committed=lambda p: (p.parent / (p.name + ".ckpts") / "3").exists(),
        timeout=900)
