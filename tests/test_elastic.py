"""Elastic fit-loop tests (ISSUE 20): durable checkpoints, resume with
fast-forward, and the straggler checkpoint-and-rejoin / rank-death
fail-fast responses — single-process; the 2-process end-to-end run is
``ci/check_pod_train.py``."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import module as mod_mod
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import elastic


def _make_mod():
    data = mx.sym.var("data")
    # explicit layer name: symbol auto-numbering differs between modules
    # built in one process, and checkpoint keys must match across "runs"
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=4, name="fc"), name="softmax")
    return mod_mod.Module(sym)


def _make_iter():
    rng = np.random.RandomState(0)
    return NDArrayIter(rng.randn(16, 8).astype(np.float32),
                       rng.randint(0, 4, (16,)).astype(np.float32),
                       batch_size=8)


class _FakePod:
    """pending_rejoin seam only — what after_step consumes."""

    def __init__(self, incidents=()):
        self._incs = list(incidents)

    def pending_rejoin(self):
        return self._incs.pop(0) if self._incs else None


def test_gate_off_is_none(monkeypatch):
    monkeypatch.delenv("MXNET_ELASTIC_DIR", raising=False)
    assert elastic.controller() is None
    mod = _make_mod()
    mod.fit(_make_iter(), num_epoch=1,
            optimizer_params={"learning_rate": 0.1})
    assert mod.elastic_stats() is None


def test_fit_saves_then_resume_fast_forwards(tmp_path, monkeypatch):
    """Run A trains 4 global steps (2 epochs x 2 batches) with periodic
    saves; run B on a fresh module resumes from the durable checkpoint,
    fast-forwards every step without recomputing, and ends with run A's
    exact final params."""
    monkeypatch.setenv("MXNET_ELASTIC_DIR", str(tmp_path / "el"))
    monkeypatch.setenv("MXNET_ELASTIC_SAVE_STEPS", "2")
    mod_a = _make_mod()
    mod_a.fit(_make_iter(), num_epoch=2,
              optimizer_params={"learning_rate": 0.1})
    stats_a = mod_a.elastic_stats()
    assert stats_a is not None
    assert stats_a["resume_step"] == 0
    assert stats_a["saves"] >= 1
    assert stats_a["steps"][-1] == 4      # final step durably saved
    args_a, aux_a = mod_a.get_params()

    mod_b = _make_mod()
    mod_b.fit(_make_iter(), num_epoch=2,
              optimizer_params={"learning_rate": 0.1})
    stats_b = mod_b.elastic_stats()
    assert stats_b["resume_step"] == 4
    args_b, _ = mod_b.get_params()
    assert set(args_b) == set(args_a)
    for k in args_a:
        np.testing.assert_array_equal(args_b[k].asnumpy(),
                                      args_a[k].asnumpy())


def test_resume_trains_only_the_tail(tmp_path, monkeypatch):
    """A relaunch asked for MORE epochs fast-forwards the restored steps
    and trains only the new tail — params move past the checkpoint."""
    monkeypatch.setenv("MXNET_ELASTIC_DIR", str(tmp_path / "el2"))
    mod_a = _make_mod()
    mod_a.fit(_make_iter(), num_epoch=1,
              optimizer_params={"learning_rate": 0.1})
    args_a, _ = mod_a.get_params()
    assert mod_a.elastic_stats()["steps"][-1] == 2

    mod_b = _make_mod()
    mod_b.fit(_make_iter(), num_epoch=2,
              optimizer_params={"learning_rate": 0.1})
    assert mod_b.elastic_stats()["resume_step"] == 2
    args_b, _ = mod_b.get_params()
    moved = any(not np.array_equal(args_b[k].asnumpy(), args_a[k].asnumpy())
                for k in args_a)
    assert moved  # epoch 2 really trained


def test_straggler_rejoin_is_value_preserving(tmp_path, monkeypatch):
    """A straggler incident schedules the rebase at its agreed
    ``rejoin_step``; the rebase force-saves, restores, and leaves every
    param bit-identical (restore returns the bytes just saved)."""
    monkeypatch.delenv("MXNET_ELASTIC_DIR", raising=False)
    mod = _make_mod()
    mod.fit(_make_iter(), num_epoch=1,
            optimizer_params={"learning_rate": 0.1})
    el = elastic.ElasticController(str(tmp_path / "rj"))
    try:
        before, _ = mod.get_params()
        before = {k: v.asnumpy().copy() for k, v in before.items()}
        inc = {"id": "inc-straggler-r1-1-1", "reason": "straggler",
               "rank": 1, "meta": {"lag_steps": 3, "rejoin_step": 6}}
        assert el.after_step(mod, 5, _FakePod([inc])) is False  # scheduled
        assert el.after_step(mod, 6, _FakePod()) is True        # rebased
        assert el.rejoins == 1 and el.last_rejoin_step == 6
        assert el._mgr.latest_step() == 6
        after, _ = mod.get_params()
        for k in before:
            np.testing.assert_array_equal(after[k].asnumpy(), before[k])
    finally:
        el.close()


def test_rank_death_fails_fast(tmp_path, monkeypatch):
    monkeypatch.delenv("MXNET_ELASTIC_DIR", raising=False)
    mod = _make_mod()
    mod.fit(_make_iter(), num_epoch=1,
            optimizer_params={"learning_rate": 0.1})
    el = elastic.ElasticController(str(tmp_path / "dead"))
    try:
        inc = {"id": "inc-rank_death-r1-1-1", "reason": "rank_death",
               "rank": 1, "meta": {"push_age_s": 9.0}}
        with pytest.raises(RuntimeError, match="presumed dead"):
            el.after_step(mod, 7, _FakePod([inc]))
    finally:
        el.close()


def test_pending_rejoin_filters_reasons(monkeypatch):
    """Podplane hands the elastic loop only straggler-with-rejoin-order
    and rank_death incidents; observation-only incidents are dropped."""
    from mxnet_tpu.telemetry import podplane

    monkeypatch.setenv("MXNET_POD_METRICS", "1")
    monkeypatch.setenv("MXNET_POD_METRICS_ADDR", "127.0.0.1:0")
    p = podplane.PodPlane(rank=1, size=2, start_listener=False)
    try:
        p._observe_incidents([
            {"id": "i1", "reason": "slo_breach", "rank": 0, "meta": {}},
            {"id": "i2", "reason": "straggler", "rank": 1,
             "meta": {"rejoin_step": 12, "lag_steps": 4}},
            {"id": "i3", "reason": "rank_death", "rank": 0, "meta": {}},
        ])
        first = p.pending_rejoin()
        assert first["id"] == "i2" and first["meta"]["rejoin_step"] == 12
        second = p.pending_rejoin()
        assert second["id"] == "i3" and second["reason"] == "rank_death"
        assert p.pending_rejoin() is None
    finally:
        p.close()
