"""Module API tests — mirrors reference ``tests/python/unittest/test_module.py``
and ``tests/python/train/test_mlp.py`` (small real training to an accuracy
threshold).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import module as mod_mod
from mxnet_tpu.io import DataBatch, DataDesc, NDArrayIter


def _mlp_sym(num_hidden=32, num_classes=4):
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=num_hidden)
    act = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = mx.sym.FullyConnected(act, name="fc2", num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _toy_classification(n=400, num_classes=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 8).astype(np.float32)
    W = rng.randn(8, num_classes).astype(np.float32)
    y = np.argmax(X @ W + 0.1 * rng.randn(n, num_classes), axis=1).astype(np.float32)
    return X, y


class TestModuleBasics:
    def test_bind_and_shapes(self):
        sym = _mlp_sym()
        mod = mod_mod.Module(sym, data_names=["data"], label_names=["softmax_label"])
        mod.bind(data_shapes=[("data", (10, 8))], label_shapes=[("softmax_label", (10,))])
        assert mod.binded
        assert mod.data_shapes[0].shape == (10, 8)
        mod.init_params()
        assert mod.params_initialized
        arg_params, aux_params = mod.get_params()
        assert set(arg_params) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}

    def test_forward_output_shape(self):
        sym = _mlp_sym()
        mod = mod_mod.Module(sym)
        mod.bind(data_shapes=[("data", (10, 8))], label_shapes=[("softmax_label", (10,))])
        mod.init_params()
        batch = DataBatch(data=[mx.nd.ones((10, 8))], label=[mx.nd.zeros((10,))])
        mod.forward(batch, is_train=False)
        out = mod.get_outputs()[0]
        assert out.shape == (10, 4)
        np.testing.assert_allclose(out.asnumpy().sum(axis=1), np.ones(10), rtol=1e-5)

    def test_forward_reshapes_on_new_batch_shape(self):
        """MutableModule semantics (reference rcnn/core/module.py:30)."""
        sym = _mlp_sym()
        mod = mod_mod.Module(sym)
        mod.bind(data_shapes=[("data", (10, 8))], label_shapes=[("softmax_label", (10,))])
        mod.init_params()
        p0 = mod.get_params()[0]["fc1_weight"].asnumpy()
        batch = DataBatch(data=[mx.nd.ones((6, 8))], label=[mx.nd.zeros((6,))])
        mod.forward(batch, is_train=False)
        assert mod.get_outputs()[0].shape == (6, 4)
        # params survived the reshape
        np.testing.assert_allclose(mod.get_params()[0]["fc1_weight"].asnumpy(), p0)

    def test_input_grads(self):
        sym = _mlp_sym()
        mod = mod_mod.Module(sym)
        mod.bind(data_shapes=[("data", (4, 8))], label_shapes=[("softmax_label", (4,))],
                 inputs_need_grad=True)
        mod.init_params()
        batch = DataBatch(data=[mx.nd.ones((4, 8))], label=[mx.nd.array([0, 1, 2, 3])])
        mod.forward(batch, is_train=True)
        mod.backward()
        g = mod.get_input_grads()[0]
        assert g.shape == (4, 8)
        assert np.abs(g.asnumpy()).sum() > 0

    def test_save_load_checkpoint(self, tmp_path):
        sym = _mlp_sym()
        mod = mod_mod.Module(sym)
        mod.bind(data_shapes=[("data", (4, 8))], label_shapes=[("softmax_label", (4,))])
        mod.init_params()
        prefix = str(tmp_path / "mlp")
        mod.save_checkpoint(prefix, 3)
        mod2 = mod_mod.Module.load(prefix, 3)
        mod2.bind(data_shapes=[("data", (4, 8))], label_shapes=[("softmax_label", (4,))])
        mod2.init_params()
        p1 = mod.get_params()[0]
        p2 = mod2.get_params()[0]
        for k in p1:
            np.testing.assert_allclose(p1[k].asnumpy(), p2[k].asnumpy())

    def test_set_params(self):
        sym = _mlp_sym()
        mod = mod_mod.Module(sym)
        mod.bind(data_shapes=[("data", (4, 8))], label_shapes=[("softmax_label", (4,))])
        mod.init_params()
        arg, aux = mod.get_params()
        arg2 = {k: mx.nd.ones(v.shape) for k, v in arg.items()}
        mod.set_params(arg2, aux)
        for v in mod.get_params()[0].values():
            np.testing.assert_allclose(v.asnumpy(), np.ones(v.shape))

    def test_fixed_params_not_updated(self):
        sym = _mlp_sym()
        mod = mod_mod.Module(sym, fixed_param_names=["fc1_weight"])
        mod.bind(data_shapes=[("data", (4, 8))], label_shapes=[("softmax_label", (4,))])
        mod.init_params()
        mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 1.0})
        before = mod.get_params()[0]["fc1_weight"].asnumpy()
        batch = DataBatch(data=[mx.nd.ones((4, 8))], label=[mx.nd.array([0, 1, 2, 3])])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
        after = mod.get_params()[0]["fc1_weight"].asnumpy()
        np.testing.assert_allclose(before, after)
        # fc1 grad was never allocated
        assert mod._exec.grad_dict.get("fc1_weight") is None


class TestModuleFit:
    def test_fit_mlp_accuracy(self):
        """Small real training to threshold (reference tests/python/train/test_mlp.py)."""
        X, y = _toy_classification()
        train = NDArrayIter(X, y, batch_size=50, shuffle=True, label_name="softmax_label")
        val = NDArrayIter(X, y, batch_size=50, label_name="softmax_label")
        mod = mod_mod.Module(_mlp_sym())
        mod.fit(train, eval_data=val, optimizer="adam",
                optimizer_params={"learning_rate": 0.01},
                num_epoch=15, eval_metric="acc")
        score = mod.score(val, "acc")[0][1]
        assert score > 0.85, score

    def test_score_and_predict(self):
        X, y = _toy_classification()
        train = NDArrayIter(X, y, batch_size=50, shuffle=True)
        mod = mod_mod.Module(_mlp_sym())
        mod.fit(train, optimizer="adam", optimizer_params={"learning_rate": 0.01}, num_epoch=5)
        pred = mod.predict(NDArrayIter(X, y, batch_size=50))
        assert pred.shape == (400, 4)

    def test_fit_with_kvstore_instance(self):
        X, y = _toy_classification(n=100)
        train = NDArrayIter(X, y, batch_size=50)
        kv = mx.kv.create("local")
        mod = mod_mod.Module(_mlp_sym())
        mod.fit(train, kvstore=kv, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1}, num_epoch=2)
        assert mod.score(train, "acc")[0][1] > 0.2


class TestBucketingModule:
    def test_buckets_share_params(self):
        def sym_gen(seq_len):
            data = mx.sym.var("data")
            fc = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
            out = mx.sym.SoftmaxOutput(fc, name="softmax")
            return out, ["data"], ["softmax_label"]

        bm = mod_mod.BucketingModule(sym_gen, default_bucket_key=8)
        bm.bind([("data", (2, 8))], [("softmax_label", (2,))])
        bm.init_params()
        bm.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.1})

        b1 = DataBatch(data=[mx.nd.ones((2, 8))], label=[mx.nd.array([0, 1])],
                       bucket_key=8, provide_data=[DataDesc("data", (2, 8))],
                       provide_label=[DataDesc("softmax_label", (2,))])
        bm.forward(b1, is_train=True)
        bm.backward()
        bm.update()
        w_after = bm.get_params()[0]["fc_weight"].asnumpy()

        # same param object visible from another bucket
        b2 = DataBatch(data=[mx.nd.ones((4, 8))], label=[mx.nd.array([0, 1, 2, 3])],
                       bucket_key=4, provide_data=[DataDesc("data", (4, 8))],
                       provide_label=[DataDesc("softmax_label", (4,))])
        bm.forward(b2, is_train=False)
        np.testing.assert_allclose(bm.get_params()[0]["fc_weight"].asnumpy(), w_after)
        assert bm.get_outputs()[0].shape == (4, 4)


class TestSequentialModule:
    def test_two_stage_chain(self):
        data = mx.sym.var("data")
        net1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
        net1 = mx.sym.Activation(net1, name="a1", act_type="relu")

        data2 = mx.sym.var("data")
        net2 = mx.sym.FullyConnected(data2, name="fc2", num_hidden=4)
        net2 = mx.sym.SoftmaxOutput(net2, name="softmax")

        seq = mod_mod.SequentialModule()
        seq.add(mod_mod.Module(net1, label_names=None))
        seq.add(mod_mod.Module(net2), take_labels=True, auto_wiring=True)
        seq.bind([("data", (4, 8))], [("softmax_label", (4,))])
        seq.init_params()
        seq.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.1})
        batch = DataBatch(data=[mx.nd.ones((4, 8))], label=[mx.nd.array([0, 1, 2, 3])])
        seq.forward(batch, is_train=True)
        out = seq.get_outputs()[0]
        assert out.shape == (4, 4)
        seq.backward()
        seq.update()


class TestFeedForward:
    def test_feedforward_fit_predict(self):
        X, y = _toy_classification(n=200)
        ff = mx.model.FeedForward(_mlp_sym(), num_epoch=5, optimizer="adam", learning_rate=0.01)
        ff.fit(X, y, kvstore=None)
        pred = ff.predict(NDArrayIter(X, y, batch_size=50))
        assert pred.shape == (200, 4)

    def test_checkpoint_roundtrip(self, tmp_path):
        sym = _mlp_sym()
        arg = {"fc1_weight": mx.nd.ones((32, 8)), "fc1_bias": mx.nd.zeros((32,)),
               "fc2_weight": mx.nd.ones((4, 32)), "fc2_bias": mx.nd.zeros((4,))}
        prefix = str(tmp_path / "ck")
        mx.model.save_checkpoint(prefix, 7, sym, arg, {})
        sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 7)
        assert sym2 is not None
        for k in arg:
            np.testing.assert_allclose(arg[k].asnumpy(), arg2[k].asnumpy())


class TestModuleMeshDP:
    def test_fit_with_mesh_sharded_batches(self):
        """Data-parallel Module over a dp mesh — the XLA replacement for
        DataParallelExecutorGroup (reference executor_group.py:143)."""
        from mxnet_tpu import parallel

        mesh = parallel.make_mesh(dp=8)
        X, y = _toy_classification(n=400)
        train = NDArrayIter(X, y, batch_size=80, shuffle=True)
        mod = mod_mod.Module(_mlp_sym(), mesh=mesh)
        mod.fit(train, optimizer="adam", optimizer_params={"learning_rate": 0.01}, num_epoch=8)
        score = mod.score(NDArrayIter(X, y, batch_size=80), "acc")[0][1]
        assert score > 0.8, score


class TestBucketingOptimizerBorrow:
    def test_update_on_late_bucket(self):
        """New bucket created after init_optimizer must be able to update
        (reference borrow_optimizer)."""

        def sym_gen(seq_len):
            data = mx.sym.var("data")
            fc = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
            return mx.sym.SoftmaxOutput(fc, name="softmax"), ["data"], ["softmax_label"]

        bm = mod_mod.BucketingModule(sym_gen, default_bucket_key=8)
        bm.bind([("data", (2, 8))], [("softmax_label", (2,))])
        bm.init_params()
        bm.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.1})
        b = DataBatch(data=[mx.nd.ones((4, 8))], label=[mx.nd.array([0, 1, 2, 3])],
                      bucket_key=4, provide_data=[DataDesc("data", (4, 8))],
                      provide_label=[DataDesc("softmax_label", (4,))])
        w0 = bm.get_params()[0]["fc_weight"].asnumpy()
        bm.forward(b, is_train=True)
        bm.backward()
        bm.update()  # must not assert
        assert not np.allclose(bm.get_params()[0]["fc_weight"].asnumpy(), w0)


class TestBucketingCompileCache:
    def test_many_buckets_cache_bounded(self):
        """rcnn-style many-shapes workload: after the first epoch touches
        every bucket, later epochs must NOT grow the executable cache
        (VERDICT round-1 weak item 8 — the stable_eager leak class, but on
        the bucketing/executor path).  /proc/self/maps is the proxy the
        leak-regression suite uses (tests/test_no_compile_leak.py)."""
        def sym_gen(seq_len):
            # param shapes must be bucket-independent (like RNN cells over
            # variable time): pool the length axis before the FC
            data = mx.sym.var("data")
            pooled = mx.sym.mean(data, axis=1, keepdims=True)
            fc = mx.sym.FullyConnected(pooled, name="fc", num_hidden=4)
            out = mx.sym.SoftmaxOutput(fc, name="softmax")
            return out, ["data"], ["softmax_label"]

        buckets = [4, 6, 8, 10, 12, 16, 20, 24]
        bm = mod_mod.BucketingModule(sym_gen, default_bucket_key=max(buckets))
        bm.bind([("data", (2, max(buckets)))], [("softmax_label", (2,))])
        bm.init_params()
        bm.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.1})

        def epoch():
            for L in buckets:
                b = DataBatch(
                    data=[mx.nd.ones((2, L))], label=[mx.nd.array([0, 1])],
                    bucket_key=L, provide_data=[DataDesc("data", (2, L))],
                    provide_label=[DataDesc("softmax_label", (2,))])
                bm.forward(b, is_train=True)
                bm.backward()
                bm.update()

        epoch()  # every bucket compiles once
        m0 = sum(1 for _ in open("/proc/self/maps"))
        for _ in range(3):
            epoch()
        m1 = sum(1 for _ in open("/proc/self/maps"))
        assert m1 - m0 <= 2, "executable cache grew across epochs: %d -> %d" % (m0, m1)
        # the per-bucket module cache is keyed by bucket, not per call
        assert len(bm._buckets) == len(buckets)
