"""gluon.functional + driver entry tests."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.functional import functionalize, make_train_step


def _small_net():
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.BatchNorm(), gluon.nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((2, 8)))  # materialize deferred shapes
    return net


class TestFunctionalize:
    def test_apply_matches_eager(self):
        import jax

        net = _small_net()
        apply, names, vals, aux_names = functionalize(net, train=False)
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        out, new_aux = apply(vals, x)
        eager = net(mx.nd.array(x)).asnumpy()
        np.testing.assert_allclose(np.asarray(out), eager, rtol=1e-5, atol=1e-6)
        # eval mode: BN stats unchanged
        aux_before = [vals[i] for i, n in enumerate(names) if n in set(aux_names)]
        for a, b in zip(aux_before, new_aux):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_apply_is_jittable(self):
        import jax

        net = _small_net()
        apply, _, vals, _ = functionalize(net, train=False)
        jf = jax.jit(lambda v, x, k: apply(v, x, k)[0])
        x = np.ones((4, 8), np.float32)
        out = jf(vals, x, jax.random.PRNGKey(0))
        assert np.asarray(out).shape == (4, 4)

    def test_train_step_learns(self):
        import jax

        rng = np.random.RandomState(1)
        X = rng.randn(64, 8).astype(np.float32)
        W = rng.randn(8, 4).astype(np.float32)
        y = np.argmax(X @ W, axis=1).astype(np.float32)

        net = _small_net()
        step, state, _ = make_train_step(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), learning_rate=0.1, momentum=0.9
        )
        jstep = jax.jit(step)
        key = jax.random.PRNGKey(0)
        losses = []
        for i in range(30):
            state, loss = jstep(state, X, y, jax.random.fold_in(key, i))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.6, losses[::10]

    def test_train_step_updates_bn_stats(self):
        import jax

        net = _small_net()
        step, state, (names, learn_idx, aux_idx) = make_train_step(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), learning_rate=0.1
        )
        aux_before = [np.asarray(a) for a in state[2]]
        X = np.random.RandomState(0).randn(32, 8).astype(np.float32) * 5 + 3
        y = np.zeros((32,), np.float32)
        state, _ = jax.jit(step)(state, X, y, jax.random.PRNGKey(0))
        aux_after = [np.asarray(a) for a in state[2]]
        moved = any(not np.allclose(a, b) for a, b in zip(aux_before, aux_after))
        assert moved, "BatchNorm running stats did not update in train step"


class TestGraftEntry:
    def test_dryrun_multichip_small(self, monkeypatch):
        import __graft_entry__ as g

        # tiny detection trunk here: the unit tier checks the wiring; the
        # driver's real dryrun_multichip(8) runs the full ResNet-101 trunk
        monkeypatch.setenv("MXNET_DRYRUN_TINY_DETECTION", "1")
        g.dryrun_multichip(4)

    def test_train_step_zero_sharded(self):
        """VERDICT r4 item 8: shard_optimizer_states=True partitions params
        + momentum over the dp mesh axis, returns a jitted step with pinned
        output shardings, and matches the unsharded step numerically."""
        import jax
        from mxnet_tpu import parallel
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = np.random.RandomState(1)
        X = rng.randn(64, 8).astype(np.float32)
        W = rng.randn(8, 4).astype(np.float32)
        y = np.argmax(X @ W, axis=1).astype(np.float32)

        n = len(jax.devices())
        mesh = parallel.make_mesh({"dp": n})

        mx.random.seed(7)
        ref_net = _small_net()
        ref_step, ref_state, _ = make_train_step(
            ref_net, gluon.loss.SoftmaxCrossEntropyLoss(),
            learning_rate=0.1, momentum=0.9)
        ref_jstep = jax.jit(ref_step)

        mx.random.seed(7)
        net = _small_net()
        step, state, _ = make_train_step(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), learning_rate=0.1,
            momentum=0.9, mesh=mesh, shard_optimizer_states=True)

        # the partition is real: at least the Dense weights split over dp
        sharded = [v for v in state[0] + state[1]
                   if not v.sharding.is_equivalent_to(
                       NamedSharding(mesh, P()), v.ndim)]
        assert sharded, "no state array was partitioned"
        per_dev = sum(int(np.prod(v.sharding.shard_shape(v.shape)))
                      * v.dtype.itemsize for v in state[0] + state[1])
        full = sum(int(np.prod(v.shape)) * v.dtype.itemsize
                   for v in state[0] + state[1])
        assert per_dev < full * 0.6, (per_dev, full)

        Xs = jax.device_put(X, NamedSharding(mesh, P("dp")))
        ys = jax.device_put(y, NamedSharding(mesh, P("dp")))
        key = jax.random.PRNGKey(0)
        losses = []
        for i in range(10):
            k = jax.random.fold_in(key, i)
            state, loss = step(state, Xs, ys, k)
            ref_state, ref_loss = ref_jstep(ref_state, X, y, k)
            np.testing.assert_allclose(float(loss), float(ref_loss),
                                       rtol=2e-4, atol=2e-5)
            losses.append(float(loss))
        # shardings survive the step (out_shardings pinned, donation safe)
        still = [v for v in state[0] + state[1]
                 if not v.sharding.is_equivalent_to(
                     NamedSharding(mesh, P()), v.ndim)]
        assert len(still) == len(sharded)
        assert losses[-1] < losses[0] * 0.8, losses
