"""Precision-tier compilation tests (ISSUE 15) —
``mxnet_tpu/graph_passes/precision.py``: the CastPlan-driven bf16 pass,
conv/FC weight folding, and the calibration-based int8 rewrite, plus the
off-path identity and fingerprint-drift contracts."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compile_cache, graph_passes
from mxnet_tpu.analysis import numerics
from mxnet_tpu.graph_passes import precision
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.test_utils import deploy_twin_checkpoint


@pytest.fixture()
def deploy_pred():
    sym, params, shapes = deploy_twin_checkpoint(batch=4, image=16)
    return Predictor(sym, params, shapes)


def _fixed_input(batch=4, image=16, seed=0):
    return np.random.RandomState(seed).rand(
        batch, 3, image, image).astype(np.float32)


def _outs(pred, x):
    return [o.asnumpy() for o in pred.forward(data=x)]


# -- off path ----------------------------------------------------------------


def test_off_path_plan_and_key_identity(deploy_pred, monkeypatch):
    monkeypatch.delenv("MXNET_PRECISION_TIER", raising=False)
    exe = deploy_pred._exec
    assert precision.tier() is None
    assert exe._precision_tier is None
    # the lowered plan IS the structural plan (no rebuild, no rewrite)
    assert exe._opt_plan(False) is exe._structural_plan(False)
    # AOT logical key carries no tier parts
    assert exe._tier_key_parts(False) == ()
    fp = graph_passes.pipeline_fingerprint()
    assert fp and "tier" not in fp


def test_invalid_tier_value_reads_as_off(monkeypatch):
    monkeypatch.setenv("MXNET_PRECISION_TIER", "fp8")
    with pytest.warns(UserWarning, match="MXNET_PRECISION_TIER"):
        assert precision.tier() is None


def test_env_gate_builds_twin(monkeypatch):
    sym, params, shapes = deploy_twin_checkpoint(batch=2, image=16)
    monkeypatch.setenv("MXNET_PRECISION_TIER", "bf16")
    pred = Predictor(sym, params, shapes)
    assert pred.precision_tier == "bf16"
    assert "tier=bf16" in graph_passes.pipeline_fingerprint()
    plan, _, _ = pred._exec._opt_plan(False)
    assert any(getattr(n.op, "name", "") == "_precision_cast"
               for n, _ in plan)


# -- bf16 tier ---------------------------------------------------------------


def test_bf16_twin_tolerance_and_shared_buffers(deploy_pred):
    x = _fixed_input()
    base = _outs(deploy_pred, x)
    twin = deploy_pred.with_precision("bf16")
    assert twin.precision_tier == "bf16"
    # shared weight buffers: same loaded param NDArrays under both
    w0 = deploy_pred._arg_params["conv0_weight"]
    assert twin._arg_params["conv0_weight"] is w0
    outs = _outs(twin, x)
    tol = precision.tier_tolerance("bf16")
    for a, b in zip(base, outs):
        assert b.dtype == a.dtype  # heads re-widen: drop-in twin
        np.testing.assert_allclose(a, b, **tol)


def test_bf16_fold_removes_bn_affine(deploy_pred):
    twin = deploy_pred.with_precision("bf16")
    plan, _, const_env = twin._exec._opt_plan(False)
    ops = [getattr(n.op, "name", "") for n, _ in plan]
    assert "_bn_affine" not in ops
    assert "BatchNorm" not in ops
    assert any(k.endswith("__folded_weight") for k in (const_env or {}))


def test_bf16_fp32_accum_visible_in_jaxpr(deploy_pred):
    """fp32_accum contract, asserted on the jaxpr: conv/dot eqns with bf16
    operands must carry preferred_element_type=float32, and every
    reduce-class island must reduce over f32 operands."""
    import jax

    twin = deploy_pred.with_precision("bf16")
    exe = twin._exec
    args = exe._aot_example_args()
    jaxpr = jax.make_jaxpr(exe._graph_fn(False))(*args)
    contractions = []

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name in ("conv_general_dilated", "dot_general"):
                in_dts = {str(v.aval.dtype) for v in eqn.invars
                          if hasattr(v, "aval")}
                if "bfloat16" in in_dts:
                    contractions.append(eqn)
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)
    walk(jaxpr.jaxpr)
    assert contractions, "no bf16 contraction traced — the tier was inert"
    for eqn in contractions:
        pet = eqn.params.get("preferred_element_type")
        assert str(pet) == "float32", \
            "%s with bf16 operands accumulates in %s" % (
                eqn.primitive.name, pet)
        assert str(eqn.outvars[0].aval.dtype) == "float32", \
            "accumulator output must be f32 before the exit narrowing"


def test_bf16_islands_wrap_reductions(deploy_pred):
    """Non-MXU fp32_accum nodes (avg-pool, L2Norm) become _fp32_island
    wrappers; fp32_only nodes (the unbounded softmax) stay untouched."""
    twin = deploy_pred.with_precision("bf16")
    plan, _, _ = twin._exec._opt_plan(False)
    by_name = {n.name: getattr(n.op, "name", "") for n, _ in plan}
    cast_plan = deploy_pred.precision_plan()
    for row in cast_plan.rows:
        op = by_name.get(row["node"])
        if op is None:
            continue  # folded away
        if row["verdict"] == "fp32_only":
            assert op == row["op"], \
                "fp32_only node %s was rewritten to %s" % (row["node"], op)
        if row["verdict"] == "fp32_accum" \
                and row["op"] not in ("Convolution", "FullyConnected"):
            assert op == "_fp32_island", \
                "fp32_accum reduction %s (%s) is not islanded: %s" % (
                    row["node"], row["op"], op)


def test_bf16_cast_economy(deploy_pred):
    """At most one cast node per (value, direction): no duplicate casts of
    the same env name, no cast feeding another cast (sandwich), and no
    DEAD cast — every convert the pass inserts is consumed (islands take
    their operands as held and must not leave orphaned casts behind)."""
    twin = deploy_pred.with_precision("bf16")
    plan, heads, _ = twin._exec._opt_plan(False)
    used = set(heads)
    for _, in_names in plan:
        used.update(in_names)
    cast_srcs = []
    cast_outs = set()
    for n, in_names in plan:
        if getattr(n.op, "name", "") == "_precision_cast":
            cast_srcs.append((in_names[0], n.attrs["dtype"]))
            assert in_names[0] not in cast_outs, \
                "cast sandwich: %s re-casts a cast output" % n.name
            out = "%s_output" % n.name
            cast_outs.add(out)
            assert out in used, "dead cast node %s (never consumed)" % n.name
    assert len(cast_srcs) == len(set(cast_srcs)), \
        "duplicate casts of one value: %s" % cast_srcs


def test_with_shapes_carries_tier(deploy_pred):
    twin = deploy_pred.with_precision("bf16")
    sib = twin.with_shapes({"data": (2, 3, 16, 16)})
    assert sib.precision_tier == "bf16"
    plan, _, _ = sib._exec._opt_plan(False)
    assert any(getattr(n.op, "name", "") == "_precision_cast"
               for n, _ in plan)


def test_train_plans_never_tier_rewritten(deploy_pred):
    exe = deploy_pred.with_precision("bf16")._exec
    assert exe._opt_plan(True) is exe._structural_plan(True)
    assert exe._tier_key_parts(True) == ()


# -- int8 tier ---------------------------------------------------------------


def test_int8_calibrated_twin_tolerance(deploy_pred):
    rng = np.random.RandomState(1)
    x = _fixed_input()
    base = _outs(deploy_pred, x)
    table = precision.calibrate(
        deploy_pred,
        ({"data": rng.rand(4, 3, 16, 16).astype(np.float32)}
         for _ in range(3)))
    assert table.batches == 3 and table.ranges
    twin = deploy_pred.with_precision("int8", calibration=table)
    plan, _, const_env = twin._exec._opt_plan(False)
    q_ops = [getattr(n.op, "name", "") for n, _ in plan
             if getattr(n.op, "name", "").startswith("_int8_")]
    assert q_ops, "calibrated int8 twin rewrote nothing"
    # baked int8 weights really are int8
    wq = [v for k, v in (const_env or {}).items()
          if k.endswith("__int8_weight")]
    assert wq and all(np.asarray(w).dtype == np.int8 for w in wq)
    outs = _outs(twin, x)
    tol = precision.tier_tolerance("int8")
    for a, b in zip(base, outs):
        assert b.dtype == a.dtype
        np.testing.assert_allclose(a, b, **tol)


def test_int8_uncalibrated_untouched(deploy_pred):
    twin = deploy_pred.with_precision("int8")  # no table: zero coverage
    plan, _, _ = twin._exec._opt_plan(False)
    assert not any(getattr(n.op, "name", "").startswith("_int8_")
                   for n, _ in plan)
    # fp32_only nodes are never quantized even when calibrated
    table = precision.calibrate(
        twin, [{"data": _fixed_input(seed=2)}])
    full = twin.with_precision("int8", calibration=table)
    planf, _, _ = full._exec._opt_plan(False)
    by_name = {n.name: getattr(n.op, "name", "") for n, _ in planf}
    for row in full.precision_plan().rows:
        if row["verdict"] == "fp32_only" and row["node"] in by_name:
            assert not by_name[row["node"]].startswith("_int8_")


def test_int8_after_fold_uses_affined_range(monkeypatch):
    """A conv/FC fed DIRECTLY by a folded BN must quantize with the
    affined activation range, not the pre-BN producer's (fold renames the
    BN output onto the conv's env name; calibration recorded the
    structural names — the pass resolves through the rename)."""
    sym_data = mx.sym.var("data")
    h = mx.sym.Convolution(sym_data, name="c0", kernel=(1, 1), num_filter=4)
    # gamma scales activations 20x: quantizing with the pre-BN range would
    # clip the FC input at ~1/20th of its real magnitude
    h = mx.sym.BatchNorm(h, name="bn0", fix_gamma=False)
    h = mx.sym.Flatten(h)
    out = mx.sym.FullyConnected(h, name="fc0", num_hidden=3)

    rng = np.random.RandomState(0)
    shapes = {"data": (2, 3, 4, 4)}
    arg_shapes, _, aux_shapes = out.infer_shape(**shapes)
    params = {}
    for n, s in zip(out.list_arguments(), arg_shapes):
        if n != "data":
            params["arg:" + n] = mx.nd.array(
                rng.randn(*s).astype(np.float32) * 0.2)
    params["arg:bn0_gamma"] = mx.nd.array(np.full((4,), 20.0, np.float32))
    for n, s in zip(out.list_auxiliary_states(), aux_shapes):
        params["aux:" + n] = mx.nd.array(
            np.ones(s, np.float32) if n.endswith("_var")
            else np.zeros(s, np.float32))
    pred = Predictor(out, params, shapes)
    x = rng.rand(2, 3, 4, 4).astype(np.float32)
    base = _outs(pred, x)
    table = precision.calibrate(
        pred, ({"data": rng.rand(2, 3, 4, 4).astype(np.float32)}
               for _ in range(3)))
    twin = pred.with_precision("int8", calibration=table)
    plan, _, _ = twin._exec._opt_plan(False)
    ops = [getattr(n.op, "name", "") for n, _ in plan]
    assert "_bn_affine" not in ops and "_int8_fullyconnected" in ops
    outs = _outs(twin, x)
    tol = precision.tier_tolerance("int8")
    for a, b in zip(base, outs):
        np.testing.assert_allclose(a, b, **tol)


def test_fold_rejects_negative_axis_on_conv():
    """_bn_affine axis=-1 over a 4-D conv output scales the WIDTH axis —
    the fold must refuse even when C_out coincidentally equals the
    trailing spatial dim (the length guard alone would pass)."""
    data = mx.sym.var("data")
    h = mx.sym.Convolution(data, name="c0", kernel=(1, 1), num_filter=4)
    out = mx.sym.BatchNorm(h, name="bn0", fix_gamma=False, axis=-1)
    rng = np.random.RandomState(0)
    shapes = {"data": (2, 3, 4, 4)}  # output (2, 4, 4, 4): C == W == 4
    arg_shapes, _, aux_shapes = out.infer_shape(**shapes)
    params = {}
    for n, s in zip(out.list_arguments(), arg_shapes):
        if n != "data":
            params["arg:" + n] = mx.nd.array(
                rng.randn(*s).astype(np.float32))
    for n, s in zip(out.list_auxiliary_states(), aux_shapes):
        params["aux:" + n] = mx.nd.array(
            np.ones(s, np.float32) if n.endswith("_var")
            else np.zeros(s, np.float32))
    pred = Predictor(out, params, shapes)
    x = rng.rand(2, 3, 4, 4).astype(np.float32)
    base = _outs(pred, x)
    twin = pred.with_precision("bf16")
    plan, _, _ = twin._exec._opt_plan(False)
    assert "_bn_affine" in [getattr(n.op, "name", "") for n, _ in plan], \
        "axis=-1 conv affine must NOT fold"
    outs = _outs(twin, x)
    tol = precision.tier_tolerance("bf16")
    for a, b in zip(base, outs):
        np.testing.assert_allclose(a, b, **tol)


def test_fold_refuses_runtime_computed_bias():
    """A conv/FC whose bias is a NODE OUTPUT (not a bound arg/const) must
    not fold — folding would silently drop the bias term."""
    data = mx.sym.var("data")
    bsrc = mx.sym.var("bsrc")
    bias = mx.sym.elemwise_mul(bsrc, bsrc, name="bexpr")
    fc = mx.sym.FullyConnected(data, bias=bias, name="fc0", num_hidden=4)
    out = mx.sym.BatchNorm(fc, name="bn0", fix_gamma=False)
    rng = np.random.RandomState(0)
    shapes = {"data": (2, 5), "bsrc": (4,)}
    arg_shapes, _, aux_shapes = out.infer_shape(**shapes)

    def bind():
        exe = out.simple_bind(grad_req="null", **shapes)
        for n, s in zip(out.list_arguments(), arg_shapes):
            if n == "data":
                exe.arg_dict[n][:] = rng2.rand(*s).astype(np.float32)
            else:
                exe.arg_dict[n][:] = _seeded(n, s)
        for n, s in zip(out.list_auxiliary_states(), aux_shapes):
            exe.aux_dict[n][:] = (np.ones(s, np.float32)
                                  if n.endswith("_var")
                                  else np.zeros(s, np.float32))
        return exe

    def _seeded(n, s):
        return np.random.RandomState(abs(hash(n)) % 2**31) \
            .randn(*s).astype(np.float32) * 3.0

    rng2 = np.random.RandomState(1)
    base_exe = bind()
    rng2 = np.random.RandomState(1)
    twin_exe = bind()
    twin_exe.set_precision_tier("bf16")
    base = [o.asnumpy() for o in base_exe.forward(is_train=False)]
    outs = [o.asnumpy() for o in twin_exe.forward(is_train=False)]
    plan, _, _ = twin_exe._opt_plan(False)
    assert "_bn_affine" in [getattr(n.op, "name", "") for n, _ in plan], \
        "runtime-bias conv must NOT fold"
    tol = precision.tier_tolerance("bf16")
    for a, b in zip(base, outs):
        np.testing.assert_allclose(a, b, **tol)


def test_int8_prunes_superseded_fold_constant():
    """int8 quantizing a fold-baked conv weight must drop the dead fp32
    copy from Graph.constants (no duplicated resident weights)."""
    data = mx.sym.var("data")
    h = mx.sym.Convolution(data, name="c0", kernel=(1, 1), num_filter=4)
    h = mx.sym.BatchNorm(h, name="bn0", fix_gamma=False)
    out = mx.sym.Activation(h, act_type="relu", name="r0")
    rng = np.random.RandomState(0)
    shapes = {"data": (2, 3, 4, 4)}
    arg_shapes, _, aux_shapes = out.infer_shape(**shapes)
    params = {}
    for n, s in zip(out.list_arguments(), arg_shapes):
        if n != "data":
            params["arg:" + n] = mx.nd.array(
                rng.randn(*s).astype(np.float32))
    for n, s in zip(out.list_auxiliary_states(), aux_shapes):
        params["aux:" + n] = mx.nd.array(
            np.ones(s, np.float32) if n.endswith("_var")
            else np.zeros(s, np.float32))
    pred = Predictor(out, params, shapes)
    table = precision.calibrate(
        pred, [{"data": rng.rand(2, 3, 4, 4).astype(np.float32)}])
    twin = pred.with_precision("int8", calibration=table)
    plan, _, const_env = twin._exec._opt_plan(False)
    assert any(getattr(n.op, "name", "").startswith("_int8_")
               for n, _ in plan)
    used = {nm for _, ins in plan for nm in ins}
    assert "c0__int8_weight" in (const_env or {})
    assert "c0__folded_weight" not in (const_env or {}), \
        "superseded fp32 fold constant left resident"
    assert all(k in used for k in (const_env or {}))


def test_reshape_carries_tier(deploy_pred):
    twin = deploy_pred.with_precision("bf16")
    twin.reshape({"data": (2, 3, 16, 16)})
    assert twin.precision_tier == "bf16"
    plan, _, _ = twin._exec._opt_plan(False)
    assert any(getattr(n.op, "name", "") == "_precision_cast"
               for n, _ in plan)
    x = np.random.RandomState(0).rand(2, 3, 16, 16).astype(np.float32)
    outs = _outs(twin, x)
    assert outs[0].shape == (2, 10)


def test_pass_stats_stable_across_tier_changes(deploy_pred):
    """Re-setting the tier (or clearing it) must not duplicate or leak
    tier pass rows — the cached structural stats are never mutated."""
    exe = deploy_pred.with_precision("bf16")._exec
    exe._opt_plan(False)
    once = [r["pass"] for r in exe.pass_stats()["eval"]["passes"]]
    exe.set_precision_tier("bf16")
    exe._opt_plan(False)
    again = [r["pass"] for r in exe.pass_stats()["eval"]["passes"]]
    assert once == again, "tier rows duplicated across re-sets"
    assert once.count("bf16_cast") == 1
    exe.set_precision_tier(None)
    cleared = exe.pass_stats()["eval"]
    assert "bf16_cast" not in [r["pass"] for r in cleared["passes"]]
    assert cleared["nodes_post"] == len(exe._opt_plan(False)[0])


def test_calibration_fingerprint_moves_with_data(deploy_pred):
    t1 = precision.calibrate(deploy_pred, [{"data": _fixed_input(seed=3)}])
    t2 = precision.calibrate(deploy_pred, [{"data": _fixed_input(seed=4)}])
    t1b = precision.calibrate(deploy_pred, [{"data": _fixed_input(seed=3)}])
    assert t1.fingerprint() == t1b.fingerprint()
    assert t1.fingerprint() != t2.fingerprint()


# -- fingerprints / AOT keys -------------------------------------------------


def _exec_key(pred):
    """The CachedFunction logical key the eval forward would persist
    under (AOT cache active or not, the key parts are what matter)."""
    exe = pred._exec
    return repr(("executor_fwd",
                 compile_cache.symbol_fingerprint(exe._symbol),
                 False) + exe._tier_key_parts(False))


def test_tier_enters_aot_key_and_calibration_too(deploy_pred):
    base_key = _exec_key(deploy_pred)
    b16 = _exec_key(deploy_pred.with_precision("bf16"))
    assert base_key != b16 and "tier=bf16" in b16
    table = precision.calibrate(deploy_pred, [{"data": _fixed_input()}])
    q1 = _exec_key(deploy_pred.with_precision("int8", calibration=table))
    q2 = _exec_key(deploy_pred.with_precision("int8"))
    assert q1 != q2 and table.fingerprint() in q1


def test_recalibration_moves_table_key_and_drift_baseline(deploy_pred):
    """ISSUE 16 satellite: a re-calibration moves the CalibrationTable
    fingerprint, the int8 twin's AOT logical key, AND the quality plane's
    drift-baseline export together — the serving executable and the live
    drift comparison can never disagree about which table is current."""
    t1 = precision.calibrate(deploy_pred, [{"data": _fixed_input(seed=3)}])
    t2 = precision.calibrate(deploy_pred,
                             [{"data": _fixed_input(seed=4) * 2.0}])
    assert t1.fingerprint() != t2.fingerprint()
    twin1 = deploy_pred.with_precision("int8", calibration=t1)
    twin2 = twin1.with_precision("int8", calibration=t2)
    k1, k2 = _exec_key(twin1), _exec_key(twin2)
    assert k1 != k2
    assert t1.fingerprint() in k1 and t2.fingerprint() in k2

    # the drift-baseline export is empty until the plan lowers, then keyed
    # to exactly the ranges of the table the executable was built from
    assert twin1.int8_sites == {}
    twin1._exec._opt_plan(False)
    sites1 = twin1.int8_sites
    assert sites1
    for d in sites1.values():
        assert t1.range(d["input"]) == (d["lo"], d["hi"])
    # the rebuilt twin re-stashes from the NEW table
    twin2._exec._opt_plan(False)
    sites2 = twin2.int8_sites
    assert set(sites2) == set(sites1)
    assert any(sites2[s] != sites1[s] for s in sites2)
    for d in sites2.values():
        assert t2.range(d["input"]) == (d["lo"], d["hi"])

    # and re-anchoring the plane with the rebuilt twin's export swaps the
    # calibrated ranges the live sketches compare against
    from mxnet_tpu.telemetry import qualityplane

    p = qualityplane.QualityPlane()
    p.set_drift_baseline(sites1)
    site = next(iter(sites1))
    assert p.status()["drift"][site]["calib"] \
        == [sites1[site]["lo"], sites1[site]["hi"]]
    p.set_drift_baseline(sites2)
    assert p.status()["drift"][site]["calib"] \
        == [sites2[site]["lo"], sites2[site]["hi"]]


def test_contract_drift_moves_everything_together(deploy_pred, monkeypatch):
    """ISSUE 15 satellite: bump SENSITIVITY_VERSION and the precision-pass
    fingerprint, the AOT logical key, and numerics.contract_fingerprint()
    must all move together — a stale executable misses cleanly."""
    old_contract = numerics.contract_fingerprint()
    old_tier_fp = precision.tier_fingerprint("bf16")
    old_key = _exec_key(deploy_pred.with_precision("bf16"))
    assert old_contract in old_tier_fp and old_tier_fp in old_key

    monkeypatch.setattr(numerics, "SENSITIVITY_VERSION",
                        numerics.SENSITIVITY_VERSION + 1)
    new_contract = numerics.contract_fingerprint()
    new_tier_fp = precision.tier_fingerprint("bf16")
    new_key = _exec_key(deploy_pred.with_precision("bf16"))
    assert new_contract != old_contract
    assert new_tier_fp != old_tier_fp and new_contract in new_tier_fp
    assert new_key != old_key and new_tier_fp in new_key
    # the environment fingerprint's "numerics" entry moves too (the other
    # half of the clean-miss story)
    assert compile_cache._env_fingerprint()["numerics"] == new_contract


def test_precision_plan_describes_structural_plan(deploy_pred):
    """The CastPlan contract surface stays defined over the fp32 graph the
    tier rewrites — identical fingerprints on the twin and its sibling."""
    twin = deploy_pred.with_precision("bf16")
    assert twin.precision_plan().fingerprint() \
        == deploy_pred.precision_plan().fingerprint()


def test_pass_stats_append_tier_rows(deploy_pred):
    twin = deploy_pred.with_precision("bf16")
    twin._exec._opt_plan(False)
    passes = [r["pass"] for r in twin.pass_stats()["eval"]["passes"]]
    assert passes[-2:] == ["fold_conv_affine", "bf16_cast"]


def test_set_precision_tier_requires_pass_layer(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "0")
    sym, params, shapes = deploy_twin_checkpoint(batch=2, image=16)
    pred = Predictor(sym, params, shapes)
    with pytest.raises(ValueError, match="graph-pass layer"):
        pred._exec.set_precision_tier("bf16")


# -- serving surface ---------------------------------------------------------


def test_warmup_rows_carry_precision_tier():
    from mxnet_tpu import serving
    from mxnet_tpu.serving.bucketing import BucketLadder
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    eng = serving.Engine(sym, params, {"data": (8,)},
                         ladder=BucketLadder((1, 2)), start=False)
    try:
        report = eng.warmup()
        assert report and all(r["precision_tier"] == "fp32" for r in report)
        stats = eng.stats()
        assert stats["warmup"]["precision_tier"] == "fp32"
        assert stats["precision_tier"] == "fp32"
    finally:
        eng.close()


def test_warmup_rows_carry_bf16_tier(monkeypatch):
    from mxnet_tpu import serving
    from mxnet_tpu.serving.bucketing import BucketLadder

    monkeypatch.setenv("MXNET_PRECISION_TIER", "bf16")
    sym, params, shapes = deploy_twin_checkpoint(batch=2, image=16)
    eng = serving.Engine(sym, params, {"data": shapes["data"][1:]},
                         ladder=BucketLadder((2,)), start=False)
    try:
        report = eng.warmup()
        assert report and all(r["precision_tier"] == "bf16" for r in report)
        assert eng.stats()["warmup"]["precision_tier"] == "bf16"
        assert eng.stats()["precision_tier"] == "bf16"
    finally:
        eng.close()
