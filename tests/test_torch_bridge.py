"""Torch plugin bridge tests (reference plugin/torch + python/mxnet/torch.py;
reference gpu tests exercised TorchModule/TorchCriterion inside graphs)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu import th


def test_th_functions_match_torch():
    x = np.random.RandomState(0).rand(3, 4).astype(np.float32)
    out = th.exp(nd.array(x))
    np.testing.assert_allclose(out.asnumpy(), np.exp(x), rtol=1e-6)
    a, b = np.random.rand(2, 3, 4).astype(np.float32)
    np.testing.assert_allclose(
        th.mm(nd.array(a), nd.array(b.T)).asnumpy(), a @ b.T, rtol=1e-5)
    # kwargs + non-array args pass through
    np.testing.assert_allclose(
        th.clamp(nd.array(x), 0.2, 0.8).asnumpy(), np.clip(x, 0.2, 0.8))
    tk = th.topk(nd.array(x), 2)
    assert tk[0].shape == (3, 2)


def test_to_from_torch_roundtrip():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    t = th.to_torch(nd.array(x))
    assert isinstance(t, torch.Tensor)
    np.testing.assert_allclose(th.from_torch(t).asnumpy(), x)


def test_torch_module_forward_matches_torch():
    tnet = torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.Tanh(), torch.nn.Linear(16, 4))
    bridged = th.TorchModule(tnet)
    x = np.random.RandomState(1).rand(5, 8).astype(np.float32)
    out = bridged(nd.array(x)).asnumpy()
    with torch.no_grad():
        ref = tnet(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_torch_module_trains_under_autograd():
    """Gradients flow through autograd.record into framework-owned params,
    and a plain SGD step reduces a torch-computed loss (the reference plugin's
    whole point: torch layers as first-class graph citizens)."""
    torch.manual_seed(0)
    tnet = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Tanh(),
                               torch.nn.Linear(8, 1))
    bridged = th.TorchModule(tnet)
    rng = np.random.RandomState(0)
    X = rng.rand(32, 4).astype(np.float32)
    Y = (X.sum(axis=1, keepdims=True) * 0.5).astype(np.float32)

    losses = []
    for _ in range(40):
        x, y = nd.array(X), nd.array(Y)
        with autograd.record():
            pred = bridged(x)
            loss = ((pred - y) ** 2).mean()
        loss.backward()
        for p in bridged.params.values():
            p[:] = p - 0.1 * p.grad
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_torch_criterion():
    crit = th.TorchCriterion(torch.nn.MSELoss())
    x = np.random.RandomState(2).rand(6, 3).astype(np.float32)
    y = np.zeros((6, 3), np.float32)
    xin = nd.array(x)
    xin.attach_grad()
    with autograd.record():
        loss = crit(xin, nd.array(y))
    loss.backward()
    np.testing.assert_allclose(float(loss.asnumpy()), (x ** 2).mean(),
                               rtol=1e-5)
    np.testing.assert_allclose(xin.grad.asnumpy(), 2 * x / x.size, rtol=1e-4)


def test_torch_module_dropout_eval_deterministic():
    """is_train=False must disable dropout (review finding: is_train was
    ignored, making inference stochastic)."""
    tnet = torch.nn.Sequential(torch.nn.Linear(4, 4), torch.nn.Dropout(0.5))
    bridged = th.TorchModule(tnet)
    x = nd.array(np.random.RandomState(0).rand(8, 4).astype(np.float32))
    a = bridged(x).asnumpy()
    b = bridged(x).asnumpy()
    np.testing.assert_array_equal(a, b)


def test_torch_module_bn_stats_not_mutated_at_inference():
    """Inference (and shape inference) must not touch BatchNorm running
    stats (review finding: infer_shape ran the live module on zeros)."""
    bn = torch.nn.BatchNorm1d(4)
    bridged = th.TorchModule(bn)
    x = nd.array(np.random.RandomState(0).rand(8, 4).astype(np.float32) + 3)
    bridged(x)  # inference call, no autograd.record
    np.testing.assert_array_equal(bn.running_mean.numpy(), np.zeros(4))
    # training DOES update stats (once, not twice)
    with autograd.record():
        out = bridged(x)
    out.backward()
    expected = 0.1 * th.to_torch(x).float().mean(0).numpy()
    np.testing.assert_allclose(bn.running_mean.numpy(), expected, rtol=1e-4)


def test_torch_module_frozen_params_still_get_grads():
    """Framework-owned params train even if the torch module had
    requires_grad=False (review finding: grad flag set after forward)."""
    lin = torch.nn.Linear(3, 2)
    for p in lin.parameters():
        p.requires_grad_(False)
    bridged = th.TorchModule(lin)
    x = nd.array(np.random.RandomState(0).rand(4, 3).astype(np.float32))
    with autograd.record():
        loss = (bridged(x) ** 2).sum()
    loss.backward()
    assert any(np.abs(p.grad.asnumpy()).sum() > 0
               for p in bridged.params.values())


def test_torch_module_wrap_twice_no_alias():
    """Wrapping the same torch module twice must not alias registrations
    (review finding: registry keyed by id(module))."""
    lin = torch.nn.Linear(3, 3)
    b1 = th.TorchModule(lin, num_data=1)
    b2 = th.TorchModule(lin, num_data=1)
    assert b1._key != b2._key
    x = nd.array(np.eye(3, dtype=np.float32))
    np.testing.assert_allclose(b1(x).asnumpy(), b2(x).asnumpy(), rtol=1e-6)


def test_torch_embedding_module():
    """Integer-input modules work (shape probe falls back to long zeros) and
    integer inputs do NOT truncate the float output (review finding:
    default infer_type propagated in_type[0] to the output)."""
    emb = torch.nn.Embedding(10, 6)
    bridged = th.TorchModule(emb, input_dtypes=["int64"])
    idx_np = np.array([[1, 2], [3, 4]])
    out = bridged(nd.array(idx_np.astype(np.int32), dtype="int32"))
    assert out.shape == (2, 2, 6)
    assert np.dtype(out.dtype) == np.float32
    with torch.no_grad():
        ref = emb(torch.from_numpy(idx_np)).numpy()
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)


def test_torch_module_close_unregisters():
    """Per-instance registrations are released (review finding: leak)."""
    lin = torch.nn.Linear(2, 2)
    b = th.TorchModule(lin)
    key = b._key
    assert key in mx.operator.get_all_registered_operators()
    b.close()
    assert key not in mx.operator.get_all_registered_operators()
