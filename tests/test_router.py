"""SLO-policy serving router tests (ISSUE 17) —
``mxnet_tpu/serving/{model_registry,policy,router}.py`` plus the
``SLOMonitor.burn_rates()`` read path (the satellite API fix): registry
twin construction and int8 seed-trace calibration, priority routing with
the reply tier-label contract, the degrade/restore hysteresis state
machine under a synthetic clock, the off-path invariance guarantees, the
quality-plane interaction (a router-downgraded request still
shadow-samples against fp32 under the right tier label) and the
/statusz ``"routers"`` mirror."""
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import serving
from mxnet_tpu import telemetry
from mxnet_tpu.serving import policy as rpolicy
from mxnet_tpu.telemetry import instrument as tin
from mxnet_tpu.telemetry import ops_server, qualityplane, slo
from mxnet_tpu.test_utils import tiny_mlp_checkpoint


def _register(reg=None, name="m", tiers=("fp32", "bf16"), **kw):
    sym, params = tiny_mlp_checkpoint()
    kw.setdefault("ladder", serving.BucketLadder((1, 2)))
    kw.setdefault("max_wait_ms", 2.0)
    kw.setdefault("max_queue", 64)
    return (reg or serving.ModelRegistry()).register(
        name, sym, params, {"data": (8,)}, tiers=tiers, **kw)


def _x(n=1, seed=0):
    return {"data": np.random.RandomState(seed).rand(n, 8)
            .astype(np.float32)}


@pytest.fixture
def clean_env(monkeypatch):
    """No ambient router/SLO/telemetry configuration."""
    for var in ("MXNET_ROUTER_POLICY", "MXNET_ROUTER_BURN_HIGH",
                "MXNET_ROUTER_BURN_LOW", "MXNET_ROUTER_HOLD_S",
                "MXNET_ROUTER_INTERVAL_S", "MXNET_ROUTER_PRESSURE",
                "MXNET_SLO", "MXNET_TELEMETRY"):
        monkeypatch.delenv(var, raising=False)
    tin._reset_for_tests()
    yield
    tin._reset_for_tests()


# -- model registry -----------------------------------------------------------
class TestModelRegistry:
    def test_twins_share_weights_and_carry_tiers(self, clean_env):
        reg = serving.ModelRegistry()
        model = _register(reg)
        assert model.tiers == ("fp32", "bf16")
        assert model.native_tier == "fp32"
        assert reg.names() == ["m"]
        # twins come off ONE base predictor: same weight device buffers
        fp32, bf16 = model.twin("fp32"), model.twin("bf16")
        assert fp32._exec.precision_tier in (None, "fp32")
        assert bf16._exec.precision_tier == "bf16"
        with pytest.raises(KeyError):
            model.twin("int8")
        reg.unregister("m")
        with pytest.raises(KeyError):
            reg.get("m")

    def test_tier_validation(self, clean_env):
        with pytest.raises(ValueError):
            _register(tiers=("fp32", "fp8"))
        with pytest.raises(ValueError):
            _register(tiers=("fp32", "bf16", "bf16"))
        with pytest.raises(ValueError):
            _register(tiers=())

    def test_int8_without_calibration_refused(self, clean_env):
        with pytest.raises(ValueError, match="calibration|seed_trace"):
            _register(tiers=("fp32", "int8"))

    def test_int8_seed_trace_autocalibrates(self, clean_env):
        model = _register(tiers=("fp32", "int8"),
                          seed_trace=[_x(2, seed=s) for s in range(3)])
        assert model.calibration is not None
        twin = model.twin("int8")
        assert twin._exec.precision_tier == "int8"
        # the twin actually serves (the calibrated rewrite compiled)
        out = twin.forward(**_x(1))
        assert tuple(out[0].shape) == (1, 4)

    def test_build_engine_respecializes_shared_twin(self, clean_env):
        model = _register()
        eng = model.build_engine("bf16", name="reg-bf16", start=True)
        try:
            eng.predict(_x(1))
            assert eng.stats()["precision_tier"] == "bf16"
        finally:
            eng.close()


# -- routing + tier-label contract --------------------------------------------
class TestRouterRouting:
    def test_priority_routes_native_and_labels_tier(self, clean_env):
        model = _register()
        r = serving.Router(model, policy="degrade", name="rt-route")
        try:
            req = r.submit(_x(1), priority="paid")
            out = req.result(30.0)
            assert out[0].shape == (1, 4)
            assert req.priority == "paid"
            assert req.routed_tier == "fp32" and req.tier == "fp32"
            assert req.engine_name.startswith("rt-route-fp32")
            # klass naming a known priority is the priority (loadgen path)
            req = r.submit(_x(1), klass="best_effort")
            req.result(30.0)
            assert req.priority == "best_effort" and req.tier == "fp32"
            # unknown klass falls back to the default (least protected)
            req = r.submit(_x(1), klass="37")
            req.result(30.0)
            assert req.priority == "best_effort"
            st = r.stats()
            assert st["router"]["priorities"]["paid"]["requests"] == 1
            assert st["router"]["priorities"]["best_effort"]["requests"] == 2
            assert st["downgrades"] == 0
            assert st["precision_tier"] == "fp32"
            assert st["router"]["route"] == {"paid": "fp32",
                                             "best_effort": "fp32"}
        finally:
            r.close()

    def test_forced_downgrade_serves_cheap_twin(self, clean_env):
        model = _register()
        r = serving.Router(model, policy="degrade", name="rt-dg")
        try:
            with r._mu:
                r._route["best_effort"] = r._degrade_tier
            req = r.submit(_x(1), priority="best_effort")
            req.result(30.0)
            assert req.routed_tier == "bf16" and req.tier == "bf16"
            # protected traffic keeps the native pool
            req = r.submit(_x(1), priority="paid")
            req.result(30.0)
            assert req.tier == "fp32"
            st = r.stats()
            assert st["router"]["priorities"]["best_effort"][
                "downgrades"] == 1
            assert st["router"]["priorities"]["paid"]["downgrades"] == 0
        finally:
            r.close()

    def test_shed_counted_per_priority(self, clean_env):
        model = _register(max_queue=1, max_wait_ms=50.0)
        # start=False: no device loop drains the queue, so the second
        # submit deterministically overflows the bounded admission gate
        r = serving.Router(model, policy="shed", name="rt-shed",
                           start=False)
        try:
            first = r.submit(_x(1), priority="best_effort")
            with pytest.raises(serving.ServerBusy):
                for _ in range(3):
                    r.submit(_x(1), priority="best_effort")
            st = r.stats()
            assert st["router"]["priorities"]["best_effort"]["sheds"] >= 1
            assert st["sheds"] == st["router"]["priorities"][
                "best_effort"]["sheds"]
            first.cancel()
        finally:
            r.close()

    def test_needs_degradation_target(self, clean_env):
        sym, params = tiny_mlp_checkpoint()
        model = serving.ModelRegistry().register(
            "solo", sym, params, {"data": (8,)}, tiers=("fp32",))
        with pytest.raises(ValueError, match="degradation target"):
            serving.Router(model)

    def test_statusz_mirrors_router_block(self, clean_env):
        model = _register()
        r = serving.Router(model, policy="degrade", name="rt-statusz",
                           start=False)
        try:
            ops_server.register_router(r)
            status = ops_server._statusz()
            assert "rt-statusz" in status["routers"]
            blk = status["routers"]["rt-statusz"]["router"]
            assert blk["policy"]["mode"] == "degrade"
            assert blk["native_tier"] == "fp32"
            assert blk["degrade_tier"] == "bf16"
        finally:
            r.close()
        assert "rt-statusz" not in ops_server._statusz()["routers"]


# -- policy state machine -----------------------------------------------------
class TestDegradePolicy:
    CFG = dict(burn_high=2.0, burn_low=0.5, hold_s=5.0, pressure=0.5)

    def _policy(self, mode="degrade"):
        cfg = rpolicy.PolicyConfig(mode=mode, **self.CFG)
        return rpolicy.DegradePolicy(cfg, ("paid", "best_effort"),
                                     protected=("paid",))

    def test_degrade_on_burn_protects_paid(self):
        p = self._policy()
        assert p.step({"burn": 0.1, "pressure": 0.0}, now=0.0) == []
        acts = p.step({"burn": 3.0, "pressure": 0.0}, now=1.0)
        assert acts == [("degrade", "best_effort")]  # never paid
        # already degraded: overload again is a no-op, not a re-degrade
        assert p.step({"burn": 3.0, "pressure": 0.0}, now=2.0) == []

    def test_degrade_on_pressure_without_monitor(self):
        p = self._policy()
        acts = p.step({"burn": None, "pressure": 0.9}, now=0.0)
        assert acts == [("degrade", "best_effort")]

    def test_hysteresis_band_holds_then_restores(self):
        p = self._policy()
        p.step({"burn": 3.0, "pressure": 0.0}, now=0.0)
        # in-band (below burn_high, above burn_low): hold, no restore ever
        for t in (1.0, 2.0, 30.0):
            assert p.step({"burn": 1.0, "pressure": 0.0}, now=t) == []
        # calm, but not yet for hold_s
        assert p.step({"burn": 0.1, "pressure": 0.0}, now=31.0) == []
        assert p.step({"burn": 0.1, "pressure": 0.0}, now=35.0) == []
        # a blip inside the hold window resets the calm clock
        assert p.step({"burn": 1.0, "pressure": 0.0}, now=35.5) == []
        assert p.step({"burn": 0.1, "pressure": 0.0}, now=36.0) == []
        assert p.step({"burn": 0.1, "pressure": 0.0}, now=40.0) == []
        acts = p.step({"burn": 0.1, "pressure": 0.0}, now=41.5)
        assert acts == [("restore", "best_effort")]
        assert p.degraded == {}

    def test_calm_requires_low_pressure_too(self):
        p = self._policy()
        p.step({"burn": None, "pressure": 0.9}, now=0.0)
        # pressure must fall below half the trigger level, not just below it
        assert p.step({"burn": None, "pressure": 0.3}, now=1.0) == []
        assert p.step({"burn": None, "pressure": 0.3}, now=100.0) == []
        assert p.step({"burn": None, "pressure": 0.1}, now=101.0) == []
        acts = p.step({"burn": None, "pressure": 0.1}, now=107.0)
        assert acts == [("restore", "best_effort")]

    def test_shed_mode_is_a_policy_noop(self):
        p = self._policy(mode="shed")
        assert p.step({"burn": 99.0, "pressure": 1.0}, now=0.0) == []
        assert p.degraded == {}

    def test_config_validation_and_env(self, monkeypatch):
        with pytest.raises(ValueError):
            rpolicy.PolicyConfig(mode="static")
        with pytest.raises(ValueError):
            rpolicy.PolicyConfig(burn_high=1.0, burn_low=2.0)
        monkeypatch.setenv("MXNET_ROUTER_POLICY", "sideways")
        monkeypatch.setenv("MXNET_ROUTER_BURN_HIGH", "lots")
        monkeypatch.setenv("MXNET_ROUTER_PRESSURE", "0.25")
        cfg = rpolicy.config_from_env()
        # never-crash contract: unknown mode / malformed float -> defaults
        assert cfg.mode == "degrade"
        assert cfg.burn_high == 1.0
        assert cfg.pressure == 0.25

    def test_router_policy_tick_applies_transitions(self, clean_env):
        model = _register()
        # start=False: the test owns the clock — no live loop races it
        r = serving.Router(model, policy="degrade", name="rt-tick",
                           start=False)
        try:
            r._policy._clear_since = None
            acts = r._policy.step({"burn": 5.0, "pressure": 0.0}, now=10.0)
            assert acts == [("degrade", "best_effort")]
            # the tick path end-to-end (pressure 0 + no monitor = calm,
            # but hold_s blocks the restore): route stays degraded
            with r._mu:
                r._route["best_effort"] = r._degrade_tier
            r._policy_tick(now=11.0)
            st = r.stats()
            assert st["router"]["policy"]["degraded"] == ["best_effort"]
            assert st["router"]["route"]["best_effort"] == "bf16"
        finally:
            r.close()


# -- off-path invariance ------------------------------------------------------
class TestOffPath:
    def _key(self, pred):
        from mxnet_tpu import compile_cache

        exe = pred._exec
        return repr(("executor_fwd",
                     compile_cache.symbol_fingerprint(exe._symbol),
                     False) + exe._tier_key_parts(False))

    def test_router_env_never_moves_aot_key(self, clean_env, monkeypatch):
        from mxnet_tpu.predictor import Predictor

        sym, params = tiny_mlp_checkpoint()
        key_off = self._key(Predictor(sym, params, {"data": (1, 8)}))
        monkeypatch.setenv("MXNET_ROUTER_POLICY", "degrade")
        monkeypatch.setenv("MXNET_ROUTER_PRESSURE", "0.1")
        monkeypatch.setenv("MXNET_ROUTER_BURN_HIGH", "0.5")
        key_on = self._key(Predictor(sym, params, {"data": (1, 8)}))
        assert key_on == key_off

    def test_telemetry_off_no_router_metrics(self, clean_env):
        assert telemetry.router_probe("nope") is None
        model = _register()
        r = serving.Router(model, policy="degrade", name="rt-notelem")
        try:
            r.predict(_x(1), priority="paid")
            assert r._probe is None
        finally:
            r.close()
        for m in ("router_requests_total", "router_downgrades_total",
                  "router_sheds_total", "router_policy_transitions_total",
                  "router_degraded"):
            assert tin.registry().get(m) is None

    def test_telemetry_on_counts_routes(self, clean_env, monkeypatch,
                                         tmp_path):
        monkeypatch.setenv("MXNET_TELEMETRY", "1")
        monkeypatch.setenv("MXNET_TELEMETRY_FILE",
                           str(tmp_path / "t.jsonl"))
        tin._reset_for_tests()
        model = _register()
        r = serving.Router(model, policy="degrade", name="rt-telem")
        try:
            with r._mu:
                r._route["best_effort"] = r._degrade_tier
            r.predict(_x(1), priority="paid")
            r.predict(_x(1), priority="best_effort")
        finally:
            r.close()
        reg = tin.registry()
        assert reg.total("router_requests_total", 0.0) == 2.0
        assert reg.total("router_downgrades_total", 0.0) == 1.0


# -- burn-rate read path (satellite 2) ---------------------------------------
class TestBurnRates:
    def _monitor(self):
        return slo.SLOMonitor(slo.parse_objectives(
            "paid:p95:50:2,best_effort:p95:100:2"))

    def test_burn_rates_shape_and_breach_edge(self):
        m = self._monitor()
        t0 = 1000.0
        for i in range(20):
            m.record(0.200, klass="paid", now=t0 + i * 0.01)  # all late
        rates = m.burn_rates(now=t0 + 2.0)
        assert set(rates) == {"paid:p95:50ms", "best_effort:p95:100ms"}
        paid = rates["paid:p95:50ms"]
        # every sample blew the 50 ms target: the full error budget burns
        assert paid["burn_rate"] == pytest.approx(1.0 / 0.05, rel=0.01)
        assert paid["breached"] is True and paid["breaches"] >= 1
        # the ok->breach edge fired during the recording window, so its
        # age is bounded by the synthetic clock span
        assert 0.0 <= paid["last_breach_age_s"] <= 2.5
        assert paid["last_breach_unix_ts"] is not None
        # the idle class never evaluated: all-None snapshot, no breach
        be = rates["best_effort:p95:100ms"]
        assert be["burn_rate"] is None and be["breached"] is False
        assert be["last_breach_age_s"] is None
        # status() carries the same breach-edge bookkeeping
        for o in m.status()["objectives"]:
            assert "last_breach_age_s" in o and "last_breach_unix_ts" in o

    def test_burn_rates_cached_within_throttle(self):
        m = self._monitor()
        t0 = 2000.0
        for i in range(10):
            m.record(0.010, klass="paid", now=t0 + i * 0.01)
        r1 = m.burn_rates(now=t0 + 1.5)
        checked = r1["paid:p95:50ms"]["checked_at"]
        assert checked is not None
        # inside the 1/s evaluation throttle: the cached snapshot comes
        # back without re-walking quantiles (same checked_at stamp)
        for _ in range(5):
            m.record(0.010, klass="paid", now=t0 + 1.6)
        r2 = m.burn_rates(now=t0 + 1.9)
        assert r2["paid:p95:50ms"]["checked_at"] == checked
        # past the throttle the snapshot refreshes
        r3 = m.burn_rates(now=t0 + 3.0)
        assert r3["paid:p95:50ms"]["checked_at"] > checked
        # healthy traffic: zero burn
        assert r3["paid:p95:50ms"]["burn_rate"] == pytest.approx(0.0)
        assert r3["paid:p95:50ms"]["met"] is True

    def test_router_shares_one_monitor(self, clean_env, monkeypatch):
        monkeypatch.setenv("MXNET_SLO", "paid:p95:500:2")
        model = _register()
        r = serving.Router(model, policy="degrade", name="rt-slo")
        try:
            monitors = {id(e._slo) for e in r.engines()}
            assert monitors == {id(r._slo)}
            assert all(e._shared_slo for e in r.engines())
            r.predict(_x(1), priority="paid")
            rates = r._slo.burn_rates()
            assert any(k.startswith("paid:p95") for k in rates)
            sig = r._signals(time.monotonic())
            assert set(sig) == {"burn", "pressure"}
        finally:
            r.close()


# -- quality plane interaction (satellite 3) ----------------------------------
class TestRouterQualityPlane:
    def test_downgraded_request_shadow_samples_as_bf16(self, clean_env,
                                                       monkeypatch):
        monkeypatch.setenv("MXNET_QUALITYPLANE", "1")
        monkeypatch.setenv("MXNET_QUALITY_SAMPLE", "1.0")
        qualityplane._reset_for_tests()
        model = _register()
        r = serving.Router(model, policy="degrade", name="rt-qual")
        try:
            with r._mu:
                r._route["best_effort"] = r._degrade_tier
            for i in range(6):
                req = r.submit(_x(1, seed=i), priority="best_effort")
                req.result(30.0)
                assert req.tier == "bf16"
            deadline = time.monotonic() + 60.0
            q = qualityplane.status()
            while time.monotonic() < deadline and not (
                    q and q["rows"] and q["divergence"]):
                time.sleep(0.05)
                q = qualityplane.status()
            # the downgraded replies landed in tier_divergence under the
            # tier that SERVED them — not the router's native tier
            assert q["divergence"] and "bf16" in q["divergence"]
            assert "fp32" not in (q["divergence"] or {})
            assert q["sampled"] >= 1
            # the router's stats surface exposes the same plane
            assert r.stats()["quality"]["seen"] == q["seen"]
        finally:
            r.close()
            qualityplane._reset_for_tests()
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("mxnet-quality")]
