"""Round-5 example-family nightly tests: the detection deployment demo
(checkpoint → detections through export + predictor, VERDICT r4 item 6)."""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
DEMO = os.path.join(REPO, "examples", "rcnn", "demo.py")


@pytest.mark.parametrize("model", ["rfcn", "frcnn"])
def test_demo_checkpoint_to_detections(model, tmp_path):
    """One command, checkpoint → boxes: quick-train a tiny synthetic
    checkpoint, rebuild the inference twin, export the deployment pair
    (symbol JSON + params), reload it through ``predictor.create`` and emit
    decoded+NMS'd detections (reference example/rcnn/demo.py + test.py)."""
    out = tmp_path / ("dets_%s.npy" % model)
    params = tmp_path / ("ckpt_%s.params" % model)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    res = subprocess.run(
        [sys.executable, DEMO, "--model", model, "--quick-train", "8",
         "--params", str(params), "--score-thresh", "0.01",
         "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=900, cwd=tmp_path)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "deployment pair:" in res.stdout, res.stdout
    assert params.exists(), "checkpoint not saved"
    # the deployment pair is on disk (symbol JSON + params blob)
    prefix = str(params)[: -len(".params")] + "-deploy"
    assert os.path.exists(prefix + "-symbol.json"), res.stdout
    assert os.path.exists(prefix + "-0000.params"), res.stdout
    dets = np.load(out)
    # (K, 6) [cls score x1 y1 x2 y2]; coordinates inside the image
    assert dets.ndim == 2 and dets.shape[1] == 6, dets.shape
    if len(dets):
        assert (dets[:, 1] >= 0.01 - 1e-6).all()
        assert (dets[:, 2:] >= 0).all()
