"""Legacy rnn package tests — mirrors reference
tests/python/unittest/test_rnn.py (cell unroll shapes, fused-vs-unfused
consistency, bidirectional/residual/zoneout, BucketSentenceIter)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu import rnn as mrnn


def _bind_run(outputs, length=3, batch=2, dim=4, seed=0, **var_shapes):
    """simple_bind an unrolled graph and run forward with random inputs."""
    out = sym.Group(outputs) if isinstance(outputs, list) else outputs
    shapes = {"data": (batch, length, dim)}
    shapes.update(var_shapes)
    exe = out.simple_bind(**shapes)
    rng = np.random.RandomState(seed)
    feed = {"data": nd.array(rng.randn(batch, length, dim).astype(np.float32))}
    outs = exe.forward(is_train=False, **feed)
    return exe, [o.asnumpy() for o in outs]


def test_rnn_cell_unroll_shapes():
    cell = mrnn.RNNCell(8, prefix="rnn_")
    outputs, states = cell.unroll(3, inputs=sym.Variable("data"), merge_outputs=False)
    assert len(outputs) == 3
    assert sorted(cell.params._params.keys()) == [
        "rnn_h2h_bias", "rnn_h2h_weight", "rnn_i2h_bias", "rnn_i2h_weight",
    ]
    exe, outs = _bind_run(outputs)
    assert all(o.shape == (2, 8) for o in outs)


def test_lstm_gru_unroll_merged():
    for cell, nstates in [(mrnn.LSTMCell(8, prefix="lstm_"), 2), (mrnn.GRUCell(8, prefix="gru_"), 1)]:
        outputs, states = cell.unroll(3, inputs=sym.Variable("data"), merge_outputs=True)
        assert len(states) == nstates
        exe, outs = _bind_run(outputs)
        assert outs[0].shape == (2, 3, 8)


def test_sequential_stack():
    stack = mrnn.SequentialRNNCell()
    stack.add(mrnn.LSTMCell(8, prefix="l0_"))
    stack.add(mrnn.LSTMCell(8, prefix="l1_"))
    outputs, states = stack.unroll(3, inputs=sym.Variable("data"), merge_outputs=True)
    assert len(states) == 4
    exe, outs = _bind_run(outputs)
    assert outs[0].shape == (2, 3, 8)


def test_bidirectional():
    cell = mrnn.BidirectionalCell(
        mrnn.LSTMCell(8, prefix="l_"), mrnn.LSTMCell(8, prefix="r_"), output_prefix="bi_"
    )
    outputs, states = cell.unroll(3, inputs=sym.Variable("data"), merge_outputs=True)
    exe, outs = _bind_run(outputs)
    assert outs[0].shape == (2, 3, 16)


def test_residual_cell():
    cell = mrnn.ResidualCell(mrnn.RNNCell(4, prefix="res_"))
    outputs, states = cell.unroll(2, inputs=sym.Variable("data"), merge_outputs=False)
    exe, outs = _bind_run(outputs, length=2, dim=4)
    assert outs[0].shape == (2, 4)


def test_zoneout_cell_runs():
    cell = mrnn.ZoneoutCell(mrnn.RNNCell(4, prefix="zo_"), zoneout_outputs=0.3, zoneout_states=0.3)
    outputs, states = cell.unroll(3, inputs=sym.Variable("data"), merge_outputs=False)
    exe, outs = _bind_run(outputs, dim=4)
    assert outs[0].shape == (2, 4)


def test_unpack_pack_roundtrip_lstm():
    cell = mrnn.LSTMCell(4, prefix="lstm_")
    rng = np.random.RandomState(0)
    args = {
        "lstm_i2h_weight": rng.randn(16, 5).astype(np.float32),
        "lstm_i2h_bias": rng.randn(16).astype(np.float32),
        "lstm_h2h_weight": rng.randn(16, 4).astype(np.float32),
        "lstm_h2h_bias": rng.randn(16).astype(np.float32),
    }
    unpacked = cell.unpack_weights(dict(args))
    assert "lstm_i2h_i_weight" in unpacked and "lstm_h2h_o_bias" in unpacked
    packed = cell.pack_weights(unpacked)
    for k, v in args.items():
        np.testing.assert_allclose(packed[k], v, rtol=1e-6)


@pytest.mark.parametrize("mode", ["rnn_tanh", "lstm", "gru"])
def test_fused_matches_unfused(mode):
    """The reference's canonical consistency check (test_rnn.py test_fused):
    FusedRNNCell and its unfuse() stack must produce identical outputs when
    weights are converted with unpack_weights."""
    T, B, D, H, L = 3, 2, 5, 4, 2
    fused = mrnn.FusedRNNCell(H, num_layers=L, mode=mode, prefix="f_", get_next_state=False)
    f_out, _ = fused.unroll(T, inputs=sym.Variable("data"), merge_outputs=True)
    f_exe = f_out.simple_bind(data=(B, T, D))

    rng = np.random.RandomState(0)
    from mxnet_tpu.ops.rnn import rnn_param_size

    psize = rnn_param_size(mode, D, H, L, False)
    params = (rng.rand(psize).astype(np.float32) - 0.5) * 0.4
    x = rng.randn(B, T, D).astype(np.float32)

    (f_y,) = f_exe.forward(is_train=False, data=nd.array(x), f_parameters=nd.array(params))

    unfused = fused.unfuse()
    u_out, _ = unfused.unroll(T, inputs=sym.Variable("data"), merge_outputs=True)
    u_exe = u_out.simple_bind(data=(B, T, D))
    args = fused.unpack_weights({"f_parameters": nd.array(params)})
    feed = {k: nd.array(np.asarray(v)) for k, v in args.items() if k != "f_parameters"}
    (u_y,) = u_exe.forward(is_train=False, data=nd.array(x), **feed)
    np.testing.assert_allclose(f_y.asnumpy(), u_y.asnumpy(), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("mode", ["rnn_tanh", "lstm", "gru"])
def test_fused_get_next_state(mode):
    """RNN op is multi-output; get_next_state must expose final states."""
    cell = mrnn.FusedRNNCell(4, mode=mode, get_next_state=True, prefix=mode + "_")
    out, states = cell.unroll(3, inputs=sym.Variable("data"), merge_outputs=True)
    assert len(states) == (2 if mode == "lstm" else 1)
    _, out_sh, _ = out.infer_shape(data=(2, 3, 5))
    assert out_sh[0] == (2, 3, 4)
    _, st_sh, _ = states[0].infer_shape(data=(2, 3, 5))
    assert st_sh[0] == (1, 2, 4)


def test_begin_state_func_zeros_binds():
    """begin_state(func=sym.zeros) with the reference's shape-0 batch dim
    must yield a bindable graph (deferred _zeros_rows), for both unfused and
    fused cells; non-zeros funcs are rejected with a clear error."""
    cell = mrnn.LSTMCell(4, prefix="l_")
    states = cell.begin_state(func=sym.zeros)
    o, _ = cell.unroll(2, inputs=sym.Variable("data"), begin_state=states, merge_outputs=True)
    exe = o.simple_bind(data=(3, 2, 5))
    (y,) = exe.forward(is_train=False, data=nd.ones((3, 2, 5)))
    assert y.shape == (3, 2, 4)

    fused = mrnn.FusedRNNCell(4, mode="lstm", prefix="f_")
    st = fused.begin_state(func=sym.zeros)
    o2, _ = fused.unroll(3, inputs=sym.Variable("data"), begin_state=st, merge_outputs=True)
    e2 = o2.simple_bind(data=(2, 3, 5))
    (y2,) = e2.forward(is_train=False, data=nd.ones((2, 3, 5)))
    assert y2.shape == (2, 3, 4)

    with pytest.raises(mx.base.MXNetError):
        mrnn.GRUCell(4, prefix="g_").begin_state(func=sym.uniform)


def test_begin_state_func_zeros_manual_step():
    """Reference pattern: begin_state(func=sym.zeros) then step the cell
    directly — deferred states resolve against the step input."""
    cell = mrnn.LSTMCell(4, prefix="l_")
    states = cell.begin_state(func=sym.zeros)
    x = sym.Variable("x")
    out, states = cell(x, states)
    out2, _ = cell(x, states)
    exe = out2.simple_bind(x=(3, 5))
    (y,) = exe.forward(is_train=False, x=nd.ones((3, 5)))
    assert y.shape == (3, 4)


def test_rnn_unroll_auto_inputs():
    """rnn_unroll(inputs=None) auto-creates per-step input Variables
    (reference rnn.py:26)."""
    cell = mrnn.RNNCell(4, prefix="r_")
    outputs, states = mrnn.rnn_unroll(cell, 3, input_prefix="t_")
    out = sym.Group(outputs) if isinstance(outputs, list) else outputs
    args = out.list_arguments()
    assert "t_t0_data" in args and "t_t2_data" in args


def test_bucket_iter_empty_bucket():
    """A user bucket longer than every sentence must not crash construction."""
    it = mrnn.BucketSentenceIter([[1, 2], [2, 1]], batch_size=2, buckets=[3, 10],
                                 invalid_label=0)
    batches = list(it)
    assert len(batches) == 1
    assert batches[0].bucket_key == 3


def test_encode_sentences_and_bucket_iter():
    sentences = [["a", "b", "c"], ["a", "c"], ["b", "c", "a", "b"], ["a", "b"], ["c", "b"]]
    enc, vocab = mrnn.encode_sentences(sentences, invalid_label=0, start_label=1)
    assert len(vocab) >= 3
    it = mrnn.BucketSentenceIter(enc, batch_size=2, buckets=[3, 5], invalid_label=0)
    batches = list(it)
    assert batches
    for b in batches:
        assert b.bucket_key in (3, 5)
        assert b.data[0].shape == (2, b.bucket_key)
        d = b.data[0].asnumpy()
        lab = b.label[0].asnumpy()
        # label is data shifted left by one
        np.testing.assert_array_equal(lab[:, :-1], d[:, 1:])
    it.reset()
    assert len(list(it)) == len(batches)


def test_rnn_checkpoint_roundtrip(tmp_path):
    cell = mrnn.LSTMCell(4, prefix="lstm_")
    outputs, _ = cell.unroll(2, inputs=sym.Variable("data"), merge_outputs=True)
    rng = np.random.RandomState(0)
    arg_params = {
        "lstm_i2h_weight": nd.array(rng.randn(16, 5).astype(np.float32)),
        "lstm_i2h_bias": nd.array(rng.randn(16).astype(np.float32)),
        "lstm_h2h_weight": nd.array(rng.randn(16, 4).astype(np.float32)),
        "lstm_h2h_bias": nd.array(rng.randn(16).astype(np.float32)),
    }
    prefix = str(tmp_path / "model")
    mrnn.save_rnn_checkpoint(cell, prefix, 1, outputs, dict(arg_params), {})
    sym2, arg2, aux2 = mrnn.load_rnn_checkpoint(cell, prefix, 1)
    for k in arg_params:
        np.testing.assert_allclose(
            arg2[k].asnumpy(), arg_params[k].asnumpy(), rtol=1e-6
        )
