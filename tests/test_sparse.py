"""Sparse NDArray tests — mirrors reference
tests/python/unittest/test_sparse_ndarray.py (creation, cast_storage, retain,
slicing, dot) and the sparse optimizer coverage of test_optimizer.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse


def _rand_rs(shape, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    dense = rng.rand(*shape).astype(np.float32)
    mask = rng.rand(shape[0]) < density
    dense[~mask] = 0
    return dense


def _rand_csr(shape, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    dense = (rng.rand(*shape) < density) * rng.rand(*shape)
    return dense.astype(np.float32)


class TestCreation:
    def test_row_sparse_from_dense(self):
        dense = _rand_rs((8, 3))
        rs = sparse.row_sparse_array(dense)
        assert rs.stype == "row_sparse"
        assert rs.shape == (8, 3)
        np.testing.assert_allclose(rs.asnumpy(), dense, rtol=1e-6)
        nz = np.where(np.any(dense != 0, axis=1))[0]
        np.testing.assert_array_equal(rs.indices.asnumpy(), nz)

    def test_row_sparse_from_components(self):
        data = np.arange(6, dtype=np.float32).reshape(2, 3)
        rs = sparse.row_sparse_array((data, [1, 3]), shape=(5, 3))
        dense = rs.asnumpy()
        assert dense.shape == (5, 3)
        np.testing.assert_array_equal(dense[1], data[0])
        np.testing.assert_array_equal(dense[3], data[1])
        np.testing.assert_array_equal(dense[0], 0)

    def test_csr_from_dense_and_components(self):
        dense = _rand_csr((6, 5))
        cs = sparse.csr_matrix(dense)
        assert cs.stype == "csr"
        np.testing.assert_allclose(cs.asnumpy(), dense, rtol=1e-6)
        cs2 = sparse.csr_matrix(
            (cs.data.asnumpy(), cs.indices.asnumpy(), cs.indptr.asnumpy()), shape=(6, 5)
        )
        np.testing.assert_allclose(cs2.asnumpy(), dense, rtol=1e-6)

    def test_zeros(self):
        rs = sparse.zeros("row_sparse", (4, 2))
        assert rs.stype == "row_sparse" and rs.asnumpy().sum() == 0
        cs = sparse.zeros("csr", (4, 2))
        assert cs.stype == "csr" and cs.asnumpy().sum() == 0
        assert nd.zeros((4, 2), stype="row_sparse").stype == "row_sparse"
        assert nd.zeros((4, 2)).stype == "default"

    def test_csr_requires_2d(self):
        with pytest.raises(mx.MXNetError):
            sparse.zeros("csr", (4, 2, 2))

    def test_component_mismatch_raises(self):
        with pytest.raises(mx.MXNetError):
            sparse.row_sparse_array((np.zeros((2, 3), np.float32), [1]), shape=(5, 3))


class TestConversion:
    def test_tostype_roundtrip(self):
        dense = _rand_rs((8, 3))
        arr = nd.array(dense)
        rs = arr.tostype("row_sparse")
        assert rs.stype == "row_sparse"
        back = rs.tostype("default")
        assert back.stype == "default"
        np.testing.assert_allclose(back.asnumpy(), dense, rtol=1e-6)

    def test_cast_storage_csr(self):
        dense = _rand_csr((5, 7))
        cs = sparse.cast_storage(nd.array(dense), "csr")
        np.testing.assert_allclose(cs.asnumpy(), dense, rtol=1e-6)
        rs = cs.tostype("row_sparse")
        assert rs.stype == "row_sparse"
        np.testing.assert_allclose(rs.asnumpy(), dense, rtol=1e-6)


class TestOps:
    def test_retain(self):
        dense = np.arange(15, dtype=np.float32).reshape(5, 3)
        rs = sparse.row_sparse_array(dense)
        out = sparse.retain(rs, [1, 3])
        np.testing.assert_array_equal(out.indices.asnumpy(), [1, 3])
        got = out.asnumpy()
        np.testing.assert_array_equal(got[1], dense[1])
        np.testing.assert_array_equal(got[0], 0)
        np.testing.assert_array_equal(got[2], 0)

    def test_csr_slice(self):
        dense = _rand_csr((8, 4))
        cs = sparse.csr_matrix(dense)
        sl = cs[2:5]
        assert sl.shape == (3, 4)
        np.testing.assert_allclose(sl.asnumpy(), dense[2:5], rtol=1e-6)
        row = cs[3]
        np.testing.assert_allclose(row.asnumpy(), dense[3:4], rtol=1e-6)

    def test_csr_dot_dense(self):
        dense_l = _rand_csr((6, 5), density=0.4)
        rhs = np.random.RandomState(1).rand(5, 3).astype(np.float32)
        cs = sparse.csr_matrix(dense_l)
        out = sparse.dot(cs, nd.array(rhs))
        np.testing.assert_allclose(out.asnumpy(), dense_l @ rhs, rtol=1e-5)

    def test_csr_dot_dense_transpose_a(self):
        dense_l = _rand_csr((6, 5), density=0.4)
        rhs = np.random.RandomState(1).rand(6, 3).astype(np.float32)
        cs = sparse.csr_matrix(dense_l)
        out = sparse.dot(cs, nd.array(rhs), transpose_a=True)
        np.testing.assert_allclose(out.asnumpy(), dense_l.T @ rhs, rtol=1e-5)

    def test_sparse_add(self):
        a = _rand_rs((6, 3), seed=0)
        b = _rand_rs((6, 3), seed=1)
        out = sparse.row_sparse_array(a) + sparse.row_sparse_array(b)
        assert out.stype == "row_sparse"
        np.testing.assert_allclose(out.asnumpy(), a + b, rtol=1e-6)
        out2 = sparse.row_sparse_array(a) + nd.array(b)
        assert out2.stype == "default"
        np.testing.assert_allclose(out2.asnumpy(), a + b, rtol=1e-6)

    def test_scipy_interop(self):
        scipy = pytest.importorskip("scipy.sparse")
        dense = _rand_csr((5, 4))
        cs = sparse.csr_matrix(dense)
        sp = cs.asscipy()
        np.testing.assert_allclose(sp.toarray(), dense, rtol=1e-6)
        back = sparse.csr_matrix(sp)
        np.testing.assert_allclose(back.asnumpy(), dense, rtol=1e-6)

    def test_blocked_methods_raise(self):
        rs = sparse.zeros("row_sparse", (4, 2))
        with pytest.raises(mx.MXNetError):
            rs[0]
        with pytest.raises(mx.MXNetError):
            rs.astype("float16")


class TestSparseOptimizer:
    def _check_lazy(self, opt_name, **kwargs):
        from mxnet_tpu import optimizer as optmod

        shape = (6, 4)
        rng = np.random.RandomState(0)
        w0 = rng.rand(*shape).astype(np.float32)
        g_rows = np.array([1, 4])
        g_data = rng.rand(2, 4).astype(np.float32)

        opt = optmod.create(opt_name, learning_rate=0.1, **kwargs)
        w = nd.array(w0.copy())
        state = opt.create_state(0, w)
        grad = sparse.row_sparse_array((g_data, g_rows), shape=shape)
        opt.update(0, w, grad, state)
        got = w.asnumpy()

        # dense twin: same update with zero-filled grad, but only touched
        # rows should move under the lazy path
        untouched = [i for i in range(shape[0]) if i not in g_rows]
        np.testing.assert_allclose(got[untouched], w0[untouched], rtol=1e-6)
        assert not np.allclose(got[list(g_rows)], w0[list(g_rows)])
        return got

    def test_sgd_lazy_rows(self):
        self._check_lazy("sgd")
        self._check_lazy("sgd", momentum=0.9)

    def test_adam_lazy_rows(self):
        self._check_lazy("adam")

    def test_sgd_sparse_matches_dense_on_touched_rows(self):
        from mxnet_tpu import optimizer as optmod

        shape = (6, 4)
        rng = np.random.RandomState(0)
        w0 = rng.rand(*shape).astype(np.float32)
        g_rows = np.array([1, 4])
        g_data = rng.rand(2, 4).astype(np.float32)
        dense_grad = np.zeros(shape, np.float32)
        dense_grad[g_rows] = g_data

        opt1 = optmod.create("sgd", learning_rate=0.1, wd=0.0)
        w1 = nd.array(w0.copy())
        opt1.update(0, w1, sparse.row_sparse_array((g_data, g_rows), shape=shape), None)

        opt2 = optmod.create("sgd", learning_rate=0.1, wd=0.0)
        w2 = nd.array(w0.copy())
        opt2.update(0, w2, nd.array(dense_grad), None)

        np.testing.assert_allclose(w1.asnumpy(), w2.asnumpy(), rtol=1e-6)

    def test_unsupported_optimizer_densifies(self):
        from mxnet_tpu import optimizer as optmod

        shape = (4, 3)
        w = nd.array(np.ones(shape, np.float32))
        opt = optmod.create("rmsprop", learning_rate=0.1)
        state = opt.create_state_multi_precision(0, w)
        grad = sparse.row_sparse_array(
            (np.ones((1, 3), np.float32), [2]), shape=shape
        )
        opt.update_multi_precision(0, w, grad, state)
        assert not np.allclose(w.asnumpy(), 1.0)


class TestKVStoreSparse:
    def test_row_sparse_pull(self):
        kv = mx.kv.create("local")
        shape = (5, 3)
        init = np.random.RandomState(0).rand(*shape).astype(np.float32)
        kv.init("w", nd.array(init))
        out = nd.zeros(shape)
        kv.row_sparse_pull("w", out=out, row_ids=nd.array(np.array([0, 2], np.float32)))
        got = out.asnumpy()
        np.testing.assert_allclose(got[0], init[0], rtol=1e-6)
        np.testing.assert_allclose(got[2], init[2], rtol=1e-6)


class TestReviewRegressions:
    def test_csr_grad_densifies_in_updater(self):
        from mxnet_tpu import optimizer as optmod

        w = nd.array(np.ones((4, 3), np.float32))
        opt = optmod.create("sgd", learning_rate=0.1)
        upd = optmod.get_updater(opt)
        g = sparse.csr_matrix(np.eye(4, 3, dtype=np.float32))
        upd(0, g, w)  # must not crash on the lazy dense cache
        assert not np.allclose(w.asnumpy(), 1.0)

    def test_kvstore_sparse_push_and_init(self):
        kv = mx.kv.create("local")
        g = sparse.row_sparse_array(
            (np.ones((1, 3), np.float32), [1]), shape=(4, 3)
        )
        kv.init("k", sparse.zeros("row_sparse", (4, 3)))
        kv.push("k", g)
        out = nd.zeros((4, 3))
        kv.pull("k", out=out)
        got = out.asnumpy()
        assert got.shape == (4, 3)

    def test_row_sparse_pull_permuted_full_ids_scatter(self):
        kv = mx.kv.create("local")
        init = np.arange(12, dtype=np.float32).reshape(4, 3)
        kv.init("w", nd.array(init))
        out = nd.zeros((4, 3))
        kv.row_sparse_pull(
            "w", out=out, row_ids=nd.array(np.array([3, 2, 1, 0], np.float32))
        )
        np.testing.assert_allclose(out.asnumpy(), init, rtol=1e-6)

    def test_row_sparse_pull_bad_out_shape_raises(self):
        kv = mx.kv.create("local")
        kv.init("w", nd.zeros((4, 3)))
        with pytest.raises(ValueError):
            kv.row_sparse_pull(
                "w", out=nd.zeros((5, 3)), row_ids=nd.array(np.array([0.0]))
            )

    def test_reflected_and_scalar_arithmetic(self):
        """Review regression: dense+sparse, scalar*sparse, sparse/scalar."""
        dense = np.ones((4, 3), np.float32)
        rs = sparse.row_sparse_array(
            (np.full((1, 3), 2.0, np.float32), [1]), shape=(4, 3)
        )
        out = nd.array(dense) + rs
        got = out.asnumpy()
        np.testing.assert_allclose(got[1], 3.0)
        np.testing.assert_allclose(got[0], 1.0)
        out2 = 2 * rs
        assert out2.stype == "row_sparse"
        np.testing.assert_allclose(out2.asnumpy()[1], 4.0)
        out3 = rs / 2
        assert out3.stype == "row_sparse"
        np.testing.assert_allclose(out3.asnumpy()[1], 1.0)
        out4 = 6.0 / (rs + nd.array(np.ones((4, 3), np.float32)))
        np.testing.assert_allclose(out4.asnumpy()[1], 2.0)

    def test_csr_negative_index(self):
        dense = np.arange(12, dtype=np.float32).reshape(4, 3)
        cs = sparse.csr_matrix(dense)
        np.testing.assert_allclose(cs[-1].asnumpy(), dense[3:4], rtol=1e-6)
        with pytest.raises(mx.MXNetError):
            cs[4]

    def test_row_sparse_pull_sparse_out_shape_check(self):
        kv = mx.kv.create("local")
        kv.init("w", nd.zeros((5, 3)))
        with pytest.raises(ValueError):
            kv.row_sparse_pull(
                "w",
                out=sparse.zeros("row_sparse", (3, 3)),
                row_ids=nd.array(np.array([4.0])),
            )

    def test_mixed_push_dense_and_sparse(self):
        kv = mx.kv.create("local")
        kv.init("k", nd.zeros((4, 3)))
        g_sparse = sparse.row_sparse_array(
            (np.ones((1, 3), np.float32), [2]), shape=(4, 3)
        )
        kv.push("k", [nd.ones((4, 3)), g_sparse])
        out = nd.zeros((4, 3))
        kv.pull("k", out=out)
        got = out.asnumpy()
        np.testing.assert_allclose(got[2], 2.0)
        np.testing.assert_allclose(got[0], 1.0)
