"""Sharded/async checkpointing tests (SURVEY §5.4: orbax-backed resume;
reference Module.save_checkpoint / callback.do_checkpoint / NDArray save)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import parallel
from mxnet_tpu.parallel import checkpoint as ckpt


def _sharded_state(mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.RandomState(0)
    return {
        "w": jax.device_put(rng.rand(16, 8).astype(np.float32),
                            NamedSharding(mesh, P("dp", None))),
        "b": jax.device_put(rng.rand(8).astype(np.float32),
                            NamedSharding(mesh, P())),
        "step": jax.device_put(np.int32(7), NamedSharding(mesh, P())),
    }


def test_save_restore_roundtrip_sharded(tmp_path):
    import jax

    mesh = parallel.make_mesh({"dp": len(jax.devices())})
    state = _sharded_state(mesh)
    path = str(tmp_path / "ckpt1")
    ckpt.save(path, state)
    out = ckpt.restore(path, like=state)
    for k in state:
        np.testing.assert_array_equal(np.asarray(out[k]), np.asarray(state[k]))
    # restored array keeps the target sharding
    assert out["w"].sharding.spec == state["w"].sharding.spec


def test_restore_reshards_to_new_layout(tmp_path):
    """Elastic-recovery story: a checkpoint saved dp-sharded restores onto a
    different layout (here: replicated) — beyond the reference's
    same-topology relaunch."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = parallel.make_mesh({"dp": len(jax.devices())})
    state = _sharded_state(mesh)
    path = str(tmp_path / "ckpt2")
    ckpt.save(path, state)
    like = {k: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                    sharding=NamedSharding(mesh, P()))
            for k, v in state.items()}
    out = ckpt.restore(path, like=like)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.asarray(state["w"]))
    assert out["w"].sharding.spec == P()


def test_async_save_and_ndarray_tree(tmp_path):
    state = {"p": nd.array(np.arange(12, dtype=np.float32).reshape(3, 4)),
             "lr": nd.array(np.float32([0.1]))}
    path = str(tmp_path / "ckpt3")
    h = ckpt.async_save(path, state)
    h.wait_until_finished()
    ckpt.wait_all()
    out = ckpt.restore(path)
    np.testing.assert_array_equal(np.asarray(out["p"]),
                                  state["p"].asnumpy())


def test_checkpoint_manager_rotation(tmp_path):
    import jax

    mesh = parallel.make_mesh({"dp": len(jax.devices())})
    state = _sharded_state(mesh)
    mgr = ckpt.CheckpointManager(str(tmp_path / "mgr"), max_to_keep=2)
    for step in (1, 2, 3):
        scaled = {k: v * step if k != "step" else v for k, v in state.items()}
        assert mgr.save(step, scaled, force=True)
    mgr.wait_until_finished()
    assert mgr.latest_step() == 3
    assert mgr.all_steps() == [2, 3]  # step 1 rotated out
    out = mgr.restore(like=state)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.asarray(state["w"]) * 3, rtol=1e-6)
    with pytest.raises(Exception):
        mgr.restore(step=1)
    mgr.close()


def test_manager_empty_dir_raises(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        mgr.restore()
    mgr.close()


def test_zero1_state_reshards_across_mesh_shape_change(tmp_path):
    """ISSUE 20 satellite: ZeRO-1 optimizer state saved under one mesh
    shape restores onto a different one — resharded via ``like=``, values
    exact.  The elastic relaunch may come back with fewer (or more) ranks;
    a 1/dp shard saved at dp=8 must land correctly at dp=4, never be
    silently misassigned."""
    import jax

    ndev = len(jax.devices())
    if ndev < 4 or ndev % 2:
        pytest.skip("needs >=4 devices with an even split")
    mesh_a = parallel.make_mesh({"dp": ndev})
    rng = np.random.RandomState(3)
    host = {"mom_w": rng.rand(16, 8).astype(np.float32),
            "mom_b": rng.rand(8).astype(np.float32)}
    state = {k: jax.device_put(v, parallel.zero_shard_spec(v, mesh_a))
             for k, v in host.items()}
    assert state["mom_w"].sharding.spec[0] == "dp"  # really 1/dp sharded
    path = str(tmp_path / "zero1")
    ckpt.save(path, state)

    # relaunch topology: half the dp extent — restore reshards onto it
    mesh_b = parallel.make_mesh({"dp": ndev // 2})
    like = {k: jax.ShapeDtypeStruct(
                v.shape, v.dtype,
                sharding=parallel.zero_shard_spec(v, mesh_b))
            for k, v in state.items()}
    out = ckpt.restore(path, like=like)
    for k in host:
        np.testing.assert_array_equal(np.asarray(out[k]), host[k])
    assert out["mom_w"].sharding.spec[0] == "dp"
    assert out["mom_w"].sharding.mesh.shape["dp"] == ndev // 2


def test_zero1_state_mesh_change_wrong_shape_fails_loudly(tmp_path):
    """The failure half of the contract: restoring onto a ``like`` whose
    global shape disagrees with the checkpoint must raise — never return
    a silently truncated/misassigned shard."""
    import jax

    mesh = parallel.make_mesh({"dp": len(jax.devices())})
    v = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    state = {"mom_w": jax.device_put(v, parallel.zero_shard_spec(v, mesh))}
    path = str(tmp_path / "zero1bad")
    ckpt.save(path, state)
    bad = {"mom_w": jax.ShapeDtypeStruct(
        (8, 8), np.float32,
        sharding=parallel.zero_shard_spec(np.zeros((8, 8), np.float32),
                                          mesh))}
    with pytest.raises(Exception):
        ckpt.restore(path, like=bad)


def test_dp_example_checkpoint_resume(tmp_path):
    """Kill-and-relaunch recovery: run 1 stops after its steps, run 2 resumes
    from the latest rotating checkpoint (reference SURVEY §5.3 recovery =
    checkpoints + relaunch; here resharded restore onto the dp mesh)."""
    import os
    import subprocess
    import sys

    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    cwd = os.path.join(repo, "examples", "distributed_training")
    ck = str(tmp_path / "dpck")
    common = ["--batch-per-device", "2", "--lr", "0.01",
              "--ckpt-dir", ck, "--ckpt-every", "4"]
    r1 = subprocess.run(
        [sys.executable, "train_dp.py", "--steps", "8"] + common,
        cwd=cwd, env=env, capture_output=True, text=True, timeout=900)
    assert r1.returncode == 0, r1.stdout + r1.stderr
    assert "DP TRAINING OK" in r1.stdout and "resumed" not in r1.stdout
    r2 = subprocess.run(
        [sys.executable, "train_dp.py", "--steps", "12"] + common,
        cwd=cwd, env=env, capture_output=True, text=True, timeout=900)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "resumed from step 8" in r2.stdout
    assert "DP TRAINING OK" in r2.stdout
