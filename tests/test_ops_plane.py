"""Live ops plane (ISSUE 10): streaming-quantile accuracy vs np.percentile,
window rotation, SLO burn/goodput accounting and breach edges, the
flight-recorder ring bound + dump-on-error + off-path no-op, ops-server
endpoint semantics (incl. 503 on a stalled heartbeat), the loadgen
per-class/goodput keys, and the byte-identical all-gates-off contract."""
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mxnet_tpu.serving import BucketLadder, Engine
from mxnet_tpu.telemetry import flightrec, ops_server, slo
from mxnet_tpu.telemetry import instrument as tin

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _mlp_engine(**kw):
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    kw.setdefault("ladder", BucketLadder((1, 2, 4)))
    kw.setdefault("max_wait_ms", 2.0)
    kw.setdefault("max_queue", 64)
    kw.setdefault("name", "opsplane")
    return Engine(sym, params, {"data": (8,)}, **kw)


@pytest.fixture
def ops_off(monkeypatch):
    """All three ISSUE 10 gates unset (the byte-identical off path)."""
    for var in ("MXNET_OPS_PORT", "MXNET_SLO", "MXNET_FLIGHTREC_DIR"):
        monkeypatch.delenv(var, raising=False)
    flightrec._reset_for_tests()
    yield
    flightrec._reset_for_tests()


@pytest.fixture
def ops_on(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_OPS_PORT", "0")
    monkeypatch.setenv("MXNET_SLO", "*:p99:500:600")
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path / "frec"))
    monkeypatch.setenv("MXNET_OPS_STALE_S", "1.0")
    flightrec._reset_for_tests()
    ops_server.stop()
    yield tmp_path
    ops_server.stop()
    flightrec._reset_for_tests()


# -- streaming quantile estimator ---------------------------------------------
class TestWindowedQuantile:
    def _check_accuracy(self, samples, quantiles=(0.5, 0.95, 0.99)):
        est = slo.WindowedQuantile(window_s=3600.0)
        for v in samples:
            est.observe(v, now=0.0)
        for q in quantiles:
            truth = float(np.percentile(samples, q * 100))
            got = est.quantile(q, now=0.0)
            # documented bound (geometric-midpoint bucket quantization)
            # plus a pinch for the rank-definition difference vs numpy's
            # linear interpolation
            tol = slo.RELATIVE_ERROR * truth + 2.0 / len(samples) * truth
            assert abs(got - truth) <= tol, \
                "q=%g: est %.6f vs true %.6f (tol %.6f)" % (q, got, truth,
                                                            tol)

    def test_uniform(self):
        rng = np.random.default_rng(0)
        self._check_accuracy(rng.uniform(0.002, 0.080, size=8000))

    def test_lognormal(self):
        rng = np.random.default_rng(1)
        # ~2-50 ms body with a heavy tail — the serving latency shape
        self._check_accuracy(np.exp(rng.normal(np.log(0.008), 0.6,
                                               size=8000)))

    def test_bimodal(self):
        rng = np.random.default_rng(2)
        # cache-hit vs compile-path mix; quantiles chosen inside the modes
        # (an interpolating estimator is unspecified inside the gap)
        lo = rng.uniform(0.001, 0.002, size=7000)
        hi = rng.uniform(0.100, 0.120, size=3000)
        samples = np.concatenate([lo, hi])
        rng.shuffle(samples)
        self._check_accuracy(samples, quantiles=(0.5, 0.99))

    def test_out_of_range_clamps(self):
        est = slo.WindowedQuantile(window_s=60.0)
        est.observe(1e-9, now=0.0)
        assert est.quantile(0.5, now=0.0) == slo.MIN_LATENCY_S
        est2 = slo.WindowedQuantile(window_s=60.0)
        est2.observe(1e6, now=0.0)
        assert est2.quantile(0.5, now=0.0) == slo.MAX_LATENCY_S

    def test_window_rotation(self):
        est = slo.WindowedQuantile(window_s=12.0)  # sub-window = 2 s
        for _ in range(100):
            est.observe(0.001, now=0.0)
        assert est.count(now=0.0) == 100
        # fully past the window (+ the partial-subwindow slack): expired
        assert est.count(now=20.0) == 0
        assert est.quantile(0.99, now=20.0) is None
        # old fast samples rotate out, new slow samples dominate (t=0
        # samples live in sub-window epoch 0, dropped once the query epoch
        # passes NSUB — at t=15 with 2 s sub-windows they are gone)
        for _ in range(100):
            est.observe(0.001, now=0.0)
        for _ in range(50):
            est.observe(0.100, now=15.0)
        p50 = est.quantile(0.50, now=15.0)
        assert abs(p50 - 0.100) <= slo.RELATIVE_ERROR * 0.100
        # memory bound: never more than NSUB+1 live sub-histograms
        for t in range(200):
            est.observe(0.005, now=float(t))
        assert len(est._subs) <= slo.NSUB + 1

    def test_mergeable(self):
        a, b = slo.WindowedQuantile(60.0), slo.WindowedQuantile(60.0)
        for v in (0.002, 0.004, 0.006):
            a.observe(v, now=0.0)
        for v in (0.100, 0.120):
            b.observe(v, now=0.0)
        counts = [0] * (slo.NBUCKETS + 2)
        a.merge_into(counts, now=0.0)
        b.merge_into(counts, now=0.0)
        assert sum(counts) == 5
        p99 = slo.quantile_of_counts(counts, 0.99)
        assert abs(p99 - 0.120) <= slo.RELATIVE_ERROR * 0.120

    def test_empty(self):
        est = slo.WindowedQuantile(60.0)
        assert est.quantile(0.99) is None
        assert slo.quantile_of_counts([0] * (slo.NBUCKETS + 2), 0.5) is None


# -- objectives / parsing -----------------------------------------------------
class TestSLOParse:
    def test_spec(self):
        objs = slo.parse_objectives("default:p99:50,interactive:p95:10:30")
        assert len(objs) == 2
        assert objs[0].klass == "default" and objs[0].percentile == 99.0
        assert objs[0].target_s == 0.05 and objs[0].window_s == 60.0
        assert objs[1].klass == "interactive" and objs[1].window_s == 30.0

    def test_bare_truthy_is_default(self):
        (obj,) = slo.parse_objectives("1")
        assert (obj.klass, obj.percentile) == ("*", 99.0)

    def test_falsy_disables(self):
        assert slo.parse_objectives("") == []
        assert slo.parse_objectives("0") == []
        assert slo.parse_objectives("off") == []

    def test_malformed_items_skipped(self):
        objs = slo.parse_objectives("a:p99:50,garbage:entry,b:pXX:nope:1")
        assert [o.klass for o in objs] == ["a"]
        # all-malformed but clearly meant to enable: default objective
        (obj,) = slo.parse_objectives("garbage:entry:")
        assert obj.klass == "*"

    def test_monitor_from_env(self, monkeypatch):
        monkeypatch.delenv("MXNET_SLO", raising=False)
        assert slo.monitor_from_env() is None
        monkeypatch.setenv("MXNET_SLO", "0")
        assert slo.monitor_from_env() is None
        monkeypatch.setenv("MXNET_SLO", "default:p99:50")
        assert slo.monitor_from_env() is not None


# -- monitor accounting -------------------------------------------------------
class TestSLOMonitor:
    def test_burn_and_goodput(self):
        mon = slo.SLOMonitor([slo.SLOObjective("*", 90.0, 10.0, 60.0)])
        for _ in range(80):
            mon.record(0.005, "a", now=1.0)
        for _ in range(20):
            mon.record(0.050, "a", now=1.0)
        (obj,) = mon.status(now=1.0)["objectives"]
        assert obj["good"] == 80 and obj["bad"] == 20
        assert obj["goodput"] == pytest.approx(0.8)
        assert obj["budget_frac"] == pytest.approx(0.1)
        # window bad fraction 0.2 over a 0.1 budget: burning 2x
        assert obj["burn_rate"] == pytest.approx(2.0, rel=0.05)
        assert obj["met"] is False  # p90 ~50 ms > 10 ms target

    def test_breach_edges_and_callback(self):
        mon = slo.SLOMonitor([slo.SLOObjective("*", 50.0, 10.0, 6.0)])
        fired = []
        mon.on_breach = lambda o, v: fired.append((o.key(), v))
        for i in range(50):
            mon.record(0.050, now=0.0 + i * 0.001)
        mon.record(0.050, now=2.0)  # past the check throttle: evaluates
        (obj,) = mon.status(now=2.0)["objectives"]
        assert obj["breaches"] == 1 and len(fired) == 1
        # stays breached: no second edge
        mon.record(0.050, now=4.0)
        assert mon.status(now=4.0)["objectives"][0]["breaches"] == 1
        # recovery (old samples rotate out), then a new breach is an edge
        for i in range(200):
            mon.record(0.001, now=20.0 + i * 0.01)
        assert mon.status(now=23.0)["objectives"][0]["met"] is True
        for i in range(400):
            mon.record(0.050, now=40.0 + i * 0.01)
        assert mon.status(now=45.0)["objectives"][0]["breaches"] == 2

    def test_drops_evaluate_as_infinite_latencies(self):
        mon = slo.SLOMonitor([slo.SLOObjective("*", 99.0, 10.0, 60.0)])
        mon.record(0.001, now=0.0)
        for _ in range(9):
            mon.record_drop(now=0.0)
        (obj,) = mon.status(now=0.0)["objectives"]
        assert obj["good"] == 1 and obj["bad"] == 9
        assert obj["window_n"] == 1 and obj["window_drops"] == 9
        # p99's rank lands among the drops: value clamps to the range top
        # and the objective is breached; burn reflects the 90% bad window
        assert obj["value_ms"] == slo.MAX_LATENCY_S * 1e3
        assert obj["met"] is False
        assert obj["burn_rate"] == pytest.approx(90.0, rel=0.01)
        # the per-class quantile block stays over completed requests only
        assert mon.status(now=0.0)["classes"]["default"]["n"] == 1

    def test_outage_with_zero_completions_breaches(self):
        mon = slo.SLOMonitor([slo.SLOObjective("*", 99.0, 10.0, 6.0)])
        fired = []
        mon.on_breach = lambda o, v: fired.append(v)
        for i in range(20):
            mon.record_drop(now=5.0 + i * 0.001)
        mon.record_drop(now=7.0)  # past the check throttle
        (obj,) = mon.status(now=7.0)["objectives"]
        assert obj["window_n"] == 0 and obj["window_drops"] == 21
        assert obj["met"] is False and obj["breaches"] == 1
        assert fired == [slo.MAX_LATENCY_S]

    def test_class_scoping(self):
        mon = slo.SLOMonitor([slo.SLOObjective("a", 50.0, 10.0, 60.0)])
        mon.record(0.050, "b", now=0.0)
        (obj,) = mon.status(now=0.0)["objectives"]
        assert obj["window_n"] == 0 and obj["good"] + obj["bad"] == 0
        mon.record(0.050, "a", now=0.0)
        (obj,) = mon.status(now=0.0)["objectives"]
        assert obj["window_n"] == 1 and obj["bad"] == 1
        assert set(mon.status(now=0.0)["classes"]) == {"a", "b"}


# -- flight recorder ----------------------------------------------------------
class TestFlightRecorder:
    def test_ring_bound_and_dump(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), cap=16,
                                       min_auto_dump_s=0.0)
        for i in range(100):
            rec.record("step", dur_s=0.001, step=i)
        assert len(rec) == 16
        path = rec.dump("unit", extra_field="x")
        assert path and os.path.exists(path)
        data = json.loads(open(path).read())
        evs = [e for e in data["traceEvents"] if e.get("cat") == "flightrec"]
        assert len(evs) == 16
        # oldest evicted: the surviving events are the LAST 16
        assert [e["args"]["step"] for e in evs] == list(range(84, 100))
        assert data["flightrec"]["reason"] == "unit"
        assert data["flightrec"]["extra_field"] == "x"
        # span record shape: X events with the shared us timebase
        assert all(e["ph"] == "X" and "dur" in e for e in evs)
        assert any(e.get("name") == "clock_sync"
                   for e in data["traceEvents"])

    def test_auto_dump_throttle(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), min_auto_dump_s=3600)
        rec.record("x")
        assert rec.dump("err", auto=True) is not None
        assert rec.dump("err", auto=True) is None   # throttled
        assert rec.dump("explicit") is not None     # explicit always writes

    def test_empty_ring_no_dump(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path))
        assert rec.dump("nothing") is None

    def test_off_path_noop(self, ops_off):
        assert flightrec.recorder() is None
        assert flightrec.dump("x") is None
        flightrec.record("x")  # no-op, no error

    def test_dump_on_batch_error(self, ops_on, monkeypatch):
        d = str(ops_on / "frec")
        eng = _mlp_engine()
        try:
            # warm first: a cold-compile first request can breach the
            # fixture's 500 ms objective, and this test wants exactly one
            # batch_error dump in the directory
            eng.warmup()
            eng.predict({"data": np.zeros((1, 8), np.float32)})

            def boom(bucket):
                raise RuntimeError("seeded model failure")

            monkeypatch.setattr(eng, "_predictor_for", boom)
            with pytest.raises(RuntimeError):
                eng.predict({"data": np.zeros((1, 8), np.float32)},
                            timeout=10.0)
            # the client unblocks at set_error; the loop writes the dump
            # just after — poll briefly
            deadline = time.monotonic() + 5.0
            dumps = []
            while time.monotonic() < deadline and not dumps:
                dumps = [f for f in os.listdir(d)
                         if f.startswith("flightrec-")
                         and "batch_error" in f] if os.path.isdir(d) else []
                if not dumps:
                    time.sleep(0.05)
            assert len(dumps) == 1
            data = json.loads(open(os.path.join(d, dumps[0])).read())
            names = [e["name"] for e in data["traceEvents"]]
            assert "batch_error" in names and "serve" in names \
                and "submit" in names
            assert data["flightrec"]["reason"] == "batch_error"
        finally:
            eng.close()


# -- ops server ---------------------------------------------------------------
def _get(port, path):
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d%s" % (port, path), timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestOpsServer:
    def test_endpoints(self, ops_on):
        eng = _mlp_engine()
        try:
            port = ops_server.port()
            assert port and ops_server.active()
            eng.warmup()
            for _ in range(5):
                eng.predict({"data": np.zeros((2, 8), np.float32)})
            code, body = _get(port, "/healthz")
            assert code == 200 and json.loads(body)["ok"] is True
            code, body = _get(port, "/statusz")
            assert code == 200
            st = json.loads(body)["engines"]["opsplane"]
            assert st["completed"] == 5 and st["warmup"] is not None
            assert st["slo"]["objectives"][0]["window_n"] == 5
            code, body = _get(port, "/metrics")
            assert code == 200  # telemetry off: renders (possibly empty)
            code, _ = _get(port, "/nope")
            assert code == 404
        finally:
            eng.close()

    def test_healthz_flips_on_stalled_heartbeat(self, ops_on):
        eng = _mlp_engine()
        try:
            port = ops_server.port()
            eng.predict({"data": np.zeros((1, 8), np.float32)})
            assert _get(port, "/healthz")[0] == 200
            eng._device_mu.acquire()
            try:
                frozen = eng.submit({"data": np.zeros((1, 8), np.float32)})
                deadline = time.monotonic() + 10.0
                code = 200
                while time.monotonic() < deadline and code != 503:
                    time.sleep(0.2)
                    code, _ = _get(port, "/healthz")
                assert code == 503
            finally:
                eng._device_mu.release()
            frozen.result(timeout=30)
            deadline = time.monotonic() + 10.0
            code = 503
            while time.monotonic() < deadline and code != 200:
                time.sleep(0.2)
                code, _ = _get(port, "/healthz")
            assert code == 200
        finally:
            eng.close()

    def test_busy_marker_separates_slow_from_dead(self, ops_on):
        """ISSUE 16 satellite: a stale heartbeat alone no longer fails
        health when a forward is in flight (``_busy_since``) — only a
        stale-AND-idle loop reads dead."""
        eng = _mlp_engine()
        try:
            eng.predict({"data": np.zeros((1, 8), np.float32)})
            # evaluate far in the future so the heartbeat is certainly
            # stale no matter how the loop's wait cycle interleaves
            now = time.monotonic() + 100.0
            h = ops_server.engine_health(eng, now=now, threshold=1.0)
            assert h["ok"] is False and h["busy_in_dispatch"] is False
            assert h["busy_s"] is None
            try:
                eng._busy_since = now - 50.0  # mid-forward for 50 s
                h = ops_server.engine_health(eng, now=now, threshold=1.0)
                assert h["ok"] is True and h["busy_in_dispatch"] is True
                assert h["busy_s"] == pytest.approx(50.0, abs=0.01)
            finally:
                eng._busy_since = None
        finally:
            eng.close()

    def test_healthz_stays_200_during_slow_forward(self, ops_on,
                                                   monkeypatch):
        """The live half of the PR 10 flapping fix: a forward outlasting
        MXNET_OPS_STALE_S (1.0 s here) keeps /healthz at 200 while the
        mutex-frozen variant above still flips 503."""
        eng = _mlp_engine()
        try:
            port = ops_server.port()
            eng.predict({"data": np.zeros((1, 8), np.float32)})
            real = eng._predictor_for

            class SlowPred:
                def __init__(self, inner):
                    self._inner = inner

                def forward(self, **arrays):
                    time.sleep(2.5)
                    return self._inner.forward(**arrays)

                def __getattr__(self, name):
                    return getattr(self._inner, name)

            monkeypatch.setattr(
                eng, "_predictor_for",
                lambda bucket: (lambda p, f: (SlowPred(p), f))(*real(bucket)))
            fut = eng.submit({"data": np.zeros((1, 8), np.float32)})
            time.sleep(1.6)  # well past the stale threshold, mid-forward
            code, body = _get(port, "/healthz")
            assert code == 200
            (check,) = json.loads(body)["engines"]
            assert check["busy_in_dispatch"] is True
            fut.result(timeout=30)
        finally:
            eng.close()

    def test_healthz_stays_200_during_slow_cold_compile(self, ops_on,
                                                        monkeypatch):
        """ISSUE 19 satellite (the rest of the flap fix): the cold-bucket
        ``_predictor_for`` build/compile runs BEFORE the device mutex, so
        the ISSUE 16 busy marker never covered it — a first-request
        compile outlasting MXNET_OPS_STALE_S flapped 503.  _dispatch now
        beats on entry and holds the busy marker across the predictor
        build, so a slow compile reads busy-not-dead."""
        eng = _mlp_engine()
        try:
            port = ops_server.port()
            real = eng._predictor_for

            def slow_build(bucket):
                time.sleep(2.5)  # a long XLA compile, pre-mutex
                return real(bucket)

            monkeypatch.setattr(eng, "_predictor_for", slow_build)
            fut = eng.submit({"data": np.zeros((1, 8), np.float32)})
            time.sleep(1.6)  # past MXNET_OPS_STALE_S=1.0, mid-"compile"
            code, body = _get(port, "/healthz")
            assert code == 200
            (check,) = json.loads(body)["engines"]
            assert check["busy_in_dispatch"] is True
            fut.result(timeout=30)
        finally:
            eng.close()

    def test_unregister_on_close(self, ops_on):
        eng = _mlp_engine()
        port = ops_server.port()
        eng.close()
        # a closed engine is off the health page — never a permanent 503
        code, body = _get(port, "/healthz")
        assert code == 200 and json.loads(body)["engines"] == []

    def test_engine_health_readiness(self, ops_on):
        eng = _mlp_engine(start=False)
        try:
            h = ops_server.engine_health(eng)
            assert h["ok"] is False and h["loop_alive"] is False
            eng.start()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline \
                    and not ops_server.engine_health(eng)["ok"]:
                time.sleep(0.05)
            assert ops_server.engine_health(eng)["ok"] is True
        finally:
            eng.close()

    def test_malformed_port_disabled(self, monkeypatch):
        monkeypatch.setenv("MXNET_OPS_PORT", "not-a-port")
        assert ops_server.configured_port() is None
        assert ops_server.maybe_start() is None


# -- engine off-path contract -------------------------------------------------
class TestOffPath:
    def test_all_gates_off_engine_is_noop(self, ops_off):
        eng = _mlp_engine()
        try:
            assert eng._slo is None and eng._flightrec is None
            assert not ops_server.active()
            out = eng.predict({"data": np.ones((2, 8), np.float32)})
            assert out[0].shape[0] == 2
            st = eng.stats()
            assert st["slo"] is None
            # the heartbeat is engine-owned liveness state (like _stats),
            # maintained regardless of gates — /healthz just reads it
            assert st["heartbeat_age_s"] is not None
        finally:
            eng.close()

    def test_fit_loop_off_path(self, ops_off, monkeypatch):
        # flightrec off in fit: recorder() None and no ring anywhere
        import mxnet_tpu as mx

        x = np.random.rand(16, 8).astype(np.float32)
        y = np.random.randint(0, 4, (16,)).astype(np.float32)
        it = mx.io.NDArrayIter(x, y, batch_size=8)
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net, label_names=("softmax_label",))
        mod.fit(it, num_epoch=1, batch_end_callback=None)
        assert flightrec._recorder is None

    def test_fit_loop_records_steps(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
        monkeypatch.delenv("MXNET_OPS_PORT", raising=False)
        flightrec._reset_for_tests()
        import mxnet_tpu as mx

        x = np.random.rand(16, 8).astype(np.float32)
        y = np.random.randint(0, 4, (16,)).astype(np.float32)
        it = mx.io.NDArrayIter(x, y, batch_size=8)
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net, label_names=("softmax_label",))
        mod.fit(it, num_epoch=1)
        rec = flightrec.recorder()
        assert rec is not None and len(rec) == 2  # 2 batches = 2 steps
        path = rec.dump("test")
        evs = json.loads(open(path).read())["traceEvents"]
        steps = [e for e in evs if e["name"] == "step"]
        assert [e["args"]["step"] for e in steps] == [0, 1]
        flightrec._reset_for_tests()


# -- telemetry summary / loadgen surfaces -------------------------------------
class TestSummaryServeKeys:
    def test_null_without_serving(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MXNET_TELEMETRY", "1")
        monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
        tin._reset_for_tests()
        try:
            s = tin.summary()
            assert s["serve_p50_ms"] is None and s["serve_p99_ms"] is None
        finally:
            tin._reset_for_tests()

    def test_populated_by_serving(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MXNET_TELEMETRY", "1")
        monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
        for var in ("MXNET_OPS_PORT", "MXNET_SLO", "MXNET_FLIGHTREC_DIR"):
            monkeypatch.delenv(var, raising=False)
        tin._reset_for_tests()
        try:
            eng = _mlp_engine()
            try:
                for _ in range(10):
                    eng.predict({"data": np.zeros((1, 8), np.float32)})
            finally:
                eng.close()
            s = tin.summary()
            assert s["serve_p50_ms"] is not None
            assert s["serve_p99_ms"] >= s["serve_p50_ms"] > 0
        finally:
            tin._reset_for_tests()

    def test_hist_quantile(self):
        from mxnet_tpu.telemetry import Registry

        r = Registry()
        h = r.histogram("lat", "", ("k",), buckets=(0.01, 0.1, 1.0))
        assert r.hist_quantile("lat", 0.5) is None
        for _ in range(90):
            h.observe(0.005, k="a")
        for _ in range(10):
            h.observe(0.5, k="b")   # merged across label sets
        assert r.hist_quantile("lat", 0.5) <= 0.01
        assert 0.1 <= r.hist_quantile("lat", 0.99) <= 1.0
        assert r.hist_quantile("absent", 0.5, default=-1) == -1


class TestLoadgenSurface:
    def _loadgen(self):
        from mxnet_tpu.test_utils import load_module_by_path

        return load_module_by_path(os.path.join(REPO, "tools", "loadgen.py"))

    def test_per_class_and_goodput(self, ops_off):
        import argparse

        loadgen = self._loadgen()
        eng = _mlp_engine(name="loadgen")
        try:
            eng.warmup()
            args = argparse.Namespace(duration=0.4, concurrency=2,
                                      sizes=(1, 2), timeout_s=10.0,
                                      rate=0.0, seed=0, slo_ms=0.001)
            line = loadgen.run(eng, {"data": (8,)}, args, "closed")
        finally:
            eng.close()
        # schema-lints (the new keys included)
        from mxnet_tpu.test_utils import load_module_by_path

        cbs = load_module_by_path(
            os.path.join(REPO, "ci", "check_bench_schema.py"))
        cbs.validate_serve_line(line, "test")
        assert set(line["latency_by_class"]) == {"1", "2"}
        for v in line["latency_by_class"].values():
            assert v["n"] > 0 and v["p99_ms"] >= v["p50_ms"]
        # an impossible 0.001 ms target: nothing qualifies as goodput
        assert line["slo_ms"] == 0.001
        assert line["goodput_rps"] == 0.0 and line["throughput_rps"] > 0

    def test_slo_class_reaches_engine(self, monkeypatch):
        monkeypatch.setenv("MXNET_SLO", "1:p99:500:600")
        eng = _mlp_engine(name="klass")
        try:
            eng.predict({"data": np.zeros((1, 8), np.float32)}, klass="1")
            eng.predict({"data": np.zeros((2, 8), np.float32)}, klass="2")
            st = eng.stats()["slo"]
            assert set(st["classes"]) == {"1", "2"}
            (obj,) = st["objectives"]
            assert obj["class"] == "1" and obj["window_n"] == 1
        finally:
            eng.close()
