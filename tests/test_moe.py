"""Expert parallelism (parallel/moe.py) — top-1 routed MoE with all_to_all
dispatch; beyond reference parity (SURVEY §2.2 EP row: absent)."""
import numpy as np

import mxnet_tpu as mx  # noqa: F401 — forces the CPU-mesh conftest
from mxnet_tpu import parallel
from mxnet_tpu.parallel import moe_ffn, stack_expert_params


def _setup(dim=16):
    import jax

    n = len(jax.devices())
    mesh = parallel.make_mesh({"ep": n})
    rng = np.random.RandomState(0)
    experts = [{"w1": rng.randn(dim, 32).astype(np.float32) * 0.3,
                "w2": rng.randn(32, dim).astype(np.float32) * 0.3}
               for _ in range(n)]
    gate_w = rng.randn(dim, n).astype(np.float32)
    return mesh, experts, gate_w, rng, dim, n


def _expert_fn(p, t):
    import jax

    return jax.nn.relu(t @ p["w1"]) @ p["w2"]


def _dense_oracle(x, gate_w, experts):
    """Every token through its argmax expert, weighted by the gate prob."""
    logits = x @ gate_w
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    e = probs.argmax(1)
    out = np.zeros_like(x)
    for i in range(len(x)):
        p = experts[e[i]]
        h = np.maximum(x[i] @ p["w1"], 0) @ p["w2"]
        out[i] = probs[i, e[i]] * h
    return out


def test_moe_matches_dense_oracle():
    import jax
    import jax.numpy as jnp

    mesh, experts, gate_w, rng, dim, n = _setup()
    T = 16 * n
    x = rng.randn(T, dim).astype(np.float32)
    # capacity_factor=n: nothing can overflow → exact match with the oracle
    out = jax.jit(lambda a, g, p: moe_ffn(
        a, g, p, _expert_fn, mesh=mesh, capacity_factor=float(n)))(
        jnp.asarray(x), jnp.asarray(gate_w), stack_expert_params(experts))
    np.testing.assert_allclose(np.asarray(out),
                               _dense_oracle(x, gate_w, experts),
                               rtol=2e-4, atol=1e-5)


def test_moe_capacity_drops_tokens():
    import jax
    import jax.numpy as jnp

    mesh, experts, gate_w, rng, dim, n = _setup()
    T = 16 * n
    x = rng.randn(T, dim).astype(np.float32)
    # all tokens forced to expert 0: tiny capacity must drop most of them
    gate_forced = np.zeros_like(gate_w)
    gate_forced[:, 0] = 0.0
    gate_forced[:, 1:] = -10.0
    out = jax.jit(lambda a, g, p: moe_ffn(
        a, g, p, _expert_fn, mesh=mesh, capacity_factor=0.5))(
        jnp.asarray(x), jnp.asarray(gate_forced),
        stack_expert_params(experts))
    out = np.asarray(out)
    dropped = (np.abs(out).sum(axis=1) == 0).sum()
    assert dropped > 0, "expected capacity overflow to drop tokens"
    assert dropped < T, "some tokens must still be served"


def test_moe_trains():
    """Router + experts learn a partitioned regression task end-to-end."""
    import jax
    import jax.numpy as jnp

    mesh, experts, gate_w, rng, dim, n = _setup()
    T = 8 * n
    x = rng.randn(T, dim).astype(np.float32)
    tgt = np.tanh(x @ rng.randn(dim, dim).astype(np.float32) * 0.5)
    params = {"gate": jnp.asarray(gate_w),
              "experts": stack_expert_params(experts)}

    def loss_fn(p):
        out = moe_ffn(jnp.asarray(x), p["gate"], p["experts"], _expert_fn,
                      mesh=mesh, capacity_factor=2.0)
        return jnp.mean((out - jnp.asarray(tgt)) ** 2)

    vg = jax.jit(jax.value_and_grad(loss_fn))
    l0, g = vg(params)
    assert np.isfinite(float(l0))
    assert any(np.abs(np.asarray(leaf)).max() > 0
               for leaf in jax.tree_util.tree_leaves(g["experts"]))
    p = params
    for _ in range(60):
        l, g = vg(p)
        p = jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g)
    assert float(l) < float(l0) * 0.7, (float(l0), float(l))
