"""Structured span tracing (ISSUE 4, telemetry/tracing.py): the
MXNET_TRACE=0 no-op guarantee, sampling, the bounded ring, cross-thread
context propagation with flow events, the serving request lifecycle
(queue/assemble/execute across submit and device-loop threads, drop
reasons), fit-loop step/data_wait spans, kvstore/Predictor spans, the
exporter's chrome-trace invariants (ci/check_trace.py), and the
trace_merge clock rebase."""
import json
import os
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.telemetry import tracing

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load_tool(relpath):
    from mxnet_tpu.test_utils import load_module_by_path

    return load_module_by_path(os.path.join(REPO, relpath))


@pytest.fixture
def tr_enabled(monkeypatch, tmp_path):
    """Fresh global tracer with tracing ON, export path in tmp."""
    monkeypatch.setenv("MXNET_TRACE", "1")
    monkeypatch.setenv("MXNET_TRACE_FILE", str(tmp_path / "trace.json"))
    monkeypatch.delenv("MXNET_TRACE_SAMPLE", raising=False)
    monkeypatch.delenv("MXNET_TRACE_BUFFER", raising=False)
    tracing._reset_for_tests()
    yield tmp_path / "trace.json"
    tracing._reset_for_tests()


@pytest.fixture
def tr_disabled(monkeypatch):
    monkeypatch.delenv("MXNET_TRACE", raising=False)
    tracing._reset_for_tests()
    yield
    tracing._reset_for_tests()


def _export_events(path):
    tracing.export(str(path))
    return json.load(open(path))["traceEvents"]


def _spans(events):
    return [e for e in events if e.get("ph") == "X"]


# -- gating / no-op guarantee -------------------------------------------------
class TestGating:
    def test_noop_guard_tracing(self, tr_disabled, tmp_path, monkeypatch):
        """MXNET_TRACE unset: the shared NULL_SPAN singleton comes back from
        every entry point, no Tracer object is ever created, and no file is
        written — the traced code paths carry only the env check."""
        monkeypatch.setenv("MXNET_TRACE_FILE", str(tmp_path / "no.json"))
        root = tracing.start_trace("step", step=1)
        assert root is tracing.NULL_SPAN
        assert not root  # falsy ⇒ `if root:` guards cost nothing
        with root:
            assert tracing.span("child") is tracing.NULL_SPAN
        assert root.context() is None
        assert root.set(x=1) is root and root.finish() is root
        assert tracing._tracer is None  # nothing allocated
        assert tracing.export() is None
        assert not (tmp_path / "no.json").exists()

    def test_unsampled_root_propagates_nothing(self, tr_enabled, monkeypatch):
        monkeypatch.setenv("MXNET_TRACE_SAMPLE", "0")
        root = tracing.start_trace("step")
        assert root is tracing.NULL_SPAN
        with root:
            assert tracing.span("child") is tracing.NULL_SPAN

    def test_sampling_is_systematic(self, tr_enabled, monkeypatch):
        monkeypatch.setenv("MXNET_TRACE_SAMPLE", "0.5")
        kept = sum(bool(tracing.start_trace("t")) for _ in range(10))
        assert kept == 5  # floor(n*0.5) increments on every 2nd root

    def test_serving_and_module_paths_untouched_when_disabled(
            self, tr_disabled):
        from mxnet_tpu.serving import BucketLadder, Engine
        from mxnet_tpu.test_utils import tiny_mlp_checkpoint

        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)},
                    ladder=BucketLadder((1, 2))) as eng:
            req = eng.submit({"data": np.zeros((1, 8), np.float32)})
            req.result(5.0)
            assert not hasattr(req, "_trace_root")
        assert tracing._tracer is None


# -- core tracer --------------------------------------------------------------
class TestTracer:
    def test_span_nesting_and_export(self, tr_enabled, tmp_path):
        with tracing.start_trace("root", kind="test") as root:
            with tracing.span("child", n=3) as child:
                pass
        events = _export_events(tmp_path / "e.json")
        (sync,) = [e for e in events if e.get("name") == "clock_sync"]
        assert sync["args"]["unix_ts"] > 0
        xs = {e["name"]: e for e in _spans(events)}
        assert xs["root"]["args"]["trace"] == xs["child"]["args"]["trace"]
        assert xs["child"]["args"]["parent"] == xs["root"]["args"]["span"]
        assert xs["child"]["args"]["n"] == 3
        assert xs["root"]["args"]["kind"] == "test"
        assert xs["root"]["dur"] >= xs["child"]["dur"] >= 0
        assert any(e.get("name") == "thread_name" for e in events
                   if e["ph"] == "M")
        # export(reset=True) drained the ring
        assert not _spans(_export_events(tmp_path / "e2.json"))

    def test_ring_buffer_bounded(self, monkeypatch, tmp_path):
        monkeypatch.setenv("MXNET_TRACE", "1")
        monkeypatch.setenv("MXNET_TRACE_BUFFER", "8")
        tracing._reset_for_tests()
        try:
            for i in range(20):
                tracing.start_trace("t", i=i).finish()
            events = _export_events(tmp_path / "ring.json")
            spans = _spans(events)
            assert len(spans) == 8
            assert [s["args"]["i"] for s in spans] == list(range(12, 20))
        finally:
            tracing._reset_for_tests()

    def test_cross_thread_flow(self, tr_enabled, tmp_path):
        root = tracing.start_trace("producer")
        ctx = root.context()
        done = threading.Event()

        def consumer():
            with tracing.span("consumer", parent=ctx):
                pass
            done.set()

        threading.Thread(target=consumer).start()
        assert done.wait(5.0)
        root.finish()
        events = _export_events(tmp_path / "x.json")
        xs = {e["name"]: e for e in _spans(events)}
        assert xs["producer"]["args"]["trace"] == \
            xs["consumer"]["args"]["trace"]
        assert xs["producer"]["tid"] != xs["consumer"]["tid"]
        (s,) = [e for e in events if e.get("ph") == "s"]
        (f,) = [e for e in events if e.get("ph") == "f"]
        assert s["id"] == f["id"] == xs["producer"]["args"]["span"]
        assert s["ts"] <= f["ts"]

    def test_finish_idempotent_and_drop_attr(self, tr_enabled, tmp_path):
        root = tracing.start_trace("r")
        sp = tracing.span("queue", parent=root)
        sp.finish(drop="timeout")
        sp.finish(drop="error")  # loses the race: first reason sticks
        root.finish()
        xs = {e["name"]: e for e in _spans(_export_events(tmp_path / "d.json"))}
        assert xs["queue"]["args"]["drop"] == "timeout"

    def test_span_without_active_trace_is_null(self, tr_enabled):
        assert tracing.span("orphan") is tracing.NULL_SPAN

    def test_unconsumed_context_leaves_no_orphan_flow(self, tr_enabled,
                                                      tmp_path):
        """A captured-but-never-bound context (a traced request batched
        behind another trace's owner) must not export an unmatched 's' —
        the anchor rides with the first 'f' bind."""
        root = tracing.start_trace("r")
        root.context()  # captured, never consumed
        root.finish()
        events = _export_events(tmp_path / "u.json")
        assert not [e for e in events if e.get("ph") in ("s", "f")]

    def test_context_bound_twice_keeps_one_s(self, tr_enabled, tmp_path):
        root = tracing.start_trace("r")
        ctx = root.context()
        tracing.span("c1", parent=ctx).finish()
        tracing.span("c2", parent=ctx).finish()
        root.finish()
        events = _export_events(tmp_path / "two.json")
        assert len([e for e in events if e.get("ph") == "s"]) == 1
        assert len([e for e in events if e.get("ph") == "f"]) == 2

    def test_flow_ring_eviction_exports_whole_pairs(self, monkeypatch,
                                                    tmp_path):
        """Oldest-first eviction can cut through an s/f pair; the export
        drops the widowed half so ci/check_trace.py always passes."""
        monkeypatch.setenv("MXNET_TRACE", "1")
        monkeypatch.setenv("MXNET_TRACE_BUFFER", "4")  # flow ring = 8
        tracing._reset_for_tests()
        try:
            for _ in range(10):
                root = tracing.start_trace("r")
                tracing.span("c", parent=root.context()).finish()
                root.finish()
            events = _export_events(tmp_path / "ev.json")
            ct = _load_tool("ci/check_trace.py")
            assert ct.validate(events) == []
        finally:
            tracing._reset_for_tests()


# -- wired hot paths ----------------------------------------------------------
class TestServingTrace:
    def test_request_lifecycle_across_threads(self, tr_enabled, tmp_path):
        """The ISSUE 4 acceptance: one request's queue/assemble/execute
        spans share a trace id across the submit and the device-loop
        threads, flow-linked."""
        from mxnet_tpu.serving import BucketLadder, Engine
        from mxnet_tpu.test_utils import tiny_mlp_checkpoint

        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)}, ladder=BucketLadder((1, 2)),
                    max_wait_ms=1.0, name="tr") as eng:
            for _ in range(3):
                eng.predict({"data": np.zeros((1, 8), np.float32)})
        events = _export_events(tmp_path / "serve.json")
        by_trace = {}
        for e in _spans(events):
            by_trace.setdefault(e["args"]["trace"], []).append(e)
        full = [evs for evs in by_trace.values()
                if {"request", "queue", "classify", "assemble",
                    "execute", "reply"} <= {e["name"] for e in evs}]
        assert full, "no complete request trace"
        evs = full[0]
        tids = {e["tid"] for e in evs}
        assert len(tids) >= 2, "request trace never crossed threads"
        execute = [e for e in evs if e["name"] == "execute"]
        classify = [e for e in evs if e["name"] == "classify"]
        assert execute[0]["tid"] != classify[0]["tid"]
        # predictor dispatch nests under the device-loop execute span
        pf = [e for e in evs if e["name"] == "predictor_forward"]
        assert pf and pf[0]["args"]["parent"] == execute[0]["args"]["span"]
        # flow events pair up and link the handoff
        ids_s = {e["id"] for e in events if e.get("ph") == "s"}
        ids_f = {e["id"] for e in events if e.get("ph") == "f"}
        assert ids_s and ids_f <= ids_s

    def test_drop_reason_lands_on_span(self, tr_enabled, tmp_path):
        from mxnet_tpu.serving import (BucketLadder, Engine, RequestTimeout)
        from mxnet_tpu.test_utils import tiny_mlp_checkpoint

        sym, params = tiny_mlp_checkpoint()
        eng = Engine(sym, params, {"data": (8,)}, ladder=BucketLadder((1,)),
                     max_wait_ms=5.0, start=False, name="drops")
        req = eng.submit({"data": np.zeros((1, 8), np.float32)},
                         timeout=0.001)
        import time

        time.sleep(0.05)  # deadline long expired before the loop starts
        eng.start()
        with pytest.raises(RequestTimeout):
            req.result(5.0)
        eng.close()
        events = _export_events(tmp_path / "drop.json")
        dropped = [e for e in _spans(events)
                   if e["args"].get("drop") == "timeout"]
        assert dropped, "timeout reap never stamped a drop reason"
        names = {e["name"] for e in dropped}
        assert "queue" in names and "request" in names

    def test_sampled_out_requests_record_nothing(self, tr_enabled,
                                                 monkeypatch, tmp_path):
        monkeypatch.setenv("MXNET_TRACE_SAMPLE", "0")
        from mxnet_tpu.serving import BucketLadder, Engine
        from mxnet_tpu.test_utils import tiny_mlp_checkpoint

        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)},
                    ladder=BucketLadder((1, 2))) as eng:
            eng.predict({"data": np.zeros((1, 8), np.float32)})
        assert not _spans(_export_events(tmp_path / "none.json"))


class TestTrainingTrace:
    def _fit(self, batches=2):
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data, name="fc", num_hidden=4)
        net = mx.sym.SoftmaxOutput(fc, name="softmax")
        X = np.random.RandomState(0).randn(8 * batches, 8).astype(np.float32)
        y = np.zeros((8 * batches,), np.float32)
        mod = mx.mod.Module(net)
        mod.fit(mx.io.NDArrayIter(X, y, batch_size=8), num_epoch=1,
                optimizer="sgd")

    def test_fit_step_spans(self, tr_enabled, tmp_path):
        self._fit()
        events = _export_events(tmp_path / "fit.json")
        xs = _spans(events)
        steps = [e for e in xs if e["name"] == "step"]
        assert len(steps) == 2
        assert sorted(s["args"]["step"] for s in steps) == [0, 1]
        by_trace = {}
        for e in xs:
            by_trace.setdefault(e["args"]["trace"], set()).add(e["name"])
        step_traces = [n for n in by_trace.values() if "step" in n]
        assert all({"data_wait", "forward_backward", "update",
                    "update_metric"} <= n for n in step_traces)

    def test_kvstore_spans_nest_in_trace(self, tr_enabled, tmp_path):
        from mxnet_tpu import kvstore

        kv = kvstore.create("local")
        kv.init("w", mx.nd.zeros((4,)))
        out = mx.nd.zeros((4,))
        with tracing.start_trace("step", step=0):
            kv.push("w", mx.nd.ones((4,)))
            kv.pull("w", out=out)
        xs = {e["name"] for e in _spans(_export_events(tmp_path / "kv.json"))}
        assert {"kv_push", "kv_pull", "step"} <= xs


# -- exporter invariants / tools ----------------------------------------------
class TestExportTools:
    def test_export_passes_check_trace(self, tr_enabled, tmp_path):
        from mxnet_tpu.serving import BucketLadder, Engine
        from mxnet_tpu.test_utils import tiny_mlp_checkpoint

        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)},
                    ladder=BucketLadder((1, 2)), max_wait_ms=1.0) as eng:
            for _ in range(4):
                eng.predict({"data": np.zeros((2, 8), np.float32)})
        events = _export_events(tmp_path / "v.json")
        ct = _load_tool("ci/check_trace.py")
        assert ct.validate(events) == []

    def test_check_trace_flags_malformed(self):
        ct = _load_tool("ci/check_trace.py")
        bad_ts = [{"name": "a", "ph": "X", "ts": -1, "dur": 2,
                   "pid": 0, "tid": 0}]
        assert any("bad ts" in p for p in ct.validate(bad_ts))
        overlap = [{"name": "a", "ph": "X", "ts": 0, "dur": 10, "pid": 0,
                    "tid": 0},
                   {"name": "b", "ph": "X", "ts": 5, "dur": 10, "pid": 0,
                    "tid": 0}]
        assert any("must nest" in p for p in ct.validate(overlap))
        orphan_f = [{"ph": "f", "bt": "e", "id": 7, "ts": 1.0, "pid": 0,
                     "tid": 0, "name": "h"}]
        assert any("without an 's'" in p for p in ct.validate(orphan_f))
        unmatched_s = [{"ph": "s", "id": 7, "ts": 1.0, "pid": 0, "tid": 0,
                        "name": "h"}]
        assert any("matching 'f'" in p for p in ct.validate(unmatched_s))
        ok = [{"name": "a", "ph": "X", "ts": 0, "dur": 10, "pid": 0,
               "tid": 0},
              {"name": "b", "ph": "X", "ts": 2, "dur": 3, "pid": 0,
               "tid": 0},
              {"ph": "s", "id": 1, "ts": 1.0, "pid": 0, "tid": 0,
               "name": "h"},
              {"ph": "f", "bt": "e", "id": 1, "ts": 2.0, "pid": 0, "tid": 0,
               "name": "h"}]
        assert ct.validate(ok) == []

    def test_trace_merge_clock_rebase(self, tmp_path):
        tm = _load_tool("tools/trace_merge.py")
        a = {"traceEvents": [
            {"name": "clock_sync", "ph": "M", "pid": 0,
             "args": {"unix_ts": 1000.0, "trace_ts_us": 500.0}},
            {"name": "a", "ph": "X", "ts": 500.0, "dur": 10.0, "pid": 0,
             "tid": 1, "args": {"trace": 1}}]}
        # same wall-clock moment, different trace epoch: b's event is 2s
        # after a's on the shared clock
        b = {"traceEvents": [
            {"name": "clock_sync", "ph": "M", "pid": 0,
             "args": {"unix_ts": 1002.0, "trace_ts_us": 9000.0}},
            {"name": "b", "ph": "X", "ts": 9000.0, "dur": 5.0, "pid": 0,
             "tid": 1},
            {"ph": "s", "id": 3, "ts": 9001.0, "pid": 0, "tid": 1,
             "name": "h"}]}
        pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        json.dump(a, open(pa, "w"))
        json.dump(b, open(pb, "w"))
        out = str(tmp_path / "m.json")
        assert tm.main([pa, pb, "-o", out]) == 0
        evs = json.load(open(out))["traceEvents"]
        ea = [e for e in evs if e.get("name") == "a"][0]
        eb = [e for e in evs if e.get("name") == "b"][0]
        assert eb["ts"] - ea["ts"] == pytest.approx(2e6)  # 2 s in us
        assert eb["pid"] == tm.PID_STRIDE  # namespaced
        (s,) = [e for e in evs if e.get("ph") == "s"]
        assert s["id"] == "m1.3"

    def test_bench_compare_gate(self, tmp_path):
        bc = _load_tool("tools/bench_compare.py")

        def capture(path, value, dps=None, metric="m_imgs_per_sec"):
            line = {"metric": metric, "value": value, "unit": "img/s"}
            if dps is not None:
                line["telemetry"] = {"compile_s": 1.0,
                                     "peak_hbm_bytes": None,
                                     "data_wait_frac": 0.0,
                                     "dispatches_per_step": dps}
            json.dump({"n": 1, "cmd": "x", "rc": 0, "parsed": line},
                      open(path, "w"))
            return path

        base = capture(str(tmp_path / "b.json"), 100.0, dps=1.0)
        ok = capture(str(tmp_path / "ok.json"), 98.0, dps=1.0)
        slow = capture(str(tmp_path / "slow.json"), 80.0, dps=1.0)
        stormy = capture(str(tmp_path / "storm.json"), 100.0, dps=12.0)
        other = capture(str(tmp_path / "other.json"), 1.0,
                        metric="different_metric")
        assert bc.main([base, ok, "--threshold", "5"]) == 0
        assert bc.main([base, slow, "--threshold", "5"]) == 1
        assert bc.main([base, stormy, "--threshold", "5"]) == 1
        # a different metric is reported, never gated
        assert bc.main([base, other, "--threshold", "5"]) == 0
        # bare bench-line files (no driver wrapper) load too
        bare = str(tmp_path / "bare.json")
        json.dump({"metric": "m_imgs_per_sec", "value": 99.0,
                   "unit": "img/s"}, open(bare, "w"))
        assert bc.main([base, bare, "--threshold", "5"]) == 0

    def test_bench_compare_multichip_gate(self, tmp_path):
        """MULTICHIP_r*.json captures diff on ok + dryrun phases (ISSUE 5):
        a capture that lost `ok` or dropped a phase exits non-zero; mixing
        capture kinds is an error."""
        bc = _load_tool("tools/bench_compare.py")
        tail_full = ("dryrun_multichip(8): mesh dp=4 tp=2, loss 2.9 -> 2.0\n"
                     "dryrun_multichip(8): pp gpipe loss 0.006, sp out, "
                     "ep moe loss 0.2 — all phases OK\n"
                     "dryrun_multichip(8): detection dp=8 step loss 5.3 — OK\n"
                     "dryrun_multichip(8): detection ZeRO-sharded state "
                     "(params+momentum over dp): 50.0 MB/device vs 399.4 MB "
                     "replicated, step loss 5.1 — OK\n")

        def capture(path, ok=True, tail=tail_full, skipped=False):
            json.dump({"n_devices": 8, "rc": 0 if ok else 1, "ok": ok,
                       "skipped": skipped, "tail": tail}, open(path, "w"))
            return path

        base = capture(str(tmp_path / "m1.json"))
        same = capture(str(tmp_path / "m2.json"))
        broke = capture(str(tmp_path / "m3.json"), ok=False)
        lost_zero = capture(str(tmp_path / "m4.json"),
                            tail=tail_full.rsplit("dryrun_multichip(8): "
                                                  "detection ZeRO", 1)[0])
        skipped = capture(str(tmp_path / "m5.json"), ok=False, tail="",
                          skipped=True)
        assert bc.main([base, same]) == 0
        assert bc.main([base, broke]) == 1
        assert bc.main([base, lost_zero]) == 1
        # driver had no devices that round: reported, never gated
        assert bc.main([base, skipped]) == 0
        # growing a phase relative to an older baseline is fine
        assert bc.main([lost_zero, base]) == 0
        # mixed kinds refuse loudly
        bench = str(tmp_path / "bench.json")
        json.dump({"metric": "m", "value": 1.0}, open(bench, "w"))
        assert bc.main([base, bench]) == 2
