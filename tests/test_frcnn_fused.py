"""Jit-fused Faster R-CNN VGG16 (model_zoo.detection FasterRCNN) —
BASELINE config 2 (reference example/rcnn/train_end2end.py +
rcnn/symbol/symbol_vgg.py).

Covers: model build (train + inference forwards), class-SPECIFIC bbox
targets/weights, the single-XLA-module train step
(examples/rcnn/train_fused.py make_frcnn_train_step), gradient flow into
every head with the conv1/conv2 FIXED_PARAMS cut, and loss decrease.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

EXDIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "examples", "rcnn"))


def _train_fused():
    # unique module name: a bare ``import train_fused`` collides with the
    # deformable_rfcn example module of the same name when the full suite
    # imports both (test_rfcn_fused.py wins the sys.modules slot)
    from mxnet_tpu.test_utils import load_module_by_path

    return load_module_by_path(os.path.join(EXDIR, "train_fused.py"),
                               "_frcnn_train_fused_tests")


def _tiny_net(**kw):
    from mxnet_tpu.gluon.model_zoo.detection import FasterRCNN

    cfg = dict(classes=3, image_shape=(64, 96),
               filters=(8, 16, 32, 32, 32), units=(1, 1, 1, 1, 1),
               fc_hidden=64, scales=(1, 2), ratios=(0.5, 1, 2),
               rpn_pre_nms=200, rpn_post_nms=32, batch_rois=16,
               rpn_batch=32, max_gts=8)
    cfg.update(kw)
    net = FasterRCNN(**cfg)
    net.initialize()
    return net


def test_model_forward_shapes_train_and_infer():
    mx.random.seed(0)
    net = _tiny_net()
    rng = np.random.RandomState(0)
    B = 2
    x = nd.array(rng.randn(B, 3, 64, 96).astype(np.float32))
    info = nd.array(np.array([[64, 96, 1.0]] * B, np.float32))
    gt = np.full((B, 8, 5), -1.0, np.float32)
    gt[0, 0] = [1, 4, 4, 40, 40]
    gt[1, 0] = [0, 10, 20, 60, 60]
    Hf, Wf = net.feat_shape
    A = net.num_anchors
    C1 = net.classes + 1
    nz1 = nd.array(rng.rand(B, Hf * Wf * A, 2).astype(np.float32))
    nz2 = nd.array(rng.rand(B, net.rpn_post_nms + 8, 2).astype(np.float32))
    outs = net(x, info, nd.array(gt), nz1, nz2)
    assert outs[0].shape == (B, 2 * A, Hf, Wf)        # rpn_cls
    assert outs[5].shape == (B * 16, 5)               # sampled rois
    assert outs[9].shape == (B * 16, C1)              # cls_score
    assert outs[10].shape == (B * 16, 4 * C1)         # class-SPECIFIC deltas
    # class-specific weights: the 4 active columns must sit in the slot of
    # the roi's own class (background rois have all-zero weight)
    label = outs[6].asnumpy()
    bw = outs[8].asnumpy().reshape(B * 16, C1, 4)
    for r in range(B * 16):
        c = int(label[r])
        active = bw[r].sum(axis=1) > 0
        if active.any():
            assert active[c] and active.sum() == 1, (r, c, active)
    rois, prob, deltas = net(x, info)                 # inference path
    assert rois.shape == (B * net.rpn_post_nms, 5)
    assert prob.shape == (B * net.rpn_post_nms, C1)
    assert deltas.shape == (B * net.rpn_post_nms, 4 * C1)
    np.testing.assert_allclose(prob.asnumpy().sum(-1), 1.0, rtol=1e-4)


def test_box_stds_normalization():
    """proposal_target's box_stds divides targets; stds=None leaves raw."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.rcnn_targets import proposal_target

    rng = np.random.RandomState(3)
    rois = np.concatenate(
        [np.zeros((8, 1), np.float32),
         np.sort(rng.rand(8, 4).astype(np.float32) * 60, axis=1)], axis=1)
    gt = np.full((1, 4, 5), -1.0, np.float32)
    gt[0, 0] = [1, 5, 5, 40, 40]
    kw = dict(num_classes=4, batch_images=1, batch_rois=8, fg_fraction=0.5)
    _, _, bt_raw, bw = proposal_target(jnp.asarray(rois), jnp.asarray(gt), **kw)
    _, _, bt_norm, _ = proposal_target(jnp.asarray(rois), jnp.asarray(gt),
                                       box_stds=(0.1, 0.1, 0.2, 0.2), **kw)
    bt_raw, bt_norm, bw = map(np.asarray, (bt_raw, bt_norm, bw))
    act = bw > 0
    assert act.any()
    stds = np.tile([0.1, 0.1, 0.2, 0.2], 4)
    np.testing.assert_allclose(bt_norm[act], (bt_raw / stds[None, :])[act],
                               rtol=1e-5)


def test_fused_step_gradients_reach_every_head():
    import jax

    tf = _train_fused()
    make_frcnn_train_step, synthetic_voc = tf.make_frcnn_train_step, tf.synthetic_voc

    mx.random.seed(1)
    net = _tiny_net()
    rng = np.random.RandomState(1)
    data, im_info, gt = synthetic_voc(rng, 1, (64, 96), 3, net.max_gts)
    net(mx.nd.array(data), mx.nd.array(im_info))  # materialise params

    from mxnet_tpu.gluon.functional import functionalize
    apply, names, vals, aux_names = functionalize(net, train=True)
    learn_names = [n for n in names if n not in set(aux_names)]

    step, state = make_frcnn_train_step(net, 1, learning_rate=0.01,
                                        momentum=0.9)
    jstep = jax.jit(step)
    new_state, loss, parts = jstep(state, data, im_info, gt,
                                   jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
    grads = {n: np.asarray(g) for n, g in zip(learn_names, new_state[1])}
    got = {k: any(np.abs(v).max() > 0 for n, v in grads.items() if k in n)
           for k in ("rpn_cls", "rpn_bbox", "rpn_conv", "fc6", "fc7",
                     "cls_score", "bbox_pred", "conv5_", "conv4_", "conv3_")}
    assert all(got.values()), got
    # FIXED_PARAMS: conv1/conv2 gradients exactly zero (BlockGrad below conv3)
    frozen = [np.abs(v).max() for n, v in grads.items()
              if "conv1_" in n or "conv2_" in n]
    assert frozen and max(frozen) == 0.0


def test_fused_step_trains():
    import jax

    tf = _train_fused()
    make_frcnn_train_step, synthetic_voc = tf.make_frcnn_train_step, tf.synthetic_voc

    mx.random.seed(2)
    net = _tiny_net()
    rng = np.random.RandomState(2)
    data, im_info, gt = synthetic_voc(rng, 1, (64, 96), 3, net.max_gts)
    net(mx.nd.array(data), mx.nd.array(im_info))
    step, state = make_frcnn_train_step(net, 1, learning_rate=0.02,
                                        momentum=0.9)
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    losses = []
    for s in range(10):
        data, im_info, gt = synthetic_voc(rng, 1, (64, 96), 3, net.max_gts)
        state, loss, parts = jstep(state, data, im_info, gt,
                                   jax.random.fold_in(key, s))
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses


def test_eval_decode_roundtrip():
    """decode_detections inverts proposal_target's normalized transform:
    perfect (normalized) deltas for a roi must decode back to the gt box."""
    from mxnet_tpu.ops.rcnn_targets import _bbox_transform
    import importlib.util
    import jax.numpy as jnp

    spec = importlib.util.spec_from_file_location(
        "_eval_frcnn", os.path.join(
            os.path.dirname(__file__), "..", "examples", "quality",
            "eval_frcnn_map.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)

    stds = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
    roi = np.array([[0, 10.0, 12.0, 50.0, 44.0]], np.float32)
    gtb = np.array([[18.0, 6.0, 61.0, 39.0]], np.float32)
    tgt = np.asarray(_bbox_transform(jnp.asarray(roi[:, 1:5]),
                                     jnp.asarray(gtb))) / stds[None]
    C = 3
    cls = 1  # foreground class index
    deltas = np.zeros((1, 4 * (C + 1)), np.float32)
    deltas[0, 4 * (cls + 1): 4 * (cls + 2)] = tgt[0]
    prob = np.zeros((1, C + 1), np.float32)
    prob[0, cls + 1] = 0.9
    dets = m.decode_detections(roi, prob, deltas, C, (96, 96),
                               box_stds=tuple(stds))
    assert dets.shape[0] == 1 and dets[0, 0, 0] == cls
    np.testing.assert_allclose(dets[0, 0, 2:6], gtb[0], atol=0.5)
