"""RecordIO + native data plane tests — mirrors reference
tests/python/unittest/test_recordio.py and the ImageRecordIter coverage in
tests/python/unittest/test_io.py."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu import _native


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(5):
        w.write(("record_%d" % i).encode())
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert r.read() == ("record_%d" % i).encode()
    assert r.read() is None
    r.close()


def test_recordio_embedded_magic(tmp_path):
    """Payloads containing the magic word must round-trip (continuation chunks)."""
    magic = struct.pack("<I", 0xCED7230A)
    payloads = [
        magic,
        b"ab" + magic + b"cd",
        magic + magic + magic,
        b"x" * 37,
        b"",
        b"tail" + magic,
    ]
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for p in payloads:
        assert r.read() == p
    r.close()


def test_native_python_interop(tmp_path):
    """Files written by the native writer parse with the pure-Python reader."""
    if _native.lib() is None:
        pytest.skip("native lib unavailable")
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")  # native
    data = [os.urandom(n) for n in (1, 4, 100, 1024)]
    for d in data:
        w.write(d)
    w.close()
    r = recordio._PyReader(path)
    for d in data:
        assert r.read() == d
    r.close()


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "t")
    w = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(10):
        w.write_idx(i, ("rec_%d" % i).encode())
    w.close()
    assert os.path.isfile(path + ".idx")
    r = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "r")
    assert r.keys == list(range(10))
    assert r.read_idx(7) == b"rec_7"
    assert r.read_idx(2) == b"rec_2"
    r.close()


def test_pack_unpack_label_array():
    label = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    header = recordio.IRHeader(0, label, 42, 0)
    s = recordio.pack(header, b"payload")
    h2, s2 = recordio.unpack(s)
    assert h2.flag == 3
    np.testing.assert_array_equal(h2.label, label)
    assert s2 == b"payload"
    assert h2.id == 42


def _smooth_img(h, w, phase=0.0):
    """Gradient image — JPEG-friendly so decode error stays small."""
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    r = (xx / w) * 255
    g = (yy / h) * 255
    b = ((xx + yy + phase) / (h + w)) % 1.0 * 255
    return np.stack([r, g, b], axis=-1).astype(np.uint8)


def test_pack_img_unpack_img():
    img = _smooth_img(32, 24)
    header = recordio.IRHeader(0, 7.0, 1, 0)
    s = recordio.pack_img(header, img, quality=95)
    h2, img2 = recordio.unpack_img(s)
    assert h2.label == 7.0
    assert img2.shape == (32, 24, 3)
    # JPEG is lossy; high quality should stay close
    assert np.mean(np.abs(img2.astype(np.float32) - img.astype(np.float32))) < 12.0


def _make_rec(tmp_path, n=20, h=18, w=14):
    """Packs n random images with label=i into a .rec file."""
    path = str(tmp_path / "imgs")
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    imgs = []
    for i in range(n):
        img = _smooth_img(h, w, phase=float(i))
        imgs.append(img)
        rec.write_idx(i, recordio.pack_img(recordio.IRHeader(0, float(i), i, 0), img))
    rec.close()
    return path + ".rec", imgs


def test_image_record_iter(tmp_path):
    rec_path, _ = _make_rec(tmp_path, n=10)
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 18, 14), batch_size=4, shuffle=False
    )
    assert len(it) == 10
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 3, 18, 14)
    assert batches[-1].pad == 2
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    assert labels[:10].tolist() == [float(i) for i in range(10)]
    # reset and re-iterate
    it.reset()
    again = list(it)
    assert len(again) == 3
    np.testing.assert_allclose(
        again[0].data[0].asnumpy(), batches[0].data[0].asnumpy(), rtol=1e-6
    )


def test_image_record_iter_decode_values(tmp_path):
    """Pixel values from the pipeline match the packed image (up to JPEG loss)."""
    rec_path, imgs = _make_rec(tmp_path, n=4)
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 18, 14), batch_size=4, shuffle=False
    )
    batch = next(iter(it))
    got = batch.data[0].asnumpy()
    for i in range(4):
        want = imgs[i].astype(np.float32).transpose(2, 0, 1)
        assert np.mean(np.abs(got[i] - want)) < 12.0


def test_image_record_iter_resize_and_normalize(tmp_path):
    rec_path, _ = _make_rec(tmp_path, n=4, h=32, w=32)
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path,
        data_shape=(3, 16, 16),
        batch_size=2,
        mean_r=127.0,
        mean_g=127.0,
        mean_b=127.0,
        std_r=58.0,
        std_g=58.0,
        std_b=58.0,
    )
    batch = next(iter(it))
    arr = batch.data[0].asnumpy()
    assert arr.shape == (2, 3, 16, 16)
    assert np.abs(arr).max() < 4.0  # normalized range


def test_image_record_iter_shuffle_epochs_differ(tmp_path):
    rec_path, _ = _make_rec(tmp_path, n=16)
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 18, 14), batch_size=16, shuffle=True, seed=3
    )
    b1 = next(iter(it)).label[0].asnumpy().copy()
    it.reset()
    b2 = next(iter(it)).label[0].asnumpy().copy()
    assert sorted(b1.tolist()) == sorted(b2.tolist()) == [float(i) for i in range(16)]
    assert not np.array_equal(b1, b2)  # reshuffled across epochs


def test_im2rec_tool(tmp_path):
    from PIL import Image
    import importlib.util

    root = tmp_path / "data"
    for cls in ("cat", "dog"):
        os.makedirs(root / cls)
        for i in range(3):
            arr = (np.random.RandomState(i).rand(20, 20, 3) * 255).astype(np.uint8)
            Image.fromarray(arr).save(root / cls / ("%d.jpg" % i))
    spec = importlib.util.spec_from_file_location(
        "im2rec", os.path.join(os.path.dirname(__file__), "..", "tools", "im2rec.py")
    )
    im2rec = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(im2rec)
    prefix = str(tmp_path / "out")
    images = list(im2rec.list_image(str(root)))
    assert len(images) == 6
    assert {lbl for _, _, lbl in images} == {0, 1}
    im2rec.write_list(prefix + ".lst", images)
    n = im2rec.pack_list(prefix, str(root))
    assert n == 6
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 20, 20), batch_size=6
    )
    labels = next(iter(it)).label[0].asnumpy()
    assert sorted(labels.tolist()) == [0.0, 0.0, 0.0, 1.0, 1.0, 1.0]


class TestImageRecordIterSharding:
    def _make_rec(self, tmp_path, n=11):
        import numpy as np
        from mxnet_tpu import recordio

        fname = str(tmp_path / "shard.rec")
        rec = recordio.MXRecordIO(fname, "w")
        for i in range(n):
            img = np.full((8, 8, 3), i * 20, dtype=np.uint8)
            header = recordio.IRHeader(0, float(i % 3), i, 0)
            rec.write(recordio.pack_img(header, img, quality=95))
        rec.close()
        return fname

    def test_equal_parts_and_validation(self, tmp_path):
        import numpy as np
        import pytest as _pytest
        import mxnet_tpu as mx

        fname = self._make_rec(tmp_path, n=11)
        # 11 records, 2 parts -> both workers truncated to 5
        its = [
            mx.io.ImageRecordIter(
                path_imgrec=fname, data_shape=(3, 8, 8), batch_size=2,
                num_parts=2, part_index=i)
            for i in range(2)
        ]
        assert len(its[0]) == len(its[1]) == 5
        with _pytest.raises(ValueError):
            mx.io.ImageRecordIter(path_imgrec=fname, data_shape=(3, 8, 8),
                                  batch_size=2, num_parts=2, part_index=2)

    def test_pad_and_scale_python_path(self, tmp_path):
        import numpy as np
        import mxnet_tpu as mx

        fname = self._make_rec(tmp_path, n=4)
        it = mx.io.ImageRecordIter(
            path_imgrec=fname, data_shape=(3, 8, 8), batch_size=2,
            pad=2, rand_crop=True, max_random_scale=1.2, min_random_scale=0.9)
        batch = next(iter(it))
        assert batch.data[0].shape == (2, 3, 8, 8)

    def test_unknown_aug_warns(self, tmp_path):
        import warnings as _w
        import mxnet_tpu as mx

        fname = self._make_rec(tmp_path, n=4)
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            mx.io.ImageRecordIter(
                path_imgrec=fname, data_shape=(3, 8, 8), batch_size=2,
                max_random_rotate_angle=10)
            assert any("IGNORED" in str(x.message) for x in rec)
