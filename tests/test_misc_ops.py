"""Long-tail op parity tests (ops/misc_ops.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym, autograd
from mxnet_tpu.ops.registry import get as _get
from mxnet_tpu.ndarray import _invoke


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def test_hard_sigmoid_reshape_like_square_sum(rng):
    x = rng.randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(
        nd.hard_sigmoid(nd.array(x)).asnumpy(), np.clip(0.2 * x + 0.5, 0, 1), rtol=1e-5)
    np.testing.assert_allclose(
        nd.reshape_like(nd.array(x), nd.array(np.zeros((4, 3)))).asnumpy(), x.reshape(4, 3))
    np.testing.assert_allclose(
        nd.square_sum(nd.array(x), axis=1).asnumpy(), (x * x).sum(1), rtol=1e-5)


def test_ravel_unravel(rng):
    idx = np.array([[0, 1, 2], [1, 0, 3]], np.float32)
    rv = nd.ravel_multi_index(nd.array(idx), shape=(3, 4))
    np.testing.assert_allclose(rv.asnumpy(), [1, 4, 11])
    ur = nd.unravel_index(nd.array(np.array([1, 4, 11], np.float32)), shape=(3, 4))
    np.testing.assert_allclose(ur.asnumpy(), idx)


def test_slice_assign():
    out = _get("_slice_assign")(
        np.zeros((4, 4), np.float32), np.ones((2, 2), np.float32), begin=(1, 1), end=(3, 3))
    assert out.sum() == 4 and out[1, 1] == 1 and out[0, 0] == 0
    out2 = _get("_slice_assign_scalar")(
        np.zeros((4, 4), np.float32), begin=(0, 0), end=(2, 4), scalar=7.0)
    assert out2[0, 0] == 7 and out2[3, 3] == 0


def test_image_ops(rng):
    img = (rng.rand(5, 6, 3) * 255).astype(np.uint8)
    tt = np.asarray(_get("_image_to_tensor")(img))
    assert tt.shape == (3, 5, 6) and tt.max() <= 1.0
    nrm = np.asarray(_get("_image_normalize")(tt, mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2)))
    np.testing.assert_allclose(nrm, (tt - 0.5) / 0.2, rtol=1e-5)


def test_v1_aliases_and_make_loss():
    s = sym.Convolution_v1(sym.Variable("d"), kernel=(3, 3), num_filter=2)
    _, osh, _ = s.infer_shape(d=(1, 3, 8, 8))
    assert osh[0] == (1, 2, 6, 6)
    s2 = sym.Pooling_v1(sym.Variable("d"), kernel=(2, 2), stride=(2, 2), pool_type="max")
    _, osh2, _ = s2.infer_shape(d=(1, 2, 8, 8))
    assert osh2[0] == (1, 2, 4, 4)
    assert sym.make_loss(sym.Variable("x")) is not None
    assert _get("BatchNorm_v1").name == "BatchNorm"
    assert _get("_grad_add").name == "elemwise_add"


def test_sparse_adagrad_update(rng):
    w0 = rng.randn(4).astype(np.float32)
    g0 = rng.randn(4).astype(np.float32)
    w = nd.array(w0); h = nd.zeros((4,))
    _invoke(_get("_sparse_adagrad_update"), (w, nd.array(g0), h), {"lr": 0.1, "out": w})
    np.testing.assert_allclose(h.asnumpy(), g0 * g0, rtol=1e-5)
    expect = w0 - 0.1 * g0 / (np.sqrt(g0 * g0) + 1e-7)
    np.testing.assert_allclose(w.asnumpy(), expect, rtol=1e-4)


def test_kl_sparse_reg_graph_mode(rng):
    """simple_bind path: aux moving_avg allocated, updated with COLUMN means."""
    s = sym.IdentityAttachKLSparseReg(sym.Variable("d"), momentum=0.0, name="klreg")
    exe = s.simple_bind(d=(8, 5))
    dv = rng.rand(8, 5).astype(np.float32)
    exe.forward(is_train=True, d=nd.array(dv))
    aux_names = s.list_auxiliary_states()
    assert aux_names, "moving_avg aux missing"
    avg = exe.aux_dict[aux_names[0]].asnumpy()
    np.testing.assert_allclose(avg, dv.mean(axis=0), rtol=1e-4)


def test_kl_sparse_reg_grad(rng):
    d = nd.array(rng.rand(8, 5).astype(np.float32) * 0.5 + 0.25)
    d.attach_grad()
    with autograd.record():
        y = nd.IdentityAttachKLSparseReg(d, penalty=0.01, sparseness_target=0.1)
        loss = y.sum()
    loss.backward()
    g = d.grad.asnumpy()
    # identity forward
    np.testing.assert_allclose(y.asnumpy(), d.asnumpy())
    # penalty term: -rho/rho_hat + (1-rho)/(1-rho_hat), rho_hat = col means
    rho_hat = d.asnumpy().mean(axis=0)
    reg = 0.01 * (-0.1 / rho_hat + 0.9 / (1 - rho_hat))
    np.testing.assert_allclose(g, 1.0 + np.broadcast_to(reg, g.shape), rtol=1e-4)
