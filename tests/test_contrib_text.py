"""Contrib text/autograd/rtc tests — mirrors reference
tests/python/unittest/test_contrib_text.py + contrib autograd API."""
import collections

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib import text


class TestVocabulary:
    def test_counter_indexing(self):
        counter = collections.Counter(["a", "b", "b", "c", "c", "c", "some_word$"])
        v = text.vocab.Vocabulary(counter, most_freq_count=None, min_freq=1,
                                  unknown_token="<unk>", reserved_tokens=["<pad>"])
        assert len(v) == 6
        assert v.token_to_idx["<unk>"] == 0
        assert v.token_to_idx["<pad>"] == 1
        assert v.idx_to_token[2] == "c"  # most frequent first
        assert v.to_indices("c") == 2
        assert v.to_indices(["c", "nope"]) == [2, 0]
        assert v.to_tokens([0, 2]) == ["<unk>", "c"]
        with pytest.raises(ValueError):
            v.to_tokens(100)

    def test_min_freq_and_cap(self):
        counter = collections.Counter(["a"] * 5 + ["b"] * 3 + ["c"])
        v = text.vocab.Vocabulary(counter, min_freq=2)
        assert "c" not in v.token_to_idx
        v2 = text.vocab.Vocabulary(counter, most_freq_count=1)
        assert len(v2) == 2  # unk + a

    def test_count_tokens(self):
        c = text.utils.count_tokens_from_str("a b  b\nc C", to_lower=True)
        assert c["b"] == 2 and c["c"] == 2 and c["a"] == 1


class TestEmbedding:
    def _write_emb(self, tmp_path):
        p = tmp_path / "emb.txt"
        p.write_text("hello 1 2 3\nworld 4 5 6\n")
        return str(p)

    def test_custom_embedding(self, tmp_path):
        emb = text.embedding.CustomEmbedding(self._write_emb(tmp_path))
        assert emb.vec_len == 3
        np.testing.assert_allclose(
            emb.get_vecs_by_tokens("world").asnumpy(), [4, 5, 6])
        np.testing.assert_allclose(
            emb.get_vecs_by_tokens(["nope"]).asnumpy(), [[0, 0, 0]])
        emb.update_token_vectors("hello", nd.array(np.array([[9., 9, 9]], np.float32)))
        np.testing.assert_allclose(emb.get_vecs_by_tokens("hello").asnumpy(), [9, 9, 9])

    def test_with_vocabulary_and_composite(self, tmp_path):
        path = self._write_emb(tmp_path)
        counter = collections.Counter(["hello", "nope"])
        vocab = text.vocab.Vocabulary(counter)
        emb = text.embedding.CustomEmbedding(path, vocabulary=vocab)
        assert len(emb) == len(vocab)
        comp = text.embedding.CompositeEmbedding(
            vocab, [text.embedding.CustomEmbedding(path)])
        assert comp.idx_to_vec.shape == (len(vocab), 3)

    def test_vocabulary_reorder_fetches_right_rows(self, tmp_path):
        """Vocabulary whose token order differs from file order must still
        map each token to its own vector (reference :344 layout-then-reindex)."""
        path = self._write_emb(tmp_path)  # file order: hello, world
        vocab = text.vocab.Vocabulary(collections.Counter(["world"]))  # world at idx 1
        emb = text.embedding.CustomEmbedding(path, vocabulary=vocab)
        np.testing.assert_allclose(emb.get_vecs_by_tokens("world").asnumpy(), [4, 5, 6])

    def test_reserved_tokens_load(self, tmp_path):
        emb = text.embedding.CustomEmbedding(
            self._write_emb(tmp_path), reserved_tokens=["<pad>"])
        assert len(emb) == 4  # unk, pad, hello, world
        np.testing.assert_allclose(emb.get_vecs_by_tokens("hello").asnumpy(), [1, 2, 3])
        np.testing.assert_allclose(emb.get_vecs_by_tokens("<pad>").asnumpy(), [0, 0, 0])

    def test_negative_index_rejected(self):
        v = text.vocab.Vocabulary(collections.Counter(["a"]))
        with pytest.raises(ValueError):
            v.to_tokens(-1)

    def test_regex_delim_escaped(self):
        c = text.utils.count_tokens_from_str("a.b.c", token_delim=".")
        assert c == collections.Counter({"a": 1, "b": 1, "c": 1})

    def test_registry(self, tmp_path):
        names = text.embedding.get_pretrained_file_names()
        assert "glove" in names and "fasttext" in names
        emb = text.embedding.create("customembedding",
                                    pretrained_file_path=self._write_emb(tmp_path))
        assert emb.vec_len == 3
        with pytest.raises(ValueError):
            text.embedding.GloVe(pretrained_file_path=str(tmp_path / "missing.txt"))


class TestLegacyAutograd:
    def test_grad_and_loss(self):
        from mxnet_tpu.contrib import autograd as cag

        x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))

        @cag.grad_and_loss
        def f(a):
            return a * a

        grads, loss = f(x)
        np.testing.assert_allclose(grads[0].asnumpy(), [2, 4, 6], rtol=1e-5)

    def test_stale_marked_vars_keep_grads(self):
        """A later unrelated backward must not zero gradient buffers already
        returned for earlier graphs."""
        from mxnet_tpu.contrib import autograd as cag

        x = nd.array(np.array([1.0, 2.0], np.float32))
        grads1, _ = cag.grad_and_loss(lambda a: a * a)(x)
        got = grads1[0].asnumpy().copy()
        np.testing.assert_allclose(got, [2, 4], rtol=1e-5)
        y = nd.array(np.array([5.0], np.float32))
        cag.grad_and_loss(lambda b: b * 3)(y)  # x still alive, not involved
        np.testing.assert_allclose(grads1[0].asnumpy(), got, rtol=1e-5)

    def test_train_test_section(self):
        from mxnet_tpu.contrib import autograd as cag
        from mxnet_tpu import autograd as ag

        assert not ag.is_recording()
        with cag.train_section():
            assert ag.is_recording() and ag.is_training()
            with cag.test_section():
                assert ag.is_recording() and not ag.is_training()
            assert ag.is_training()
        assert not ag.is_recording()


class TestRtc:
    def test_cuda_module_raises_with_guidance(self):
        with pytest.raises(mx.base.MXNetError, match="[Pp]allas"):
            mx.rtc.CudaModule("__global__ void k(){}")
