"""Cross-backend consistency: CPU vs TPU forward+backward per op.

Port of the reference's ``check_consistency`` discipline
(``python/mxnet/test_utils.py:1207`` — the same symbol is run on a context
list and outputs/gradients are cross-compared with dtype-aware tolerances;
the GPU test tier re-runs the whole unit suite this way, SURVEY §4.1).

Here the context list is {CPU backend, TPU chip}: each case is a pure jax
function run jitted on both backends under ``default_matmul_precision
('highest')`` (numerics comparison, not a speed test), comparing outputs
and — for float inputs — VJP gradients against a fixed cotangent.

Runs on the bench chip: ``cd /root/repo && python -m pytest
tests/test_consistency_tpu.py`` (bare env — the axon plugin needs
PYTHONPATH unset).  Under ``./dev.sh`` (CPU-only) every case skips.
"""
import functools

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401 — populates the registry
from mxnet_tpu.ops import registry


def _tpu_device():
    import jax

    for d in jax.devices():
        if d.platform == "tpu":
            return d
    return None


def _cpu_device():
    import jax

    return jax.devices("cpu")[0]


requires_tpu = pytest.mark.skipif(
    _tpu_device() is None, reason="no TPU backend attached (CPU-only env)")

_R = np.random.RandomState(7)


def _d(*shape, lo=-1.0, hi=1.0):
    return (_R.rand(*shape) * (hi - lo) + lo).astype(np.float32)


def _run(dev, fn, args, with_grad, dtype=None):
    import jax
    import jax.numpy as jnp

    def cast(a):
        a = np.asarray(a)
        if dtype is not None and np.issubdtype(a.dtype, np.floating):
            return a.astype(jnp.dtype(dtype))  # ml_dtypes bfloat16 via jnp
        return a

    ja = [jax.device_put(cast(a), dev) for a in args]
    with jax.default_matmul_precision("highest"):
        if not with_grad:
            out = jax.jit(fn)(*ja)
            return [np.asarray(o) for o in jax.tree_util.tree_leaves(out)], []

        def scalarized(*xs):
            out = fn(*xs)
            leaves = jax.tree_util.tree_leaves(out)
            # fixed deterministic cotangent: sum of o * cos(iota)
            s = 0.0
            for o in leaves:
                if jnp.issubdtype(o.dtype, jnp.floating):
                    w = jnp.cos(jnp.arange(o.size, dtype=jnp.float32)).reshape(o.shape)
                    s = s + jnp.sum(o.astype(jnp.float32) * w)
            return s, leaves

        grad_fn = jax.grad(scalarized, argnums=tuple(
            i for i, a in enumerate(args)
            if np.issubdtype(np.asarray(a).dtype, np.floating)), has_aux=True)
        grads, leaves = jax.jit(grad_fn)(*ja)
        return ([np.asarray(o) for o in leaves],
                [np.asarray(g) for g in grads])


def _check(fn, args, with_grad=True, rtol=2e-3, atol=2e-3, dtype=None):
    cpu_out, cpu_g = _run(_cpu_device(), fn, args, with_grad, dtype)
    tpu_out, tpu_g = _run(_tpu_device(), fn, args, with_grad, dtype)
    for i, (c, t) in enumerate(zip(cpu_out, tpu_out)):
        np.testing.assert_allclose(
            np.asarray(t, np.float32), np.asarray(c, np.float32),
            rtol=rtol, atol=atol, err_msg="output %d" % i)
    for i, (c, t) in enumerate(zip(cpu_g, tpu_g)):
        np.testing.assert_allclose(
            np.asarray(t, np.float32), np.asarray(c, np.float32),
            rtol=rtol, atol=atol, err_msg="grad %d" % i)


def _op(name, **attrs):
    fn = registry.get(name)
    return functools.partial(fn, **attrs) if attrs else fn


# --------------------------------------------------------------------------
# the sweep: (id, fn, args, with_grad, tolerances)
#
# ``bf16=True`` (or a tolerance dict) additionally emits a bfloat16 variant
# of the case — the dtype production actually trains in (VERDICT round-2
# item 2; reference check_consistency includes fp16 the same way,
# test_utils.py:470,1207).  bf16 tolerances default to 4e-2: inputs are
# rounded to 8 mantissa bits on BOTH backends, so remaining divergence is
# accumulation order, but one bf16 ulp at |x|~1 is 2^-8 ≈ 4e-3 and errors
# compound through reductions.
# --------------------------------------------------------------------------
BF16_TOL = dict(rtol=4e-2, atol=4e-2)


def _cases():
    C = []

    def add(name, fn, args, with_grad=True, bf16=None, **tol):
        C.append(pytest.param(fn, args, with_grad, dict(tol), id=name))
        if bf16:
            btol = dict(BF16_TOL)
            if isinstance(bf16, dict):
                btol.update(bf16)
            btol["dtype"] = "bfloat16"
            C.append(pytest.param(fn, args, with_grad, btol, id=name + "_bf16"))

    # elemwise / math (12)
    for u in ["sigmoid", "tanh", "exp", "log", "sqrt", "square", "erf",
              "softsign", "log1p", "rsqrt", "sin", "arctan"]:
        x = _d(4, 5, lo=0.2, hi=2.0)
        add(u, _op(u), [x], bf16=u in ("sigmoid", "tanh", "exp", "erf"))
    # binary + broadcast (6)
    add("broadcast_add", _op("broadcast_add"), [_d(3, 1, 4), _d(1, 2, 4)], bf16=True)
    add("broadcast_mul", _op("broadcast_mul"), [_d(3, 1, 4), _d(1, 2, 4)], bf16=True)
    add("broadcast_div", _op("broadcast_div"), [_d(3, 1, 4), _d(1, 2, 4, lo=0.5, hi=2.0)])
    add("broadcast_maximum", _op("broadcast_maximum"), [_d(3, 4), _d(3, 4)])
    add("dot", _op("dot"), [_d(6, 7), _d(7, 5)], bf16=True)
    add("batch_dot", _op("batch_dot"), [_d(3, 4, 5), _d(3, 5, 6)], bf16=True)
    # reductions (6)
    add("sum_axis", _op("sum", axis=1), [_d(4, 5, 6)], bf16=True)
    add("mean", _op("mean", axis=(0, 2)), [_d(4, 5, 6)], bf16=True)
    add("max", _op("max", axis=1), [_d(4, 5, 6)], bf16=True)
    add("prod", _op("prod", axis=2), [_d(3, 4, 5, lo=0.5, hi=1.5)], bf16=True)
    add("norm", _op("norm"), [_d(4, 5)], bf16=True)
    add("topk", _op("topk", k=3, axis=-1, ret_typ="value"), [_d(4, 9)], False)
    # nn core (12)
    add("Convolution", _op("Convolution", kernel=(3, 3), num_filter=8, pad=(1, 1)),
        [_d(2, 4, 9, 9), _d(8, 4, 3, 3), _d(8)], bf16=True)
    add("Convolution_stride", _op("Convolution", kernel=(3, 3), num_filter=6,
                                  stride=(2, 2), no_bias=True),
        [_d(2, 3, 11, 11), _d(6, 3, 3, 3)], bf16=True)
    add("Deconvolution", _op("Deconvolution", kernel=(2, 2), num_filter=5,
                             stride=(2, 2), no_bias=True),
        [_d(2, 3, 5, 5), _d(3, 5, 2, 2)], bf16=True)
    add("FullyConnected", _op("FullyConnected", num_hidden=7),
        [_d(4, 10), _d(7, 10), _d(7)], bf16=True)
    add("Pooling_max", _op("Pooling", kernel=(2, 2), pool_type="max", stride=(2, 2)),
        [_d(2, 3, 8, 8)], bf16=True)
    add("Pooling_avg", _op("Pooling", kernel=(3, 3), pool_type="avg", pad=(1, 1)),
        [_d(2, 3, 8, 8)], bf16=True)
    add("softmax", _op("softmax", axis=-1), [_d(4, 9)], bf16=True)
    add("log_softmax", _op("log_softmax", axis=-1), [_d(4, 9)], bf16=True)
    add("Activation_relu", _op("Activation", act_type="relu"), [_d(4, 5)], bf16=True)
    add("LeakyReLU_elu", _op("LeakyReLU", act_type="elu", slope=0.3), [_d(4, 5)])
    add("LayerNorm", _op("LayerNorm"), [_d(4, 6), _d(6, lo=0.5, hi=1.5), _d(6)], bf16=True)
    add("L2Normalization", _op("L2Normalization"), [_d(3, 4, 5)], bf16=True)
    # BatchNorm fwd (aux mutation excluded from grad comparison)
    bn = _op("BatchNorm", fix_gamma=False)
    add("BatchNorm", lambda x, g, b, mm, mv: bn(x, g, b, mm, mv)[0],
        [_d(3, 4, 5, 5), _d(4, lo=0.5, hi=1.5), _d(4),
         np.zeros(4, np.float32), np.ones(4, np.float32)], bf16=True)
    # shape / indexing (8)
    add("transpose", _op("transpose", axes=(0, 2, 1)), [_d(3, 4, 5)])
    add("Reshape", _op("Reshape", shape=(0, -1)), [_d(3, 4, 5)])
    add("take", _op("take"), [_d(5, 4), np.array([0, 3, 1], np.float32)])
    add("gather_nd", _op("gather_nd"),
        [_d(4, 5), np.array([[0, 2], [1, 3]], np.float32)])
    add("Embedding", _op("Embedding", input_dim=10, output_dim=4),
        [np.array([1, 4, 7], np.float32), _d(10, 4)])
    add("one_hot", _op("one_hot", depth=6), [np.array([0, 3, 5], np.float32)], False)
    add("where", _op("where"),
        [(_d(3, 4) > 0).astype(np.float32), _d(3, 4), _d(3, 4)])
    add("Concat", _op("Concat", dim=1), [_d(2, 3), _d(2, 4)])
    # sequence / rnn-ish (3)
    add("SequenceMask", _op("SequenceMask", use_sequence_length=True, value=-1.0),
        [_d(5, 3, 2), np.array([2, 5, 1], np.float32)])
    add("SwapAxis", _op("SwapAxis", dim1=0, dim2=2), [_d(3, 4, 5)])
    add("slice_axis", _op("slice_axis", axis=1, begin=1, end=4), [_d(3, 5, 2)])
    # losses (3)
    add("smooth_l1", _op("smooth_l1", scalar=2.0), [_d(4, 5)], bf16=True)
    add("softmax_cross_entropy", _op("softmax_cross_entropy"),
        [_d(4, 6), np.array([0, 2, 5, 1], np.float32)], bf16=True)
    add("SoftmaxOutput", _op("SoftmaxOutput"),
        [_d(4, 6), np.array([0, 2, 5, 1], np.float32)], False, bf16=True)
    # detection set (10) — the north-star ops
    rois = np.concatenate([
        np.zeros((8, 1), np.float32),
        np.sort(_R.rand(8, 2, 2).astype(np.float32) * 12, axis=1).reshape(8, 4)],
        axis=1)
    rois[:, 3:] += 2.0
    add("ROIPooling", _op("ROIPooling", pooled_size=(3, 3), spatial_scale=0.5),
        [_d(1, 4, 10, 10), rois], bf16=True)
    add("ROIPooling_grouped",  # the Faster-RCNN head's gather-free path
        _op("ROIPooling", pooled_size=(3, 3), spatial_scale=0.5,
            rois_per_image=8),
        [_d(1, 4, 10, 10), rois], bf16=True)
    add("ROIAlign", _op("_contrib_ROIAlign", pooled_size=(3, 3),
                        spatial_scale=0.5, sample_ratio=2),
        [_d(1, 4, 10, 10), rois], bf16=True)
    add("PSROIPooling", _op("_contrib_PSROIPooling", spatial_scale=0.5,
                            output_dim=2, pooled_size=3),
        [_d(1, 18, 10, 10), rois], bf16=True)
    add("DefPSROIPooling_gather",
        _op("_contrib_DeformablePSROIPooling", spatial_scale=0.5, output_dim=2,
            group_size=3, pooled_size=3, part_size=3, trans_std=0.1),
        [_d(1, 18, 10, 10), rois, 0.2 * _d(8, 2, 3, 3)], bf16=True)
    bigrois = np.tile(rois, (40, 1))
    add("DefPSROIPooling_matmul",
        _op("_contrib_DeformablePSROIPooling", spatial_scale=0.5, output_dim=2,
            group_size=3, pooled_size=3, part_size=3, trans_std=0.1),
        [_d(1, 18, 10, 10), bigrois, 0.2 * _d(320, 2, 3, 3)], bf16=True)
    add("DeformableConvolution",
        _op("_contrib_DeformableConvolution", kernel=(3, 3), num_filter=6,
            pad=(1, 1), num_deformable_group=2, no_bias=True),
        [_d(1, 4, 8, 8), 0.5 * _d(1, 36, 8, 8), _d(6, 4, 3, 3)], bf16=True)
    add("DeformableConvolution_matmul",  # K2·Ho·Wo·H·W ≥ 2^22 → the
        # separable one-hot-matmul sampling path (the res5 hot path).
        # fp32 only: with 7k offset-driven samples, bf16-rounded offsets
        # flip floor() bins for ~2% of samples vs the f32 oracle (the same
        # score-discontinuity rationale that excludes bf16 MultiProposal)
        _op("_contrib_DeformableConvolution", kernel=(3, 3), num_filter=6,
            pad=(1, 1), num_deformable_group=2, no_bias=True),
        [_d(1, 4, 28, 28), 0.5 * _d(1, 36, 28, 28), _d(6, 4, 3, 3)],
        bf16=False)
    add("MultiProposal",
        _op("_contrib_MultiProposal", rpn_pre_nms_top_n=60, rpn_post_nms_top_n=12,
            scales=(4, 8), ratios=(0.5, 1, 2), feature_stride=16, rpn_min_size=4),
        [np.sort(_R.rand(1, 12, 5, 7).astype(np.float32), axis=1),  # 2A=12
         0.1 * _d(1, 24, 5, 7), np.array([[80, 112, 1.0]], np.float32)], False)
    # (no bf16 MultiProposal/box_nms variants: bf16-rounded scores collapse
    # into exact ties and CPU/TPU break them in different orders — discrete
    # keep-set divergence no numeric tolerance can absorb, like plain topk)
    nmsdat = np.concatenate([
        _R.randint(0, 3, (1, 64, 1)).astype(np.float32),
        _R.rand(1, 64, 1).astype(np.float32),
        np.sort(_R.rand(1, 64, 2, 2) * 20, axis=2).reshape(1, 64, 4).astype(np.float32),
    ], axis=2)
    add("box_nms", _op("_contrib_box_nms", overlap_thresh=0.5, coord_start=2,
                       score_index=1, id_index=0), [nmsdat], False)
    # big-N variant: N>=1024 routes through the Pallas NMS kernel on TPU
    # (ops/pallas_kernels.nms_alive_pallas) while CPU stays on the XLA
    # formulation — this case cross-checks the two implementations on the
    # actual hardware dispatch boundary
    nmsbig = np.concatenate([
        _R.randint(0, 8, (1, 1536, 1)).astype(np.float32),
        _R.rand(1, 1536, 1).astype(np.float32),
        np.sort(_R.rand(1, 1536, 2, 2) * 300, axis=2).reshape(1, 1536, 4).astype(np.float32),
    ], axis=2)
    add("box_nms_pallas_dispatch",
        _op("_contrib_box_nms", overlap_thresh=0.5, coord_start=2,
            score_index=1, id_index=0), [nmsbig], False)
    add("box_iou", _op("_contrib_box_iou"),
        [np.sort(_R.rand(6, 2, 2) * 10, axis=1).reshape(6, 4).astype(np.float32),
         np.sort(_R.rand(4, 2, 2) * 10, axis=1).reshape(4, 4).astype(np.float32)], bf16=True)
    anchors = np.sort(_R.rand(1, 20, 2, 2), axis=2).reshape(1, 20, 4).astype(np.float32)
    lab = np.full((1, 3, 5), -1.0, np.float32)
    lab[0, 0] = [1, 0.1, 0.1, 0.6, 0.7]
    add("MultiBoxTarget", _op("_contrib_MultiBoxTarget"),
        [anchors, lab, _d(1, 2, 20)], False, bf16=True)
    # rcnn targets (2)
    gt = np.full((1, 4, 5), -1.0, np.float32)
    gt[0, 0] = [0, 4, 4, 40, 40]
    gt[0, 1] = [2, 20, 10, 70, 60]
    add("rpn_anchor_target",
        _op("_contrib_rpn_anchor_target", feat_height=5, feat_width=6,
            feature_stride=16, scales=(2, 4), ratios=(0.5, 1, 2), batch_rois=32),
        [gt, np.array([[80, 96, 1.0]], np.float32)], False, bf16=True)
    prois = np.concatenate([
        np.zeros((20, 1), np.float32),
        np.sort(_R.rand(20, 2, 2) * 60, axis=1).reshape(20, 4).astype(np.float32)],
        axis=1)
    add("proposal_target",
        _op("_contrib_proposal_target", num_classes=4, batch_images=1,
            batch_rois=8), [prois, gt], False, bf16=True)
    # linalg (3)
    spd = _d(4, 4)
    spd = spd @ spd.T + 4 * np.eye(4, dtype=np.float32)
    add("linalg_potrf", _op("_linalg_potrf"), [spd])
    add("linalg_gemm2", _op("_linalg_gemm2"), [_d(3, 4), _d(4, 5)], bf16=True)
    add("linalg_sumlogdiag", _op("_linalg_sumlogdiag"), [spd])
    return C


@requires_tpu
@pytest.mark.parametrize("fn,args,with_grad,tol", _cases())
def test_cpu_tpu_consistency(fn, args, with_grad, tol):
    _check(fn, args, with_grad=with_grad, **tol)
