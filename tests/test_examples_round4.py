"""Round-4 example E2E tests (nightly tier): the Faster-RCNN VGG16
fused recipe (BASELINE config 2, reference example/rcnn/train_end2end.py)
runs end-to-end as a script and learns."""
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _run(script, *args, timeout=3600):
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, script), *args],
        capture_output=True, text=True, timeout=timeout)
    tail = "\n".join(res.stdout.splitlines()[-8:]) + res.stderr[-2000:]
    assert res.returncode == 0, "%s failed:\n%s" % (script, tail)
    return res.stdout


def test_frcnn_train_fused_script():
    out = _run("examples/rcnn/train_fused.py",
               "--steps", "40", "--lr", "0.02")
    assert "FASTER-RCNN FUSED TRAIN OK" in out


def test_frcnn_train_fused_bench_mode():
    """--bench exercises the donated-state chained-step bench path."""
    out = _run("examples/rcnn/train_fused.py",
               "--bench", "--bench-iters", "2")
    assert "frcnn_fused_bench:" in out
