"""Pod observability plane (ISSUE 19, telemetry/podplane.py).

Coverage demanded by the issue's merge-semantics satellite plus the
tentpole contracts:
- the off path: ``MXNET_POD_METRICS`` unset ⇒ no plane, no thread, no
  socket, registry and ops_server untouched, ``/podz`` still routable;
- histogram sub-bucket merge is exact: associative, order-independent,
  and equal to observing the union (the slo.py encoding's point);
- rank-labeled counter collisions are SUMMED in the fleet rollup, never
  clobbered; pushed series mirror under ``pod_``-prefixed rank-labeled
  gauges without colliding with rank 0's local series;
- a stale snapshot (rank restart with an older incarnation epoch, or an
  out-of-order seq) is dropped with a counter;
- ledger divergence fires per key on flops/bytes mismatch (compile_s is
  skew, not divergence), with a flight-recorder dump naming key+ranks;
- straggler verdicts are edge-triggered with hysteresis;
- incidents mint once per (rank, reason) window and broadcast over the
  push channel, tagging a dump on the pushing rank;
- the fit loop feeds ``note_step`` when the gate is on.
"""
import glob
import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from mxnet_tpu.telemetry import flightrec, ops_server, podplane
from mxnet_tpu.telemetry import instrument as tin
from mxnet_tpu.telemetry.registry import MetricError
from mxnet_tpu.telemetry.slo import NBUCKETS, WindowedQuantile, \
    quantile_of_counts


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _snap(rank, epoch=100.0, seq=1, steps=0, hist=None, metrics=(),
          ledger=None, **kw):
    base = {"v": 1, "rank": rank, "size": 2, "epoch": epoch, "seq": seq,
            "unix_ts": time.time(), "steps": steps,
            "step_hist": list(hist) if hist is not None
            else [0] * (NBUCKETS + 2),
            "metrics": list(metrics), "healthz": None,
            "heartbeat_age_s": None, "flightrec": False,
            "ledger": dict(ledger or {}), "slo_breaches": 0, "nonfinite": 0}
    base.update(kw)
    return base


@pytest.fixture
def pod_off(monkeypatch):
    for var in ("MXNET_POD_METRICS", "MXNET_POD_METRICS_ADDR",
                "MXNET_POD_PUSH_S", "MXNET_COORDINATOR"):
        monkeypatch.delenv(var, raising=False)
    podplane._reset_for_tests()
    yield
    podplane._reset_for_tests()


@pytest.fixture
def pod_on(monkeypatch, tmp_path):
    """Gate on, instant pushes, a real loopback channel, frec armed."""
    port = _free_port()
    monkeypatch.setenv("MXNET_POD_METRICS", "1")
    monkeypatch.setenv("MXNET_POD_METRICS_ADDR", "127.0.0.1:%d" % port)
    monkeypatch.setenv("MXNET_POD_PUSH_S", "0")
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path / "frec"))
    podplane._reset_for_tests()
    flightrec._reset_for_tests()
    yield ("127.0.0.1", port), tmp_path
    podplane._reset_for_tests()
    flightrec._reset_for_tests()


# -- off path -----------------------------------------------------------------
class TestOffPath:
    def test_no_plane_no_thread_no_socket(self, pod_off):
        before = {t.name for t in threading.enumerate()}
        assert podplane.plane() is None
        assert podplane.plane() is None  # stable, never lazily flips on
        assert podplane.status() is None
        assert podplane.podz() == {"enabled": False}
        after = {t.name for t in threading.enumerate()}
        assert before == after  # zero new threads (ergo zero listeners)

    def test_registry_untouched(self, pod_off, monkeypatch, tmp_path):
        """Telemetry ON but the pod gate OFF: exercising the module-level
        surfaces adds nothing to the registry — the pod plane is invisible
        to /metrics until explicitly enabled."""
        monkeypatch.setenv("MXNET_TELEMETRY", "1")
        monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
        tin._reset_for_tests()
        try:
            before = json.dumps(tin.registry().collect(), default=str)
            assert podplane.plane() is None
            podplane.podz()
            podplane.status()
            after = json.dumps(tin.registry().collect(), default=str)
            assert before == after
        finally:
            tin._reset_for_tests()

    def test_fit_loop_off_path(self, pod_off, monkeypatch):
        """The base_module wiring resolves to None and the loop never
        calls note_step — same `is None` contract as trainhealth."""
        import mxnet_tpu as mx
        from mxnet_tpu import module as mod_mod
        from mxnet_tpu.io import NDArrayIter

        calls = []
        monkeypatch.setattr(podplane.PodPlane, "note_step",
                            lambda self, s: calls.append(s))
        data = mx.sym.var("data")
        sym = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(data, num_hidden=4), name="softmax")
        mod = mod_mod.Module(sym)
        rng = np.random.RandomState(0)
        it = NDArrayIter(rng.randn(8, 8).astype(np.float32),
                         rng.randint(0, 4, (8,)).astype(np.float32),
                         batch_size=8)
        mod.fit(it, num_epoch=1,
                optimizer_params={"learning_rate": 0.1})
        assert calls == []

    def test_podz_endpoint_reports_disabled(self, pod_off, monkeypatch):
        monkeypatch.setenv("MXNET_OPS_PORT", "0")
        ops_server.stop()
        try:
            port = ops_server.maybe_start()
            import urllib.request

            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/podz" % port, timeout=5) as r:
                assert json.loads(r.read()) == {"enabled": False}
        finally:
            ops_server.stop()


# -- mergeable histogram semantics --------------------------------------------
class TestHistogramMerge:
    def _counts(self, samples):
        wq = WindowedQuantile(window_s=3600.0)
        for v in samples:
            wq.observe(v, now=0.0)
        return wq._merged(0.0)

    def _vadd(self, a, b):
        return [x + y for x, y in zip(a, b)]

    def test_merge_exact_associative_order_independent(self):
        rng = np.random.RandomState(7)
        parts = [rng.lognormal(-3, 1, 500), rng.lognormal(-2, 0.5, 300),
                 rng.lognormal(-4, 2, 700)]
        vecs = [self._counts(p) for p in parts]
        union = self._counts(np.concatenate(parts))
        ab_c = self._vadd(self._vadd(vecs[0], vecs[1]), vecs[2])
        a_bc = self._vadd(vecs[0], self._vadd(vecs[1], vecs[2]))
        cba = self._vadd(self._vadd(vecs[2], vecs[1]), vecs[0])
        # associativity and commutativity are EXACT (integer vector adds)
        assert ab_c == a_bc == cba
        # and merging vectors == observing the union: same counts, so the
        # merged quantile is identical, not merely approximate
        assert ab_c == union
        for q in (0.5, 0.95, 0.99):
            assert quantile_of_counts(ab_c, q) \
                == quantile_of_counts(union, q)

    def test_aggregator_merged_counts_sum_ranks(self):
        agg = podplane.Aggregator(size=3)
        vecs = []
        rng = np.random.RandomState(3)
        for rank in range(3):
            v = self._counts(rng.lognormal(-3, 1, 200))
            vecs.append(v)
            agg.ingest(_snap(rank, hist=v, steps=10), now=0.0)
        want = self._vadd(self._vadd(vecs[0], vecs[1]), vecs[2])
        assert agg.merged_step_counts() == want


# -- rollup + mirror semantics ------------------------------------------------
class TestRollupAndMirror:
    def test_counter_collisions_summed_not_clobbered(self, pod_off):
        agg = podplane.Aggregator(size=2)
        m = [["serve_requests_total", "counter", {"engine": "e"}, 5.0],
             ["hbm_bytes", "gauge", {"dev": "0"}, 100.0]]
        agg.ingest(_snap(0, metrics=m, steps=1), now=0.0)
        m2 = [["serve_requests_total", "counter", {"engine": "e"}, 7.0],
              ["hbm_bytes", "gauge", {"dev": "0"}, 300.0]]
        agg.ingest(_snap(1, metrics=m2, steps=1), now=0.0)
        roll = agg.fleet_rollup()
        assert roll["counters"]["serve_requests_total{engine=e}"] == 12.0
        g = roll["gauges"]["hbm_bytes{dev=0}"]
        assert (g["min"], g["max"], g["mean"]) == (100.0, 300.0, 200.0)

    def test_mirror_rank_labeled_no_collision(self, pod_off, monkeypatch,
                                              tmp_path):
        """Rank 0 already owns a rank-LESS `steps_total`; the pushed copy
        lands under `pod_steps_total{rank=N}` — same registry, no
        MetricError, both readable."""
        monkeypatch.setenv("MXNET_TELEMETRY", "1")
        monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
        tin._reset_for_tests()
        try:
            local = tin.registry().counter("steps_total", "local", ())
            local.inc(3)
            agg = podplane.Aggregator(size=2)
            agg.ingest(_snap(1, metrics=[
                ["steps_total", "counter", {}, 9.0]], steps=1), now=0.0)
            agg.ingest(_snap(0, metrics=[
                ["steps_total", "counter", {}, 3.0]], seq=1, steps=1),
                now=0.0)
            assert tin.registry().counter("steps_total", "", ()).value() \
                == 3.0
            mirrored = tin.registry().get("pod_steps_total")
            vals = {s["labels"]["rank"]: s["value"]
                    for s in mirrored.samples()}
            assert vals == {"1": 9.0, "0": 3.0}
        finally:
            tin._reset_for_tests()


# -- stale-snapshot semantics -------------------------------------------------
class TestStaleDrop:
    def test_out_of_order_seq_dropped(self, pod_off):
        agg = podplane.Aggregator(size=2)
        assert agg.ingest(_snap(1, seq=2, steps=20), now=0.0)["ok"]
        v = agg.ingest(_snap(1, seq=1, steps=10), now=0.0)
        assert v == {"ok": False, "reason": "stale"}
        assert agg.stale_dropped == 1
        assert agg.podz(now=0.0)["ranks"]["1"]["steps"] == 20

    def test_restart_supersedes_old_incarnation(self, pod_off):
        agg = podplane.Aggregator(size=2)
        agg.ingest(_snap(1, epoch=100.0, seq=50, steps=500), now=0.0)
        # the restarted rank begins a NEW incarnation at seq 1: accepted
        assert agg.ingest(_snap(1, epoch=200.0, seq=1, steps=3),
                          now=0.0)["ok"]
        assert agg.podz(now=0.0)["ranks"]["1"]["steps"] == 3
        # ...and a straggler push from the DEAD incarnation arriving late
        # is dropped, not merged back
        v = agg.ingest(_snap(1, epoch=100.0, seq=51, steps=501), now=0.0)
        assert v["reason"] == "stale"
        assert agg.stale_dropped == 1
        assert agg.podz(now=0.0)["ranks"]["1"]["steps"] == 3

    def test_stale_counter_on_registry(self, pod_off, monkeypatch,
                                       tmp_path):
        monkeypatch.setenv("MXNET_TELEMETRY", "1")
        monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
        tin._reset_for_tests()
        try:
            agg = podplane.Aggregator(size=2)
            agg.ingest(_snap(1, seq=2), now=0.0)
            agg.ingest(_snap(1, seq=2), now=0.0)
            assert tin.registry().total("pod_snapshots_stale_total") == 1.0
        finally:
            tin._reset_for_tests()


# -- ledger divergence --------------------------------------------------------
class TestLedgerDivergence:
    def test_divergence_fires_once_per_key_with_dump(self, pod_off,
                                                     monkeypatch,
                                                     tmp_path):
        monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path / "frec"))
        flightrec._reset_for_tests()
        try:
            flightrec.record("warm", x=1)  # a non-empty ring can dump
            agg = podplane.Aggregator(size=2)
            agg.ingest(_snap(0, ledger={"k1": [100, 4096, 0.2],
                                        "same": [1, 1, 0.1]}), now=0.0)
            assert agg.divergences == 0
            agg.ingest(_snap(1, ledger={"k1": [999, 4096, 0.3],
                                        "same": [1, 1, 0.9]}), now=0.0)
            assert agg.divergences == 1  # k1 only; "same" differs solely
            # in compile_s, which is skew, not divergence
            pz = agg.podz(now=0.0)
            assert set(pz["ledger_divergences"]) == {"k1"}
            d = pz["ledger_divergences"]["k1"]
            assert d["ranks"] == [0, 1]
            assert d["fingerprints"]["0"][:2] == [100, 4096]
            assert d["fingerprints"]["1"][:2] == [999, 4096]
            # compile_s spread for the non-diverged key shows up as skew
            assert pz["skew"]["compile_s"]["same"] == pytest.approx(0.8)
            # the dump names the key and both ranks
            (dump,) = glob.glob(str(tmp_path / "frec" /
                                    "*pod_ledger_divergence*.json"))
            meta = json.load(open(dump))["flightrec"]
            assert meta["key"] == "k1" and meta["ranks"] == [0, 1]
            # repeated ingests never re-fire the same key
            agg.ingest(_snap(1, seq=2, ledger={"k1": [999, 4096, 0.3]}),
                       now=0.0)
            assert agg.divergences == 1
            # ...and a divergence is ALSO an incident (the broadcast is
            # how the non-aggregating rank learns to dump)
            assert [i["reason"] for i in agg.incidents()] \
                == ["ledger_divergence"]
        finally:
            flightrec._reset_for_tests()


# -- straggler verdicts -------------------------------------------------------
class TestStragglerVerdicts:
    def test_edge_triggered_with_hysteresis(self, pod_off, monkeypatch):
        monkeypatch.setenv("MXNET_POD_STRAGGLER_LAG", "10")
        monkeypatch.setenv("MXNET_POD_STRAGGLER_AGE_S", "1000")
        agg = podplane.Aggregator(size=2)
        agg.ingest(_snap(0, seq=1, steps=100), now=0.0)
        agg.ingest(_snap(1, seq=1, steps=95), now=0.0)   # lag 5: fine
        assert agg.straggler_verdicts == 0
        agg.ingest(_snap(1, seq=2, steps=96), now=0.0)
        agg.ingest(_snap(0, seq=2, steps=120), now=0.0)  # lag 24: verdict
        assert agg.straggler_verdicts == 1
        assert agg.podz(now=0.0)["ranks"]["1"]["straggler"] is True
        # STILL behind: edge-triggered, no second verdict
        agg.ingest(_snap(0, seq=3, steps=130), now=0.0)
        assert agg.straggler_verdicts == 1
        # recovers to lag 8 — above lag/2=5, hysteresis holds the verdict
        agg.ingest(_snap(1, seq=3, steps=122), now=0.0)
        assert agg.straggler_verdicts == 1
        assert agg.podz(now=0.0)["ranks"]["1"]["straggler"] is True
        # recovers below half the threshold: one recovery edge
        agg.ingest(_snap(1, seq=4, steps=127), now=0.0)
        assert agg.straggler_verdicts == 2
        assert agg.podz(now=0.0)["ranks"]["1"]["straggler"] is False

    def test_push_age_straggler_and_death_incident(self, pod_off,
                                                   monkeypatch):
        monkeypatch.setenv("MXNET_POD_STRAGGLER_AGE_S", "10")
        agg = podplane.Aggregator(size=2)
        agg.ingest(_snap(0, steps=5), now=0.0)
        agg.ingest(_snap(1, steps=5), now=0.0)
        assert agg.podz(now=5.0)["ranks"]["1"]["straggler"] is False
        # rank 1 stops pushing; rank 0 keeps going
        agg.ingest(_snap(0, seq=2, steps=6), now=11.0)
        pz = agg.podz(now=12.0)
        assert pz["ranks"]["1"]["straggler"] is True
        assert pz["ranks"]["1"]["dead"] is False
        assert not any(i["reason"] == "rank_death" for i in pz["incidents"])
        # past 3x the age threshold: presumed dead, incident minted
        pz = agg.podz(now=31.0)
        assert pz["ranks"]["1"]["dead"] is True
        deaths = [i for i in pz["incidents"] if i["reason"] == "rank_death"]
        assert len(deaths) == 1 and deaths[0]["rank"] == 1


# -- incidents ----------------------------------------------------------------
class TestIncidents:
    def test_mint_throttled_per_rank_reason(self, pod_off):
        agg = podplane.Aggregator(size=2)
        assert agg.mint_incident("slo_breach", 1, now=0.0) is not None
        assert agg.mint_incident("slo_breach", 1, now=1.0) is None
        assert agg.mint_incident("slo_breach", 0, now=1.0) is not None
        assert agg.mint_incident("nonfinite", 1, now=1.0) is not None
        assert agg.mint_incident("slo_breach", 1, now=40.0) is not None
        assert len(agg.incidents()) == 4

    def test_slo_and_nonfinite_edges_mint(self, pod_off):
        agg = podplane.Aggregator(size=2)
        agg.ingest(_snap(1, seq=1, slo_breaches=2, nonfinite=0), now=0.0)
        assert agg.incidents() == []  # no baseline = no edge
        agg.ingest(_snap(1, seq=2, slo_breaches=2, nonfinite=0), now=1.0)
        assert agg.incidents() == []  # unchanged = no edge
        agg.ingest(_snap(1, seq=3, slo_breaches=3, nonfinite=1), now=2.0)
        assert sorted(i["reason"] for i in agg.incidents()) \
            == ["nonfinite", "slo_breach"]

    def test_broadcast_tags_dump_on_pushing_rank(self, pod_on):
        """The correlation contract end-to-end over a real socket: rank 0
        mints, the id rides the push response, rank 1 writes a dump
        carrying the shared id."""
        addr, tmp_path = pod_on
        r1_dir = tmp_path / "frec_r1"
        p0 = podplane.PodPlane(rank=0, size=2, addr=addr)
        p1 = podplane.PodPlane(rank=1, size=2, addr=addr)
        try:
            inc = p0.aggregator.mint_incident("slo_breach", 0, breaches=3)
            os.environ["MXNET_FLIGHTREC_DIR"] = str(r1_dir)
            flightrec._reset_for_tests()
            flightrec.record("warm", x=1)
            p1.note_step(0.01)  # push -> response carries the incident
            deadline = time.monotonic() + 10.0
            dumps = []
            while time.monotonic() < deadline and not dumps:
                dumps = glob.glob(str(r1_dir / "*pod_incident*.json"))
                time.sleep(0.05)
            assert dumps, "rank 1 never dumped the broadcast incident"
            meta = json.load(open(dumps[0]))["flightrec"]
            assert meta["incident"] == inc["id"]
            assert meta["why"] == "slo_breach"
            assert p1.push_stats()["incidents_seen"] == 1
            # the same id never re-dumps
            p1.note_step(0.01)
            time.sleep(0.2)
            assert len(glob.glob(str(r1_dir / "*pod_incident*.json"))) == 1
        finally:
            p0.close()
            p1.close()


# -- live plane over the socket -----------------------------------------------
class TestLivePlane:
    def test_two_rank_aggregation_and_podz(self, pod_on):
        addr, _ = pod_on
        p0 = podplane.PodPlane(rank=0, size=2, addr=addr)
        p1 = podplane.PodPlane(rank=1, size=2, addr=addr)
        try:
            p0.seed_ledger("site#fwd", flops=100, bytes_accessed=64)
            p1.seed_ledger("site#fwd", flops=999, bytes_accessed=64)
            for _ in range(3):
                p0.note_step(0.002)
                p1.note_step(0.004)
            deadline = time.monotonic() + 10.0
            pz = p0.podz()
            while time.monotonic() < deadline \
                    and pz["ranks_reporting"] < 2:
                time.sleep(0.05)
                pz = p0.podz()
            assert pz["ranks_reporting"] == 2
            assert pz["ranks"]["0"]["steps"] == 3
            assert pz["ranks"]["1"]["steps"] == 3
            assert pz["ranks"]["1"]["step_p50_ms"] is not None
            assert pz["ledger_divergence_count"] == 1
            assert pz["fleet"]["max_step_lag"] == 0
            assert p1.push_stats()["push_failures"] == 0
        finally:
            p0.close()
            p1.close()

    def test_push_failure_degrades_never_raises(self, pod_on):
        """No listener at the address: every push counts a failure and
        note_step still returns — the step path never blocks or throws."""
        p1 = podplane.PodPlane(rank=1, size=2,
                               addr=("127.0.0.1", _free_port()))
        try:
            for _ in range(3):
                p1.note_step(0.001)
            st = p1.push_stats()
            assert st["push_failures"] == 3 and st["steps"] == 3
            assert st["connected"] is False
        finally:
            p1.close()

    def test_fit_loop_feeds_note_step(self, pod_on, monkeypatch):
        """base_module wiring: gate on ⇒ one note_step per batch."""
        import mxnet_tpu as mx
        from mxnet_tpu import module as mod_mod
        from mxnet_tpu.io import NDArrayIter

        calls = []
        monkeypatch.setattr(podplane.PodPlane, "note_step",
                            lambda self, s: calls.append(s))
        data = mx.sym.var("data")
        sym = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(data, num_hidden=4), name="softmax")
        mod = mod_mod.Module(sym)
        rng = np.random.RandomState(0)
        it = NDArrayIter(rng.randn(16, 8).astype(np.float32),
                         rng.randint(0, 4, (16,)).astype(np.float32),
                         batch_size=8)
        mod.fit(it, num_epoch=1,
                optimizer_params={"learning_rate": 0.1})
        assert len(calls) == 2 and all(s > 0 for s in calls)


# -- CLI rendering ------------------------------------------------------------
class TestPodStatusCli:
    def _tool(self):
        import importlib.util
        import sys as _sys

        tools = os.path.join(os.path.dirname(__file__), "..", "tools")
        _sys.path.insert(0, os.path.abspath(tools))
        try:
            import pod_status
        finally:
            _sys.path.pop(0)
        return pod_status

    def test_render_tables(self, pod_off):
        pod_status = self._tool()
        agg = podplane.Aggregator(size=2)
        agg.ingest(_snap(0, steps=10), now=0.0)
        agg.ingest(_snap(1, steps=8, ledger={"k": [1, 2, 0.1]}), now=0.0)
        text = pod_status.render_podz(agg.podz(now=0.0))
        assert "pod aggregator: 2/2 ranks reporting" in text
        assert "max_lag=2" in text
        assert pod_status.render_podz({"enabled": False}) \
            == "pod plane disabled (MXNET_POD_METRICS unset)"

    def test_collect_groups_by_incident(self, pod_on, tmp_path, capsys):
        pod_status = self._tool()
        addr, base = pod_on
        p0 = podplane.PodPlane(rank=0, size=2, addr=addr)
        p1 = podplane.PodPlane(rank=1, size=2, addr=addr)
        try:
            flightrec.record("warm", x=1)
            inc = p0.aggregator.mint_incident("nonfinite", 1, trips=1)
            p0.tick()   # rank 0 observes + dumps its own incident
            r1_dir = base / "frec_r1"
            os.environ["MXNET_FLIGHTREC_DIR"] = str(r1_dir)
            flightrec._reset_for_tests()
            flightrec.record("warm", x=1)
            p1.note_step(0.01)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not glob.glob(
                    str(r1_dir / "*pod_incident*")):
                time.sleep(0.05)
            out = tmp_path / "merged"
            rc = pod_status.collect([str(base / "frec"), str(r1_dir)],
                                    str(out))
            assert rc == 0
            (merged,) = glob.glob(str(out / "*.json"))
            assert inc["id"] in os.path.basename(merged)
            evs = json.load(open(merged))["traceEvents"]
            # both ranks' dumps landed on ONE timeline, every event
            # rank-labeled (the observer_rank metadata became explicit
            # --rank flags, force-stamped into event args)
            ranks = {e.get("args", {}).get("rank") for e in evs
                     if e.get("ph") != "M"}
            assert {0, 1} <= ranks
        finally:
            p0.close()
            p1.close()
